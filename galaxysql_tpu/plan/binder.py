"""Binder: parse AST -> typed logical plan.

Reference analog: validation + SqlNode->RelNode conversion (`TddlSqlToRelConverter`,
SURVEY.md §2.5) including the subquery transformations the reference gets from Calcite
rules.  Subqueries are decorrelated at bind time:

- `x IN (SELECT ...)`            -> semi join        (`NOT IN` -> anti join)
- `EXISTS (SELECT ... WHERE corr)` -> semi join on the correlated equalities, remaining
                                       correlated predicates become the join residual
- `expr CMP (SELECT agg ... WHERE corr)` -> inner join against the subquery re-grouped by
                                       its correlation keys (Q2/Q17/Q20 pattern)
- uncorrelated scalar subquery   -> cross join with the 1-row aggregate (Q11/Q15/Q22)

Column identity: every base column gets the id "<alias>.<column>"; derived/aggregate
outputs get their output names (qualified by the derived alias).  All ir.ColRef names in
the plan use these ids.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from galaxysql_tpu.chunk.batch import Dictionary
from galaxysql_tpu.expr import ir
from galaxysql_tpu.expr.compiler import _find_dictionary
from galaxysql_tpu.meta.catalog import Catalog, TableMeta
from galaxysql_tpu.plan import logical as L
from galaxysql_tpu.plan.rules import conjuncts as _conjuncts
from galaxysql_tpu.sql import ast
from galaxysql_tpu.types import datatype as dt
from galaxysql_tpu.types import temporal
from galaxysql_tpu.utils import errors

_AGG_FUNCS = {"sum", "count", "avg", "min", "max"}

_SCALAR_FUNC_OPS = {
    "year": "year", "month": "month", "dayofmonth": "dayofmonth", "day": "dayofmonth",
    "quarter": "quarter", "abs": "abs", "coalesce": "coalesce", "ifnull": "ifnull",
    "if": "if", "least": "least", "greatest": "greatest", "datediff": "datediff",
    "mod": "mod",
}


class Scope:
    """Name-resolution scope: an ordered set of (alias -> fields), with an optional
    parent scope for correlated subqueries."""

    def __init__(self, parent: Optional["Scope"] = None):
        self.entries: List[Tuple[str, List[L.Field]]] = []
        self.parent = parent
        # correlated references collected while binding a subquery: (outer ColRef)
        self.correlated: List[ir.ColRef] = []

    def add(self, alias: str, fields: List[L.Field]):
        if any(a == alias.lower() for a, _ in self.entries):
            raise errors.TddlError(f"Not unique table/alias: '{alias}'")
        self.entries.append((alias.lower(), fields))

    def all_fields(self) -> List[L.Field]:
        return [f for _, fs in self.entries for f in fs]

    def resolve(self, parts: List[str]) -> Optional[ir.ColRef]:
        if len(parts) == 1:
            col = parts[0].lower()
            hits = []
            for alias, fs in self.entries:
                for fid, typ, d in fs:
                    base = fid.split(".")[-1].lower()
                    if base == col:
                        hits.append(ir.ColRef(fid, typ, d))
            if len(hits) > 1:
                # identical id means same physical column via different paths
                if len({h.name for h in hits}) > 1:
                    raise errors.AmbiguousColumnError(
                        f"Column '{parts[0]}' in field list is ambiguous")
            if hits:
                return hits[0]
            return None
        alias = parts[-2].lower()
        col = parts[-1].lower()
        for a, fs in self.entries:
            if a != alias:
                continue
            for fid, typ, d in fs:
                if fid.split(".")[-1].lower() == col:
                    return ir.ColRef(fid, typ, d)
            return None
        return None

    def resolve_or_correlate(self, parts: List[str]) -> ir.ColRef:
        r = self.resolve(parts)
        if r is not None:
            return r
        if self.parent is not None:
            outer = self.parent.resolve_or_correlate(parts)
            self.correlated.append(outer)
            return outer
        raise errors.UnknownColumnError(f"Unknown column '{'.'.join(parts)}'")


class Binder:
    def __init__(self, catalog: Catalog, default_schema: str,
                 params: Optional[List] = None):
        self.catalog = catalog
        self.default_schema = default_schema
        self.params = params or []
        self._ids = itertools.count()
        # session hooks (sequences, connection id) — set by the caller when available
        self.sequence_hook = None
        self.connection_id = None
        self.lock_fn_hook = None  # (fn_name, args) -> int|None (GET_LOCK family)
        # CTE scopes: a stack of {name: ast.Cte}; bodies re-bind per reference
        # (fresh column ids per occurrence, like the reference's view expansion)
        self._ctes: List[Dict[str, ast.Cte]] = []
        self._cte_in_progress: set = set()
        self._views_in_progress: set = set()

    def fresh(self, prefix: str) -> str:
        return f"{prefix}${next(self._ids)}"

    # --------------------------------------------------------------- queries

    def bind_query(self, stmt: ast.Statement,
                   scope_parent: Optional[Scope] = None
                   ) -> Tuple[L.RelNode, List[str]]:
        """Bind a SELECT or a UNION chain (the statement-level entry point)."""
        if isinstance(stmt, ast.Select):
            rel, names, _ = self.bind_select(stmt, scope_parent)
            return rel, names
        assert isinstance(stmt, ast.SetOpSelect)
        pushed = bool(stmt.ctes)
        if pushed:
            self._ctes.append({c.name.lower(): c for c in stmt.ctes})
        try:
            parts: List[Tuple[L.RelNode, List[str]]] = []

            def flatten(s):
                if isinstance(s, ast.SetOpSelect) and not s.ctes and \
                        s.op == stmt.op:
                    flatten(s.left)
                    flatten(s.right)
                else:
                    parts.append(self.bind_query(s, scope_parent))
            flatten(stmt.left)
            flatten(stmt.right)
            rels = [r for r, _ in parts]
            names = parts[0][1]
            node: L.RelNode = L.Union(rels, stmt.op == "union_all")
            if stmt.order_by:
                node = self._bind_union_order(node, stmt, names)
            if stmt.limit is not None:
                off = self._limit_value(stmt.offset) if stmt.offset else 0
                if isinstance(node, L.Sort):
                    node.limit = self._limit_value(stmt.limit)
                    node.offset = off
                else:
                    node = L.Limit(node, self._limit_value(stmt.limit), off)
            return node, names
        finally:
            if pushed:
                self._ctes.pop()

    def _bind_union_order(self, node: L.RelNode, stmt: ast.SetOpSelect,
                          names: List[str]) -> L.RelNode:
        """Trailing ORDER BY of a union chain: output aliases or ordinals only."""
        fields = node.fields()
        keys = []
        for e, desc in stmt.order_by:
            ref = None
            if isinstance(e, ast.NumberLit):
                ix = int(e.value) - 1
                if not (0 <= ix < len(fields)):
                    raise errors.TddlError(f"ORDER BY position {ix + 1} invalid")
                ref = fields[ix]
            elif isinstance(e, ast.Name):
                nm = e.simple.lower()
                for n, f in zip(names, fields):
                    if n.lower() == nm:
                        ref = f
                        break
            if ref is None:
                raise errors.NotSupportedError(
                    "UNION ORDER BY supports output aliases and ordinals only")
            fid, typ, d = ref
            keys.append((ir.ColRef(fid, typ, d), desc))
        return L.Sort(node, keys)

    # ------------------------------------------------------------------ SELECT

    def bind_select(self, sel: ast.Select, scope_parent: Optional[Scope] = None
                    ) -> Tuple[L.RelNode, List[str], Scope]:
        """Returns (plan, output display names, the FROM scope used)."""
        if sel.ctes:
            self._ctes.append({c.name.lower(): c for c in sel.ctes})
            try:
                return self._bind_select_body(sel, scope_parent)
            finally:
                self._ctes.pop()
        return self._bind_select_body(sel, scope_parent)

    def _bind_select_body(self, sel: ast.Select,
                          scope_parent: Optional[Scope] = None
                          ) -> Tuple[L.RelNode, List[str], Scope]:
        scope = Scope(scope_parent)
        if sel.from_ is None:
            # SELECT without FROM: one anonymous row
            node: L.RelNode = L.Values([], [[]])
        else:
            node = self._bind_from(sel.from_, scope)

        if sel.where is not None:
            node = self._apply_where(node, sel.where, scope)

        # aggregate analysis
        agg_calls: List[Tuple[ast.Func, L.AggSpec]] = []
        has_agg = bool(sel.group_by) or self._contains_agg(sel)

        display_names: List[str] = []
        out_exprs: List[Tuple[str, ir.Expr]] = []

        # window functions (over the filtered FROM result; not combinable with
        # GROUP BY in this round)
        win_rep: Dict[int, ir.Expr] = {}
        if any(isinstance(n, ast.WindowExpr)
               for i in sel.items for n in _ast_walk(i.expr)):
            if has_agg:
                raise errors.NotSupportedError(
                    "window functions combined with GROUP BY not supported yet")
            node, win_rep = self._bind_windows(node, sel, scope)

        if has_agg:
            node, out_exprs, display_names = self._bind_aggregate(node, sel, scope)
        else:
            # plain select list (scalar subqueries attach as joins, like WHERE)
            items = self._expand_stars(sel.items, scope)
            for item in items:
                if self._has_scalar_subquery(item.expr):
                    node, e = self._bind_with_scalar_subquery(
                        node, item.expr, scope, seed_rep=win_rep)
                else:
                    e = self._bind_expr(item.expr, scope, dict(win_rep))
                name = item.alias or self._display_name(item.expr)
                out_id = name if "." not in name else name.split(".")[-1]
                out_exprs.append((self.fresh(out_id), e))
                display_names.append(out_id)

            if sel.distinct:
                groups = [(oid, e) for oid, e in out_exprs]
                node = L.Aggregate(node, groups, [])
                out_exprs = [(oid, ir.ColRef(oid, e.dtype, _find_dictionary(e)))
                             for oid, e in groups]
            # ORDER BY for non-agg query binds against select aliases then scope
            if sel.order_by:
                node = self._bind_order(node, sel, scope, out_exprs, display_names,
                                        project_first=True)
                out_exprs = [(oid, ir.ColRef(oid, e.dtype, _find_dictionary(e)))
                             for oid, e in out_exprs]
            else:
                node = L.Project(node, out_exprs)
                out_exprs = [(oid, ir.ColRef(oid, e.dtype, _find_dictionary(e)))
                             for oid, e in out_exprs]
            node = self._apply_limit(node, sel)
            return node, display_names, scope

        # aggregate path: out_exprs reference agg/group outputs
        if sel.order_by:
            node = self._bind_order_agg(node, sel, out_exprs, display_names)
        else:
            node = L.Project(node, out_exprs)
        node = self._apply_limit(node, sel)
        return node, display_names, scope

    # -- FROM ----------------------------------------------------------------

    def _bind_from(self, t: ast.TableExpr, scope: Scope) -> L.RelNode:
        if isinstance(t, ast.TableName):
            if t.schema is None:
                cte = self._lookup_cte(t.table)
                if cte is not None:
                    if t.as_of is not None:
                        # silent wrong-snapshot results are worse than refusal
                        raise errors.NotSupportedError(
                            "AS OF TSO on a CTE reference")
                    return self._bind_cte_ref(cte, t, scope)
            schema = t.schema or self.default_schema
            view = self.catalog.view(schema, t.table)
            if view is not None:
                if t.as_of is not None:
                    raise errors.NotSupportedError("AS OF TSO on a view")
                return self._bind_view_ref(view, t, scope)
            tm = self.catalog.table(schema, t.table)
            alias = (t.alias or t.table).lower()
            # ONE read of the live column list; the metas ride the scan so
            # fields() never re-resolves names a concurrent DDL may drop
            metas = list(tm.columns)
            cols = [(f"{alias}.{c.name}", c.name) for c in metas]
            scan = L.Scan(tm, alias, cols,
                          col_meta={c.name: c for c in metas})
            as_of = t.as_of
            if isinstance(as_of, ast.ParamRef):
                as_of = int(self.params[as_of.index])
            scan.as_of = as_of
            scope.add(alias, scan.fields())
            return scan
        if isinstance(t, ast.SubqueryRef):
            sub, names = self.bind_query(t.select, scope.parent)
            alias = t.alias.lower()
            # re-expose subquery outputs under the derived alias
            fields = sub.fields()
            renames = [(f"{alias}.{n}", ir.ColRef(fid, typ, d))
                       for n, (fid, typ, d) in zip(names, fields)]
            proj = L.Project(sub, renames)
            scope.add(alias, proj.fields())
            return proj
        if isinstance(t, ast.Join):
            left = self._bind_from(t.left, scope)
            right = self._bind_from(t.right, scope)
            return self._bind_join_expr(t, left, right, scope)
        raise errors.NotSupportedError(f"FROM item {type(t).__name__}")

    def _lookup_cte(self, name: str) -> Optional[ast.Cte]:
        key = name.lower()
        for frame in reversed(self._ctes):
            c = frame.get(key)
            if c is not None:
                return c
        return None

    def _bind_cte_ref(self, cte: ast.Cte, t: ast.TableName,
                      scope: Scope) -> L.RelNode:
        """Expand a CTE reference: the body re-binds per occurrence (fresh ids),
        exposed under the reference alias like a derived table."""
        key = id(cte)
        if key in self._cte_in_progress:
            raise errors.NotSupportedError(
                f"CTE '{cte.name}' references itself (recursion unsupported)")
        self._cte_in_progress.add(key)
        try:
            sub, names = self.bind_query(cte.select, scope.parent)
        finally:
            self._cte_in_progress.discard(key)
        if cte.columns:
            if len(cte.columns) != len(names):
                raise errors.TddlError(
                    f"CTE '{cte.name}' column list length mismatch")
            names = cte.columns
        alias = (t.alias or cte.name).lower()
        fields = sub.fields()
        renames = [(f"{alias}.{n}", ir.ColRef(fid, typ, d))
                   for n, (fid, typ, d) in zip(names, fields)]
        proj = L.Project(sub, renames)
        scope.add(alias, proj.fields())
        return proj

    def _bind_view_ref(self, view, t: ast.TableName, scope: Scope) -> L.RelNode:
        """Expand a view reference (DrdsViewExpander analog,
        `optimizer/view/DrdsViewExpander.java`): parse the stored SELECT and bind
        it as a derived table under the reference alias.  The body binds in the
        VIEW's schema (unqualified names resolve where the view was defined),
        with a cycle guard — OR REPLACE can create self/mutual references."""
        from galaxysql_tpu.sql.parser import parse
        vkey = (view.schema.lower(), view.name.lower())
        if vkey in self._views_in_progress:
            raise errors.TddlError(
                f"View '{view.schema}.{view.name}' references itself "
                "(directly or through another view)")
        stmt = parse(view.sql)
        saved_schema = self.default_schema
        self._views_in_progress.add(vkey)
        self.default_schema = view.schema
        try:
            sub, names = self.bind_query(stmt)
        finally:
            self.default_schema = saved_schema
            self._views_in_progress.discard(vkey)
        if view.columns:
            if len(view.columns) != len(names):
                raise errors.TddlError(
                    f"View '{view.name}' column list length mismatch")
            names = list(view.columns)
        alias = (t.alias or view.name).lower()
        fields = sub.fields()
        renames = [(f"{alias}.{n}", ir.ColRef(fid, typ, d))
                   for n, (fid, typ, d) in zip(names, fields)]
        proj = L.Project(sub, renames)
        scope.add(alias, proj.fields())
        return proj

    def _bind_join_expr(self, t: ast.Join, left: L.RelNode, right: L.RelNode,
                        scope: Scope) -> L.RelNode:
        if t.kind == "cross":
            # comma joins: conditions live in WHERE; bind as unconditional cross,
            # the rewriter turns cross+filter into equi joins
            return L.Join(left, right, "cross", [])
        cond = None
        if t.using:
            eqs = []
            for c in t.using:
                le = self._resolve_in(left, c, scope)
                re = self._resolve_in(right, c, scope)
                eqs.append(ir.call("eq", le, re))
            cond = ir.and_(*eqs)
        elif t.on is not None:
            cond = self._bind_expr(t.on, scope)
        if t.kind == "right":
            left, right = right, left
            kind = "left"
        else:
            kind = t.kind
        if kind == "full":
            raise errors.NotSupportedError("FULL OUTER JOIN not supported")
        equi, residual, leftover = self._split_join_condition(cond, left, right)
        node = L.Join(left, right, kind, equi, residual)
        if leftover is not None:
            if kind == "left":
                raise errors.NotSupportedError(
                    "LEFT JOIN ON condition too complex to decompose")
            node = L.Filter(node, leftover)
        return node

    def _resolve_in(self, node: L.RelNode, col: str, scope: Scope) -> ir.ColRef:
        for fid, typ, d in node.fields():
            if fid.split(".")[-1].lower() == col.lower():
                return ir.ColRef(fid, typ, d)
        raise errors.UnknownColumnError(f"Unknown column '{col}' in USING")

    def _split_join_condition(self, cond: Optional[ir.Expr], left: L.RelNode,
                              right: L.RelNode):
        """Split an ON condition into (equi pairs, one-side/residual predicate, leftover).

        - a.x = b.y with sides on opposite inputs -> equi pair
        - predicates referencing only the right side -> pushed below (returned as part of
          residual for outer joins; callers may instead push into the right child)
        - anything else -> residual (inner) / leftover (needs a Filter above)
        """
        if cond is None:
            return [], None, None
        left_ids = set(left.field_ids())
        right_ids = set(right.field_ids())
        equi: List[Tuple[ir.Expr, ir.Expr]] = []
        residuals: List[ir.Expr] = []
        for c in _conjuncts(cond):
            if isinstance(c, ir.Call) and c.op == "eq":
                a, b = c.args
                ra = set(ir.referenced_columns(a))
                rb = set(ir.referenced_columns(b))
                if ra and rb and ra <= left_ids and rb <= right_ids:
                    equi.append((a, b))
                    continue
                if ra and rb and ra <= right_ids and rb <= left_ids:
                    equi.append((b, a))
                    continue
            residuals.append(c)
        residual = ir.and_(*residuals) if residuals else None
        return equi, residual, None

    # -- WHERE (incl. subquery unnesting) --------------------------------------

    def _apply_where(self, node: L.RelNode, where: ast.ExprNode, scope: Scope
                     ) -> L.RelNode:
        plain: List[ir.Expr] = []
        for conj in _ast_conjuncts(where):
            if isinstance(conj, ast.ExistsExpr):
                node = self._bind_exists(node, conj.select, conj.negated, scope)
            elif isinstance(conj, ast.Unary) and conj.op == "not" and \
                    isinstance(conj.arg, ast.ExistsExpr):
                node = self._bind_exists(node, conj.arg.select, True, scope)
            elif isinstance(conj, ast.InExpr) and conj.select is not None:
                node = self._bind_in_subquery(node, conj, scope)
            elif self._has_scalar_subquery(conj):
                node, e = self._bind_with_scalar_subquery(node, conj, scope)
                plain.append(e)
            else:
                plain.append(self._bind_expr(conj, scope))
        if plain:
            node = L.Filter(node, ir.and_(*plain))
        return node

    def _bind_exists(self, node: L.RelNode, sub: ast.Statement, negated: bool,
                     scope: Scope) -> L.RelNode:
        if not isinstance(sub, ast.Select):
            raise errors.NotSupportedError("EXISTS over a UNION is not supported")
        subscope = Scope(scope)
        # bind the subquery's FROM + WHERE only (EXISTS ignores the select list)
        inner = self._bind_from(sub.from_, subscope)
        equi: List[Tuple[ir.Expr, ir.Expr]] = []
        residuals: List[ir.Expr] = []
        filters: List[ir.Expr] = []
        outer_ids = set(node.field_ids())
        inner_ids = set(inner.field_ids())
        if sub.where is not None:
            for conj in _ast_conjuncts(sub.where):
                e = self._bind_expr(conj, subscope)
                refs = set(ir.referenced_columns(e))
                if refs <= inner_ids:
                    filters.append(e)
                    continue
                # correlated conjunct
                if isinstance(e, ir.Call) and e.op == "eq":
                    a, b = e.args
                    ra, rb = set(ir.referenced_columns(a)), set(ir.referenced_columns(b))
                    if ra <= outer_ids and rb <= inner_ids:
                        equi.append((a, b))
                        continue
                    if rb <= outer_ids and ra <= inner_ids:
                        equi.append((b, a))
                        continue
                residuals.append(e)
        if filters:
            inner = L.Filter(inner, ir.and_(*filters))
        if not equi:
            raise errors.NotSupportedError(
                "EXISTS subquery requires at least one correlated equality")
        return L.Join(node, inner, "anti" if negated else "semi", equi,
                      ir.and_(*residuals) if residuals else None)

    def _bind_in_subquery(self, node: L.RelNode, e: ast.InExpr, scope: Scope
                          ) -> L.RelNode:
        arg = self._bind_expr(e.arg, scope)
        # bind_query handles both plain SELECT and UNION chains
        sub, _names = self.bind_query(e.select, scope)
        fields = sub.fields()
        if len(fields) != 1:
            raise errors.TddlError("Operand should contain 1 column")
        fid, typ, d = fields[0]
        # NOT IN with NULLs on either side has three-valued semantics; the anti join
        # treats NULL as non-matching (documented divergence for nullable inputs)
        return L.Join(node, sub, "anti" if e.negated else "semi",
                      [(arg, ir.ColRef(fid, typ, d))], None)

    # -- scalar subqueries ------------------------------------------------------

    def _has_scalar_subquery(self, e: ast.ExprNode) -> bool:
        found = False
        for n in _ast_walk(e):
            if isinstance(n, ast.SubqueryExpr):
                found = True
        return found

    def _bind_with_scalar_subquery(self, node: L.RelNode, conj: ast.ExprNode,
                                   scope: Scope,
                                   seed_rep: Optional[Dict[int, ir.Expr]] = None
                                   ) -> Tuple[L.RelNode, ir.Expr]:
        """Rewrite an expression containing scalar subqueries into joins + plain
        expr (shared by the WHERE and SELECT-list paths)."""
        replacements: Dict[int, ir.Expr] = dict(seed_rep or {})
        for n in _ast_walk(conj):
            if isinstance(n, ast.SubqueryExpr):
                node, ref = self._attach_scalar_subquery(node, n.select, scope)
                replacements[id(n)] = ref
        e = self._bind_expr(conj, scope, replacements)
        return node, e

    def _attach_scalar_subquery(self, node: L.RelNode, sub: ast.Statement,
                                scope: Scope) -> Tuple[L.RelNode, ir.Expr]:
        if not isinstance(sub, ast.Select):
            raise errors.NotSupportedError(
                "scalar subquery over a UNION is not supported")
        plan, names, used_scope = self.bind_select(sub, scope)
        correlated = used_scope.correlated
        fields = plan.fields()
        if len(fields) != 1:
            raise errors.TddlError("Scalar subquery must return one column")
        fid, typ, d = fields[0]
        if not correlated:
            # uncorrelated: scalar cross join — exactly-one-row semantics (empty
            # result NULL-extends, >1 rows is an error at execution)
            j = L.Join(node, plan, "cross", [])
            j.scalar = True
            return j, ir.ColRef(fid, typ.with_nullable(True), d)
        # correlated scalar aggregate: re-group by correlation keys and LEFT join
        # (outer rows with no group must survive with NULL, not vanish)
        plan2, out_ref, equi = self._bind_correlated_agg(sub, scope)
        return L.Join(node, plan2, "left", equi), \
            ir.ColRef(out_ref.name, out_ref.dtype.with_nullable(True),
                      _find_dictionary(out_ref))

    def _bind_correlated_agg(self, sub: ast.Select, scope: Scope):
        """Q2/Q17/Q20 pattern: SELECT agg(expr) FROM ... WHERE corr-eqs AND local-preds."""
        if sub.group_by or sub.having or len(sub.items) != 1:
            raise errors.NotSupportedError("unsupported correlated scalar subquery shape")
        subscope = Scope(scope)
        inner = self._bind_from(sub.from_, subscope)
        inner_ids = set(inner.field_ids())
        equi_outer: List[ir.Expr] = []
        group_inner: List[ir.Expr] = []
        filters: List[ir.Expr] = []
        if sub.where is not None:
            for conj in _ast_conjuncts(sub.where):
                e = self._bind_expr(conj, subscope)
                refs = set(ir.referenced_columns(e))
                if refs <= inner_ids:
                    filters.append(e)
                    continue
                if isinstance(e, ir.Call) and e.op == "eq":
                    a, b = e.args
                    ra, rb = set(ir.referenced_columns(a)), set(ir.referenced_columns(b))
                    if ra <= inner_ids and not (rb & inner_ids):
                        group_inner.append(a)
                        equi_outer.append(b)
                        continue
                    if rb <= inner_ids and not (ra & inner_ids):
                        group_inner.append(b)
                        equi_outer.append(a)
                        continue
                raise errors.NotSupportedError(
                    "correlated subquery predicate too complex")
        if filters:
            inner = L.Filter(inner, ir.and_(*filters))
        # the single select item must be an aggregate expression
        item = sub.items[0].expr
        aggs: List[L.AggSpec] = []
        rep: Dict[int, ir.Expr] = {}
        for n in _ast_walk(item):
            if isinstance(n, ast.Func) and n.name in _AGG_FUNCS:
                arg = None if n.star else self._bind_expr(n.args[0], subscope)
                kind = "count_star" if (n.name == "count" and n.star) else n.name
                out_id = self.fresh(kind)
                spec = L.AggSpec(kind, arg, out_id)
                aggs.append(spec)
                rep[id(n)] = ir.ColRef(out_id, spec.dtype, None)
        if not aggs:
            raise errors.NotSupportedError(
                "correlated scalar subquery must be an aggregate")
        groups = [(self.fresh("ck"), g) for g in group_inner]
        agg_node = L.Aggregate(inner, groups, aggs)
        # value expression over agg outputs (e.g. 0.2 * avg(...))
        val = self._bind_expr(item, subscope, rep)
        val_id = self.fresh("sq")
        group_refs = [(gid, ir.ColRef(gid, g.dtype, _find_dictionary(g)))
                      for (gid, g) in groups]
        proj = L.Project(agg_node, group_refs + [(val_id, val)])
        equi = [(outer, ir.ColRef(gid, g.dtype, _find_dictionary(g)))
                for outer, (gid, g) in zip(equi_outer, groups)]
        return proj, ir.ColRef(val_id, val.dtype, _find_dictionary(val)), equi

    # -- window functions ---------------------------------------------------------

    _WINDOW_KINDS = {"row_number", "rank", "dense_rank", "sum", "count", "avg",
                     "min", "max", "lag", "lead", "first_value", "last_value"}

    def _bind_windows(self, node: L.RelNode, sel: ast.Select, scope: Scope):
        """One L.Window node per distinct (PARTITION BY, ORDER BY) spec; window
        expressions in the select list are replaced by output column refs."""
        groups: Dict[Tuple, Tuple[List, List, List[L.WindowCall]]] = {}
        rep: Dict[int, ir.Expr] = {}
        for item in sel.items:
            for n in _ast_walk(item.expr):
                if not isinstance(n, ast.WindowExpr):
                    continue
                fname = n.func.name
                if fname not in self._WINDOW_KINDS:
                    raise errors.NotSupportedError(f"window function {fname}()")
                parts = [self._bind_expr(p, scope) for p in n.partition_by]
                orders = [(self._bind_expr(e, scope), desc)
                          for e, desc in n.order_by]
                key = (tuple(p.key() for p in parts),
                       tuple((e.key(), d) for e, d in orders))
                if key not in groups:
                    groups[key] = (parts, orders, [])
                calls = groups[key][2]
                if n.func.distinct:
                    raise errors.NotSupportedError(
                        "DISTINCT in window aggregates is not supported")
                if n.frame is not None and n.frame[1] == "current":
                    raise errors.NotSupportedError(
                        "frames starting at CURRENT ROW are not supported yet")
                # frame semantics: SQL default with ORDER BY is RANGE ..CURRENT
                if n.frame is None:
                    frame = "range" if n.order_by else "whole"
                elif n.frame[2] == "unbounded_following":
                    frame = "whole"
                else:
                    frame = "running" if n.frame[0] == "rows" else "range"
                offset = 1
                arg = None
                if fname in ("row_number", "rank", "dense_rank"):
                    if not n.order_by:
                        raise errors.TddlError(f"{fname}() requires ORDER BY")
                elif fname == "count" and (n.func.star or not n.func.args):
                    arg = ir.lit(1)
                else:
                    if not n.func.args:
                        raise errors.TddlError(f"{fname}() needs an argument")
                    arg = self._bind_expr(n.func.args[0], scope)
                    if fname in ("lag", "lead") and len(n.func.args) > 1:
                        off = self._bind_expr(n.func.args[1], scope)
                        if not isinstance(off, ir.Literal):
                            raise errors.NotSupportedError(
                                "lag/lead offset must be a literal")
                        offset = int(off.value)
                out_id = self.fresh(fname)
                call = L.WindowCall(fname, arg, out_id, offset, frame)
                calls.append(call)
                rep[id(n)] = ir.ColRef(out_id, call.dtype,
                                       _find_dictionary(arg) if arg is not None and
                                       arg.dtype.is_string else None)
        for parts, orders, calls in groups.values():
            node = L.Window(node, parts, orders, calls)
        # window outputs become visible to ORDER BY via the select aliases only
        return node, rep

    # -- aggregation -------------------------------------------------------------

    def _contains_agg(self, sel: ast.Select) -> bool:
        exprs = [i.expr for i in sel.items]
        if sel.having is not None:
            exprs.append(sel.having)
        for e in exprs:
            # sum(x) OVER (...) is a window call, not a grouping aggregate
            win_funcs = {id(n.func) for n in _ast_walk(e)
                         if isinstance(n, ast.WindowExpr)}
            for n in _ast_walk(e):
                if isinstance(n, ast.Func) and n.name in _AGG_FUNCS and \
                        id(n) not in win_funcs:
                    return True
        return False

    def _expand_grouping_sets(self, node: L.RelNode, sel: ast.Select,
                              groups, aggs) -> L.RelNode:
        """ROLLUP/CUBE/GROUPING SETS as a UNION ALL of one Aggregate per grouping
        set over the shared child — absent keys project as typed NULLs carrying
        the column's dictionary (the extra-lexsort-pass-per-set strategy; MySQL
        WITH ROLLUP semantics: subtotal rows have NULL in rolled-up columns)."""
        n = len(groups)
        if sel.grouping_sets is not None:
            sets = self._gs_membership
        elif sel.group_modifier == "rollup":
            sets = [list(range(k)) for k in range(n, -1, -1)]
        else:  # cube
            sets = []
            for size in range(n, -1, -1):
                for comb in itertools.combinations(range(n), size):
                    sets.append(list(comb))
        branches = []
        for s in sets:
            member = set(s)
            # clone the shared child per branch: optimizer rules mutate subtrees
            # in place (column pruning), and branches prune differently
            agg_b = L.Aggregate(L.clone_tree(node), [groups[i] for i in s],
                                list(aggs))
            proj = []
            for i, (gid, ge) in enumerate(groups):
                if i in member:
                    proj.append((gid, ir.ColRef(gid, ge.dtype,
                                                _find_dictionary(ge))))
                else:
                    proj.append((gid, ir.Literal(
                        None, ge.dtype.with_nullable(True),
                        _find_dictionary(ge))))
            for a in aggs:
                d = _find_dictionary(a.arg) if (
                    a.arg is not None and a.arg.dtype.is_string and
                    a.kind in ("min", "max")) else None
                proj.append((a.out_id, ir.ColRef(a.out_id, a.dtype, d)))
            branches.append(L.Project(agg_b, proj))
        return L.Union(branches, True)

    def _bind_aggregate(self, node: L.RelNode, sel: ast.Select, scope: Scope):
        # 1. bind group keys
        groups: List[Tuple[str, ir.Expr]] = []
        group_map: Dict[Tuple, ir.ColRef] = {}
        alias_map = {i.alias.lower(): i.expr for i in sel.items if i.alias}

        def bind_group_expr(g: ast.ExprNode) -> ir.Expr:
            gexpr = g
            if isinstance(g, ast.NumberLit):
                ix = int(g.value) - 1
                if not 0 <= ix < len(sel.items):
                    raise errors.TddlError("GROUP BY ordinal out of range")
                gexpr = sel.items[ix].expr
            elif isinstance(g, ast.Name) and len(g.parts) == 1 and \
                    g.parts[0].lower() in alias_map and scope.resolve(g.parts) is None:
                gexpr = alias_map[g.parts[0].lower()]
            return self._bind_expr(gexpr, scope)

        def add_group(e: ir.Expr) -> int:
            k = e.key()
            ref = group_map.get(k)
            if ref is not None:
                return next(i for i, (gid, _) in enumerate(groups)
                            if gid == ref.name)
            gid = self.fresh("g")
            groups.append((gid, e))
            group_map[k] = ir.ColRef(gid, e.dtype, _find_dictionary(e))
            return len(groups) - 1

        self._gs_membership = None
        if sel.grouping_sets is not None:
            # GROUP BY GROUPING SETS: groups = ordered union of all set exprs;
            # remember each set's membership for the union expansion
            self._gs_membership = [
                sorted({add_group(bind_group_expr(g)) for g in s_ast})
                for s_ast in sel.grouping_sets]
        else:
            for g in sel.group_by:
                add_group(bind_group_expr(g))

        # 2. collect aggregate calls from select list + having + order by
        aggs: List[L.AggSpec] = []
        agg_map: Dict[Tuple, ir.ColRef] = {}

        def collect(e: ast.ExprNode):
            for n in _ast_walk(e):
                if isinstance(n, ast.Func) and n.name in _AGG_FUNCS:
                    arg = None if n.star else self._bind_expr(n.args[0], scope)
                    kind = "count_star" if (n.name == "count" and n.star) else n.name
                    key = (kind, arg.key() if arg is not None else None, n.distinct)
                    if key in agg_map:
                        continue
                    out_id = self.fresh(kind)
                    spec = L.AggSpec(kind, arg, out_id, n.distinct)
                    aggs.append(spec)
                    agg_map[key] = ir.ColRef(out_id, spec.dtype,
                                             _find_dictionary(arg) if arg is not None and
                                             arg.dtype.is_string else None)

        for i in sel.items:
            collect(i.expr)
        if sel.having is not None:
            # HAVING may contain uncorrelated scalar subqueries (Q11): binds later
            for conj in _ast_conjuncts(sel.having):
                if not self._has_scalar_subquery(conj):
                    collect(conj)
                else:
                    for n in _ast_walk(conj):
                        if not isinstance(n, ast.SubqueryExpr):
                            continue
                    collect(conj)
        for e, _ in sel.order_by:
            collect(e)

        # 3. DISTINCT aggregates: rewrite through a pre-aggregate on
        # (groups + distinct arg).  min/max(DISTINCT) == min/max, so their flag
        # drops.  Plain aggregates ride through the pre-aggregate as partials and
        # re-aggregate in the final pass (sum of sums / sum of counts / min of
        # mins), so ANY mix of one DISTINCT argument with plain aggregates works
        # — the reference's two-phase distinct-agg expansion without a join.
        aggs = [dataclasses.replace(a, distinct=False)
                if a.distinct and a.kind in ("min", "max") else a for a in aggs]
        distinct_aggs = [a for a in aggs if a.distinct]
        if distinct_aggs:
            bad = [a for a in distinct_aggs if a.kind not in ("count", "sum")]
            if bad:
                raise errors.NotSupportedError(
                    f"{bad[0].kind}(DISTINCT) not supported yet")
            if len({a.arg.key() for a in distinct_aggs}) > 1:
                raise errors.NotSupportedError(
                    "multiple different DISTINCT arguments in one aggregate")
            darg = distinct_aggs[0].arg
            did = self.fresh("d")
            pre_groups = list(groups) + [(did, darg)]
            pre_aggs: List[L.AggSpec] = []
            final_aggs: List[L.AggSpec] = []
            dref = ir.ColRef(did, darg.dtype, _find_dictionary(darg))
            merge_kind = {"sum": "sum", "count": "sum", "count_star": "sum",
                          "min": "min", "max": "max"}
            for a in aggs:
                if a.distinct:
                    # each pre-group holds one distinct (group, value): counting/
                    # summing the pre-group keys IS the distinct aggregate
                    final_aggs.append(L.AggSpec(a.kind, dref, a.out_id))
                    continue
                if a.kind == "avg":
                    raise errors.NotSupportedError(
                        "AVG mixed with DISTINCT aggregates not supported yet")
                pid = self.fresh(a.kind)
                pre_aggs.append(L.AggSpec(a.kind, a.arg, pid))
                pref = ir.ColRef(pid, pre_aggs[-1].dtype, None)
                final_aggs.append(L.AggSpec(merge_kind[a.kind], pref, a.out_id))
            pre = L.Aggregate(node, pre_groups, pre_aggs)
            regrouped = [(gid, ir.ColRef(gid, e.dtype, _find_dictionary(e)))
                         for gid, e in groups]
            node = L.Aggregate(pre, regrouped, final_aggs)
            groups = regrouped
            # group_map keeps the ORIGINAL group-expression keys: select items still
            # reference the source expressions, which map to the re-grouped ids
            if sel.group_modifier or sel.grouping_sets:
                raise errors.NotSupportedError(
                    "DISTINCT aggregates with ROLLUP/CUBE/GROUPING SETS")
        elif sel.group_modifier or sel.grouping_sets:
            node = self._expand_grouping_sets(node, sel, groups, aggs)
        else:
            node = L.Aggregate(node, groups, aggs)

        # helper: bind an expression in post-aggregate space
        def bind_post(e: ast.ExprNode) -> ir.Expr:
            rep: Dict[int, ir.Expr] = {}
            for n in _ast_walk(e):
                if isinstance(n, ast.Func) and n.name in _AGG_FUNCS:
                    arg = None if n.star else self._bind_expr(n.args[0], scope)
                    kind = "count_star" if (n.name == "count" and n.star) else n.name
                    key = (kind, arg.key() if arg is not None else None, n.distinct)
                    rep[id(n)] = agg_map[key]
            bound = self._bind_expr(e, scope, rep)
            return _substitute(bound, group_map)

        # 4. HAVING
        if sel.having is not None:
            having_parts = []
            for conj in _ast_conjuncts(sel.having):
                if self._has_scalar_subquery(conj):
                    node, e = self._bind_having_subquery(node, conj, scope, bind_post)
                    having_parts.append(e)
                else:
                    having_parts.append(bind_post(conj))
            node = L.Filter(node, ir.and_(*having_parts))

        # 5. select list
        out_exprs: List[Tuple[str, ir.Expr]] = []
        display_names: List[str] = []
        for item in sel.items:
            e = bind_post(item.expr)
            self._check_agg_refs(e, node)
            name = item.alias or self._display_name(item.expr)
            out_exprs.append((self.fresh(name.split(".")[-1]), e))
            display_names.append(name.split(".")[-1])
        return node, out_exprs, display_names

    def _bind_having_subquery(self, node: L.RelNode, conj: ast.ExprNode, scope: Scope,
                              bind_post) -> Tuple[L.RelNode, ir.Expr]:
        replacements: Dict[int, ir.Expr] = {}
        for n in _ast_walk(conj):
            if isinstance(n, ast.SubqueryExpr):
                if not isinstance(n.select, ast.Select):
                    raise errors.NotSupportedError(
                        "UNION subquery in HAVING not supported")
                plan, names, used = self.bind_select(n.select, scope)
                if used.correlated:
                    raise errors.NotSupportedError(
                        "correlated subquery in HAVING not supported")
                fields = plan.fields()
                fid, typ, d = fields[0]
                node = L.Join(node, plan, "cross", [])
                replacements[id(n)] = ir.ColRef(fid, typ, d)
        # rebuild the HAVING conjunct with agg refs and subquery refs
        rep2 = dict(replacements)
        for n in _ast_walk(conj):
            if isinstance(n, ast.Func) and n.name in _AGG_FUNCS and id(n) not in rep2:
                pass
        # bind via bind_post but inject subquery replacements
        e = self._bind_post_with_rep(conj, scope, bind_post, replacements)
        return node, e

    def _bind_post_with_rep(self, e: ast.ExprNode, scope: Scope, bind_post, rep):
        # bind_post handles agg substitution; wrap to also substitute subqueries
        marker: Dict[int, ir.Expr] = rep

        orig_bind_expr = self._bind_expr

        def patched(expr, sc, extra=None):
            merged = dict(marker)
            if extra:
                merged.update(extra)
            return orig_bind_expr(expr, sc, merged)

        self._bind_expr = patched  # type: ignore
        try:
            return bind_post(e)
        finally:
            self._bind_expr = orig_bind_expr  # type: ignore

    def _check_agg_refs(self, e: ir.Expr, node: L.RelNode):
        ids = set(node.field_ids())
        for n in ir.walk(e):
            if isinstance(n, ir.ColRef) and n.name not in ids:
                raise errors.TddlError(
                    f"column '{n.name}' must appear in GROUP BY or an aggregate")

    # -- ORDER BY ----------------------------------------------------------------

    def _bind_order(self, node: L.RelNode, sel: ast.Select, scope: Scope,
                    out_exprs, display_names, project_first: bool) -> L.RelNode:
        """Non-aggregate ORDER BY: project select outputs first, sort over them
        (underlying columns still available pre-projection)."""
        # bind sort keys against select aliases, ordinals, then scope
        alias_to_ref = {}
        for (oid, e), disp in zip(out_exprs, display_names):
            alias_to_ref[disp.lower()] = ir.ColRef(oid, e.dtype, _find_dictionary(e))
        keys: List[Tuple[ir.Expr, bool]] = []
        extra: List[Tuple[str, ir.Expr]] = []
        for oexpr, desc in sel.order_by:
            if isinstance(oexpr, ast.NumberLit):
                ix = int(oexpr.value) - 1
                oid, e = out_exprs[ix]
                keys.append((ir.ColRef(oid, e.dtype, _find_dictionary(e)), desc))
            elif isinstance(oexpr, ast.Name) and len(oexpr.parts) == 1 and \
                    oexpr.parts[0].lower() in alias_to_ref:
                keys.append((alias_to_ref[oexpr.parts[0].lower()], desc))
            else:
                e = self._bind_expr(oexpr, scope)
                kid = self.fresh("sk")
                extra.append((kid, e))
                ref = ir.ColRef(kid, e.dtype, _find_dictionary(e))
                from galaxysql_tpu.types import collation as _coll
                if _coll.collation_of_expr(e) is not None:
                    # the hidden sort column holds fold-class representative
                    # codes; the SORT must rank them under the collation, so
                    # the collation tag rides the key reference
                    ref.meta = e.meta
                keys.append((ref, desc))
        node = L.Project(node, out_exprs + extra)
        node = L.Sort(node, keys, sel.limit and self._limit_value(sel.limit),
                      self._limit_value(sel.offset) if sel.offset else 0)
        if extra:
            node = L.Project(node, [(oid, ir.ColRef(oid, e.dtype, _find_dictionary(e)))
                                    for oid, e in out_exprs])
        return node

    def _bind_order_agg(self, node: L.RelNode, sel: ast.Select, out_exprs,
                        display_names) -> L.RelNode:
        agg_ids = {fid: (typ, d) for fid, typ, d in node.fields()}
        alias_to_ref = {}
        for (oid, e), disp in zip(out_exprs, display_names):
            alias_to_ref[disp.lower()] = (oid, e)
        keys: List[Tuple[ir.Expr, bool]] = []
        proj = L.Project(node, out_exprs)
        for oexpr, desc in sel.order_by:
            if isinstance(oexpr, ast.NumberLit):
                ix = int(oexpr.value) - 1
                oid, e = out_exprs[ix]
                keys.append((ir.ColRef(oid, e.dtype, _find_dictionary(e)), desc))
            elif isinstance(oexpr, ast.Name) and len(oexpr.parts) == 1 and \
                    oexpr.parts[0].lower() in alias_to_ref:
                oid, e = alias_to_ref[oexpr.parts[0].lower()]
                keys.append((ir.ColRef(oid, e.dtype, _find_dictionary(e)), desc))
            else:
                # expression over group keys: match by re-binding through out_exprs
                matched = None
                for (oid, e), disp in zip(out_exprs, display_names):
                    if isinstance(oexpr, ast.Name) and \
                            disp.lower() == oexpr.parts[-1].lower():
                        matched = ir.ColRef(oid, e.dtype, _find_dictionary(e))
                        break
                if matched is None:
                    raise errors.NotSupportedError(
                        "ORDER BY expression must reference select outputs "
                        "in aggregate queries")
                keys.append((matched, desc))
        return L.Sort(proj, keys, sel.limit and self._limit_value(sel.limit),
                      self._limit_value(sel.offset) if sel.offset else 0)

    def _apply_limit(self, node: L.RelNode, sel: ast.Select) -> L.RelNode:
        if sel.limit is None:
            return node
        if isinstance(node, L.Sort) and node.limit is not None:
            return node  # limit already fused into sort
        if isinstance(node, L.Sort):
            node.limit = self._limit_value(sel.limit)
            node.offset = self._limit_value(sel.offset) if sel.offset else 0
            return node
        return L.Limit(node, self._limit_value(sel.limit),
                       self._limit_value(sel.offset) if sel.offset else 0)

    def _limit_value(self, e) -> int:
        if isinstance(e, ast.NumberLit):
            return int(e.value)
        if isinstance(e, ast.ParamRef):
            return int(self.params[e.index])
        if isinstance(e, int):
            return e
        raise errors.TddlError("LIMIT must be a literal")

    # -- star expansion -------------------------------------------------------

    def _expand_stars(self, items: Sequence[ast.SelectItem], scope: Scope
                      ) -> List[ast.SelectItem]:
        out: List[ast.SelectItem] = []
        for item in items:
            if isinstance(item.expr, ast.Star):
                q = item.expr.qualifier
                for alias, fs in scope.entries:
                    if q and alias != q[-1].lower():
                        continue
                    for fid, typ, d in fs:
                        out.append(ast.SelectItem(
                            ast.Name(fid.split(".")), None))
                if q and not any(a == q[-1].lower() for a, _ in scope.entries):
                    raise errors.UnknownTableError(f"Unknown table '{q[-1]}'")
            else:
                out.append(item)
        return out

    def _display_name(self, e: ast.ExprNode) -> str:
        if isinstance(e, ast.Name):
            return e.parts[-1]
        if isinstance(e, ast.Func):
            return e.name
        return "expr"

    # ------------------------------------------------------------------ expressions

    def _bind_expr(self, e: ast.ExprNode, scope: Scope,
                   replacements: Optional[Dict[int, ir.Expr]] = None) -> ir.Expr:
        rep = replacements or {}
        if id(e) in rep:
            return rep[id(e)]
        if isinstance(e, ast.Name):
            return scope.resolve_or_correlate(e.parts)
        if isinstance(e, ast.NumberLit):
            # MySQL semantics: a dotted numeric literal is an exact DECIMAL, not a
            # double — 0.06 - 0.01 must be exactly 0.05 (textual scale preserved)
            t = e.text
            if "." in t and "e" not in t.lower():
                scale = min(len(t.split(".")[1]), 8)
                return ir.Literal(float(t), dt.decimal(18, scale))
            return ir.lit(e.value)
        if isinstance(e, ast.StringLit):
            return ir.lit(e.value)
        if isinstance(e, ast.NullLit):
            return ir.lit(None, dt.NULLTYPE)
        if isinstance(e, ast.BoolLit):
            return ir.lit(e.value, dt.BOOL)
        if isinstance(e, ast.ParamRef):
            if e.index >= len(self.params):
                raise errors.TddlError("not enough parameters bound")
            v = self.params[e.index]
            from galaxysql_tpu.sql.parameterize import DecimalParam
            if isinstance(v, DecimalParam):
                return ir.Literal(v.value, dt.decimal(18, v.scale))
            return ir.lit(v)
        if isinstance(e, ast.DateLit):
            if e.kind == "date":
                return ir.Literal(temporal.parse_date(e.value), dt.DATE)
            return ir.Literal(temporal.parse_datetime(e.value), dt.DATETIME)
        if isinstance(e, ast.Unary):
            arg = self._bind_expr(e.arg, scope, rep)
            if e.op == "-":
                if isinstance(arg, ir.Literal) and arg.value is not None and \
                        not arg.dtype.is_temporal:
                    return ir.Literal(-arg.value, arg.dtype)
                return ir.call("neg", arg)
            if e.op == "not":
                return ir.call("not", arg)
            raise errors.NotSupportedError(f"unary {e.op}")
        if isinstance(e, ast.Collate):
            return self._bind_collate(e, scope, rep)
        if isinstance(e, ast.Binary):
            return self._bind_binary(e, scope, rep)
        if isinstance(e, ast.BetweenExpr):
            arg = self._bind_expr(e.arg, scope, rep)
            lo = self._bind_expr(e.low, scope, rep)
            hi = self._bind_expr(e.high, scope, rep)
            b = ir.call("between", arg, lo, hi)
            return ir.call("not", b) if e.negated else b
        if isinstance(e, ast.LikeExpr):
            arg = self._bind_expr(e.arg, scope, rep)
            pat = self._bind_expr(e.pattern, scope, rep)
            return ir.Call("not_like" if e.negated else "like", [arg, pat], dt.BOOL)
        if isinstance(e, ast.IsNullExpr):
            arg = self._bind_expr(e.arg, scope, rep)
            return ir.call("is_not_null" if e.negated else "is_null", arg)
        if isinstance(e, ast.InExpr):
            if e.select is not None:
                raise errors.NotSupportedError(
                    "IN subquery only supported as a top-level WHERE conjunct")
            arg = self._bind_expr(e.arg, scope, rep)
            values = []
            for item in e.items:
                v = self._bind_expr(item, scope, rep)
                if not isinstance(v, ir.Literal):
                    raise errors.NotSupportedError("IN list must be literals")
                if v.dtype.is_temporal or arg.dtype.is_temporal:
                    values.append(v.value)
                else:
                    values.append(v.value)
            return ir.InList(arg, tuple(values), e.negated)
        if isinstance(e, ast.CaseExpr):
            return self._bind_case(e, scope, rep)
        if isinstance(e, ast.CastExpr):
            arg = self._bind_expr(e.arg, scope, rep)
            target = dt.from_sql_name({"SIGNED": "BIGINT", "UNSIGNED": "BIGINT UNSIGNED",
                                       "CHAR": "VARCHAR"}.get(e.type_name, e.type_name),
                                      e.precision, e.scale)
            return ir.Cast(arg, target)
        if isinstance(e, ast.ExtractExpr):
            arg = self._bind_expr(e.arg, scope, rep)
            unit = e.unit.lower()
            if unit == "year":
                return ir.call("year", arg)
            if unit == "month":
                return ir.call("month", arg)
            if unit == "day":
                return ir.call("dayofmonth", arg)
            if unit == "quarter":
                return ir.call("quarter", arg)
            if unit == "year_month":
                return ir.call("extract_year_month", arg)
            raise errors.NotSupportedError(f"EXTRACT({e.unit})")
        if isinstance(e, ast.Func):
            return self._bind_func(e, scope, rep)
        if isinstance(e, ast.SubqueryExpr):
            raise errors.NotSupportedError(
                "scalar subquery not supported in this position")
        if isinstance(e, ast.ExistsExpr):
            raise errors.NotSupportedError(
                "EXISTS only supported as a top-level WHERE conjunct")
        if isinstance(e, ast.IntervalLit):
            raise errors.TddlError("INTERVAL literal outside date arithmetic")
        raise errors.NotSupportedError(f"expression {type(e).__name__}")

    def _bind_collate(self, e: ast.Collate, scope, rep) -> ir.Expr:
        """expr COLLATE name: lower to a fold-class representative-code
        translation (one device gather), so equality/grouping under the
        collation is integer equality of translated codes (common/collation/*
        analog).  The node is tagged so comparisons fold the literal side to
        its class representative too."""
        from galaxysql_tpu.types import collation as coll
        inner = self._bind_expr(e.arg, scope, rep)
        if not inner.dtype.is_string:
            raise errors.NotSupportedError("COLLATE on a non-string expression")
        coll.fold_fn(e.name)  # validate the collation name eagerly
        if isinstance(inner, ir.Literal):
            # 'lit' COLLATE ci: the collation governs the COMPARISON; carry a
            # marker the comparison binder resolves against the column side
            m = ir.Call("collate_lit", [inner], inner.dtype)
            m.meta = (None, "collate", e.name.lower())
            return m
        d = _find_dictionary(inner)
        if d is None:
            raise errors.NotSupportedError(
                "COLLATE needs a dictionary-backed string")
        table = coll.rep_table(d, e.name)
        c = ir.Call("dict_transform", [inner], inner.dtype, dictionary=d)
        c.meta = (table, "collate", e.name.lower())
        return c

    @staticmethod
    def _collation_of(x: ir.Expr):
        if isinstance(x, ir.Call) and x.op in ("dict_transform", "collate_lit") \
                and x.meta is not None and len(x.meta) >= 3 \
                and x.meta[1] == "collate":
            return x.meta[2]
        return None

    def _bind_binary(self, e: ast.Binary, scope, rep) -> ir.Expr:
        op_map = {"=": "eq", "<>": "ne", "<": "lt", "<=": "le", ">": "gt", ">=": "ge",
                  "and": "and", "or": "or", "+": "add", "-": "sub", "*": "mul",
                  "/": "div", "%": "mod", "div": "div", "xor": "ne"}
        # interval arithmetic: date +/- INTERVAL n unit
        if e.op in ("+", "-") and isinstance(e.right, ast.IntervalLit):
            base = self._bind_expr(e.left, scope, rep)
            return self._bind_interval_add(base, e.right, e.op == "-", scope, rep)
        if e.op == "+" and isinstance(e.left, ast.IntervalLit):
            base = self._bind_expr(e.right, scope, rep)
            return self._bind_interval_add(base, e.left, False, scope, rep)
        op = op_map.get(e.op)
        if op is None:
            raise errors.NotSupportedError(f"operator {e.op}")
        a = self._bind_expr(e.left, scope, rep)
        b = self._bind_expr(e.right, scope, rep)
        if op in ("eq", "ne", "lt", "le", "gt", "ge"):
            cname = self._collation_of(a) or self._collation_of(b)
            if cname is not None:
                from galaxysql_tpu.types import collation as coll
                # unwrap literal-side markers; translate the column side to
                # fold-class representative codes; fold the literal to its
                # class representative so codes compare consistently
                if isinstance(a, ir.Call) and a.op == "collate_lit":
                    a = a.args[0]
                if isinstance(b, ir.Call) and b.op == "collate_lit":
                    b = b.args[0]

                def colled(x):
                    if self._collation_of(x) is not None:
                        return x
                    d = _find_dictionary(x)
                    if d is None or isinstance(x, ir.Literal):
                        return x
                    t = coll.rep_table(d, cname)
                    c = ir.Call("dict_transform", [x], x.dtype, dictionary=d)
                    c.meta = (t, "collate", cname)
                    return c
                a, b = colled(a), colled(b)
                for side, other in ((a, b), (b, a)):
                    if isinstance(other, ir.Literal) and \
                            isinstance(other.value, str):
                        d = _find_dictionary(side)
                        if d is not None:
                            other.value = coll.rep_text(d, cname, other.value)
        if op == "div" and e.op == "div":
            return ir.Cast(ir.call("div", a, b), dt.BIGINT)
        return ir.call(op, a, b)

    def _bind_interval_add(self, base: ir.Expr, iv: ast.IntervalLit, negate: bool,
                           scope, rep) -> ir.Expr:
        n_e = self._bind_expr(iv.value, scope, rep)
        if isinstance(n_e, ir.Literal):
            n = int(n_e.value)
        else:
            raise errors.NotSupportedError("INTERVAL value must be a literal")
        if negate:
            n = -n
        unit = iv.unit
        if unit == "DAY":
            return ir.call("date_add_days", base, ir.lit(n))
        if unit == "WEEK":
            return ir.call("date_add_days", base, ir.lit(n * 7))
        if unit == "MONTH":
            return ir.call("date_add_months", base, ir.lit(n))
        if unit == "QUARTER":
            return ir.call("date_add_months", base, ir.lit(n * 3))
        if unit == "YEAR":
            return ir.call("date_add_months", base, ir.lit(n * 12))
        raise errors.NotSupportedError(f"INTERVAL {unit}")

    def _bind_case(self, e: ast.CaseExpr, scope, rep) -> ir.Expr:
        whens = []
        for c, v in e.whens:
            if e.operand is not None:
                cond = ir.call("eq", self._bind_expr(e.operand, scope, rep),
                               self._bind_expr(c, scope, rep))
            else:
                cond = self._bind_expr(c, scope, rep)
            whens.append((cond, self._bind_expr(v, scope, rep)))
        default = self._bind_expr(e.else_, scope, rep) if e.else_ is not None else None
        # result type: common type over branch values
        t = whens[0][1].dtype
        for _, v in whens[1:]:
            t = dt.common_type(t, v.dtype)
        if default is not None:
            t = dt.common_type(t, default.dtype)
        return ir.Case(whens, default, t)

    def _bind_func(self, e: ast.Func, scope, rep) -> ir.Expr:
        name = e.name
        if name in _AGG_FUNCS:
            raise errors.TddlError(f"misplaced aggregate function {name}()")
        args = [self._bind_expr(a, scope, rep) for a in e.args]
        if name in _SCALAR_FUNC_OPS:
            return ir.call(_SCALAR_FUNC_OPS[name], *args)
        if name in ("date_add", "adddate"):
            raise errors.NotSupportedError("use + INTERVAL syntax")
        if name in ("substring", "substr", "left", "upper", "lower", "ltrim", "rtrim",
                    "trim", "reverse"):
            return self._bind_string_func(name, args, e)
        if name == "concat":
            return self._bind_concat(args)
        if name == "nullif":
            cond = ir.call("eq", args[0], args[1])
            return ir.Case([(cond, ir.lit(None, args[0].dtype))], args[0],
                           args[0].dtype)
        if name == "round":
            if len(args) == 1 or (isinstance(args[1], ir.Literal)
                                  and int(args[1].value) == 0):
                return ir.Cast(args[0], dt.BIGINT) if args[0].dtype.clazz != \
                    dt.TypeClass.DECIMAL else ir.Cast(args[0], dt.decimal(18, 0))
            d = int(args[1].value)
            return ir.Cast(args[0], dt.decimal(18, max(d, 0)))
        if name in ("now", "current_timestamp", "current_date", "curdate"):
            import time
            us = int(time.time() * 1_000_000)
            if name in ("current_date", "curdate"):
                return ir.Literal(us // temporal.MICROS_PER_DAY, dt.DATE)
            return ir.Literal(us, dt.DATETIME)
        if name == "database":
            return _const_str(self.default_schema)
        if name == "version":
            return _const_str("8.0.3-galaxysql-tpu")
        if name in ("nextval", "seq_nextval"):
            if not args or not isinstance(args[0], ir.Literal):
                raise errors.TddlError("NEXTVAL requires a sequence name literal")
            seq_name = str(args[0].value)
            v = self.sequence_hook(seq_name) if self.sequence_hook else 0
            return ir.lit(int(v))
        if name == "connection_id":
            return ir.lit(int(self.connection_id or 0))
        if name in ("get_lock", "release_lock", "is_free_lock", "is_used_lock"):
            # user-level advisory locks (LockingFunctionManager.java): evaluated
            # at bind with session identity; never plan-cached (side effects)
            if self.lock_fn_hook is None:
                raise errors.NotSupportedError(f"{name.upper()} outside a session")
            vals = []
            for a in args:
                if not isinstance(a, ir.Literal):
                    raise errors.TddlError(f"{name.upper()} requires literal args")
                vals.append(a.value)
            r = self.lock_fn_hook(name, vals)
            return ir.lit(None, dt.NULLTYPE) if r is None else ir.lit(int(r))
        if name == "@@":
            raise errors.NotSupportedError("system variable in expression")
        if name == "length" or name == "char_length":
            arg = args[0]
            d = _find_dictionary(arg)
            if d is None:
                raise errors.NotSupportedError("LENGTH on non-string")
            table = np.array([len(v) for v in d.values] or [0], dtype=np.int64)
            c = ir.Call("dict_transform", [arg], dt.BIGINT)
            c.meta = (table,)
            return c
        raise errors.NotSupportedError(f"function {name}()")

    def _bind_string_func(self, name: str, args: List[ir.Expr], e: ast.Func) -> ir.Expr:
        arg = args[0]
        d = _find_dictionary(arg)
        if d is None or not arg.dtype.is_string:
            raise errors.NotSupportedError(f"{name}() requires a string column")

        def fn(s: str) -> str:
            if name in ("substring", "substr"):
                start = int(args[1].value)
                ln = int(args[2].value) if len(args) > 2 else None
                if start > 0:
                    base = start - 1
                elif start < 0:
                    base = len(s) + start
                else:
                    return ""
                return s[base:base + ln] if ln is not None else s[base:]
            if name == "left":
                return s[:int(args[1].value)]
            if name == "upper":
                return s.upper()
            if name == "lower":
                return s.lower()
            if name == "ltrim":
                return s.lstrip()
            if name == "rtrim":
                return s.rstrip()
            if name == "trim":
                return s.strip()
            if name == "reverse":
                return s[::-1]
            raise AssertionError(name)

        derived = Dictionary()
        trans = np.array([derived.encode_one(fn(v)) for v in d.values] or [0],
                         dtype=np.int32)
        c = ir.Call("dict_transform", [arg], dt.VARCHAR)
        c.dictionary = derived
        c.meta = (trans,)
        return c

    def _bind_concat(self, args: List[ir.Expr]) -> ir.Expr:
        # concat over one dict column + literals: host dictionary transform
        col_args = [a for a in args if not isinstance(a, ir.Literal)]
        if len(col_args) != 1:
            raise errors.NotSupportedError(
                "CONCAT supports one column plus literals for now")
        col = col_args[0]
        d = _find_dictionary(col)
        if d is None:
            raise errors.NotSupportedError("CONCAT requires a string column")
        derived = Dictionary()
        trans = np.zeros(max(len(d), 1), dtype=np.int32)
        for code, v in enumerate(d.values):
            parts = []
            for a in args:
                parts.append(str(a.value) if isinstance(a, ir.Literal) else v)
            trans[code] = derived.encode_one("".join(parts))
        c = ir.Call("dict_transform", [col], dt.VARCHAR)
        c.dictionary = derived
        c.meta = (trans,)
        return c


def _const_str(s: str) -> ir.Expr:
    """A constant string expression carrying its own single-entry dictionary."""
    c = ir.Call("dict_transform", [ir.lit(0, dt.INT)], dt.VARCHAR)
    c.dictionary = Dictionary([s])
    c.meta = (np.zeros(1, dtype=np.int32),)
    return c


# ---------------------------------------------------------------------------
# AST / IR walking helpers
# ---------------------------------------------------------------------------

def _ast_conjuncts(e: ast.ExprNode):
    if isinstance(e, ast.Binary) and e.op == "and":
        yield from _ast_conjuncts(e.left)
        yield from _ast_conjuncts(e.right)
    else:
        yield e


def _ast_walk(e):
    yield e
    if isinstance(e, ast.ExprNode):
        for f in getattr(e, "__dataclass_fields__", {}):
            v = getattr(e, f)
            if isinstance(v, ast.ExprNode):
                yield from _ast_walk(v)
            elif isinstance(v, (list, tuple)):
                for x in v:
                    if isinstance(x, ast.ExprNode):
                        yield from _ast_walk(x)
                    elif isinstance(x, tuple):
                        for y in x:
                            if isinstance(y, ast.ExprNode):
                                yield from _ast_walk(y)


def _substitute(e: ir.Expr, mapping: Dict[Tuple, ir.Expr]) -> ir.Expr:
    if e.key() in mapping:
        return mapping[e.key()]
    if isinstance(e, ir.Call):
        new_args = [_substitute(a, mapping) for a in e.args]
        c = ir.Call(e.op, new_args, e.dtype, e.dictionary, e.meta)
        return c
    if isinstance(e, ir.Cast):
        return ir.Cast(_substitute(e.arg, mapping), e.dtype)
    if isinstance(e, ir.InList):
        return ir.InList(_substitute(e.arg, mapping), e.values, e.negated, e.dtype)
    if isinstance(e, ir.Case):
        whens = [(_substitute(c, mapping), _substitute(v, mapping)) for c, v in e.whens]
        default = _substitute(e.default, mapping) if e.default is not None else None
        return ir.Case(whens, default, e.dtype)
    return e
