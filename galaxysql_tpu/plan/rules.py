"""Rule-based rewrites + greedy cost-guided join ordering.

Reference analog: the RBO push-down rule set + CBO join reorder of `core/planner/rule`
(SURVEY.md §2.5).  Kept deliberately small: the four rewrites below shape all of TPC-H.

1. factor_or_conjuncts — Q19 pattern: (A and X) or (B and X) -> X and (A or B), so the
   shared equi predicate becomes a join key.
2. build_join_tree — flatten cross-join forests + the WHERE conjunction into a join
   graph; greedily order joins smallest-estimated-first (broadcast/filtered dimensions
   join early), emitting equi joins with residuals.
3. push_filters / prune_columns — classic pushdown; scans read only referenced columns.
4. prune_partitions — point/range predicates on partition columns shrink scanned shards
   (`PartitionPruner` analog).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from galaxysql_tpu.expr import ir
from galaxysql_tpu.meta.catalog import PartitionRouter
from galaxysql_tpu.plan import logical as L
from galaxysql_tpu.types import datatype as dt

# ---------------------------------------------------------------------------
# expression helpers
# ---------------------------------------------------------------------------


def conjuncts(e: Optional[ir.Expr]) -> List[ir.Expr]:
    if e is None:
        return []
    if isinstance(e, ir.Call) and e.op == "and":
        return conjuncts(e.args[0]) + conjuncts(e.args[1])
    return [e]


def disjuncts(e: ir.Expr) -> List[ir.Expr]:
    if isinstance(e, ir.Call) and e.op == "or":
        return disjuncts(e.args[0]) + disjuncts(e.args[1])
    return [e]


def factor_or_conjuncts(e: ir.Expr) -> ir.Expr:
    """(A ∧ X ∧ ...) ∨ (B ∧ X ∧ ...) -> X ∧ ((A ∧ ...) ∨ (B ∧ ...))."""
    ds = disjuncts(e)
    if len(ds) < 2:
        return e
    sets = [{c.key(): c for c in conjuncts(d)} for d in ds]
    common_keys = set(sets[0])
    for s in sets[1:]:
        common_keys &= set(s)
    if not common_keys:
        return e
    common = [sets[0][k] for k in common_keys]
    rest = []
    for d, s in zip(ds, sets):
        remaining = [c for c in conjuncts(d) if c.key() not in common_keys]
        rest.append(ir.and_(*remaining) if remaining else ir.lit(True, dt.BOOL))
    return ir.and_(*(common + [ir.or_(*rest)]))


# ---------------------------------------------------------------------------
# join tree construction
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _Rel:
    node: L.RelNode
    ids: Set[str]
    est_rows: float


def estimate_rows(node: L.RelNode) -> float:
    """Cheap cardinality estimate for ordering decisions (stats-backed at scans)."""
    if isinstance(node, L.Scan):
        return max(float(node.table.stats.row_count), 1.0)
    if isinstance(node, L.Filter):
        sel = 1.0
        resolver = _stats_resolver(node.child)
        for c in conjuncts(node.cond):
            sel *= _selectivity(c, resolver)
        return max(estimate_rows(node.child) * sel, 1.0)
    if isinstance(node, L.Project):
        return estimate_rows(node.child)
    if isinstance(node, L.Aggregate):
        base = estimate_rows(node.child)
        if not node.groups:
            return 1.0
        return max(base ** 0.7, 1.0)
    if isinstance(node, L.Join):
        l = estimate_rows(node.left)
        r = estimate_rows(node.right)
        if node.kind == "cross":
            return l * r
        if node.kind in ("semi", "anti"):
            return l * 0.5
        return max(l, r)  # FK-join heuristic
    if isinstance(node, L.Sort):
        n = estimate_rows(node.child)
        return min(n, node.limit) if node.limit else n
    if isinstance(node, L.Limit):
        return float(node.limit)
    if isinstance(node, L.Union):
        return sum(estimate_rows(c) for c in node.children)
    if isinstance(node, L.Values):
        return float(len(node.rows))
    return 1000.0


def _stats_resolver(node: L.RelNode):
    """field_id -> (TableMeta, column_name) over every Scan under `node`."""
    out: Dict[str, Tuple] = {}
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, L.Scan):
            for out_id, col in n.columns:
                out[out_id] = (n.table, col)
        else:
            stack.extend(n.children)
    return out


def _lit_lane_value(e: ir.Literal, col_dtype) -> Optional[float]:
    """Literal -> lane-domain float comparable against histogram bounds."""
    from galaxysql_tpu.expr.compiler import _encode_literal_value
    try:
        v = _encode_literal_value(e.value, col_dtype)
    except (TypeError, ValueError):
        return None
    return float(v) if not isinstance(v, str) else None


def _col_lit_cmp(c: ir.Call):
    """(colref, literal, flipped) for a simple column-vs-literal comparison."""
    a, b = c.args[0], c.args[1]
    if isinstance(a, ir.ColRef) and isinstance(b, ir.Literal) and \
            b.value is not None:
        return a, b, False
    if isinstance(b, ir.ColRef) and isinstance(a, ir.Literal) and \
            a.value is not None:
        return b, a, True
    return None


_FLIP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}


def _selectivity(c: ir.Expr, resolver=None) -> float:
    """Predicate selectivity: histogram/NDV-backed when ANALYZE has run
    (Histogram.java / statistic/ndv analog), fixed guesses otherwise."""
    if isinstance(c, ir.Call):
        stats = None
        if resolver is not None and c.op in ("eq", "ne", "lt", "le", "gt", "ge") \
                and len(c.args) == 2:
            cl = _col_lit_cmp(c)
            if cl is not None:
                col, lit, flipped = cl
                tmcol = resolver.get(col.name)
                if tmcol is not None:
                    tm, cname = tmcol
                    cm = tm.column(cname)
                    hist = tm.stats.histograms.get(cm.name)
                    ndv = tm.stats.ndv.get(cm.name, 0)
                    op = _FLIP.get(c.op, c.op) if flipped else c.op
                    if op in ("eq", "ne") and ndv > 0:
                        f = 1.0 / ndv
                        return max(min(f if op == "eq" else 1.0 - f, 1.0), 1e-9)
                    if hist is not None and op in ("lt", "le", "gt", "ge"):
                        v = _lit_lane_value(lit, cm.dtype)
                        if v is not None:
                            le = hist.frac_le(v)
                            eq = hist.frac_eq(v)
                            if op == "le":
                                f = le
                            elif op == "lt":
                                f = le - eq
                            elif op == "gt":
                                f = 1.0 - le
                            else:
                                f = 1.0 - le + eq
                            return max(min(f, 1.0), 1e-9)
        if c.op == "eq":
            return 0.05
        if c.op in ("lt", "le", "gt", "ge"):
            return 0.3
        if c.op == "between":
            return 0.25
        if c.op in ("like",):
            return 0.1
        if c.op == "or":
            return min(sum(_selectivity(d, resolver) for d in disjuncts(c)), 1.0)
        if c.op == "and":
            s = 1.0
            for d in conjuncts(c):
                s *= _selectivity(d, resolver)
            return s
        if c.op == "ne":
            return 0.9
    if isinstance(c, ir.InList):
        return min(0.05 * max(len(c.values), 1), 1.0)
    return 0.5


def _rel_label(node: L.RelNode) -> str:
    """Stable identity of a join-forest member for SPM baselines: the scanned
    table when the member bottoms out in one, else a field-id digest."""
    n = node
    while isinstance(n, (L.Filter, L.Project)):
        n = n.children[0]
    if isinstance(n, L.Scan):
        return f"{n.table.schema.lower()}.{n.table.name.lower()}"
    return "rel:" + ",".join(sorted(node.field_ids())[:4])


def build_join_tree(node: L.RelNode, spm=None) -> L.RelNode:
    """Rewrite Filter-over-cross-join forests into ordered equi-join trees.

    `spm` (plan/spm.py SpmContext) makes the join order externally pinnable:
    the chosen member order of every forest is reported out, and a forced
    order — an accepted SPM baseline — overrides the greedy cost choice when
    its labels still match the forest (PlanManager.java:92 accepted plans)."""
    node = _rewrite_children(node, lambda c: build_join_tree(c, spm))
    preds: List[ir.Expr] = []
    base = node
    if isinstance(node, L.Filter):
        preds = [factor_or_conjuncts(c) for c in conjuncts(node.cond)]
        # factoring may expose new conjuncts
        preds = [c2 for p in preds for c2 in conjuncts(p)]
        base = node.child
    rels = _flatten_crosses(base)
    if len(rels) <= 1 and not isinstance(base, L.Join):
        return L.Filter(base, ir.and_(*preds)) if preds else base
    if not any(isinstance(r, L.Join) and r.kind == "cross" for r in [base]) and \
            len(rels) == 1:
        return L.Filter(base, ir.and_(*preds)) if preds else base

    relinfos = [_Rel(r, set(r.field_ids()), 0.0) for r in rels]

    # split predicates: single-rel -> push down; two-rel equi -> join edges; rest -> later
    edges: List[Tuple[int, int, ir.Expr, ir.Expr]] = []
    residual_preds: List[ir.Expr] = []
    local: Dict[int, List[ir.Expr]] = {i: [] for i in range(len(relinfos))}
    for p in preds:
        refs = set(ir.referenced_columns(p))
        owners = [i for i, ri in enumerate(relinfos) if refs & ri.ids]
        if len(owners) == 0:
            residual_preds.append(p)  # constant predicate
        elif len(owners) == 1:
            local[owners[0]].append(p)
        elif len(owners) == 2 and isinstance(p, ir.Call) and p.op == "eq":
            a, b = p.args
            ra, rb = set(ir.referenced_columns(a)), set(ir.referenced_columns(b))
            i, j = owners
            if ra <= relinfos[i].ids and rb <= relinfos[j].ids:
                edges.append((i, j, a, b))
            elif ra <= relinfos[j].ids and rb <= relinfos[i].ids:
                edges.append((j, i, a, b))
            else:
                residual_preds.append(p)
        else:
            residual_preds.append(p)

    for i, ps in local.items():
        if ps:
            relinfos[i] = _Rel(L.Filter(relinfos[i].node, ir.and_(*ps)),
                               relinfos[i].ids, 0.0)
    for ri in relinfos:
        ri.est_rows = estimate_rows(ri.node)

    # greedy: start at the smallest relation, repeatedly join the connected relation
    # with the smallest estimate; unconnected relations fall back to cross joins last.
    # An applicable SPM forced order replaces every greedy choice verbatim.
    labels = [_rel_label(r) for r in rels]
    forced_seq = None
    # SPM only engages on forests with equi-join edges: predicate-free inner
    # cross levels are re-flattened and re-ordered by the enclosing call, and
    # recording them would misalign the per-forest force/capture sequence
    spm_active = spm is not None and bool(edges)
    if spm_active:
        f = spm.next_forced()
        if f is not None and sorted(f) == sorted(labels):
            forced_seq = list(f)

    by_label: Dict[str, List[int]] = {}
    for i, lab in enumerate(labels):
        by_label.setdefault(lab, []).append(i)

    # field id -> (TableMeta, column) for NDV-backed join cardinalities
    resolver: Dict[str, Tuple] = {}
    for r in rels:
        resolver.update(_stats_resolver(r))

    def _ndv_of(e: ir.Expr, side_est: float) -> float:
        if isinstance(e, ir.ColRef):
            tmcol = resolver.get(e.name)
            if tmcol is not None:
                ndv = tmcol[0].stats.ndv.get(tmcol[1]) or \
                    tmcol[0].stats.ndv.get(tmcol[0].column(tmcol[1]).name, 0)
                if ndv:
                    return float(ndv)
        return side_est  # no stats: V(R, a) ~ |R| (FK assumption)

    def join_est(ca: "_Rel", cb: "_Rel", pair_edges) -> float:
        """System-R cardinality: |A||B| / prod(max(V(A,a), V(B,b))) — the
        formula that makes a many-to-many low-NDV edge (s_nationkey =
        c_nationkey: 25 distinct values) cost its real blowup instead of the
        FK max(l, r) guess (reference: the CBO's mq.getRowCount join logic)."""
        est = ca.est_rows * cb.est_rows
        for ea, eb in pair_edges:
            est /= max(_ndv_of(ea, ca.est_rows), _ndv_of(eb, cb.est_rows), 1.0)
        return max(est, 1.0)

    used_edges: Set[int] = set()
    chosen: List[str] = []

    def merge(ca: "_Rel", cb: "_Rel", a_members: Set[int],
              b_members: Set[int]) -> "_Rel":
        eq_pairs: List[Tuple[ir.Expr, ir.Expr]] = []
        for k, (a, b, ea, eb) in enumerate(edges):
            if k in used_edges:
                continue
            if a in a_members and b in b_members:
                eq_pairs.append((ea, eb))
                used_edges.add(k)
            elif b in a_members and a in b_members:
                eq_pairs.append((eb, ea))
                used_edges.add(k)
        if not eq_pairs:
            return _Rel(L.Join(ca.node, cb.node, "cross", []),
                        ca.ids | cb.ids, ca.est_rows * cb.est_rows)
        return _Rel(L.Join(ca.node, cb.node, "inner", eq_pairs),
                    ca.ids | cb.ids, join_est(ca, cb, eq_pairs))

    def goo_plan() -> Tuple[List[Tuple[Set[int], Set[int]]], Tuple[str, ...]]:
        """Greedy operator ordering (GOO): repeatedly merge the component PAIR
        with the smallest estimated join output.  Unlike left-deep growth from
        the smallest relation, this does not trap dimension chains into m:n
        edges (TPC-H Q5's nation-keyed supplier x customer).

        Pure planning over estimate floats and a SCRATCH edge set — returns
        the merge steps (as member-set pairs, smaller-est side first) plus the
        label order.  The tree build replays the steps; drift detection uses
        just the labels — one selection loop serves both."""
        sim_used: Set[int] = set()
        comps = [(relinfos[i].est_rows, {i}, [labels[i]])
                 for i in range(len(relinfos))]
        steps: List[Tuple[Set[int], Set[int]]] = []
        while len(comps) > 1:
            best = None
            for x in range(len(comps)):
                for y in range(x + 1, len(comps)):
                    pe = []
                    for k, (a, b, ea, eb) in enumerate(edges):
                        if k in sim_used:
                            continue
                        if (a in comps[x][1] and b in comps[y][1]) or \
                                (b in comps[x][1] and a in comps[y][1]):
                            pe.append((ea, eb) if a in comps[x][1]
                                      else (eb, ea))
                    if not pe:
                        continue
                    est = comps[x][0] * comps[y][0]
                    for ea, eb in pe:
                        est /= max(_ndv_of(ea, comps[x][0]),
                                   _ndv_of(eb, comps[y][0]), 1.0)
                    if best is None or est < best[0]:
                        best = (max(est, 1.0), x, y)
            if best is None:
                # no joinable pair left: cross the two smallest components
                order = sorted(range(len(comps)), key=lambda i: comps[i][0])
                x, y = min(order[0], order[1]), max(order[0], order[1])
                best = (comps[x][0] * comps[y][0], x, y)
            est, x, y = best
            for k, (a, b, _ea, _eb) in enumerate(edges):
                if k in sim_used:
                    continue
                if (a in comps[x][1] and b in comps[y][1]) or \
                        (b in comps[x][1] and a in comps[y][1]):
                    sim_used.add(k)
            if comps[y][0] < comps[x][0]:
                x, y = y, x  # smaller side leads (label-order convention)
            _e, ma, la = comps[x]
            _e2, mb, lb = comps[y]
            steps.append((set(ma), set(mb)))
            comps = [c for i, c in enumerate(comps) if i not in (x, y)]
            comps.append((est, ma | mb, la + lb))
        # the reported label order is the MERGE order (first merged pair
        # first, later-joined relations appended), NOT the lead-concat
        # display order: an SPM baseline replays its order as a left-deep
        # chain, and only the merge sequence makes that replay reproduce the
        # join tree GOO actually built — concat order can turn a healthy
        # bushy plan into an m:n-first blowup on replay.  (Plan fingerprints
        # ARE order-sensitive within a forest, so persisted pre-merge-order
        # baselines are dropped by the SPM kv-format version bump.)  Within
        # a step, members connected by an edge to the already-placed prefix
        # go first: a bushy-bushy merge flattened naively could put an
        # edge-less member next and hand the replay a cross join the
        # original never ran.
        def _connected(i: int, group: Set[int]) -> bool:
            return any((a == i and bb in group) or (bb == i and a in group)
                       for a, bb, _ea, _eb in edges)

        seq: List[str] = []
        placed: Set[int] = set()
        for ma, mb in steps:
            fresh = sorted(ma - placed) + sorted(mb - placed)
            while fresh:
                nxt = next((i for i in fresh if placed and
                            _connected(i, placed)), fresh[0])
                seq.append(labels[nxt])
                placed.add(nxt)
                fresh.remove(nxt)
        for i in range(len(relinfos)):
            if i not in placed:  # defensive: unmerged singleton
                seq.append(labels[i])
        return steps, tuple(seq)

    if forced_seq is not None:
        # SPM baseline: replay the pinned order verbatim as a left-deep chain
        # (the accepted plan's identity is its member order)
        remaining = set(range(len(relinfos)))

        def take(lab: str) -> int:
            for i in by_label[lab]:
                if i in remaining:
                    return i
            raise KeyError(lab)

        start = take(forced_seq[0])
        current = relinfos[start]
        remaining.discard(start)
        members = {start}
        chosen.append(labels[start])
        while remaining:
            nxt = take(forced_seq[len(chosen)])
            chosen.append(labels[nxt])
            current = merge(current, relinfos[nxt], members, {nxt})
            members.add(nxt)
            remaining.discard(nxt)
        cost_pref = goo_plan()[1]
    else:
        steps, order = goo_plan()
        nodes: Dict[frozenset, "_Rel"] = {
            frozenset({i}): relinfos[i] for i in range(len(relinfos))}
        for ma, mb in steps:
            ca = nodes.pop(frozenset(ma))
            cb = nodes.pop(frozenset(mb))
            # merge() consumes real used_edges in the same sequence the
            # planning pass simulated, so edge bookkeeping stays in lockstep
            nodes[frozenset(ma | mb)] = merge(ca, cb, ma, mb)
        current = next(iter(nodes.values()))
        chosen = list(order)
        cost_pref = order
    if spm_active:
        spm.chosen.append(tuple(chosen))
        spm.cost_preferred.append(cost_pref)

    # any edges between already-joined members that were not consumed become filters
    for k, (a, b, ea, eb) in enumerate(edges):
        if k not in used_edges:
            residual_preds.append(ir.call("eq", ea, eb))
    out = current.node
    if residual_preds:
        out = L.Filter(out, ir.and_(*residual_preds))
    return out


def _flatten_crosses(node: L.RelNode) -> List[L.RelNode]:
    if isinstance(node, L.Join) and node.kind == "cross" and not node.equi and \
            not getattr(node, "scalar", False):
        # scalar crosses (uncorrelated scalar subqueries) carry exactly-one-row
        # semantics and must survive join-tree reconstruction intact
        return _flatten_crosses(node.left) + _flatten_crosses(node.right)
    return [node]


def _rewrite_children(node: L.RelNode, fn) -> L.RelNode:
    node.children = [fn(c) for c in node.children]
    return node


# ---------------------------------------------------------------------------
# filter pushdown (through Project / into Join sides)
# ---------------------------------------------------------------------------

def push_filters(node: L.RelNode) -> L.RelNode:
    node = _rewrite_children(node, push_filters)
    if not isinstance(node, L.Filter):
        return node
    child = node.child
    if isinstance(child, L.Filter):
        merged = L.Filter(child.child, ir.and_(child.cond, node.cond))
        return push_filters(merged)
    if isinstance(child, L.Join) and child.kind in ("inner", "semi", "anti", "left"):
        left_ids = set(child.left.field_ids())
        right_ids = set(child.right.field_ids())
        keep: List[ir.Expr] = []
        lpush: List[ir.Expr] = []
        rpush: List[ir.Expr] = []
        for c in conjuncts(node.cond):
            refs = set(ir.referenced_columns(c))
            if refs <= left_ids:
                lpush.append(c)
            elif refs <= right_ids and child.kind == "inner":
                rpush.append(c)
            else:
                keep.append(c)
        if lpush:
            child.children[0] = push_filters(L.Filter(child.left, ir.and_(*lpush)))
        if rpush:
            child.children[1] = push_filters(L.Filter(child.right, ir.and_(*rpush)))
        if keep:
            return L.Filter(child, ir.and_(*keep))
        return child
    return node


# ---------------------------------------------------------------------------
# column pruning
# ---------------------------------------------------------------------------

def prune_columns(node: L.RelNode, required: Optional[Set[str]] = None) -> L.RelNode:
    """Drop unreferenced columns from scans and projections (top-down)."""
    if required is None:
        required = set(node.field_ids())

    if isinstance(node, L.Scan):
        cols = [(oid, c) for oid, c in node.columns if oid in required]
        if not cols:
            cols = node.columns[:1]  # keep at least one lane for row existence
        node.columns = cols
        return node
    if isinstance(node, L.Project):
        node.exprs = [(n, e) for n, e in node.exprs if n in required] or node.exprs[:1]
        need = set()
        for _, e in node.exprs:
            need.update(ir.referenced_columns(e))
        node.children = [prune_columns(node.child, need)]
        return node
    if isinstance(node, L.Filter):
        need = set(required) | set(ir.referenced_columns(node.cond))
        node.children = [prune_columns(node.child, need)]
        return node
    if isinstance(node, L.Aggregate):
        need = set()
        for _, e in node.groups:
            need.update(ir.referenced_columns(e))
        for a in node.aggs:
            if a.arg is not None:
                need.update(ir.referenced_columns(a.arg))
        node.children = [prune_columns(node.child, need)]
        return node
    if isinstance(node, L.Join):
        need = set(required)
        for a, b in node.equi:
            need.update(ir.referenced_columns(a))
            need.update(ir.referenced_columns(b))
        if node.residual is not None:
            need.update(ir.referenced_columns(node.residual))
        left_ids = set(node.left.field_ids())
        right_ids = set(node.right.field_ids())
        node.children = [prune_columns(node.left, need & left_ids),
                         prune_columns(node.right, need & right_ids)]
        return node
    if isinstance(node, L.Sort):
        need = set(required)
        for e, _ in node.keys:
            need.update(ir.referenced_columns(e))
        node.children = [prune_columns(node.child, need)]
        return node
    if isinstance(node, L.Window):
        need = set(required)
        for p in node.partitions:
            need.update(ir.referenced_columns(p))
        for e, _ in node.orders:
            need.update(ir.referenced_columns(e))
        for c in node.calls:
            if c.arg is not None:
                need.update(ir.referenced_columns(c.arg))
        need -= {c.out_id for c in node.calls}
        node.children = [prune_columns(node.child, need)]
        return node
    if isinstance(node, (L.Limit,)):
        node.children = [prune_columns(node.child, set(required))]
        return node
    if isinstance(node, L.Union):
        node.children = [prune_columns(c, set(c.field_ids())) for c in node.children]
        return node
    return node


# ---------------------------------------------------------------------------
# runtime-filter planning
# ---------------------------------------------------------------------------

def plan_runtime_filters(node: L.RelNode, hints=None) -> L.RelNode:
    """Annotate inner/semi hash joins with runtime-filter edges.

    Reference analog: `rule/mpp/runtimefilter` (`JoinToRuntimeFilterJoinRule`,
    `PushBloomFilterRule`, SURVEY.md §2.5): for each equi pair whose probe key
    is a bare column traceable — through projections/renames, filters, group
    keys, and row-preserving join sides — to a base-table scan column, the
    join gains a producer edge (`L.Join.rf_plans`) and the scan a consumer
    edge (`L.Scan.rf_targets`).  Filtering a scan to rows whose key can match
    the build side is sound anywhere on that path: a filtered-out row could
    only ever produce join rows the upper inner/semi join discards anyway.

    Cost-gated on stats: no filter when the probe is already cheap
    (broadcast-small shapes) or when build-key NDV says the filter would pass
    nearly everything.  `NO_BLOOM` / `RUNTIME_FILTER(OFF)` hints disable the
    pass; `RUNTIME_FILTER(BLOOM|MINMAX)` restricts the filter kinds."""
    import itertools
    h = hints or {}
    mode = str(h.get("runtime_filter") or "").lower()
    if h.get("no_bloom") or mode == "off":
        return node
    _rf_walk(node, itertools.count(1), mode)
    return node


def _rf_resolve_scan(node: L.RelNode, col_id: str):
    """(scan, out_id) the plan column `col_id` is a bare rename-chain of, or
    None.  Descends only row-preserving edges (see plan_runtime_filters)."""
    if isinstance(node, L.Scan):
        for oid, _c in node.columns:
            if oid == col_id:
                return node, oid
        return None
    if isinstance(node, L.Filter):
        return _rf_resolve_scan(node.child, col_id)
    if isinstance(node, L.Project):
        for name, e in node.exprs:
            if name == col_id:
                return _rf_resolve_scan(node.child, e.name) \
                    if isinstance(e, ir.ColRef) else None
        return None
    if isinstance(node, L.Aggregate):
        # sound only through GROUP KEYS: pruning rows of a group whose key the
        # filter refutes removes exactly the groups the upper join discards
        for name, e in node.groups:
            if name == col_id:
                return _rf_resolve_scan(node.child, e.name) \
                    if isinstance(e, ir.ColRef) else None
        return None
    if isinstance(node, L.Join):
        if node.kind == "cross":
            return None
        sides = [node.left] if node.kind in ("semi", "anti", "left") \
            else [node.left, node.right]
        for s in sides:
            if col_id in set(s.field_ids()):
                return _rf_resolve_scan(s, col_id)
        return None
    return None


def _rf_walk(node: L.RelNode, ctr, mode: str):
    for c in node.children:
        _rf_walk(c, ctr, mode)
    if not isinstance(node, L.Join) or node.kind not in ("inner", "semi") or \
            not node.equi:
        return
    l_est = estimate_rows(node.left)
    r_est = estimate_rows(node.right)
    # Plant edges for EVERY probe direction that passes the cost gates, not
    # just the build side the local engine would pick: engines differ (MPP
    # flips the build only below a 4x estimate ratio), and the executor
    # activates only the direction matching its actual probe side — an edge
    # for the other direction simply never publishes.  Semi joins fix the
    # probe to the preserved left side.
    if node.kind == "semi":
        directions = [("left", node.left, node.right, r_est, l_est)]
    else:
        directions = [("left", node.left, node.right, r_est, l_est),
                      ("right", node.right, node.left, l_est, r_est)]
    for direction in directions:
        _rf_plan_direction(node, direction, ctr, mode)


def _rf_plan_direction(node: L.Join, direction, ctr, mode: str):
    from galaxysql_tpu.exec.runtime_filter import (
        RF_BLOOM_MAX_BUILD, RF_MAX_SELECTIVITY, RF_MIN_PROBE_ROWS,
        RuntimeFilterPlan, RuntimeFilterTarget)
    target_side, probe_node, build_node, build_est, probe_est = direction
    if probe_est < RF_MIN_PROBE_ROWS:
        return  # broadcast-small shape: the probe is already cheap
    build_resolver = _stats_resolver(build_node)
    for i, (le, re_) in enumerate(node.equi):
        pk = le if target_side == "left" else re_
        bk = re_ if target_side == "left" else le
        if not isinstance(pk, ir.ColRef):
            continue
        if pk.dtype.is_string != bk.dtype.is_string:
            continue
        got = _rf_resolve_scan(probe_node, pk.name)
        if got is None:
            continue
        scan, out_id = got
        colname = dict(scan.columns).get(out_id)
        if colname is None:
            continue
        # selectivity gate: distinct build keys vs distinct probe values
        tm = scan.table
        ndv_p = tm.stats.ndv.get(colname) or \
            tm.stats.ndv.get(tm.column(colname).name, 0)
        b_card = build_est
        if isinstance(bk, ir.ColRef):
            tmcol = build_resolver.get(bk.name)
            if tmcol is not None:
                bndv = tmcol[0].stats.ndv.get(tmcol[1]) or \
                    tmcol[0].stats.ndv.get(tmcol[0].column(tmcol[1]).name, 0)
                if bndv:
                    b_card = min(b_card, float(bndv))
        sel = b_card / ndv_p if ndv_p else build_est / max(probe_est, 1.0)
        if sel > RF_MAX_SELECTIVITY:
            continue
        kinds = set()
        if not pk.dtype.is_string:
            kinds.add("minmax")  # codes are assignment-ordered: numeric only
        if build_est <= RF_BLOOM_MAX_BUILD:
            kinds.add("bloom")
        if mode == "bloom":
            kinds &= {"bloom"}
        elif mode == "minmax":
            kinds &= {"minmax"}
        if not kinds:
            continue
        fid = next(ctr)
        scan.rf_targets.append(
            RuntimeFilterTarget(fid, out_id, colname, frozenset(kinds)))
        node.rf_plans.append(
            RuntimeFilterPlan(fid, i, target_side, frozenset(kinds)))


# ---------------------------------------------------------------------------
# skew planning (heavy-hitter hybrid joins + salted aggregation)
# ---------------------------------------------------------------------------

def plan_skew(node: L.RelNode, hints=None) -> L.RelNode:
    """Annotate joins/aggregates whose repartition key column has heavy
    hitters (exec/skew.py policy; detection from ANALYZE's Space-Saving
    sketches in meta/statistics.py).

    Joins: for each probe direction of a single-pair equi join whose probe
    key is a bare integer column traceable to a base-table scan
    (`_rf_resolve_scan`, the runtime-filter lineage walk), plant a
    `SkewJoinPlan` carrying the column's heavy-hitter candidates — the MPP
    executor thresholds them by its actual mesh size and splits the shuffle
    into a broadcast (hot) and a hash (cold) lane.  Aggregates: a skewed
    group-key column plants a `SaltAggPlan`; the executor repartitions on a
    salted key hash and adds a final merge stage.  The SKEW(OFF|JOIN|AGG)
    hint and the GALAXYSQL_SKEW env switch gate the pass STRUCTURALLY: a
    disabled mode plants nothing, so the hybrid path cannot engage."""
    from galaxysql_tpu.exec import skew as sk
    modes = sk.plan_modes(hints)
    if not modes:
        return node
    for n in L.walk(node):
        if isinstance(n, L.Join) and "join" in modes:
            _skew_plan_join(n, sk)
        elif isinstance(n, L.Aggregate) and "agg" in modes:
            _skew_plan_agg(n, sk)
    return node


def _skew_candidates(probe_node: L.RelNode, key: ir.Expr, sk):
    """(SkewPlan fields) for a bare-column repartition key with heavy
    hitters, or None.  Integer lanes only: hot-key classification hashes the
    host-side candidate values with the device hash's exact cast semantics,
    which float lanes do not share."""
    if not isinstance(key, ir.ColRef):
        return None
    got = _rf_resolve_scan(probe_node, key.name)
    if got is None:
        return None
    scan, out_id = got
    tm = scan.table
    if getattr(tm, "remote", None) is not None:
        return None
    colname = dict(scan.columns).get(out_id)
    if colname is None:
        return None
    cm = tm.column(colname)
    if not np.issubdtype(np.dtype(cm.dtype.lane), np.integer):
        return None
    hh = tm.stats.heavy.get(cm.name)
    if hh is None:
        return None
    cands = tuple((v, round(f, 6)) for v, f in
                  hh.candidates(sk.MIN_CANDIDATE_FRAC))
    if not cands:
        return None
    return cands, f"{tm.schema.lower()}.{tm.name.lower()}", cm.name, \
        hh.total, tm


def _skew_plan_join(node: L.Join, sk):
    if node.kind not in ("inner", "left", "semi", "anti") or \
            len(node.equi) != 1:
        return
    le, re_ = node.equi[0]
    # probe directions mirror _rf_walk: inner joins may flip sides at
    # execution, so plant both and let the executor pick its actual probe
    directions = [("left", node.left, le)]
    if node.kind == "inner":
        directions.append(("right", node.right, re_))
    for side, probe_node, pk in directions:
        if pk.dtype.is_string:
            # hybrid classification hashes host-side hot values; string codes
            # may be dictionary-TRANSLATED before the device hash, so the
            # host twin cannot reproduce it.  Salted aggregation (no value
            # hashing) still covers skewed string keys.
            continue
        if estimate_rows(probe_node) < sk.MIN_SKEW_ROWS:
            continue
        got = _skew_candidates(probe_node, pk, sk)
        if got is None:
            continue
        cands, table, column, total, tm = got
        node.skew_plans.append(sk.SkewJoinPlan(
            0, side, cands, table, column, total, tm))


def _skew_plan_agg(node: L.Aggregate, sk):
    # single group key only: the repartition hashes the COMBINED key, and a
    # hot value in one column of a composite key says nothing about the
    # composite's distribution (GROUP BY region, customer_id is uniform even
    # when region has a dominant value) — salting there is pure overhead
    if len(node.groups) != 1:
        return
    if estimate_rows(node.child) < sk.MIN_SKEW_ROWS:
        return
    got = _skew_candidates(node.child, node.groups[0][1], sk)
    if got is not None:
        cands, table, column, total, tm = got
        node.salt_plan = sk.SaltAggPlan(cands, table, column, total, tm)


# ---------------------------------------------------------------------------
# partition pruning
# ---------------------------------------------------------------------------

def prune_partitions(node: L.RelNode) -> L.RelNode:
    node = _rewrite_children(node, prune_partitions)
    if not isinstance(node, L.Filter) or not isinstance(node.child, L.Scan):
        return node
    scan = node.child
    _extract_sargs(node.cond, scan)
    _choose_point_eq(node.cond, scan)
    info = scan.table.partition
    if info.method in ("single", "broadcast") or info.num_partitions <= 1:
        return node
    router = PartitionRouter(scan.table)
    id_to_col = {oid: col for oid, col in scan.columns}
    parts: Optional[Set[int]] = None
    for c in conjuncts(node.cond):
        got = _prune_one(c, router, id_to_col, scan.table)
        if got is not None:
            parts = set(got) if parts is None else (parts & set(got))
    if parts is not None:
        scan.partitions = sorted(parts)
    return node


def _lane_encode(tm, col: str, value):
    """Literal -> lane-domain value for routing (hash routing keys off LANE
    values: dictionary codes for strings, scaled ints for decimals, day
    numbers for dates).  Returns None when unencodable; a string absent from
    the dictionary encodes to -1 (matches no stored row)."""
    cm = tm.column(col)
    if cm.dtype.is_string:
        d = tm.dictionaries.get(col.lower())
        return None if d is None else d.encode_one(str(value), add=False)
    from galaxysql_tpu.expr.compiler import _encode_literal_value
    try:
        v = _encode_literal_value(value, cm.dtype)
    except (TypeError, ValueError):
        return None
    return None if isinstance(v, str) else v


def _extract_sargs(cond: ir.Expr, scan: L.Scan):
    """Collect simple col-vs-literal conjuncts as lane-domain SARGs on the
    scan — the archive layer prunes parquet files by min-max stats against
    them (OSSTableScanExec.java:45-61 analog)."""
    id_to_col = {oid: col for oid, col in scan.columns}
    for c in conjuncts(cond):
        if not (isinstance(c, ir.Call) and
                c.op in ("eq", "lt", "le", "gt", "ge") and len(c.args) == 2):
            continue
        cl = _col_lit_cmp(c)
        if cl is None:
            continue
        col, lit, flipped = cl
        if col.name not in id_to_col:
            continue
        cm = scan.table.column(id_to_col[col.name])
        if cm.dtype.is_string:
            continue  # codes are assignment-ordered; min-max means nothing
        v = _lit_lane_value(lit, cm.dtype)
        if v is None:
            continue
        op = _FLIP.get(c.op, c.op) if flipped else c.op
        scan.sargs.append((cm.name, op, v))


def _choose_point_eq(cond: ir.Expr, scan: L.Scan):
    """Access-path choice: equality on an indexed column marks the scan for
    index-candidate reads (DirectShardingKeyTableOperation / XPlan key-Get,
    reference Planner.java:914, RelToXPlanConverter.java:41).

    Candidate columns, best first: primary-key lead, partition-key lead (the
    shard key — also how a routed GSI table is read), any PUBLIC local index
    lead.  The value is stored in LANE domain; the physical scan serves
    candidate rows through the partition's sorted key index and the Filter
    above re-verifies, so this is advisory like sargs."""
    tm = scan.table
    id_to_col = {oid: col for oid, col in scan.columns}
    eqs: Dict[str, ir.Literal] = {}
    for c in conjuncts(cond):
        if not (isinstance(c, ir.Call) and c.op == "eq" and len(c.args) == 2):
            continue
        cl = _col_lit_cmp(c)
        if cl is None:
            continue
        col, lit, _ = cl
        if col.name in id_to_col:
            eqs[id_to_col[col.name].lower()] = lit
    if not eqs:
        return
    cands: List[str] = []
    if tm.primary_key:
        cands.append(tm.primary_key[0])
    if tm.partition.columns:
        cands.append(tm.partition.columns[0])
    for i in tm.indexes:
        if i.status == "PUBLIC" and not i.global_index and i.columns:
            cands.append(i.columns[0])
    for cname in cands:
        lit = eqs.get(cname.lower())
        if lit is None:
            continue
        cm = tm.column(cname)
        # access-path cost check: a low-cardinality index lead (status flags
        # etc.) would return huge candidate sets through the host index path —
        # worse than the device full scan.  NDV comes from ANALYZE.
        ndv = tm.stats.ndv.get(cm.name, 0)
        if ndv and tm.stats.row_count and \
                tm.stats.row_count / ndv > 65536:
            continue
        v = _lane_encode(tm, cm.name, lit.value)
        if v is None:
            continue
        if cm.dtype.is_string:
            v = np.int32(v)
        scan.point_eq = (cm.name, v)
        return


def route_covering_gsi(node: L.RelNode, catalog) -> L.RelNode:
    """Rewrite a filtered base-table scan onto a covering GSI backing table.

    Reference analog: CBO index selection over global secondary indexes
    (SURVEY.md App.D; `polardbx-optimizer/.../index`): when a predicate has an
    equality on a PUBLIC GSI's leading column and the GSI's backing table
    carries every referenced column (index + covering + PK), the scan reads
    the GSI table instead — partition pruning then routes on the GSI's
    partition key and the point-eq path serves it as an index lookup.  Skipped
    when the predicate already pins the base table's own point key."""
    node.children = [route_covering_gsi(c, catalog) for c in node.children]
    if not isinstance(node, L.Filter) or not isinstance(node.child, L.Scan):
        return node
    scan = node.child
    tm = scan.table
    if getattr(tm, "remote", None) is not None or "$" in tm.name:
        return node
    id_to_col = {oid: col.lower() for oid, col in scan.columns}
    eq_cols = set()
    for c in conjuncts(node.cond):
        if isinstance(c, ir.Call) and c.op == "eq" and len(c.args) == 2:
            cl = _col_lit_cmp(c)
            if cl is not None and cl[0].name in id_to_col:
                eq_cols.add(id_to_col[cl[0].name])
    if not eq_cols:
        return node
    if tm.primary_key and tm.primary_key[0].lower() in eq_cols:
        return node  # base point read is already optimal
    if tm.partition.columns and tm.partition.columns[0].lower() in eq_cols:
        return node  # already routable to one shard of the base table
    referenced = {col.lower() for _, col in scan.columns}
    for i in tm.indexes:
        if not (i.global_index and i.status == "PUBLIC" and i.columns):
            continue
        if i.columns[0].lower() not in eq_cols:
            continue
        try:
            gtm = catalog.table(tm.schema, f"{tm.name}${i.name}")
        except Exception:
            continue
        if not referenced <= {c.name.lower() for c in gtm.columns}:
            continue  # not covering: would need a back-lookup join
        scan.table = gtm
        scan.partitions = None
        scan.sargs = []
        return node
    return node


def _prune_one(c: ir.Expr, router: PartitionRouter, id_to_col,
               tm) -> Optional[List[int]]:
    if isinstance(c, ir.Call) and c.op == "eq":
        col, lit = _col_lit(c.args[0], c.args[1], id_to_col)
        if col is not None:
            v = _lane_encode(tm, col, lit)
            if v is None:
                return None
            return router.prune_eq(col, v)
    if isinstance(c, ir.InList) and not c.negated:
        if isinstance(c.arg, ir.ColRef) and c.arg.name in id_to_col:
            out: List[int] = []
            for v in c.values:
                lv = _lane_encode(tm, id_to_col[c.arg.name], v)
                if lv is None:
                    return None
                got = router.prune_eq(id_to_col[c.arg.name], lv)
                if got is None:
                    return None
                out.extend(got)
            return sorted(set(out))
    return None


def _col_lit(a: ir.Expr, b: ir.Expr, id_to_col):
    if isinstance(a, ir.ColRef) and isinstance(b, ir.Literal) and a.name in id_to_col:
        return id_to_col[a.name], b.value
    if isinstance(b, ir.ColRef) and isinstance(a, ir.Literal) and b.name in id_to_col:
        return id_to_col[b.name], a.value
    return None, None


def optimize(node: L.RelNode, spm=None, catalog=None, hints=None) -> L.RelNode:
    """The full RBO pipeline.

    push_filters runs BEFORE join-tree construction: subquery unnesting wraps the
    cross-join forest in semi/anti joins, and the WHERE conjuncts above them must reach
    the forest first or the forest would be ordered without its predicates.

    `spm` (SpmContext) pins/reports join orders — see build_join_tree.
    `catalog` (when given) enables GSI access-path routing.
    `hints` gate the runtime-filter pass (NO_BLOOM / RUNTIME_FILTER)."""
    node = push_filters(node)
    node = build_join_tree(node, spm)
    node = push_filters(node)
    node = prune_columns(node)
    if catalog is not None:
        # after column pruning: covering is judged on the columns actually
        # referenced, not the table's full column list
        node = route_covering_gsi(node, catalog)
    node = prune_partitions(node)
    # LAST: filter edges bind scan identities, which GSI routing just settled
    node = plan_runtime_filters(node, hints)
    # skew plans bind the same scan identities (and reuse the rf lineage walk)
    node = plan_skew(node, hints)
    return node
