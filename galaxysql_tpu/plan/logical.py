"""Logical relational algebra.

Reference analog: the Calcite logical rel layer (SURVEY.md §2.4) — but deliberately small:
a closed set of nodes, each knowing its output schema as [(column_id, DataType, Dictionary)].
Column identity is by unique string id assigned at bind time ("alias.column" for base
columns, generated names for derived), which stands in for Calcite's field indexes.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

from galaxysql_tpu.chunk.batch import Dictionary
from galaxysql_tpu.expr import ir
from galaxysql_tpu.meta.catalog import TableMeta
from galaxysql_tpu.types import datatype as dt

# (column_id, type, dictionary)
Field = Tuple[str, dt.DataType, Optional[Dictionary]]


@dataclasses.dataclass
class AggSpec:
    kind: str                    # sum | count | avg | min | max | count_star
    arg: Optional[ir.Expr]
    out_id: str
    distinct: bool = False

    @property
    def dtype(self) -> dt.DataType:
        from galaxysql_tpu.exec.operators import AggCall
        return AggCall(self.kind, self.arg, self.out_id).dtype


def clone_tree(n: "RelNode") -> "RelNode":
    """Structural copy of a plan subtree: fresh RelNodes and fresh list attrs
    (optimizer rules mutate Scan.columns / Project.exprs in place), while ir
    expressions, TableMetas and dictionaries stay shared (immutable identities).
    Needed wherever one bound subtree feeds several parents (grouping sets)."""
    import copy
    c = copy.copy(n)
    for attr, v in vars(c).items():
        if attr != "children" and isinstance(v, list):
            setattr(c, attr, list(v))
    c.children = [clone_tree(ch) for ch in n.children]
    return c


class RelNode:
    children: List["RelNode"]

    def fields(self) -> List[Field]:
        raise NotImplementedError

    def field_ids(self) -> List[str]:
        return [f[0] for f in self.fields()]

    def explain_lines(self, depth: int = 0) -> List[str]:
        line = "  " * depth + self.label()
        out = [line]
        for c in self.children:
            out += c.explain_lines(depth + 1)
        return out

    def label(self) -> str:
        return type(self).__name__


class Scan(RelNode):
    def __init__(self, table: TableMeta, alias: str,
                 columns: Sequence[Tuple[str, str]],  # (out_id, table_column)
                 col_meta: Optional[Dict[str, Any]] = None):
        self.table = table
        self.alias = alias
        self.columns = list(columns)
        # bind-time ColumnMeta snapshot: planning holds no MDL, so a
        # concurrent DROP COLUMN can remove a name from the live catalog
        # between Scan construction and a later fields() call — resolving
        # through the snapshot keeps the plan self-consistent (pruning will
        # drop the unreferenced lane anyway)
        self._col_meta: Dict[str, Any] = dict(col_meta or {})
        self.children = []
        # filled by the pruning pass; None = all partitions
        self.partitions: Optional[List[int]] = None
        self.as_of: Optional[int] = None  # flashback snapshot TSO (AS OF TSO)
        # advisory column-vs-literal conjuncts in LANE domain, extracted by the
        # pruning pass: (table_column, op, lane_value); archive scans use them
        # for parquet min-max file pruning (SARG analog); the Filter above the
        # scan still applies, so sargs are never load-bearing for correctness
        self.sargs: List[Tuple[str, str, Any]] = []
        # index access path (DirectShardingKeyTableOperation / XPlan key-Get
        # analog, Planner.java:914): (table_column, lane_value) equality on an
        # indexed column — the physical scan reads index candidates instead of
        # full lanes.  Advisory like sargs: the Filter above re-verifies.
        self.point_eq: Optional[Tuple[str, Any]] = None
        # runtime-filter consumer edges (exec/runtime_filter.RuntimeFilterTarget)
        # planted by plan_runtime_filters: probe-side join filters applied at
        # the scan (the join above re-verifies, so these prune, never decide)
        self.rf_targets: List[Any] = []

    def column_meta(self, col: str):
        """ColumnMeta for a scan column — the bind-time snapshot when one was
        taken, the live catalog otherwise (rule-built scans)."""
        cm = self._col_meta.get(col)
        if cm is None:
            cm = self.table.column(col)
            self._col_meta[col] = cm
        return cm

    def fields(self) -> List[Field]:
        out = []
        for out_id, col in self.columns:
            cm = self.column_meta(col)
            out.append((out_id, cm.dtype, self.table.dictionaries.get(col.lower())))
        return out

    def label(self):
        p = f" partitions={self.partitions}" if self.partitions is not None else ""
        cols = ",".join(c for _, c in self.columns)
        return f"Scan({self.table.name} as {self.alias}, [{cols}]{p})"


class Filter(RelNode):
    def __init__(self, child: RelNode, cond: ir.Expr):
        self.children = [child]
        self.cond = cond

    @property
    def child(self) -> RelNode:
        return self.children[0]

    def fields(self) -> List[Field]:
        return self.child.fields()

    def label(self):
        return f"Filter({self.cond!r})"


class Project(RelNode):
    def __init__(self, child: RelNode, exprs: Sequence[Tuple[str, ir.Expr]]):
        self.children = [child]
        self.exprs = list(exprs)

    @property
    def child(self) -> RelNode:
        return self.children[0]

    def fields(self) -> List[Field]:
        from galaxysql_tpu.expr.compiler import _find_dictionary
        return [(name, e.dtype, _find_dictionary(e)) for name, e in self.exprs]

    def label(self):
        return f"Project({', '.join(n for n, _ in self.exprs)})"


class Aggregate(RelNode):
    def __init__(self, child: RelNode, groups: Sequence[Tuple[str, ir.Expr]],
                 aggs: Sequence[AggSpec]):
        self.children = [child]
        self.groups = list(groups)
        self.aggs = list(aggs)
        # skew-aware salted repartition plan (exec/skew.SaltAggPlan), planted
        # by plan/rules.plan_skew when a group key's heavy-hitter stats say a
        # plain key-hash repartition would hot-spot one shard
        self.salt_plan: Optional[Any] = None

    @property
    def child(self) -> RelNode:
        return self.children[0]

    def fields(self) -> List[Field]:
        from galaxysql_tpu.expr.compiler import _find_dictionary
        out: List[Field] = [(n, e.dtype, _find_dictionary(e)) for n, e in self.groups]
        for a in self.aggs:
            d = _find_dictionary(a.arg) if (a.arg is not None and a.arg.dtype.is_string
                                            and a.kind in ("min", "max")) else None
            out.append((a.out_id, a.dtype, d))
        return out

    def label(self):
        gs = ",".join(n for n, _ in self.groups)
        as_ = ",".join(f"{a.kind}({'' if a.arg is None else a.arg!r})" for a in self.aggs)
        return f"Aggregate(by=[{gs}], aggs=[{as_}])"


class Join(RelNode):
    """Equi-join with optional residual.  kind: inner|left|semi|anti|cross.

    For semi/anti, output fields are the LEFT side only (left = probe/outer side)."""

    def __init__(self, left: RelNode, right: RelNode, kind: str,
                 equi: Sequence[Tuple[ir.Expr, ir.Expr]],
                 residual: Optional[ir.Expr] = None):
        self.children = [left, right]
        self.kind = kind
        self.equi = list(equi)
        self.residual = residual
        # scalar cross join (uncorrelated scalar subquery): exactly-one-row build
        self.scalar = False
        # runtime-filter producer edges (exec/runtime_filter.RuntimeFilterPlan):
        # equi pairs whose build side publishes a bloom/min-max filter
        self.rf_plans: List[Any] = []
        # skew-aware hybrid-join plans (exec/skew.SkewJoinPlan), one per probe
        # direction whose key column has heavy hitters; the executor activates
        # only the direction matching its actual probe side (rf_plans stance)
        self.skew_plans: List[Any] = []

    @property
    def left(self) -> RelNode:
        return self.children[0]

    @property
    def right(self) -> RelNode:
        return self.children[1]

    def fields(self) -> List[Field]:
        if self.kind in ("semi", "anti"):
            return self.left.fields()
        right = self.right.fields()
        if self.kind == "left":
            right = [(n, t.with_nullable(True), d) for n, t, d in right]
        return self.left.fields() + right

    def label(self):
        eq = ", ".join(f"{l!r}={r!r}" for l, r in self.equi)
        res = f" residual={self.residual!r}" if self.residual is not None else ""
        return f"Join({self.kind}, [{eq}]{res})"


@dataclasses.dataclass
class WindowCall:
    kind: str                  # row_number|rank|dense_rank|sum|count|avg|min|max|
                               # lag|lead|first_value|last_value
    arg: Optional[ir.Expr]
    out_id: str
    offset: int = 1            # lag/lead
    frame: str = "range"       # running | range | whole

    @property
    def dtype(self) -> dt.DataType:
        if self.kind in ("row_number", "rank", "dense_rank", "count"):
            return dt.BIGINT
        from galaxysql_tpu.exec.operators import AggCall
        if self.kind in ("sum", "avg", "min", "max"):
            return AggCall(self.kind, self.arg, self.out_id).dtype
        return self.arg.dtype  # lag/lead/first/last


class Window(RelNode):
    """Window functions over sorted partitions (OverWindowFramesExec analog, §2.6)."""

    def __init__(self, child: RelNode, partitions: Sequence[ir.Expr],
                 orders: Sequence[Tuple[ir.Expr, bool]],
                 calls: Sequence[WindowCall]):
        self.children = [child]
        self.partitions = list(partitions)
        self.orders = list(orders)
        self.calls = list(calls)

    @property
    def child(self) -> RelNode:
        return self.children[0]

    def fields(self) -> List[Field]:
        from galaxysql_tpu.expr.compiler import _find_dictionary
        out = list(self.child.fields())
        for c in self.calls:
            d = _find_dictionary(c.arg) if (c.arg is not None and
                                            c.arg.dtype.is_string) else None
            out.append((c.out_id, c.dtype, d))
        return out

    def label(self):
        ps = ",".join(repr(p) for p in self.partitions)
        cs = ",".join(c.kind for c in self.calls)
        return f"Window(by=[{ps}], calls=[{cs}])"


class Sort(RelNode):
    def __init__(self, child: RelNode, keys: Sequence[Tuple[ir.Expr, bool]],
                 limit: Optional[int] = None, offset: int = 0):
        self.children = [child]
        self.keys = list(keys)
        self.limit = limit
        self.offset = offset

    @property
    def child(self) -> RelNode:
        return self.children[0]

    def fields(self) -> List[Field]:
        return self.child.fields()

    def label(self):
        ks = ", ".join(f"{e!r}{' desc' if d else ''}" for e, d in self.keys)
        lim = f" limit={self.limit}" if self.limit is not None else ""
        return f"Sort([{ks}]{lim})"


class Limit(RelNode):
    def __init__(self, child: RelNode, limit: int, offset: int = 0):
        self.children = [child]
        self.limit = limit
        self.offset = offset

    @property
    def child(self) -> RelNode:
        return self.children[0]

    def fields(self) -> List[Field]:
        return self.child.fields()

    def label(self):
        return f"Limit({self.limit} offset {self.offset})"


class Union(RelNode):
    def __init__(self, children: Sequence[RelNode], all_: bool):
        self.children = list(children)
        self.all = all_

    def fields(self) -> List[Field]:
        return self.children[0].fields()

    def label(self):
        return f"Union(all={self.all})"


class Values(RelNode):
    """Literal rows (INSERT ... VALUES, SELECT without FROM)."""

    def __init__(self, schema: Sequence[Field], rows: List[List[Any]]):
        self.children = []
        self.schema = list(schema)
        self.rows = rows

    def fields(self) -> List[Field]:
        return self.schema

    def label(self):
        return f"Values({len(self.rows)} rows)"


def walk(node: RelNode):
    yield node
    for c in node.children:
        yield from walk(c)


def explain(node: RelNode) -> str:
    return "\n".join(node.explain_lines())
