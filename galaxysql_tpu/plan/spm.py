"""SPM — SQL Plan Management: baselines, accepted plans, evolution.

Reference analog: `polardbx-optimizer/.../planmanager/PlanManager.java:92` and
`BaselineInfo`/`PlanInfo`: per parameterized-SQL *baselines* pin the join order
the executor runs, independent of what the cost model would pick today.  The
first execution captures the cost-based choice as the accepted plan; later
plannings reuse it even when statistics drift would flip the greedy order
(plan stability).  When the cost model disagrees with the accepted plan, its
choice is kept as an *unaccepted candidate*; `BASELINE EVOLVE` executes
candidates and promotes one that is measurably faster (plan evolution).
Baselines are invalidated by DDL (catalog version) and persisted in the metadb
kv store so they survive restarts.

The plan identity here is the join order — the one decision our optimizer makes
that is both cost-driven and high-blast-radius (the reference's PlanInfo stores
full RelNode JSON; on this engine every other physical choice is deterministic
given the join tree)."""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional, Tuple

_KV_PREFIX = "spm.baseline."


class SpmContext:
    """Per-planning handshake with build_join_tree: carries the forced order in
    and the chosen order out (one entry per join forest, preorder)."""

    def __init__(self, forced: Optional[List[Tuple[str, ...]]] = None):
        self.forced = forced or []   # list of label tuples, one per forest
        self.chosen: List[Tuple[str, ...]] = []
        # what the cost model would pick (== chosen unless a baseline forced)
        self.cost_preferred: List[Tuple[str, ...]] = []
        self._forest_ix = 0

    def next_forced(self) -> Optional[Tuple[str, ...]]:
        ix = self._forest_ix
        self._forest_ix += 1
        if ix < len(self.forced):
            return self.forced[ix]
        return None


class PlanRecord:
    __slots__ = ("orders", "origin", "runs", "total_ms", "regressions",
                 "last_regression")

    def __init__(self, orders: List[Tuple[str, ...]], origin: str = "cost",
                 runs: int = 0, total_ms: float = 0.0):
        self.orders = [tuple(o) for o in orders]
        self.origin = origin          # cost | evolved | manual
        self.runs = runs
        self.total_ms = total_ms
        # runtime-regression audit trail, written by the statement-summary
        # sentinel (meta/statement_summary.py): how often this accepted plan
        # was flagged against the digest's latency baseline, and why last
        self.regressions = 0
        self.last_regression = ""

    @property
    def avg_ms(self) -> float:
        return self.total_ms / self.runs if self.runs else float("inf")

    def to_json(self):
        return {"orders": [list(o) for o in self.orders], "origin": self.origin,
                "runs": self.runs, "total_ms": self.total_ms,
                "regressions": self.regressions,
                "last_regression": self.last_regression}

    @classmethod
    def from_json(cls, d):
        r = cls([tuple(o) for o in d["orders"]], d.get("origin", "cost"),
                d.get("runs", 0), d.get("total_ms", 0.0))
        r.regressions = d.get("regressions", 0)
        r.last_regression = d.get("last_regression", "")
        return r


class Baseline:
    __slots__ = ("key", "catalog_version", "accepted", "candidate", "baseline_id",
                 "last_params")

    def __init__(self, key: Tuple[str, str], catalog_version: int,
                 accepted: PlanRecord, baseline_id: int,
                 candidate: Optional[PlanRecord] = None):
        self.key = key
        self.catalog_version = catalog_version
        self.accepted = accepted
        self.candidate = candidate
        self.baseline_id = baseline_id
        self.last_params: list = []  # most recent bind values (evolution input)


class PlanManager:
    """Baseline store + accepted-plan choice + evolution (PlanManager.java:92)."""

    def __init__(self):
        self._baselines: Dict[Tuple[str, str], Baseline] = {}
        self._lock = threading.Lock()
        self._metadb = None
        self._next_id = 1
        self.enabled = True

    # -- persistence --------------------------------------------------------

    def attach(self, metadb):
        """Bind the metadb and reload persisted baselines."""
        self._metadb = metadb
        for k, v in metadb.kv_scan(_KV_PREFIX):
            try:
                d = json.loads(v)
                key = (d["schema"], d["sql"])
                b = Baseline(key, d["catalog_version"],
                             PlanRecord.from_json(d["accepted"]),
                             d.get("id", self._next_id),
                             PlanRecord.from_json(d["candidate"])
                             if d.get("candidate") else None)
                with self._lock:
                    self._baselines[key] = b
                    self._next_id = max(self._next_id, b.baseline_id + 1)
            except Exception:
                continue  # a corrupt record must not poison boot

    def _persist(self, b: Baseline):
        if self._metadb is None:
            return
        d = {"schema": b.key[0], "sql": b.key[1], "id": b.baseline_id,
             "catalog_version": b.catalog_version,
             "accepted": b.accepted.to_json(),
             "candidate": b.candidate.to_json() if b.candidate else None}
        self._metadb.kv_put(_KV_PREFIX + f"{b.baseline_id}", json.dumps(d))

    def _unpersist(self, b: Baseline):
        if self._metadb is not None:
            self._metadb.kv_delete(_KV_PREFIX + f"{b.baseline_id}")

    # -- planning-time API --------------------------------------------------

    def choose(self, key: Tuple[str, str],
               catalog_version: int) -> Optional[List[Tuple[str, ...]]]:
        """Accepted join orders for this SQL, or None.  A DDL since capture
        (catalog version mismatch) drops the stale baseline (invalidation)."""
        if not self.enabled:
            return None
        with self._lock:
            b = self._baselines.get(key)
            if b is None:
                return None
            if b.catalog_version != catalog_version:
                del self._baselines[key]
                self._unpersist(b)
                return None
            return list(b.accepted.orders)

    def capture(self, key: Tuple[str, str], chosen: List[Tuple[str, ...]],
                catalog_version: int, followed_baseline: bool,
                cost_preferred: Optional[List[Tuple[str, ...]]] = None):
        """Record the planner's outcome.  First sight => accepted baseline;
        a cost-model disagreement (cost_preferred != accepted) => unaccepted
        candidate (evolution input), while execution keeps following the
        accepted plan."""
        if not self.enabled or not chosen:
            return
        with self._lock:
            b = self._baselines.get(key)
            if b is None:
                b = Baseline(key, catalog_version, PlanRecord(chosen, "cost"),
                             self._next_id)
                self._next_id += 1
                self._baselines[key] = b
                self._persist(b)
                return
            pref = [tuple(o) for o in (cost_preferred or chosen)]
            if pref != b.accepted.orders and \
                    (b.candidate is None or pref != b.candidate.orders):
                b.candidate = PlanRecord(pref, "cost")
                self._persist(b)

    def record_execution(self, key: Tuple[str, str], elapsed_ms: float,
                         params: Optional[list] = None):
        with self._lock:
            b = self._baselines.get(key)
            if b is None:
                return
            b.accepted.runs += 1
            b.accepted.total_ms += elapsed_ms
            if params is not None:
                b.last_params = list(params)

    def last_params(self, key: Tuple[str, str]) -> list:
        with self._lock:
            b = self._baselines.get(key)
            return list(b.last_params) if b is not None else []

    def note_regression(self, key: Tuple[str, str], note: str) -> bool:
        """Statement-summary sentinel verdict: stamp the accepted PlanRecord
        so BASELINE audits (SHOW BASELINE, /baselines) carry the runtime
        truth.  Returns False when the key has no baseline (hinted or
        uncached plans never captured one)."""
        with self._lock:
            b = self._baselines.get(key)
            if b is None:
                return False
            b.accepted.regressions += 1
            b.accepted.last_regression = note[:256]
            self._persist(b)
            return True

    # -- DAL ----------------------------------------------------------------

    def rows(self) -> List[tuple]:
        """SHOW BASELINE rows."""
        out = []
        with self._lock:
            for b in sorted(self._baselines.values(),
                            key=lambda x: x.baseline_id):
                out.append((b.baseline_id, b.key[0], b.key[1],
                            json.dumps([list(o) for o in b.accepted.orders]),
                            b.accepted.origin, b.accepted.runs,
                            round(b.accepted.avg_ms, 3) if b.accepted.runs else None,
                            json.dumps([list(o) for o in b.candidate.orders])
                            if b.candidate else None,
                            b.accepted.regressions,
                            b.accepted.last_regression))
        return out

    def delete(self, baseline_id: int) -> bool:
        with self._lock:
            for k, b in list(self._baselines.items()):
                if b.baseline_id == baseline_id:
                    del self._baselines[k]
                    self._unpersist(b)
                    return True
        return False

    def evolve(self, measure, min_gain: float = 0.8) -> List[tuple]:
        """Execute unaccepted candidates and promote the measurably faster ones.

        `measure(key, orders) -> elapsed_ms` runs the SQL with the given join
        orders forced (the session provides this).  A candidate is promoted
        when its measured time is < min_gain * accepted's average.  Returns
        (baseline_id, promoted, candidate_ms, accepted_avg_ms) per candidate."""
        results = []
        with self._lock:
            pending = [(k, b) for k, b in self._baselines.items()
                       if b.candidate is not None]
        for k, b in pending:
            cand_ms = measure(k, list(b.candidate.orders))
            accepted_avg = b.accepted.avg_ms
            promoted = cand_ms < min_gain * accepted_avg
            with self._lock:
                if promoted:
                    b.candidate.origin = "evolved"
                    b.candidate.runs = 1
                    b.candidate.total_ms = cand_ms
                    b.accepted = b.candidate
                b.candidate = None
                self._persist(b)
            results.append((b.baseline_id, promoted, round(cand_ms, 3),
                            round(accepted_avg, 3)))
        return results
