"""SPM — SQL Plan Management: baselines, accepted plans, evolution.

Reference analog: `polardbx-optimizer/.../planmanager/PlanManager.java:92` and
`BaselineInfo`/`PlanInfo`: per parameterized-SQL *baselines* pin the join order
the executor runs, independent of what the cost model would pick today.  The
first execution captures the cost-based choice as the accepted plan; later
plannings reuse it even when statistics drift would flip the greedy order
(plan stability).  When the cost model disagrees with the accepted plan, its
choice is kept as an *unaccepted candidate*; `BASELINE EVOLVE` executes
candidates and promotes one that is measurably faster (plan evolution).
Baselines are invalidated by DDL (catalog version) and persisted in the metadb
kv store so they survive restarts.

The plan identity here is the join order — the one decision our optimizer makes
that is both cost-driven and high-blast-radius (the reference's PlanInfo stores
full RelNode JSON; on this engine every other physical choice is deterministic
given the join tree).

Self-healing (round 10): each baseline is additionally a persisted per-digest
quarantine state machine driven by the statement-summary sentinel
(meta/statement_summary.py):

    HEALTHY --sentinel--> REGRESSED --next bind--> PROBATION
                                                     |-- verified fast --> HEALED
                                                     |-- old plan slow too --> EVOLVED
                                                     '-- repair didn't help --> HEAL_FAILED

A REGRESSED baseline's next bind re-plans pinned to the episode's rollback
orders (the frozen known-good PlanRecord) — or, for same-plan stats drift,
unpinned so repaired statistics can pick a better order — then the next
`PLAN_HEAL_VERIFY_EXECS` executions are judged against the frozen latency
baseline median.  Flap damping is breaker-style (per-digest cooldown + a max
episode count); HEAL_FAILED parks the digest until ANALYZE/DDL moves the
catalog version.  The whole machine persists in the metadb baseline record so
a coordinator restart resumes probation instead of re-thrashing."""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional, Tuple

_KV_PREFIX = "spm.baseline."
# record-format version: 2 = orders captured in GOO MERGE order (left-deep
# replay reproduces the captured tree); older records are dropped at attach
_KV_VERSION = 2

# quarantine states of a baseline's heal machine (SHOW BASELINE `STATE`)
HEAL_STATES = ("HEALTHY", "REGRESSED", "PROBATION", "HEALED", "EVOLVED",
               "HEAL_FAILED")
# states with a live episode: the sentinel must not start another
_ACTIVE_STATES = frozenset({"REGRESSED", "PROBATION"})


class HealEpisode:
    """One in-flight quarantine episode (persisted with its baseline)."""

    __slots__ = ("mode", "reason", "rollback_orders", "baseline_ms",
                 "regressed_ms", "factor", "verify_execs", "samples",
                 "observed_orders", "started_at", "armed", "rejects")

    def __init__(self, mode: str, reason: str,
                 rollback_orders: Optional[List[Tuple[str, ...]]],
                 baseline_ms: float, regressed_ms: float, factor: float,
                 verify_execs: int, started_at: float):
        self.mode = mode              # rollback | repair
        self.reason = reason          # new_plan | plan_drift
        self.rollback_orders = [tuple(o) for o in (rollback_orders or [])]
        self.baseline_ms = baseline_ms
        self.regressed_ms = regressed_ms  # the flagged window's median
        self.factor = factor
        self.verify_execs = max(int(verify_execs), 1)
        self.samples: List[float] = []
        # join orders the probation executions actually ran (the promote
        # target; for rollback mode these equal rollback_orders, for repair
        # mode they are whatever the corrected stats made the cost model pick)
        self.observed_orders: List[Tuple[str, ...]] = []
        self.started_at = started_at
        # repair episodes stay UNARMED (binds keep the pinned accepted plan)
        # until the synchronous stats repair completes — a bind racing the
        # repair would otherwise anchor probation on still-drifted stats
        self.armed = mode == "rollback"
        # probation executions whose orders did not match the expected plan;
        # a bounded count closes a wedged episode instead of pinning the
        # digest in PROBATION forever
        self.rejects = 0

    def to_json(self):
        return {"mode": self.mode, "reason": self.reason,
                "rollback_orders": [list(o) for o in self.rollback_orders],
                "baseline_ms": self.baseline_ms,
                "regressed_ms": self.regressed_ms, "factor": self.factor,
                "verify_execs": self.verify_execs, "samples": self.samples,
                "observed_orders": [list(o) for o in self.observed_orders],
                "started_at": self.started_at, "armed": self.armed,
                "rejects": self.rejects}

    @classmethod
    def from_json(cls, d):
        h = cls(d.get("mode", "rollback"), d.get("reason", "new_plan"),
                [tuple(o) for o in d.get("rollback_orders", [])],
                d.get("baseline_ms", 0.0), d.get("regressed_ms", 0.0),
                d.get("factor", 1.5), d.get("verify_execs", 5),
                d.get("started_at", 0.0))
        h.samples = [float(v) for v in d.get("samples", [])]
        h.observed_orders = [tuple(o) for o in d.get("observed_orders", [])]
        h.armed = d.get("armed", True)
        h.rejects = int(d.get("rejects", 0))
        return h


class SpmContext:
    """Per-planning handshake with build_join_tree: carries the forced order in
    and the chosen order out (one entry per join forest, preorder)."""

    def __init__(self, forced: Optional[List[Tuple[str, ...]]] = None):
        self.forced = forced or []   # list of label tuples, one per forest
        self.chosen: List[Tuple[str, ...]] = []
        # what the cost model would pick (== chosen unless a baseline forced)
        self.cost_preferred: List[Tuple[str, ...]] = []
        self._forest_ix = 0

    def next_forced(self) -> Optional[Tuple[str, ...]]:
        ix = self._forest_ix
        self._forest_ix += 1
        if ix < len(self.forced):
            return self.forced[ix]
        return None


class PlanRecord:
    __slots__ = ("orders", "origin", "runs", "total_ms", "regressions",
                 "last_regression")

    def __init__(self, orders: List[Tuple[str, ...]], origin: str = "cost",
                 runs: int = 0, total_ms: float = 0.0):
        self.orders = [tuple(o) for o in orders]
        self.origin = origin          # cost | evolved | manual
        self.runs = runs
        self.total_ms = total_ms
        # runtime-regression audit trail, written by the statement-summary
        # sentinel (meta/statement_summary.py): how often this accepted plan
        # was flagged against the digest's latency baseline, and why last
        self.regressions = 0
        self.last_regression = ""

    @property
    def avg_ms(self) -> float:
        return self.total_ms / self.runs if self.runs else float("inf")

    def to_json(self):
        return {"orders": [list(o) for o in self.orders], "origin": self.origin,
                "runs": self.runs, "total_ms": self.total_ms,
                "regressions": self.regressions,
                "last_regression": self.last_regression}

    @classmethod
    def from_json(cls, d):
        r = cls([tuple(o) for o in d["orders"]], d.get("origin", "cost"),
                d.get("runs", 0), d.get("total_ms", 0.0))
        r.regressions = d.get("regressions", 0)
        r.last_regression = d.get("last_regression", "")
        return r


class Baseline:
    __slots__ = ("key", "catalog_version", "accepted", "candidate", "baseline_id",
                 "last_params", "state", "rollbacks", "last_heal",
                 "last_heal_at", "park_version", "heal")

    def __init__(self, key: Tuple[str, str], catalog_version: int,
                 accepted: PlanRecord, baseline_id: int,
                 candidate: Optional[PlanRecord] = None):
        self.key = key
        self.catalog_version = catalog_version
        self.accepted = accepted
        self.candidate = candidate
        self.baseline_id = baseline_id
        self.last_params: list = []  # most recent bind values (evolution input)
        # self-heal quarantine machine (HEAL_STATES); all persisted so a
        # coordinator restart resumes probation instead of re-thrashing
        self.state = "HEALTHY"
        self.rollbacks = 0            # lifetime heal episodes (flap damping)
        self.last_heal = ""           # one human line about the last verdict
        self.last_heal_at = 0.0       # episode-start stamp (cooldown gate)
        self.park_version = -1        # catalog version at HEAL_FAILED park
        self.heal: Optional[HealEpisode] = None


class PlanManager:
    """Baseline store + accepted-plan choice + evolution (PlanManager.java:92)."""

    def __init__(self):
        self._baselines: Dict[Tuple[str, str], Baseline] = {}
        self._lock = threading.Lock()
        self._metadb = None
        self._next_id = 1
        self.enabled = True
        # live heal episodes (REGRESSED/PROBATION).  heal_pin() reads this
        # without the lock so the zero-episode hot path costs one int compare.
        self._healing = 0

    # -- persistence --------------------------------------------------------

    def attach(self, metadb):
        """Bind the metadb and reload persisted baselines."""
        self._metadb = metadb
        for k, v in metadb.kv_scan(_KV_PREFIX):
            try:
                d = json.loads(v)
                if d.get("v", 1) < _KV_VERSION:
                    # pre-merge-order records hold lead-concat label orders:
                    # replaying one left-deep can reconstruct a DIFFERENT
                    # (possibly m:n-first) tree than the plan it pinned —
                    # drop it; the next execution re-captures correctly
                    metadb.kv_delete(k)
                    continue
                key = (d["schema"], d["sql"])
                b = Baseline(key, d["catalog_version"],
                             PlanRecord.from_json(d["accepted"]),
                             d.get("id", self._next_id),
                             PlanRecord.from_json(d["candidate"])
                             if d.get("candidate") else None)
                b.state = d.get("state", "HEALTHY")
                b.rollbacks = d.get("rollbacks", 0)
                b.last_heal = d.get("last_heal", "")
                b.last_heal_at = d.get("last_heal_at", 0.0)
                b.park_version = d.get("park_version", -1)
                if d.get("heal"):
                    b.heal = HealEpisode.from_json(d["heal"])
                if b.heal is not None and not b.heal.armed:
                    # crash between begin_quarantine and arm_heal: whether
                    # the stats repair completed is unknowable — abort the
                    # episode (un-parked) instead of reloading a wedge the
                    # sentinel could never close
                    b.state = "HEAL_FAILED"
                    b.park_version = -1
                    b.last_heal = "aborted: repair interrupted by restart"
                    b.heal = None
                with self._lock:
                    self._baselines[key] = b
                    self._next_id = max(self._next_id, b.baseline_id + 1)
                    if b.state in _ACTIVE_STATES and b.heal is not None:
                        self._healing += 1  # restart resumes probation
            except Exception:
                continue  # a corrupt record must not poison boot

    def _persist(self, b: Baseline):
        if self._metadb is None:
            return
        d = {"v": _KV_VERSION,
             "schema": b.key[0], "sql": b.key[1], "id": b.baseline_id,
             "catalog_version": b.catalog_version,
             "accepted": b.accepted.to_json(),
             "candidate": b.candidate.to_json() if b.candidate else None,
             "state": b.state, "rollbacks": b.rollbacks,
             "last_heal": b.last_heal, "last_heal_at": b.last_heal_at,
             "park_version": b.park_version,
             "heal": b.heal.to_json() if b.heal else None}
        self._metadb.kv_put(_KV_PREFIX + f"{b.baseline_id}", json.dumps(d))

    def _unpersist(self, b: Baseline):
        if self._metadb is not None:
            self._metadb.kv_delete(_KV_PREFIX + f"{b.baseline_id}")

    # -- planning-time API --------------------------------------------------

    def choose(self, key: Tuple[str, str],
               catalog_version: int) -> Optional[List[Tuple[str, ...]]]:
        """Accepted join orders for this SQL, or None.  A DDL since capture
        (catalog version mismatch) drops the stale baseline (invalidation).

        A REGRESSED baseline's next bind enters PROBATION here: rollback
        episodes pin the frozen known-good orders; repair (stats-drift)
        episodes return None so the corrected statistics drive a fresh cost
        choice.  The probation plan is then judged by record_execution."""
        if not self.enabled:
            return None
        with self._lock:
            b = self._baselines.get(key)
            if b is None:
                return None
            if b.catalog_version != catalog_version:
                if b.state in _ACTIVE_STATES and b.heal is not None:
                    self._healing -= 1  # DDL aborts the episode with the plan
                del self._baselines[key]
                self._unpersist(b)
                return None
            if b.heal is not None and b.state in _ACTIVE_STATES and \
                    b.heal.armed:
                if b.state == "REGRESSED":
                    b.state = "PROBATION"
                    self._persist(b)
                if b.heal.mode == "rollback":
                    return [tuple(o) for o in b.heal.rollback_orders]
                return None  # repair probation: repaired stats pick the plan
            # an UNARMED repair episode keeps the pinned plan: the stats
            # repair has not finished yet, so probation must not start
            return list(b.accepted.orders)

    def heal_pin(self, key: Tuple[str, str]) -> str:
        """Fragment-cache salt for plans bound while this key's heal episode
        is live: probation artifacts and regressed-plan artifacts must never
        cross in the cache.  '' (steady state) costs one int compare."""
        if self._healing == 0:
            return ""
        with self._lock:
            b = self._baselines.get(key)
            if b is None or b.heal is None or b.state not in _ACTIVE_STATES:
                return ""
            return f"heal:{b.baseline_id}:{b.rollbacks}"

    # -- self-heal loop (statement-summary sentinel drives this) -------------

    def arm_heal(self, key: Tuple[str, str]):
        """Arm a repair episode once the stats repair has completed: from
        the NEXT bind on, probation runs unpinned and anchors on the
        corrected-stats cost choice (capture)."""
        with self._lock:
            b = self._baselines.get(key)
            if b is not None and b.heal is not None and not b.heal.armed:
                b.heal.armed = True
                self._persist(b)

    def abort_heal(self, key: Tuple[str, str], note: str):
        """Close a live episode that cannot proceed (repair raised, heal
        machinery error).  Unlike a judged HEAL_FAILED, an abort does NOT
        park: park_version stays -1, so the sentinel may open a fresh
        episode after the cooldown — an interrupted repair must not kill the
        digest's heal loop forever."""
        with self._lock:
            b = self._baselines.get(key)
            if b is None or b.heal is None or b.state not in _ACTIVE_STATES:
                return
            b.state = "HEAL_FAILED"
            b.park_version = -1
            b.last_heal = f"aborted: {note}"[:256]
            b.heal = None
            self._healing -= 1
            self._persist(b)

    def begin_quarantine(self, key: Tuple[str, str], mode: str, reason: str,
                         rollback_orders: Optional[List[Tuple[str, ...]]],
                         baseline_ms: float, factor: float, verify_execs: int,
                         max_rollbacks: int, cooldown_s: float,
                         stats_version: int, regressed_ms: float = 0.0,
                         now: Optional[float] = None) -> Optional[dict]:
        """Open a heal episode for a sentinel-flagged digest.  Returns the
        action taken — {"action": "rollback"|"repair"|"damped", ...} — or
        None when no episode may start (no baseline, one already live,
        parked, or cooling down).  Breaker-style flap damping: a digest that
        keeps regressing within the cooldown, or that has burned its episode
        budget, parks in HEAL_FAILED until ANALYZE/DDL/stats-repair moves
        the STATS epoch (`Catalog.stats_version` — deliberately not
        `catalog.version`, which every DML commit bumps)."""
        now = time.time() if now is None else now
        with self._lock:
            b = self._baselines.get(key)
            if b is None or not self.enabled:
                return None
            if b.state in _ACTIVE_STATES:
                return None  # one episode at a time
            if b.state == "HEAL_FAILED":
                if b.park_version == stats_version:
                    return None  # parked: re-arm only on ANALYZE/DDL
                # stats/schema moved since the park: re-arm with a fresh
                # episode budget
                b.rollbacks = 0
                b.park_version = -1
            if b.last_heal_at and now - b.last_heal_at < cooldown_s:
                return None  # cooling down: detect-only until it elapses
            if b.rollbacks >= max(int(max_rollbacks), 1):
                b.state = "HEAL_FAILED"
                b.park_version = stats_version
                b.last_heal = f"flap_damped: {b.rollbacks} episodes"
                b.heal = None
                self._persist(b)
                return {"action": "damped", "baseline_id": b.baseline_id,
                        "rollbacks": b.rollbacks}
            b.heal = HealEpisode(mode, reason, rollback_orders, baseline_ms,
                                 regressed_ms, factor, verify_execs, now)
            b.state = "REGRESSED"
            b.rollbacks += 1
            b.last_heal_at = now
            self._healing += 1
            self._persist(b)
            return {"action": mode, "baseline_id": b.baseline_id,
                    "rollbacks": b.rollbacks,
                    "rollback_orders": [list(o)
                                        for o in b.heal.rollback_orders]}

    def capture(self, key: Tuple[str, str], chosen: List[Tuple[str, ...]],
                catalog_version: int, followed_baseline: bool,
                cost_preferred: Optional[List[Tuple[str, ...]]] = None):
        """Record the planner's outcome.  First sight => accepted baseline;
        a cost-model disagreement (cost_preferred != accepted) => unaccepted
        candidate (evolution input), while execution keeps following the
        accepted plan."""
        if not self.enabled or not chosen:
            return
        with self._lock:
            b = self._baselines.get(key)
            if b is None:
                b = Baseline(key, catalog_version, PlanRecord(chosen, "cost"),
                             self._next_id)
                self._next_id += 1
                self._baselines[key] = b
                self._persist(b)
                return
            if b.state == "PROBATION" and b.heal is not None and \
                    b.heal.mode == "repair" and not b.heal.observed_orders:
                # anchor the repair episode on the POST-REPAIR bind's cost
                # choice: only executions of THIS plan count as verification
                # samples (an in-flight regressed-plan straggler never
                # re-binds, so it can neither set nor match the anchor)
                b.heal.observed_orders = [tuple(o) for o in chosen]
                self._persist(b)
            pref = [tuple(o) for o in (cost_preferred or chosen)]
            if pref != b.accepted.orders and \
                    (b.candidate is None or pref != b.candidate.orders):
                b.candidate = PlanRecord(pref, "cost")
                self._persist(b)

    def record_execution(self, key: Tuple[str, str], elapsed_ms: float,
                         params: Optional[list] = None,
                         orders: Optional[List[Tuple[str, ...]]] = None,
                         stats_version: int = -1) -> Optional[dict]:
        """Per-execution bookkeeping; during PROBATION also a verification
        sample.  Returns a heal VERDICT dict once the episode's sample quota
        fills — {"kind": "promoted"|"evolved"|"failed", ...} — else None (the
        steady-state path pays one extra attribute compare)."""
        with self._lock:
            b = self._baselines.get(key)
            if b is None:
                return None
            b.accepted.runs += 1
            b.accepted.total_ms += elapsed_ms
            if params is not None:
                b.last_params = list(params)
            if b.state != "PROBATION" or b.heal is None:
                return None
            h = b.heal
            # verification samples must come from the PROBATION plan: a
            # regressed-plan execution already in flight when the episode
            # opened (bound before the cache invalidation) would otherwise
            # pollute the median — or, worse, land as observed_orders and
            # get PROMOTED as the "verified" plan.  Rollback episodes expect
            # exactly the pinned orders; repair episodes lock onto whatever
            # the first post-repair bind chose.
            got = [tuple(o) for o in orders] if orders else None
            if got is None:
                return None  # unattributable execution: not a sample
            expected = h.rollback_orders if h.mode == "rollback" \
                else h.observed_orders  # anchored by the probation bind
            if not expected or got != expected:
                # straggler of another plan (or pre-anchor).  Bounded: a
                # probation that only ever sees mismatching executions would
                # otherwise wedge the digest in PROBATION forever — close it
                # as failed once the rejects clearly outnumber any plausible
                # straggler tail.
                h.rejects += 1
                if h.rejects > 8 * h.verify_execs:
                    b.last_heal = (f"heal_failed({h.reason}): probation "
                                   f"never observed the expected plan "
                                   f"({h.rejects} mismatched executions)")
                    b.state = "HEAL_FAILED"
                    b.park_version = stats_version
                    verdict = {"key": b.key, "baseline_id": b.baseline_id,
                               "mode": h.mode, "reason": h.reason,
                               "kind": "failed", "median_ms": 0.0,
                               "baseline_ms": round(h.baseline_ms, 3),
                               "factor": h.factor, "rollbacks": b.rollbacks,
                               "refreeze": False}
                    b.heal = None
                    self._healing -= 1
                    self._persist(b)
                    return verdict
                return None
            h.samples.append(elapsed_ms)
            if len(h.samples) < h.verify_execs:
                self._persist(b)  # probation progress survives a restart
                return None
            return self._judge_locked(b, stats_version)

    def _judge_locked(self, b: Baseline, stats_version: int) -> dict:
        """Close the episode: compare the probation median against the frozen
        latency baseline and promote / evolve / park.  Caller holds _lock."""
        h = b.heal
        s = sorted(h.samples)
        median = s[len(s) // 2]
        met_baseline = h.baseline_ms > 0 and median <= h.factor * h.baseline_ms
        # the baseline may be unreachable (real data growth) while the
        # probation plan still clearly beats the regressed one — keeping the
        # regressed plan because probation "only" won by 100x would be
        # perverse; promote, but re-freeze the latency baseline to the new
        # normal so the sentinel keeps an honest yardstick
        beats_regressed = h.regressed_ms > 0 and \
            median * h.factor <= h.regressed_ms
        verdict = {"key": b.key, "baseline_id": b.baseline_id, "mode": h.mode,
                   "reason": h.reason, "median_ms": round(median, 3),
                   "baseline_ms": round(h.baseline_ms, 3),
                   "factor": h.factor, "rollbacks": b.rollbacks,
                   "refreeze": False}
        if met_baseline or beats_regressed:
            # probation plan verified: promote it as the accepted plan
            # (rollback mode: the frozen known-good orders; repair mode:
            # whatever the corrected stats made the cost model pick)
            orders = h.observed_orders or h.rollback_orders
            if orders:
                b.accepted = PlanRecord([tuple(o) for o in orders], "healed",
                                        runs=len(h.samples),
                                        total_ms=sum(h.samples))
            b.candidate = None
            b.state = "HEALED"
            b.last_heal = (f"healed({h.reason}): median {median:.1f}ms vs "
                           f"baseline {h.baseline_ms:.1f}ms"
                           + ("" if met_baseline else
                              f" (baseline unreachable; beat regressed "
                              f"{h.regressed_ms:.1f}ms, re-frozen)"))
            verdict["kind"] = "promoted"
            verdict["orders"] = [list(o) for o in b.accepted.orders]
            verdict["refreeze"] = not met_baseline
        elif h.mode == "rollback":
            # the old plan is slow now too: the regression wasn't the plan's
            # fault — keep the new plan and let the latency baseline re-freeze
            # on it (plan evolution under drifted data)
            b.accepted.origin = "evolved"
            b.candidate = None
            b.state = "EVOLVED"
            b.last_heal = (f"evolved({h.reason}): rollback median "
                           f"{median:.1f}ms missed baseline "
                           f"{h.baseline_ms:.1f}ms; new plan kept, "
                           f"baseline re-frozen")
            verdict["kind"] = "evolved"
            verdict["orders"] = [list(o) for o in b.accepted.orders]
            verdict["refreeze"] = True
        else:
            # stats repair didn't recover the digest: park until ANALYZE/DDL
            b.state = "HEAL_FAILED"
            b.park_version = stats_version
            b.last_heal = (f"heal_failed({h.reason}): post-repair median "
                           f"{median:.1f}ms vs baseline {h.baseline_ms:.1f}ms")
            verdict["kind"] = "failed"
        b.heal = None
        self._healing -= 1
        self._persist(b)
        return verdict

    def last_params(self, key: Tuple[str, str]) -> list:
        with self._lock:
            b = self._baselines.get(key)
            return list(b.last_params) if b is not None else []

    def note_regression(self, key: Tuple[str, str], note: str) -> bool:
        """Statement-summary sentinel verdict: stamp the accepted PlanRecord
        so BASELINE audits (SHOW BASELINE, /baselines) carry the runtime
        truth.  Returns False when the key has no baseline (hinted or
        uncached plans never captured one)."""
        with self._lock:
            b = self._baselines.get(key)
            if b is None:
                return False
            b.accepted.regressions += 1
            b.accepted.last_regression = note[:256]
            self._persist(b)
            return True

    # -- DAL ----------------------------------------------------------------

    def rows(self) -> List[tuple]:
        """SHOW BASELINE rows."""
        out = []
        with self._lock:
            for b in sorted(self._baselines.values(),
                            key=lambda x: x.baseline_id):
                out.append((b.baseline_id, b.key[0], b.key[1],
                            json.dumps([list(o) for o in b.accepted.orders]),
                            b.accepted.origin, b.accepted.runs,
                            round(b.accepted.avg_ms, 3) if b.accepted.runs else None,
                            json.dumps([list(o) for o in b.candidate.orders])
                            if b.candidate else None,
                            b.accepted.regressions,
                            b.accepted.last_regression,
                            b.state, b.rollbacks, b.last_heal))
        return out

    def delete(self, baseline_id: int) -> bool:
        with self._lock:
            for k, b in list(self._baselines.items()):
                if b.baseline_id == baseline_id:
                    if b.state in _ACTIVE_STATES and b.heal is not None:
                        self._healing -= 1
                    del self._baselines[k]
                    self._unpersist(b)
                    return True
        return False

    def evolve(self, measure, min_gain: float = 0.8) -> List[tuple]:
        """Execute unaccepted candidates and promote the measurably faster ones.

        `measure(key, orders) -> elapsed_ms` runs the SQL with the given join
        orders forced (the session provides this).  A candidate is promoted
        when its measured time is < min_gain * accepted's average.  Returns
        (baseline_id, promoted, candidate_ms, accepted_avg_ms) per candidate."""
        results = []
        with self._lock:
            pending = [(k, b) for k, b in self._baselines.items()
                       if b.candidate is not None]
        for k, b in pending:
            cand_ms = measure(k, list(b.candidate.orders))
            accepted_avg = b.accepted.avg_ms
            promoted = cand_ms < min_gain * accepted_avg
            with self._lock:
                if promoted:
                    b.candidate.origin = "evolved"
                    b.candidate.runs = 1
                    b.candidate.total_ms = cand_ms
                    b.accepted = b.candidate
                b.candidate = None
                self._persist(b)
            results.append((b.baseline_id, promoted, round(cand_ms, 3),
                            round(accepted_avg, 3)))
        return results
