"""Planner driver + plan cache.

Reference analog: `Planner.plan(sql, ec) -> ExecutionPlan` (SURVEY.md §2.5):
parameterize -> plan-cache probe -> parse -> bind/validate -> RBO -> (physical at
execution).  The cache key is (schema, parameterized SQL); entries are invalidated by
catalog version, mirroring `PlanCache.java:80`'s metadata-version keying.

Workload classification (TP vs AP) follows `WorkloadUtil.determineWorkloadType`
(§2.5): estimated scanned rows under threshold -> TP; over -> AP.  The executor uses
this to pick the engine (host path for latency-bound point queries, device kernels for
scans), mirroring `ExecutorHelper.selectExecutorMode`.
"""

from __future__ import annotations

import collections
import threading
from typing import Dict, List, Optional, Tuple

from galaxysql_tpu.meta.catalog import Catalog
from galaxysql_tpu.plan import logical as L
from galaxysql_tpu.plan.binder import Binder
from galaxysql_tpu.plan.rules import estimate_rows, optimize
from galaxysql_tpu.sql import ast
from galaxysql_tpu.sql.parameterize import parameterize
from galaxysql_tpu.sql.parser import parse


class ExecutionPlan:
    def __init__(self, rel: L.RelNode, display_names: List[str],
                 statement: ast.Statement, catalog_version: int,
                 param_count: int):
        self.rel = rel
        self.display_names = display_names
        self.statement = statement
        self.catalog_version = catalog_version
        self.param_count = param_count
        self.scanned_rows = scanned_rows_estimate(rel)
        self.workload = "AP" if self.scanned_rows >= AP_ROW_THRESHOLD else "TP"
        self.spm_key = None          # set when planned through the cache path
        self.join_orders: List[Tuple[str, ...]] = []
        self.hints: Dict[str, object] = {}
        self.heal_pin = ""           # fragment-cache salt while healing

    def fields(self) -> List[L.Field]:
        return self.rel.fields()

    def explain(self) -> str:
        return L.explain(self.rel)


AP_ROW_THRESHOLD = 50_000


def scanned_rows_estimate(rel: L.RelNode) -> float:
    total = 0.0
    for n in L.walk(rel):
        if isinstance(n, L.Scan):
            if n.point_eq is not None:
                # index access path: the scan touches ~rows/NDV candidates,
                # not the table (DirectShardingKeyTableOperation analog);
                # ANALYZE stats keep the TP/AP classification honest for
                # non-unique index leads
                ndv = n.table.stats.ndv.get(n.point_eq[0], 0)
                est = (n.table.stats.row_count / ndv) if ndv else 2.0
                total += max(est, 2.0)
                continue
            frac = 1.0
            if n.partitions is not None and n.table.partition.num_partitions > 0:
                frac = len(n.partitions) / n.table.partition.num_partitions
            total += n.table.stats.row_count * frac
    return total


def classify_workload(rel: L.RelNode) -> str:
    """TP = small row footprint (host engine); AP = large (device engine)."""
    return "AP" if scanned_rows_estimate(rel) >= AP_ROW_THRESHOLD else "TP"


class PlanCache:
    """Guava-cache analog: bounded LRU keyed by (schema, parameterized SQL)."""

    def __init__(self, capacity: int = 4096):
        self.capacity = capacity
        self._map: "collections.OrderedDict[Tuple[str, str], ExecutionPlan]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def get(self, key: Tuple[str, str], catalog_version: int) -> Optional[ExecutionPlan]:
        with self._lock:
            plan = self._map.get(key)
            if plan is None or plan.catalog_version != catalog_version:
                if plan is not None:
                    del self._map[key]
                self.misses += 1
                return None
            self._map.move_to_end(key)
            self.hits += 1
            return plan

    def put(self, key: Tuple[str, str], plan: ExecutionPlan):
        with self._lock:
            self._map[key] = plan
            self._map.move_to_end(key)
            while len(self._map) > self.capacity:
                self._map.popitem(last=False)

    def invalidate(self, key: Tuple[str, str]):
        """Drop ONE digest's cached plan (the self-heal loop retires a
        regressed or probation plan without a fleet-wide replan storm)."""
        with self._lock:
            self._map.pop(key, None)

    def invalidate_all(self):
        with self._lock:
            self._map.clear()


class Planner:
    def __init__(self, catalog: Catalog):
        self.catalog = catalog
        self.cache = PlanCache()
        from galaxysql_tpu.plan.spm import PlanManager
        self.spm = PlanManager()

    def plan_select(self, sql: str, schema: str,
                    params: Optional[list] = None, session=None) -> ExecutionPlan:
        """Plan a SELECT (or EXPLAIN-able) statement with caching.

        The PARAMETERIZED text is what gets parsed, so the cached AST carries `?`
        placeholders; executions with new values re-bind from that AST (skipping the
        parse — the reference's PlanCache + per-execution PostPlanner split).  Literal
        values and client-protocol params resolve through the slot plan in order.
        """
        p = parameterize(sql)
        key = (schema.lower(), p.cache_key)
        bind_values = p.resolve(params or [])
        low = sql.lower()
        if "nextval" in low or "connection_id" in low or "_lock" in low:
            # per-execution values (sequences, session identity): never cache; bind
            # the PARAMETERIZED text so client '?' indexes stay aligned
            return self.bind_statement(parse(p.parameterized), schema, bind_values,
                                       session)
        cached = self.cache.get(key, self.catalog.version)
        if cached is not None and cached.param_count == len(bind_values):
            if cached.bound_params == bind_values:
                return cached
            plan = self.bind_statement(cached.statement, schema, bind_values, session,
                                       spm_key=key)
            self.cache.put(key, plan)
            return plan
        stmt = parse(p.parameterized)
        plan = self.bind_statement(stmt, schema, bind_values, session, spm_key=key)
        self.cache.put(key, plan)
        return plan

    def bind_statement(self, stmt: ast.Statement, schema: str,
                       params: list, session=None,
                       spm_key: Optional[Tuple[str, str]] = None,
                       forced_orders: Optional[list] = None) -> ExecutionPlan:
        binder = Binder(self.catalog, schema, params)
        if session is not None:
            binder.sequence_hook = \
                lambda nm: session.instance.sequences.next_value(schema, nm)
            binder.connection_id = session.conn_id
            binder.lock_fn_hook = session._lock_fn
        if isinstance(stmt, (ast.Select, ast.SetOpSelect)):
            rel, names = binder.bind_query(stmt)
        else:
            raise ValueError(f"not a plannable statement: {type(stmt).__name__}")
        # hints outrank SPM; SPM accepted baselines outrank the cost model
        from galaxysql_tpu.sql.hints import parse_hints, qualified_order
        hints = parse_hints(getattr(stmt, "hints", None))
        from galaxysql_tpu.plan.spm import SpmContext
        forced = forced_orders
        if forced is None and hints.get("join_order"):
            forced = [tuple(qualified_order(hints["join_order"], schema))]
        hinted = forced_orders is None and (bool(hints.get("join_order")) or
                                            hints.get("baseline_off"))
        if forced is None and spm_key is not None and not hinted:
            forced = self.spm.choose(spm_key, self.catalog.schema_version)
        spm_ctx = SpmContext(forced)
        rel = optimize(rel, spm_ctx, catalog=self.catalog, hints=hints)
        if forced_orders is None and not hinted and spm_key is not None and \
                spm_ctx.chosen:
            self.spm.capture(spm_key, spm_ctx.chosen, self.catalog.schema_version,
                             followed_baseline=forced is not None,
                             cost_preferred=spm_ctx.cost_preferred)
        plan = ExecutionPlan(rel, names, stmt, self.catalog.version, len(params))
        plan.bound_params = list(params)
        plan.spm_key = None if hinted else spm_key
        plan.join_orders = list(spm_ctx.chosen)
        plan.hints = hints
        # self-heal salt: plans bound while this digest's heal episode is live
        # carry a pin that re-keys fragment-cache fingerprints, so probation
        # and regressed artifacts never cross (zero-episode path: one compare)
        plan.heal_pin = self.spm.heal_pin(spm_key) \
            if plan.spm_key is not None else ""
        return plan



