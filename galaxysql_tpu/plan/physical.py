"""Physical planning: logical plan -> operator tree.

Reference analog: the physical convention step (`DrdsConvention`, SURVEY.md §2.5) +
`LocalExecutionPlanner` building operator pipelines (§2.7).  Decisions made here:

- hash join sides: build = smaller estimated input (the probe side streams);
  left/semi/anti joins fix the probe side to the preserved/output side.
- aggregates use estimated group counts to size the fixed-shape kernel output.
- scans rename storage columns to plan field ids and carry pruned partition lists.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterator, List, Optional

import numpy as np

from galaxysql_tpu.chunk.batch import Column, ColumnBatch
from galaxysql_tpu.exec import fusion
from galaxysql_tpu.exec import operators as ops
from galaxysql_tpu.expr import ir
from galaxysql_tpu.expr.compiler import _find_dictionary
from galaxysql_tpu.plan import logical as L
from galaxysql_tpu.plan.rules import estimate_rows
from galaxysql_tpu.storage.table_store import TableStore
from galaxysql_tpu.types import datatype as dt
from galaxysql_tpu.utils import errors


class ExecContext:
    """Per-execution context (ExecutionContext analog, SURVEY.md §2.5 misc)."""

    def __init__(self, stores: Dict[str, TableStore], snapshot_ts: Optional[int] = None,
                 params: Optional[list] = None, batch_rows: int = 1 << 20,
                 device_cache=None, txn_id: int = 0, archive=None,
                 archive_instance=None, hints=None):
        self.stores = stores          # "schema.table" -> TableStore
        self.snapshot_ts = snapshot_ts
        self.params = params or []
        self.batch_rows = batch_rows
        self.device_cache = device_cache  # DeviceCache or None (host-batch scans)
        self.txn_id = txn_id          # owning txn for MVCC visibility (0 = none)
        self.archive = archive        # ArchiveManager (cold parquet scans)
        self.archive_instance = archive_instance
        self.hints = hints or {}  # statement hints (sql/hints.py)
        # open worker branches of the session's txn: addr -> xid.  Remote scans
        # ship the xid so the worker reads through the branch (read-your-own-
        # writes across the seam, like the reference's txn-bound DN connection)
        self.remote_xids: Dict = {}
        self.sort_spill_bytes = 256 << 20   # SORT_SPILL_BYTES (session override)
        self.join_spill_bytes = 256 << 20   # JOIN_SPILL_BYTES
        self.agg_spill_bytes = 256 << 20    # partial-agg spill threshold
        # per-query memory pool (exec/memory.py child of GLOBAL_POOL): join
        # build / agg partial / sort slab reservations charge it; None keeps
        # every operator charge a no-op (admission disabled, bare contexts)
        self.mem_pool = None
        self.collect_stats = False       # EXPLAIN ANALYZE / profiling stats
        self.op_stats: List[dict] = []   # filled by StatsOp when collecting
        self.profile = None              # owning QueryProfile (utils/tracing)
        self.trace: List[str] = []
        # pipeline segment fusion (exec/fusion.py): module switch + NO_FUSE hint
        self.enable_fusion = fusion.default_enabled(self.hints)
        # per-execution runtime-filter hub (exec/runtime_filter.py): joins
        # publish build-side filters here, probe-side scans consume them;
        # NO_BLOOM / RUNTIME_FILTER(OFF) hints turn it off
        from galaxysql_tpu.exec.runtime_filter import RuntimeFilterManager
        self.rf = RuntimeFilterManager(
            hints=self.hints,
            metrics=getattr(archive_instance, "metrics", None))
        # cross-query fragment cache (exec/fragment_cache.py): join build
        # artifacts, deterministic subplan results, cached filter publications.
        # None when disabled (env/config/hint) or outside an Instance context.
        from galaxysql_tpu.exec import fragment_cache as _fc
        self.frag = _fc.for_context(archive_instance, self.hints)
        # store uids this execution's txn has written (session fills it in);
        # None with a live txn means "unknown write set" — the cache bypasses
        self.txn_write_uids = frozenset() if txn_id == 0 else None
        # skew-aware execution (exec/skew.py): which planted plans this
        # execution may activate (env + SKEW hint + ENABLE_SKEW_EXECUTION),
        # and the per-node decisions EXPLAIN ANALYZE / stage spans surface
        from galaxysql_tpu.exec import skew as _skew
        self.skew_modes = _skew.exec_modes(self.hints, archive_instance)
        self.skew_stats: Dict[int, dict] = {}
        # MAX_EXECUTION_TIME deadline (absolute time.time() seconds, or None):
        # checked at operator drain / fused-segment / MPP-stage boundaries and
        # propagated to workers as the remaining budget in RPC headers
        self.deadline: Optional[float] = None
        # self-heal pin (plan/spm.py heal_pin): non-empty while the plan's
        # digest has a live quarantine episode; salts fragment-cache
        # fingerprints so probation and regressed artifacts never cross
        self.plan_pin = ""
        # columnar HTAP routing (storage/columnar.py): table key ->
        # ReplicaView snapshot taken at routing; scans of those tables read
        # the replica at the routed watermark instead of the row store.
        # The fragment cache fingerprints them as ("cscan", seed_ts, events)
        # so replica-fed and row-fed artifacts never cross.
        self.columnar: Dict[str, object] = {}

    def check_deadline(self):
        """Raise a typed QueryTimeoutError once the deadline passes.  Called
        at pipeline boundaries — a None deadline costs one attribute read."""
        if self.deadline is not None:
            import time as _t
            if _t.time() > self.deadline:
                raise errors.QueryTimeoutError(
                    "query exceeded MAX_EXECUTION_TIME deadline")


# per-(store, version) scan metadata: O(table) host reductions must run once per
# version, not per query (the lanes themselves are cached the same way)
_SCAN_META: Dict = {}


def _scan_meta(store, version: int) -> Dict:
    key = (store.uid, version)
    meta = _SCAN_META.get(key)
    if meta is None:
        all_current = True
        max_begin = 0
        for p in store.partitions:
            if p.num_rows == 0:
                continue
            if not (bool((p.end_ts == np.iinfo(np.int64).max).all()) and
                    bool((p.begin_ts >= 0).all())):
                all_current = False
            else:
                max_begin = max(max_begin, int(p.begin_ts.max()))
        meta = {"all_current": all_current, "max_begin": max_begin,
                "valid_all": {}}
        if len(_SCAN_META) > 512:
            _SCAN_META.clear()
        _SCAN_META[key] = meta
    return meta


def _device_visibility(begin, end, ts, txn_id):
    """Device-side MVCC visibility — the jnp twin of native.visible_mask (one
    semantic change must touch exactly these two implementations)."""
    ins_ok = (begin >= 0) & (begin <= ts)
    dele = (end >= 0) & (end <= ts)
    if txn_id:
        ins_ok = ins_ok | (begin == -txn_id)
        dele = dele | (end == -txn_id)
    return ins_ok & ~dele


class ScanSource(ops.Operator):
    """Storage scan renamed into plan field-id space."""

    def __init__(self, node: L.Scan, ctx: ExecContext):
        self.node = node
        self.ctx = ctx

    def batches(self) -> Iterator[ColumnBatch]:
        t = self.node.table
        self.ctx.check_deadline()  # drain boundary: scans feed every pipeline
        if getattr(t, "remote", None) is not None:
            yield from self._remote_batches(t)
            return
        store = self.ctx.stores[f"{t.schema.lower()}.{t.name.lower()}"]
        storage_cols = [c for _, c in self.node.columns]
        rename = {c: oid for oid, c in self.node.columns}
        # flashback (AS OF TSO n): the scan reads at the requested snapshot —
        # own-txn provisional rows excluded (a historical read, not a txn read)
        as_of = self.node.as_of
        snap = as_of if as_of is not None else self.ctx.snapshot_ts
        txn_id = 0 if as_of is not None else self.ctx.txn_id
        self.ctx.trace.append(
            f"scan {t.name} partitions={self.node.partitions or 'all'}" +
            (f" as_of={as_of}" if as_of is not None else ""))
        yield from self._archive_batches(t, storage_cols, rename, snap)
        # columnar-replica route: the session snapshotted a ReplicaView at
        # the routed watermark (== ctx.snapshot_ts).  The archive batches
        # above still run — TTL-archived rows never reached the replica's
        # seed scan.  Flashback reads (as_of) always stay on the row store.
        if self.ctx.columnar and as_of is None:
            view = self.ctx.columnar.get(f"{t.schema.lower()}.{t.name.lower()}")
            if view is not None:
                yield from self._columnar_batches(t, view, storage_cols,
                                                  rename)
                return
        from galaxysql_tpu.exec.operators import bucket_capacity
        if self.node.point_eq is not None:
            yield from self._point_batches(t, store, snap, txn_id)
            return
        cache = self.ctx.device_cache
        if cache is None:
            for b in store.scan(storage_cols, self.node.partitions,
                                snap, txn_id=txn_id):
                self.ctx.check_deadline()  # per-partition drain boundary
                # pad to power-of-two buckets: partitions of different sizes must not
                # each compile their own kernel shapes
                yield b.pad_to(bucket_capacity(b.capacity)).rename(rename)
            return
        # device-resident path: whole column lanes pinned in HBM keyed by table
        # version; MVCC visibility computed on device from cached ts lanes
        import jax.numpy as jnp
        if self.node.partitions is None:
            # full-table scans fuse all partitions into ONE cached device batch:
            # one kernel dispatch per operator instead of one per partition
            b = self._fused_table_batch(t, store, cache, jnp, snap, txn_id)
            if b is not None:
                yield b.rename(rename)  # fused cols are storage-name keyed
                return
        pids = (range(len(store.partitions)) if self.node.partitions is None
                else self.node.partitions)
        ts = snap
        for pid in pids:
            p = store.partitions[pid]
            if p.num_rows == 0:
                continue
            cap = bucket_capacity(p.num_rows)

            def padded(arr, fill=0):
                if arr.shape[0] == cap:
                    return arr
                return np.concatenate(
                    [arr, np.full(cap - arr.shape[0], fill, dtype=arr.dtype)])

            cols = {}
            for oid, cname in self.node.columns:
                cm = t.column(cname)
                data = cache.get_lane(store, pid, cname, t.version,
                                      padded(p.lanes[cname]))
                valid = None
                if not bool(p.valid[cname].all()):
                    valid = cache.get_lane(store, pid, f"valid::{cname}", t.version,
                                           padded(p.valid[cname], False))
                cols[oid] = Column(data, valid, cm.dtype,
                                   t.dictionaries.get(cname.lower()))
            pad_live = jnp.arange(cap) < p.num_rows if cap != p.num_rows else None
            all_current = bool((p.end_ts == np.iinfo(np.int64).max).all()) and \
                bool((p.begin_ts >= 0).all())
            max_begin = int(p.begin_ts.max()) if p.num_rows else 0
            if all_current and (ts is None or max_begin <= ts):
                live = pad_live
            else:
                begin = cache.get_lane(store, pid, "::begin_ts", t.version,
                                       padded(p.begin_ts))
                end = cache.get_lane(store, pid, "::end_ts", t.version,
                                     padded(p.end_ts, -1))
                live = _device_visibility(begin, end, ts, txn_id)
                if pad_live is not None:
                    live = live & pad_live
            yield ColumnBatch(cols, live)


    def _point_batches(self, t, store, snap, txn_id) -> Iterator[ColumnBatch]:
        """Index access path: candidate rows from the partition's sorted key
        index instead of full lanes (XPlan key-Get / DirectShardingKey plan,
        Planner.java:914).  The Filter above the scan re-verifies the whole
        predicate, so candidates only need to be a superset of the matches
        for the indexed column; MVCC visibility is applied here."""
        from galaxysql_tpu import native
        from galaxysql_tpu.exec.operators import bucket_capacity
        col, val = self.node.point_eq
        pids = (range(len(store.partitions)) if self.node.partitions is None
                else self.node.partitions)
        for pid in pids:
            p = store.partitions[pid]
            if p.num_rows == 0:
                continue
            with p.lock:
                ids = p.key_candidates(col, val)
                if ids.size == 0:
                    continue
                vis = native.visible_mask(p.begin_ts[ids], p.end_ts[ids],
                                          snap, txn_id)
                ids = ids[vis]
                if ids.size == 0:
                    continue
                cols = {}
                for oid, cname in self.node.columns:
                    cm = t.column(cname)
                    d = p.lanes[cname][ids]
                    v = p.valid[cname][ids]
                    cols[oid] = Column(d, None if bool(v.all()) else v,
                                       cm.dtype,
                                       t.dictionaries.get(cname.lower()))
            self.ctx.trace.append(f"point-get {t.name} p{pid} rows={ids.size}")
            yield ColumnBatch(cols, None).pad_to(
                bucket_capacity(max(int(ids.size), 1)))

    def _remote_batches(self, t) -> Iterator[ColumnBatch]:
        """Plan shipping: the scan compiles to SQL executed by the worker
        process that owns the table (MyJdbcHandler.java:691 analog) — column
        pruning rides the SELECT list; results re-encode into this CN's lanes
        and dictionaries."""
        from galaxysql_tpu.chunk.batch import column_from_pylist
        from galaxysql_tpu.exec.operators import bucket_capacity
        inst = self.ctx.archive_instance
        if inst is None:
            raise errors.TddlError(
                f"remote table {t.name} needs an owning instance context")
        # weighted read routing over primary + replicas with fence-triggered
        # failover (TGroupDataSource analog): a request failure fences the
        # endpoint and retries another until none remain — WITHIN the same
        # statement, so a dead replica costs a re-route, not an error
        last_err = None
        for _attempt in range(1 + len(getattr(t, "replicas", []))):
            addr, client = inst.read_endpoint(t)
            try:
                # materialize BEFORE yielding: a mid-stream failover retry must
                # not re-emit rows already handed downstream
                got = list(self._remote_batches_from(t, inst, addr, client))
                yield from got
                return
            except errors.QueryTimeoutError:
                raise  # the deadline kills the STATEMENT, not the endpoint
            except (errors.TddlError, ConnectionError, OSError) as e:
                last_err = e
                transport = isinstance(
                    e, (errors.WorkerUnavailableError, ConnectionError,
                        OSError))
                if transport and not client.ping():
                    # ping-verified dead: fence and re-route — a transient
                    # blip (worker restarting, half-open probe race) must
                    # not fence an endpoint the next ping proves alive
                    from galaxysql_tpu.utils.metrics import WORKER_FAILOVERS
                    from galaxysql_tpu.utils import events
                    inst.ha.fence_worker(addr, True)
                    WORKER_FAILOVERS.inc()
                    events.publish("worker_failover",
                                   f"scan {t.name}: fenced dead endpoint "
                                   f"{addr[0]}:{addr[1]}, re-routing",
                                   node=inst.node_id, table=t.name,
                                   worker=f"{addr[0]}:{addr[1]}",
                                   fenced=True)
                    self.ctx.trace.append(
                        f"failover {t.name}: fenced {addr[0]}:{addr[1]}")
                    continue  # endpoint dead: re-route within the statement
                if transport:
                    # alive but erroring (breaker mid-recovery): re-route
                    # this statement without fencing
                    from galaxysql_tpu.utils.metrics import WORKER_FAILOVERS
                    from galaxysql_tpu.utils import events
                    WORKER_FAILOVERS.inc()
                    events.publish("worker_failover",
                                   f"scan {t.name}: rerouted off live "
                                   f"endpoint {addr[0]}:{addr[1]}",
                                   node=inst.node_id, table=t.name,
                                   worker=f"{addr[0]}:{addr[1]}",
                                   fenced=False)
                    self.ctx.trace.append(
                        f"failover {t.name}: rerouted off "
                        f"{addr[0]}:{addr[1]} (alive)")
                    continue
                raise
        raise errors.WorkerUnavailableError(
            f"remote table {t.name}: no serving endpoint ({last_err})")

    def _remote_batches_from(self, t, inst, addr, client
                             ) -> Iterator[ColumnBatch]:
        from galaxysql_tpu.chunk.batch import column_from_pylist
        from galaxysql_tpu.exec.operators import bucket_capacity
        storage_cols = [c for _, c in self.node.columns]
        # ship the BOUND FRAGMENT first (XPlan analog): table + pruned columns
        # + lane-domain SARGs + numeric point key; the worker executes it with
        # no parse/plan work.  Any error degrades to SQL text, exactly the
        # XPlanTemplate.java:86,132 fallback ladder.
        def lane_safe(v):
            return int(v) if float(v).is_integer() else float(v)
        # planned runtime filters ride the fragment: the build side's min/max
        # range as extra SARGs, small builds additionally as an IN-list — the
        # DN-side scan prunes before rows cross the process seam (the
        # reference's runtime-filter-into-DN-scan pushdown, SURVEY.md §5.1)
        rf_sargs, rf_in = self._rf_pushdown()
        frag = {"schema": t.schema, "table": t.name, "columns": storage_cols,
                "sargs": [[c, op, lane_safe(v)] for c, op, v in
                          list(getattr(self.node, "sargs", [])) + rf_sargs]}
        if rf_in:
            frag["rf_in"] = [[c, vals] for c, vals in rf_in]
        xid = self.ctx.remote_xids.get(addr)
        if xid is not None:
            frag["xid"] = xid  # read through the session's open worker branch
        pe = self.node.point_eq
        if pe is not None and not t.column(pe[0]).dtype.is_string and \
                isinstance(pe[1], (int, np.integer)):
            frag["point"] = [pe[0], int(pe[1])]
        dl = self.ctx.deadline
        try:
            names, rtypes, data, valid = client.exec_plan(frag, deadline=dl)
            self.ctx.trace.append(
                f"remote-plan {t.name} -> {addr[0]}:{addr[1]}")
        except (errors.QueryTimeoutError, errors.WorkerUnavailableError):
            # degrade ladder stops typed: a dead endpoint fails over (the
            # caller re-routes), a blown deadline kills the statement —
            # re-shipping as SQL text would help neither
            raise
        except errors.TddlError:
            sql = (f"SELECT {', '.join(storage_cols)} FROM "
                   f"{t.schema}.{t.name}")
            self.ctx.trace.append(
                f"remote-scan {t.name} -> {addr[0]}:{addr[1]}")
            # the degrade path keeps the branch xid: txn visibility must not
            # depend on which wire form served the scan
            names, rtypes, data, valid = client.execute(sql, t.schema, xid=xid,
                                                        deadline=dl)
        scaled = {nm for nm, ty in zip(names, rtypes)
                  if isinstance(ty, str) and ty.endswith("#scaled")}
        n = len(next(iter(data.values()))) if data else 0
        cols = {}
        for oid, cname in self.node.columns:
            cm = t.column(cname)
            arr = data[cname]
            v = valid.get(cname)
            if cname in scaled:
                # worker shipped the DECIMAL lane as scaled int64 — adopt it
                # directly (no float re-round; exact to the lane's 18 digits)
                cols[oid] = Column(arr.astype(cm.dtype.lane),
                                   None if v is None else v.astype(np.bool_),
                                   cm.dtype, None)
                continue
            vals = arr.tolist()
            if v is not None:
                vals = [x if ok else None for x, ok in zip(vals, v.tolist())]
            cols[oid] = column_from_pylist(vals, cm.dtype,
                                           t.dictionaries.get(cname.lower()))
        if not cols:
            return
        import jax.numpy as jnp
        b = ColumnBatch(cols, jnp.ones(n, dtype=jnp.bool_) if n else
                        jnp.zeros(0, dtype=jnp.bool_))
        yield b.pad_to(bucket_capacity(max(n, 1)))

    def _rf_pushdown(self):
        """(min/max sargs, in-lists) from published runtime filters — the
        lane-domain pushdown shared by remote fragments and archive SARGs."""
        rf = getattr(self.ctx, "rf", None)
        if rf is None or not getattr(self.node, "rf_targets", None):
            return [], []
        sargs, inlists = rf.scan_pushdown(self.node)
        return [[c, op, v] for c, op, v in sargs], inlists

    def _columnar_batches(self, t, view, storage_cols, rename):
        """Vectorized columnar-replica scan: pre-padded immutable stripes +
        one concatenated delta batch, zone-map-pruned by the same SARGs the
        parquet archive refutes with, MVCC-visible at the routed watermark."""
        from galaxysql_tpu.storage import columnar as _col
        mgr = getattr(self.ctx.archive_instance, "columnar", None)
        sargs = [tuple(s) for s in (getattr(self.node, "sargs", None) or [])]
        rf_sargs, _ = self._rf_pushdown()
        sargs += [tuple(s) for s in rf_sargs]
        pruned0 = view.replica.pruned_stripes
        self.ctx.trace.append(
            f"scan-columnar {t.name} watermark={view.watermark} "
            f"stripes={len(view.stripes)} delta={len(view.delta)}")
        for b in _col.scan_view(view, t, storage_cols, sargs, mgr):
            self.ctx.check_deadline()  # per-stripe drain boundary
            yield b.rename(rename)
        pruned = view.replica.pruned_stripes - pruned0
        if pruned:
            self.ctx.trace.append(
                f"scan-columnar {t.name} pruned_stripes={pruned}")

    def _archive_batches(self, t, storage_cols, rename, snap=None):
        """Cold rows from parquet archives (OSSTableScanExec analog)."""
        am = self.ctx.archive
        if am is None:
            return
        snap = self.ctx.snapshot_ts if snap is None else snap
        from galaxysql_tpu.exec.operators import bucket_capacity
        inst_key = f"{t.schema.lower()}.{t.name.lower()}"
        if not am.files_for(inst_key, snap):
            return
        # runtime-filter min/max ranges feed the same parquet SARG refutation
        # as WHERE-derived sargs, skipping whole files the build side refutes
        rf_sargs, _ = self._rf_pushdown()
        rf = getattr(self.ctx, "rf", None)
        cb = rf.note_file_pruned if rf is not None else None
        for b in am.scan_archive(self.ctx.archive_instance, t.schema, t.name,
                                 storage_cols, snap,
                                 sargs=getattr(self.node, "sargs", None),
                                 rf_sargs=[tuple(s) for s in rf_sargs],
                                 rf_pruned_cb=cb):
            self.ctx.trace.append(f"scan-archive {t.name} rows={b.capacity}")
            yield b.pad_to(bucket_capacity(max(b.capacity, 1))).rename(rename)


    def _fused_table_batch(self, t, store, cache, jnp, snap=None, txn_id=None):
        from galaxysql_tpu.exec.operators import bucket_capacity
        ts = self.ctx.snapshot_ts if snap is None else snap
        txn_id = self.ctx.txn_id if txn_id is None else txn_id
        total = sum(p.num_rows for p in store.partitions)
        if total == 0 or total > (1 << 27):
            return None  # empty: old per-partition loop yields nothing
        cap = bucket_capacity(total)

        def fused(name, parts, fill=0):
            def build():
                lane = np.full(cap, fill, dtype=parts[0].dtype)
                off = 0
                for arr in parts:
                    lane[off:off + arr.shape[0]] = arr
                    off += arr.shape[0]
                return lane
            # lazy: a cache hit must not pay the O(table) host concatenation
            return cache.get_lane_built(store, -1, name, t.version, cap, build)

        meta = _scan_meta(store, t.version)
        cols = {}
        for oid, cname in self.node.columns:
            cm = t.column(cname)
            data = fused(cname, [p.lanes[cname] for p in store.partitions])
            valid = None
            v_all = meta["valid_all"].get(cname)
            if v_all is None:
                v_all = all(bool(p.valid[cname].all())
                            for p in store.partitions)
                meta["valid_all"][cname] = v_all
            if not v_all:
                valid = fused(f"valid::{cname}",
                              [p.valid[cname] for p in store.partitions], False)
            cols[oid] = Column(data, valid, cm.dtype,
                               t.dictionaries.get(cname.lower()))
        # O(table) host reductions cached per (store, version) — a warm scan
        # must not re-reduce every timestamp lane per query
        all_current = meta["all_current"] and \
            (ts is None or meta["max_begin"] <= ts)
        pad_live = None
        if cap != total:
            # the pad mask is version-static: cache it beside the lanes
            pad_live = cache.get_lane_built(
                store, -1, "::padlive", t.version, cap,
                lambda: np.arange(cap) < total)
        if all_current:
            live = pad_live
        else:
            begin = fused("::begin_ts", [p.begin_ts for p in store.partitions])
            end = fused("::end_ts", [p.end_ts for p in store.partitions], -1)
            live = _device_visibility(begin, end, ts, txn_id)
            if pad_live is not None:
                live = live & pad_live
        return ColumnBatch(cols, live)


class ValuesSource(ops.Operator):
    def __init__(self, node: L.Values):
        self.node = node

    def batches(self) -> Iterator[ColumnBatch]:
        from galaxysql_tpu.chunk.batch import batch_from_pydict
        rows = self.node.rows
        if not self.node.schema:
            # SELECT without FROM: one anonymous row
            yield batch_from_pydict({"__one": [1] * max(len(rows), 1)},
                                    {"__one": dt.BIGINT})
            return
        data = {fid: [r[i] for r in rows] for i, (fid, _, _) in
                enumerate(self.node.schema)}
        schema = {fid: typ for fid, typ, _ in self.node.schema}
        dicts = {fid: d for fid, typ, d in self.node.schema if d is not None}
        yield batch_from_pydict(data, schema, dicts)


class StatsOp(ops.Operator):
    """EXPLAIN ANALYZE / profiling instrumentation: per-operator batches/rows/
    wall time (RuntimeStatistics analog).  Only wrapped when ctx.collect_stats
    is set — num_live() forces a device sync per batch, so the normal path
    never pays."""

    def __init__(self, inner: ops.Operator, node: L.RelNode, ctx: ExecContext):
        self.inner = inner
        self.node = node
        self.ctx = ctx

    def batches(self):
        import time as _t
        t0 = _t.perf_counter()
        rows = 0
        nb = 0
        for b in self.inner.batches():
            nb += 1
            rows += b.num_live()
            yield b
        self.ctx.op_stats.append(
            {"node_id": id(self.node), "operator": type(self.node).__name__,
             "batches": nb, "rows_out": rows,
             "wall_ms": round((_t.perf_counter() - t0) * 1000, 3)})


class SegmentStatsOp(ops.Operator):
    """Per-operator stats INSIDE a fused segment: drains the segment's stats
    sink (per-stage live counts per dispatch, from the stats program variant)
    and attributes stage i's rows back to chain node i.  Wall time is the
    whole segment's — stages share one program, so per-stage wall does not
    exist; each chain row carries the shared value, flagged `fused`.

    The sink's leading count is the segment INPUT; runtime-filter prelude
    stages (`rf_node` = the scan they mask) report rows pruned per filter to
    the execution's RuntimeFilterManager — the EXPLAIN ANALYZE
    `RuntimeFilter(col, kinds, pruned=…)` lines and the `rf_rows_pruned`
    counter."""

    def __init__(self, inner: ops.Operator, segment, nodes: List[L.RelNode],
                 ctx: ExecContext, rf_node: Optional[L.RelNode] = None):
        self.inner = inner
        self.segment = segment
        self.nodes = nodes
        self.ctx = ctx
        self.rf_node = rf_node
        segment.stats_sink = []

    def batches(self):
        yield from self.inner.batches()
        sink = self.segment.stats_sink
        if not sink:
            return
        totals = np.sum([c for c, _ in sink], axis=0)
        wall = round(sum(w for _, w in sink), 3)
        record_rf_stats(self.ctx, self.segment, self.rf_node, totals)
        off = 1 + self.segment.rf_stage_count  # input count + rf preludes
        for i, n in enumerate(self.nodes):
            self.ctx.op_stats.append(
                {"node_id": id(n), "operator": type(n).__name__,
                 "batches": len(sink), "rows_out": int(totals[off + i]),
                 "wall_ms": wall, "fused": True,
                 "segment": self.segment.chain})


def record_rf_stats(ctx, segment, rf_node, totals):
    """Attribute per-rf-stage pruned rows (stats-sink deltas) to the manager.
    totals[0] is the segment input count; rf stages are a prefix."""
    refs = getattr(segment, "rf_refs", None)
    if not refs:
        return
    mgr = getattr(ctx, "rf", None)
    if mgr is None:
        return
    for j, ref in enumerate(refs):
        pruned = int(totals[j]) - int(totals[j + 1])
        mgr.note_pruned(ref.target, pruned,
                        node_id=id(rf_node) if rf_node is not None else None)


class TraceOp(ops.Operator):
    """Span-tracing wrapper: one `operator` span per plan node, parented at
    BUILD time (the plan tree is the span tree), timed at DRAIN time.  While a
    batch is being pulled from the wrapped operator the context's cursor
    points at this span, so leaf recorders that fire inside the pull — fused
    segment dispatches, compile events, device-cache transfers, worker RPCs —
    attach under the operator doing the work.  Row counts are deliberately
    NOT collected here (that is profiling's job and costs a device sync);
    tracing measures only where wall time went."""

    def __init__(self, inner: ops.Operator, span, tc):
        self.inner = inner
        self.span = span
        self.tc = tc

    def batches(self):
        import time as _t
        from galaxysql_tpu.utils import tracing as _tr
        tc, sp = self.tc, self.span
        sp.start_us = _tr.now_us()
        t0 = _t.perf_counter()
        batches = 0
        it = self.inner.batches()
        while True:
            prev = tc.cursor
            tc.cursor = sp.span_id
            try:
                try:
                    b = next(it)
                except StopIteration:
                    break
            finally:
                tc.cursor = prev
            batches += 1
            # finalize-per-pull: a downstream LIMIT may drop the generator
            # without exhausting it, and the span must still carry real time
            sp.dur_us = round((_t.perf_counter() - t0) * 1e6, 1)
            sp.attrs["batches"] = batches
            yield b
        sp.dur_us = round((_t.perf_counter() - t0) * 1e6, 1)
        sp.attrs["batches"] = batches


def build_operator(node: L.RelNode, ctx: ExecContext) -> ops.Operator:
    from galaxysql_tpu.utils import tracing
    tc = tracing.current()
    if tc is None:
        op = _build_operator(node, ctx)
        if getattr(ctx, "collect_stats", False) and \
                not isinstance(op, SegmentStatsOp):
            return StatsOp(op, node, ctx)
        return op
    # traced build: mint this node's span under the parent operator's (the
    # recursion below threads the cursor through ctx), then wrap the drain
    parent = getattr(ctx, "_trace_parent", None)
    sp = tc.add(type(node).__name__, kind="operator",
                parent=tc.cursor if parent is None else parent)
    ctx._trace_parent = sp.span_id
    try:
        op = _build_operator(node, ctx)
    finally:
        ctx._trace_parent = parent
    if getattr(ctx, "collect_stats", False) and \
            not isinstance(op, SegmentStatsOp):
        op = StatsOp(op, node, ctx)
    return TraceOp(op, sp, tc)


def _fusing(ctx: ExecContext) -> bool:
    # kernel-prelude fusion (chains folded INTO the HashAgg partial / join
    # probe programs) has no per-stage observation point, so profiling keeps
    # those chains as standalone operators; standalone SEGMENT fusion stays on
    # under collect_stats — the stats program variant reports per-stage rows,
    # so EXPLAIN ANALYZE describes the fused shape users actually run
    return ctx.enable_fusion and not getattr(ctx, "collect_stats", False)


def _wrap_scan_rf(src: ops.Operator, node: L.Scan,
                  ctx: ExecContext) -> ops.Operator:
    """Scan-level runtime-filter fallback: when no downstream fused segment
    consumed the scan's planned filters (bare join-probe scans, fusion off,
    profiling), apply them here as an rf-only FusedSegment — still one
    on-device program per batch, value-independent cache keys."""
    rf = getattr(ctx, "rf", None)
    seg = rf.segment_for_scan(node) if rf is not None else None
    if seg is None:
        return src
    ctx.trace.append(f"rf-scan {node.table.name} filters={len(seg.stages)}")
    if getattr(ctx, "collect_stats", False):
        # inner StatsOp keeps the scan's own (pre-filter) actual rows; the
        # SegmentStatsOp wrapper reports per-filter pruned counts
        return SegmentStatsOp(
            fusion.FusedPipelineOp(StatsOp(src, node, ctx), seg, ctx),
            seg, [],
            ctx, rf_node=node)
    return fusion.FusedPipelineOp(src, seg, ctx)


def _build_operator(node: L.RelNode, ctx: ExecContext) -> ops.Operator:
    if isinstance(node, L.Scan):
        return _wrap_scan_rf(ScanSource(node, ctx), node, ctx)
    if isinstance(node, L.Values):
        return ValuesSource(node)
    if isinstance(node, (L.Filter, L.Project)):
        if ctx.enable_fusion:
            # profiling fuses even single-stage chains: in production those
            # fold INTO the downstream kernel (agg prelude / join probe), so
            # running them as an instrumented one-stage segment keeps the
            # ANALYZE shape honest (fused tag + per-stage rows) while the
            # kernel-prelude path is held off (no observation point there)
            collecting = getattr(ctx, "collect_stats", False)
            base, seg = fusion.segment_for(node,
                                           min_stages=1 if collecting else 2,
                                           rf=getattr(ctx, "rf", None))
            if seg is not None:
                ctx.trace.append(f"fuse-segment {seg.chain}")
                inner = fusion.FusedPipelineOp(build_operator(base, ctx), seg,
                                               ctx)
                if collecting:
                    return SegmentStatsOp(
                        inner, seg, fusion.chain_nodes(node), ctx,
                        rf_node=base if isinstance(base, L.Scan) else None)
                return inner
        if isinstance(node, L.Filter):
            return ops.FilterOp(build_operator(node.child, ctx), node.cond)
        return ops.ProjectOp(build_operator(node.child, ctx), node.exprs)
    if isinstance(node, L.Aggregate):
        est = estimate_rows(node)
        max_groups = 1 << max(int(est * 2).bit_length(), 10)
        max_groups = min(max_groups, 1 << 22)
        calls = [ops.AggCall(a.kind, a.arg, a.out_id) for a in node.aggs]
        child_node, prelude = node.child, None
        if _fusing(ctx):
            # the agg is itself a pipeline breaker: its feeding chain fuses
            # INTO the partial kernel (scan→filter→project→partial-agg, one
            # program), not into a separate segment in front of it — the
            # base scan's runtime filters ride along as rf prelude stages
            base, prelude = fusion.segment_for(node.child,
                                               rf=getattr(ctx, "rf", None))
            if prelude is not None:
                child_node = base
                ctx.trace.append(f"fuse-agg-prelude {prelude.chain}")
        agg = ops.HashAggOp(build_operator(child_node, ctx),
                            node.groups, calls, max_groups=max_groups,
                            spill_threshold=ctx.agg_spill_bytes,
                            prelude=prelude, mem_pool=ctx.mem_pool)
        # the aggregate is a pipeline breaker with a DETERMINISTIC, usually
        # tiny output: fragment-cache it (version-keyed, same rules as join
        # builds), so a warm repeated query replays grouped rows instead of
        # re-streaming the fact side.  Profiling runs bypass — EXPLAIN
        # ANALYZE must measure the real pipeline, not a cache replay.
        if not getattr(ctx, "collect_stats", False):
            from galaxysql_tpu.exec import fragment_cache as fc
            fkey = fc.fingerprint(node, ctx)
            if fkey is not None:
                return fc.CachedSubplanOp(agg, ctx.frag, fkey,
                                          trace=ctx.trace)
        return agg
    if isinstance(node, L.Window):
        return ops.WindowOp(build_operator(node.child, ctx), node.partitions,
                            node.orders, node.calls, out_schema=node.fields())
    if isinstance(node, L.Join):
        return _build_join(node, ctx)
    if isinstance(node, L.Sort):
        return ops.SortOp(build_operator(node.child, ctx), node.keys,
                          node.limit, node.offset,
                          spill_threshold=ctx.sort_spill_bytes,
                          mem_pool=ctx.mem_pool)
    if isinstance(node, L.Limit):
        return ops.LimitOp(build_operator(node.child, ctx), node.limit, node.offset)
    if isinstance(node, L.Union):
        children = [build_operator(c, ctx) for c in node.children]
        # align column ids across inputs: rename every child to the first child's ids
        first_ids = node.children[0].field_ids()
        target_dicts = {fid: d for fid, _t, d in node.children[0].fields()}

        class UnionOp(ops.Operator):
            def __init__(self, children, id_lists):
                self.children_ops = children
                self.id_lists = id_lists

            def batches(self):
                for op, ids in zip(self.children_ops, self.id_lists):
                    rename = dict(zip(ids, first_ids))
                    for b in op.batches():
                        yield self._align(b.rename(rename))

            def _align(self, b):
                """Translate string codes into the first child's dictionary —
                children from different tables encode against different dicts,
                and concatenating raw codes would silently decode wrong values."""
                from galaxysql_tpu.chunk.batch import dictionary_union_translation
                cols = {}
                for fid, c in b.columns.items():
                    tgt = target_dicts.get(fid)
                    if c.dictionary is None or tgt is None or c.dictionary is tgt:
                        cols[fid] = c
                        continue
                    trans = dictionary_union_translation(tgt, c.dictionary)
                    cols[fid] = Column(trans[np.asarray(c.data)], c.valid,
                                       c.dtype, tgt)
                return ColumnBatch(cols, b.live)

        u = UnionOp(children, [c.field_ids() for c in node.children])
        if node.all:
            return u
        return ops.DistinctOp(u, [(fid, ir.ColRef(fid, typ, d))
                                  for fid, typ, d in node.fields()])
    raise errors.NotSupportedError(f"no physical operator for {type(node).__name__}")


def annotate_explain(rel: L.RelNode, op_stats: List[dict],
                     rf=None, skew_stats=None) -> List[str]:
    """EXPLAIN ANALYZE tree rendering: the logical plan's explain lines with
    each node annotated with its measured rows/batches/wall time (matched by
    node identity).  Operators that executed inside a fused segment carry a
    `fused(<chain>)` tag — their wall time is the whole segment's program.

    `rf` (the execution's RuntimeFilterManager) adds one indented
    `RuntimeFilter(column, kinds, pruned=…)` line under each scan a planned
    runtime filter masked.  `skew_stats` (ExecContext.skew_stats) adds one
    `HotKeys(n, broadcast)` / `Salted(f)` line under each join/aggregate the
    skew-aware executor split.

    Rendering rides the existing `explain_lines` (plain EXPLAIN and ANALYZE
    must draw the same tree): `explain_lines` emits one line per node in
    pre-order, which is exactly `L.walk`'s order, so lines and nodes zip."""
    by_id: Dict[int, dict] = {}
    for st in op_stats:
        nid = st.get("node_id")
        if nid is None:
            continue
        # fused/cached entries win: they mark chain membership (or a fragment
        # cache hit) the plain StatsOp wrapper covering the same node can't see
        if nid not in by_id or st.get("fused") or st.get("cached"):
            by_id[nid] = st
    rf_by_node: Dict[int, List[dict]] = {}
    if rf is not None:
        for st in rf.stats.values():
            rf_by_node.setdefault(st.get("node_id"), []).append(st)
    lines: List[str] = []
    for line, n in zip(rel.explain_lines(), L.walk(rel)):
        st = by_id.get(id(n))
        if st is not None:
            tag = f" fused({st['segment']})" if st.get("fused") else ""
            if st.get("cached"):
                tag += " [cached build]"
            line += (f"  (actual rows={st['rows_out']} "
                     f"batches={st['batches']} wall={st['wall_ms']}ms{tag})")
        lines.append(line)
        indent = " " * (len(line) - len(line.lstrip()) + 2)
        for rst in rf_by_node.get(id(n), []):
            lines.append(f"{indent}RuntimeFilter({rst['column']}, "
                         f"{rst['kinds']}, pruned={rst['pruned']})")
        info = (skew_stats or {}).get(id(n))
        if info is not None:
            from galaxysql_tpu.exec import skew as _skew
            lines.append(f"{indent}{_skew.explain_line(info)}")
    return lines


def _probe_prelude(ctx: ExecContext, probe_node: L.RelNode):
    """(base node, filter-only FusedSegment | None) for an inner join's probe
    side: the WHERE chain above the probe scan fuses INTO the probe kernels
    (one program per batch instead of filter + probe).  Project stages change
    the column namespace the join gathers from, so only all-filter chains
    collapse here; anything else stays a segment in front of the join."""
    if not _fusing(ctx):
        return probe_node, None
    base, seg = fusion.segment_for(probe_node, filters_only=True)
    if seg is not None:
        ctx.trace.append(f"fuse-join-probe {seg.chain}")
    return base, seg


def _rf_publish_specs(node: L.Join, ctx: ExecContext, probe_side: str):
    """Planned runtime-filter producer specs ACTIVE for this execution
    (side-flip/deactivation logic shared with MPP: runtime_filter.specs_for)."""
    from galaxysql_tpu.exec.runtime_filter import specs_for
    rf = getattr(ctx, "rf", None)
    specs = specs_for(node, probe_side, rf)
    if not specs:
        return None, []
    ctx.trace.append(f"rf-publish join filters={len(specs)}")
    return rf, specs


def _frag_build_wiring(build_node: L.RelNode, ctx: ExecContext):
    """Fragment-cache wiring for a join build side: (fingerprint, cache,
    subplan-wrapper, hit-note callback).  The note lands the hit in the trace
    and — under EXPLAIN ANALYZE / profiling — as a `[cached build]` op stat
    on the build node, whose subtree never executed."""
    from galaxysql_tpu.exec import fragment_cache as fc
    fkey = fc.fingerprint(build_node, ctx)
    if fkey is None:
        return None, None, None

    def note(art, _node=build_node):
        ctx.trace.append(
            f"frag-cache build hit [{','.join(sorted(fkey.tables))}] "
            f"rows={art.rows}")
        if getattr(ctx, "collect_stats", False):
            ctx.op_stats.append(
                {"node_id": id(_node), "operator": type(_node).__name__,
                 "batches": 0, "rows_out": art.rows, "wall_ms": 0.0,
                 "cached": True})
    return fkey, ctx.frag, note


def _build_side_op(build_node: L.RelNode, ctx: ExecContext, fkey, cache):
    op = build_operator(build_node, ctx)
    # the subplan lane deliberately duplicates rows the join_build artifact
    # also holds (caps bound it): it is keyed by the subtree ALONE, so other
    # joins with different key/filter shapes — and executions after an
    # artifact eviction — still skip the subtree.  Profiling bypasses, same
    # stance as the aggregate replay: a subplan hit under EXPLAIN ANALYZE
    # would hide the build operators without any [cached build] mark.
    if fkey is not None and not getattr(ctx, "collect_stats", False):
        from galaxysql_tpu.exec import fragment_cache as fc
        op = fc.CachedSubplanOp(op, cache, fkey, trace=ctx.trace)
    return op


def _skew_watch(build_node: L.RelNode, build_keys, ctx: ExecContext):
    """Heavy-hitter runtime-refresh targets for a join build side: one
    (TableMeta, column, field id) per build key that is a bare scan column —
    the materialized build pass folds the key lane into the column's runtime
    sketch (meta/statistics.observe_build_keys), keeping skew detection fresh
    between ANALYZE runs at zero extra device syncs."""
    if not getattr(ctx, "skew_modes", None):
        return []
    from galaxysql_tpu.plan.rules import _rf_resolve_scan
    out = []
    for e in build_keys:
        if not isinstance(e, ir.ColRef):
            continue
        got = _rf_resolve_scan(build_node, e.name)
        if got is None:
            continue
        scan, out_id = got
        if getattr(scan.table, "remote", None) is not None:
            continue
        colname = dict(scan.columns).get(out_id)
        if colname is not None:
            out.append((scan.table, scan.table.column(colname).name, e.name))
    return out


def _build_join(node: L.Join, ctx: ExecContext) -> ops.Operator:
    if node.kind == "cross":
        left = build_operator(node.left, ctx)
        right = build_operator(node.right, ctx)
        bschema = {fid: (typ, d) for fid, typ, d in node.right.fields()}
        return ops.CrossJoinOp(right, left, scalar=getattr(node, "scalar", False),
                               build_schema=bschema)
    lkeys = [a for a, _ in node.equi]
    rkeys = [b for _, b in node.equi]
    bloom = not ctx.hints.get("no_bloom", False)
    if node.kind in ("left", "semi", "anti"):
        # probe side MUST be the preserved/output (left) side
        rf_mgr, rf_specs = _rf_publish_specs(node, ctx, "left") \
            if node.kind == "semi" else (None, [])
        right_schema = {fid: (typ, d) for fid, typ, d in node.right.fields()}
        fkey, cache, note = _frag_build_wiring(node.right, ctx)
        return ops.HashJoinOp(_build_side_op(node.right, ctx, fkey, cache),
                              build_operator(node.left, ctx),
                              rkeys, lkeys, node.kind,
                              residual=node.residual, build_schema=right_schema,
                              enable_bloom=bloom,
                              spill_threshold=ctx.join_spill_bytes,
                              rf_publish=rf_specs, rf_manager=rf_mgr,
                              frag_cache=cache, frag_key=fkey, frag_note=note,
                              skew_watch=_skew_watch(node.right, rkeys, ctx),
                              mem_pool=ctx.mem_pool)
    # inner: build the smaller estimated side
    l_est = estimate_rows(node.left)
    r_est = estimate_rows(node.right)
    if r_est <= l_est:
        build_node, probe_node = node.right, node.left
        build_keys, probe_keys = rkeys, lkeys
        probe_side = "left"
    else:
        build_node, probe_node = node.left, node.right
        build_keys, probe_keys = lkeys, rkeys
        probe_side = "right"
    rf_mgr, rf_specs = _rf_publish_specs(node, ctx, probe_side)
    build_schema = {fid: (typ, d) for fid, typ, d in build_node.fields()}
    probe_node, prelude = _probe_prelude(ctx, probe_node)
    fkey, cache, note = _frag_build_wiring(build_node, ctx)
    return ops.HashJoinOp(_build_side_op(build_node, ctx, fkey, cache),
                          build_operator(probe_node, ctx),
                          build_keys, probe_keys, "inner",
                          residual=node.residual, build_schema=build_schema,
                          enable_bloom=bloom,
                          spill_threshold=ctx.join_spill_bytes,
                          probe_prelude=prelude,
                          rf_publish=rf_specs, rf_manager=rf_mgr,
                          frag_cache=cache, frag_key=fkey, frag_note=note,
                          skew_watch=_skew_watch(build_node, build_keys, ctx),
                          mem_pool=ctx.mem_pool)
