"""Typed metrics registry: named counters and gauges with one SQL/HTTP surface.

Reference analog: SURVEY.md §5.5 — `MatrixStatistics` instance counters plus the
MPP coordinator's JSON stats resources.  The reference scatters counters across
ad-hoc fields; here every metric registers in one typed registry so
`information_schema.metrics`, `SHOW METRICS`, and the web console's Prometheus
`/metrics` endpoint all render the same set without per-counter wiring.

All operations are host-side integer/float updates under a registry lock —
nothing here may touch device state (the metrics layer must be free on the
query hot path).
"""

from __future__ import annotations

import re
import threading
from typing import Dict, Iterator, List, Tuple


class Counter:
    """Monotonic named counter (Prometheus `counter`)."""

    __slots__ = ("name", "help", "_value", "_lock")

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1):
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value

    def _set(self, v):
        # CounterMap compatibility (`counters[k] += 1` does get-then-set);
        # not part of the public counter API — counters stay monotonic there
        # because += only grows.
        with self._lock:
            self._value = v


class Gauge:
    """Settable instantaneous value (Prometheus `gauge`)."""

    __slots__ = ("name", "help", "_value", "_lock")

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v):
        with self._lock:
            self._value = v

    def inc(self, n=1):
        with self._lock:
            self._value += n

    def dec(self, n=1):
        with self._lock:
            self._value -= n

    @property
    def value(self):
        return self._value


_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _sanitize(name: str) -> str:
    return _NAME_RE.sub("_", name)


class MetricsRegistry:
    """get-or-create registry of typed metrics.

    A name registers as exactly one kind; asking for the same name with the
    other kind raises (a counter silently readable as a gauge would hide a
    wiring bug forever).
    """

    def __init__(self, namespace: str = "galaxysql"):
        self.namespace = _sanitize(namespace)
        self._metrics: "Dict[str, object]" = {}
        self._lock = threading.Lock()

    def _get_or_create(self, name: str, cls, help: str):
        name = _sanitize(name)
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(name, Counter, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(name, Gauge, help)

    def counter_map(self, prefix: str) -> "CounterMap":
        return CounterMap(self, prefix)

    def rows(self) -> List[Tuple[str, str, float, str]]:
        """(name, kind, value, help) per metric, name-sorted — the
        information_schema.metrics / SHOW METRICS row shape."""
        with self._lock:
            ms = sorted(self._metrics.items())
        return [(n, m.kind, m.value, m.help) for n, m in ms]

    def prometheus_text(self) -> str:
        """Prometheus text exposition format (one block per metric)."""
        out = []
        for name, kind, value, help in self.rows():
            full = f"{self.namespace}_{name}"
            if help:
                out.append(f"# HELP {full} {help}")
            out.append(f"# TYPE {full} {kind}")
            if isinstance(value, float) and not value.is_integer():
                out.append(f"{full} {value}")
            else:
                out.append(f"{full} {int(value)}")
        return "\n".join(out) + "\n"


class CounterMap:
    """dict-like adapter over registry counters (the `instance.counters`
    surface: `counters["mpp_queries"] += 1`, `dict(counters)`, `.items()`).
    Every entry is a real typed Counter named `<prefix>_<key>`, so ad-hoc
    engine counters surface through /metrics and information_schema.metrics
    with zero extra wiring."""

    def __init__(self, registry: MetricsRegistry, prefix: str):
        self._registry = registry
        self._prefix = _sanitize(prefix)

    def _counter(self, key: str) -> Counter:
        return self._registry.counter(f"{self._prefix}_{_sanitize(key)}")

    def __getitem__(self, key: str) -> int:
        return self._counter(key).value

    def __setitem__(self, key: str, value: int):
        # NOTE: `counters[k] += 1` decomposes into get-then-set and can lose
        # concurrent increments; hot counter bumps use inc() (atomic).
        self._counter(key)._set(value)

    def inc(self, key: str, n: int = 1):
        """Atomic increment (the locked Counter.inc) — use this on paths that
        can race, not `counters[k] += 1`."""
        self._counter(key).inc(n)

    def get(self, key: str, default: int = 0) -> int:
        name = f"{self._prefix}_{_sanitize(key)}"
        with self._registry._lock:
            m = self._registry._metrics.get(name)
        return m.value if m is not None else default

    def keys(self) -> List[str]:
        pre = self._prefix + "_"
        with self._registry._lock:
            names = list(self._registry._metrics)
        return [n[len(pre):] for n in sorted(names) if n.startswith(pre)]

    def items(self) -> List[Tuple[str, int]]:
        return [(k, self[k]) for k in self.keys()]

    def __iter__(self) -> Iterator[str]:
        return iter(self.keys())

    def __len__(self) -> int:
        return len(self.keys())

    def __contains__(self, key: str) -> bool:
        return key in self.keys()
