"""Typed metrics registry: named counters and gauges with one SQL/HTTP surface.

Reference analog: SURVEY.md §5.5 — `MatrixStatistics` instance counters plus the
MPP coordinator's JSON stats resources.  The reference scatters counters across
ad-hoc fields; here every metric registers in one typed registry so
`information_schema.metrics`, `SHOW METRICS`, and the web console's Prometheus
`/metrics` endpoint all render the same set without per-counter wiring.

All operations are host-side integer/float updates under a registry lock —
nothing here may touch device state (the metrics layer must be free on the
query hot path).
"""

from __future__ import annotations

import random
import re
import threading
from typing import Dict, Iterator, List, Tuple


class Counter:
    """Monotonic named counter (Prometheus `counter`)."""

    __slots__ = ("name", "help", "_value", "_lock")

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1):
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value

    def _set(self, v):
        # CounterMap compatibility (`counters[k] += 1` does get-then-set);
        # not part of the public counter API — counters stay monotonic there
        # because += only grows.
        with self._lock:
            self._value = v


class Gauge:
    """Settable instantaneous value (Prometheus `gauge`)."""

    __slots__ = ("name", "help", "_value", "_lock")

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v):
        with self._lock:
            self._value = v

    def inc(self, n=1):
        with self._lock:
            self._value += n

    def dec(self, n=1):
        with self._lock:
            self._value -= n

    @property
    def value(self):
        return self._value


class Histogram:
    """Quantile summary over a bounded reservoir (Prometheus `summary`).

    Algorithm R reservoir sampling: the first `reservoir` observations are
    kept verbatim, later ones replace a uniformly random slot with probability
    reservoir/count — every observation ever made has equal survival odds, so
    p50/p95/p99 stay unbiased without unbounded memory.  All host-side float
    work under the lock; nothing here may touch device state."""

    __slots__ = ("name", "help", "_buf", "_cap", "_count", "_sum", "_lock")

    kind = "histogram"
    QUANTILES = (0.5, 0.95, 0.99)

    def __init__(self, name: str, help: str = "", reservoir: int = 1024):
        self.name = name
        self.help = help
        self._buf: List[float] = []
        self._cap = reservoir
        self._count = 0
        self._sum = 0.0
        self._lock = threading.Lock()

    def _observe_locked(self, v: float):
        self._count += 1
        self._sum += v
        if len(self._buf) < self._cap:
            self._buf.append(v)
        else:
            j = random.randrange(self._count)
            if j < self._cap:
                self._buf[j] = v

    def observe(self, v: float):
        with self._lock:
            self._observe_locked(float(v))

    def reset(self):
        """Clear count/sum/reservoir — scopes quantiles to a measurement
        window (the serving bench resets per level so each level's group-size
        p50 isn't blended with warmup and earlier levels)."""
        with self._lock:
            self._buf = []
            self._count = 0
            self._sum = 0.0

    def observe_many(self, vals):
        """One lock acquisition for a whole batch of observations (the batch
        scheduler records per-member waits once per flush — at group sizes in
        the hundreds, per-observation locking would tax the flush path)."""
        with self._lock:
            for v in vals:
                self._observe_locked(float(v))

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def quantile(self, q: float) -> float:
        with self._lock:
            if not self._buf:
                return 0.0
            s = sorted(self._buf)
        idx = min(int(q * len(s)), len(s) - 1)
        return s[idx]

    def quantiles(self) -> Dict[float, float]:
        with self._lock:
            if not self._buf:
                return {q: 0.0 for q in self.QUANTILES}
            s = sorted(self._buf)
        return {q: s[min(int(q * len(s)), len(s) - 1)]
                for q in self.QUANTILES}

    @property
    def value(self) -> float:
        """Scalar view (p50) for generic metric listings."""
        return self.quantile(0.5)


# process-shared histograms: observed from code that has no Instance handle
# (fused-segment dispatches, worker RPC clients); every Instance adopts them
# into its registry so SHOW METRICS / /metrics export the quantiles.
SEGMENT_WALL_MS = Histogram(
    "segment_wall_ms", "fused-segment dispatch wall time (ms)")
RPC_RTT_MS = Histogram(
    "rpc_rtt_ms", "coordinator->worker RPC round-trip (ms)")
# batched TP serving (server/batch_scheduler.py): coalesced group sizes per
# vectorized flush and per-request collection-window wait
BATCH_GROUP_SIZE = Histogram(
    "batch_group_size", "coalesced point-query group size (requests/flush)")
BATCH_WAIT_MS = Histogram(
    "batch_wait_ms", "batched point-query collection wait (ms)")
# batched write path (server/dml_batch.py): coalesced DML group sizes per
# vectorized flush and per-statement collection wait
DML_GROUP_SIZE = Histogram(
    "dml_group_size", "coalesced point-DML group size (statements/flush)")
DML_WAIT_MS = Histogram(
    "dml_wait_ms", "batched DML collection wait (ms)")

# fault-tolerance plane (net/dn.py retry/breaker, SyncBus, deadline kills):
# process-shared like the histograms above — WorkerClient instances have no
# Instance handle; every Instance adopts these into its registry.
RPC_RETRIES = Counter(
    "rpc_retries", "worker RPC attempts retried after a transport failure")
RPC_FAILURES = Counter(
    "rpc_failures", "worker RPCs failed after exhausting the retry budget")
BREAKER_OPENS = Counter(
    "breaker_opens", "worker circuit breakers tripped open")
WORKER_FAILOVERS = Counter(
    "worker_failovers",
    "replica-read requests re-routed to another endpoint mid-statement")
SYNC_FAILURES = Counter(
    "sync_failures", "sync-bus broadcast deliveries that failed")
SYNC_HEALS = Counter(
    "sync_heals",
    "wholesale cache invalidations from a detected sync-epoch gap")
QUERY_TIMEOUTS = Counter(
    "query_timeouts", "queries killed by a MAX_EXECUTION_TIME deadline")
RETRY_BUDGET_EXHAUSTED = Counter(
    "retry_budget_exhausted",
    "worker RPCs failed fast because the per-endpoint retry token bucket "
    "was empty (anti-retry-storm backstop)")
# spill observability (exec/spill.py Spiller): promoted out of per-operator
# attributes so SHOW METRICS / Prometheus / statement-summary deltas see
# WHERE memory pressure went — process-shared, adopted per instance.
SPILL_BYTES = Counter(
    "spill_bytes_total", "bytes written to spill files (agg/join/sort)")
SPILL_FILES = Counter(
    "spill_files_total", "spill files/runs written")


_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _sanitize(name: str) -> str:
    return _NAME_RE.sub("_", name)


class MetricsRegistry:
    """get-or-create registry of typed metrics.

    A name registers as exactly one kind; asking for the same name with the
    other kind raises (a counter silently readable as a gauge would hide a
    wiring bug forever).
    """

    def __init__(self, namespace: str = "galaxysql"):
        self.namespace = _sanitize(namespace)
        self._metrics: "Dict[str, object]" = {}
        self._lock = threading.Lock()

    def _get_or_create(self, name: str, cls, help: str):
        name = _sanitize(name)
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(name, Counter, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(name, Gauge, help)

    def histogram(self, name: str, help: str = "") -> Histogram:
        return self._get_or_create(name, Histogram, help)

    def adopt(self, metric) -> None:
        """Register an EXISTING metric object (the process-shared histograms)
        under its own name; same kind-conflict rule as get-or-create."""
        name = _sanitize(metric.name)
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                self._metrics[name] = metric
            elif m is not metric and not isinstance(metric, type(m)):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}")

    def counter_map(self, prefix: str) -> "CounterMap":
        return CounterMap(self, prefix)

    def rows(self) -> List[Tuple[str, str, float, str]]:
        """(name, kind, value, help) per metric, name-sorted — the
        information_schema.metrics / SHOW METRICS row shape.  Histograms
        expand into one row per quantile plus _count/_sum so SQL surfaces see
        scalars."""
        with self._lock:
            ms = sorted(self._metrics.items())
        out: List[Tuple[str, str, float, str]] = []
        for n, m in ms:
            if m.kind == "histogram":
                qs = m.quantiles()
                for q, v in sorted(qs.items()):
                    out.append((f"{n}_p{int(q * 100)}", "histogram",
                                float(v), m.help))
                out.append((f"{n}_count", "histogram", float(m.count), m.help))
                out.append((f"{n}_sum", "histogram", float(m.sum), m.help))
            else:
                out.append((n, m.kind, m.value, m.help))
        return out

    def prometheus_text(self) -> str:
        """Prometheus text exposition format (one block per metric;
        histograms render as summaries with quantile labels)."""
        out = []
        with self._lock:
            ms = sorted(self._metrics.items())
        for name, m in ms:
            full = f"{self.namespace}_{name}"
            if m.help:
                out.append(f"# HELP {full} {m.help}")
            if m.kind == "histogram":
                out.append(f"# TYPE {full} summary")
                for q, v in sorted(m.quantiles().items()):
                    out.append(f'{full}{{quantile="{q}"}} {v}')
                out.append(f"{full}_sum {m.sum}")
                out.append(f"{full}_count {m.count}")
                continue
            out.append(f"# TYPE {full} {m.kind}")
            value = m.value
            if isinstance(value, float) and not value.is_integer():
                out.append(f"{full} {value}")
            else:
                out.append(f"{full} {int(value)}")
        return "\n".join(out) + "\n"


class CounterMap:
    """dict-like adapter over registry counters (the `instance.counters`
    surface: `counters["mpp_queries"] += 1`, `dict(counters)`, `.items()`).
    Every entry is a real typed Counter named `<prefix>_<key>`, so ad-hoc
    engine counters surface through /metrics and information_schema.metrics
    with zero extra wiring."""

    def __init__(self, registry: MetricsRegistry, prefix: str):
        self._registry = registry
        self._prefix = _sanitize(prefix)

    def _counter(self, key: str) -> Counter:
        return self._registry.counter(f"{self._prefix}_{_sanitize(key)}")

    def __getitem__(self, key: str) -> int:
        return self._counter(key).value

    def __setitem__(self, key: str, value: int):
        # NOTE: `counters[k] += 1` decomposes into get-then-set and can lose
        # concurrent increments; hot counter bumps use inc() (atomic).
        self._counter(key)._set(value)

    def inc(self, key: str, n: int = 1):
        """Atomic increment (the locked Counter.inc) — use this on paths that
        can race, not `counters[k] += 1`."""
        self._counter(key).inc(n)

    def get(self, key: str, default: int = 0) -> int:
        name = f"{self._prefix}_{_sanitize(key)}"
        with self._registry._lock:
            m = self._registry._metrics.get(name)
        return m.value if m is not None else default

    def keys(self) -> List[str]:
        pre = self._prefix + "_"
        with self._registry._lock:
            names = list(self._registry._metrics)
        return [n[len(pre):] for n in sorted(names) if n.startswith(pre)]

    def items(self) -> List[Tuple[str, int]]:
        return [(k, self[k]) for k in self.keys()]

    def __iter__(self) -> Iterator[str]:
        return iter(self.keys())

    def __len__(self) -> int:
        return len(self.keys())

    def __contains__(self, key: str) -> bool:
        return key in self.keys()
