"""Windowed metric history: the retention leg of the SLO plane.

``MetricHistory`` periodically snapshots every scalar the node already
exposes — the typed metrics registry (histograms expanded to
p50/p95/p99/count/sum), per-class admission stats, statement-summary
per-(schema, workload) rollups, and the host-side compile/dispatch
telemetry dicts — into a bounded, delta-encoded ring.  Everything read
is a host float that its owner already maintains under its own lock:
sampling never touches a device buffer, never forces a sync, and never
runs on the query hot path (the maintain loop and explicit
``Instance.slo_tick`` calls are the only drivers).

Storage is delta-encoded: one full ``_base`` dict holding the state
just before the oldest retained sample, plus a deque of
``(ts, {name: new_value})`` entries recording only the names that
changed at each tick.  Most counters are idle most of the time, so a
360-sample window costs far less than 360 full snapshots; trimming
folds the evicted delta into ``_base`` so replay stays exact.

Hatch duo (same convention as the statement summary / Pallas tiers):

* ``GALAXYSQL_METRIC_HISTORY=0`` env var — read once at import, kills
  sampling process-wide.
* ``ENABLE_METRIC_HISTORY`` config param — per-instance/session toggle.
"""

from __future__ import annotations

import os
import re
import threading
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

# escape hatch: read once at import time (hot-loop code must not pay a
# getenv per sample), flipped only for tests via monkeypatch
ENABLED = os.environ.get("GALAXYSQL_METRIC_HISTORY", "1") != "0"

_NAME_RE = re.compile(r"[^a-z0-9_]+")


def _sanitize(name: str) -> str:
    """Normalize arbitrary stat labels into metric-name idiom."""
    return _NAME_RE.sub("_", str(name).strip().lower()).strip("_")


class MetricHistory:
    """Bounded delta-encoded ring of node-wide metric snapshots."""

    def __init__(self, instance):
        self.instance = instance
        self._lock = threading.Lock()
        # state strictly before the oldest retained delta
        self._base: Dict[str, float] = {}
        # (ts, {name: value}) — only names whose value changed that tick
        self._deltas: Deque[Tuple[float, Dict[str, float]]] = deque()
        # state after the newest delta (== replayed tip), plus its stamp
        self._last: Dict[str, float] = {}
        self._last_at = 0.0
        # name -> "counter" | "gauge" | "histogram" | "derived"; counters
        # (and histogram _count rows) are what the anomaly detector rates
        self._kinds: Dict[str, str] = {}
        self._samples_total = instance.metrics.counter(
            "metric_history_samples", "history snapshots taken on this node")

    # -- hatches ---------------------------------------------------------------

    def on(self) -> bool:
        if not ENABLED:
            return False
        try:
            return bool(self.instance.config.get("ENABLE_METRIC_HISTORY"))
        except Exception:
            return True

    def interval_s(self) -> float:
        try:
            return float(self.instance.config.get("METRIC_HISTORY_INTERVAL_S"))
        except Exception:
            return 5.0

    def bound(self) -> int:
        try:
            return max(2, int(self.instance.config.get(
                "METRIC_HISTORY_SAMPLES")))
        except Exception:
            return 360

    # -- collection ------------------------------------------------------------

    def collect(self) -> Dict[str, float]:
        """One full host-side snapshot; never raises, never syncs.

        Each source is read under that source's own lock (registry,
        admission, statement summary) and merged into a plain dict —
        the history lock is NOT held here, so there is no lock-order
        edge between the sampler and the stores it reads.
        """
        vals: Dict[str, float] = {}
        kinds: Dict[str, str] = {}
        inst = self.instance
        try:
            for name, kind, value, _help in inst.metrics.rows():
                vals[name] = float(value)
                if kind == "histogram" and name.endswith("_count"):
                    kinds[name] = "counter"  # monotone — rateable
                else:
                    kinds[name] = kind
        except Exception:
            pass
        adm = getattr(inst, "admission", None)
        if adm is not None:
            try:
                for stat, value in adm.stats_rows():
                    n = f"admission_{_sanitize(stat)}"
                    vals[n] = float(value)
                    kinds[n] = "gauge"
            except Exception:
                pass
        col = getattr(inst, "columnar", None)
        if col is not None:
            try:
                # live freshness: the columnar_lag_ms gauge only moves on
                # tailer cycles, but lag keeps growing while the tailer is
                # wedged — recompute from the watermarks at sample time so
                # the SLO burn engine judges reality (ISSUE 20 satellite)
                lag = 0.0
                for rep in col.replicas.values():
                    if getattr(rep, "state", "") == "READY":
                        lag = max(lag, float(rep.lag_ms()))
                vals["columnar_lag_ms"] = round(max(lag, 0.0), 3)
                kinds["columnar_lag_ms"] = "gauge"
            except Exception:
                pass
        ss = getattr(inst, "stmt_summary", None)
        if ss is not None:
            try:
                for name, kind, value in ss.class_stats_rows():
                    n = f"stmt_{name}"
                    vals[n] = float(value)
                    kinds[n] = kind
            except Exception:
                pass
        try:
            from galaxysql_tpu.exec import operators as ops
            vals["compile_retraces"] = float(ops.COMPILE_STATS["retraces"])
            vals["compile_ms_total"] = float(ops.COMPILE_STATS["compile_ms"])
            vals["compile_cache_hits"] = float(ops.COMPILE_STATS["cache_hits"])
            vals["exec_dispatches"] = float(ops.DISPATCH_STATS["dispatches"])
            for n in ("compile_retraces", "compile_ms_total",
                      "compile_cache_hits", "exec_dispatches"):
                kinds[n] = "counter"
        except Exception:
            pass
        with self._lock:
            self._kinds.update(kinds)
        return vals

    # -- sampling --------------------------------------------------------------

    def sample(self, now: Optional[float] = None) -> Optional[Dict[str, float]]:
        """Take one snapshot unconditionally (tests and the ``health``
        sync action call this; the maintain loop goes through
        ``maybe_sample``).  Returns the full snapshot dict, or None
        when the hatch is off."""
        if not self.on():
            return None
        if now is None:
            import time
            now = time.time()
        vals = self.collect()
        with self._lock:
            delta = {k: v for k, v in vals.items()
                     if self._last.get(k) != v}
            self._deltas.append((float(now), delta))
            self._last = vals
            self._last_at = float(now)
            bound = self.bound()
            while len(self._deltas) > bound:
                _ts, evicted = self._deltas.popleft()
                self._base.update(evicted)
        self._samples_total.inc()
        return vals

    def maybe_sample(self,
                     now: Optional[float] = None) -> Optional[Dict[str, float]]:
        """Interval-gated sample — the maintain-loop entry point."""
        if not self.on():
            return None
        if now is None:
            import time
            now = time.time()
        with self._lock:
            due = (now - self._last_at) >= self.interval_s()
        if not due:
            return None
        return self.sample(now=now)

    # -- queries ---------------------------------------------------------------

    @property
    def samples_count(self) -> int:
        """Retained sample count — cheap enough for reply piggybacks."""
        with self._lock:
            return len(self._deltas)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(set(self._base) | set(self._last))

    def counter_names(self) -> List[str]:
        with self._lock:
            return sorted(n for n, k in self._kinds.items() if k == "counter")

    def latest(self, name: str) -> Optional[float]:
        with self._lock:
            return self._last.get(name, self._base.get(name))

    def series(self, name: str,
               samples: Optional[int] = None) -> List[Tuple[float, float]]:
        """Replay ``(ts, value)`` points for one metric, oldest first.

        A name absent from a delta means "unchanged since the previous
        point", so the replayed series always has one point per sample
        taken while the metric existed.
        """
        with self._lock:
            deltas = list(self._deltas)
            value = self._base.get(name)
        out: List[Tuple[float, float]] = []
        for ts, delta in deltas:
            if name in delta:
                value = delta[name]
            if value is not None:
                out.append((ts, value))
        if samples is not None and samples > 0:
            out = out[-samples:]
        return out

    def rate(self, name: str, samples: Optional[int] = None) -> float:
        """Average per-second rate over the (tail of the) series —
        meaningful for counters; 0.0 when underdetermined."""
        pts = self.series(name, samples=samples)
        if len(pts) < 2:
            return 0.0
        (t0, v0), (t1, v1) = pts[0], pts[-1]
        dt = t1 - t0
        if dt <= 0:
            return 0.0
        return (v1 - v0) / dt

    def derivative(self, name: str,
                   samples: Optional[int] = None) -> List[Tuple[float, float]]:
        """Per-step rates: ``(ts, dv/dt)`` for each adjacent pair."""
        pts = self.series(name, samples=samples)
        out: List[Tuple[float, float]] = []
        for (t0, v0), (t1, v1) in zip(pts, pts[1:]):
            dt = t1 - t0
            if dt > 0:
                out.append((t1, (v1 - v0) / dt))
        return out

    def mean(self, name: str, samples: Optional[int] = None) -> float:
        pts = self.series(name, samples=samples)
        if not pts:
            return 0.0
        return sum(v for _t, v in pts) / len(pts)

    def summary(self) -> Dict[str, float]:
        with self._lock:
            return {"samples": float(len(self._deltas)),
                    "names": float(len(self._last) or len(self._base)),
                    "last_at": self._last_at,
                    "interval_s": self.interval_s(),
                    "enabled": 1.0 if self.on() else 0.0}

    def rows(self, like: Optional[str] = None) -> List[Tuple]:
        """SHOW METRIC HISTORY / information_schema.metric_history rows:
        (name, points, latest, min, max, rate_per_s)."""
        import fnmatch
        pat = None
        if like:
            pat = like.replace("%", "*").replace("_", "?").lower()
        out: List[Tuple] = []
        for name in self.names():
            if pat is not None and not fnmatch.fnmatchcase(name.lower(), pat):
                continue
            pts = self.series(name)
            if not pts:
                continue
            values = [v for _t, v in pts]
            out.append((name, len(pts), values[-1], min(values), max(values),
                        round(self.rate(name), 6)))
        return out
