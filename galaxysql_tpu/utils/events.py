"""Instance event journal: a bounded ring of typed infrastructure events.

Reference analog: SURVEY.md §L2 manager surfaces — the reference scatters
"something happened" signals (DDL runs, breaker trips, failovers, cache heals)
across counters and log lines; this journal gives them one typed home so
`SHOW EVENTS`, `information_schema.events`, the web console, and Prometheus
all render the same stream.  The plan-regression sentinel
(meta/statement_summary.py) publishes here too.

Process-shared like SLOW_LOG and the fault-tolerance counters: most publishers
(WorkerClient breakers, skew activation checks, remote-scan failover) have no
Instance handle.  Each event carries the publishing node id when known.

Everything is host-side appends under one lock — nothing here may touch
device state (publishers sit on query hot paths)."""

from __future__ import annotations

import collections
import dataclasses
import itertools
import threading
import time
from typing import Any, Deque, Dict, List, Optional

# Known kinds (open set — publishers may mint new ones; these are the ones
# wired today).  severity defaults: warn for failure-shaped kinds, info else.
KINDS = (
    "ddl", "breaker_open", "breaker_close", "worker_failover",
    "sync_failure", "sync_heal", "skew_activate", "skew_deactivate",
    "batch_fallback", "plan_regression",
    # self-heal loop (plan/spm.py quarantine machine, driven by the
    # statement-summary sentinel): quarantine opened with a rollback pin /
    # targeted statistics repair, probation verdicts
    "plan_rollback", "stats_repair", "plan_promoted", "plan_heal_failed",
    # resource-governance plane (server/admission.py, utils/ccl.py,
    # net/dn.py retry budgets): overload sheds, CCL rejects/queue-fulls,
    # memory-pressure tier transitions, exhausted retry budgets
    "admission_reject", "ccl_reject", "mem_pressure",
    "retry_budget_exhausted",
    # SLO plane (server/slo.py): burn-rate transitions over the metric
    # history + robust-EWMA counter-rate anomalies (retrace storms,
    # breaker flaps, shed spikes) — detection only, never fails a query
    "slo_burn", "slo_recovered", "metric_anomaly",
    # serving tier (server/router.py): peer coordinators joining/leaving the
    # front router's ring — a leave also fires when failover evicts a dead
    # peer mid-statement
    "coordinator_joined", "coordinator_left",
)

_WARN_KINDS = frozenset({
    "breaker_open", "worker_failover", "sync_failure", "batch_fallback",
    "plan_regression", "plan_rollback", "plan_heal_failed",
    "admission_reject", "ccl_reject", "retry_budget_exhausted",
    "slo_burn", "metric_anomaly", "coordinator_left",
})


@dataclasses.dataclass
class InstanceEvent:
    seq: int
    at: float                  # wall-clock seconds
    kind: str
    severity: str              # info | warn
    node: str                  # publishing node id ("" when unknown)
    detail: str                # one human line
    attrs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # correlation keys (ISSUE 20): link this event to a retained trace
    # (utils/tracing.TraceStore) and/or a statement-summary digest so SHOW
    # EVENTS rows and incident bundles jump straight to their evidence.
    # Lifted out of **attrs by publish(); 0/"" = uncorrelated.
    trace_id: int = 0
    digest: str = ""


class EventJournal:
    """Bounded ring of InstanceEvents + lifetime per-kind counters.

    The counters outlive ring eviction (Prometheus sees totals, the ring shows
    the recent tail) — same split as SLOW_LOG vs slow_queries."""

    def __init__(self, capacity: int = 512):
        self._ring: Deque[InstanceEvent] = collections.deque(maxlen=capacity)
        self._counts: Dict[str, int] = {}
        self._seq = itertools.count(1)
        self._lock = threading.Lock()
        self._deduped: set = set()

    def publish(self, kind: str, detail: str = "", severity: str = "",
                node: str = "", dedupe: Optional[str] = None,
                **attrs) -> InstanceEvent:
        """Append an event.  `dedupe`: for per-execution publishers (skew
        activation fires on EVERY hybrid join) — the kind counter always
        bumps, but only the FIRST occurrence of a dedupe key lands in the
        ring, so a steady hot workload cannot evict the rare breaker/
        failover/regression events the journal exists to retain."""
        trace_id = attrs.pop("trace_id", 0)
        digest = attrs.pop("digest", "")
        try:
            trace_id = int(trace_id or 0)
        except (TypeError, ValueError):
            trace_id = 0
        ev = InstanceEvent(next(self._seq), time.time(), kind,
                           severity or ("warn" if kind in _WARN_KINDS
                                        else "info"),
                           node, detail[:512], attrs,
                           trace_id=trace_id, digest=str(digest or ""))
        with self._lock:
            self._counts[kind] = self._counts.get(kind, 0) + 1
            if dedupe is not None:
                if dedupe in self._deduped:
                    return ev
                if len(self._deduped) > 4096:
                    self._deduped.clear()  # epoch reset, bounded
                self._deduped.add(dedupe)
            self._ring.append(ev)
        return ev

    def entries(self, kind: Optional[str] = None,
                severity: Optional[str] = None,
                kind_like: Optional[str] = None) -> List[InstanceEvent]:
        """Recent tail, optionally filtered: exact `kind`, exact
        `severity` (info|warn|critical), and/or `kind_like` — a SQL LIKE
        pattern over the kind (SHOW EVENTS ... LIKE 'slo%' triage)."""
        with self._lock:
            evs = list(self._ring)
        if kind:
            evs = [e for e in evs if e.kind == kind]
        if severity:
            evs = [e for e in evs if e.severity == severity.lower()]
        if kind_like:
            import fnmatch
            pat = kind_like.lower().replace("%", "*").replace("_", "?")
            evs = [e for e in evs if fnmatch.fnmatchcase(e.kind, pat)]
        return evs

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def clear(self):
        with self._lock:
            self._ring.clear()
            self._counts.clear()
            self._deduped.clear()


EVENTS = EventJournal()


def publish(kind: str, detail: str = "", **kw) -> InstanceEvent:
    """Module-level convenience over the process journal."""
    return EVENTS.publish(kind, detail, **kw)
