"""User-level named locks: GET_LOCK / RELEASE_LOCK / IS_FREE_LOCK / IS_USED_LOCK.

Reference analog: `polardbx-common/.../common/lock/LockingFunctionManager.java` —
cross-session advisory locks with MySQL semantics: re-entrant for the owning
session, blocking acquire with timeout, auto-released when the session closes.
The reference persists them in the metadb so they span CNs; this engine's
single-process collapse makes the instance-scoped table the same thing.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional


class _Lock:
    __slots__ = ("owner", "count", "cond")

    def __init__(self):
        self.owner: Optional[int] = None
        self.count = 0
        self.cond = threading.Condition()


class LockingFunctionManager:
    def __init__(self):
        self._locks: Dict[str, _Lock] = {}
        self._mu = threading.Lock()

    def _lock(self, name: str) -> _Lock:
        with self._mu:
            l = self._locks.get(name)
            if l is None:
                l = _Lock()
                self._locks[name] = l
            return l

    def get_lock(self, name: str, timeout: float, conn_id: int) -> int:
        """1 = acquired, 0 = timeout (MySQL GET_LOCK).  Re-entrant per session."""
        l = self._lock(name)
        with l.cond:
            if l.owner == conn_id:
                l.count += 1
                return 1
            ok = l.cond.wait_for(lambda: l.owner is None,
                                 timeout if timeout >= 0 else None)
            if not ok:
                return 0
            l.owner = conn_id
            l.count = 1
            return 1

    def release_lock(self, name: str, conn_id: int) -> Optional[int]:
        """1 = released, 0 = held by another session, NULL = not held at all."""
        with self._mu:
            l = self._locks.get(name)
        if l is None:
            return None
        with l.cond:
            if l.owner is None:
                return None
            if l.owner != conn_id:
                return 0
            l.count -= 1
            if l.count == 0:
                l.owner = None
                l.cond.notify_all()
            return 1

    def is_free_lock(self, name: str) -> int:
        with self._mu:
            l = self._locks.get(name)
        if l is None:
            return 1
        with l.cond:
            return 1 if l.owner is None else 0

    def is_used_lock(self, name: str) -> Optional[int]:
        """Owning connection id, or NULL when free (MySQL IS_USED_LOCK)."""
        with self._mu:
            l = self._locks.get(name)
        if l is None:
            return None
        with l.cond:
            return l.owner

    def release_all(self, conn_id: int):
        """Session close: drop every lock the connection still holds."""
        with self._mu:
            locks = list(self._locks.values())
        for l in locks:
            with l.cond:
                if l.owner == conn_id:
                    l.owner = None
                    l.count = 0
                    l.cond.notify_all()
