"""Runtime lock-order witness (lockdep): catches POTENTIAL deadlocks.

Reference analog: the Linux kernel's lockdep validator — instead of waiting for
an interleaving that actually deadlocks, record every held->acquired edge
between lock CLASSES per thread and fail loudly the moment the acquisition
graph grows a cycle.  A test run that merely *touches* both orders of a pair
of locks proves the inversion, even if the threads never actually collide —
which is exactly what the chaos/dml/batch smoke suites do all day.

Disarmed (the default), `named_lock()` returns a plain `threading.Lock`/
`RLock` — zero wrapper, zero overhead, nothing on the hot path.  Armed via
`GALAXYSQL_LOCKDEP=1` in the environment (read at import) or `enable()`
(affects locks created afterwards — tests call it before building their
Instance), every named lock is wrapped in a `_DepLock` that reports each
acquisition to the process-wide `WITNESS` before blocking on the real lock.

Lock classes wired today (the canonical order, outermost first):

    append_lock  -> partition -> metadb
    instance     (coarse instance/DDL lock; unordered vs the chain above
                  until an edge proves otherwise)

The witness is ORDER-AGNOSTIC: it learns edges from execution and only fails
on a cycle, so a new subsystem's locks join the proof without registration.
Violations raise `LockOrderViolation` (an AssertionError: this is test
machinery, not a typed wire error) and are also recorded in
`WITNESS.violations` for harnesses that assert after the fact.
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Dict, List, Optional, Set, Tuple

__all__ = [
    "LockOrderViolation", "named_lock", "enabled", "enable", "disable",
    "WITNESS",
]


class LockOrderViolation(AssertionError):
    """A lock acquisition completed a cycle in the held->acquired graph
    (or two locks of the same unordered class were held together)."""


_enabled = os.environ.get("GALAXYSQL_LOCKDEP", "") not in ("", "0", "false")


def enabled() -> bool:
    return _enabled


def enable():
    """Arm lockdep for locks created from now on (tests: call before
    building the Instance under test)."""
    global _enabled
    _enabled = True


def disable():
    global _enabled
    _enabled = False


class _Held(threading.local):
    def __init__(self):
        self.stack: List["_DepLock"] = []


class Witness:
    """Process-wide acquisition-order graph over lock class names."""

    def __init__(self):
        self._graph: Dict[str, Set[str]] = {}
        # (a, b) -> one-line provenance of the first time a->b was seen
        self._edges: Dict[Tuple[str, str], str] = {}
        self._lock = threading.Lock()
        self._held = _Held()
        self.violations: List[str] = []

    # -- bookkeeping ---------------------------------------------------------

    def reset(self):
        with self._lock:
            self._graph.clear()
            self._edges.clear()
            self.violations.clear()

    def edges(self) -> Dict[Tuple[str, str], str]:
        with self._lock:
            return dict(self._edges)

    def assert_clean(self):
        if self.violations:
            raise LockOrderViolation("; ".join(self.violations))

    # -- the check -----------------------------------------------------------

    def _path(self, src: str, dst: str) -> Optional[List[str]]:
        """DFS for src ->* dst in the edge graph (caller holds self._lock)."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._graph.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def _violate(self, msg: str):
        site = traceback.extract_stack(limit=8)
        # skip lockdep's own frames when naming the acquisition site
        frames = [f for f in site if "lockdep" not in (f.filename or "")]
        where = f" at {frames[-1].filename}:{frames[-1].lineno}" if frames else ""
        full = msg + where
        self.violations.append(full)
        raise LockOrderViolation(full)

    def on_acquire(self, lk: "_DepLock"):
        """Called BEFORE the real acquire: the failing thread does not end up
        holding the inverted lock."""
        held = self._held.stack
        if any(h is lk for h in held):
            return  # re-entrant on the same instance: no new edge
        for h in held:
            if h.dep_name == lk.dep_name:
                self._violate(
                    f"lockdep: two '{lk.dep_name}' locks held by one thread "
                    f"(no intra-class order is declared)")
        with self._lock:
            for h in held:
                a, b = h.dep_name, lk.dep_name
                if b in self._graph.get(a, ()):
                    continue  # known-good edge
                cycle = self._path(b, a)
                if cycle is not None:
                    chain = " -> ".join(cycle + [b])
                    known = self._edges.get((cycle[0], cycle[1]), "")
                    self._violate(
                        f"lockdep: acquiring '{b}' while holding '{a}' "
                        f"inverts the established order ({chain}"
                        f"{'; first seen ' + known if known else ''})")
                self._graph.setdefault(a, set()).add(b)
                caller = traceback.extract_stack(limit=6)
                frames = [f for f in caller
                          if "lockdep" not in (f.filename or "")]
                self._edges[(a, b)] = (
                    f"{frames[-1].filename}:{frames[-1].lineno}"
                    if frames else "?")

    def did_acquire(self, lk: "_DepLock"):
        self._held.stack.append(lk)

    def did_release(self, lk: "_DepLock"):
        stack = self._held.stack
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is lk:
                del stack[i]
                return


WITNESS = Witness()


class _DepLock:
    """Thin lock wrapper reporting acquisitions to the witness.

    Supports the `with` protocol plus explicit acquire/release (timeouts
    included) so it drops in for every named-lock use in the engine."""

    __slots__ = ("dep_name", "_real")

    def __init__(self, name: str, reentrant: bool = True):
        self.dep_name = name
        self._real = threading.RLock() if reentrant else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        WITNESS.on_acquire(self)
        ok = self._real.acquire(blocking, timeout)
        if ok:
            WITNESS.did_acquire(self)
        return ok

    def release(self):
        self._real.release()
        WITNESS.did_release(self)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<DepLock {self.dep_name}>"


def named_lock(name: str, reentrant: bool = True):
    """The one constructor for the engine's named locks.

    Disarmed (default): a plain threading primitive — identical hot-path cost
    to before lockdep existed.  Armed: a witness-wrapped lock whose every
    acquisition extends the order proof."""
    if not _enabled:
        return threading.RLock() if reentrant else threading.Lock()
    return _DepLock(name, reentrant)
