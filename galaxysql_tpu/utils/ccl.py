"""CCL: SQL concurrency control (admission, queuing, throttling).

Reference analog: `optimizer/ccl` (SURVEY.md §2.5) — rule-matched query queuing with
wait queues and timeouts, integrated at the top of query execution the way
ServerConnection reschedules (`Reschedulable`).  Rules match on keyword substring
and/or user; a matched query must win a slot or wait (bounded queue + timeout).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional

from galaxysql_tpu.utils import errors


@dataclasses.dataclass
class CclRule:
    name: str
    max_concurrency: int
    keyword: Optional[str] = None        # match: substring of the SQL (ci)
    user: Optional[str] = None           # match: session user
    wait_queue_size: int = 64
    wait_timeout_ms: int = 10_000

    def matches(self, user: str, sql: str) -> bool:
        if self.user and self.user != user:
            return False
        if self.keyword and self.keyword.lower() not in sql.lower():
            return False
        return True


class _RuleState:
    def __init__(self, rule: CclRule):
        self.rule = rule
        self.sem = threading.BoundedSemaphore(rule.max_concurrency)
        self.waiting = 0
        self.running = 0
        self.total_matched = 0
        self.total_rejected = 0
        self.lock = threading.Lock()


class _Admission:
    """Handle returned by admit(); release() frees the slot."""

    def __init__(self, state: Optional[_RuleState]):
        self._state = state
        self._released = False

    def release(self):
        if self._state is not None and not self._released:
            self._released = True
            with self._state.lock:
                self._state.running -= 1
            self._state.sem.release()


_NO_ADMISSION = _Admission(None)


class CclManager:
    def __init__(self):
        self._rules: Dict[str, _RuleState] = {}
        self._lock = threading.Lock()

    def add_rule(self, rule: CclRule):
        with self._lock:
            self._rules[rule.name.lower()] = _RuleState(rule)

    def drop_rule(self, name: str) -> bool:
        with self._lock:
            return self._rules.pop(name.lower(), None) is not None

    def rules(self) -> List[_RuleState]:
        with self._lock:
            return list(self._rules.values())

    def clear(self):
        with self._lock:
            self._rules.clear()

    def admit(self, session, sql: str) -> _Admission:
        """Block (bounded) until the query may run; raise CclRejectError on overflow
        or timeout.  Returns a handle whose release() must be called when done."""
        if not self._rules:
            # rule-free fast path: no lock on the per-query hot path — the
            # batched TP serving loop calls admit() at millions/sec and a
            # contended lock here would serialize the whole admission plane
            # (dict truthiness is a single atomic read; a rule added
            # concurrently applies from the next statement on)
            return _NO_ADMISSION
        with self._lock:
            states = list(self._rules.values())
        for st in states:
            if not st.rule.matches(getattr(session, "user", "root"), sql):
                continue
            with st.lock:
                st.total_matched += 1
            if st.sem.acquire(blocking=False):
                with st.lock:
                    st.running += 1
                return _Admission(st)
            # slot busy: join the bounded wait queue
            with st.lock:
                if st.waiting >= st.rule.wait_queue_size:
                    st.total_rejected += 1
                    self._publish_reject(st, "queue_full")
                    raise errors.CclRejectError(
                        f"CCL rule '{st.rule.name}': wait queue full")
                st.waiting += 1
            ok = st.sem.acquire(timeout=st.rule.wait_timeout_ms / 1000.0)
            with st.lock:
                st.waiting -= 1
                if not ok:
                    st.total_rejected += 1
                else:
                    st.running += 1
            if not ok:
                self._publish_reject(st, "wait_timeout")
                raise errors.CclRejectError(
                    f"CCL rule '{st.rule.name}': wait timeout")
            return _Admission(st)
        return _NO_ADMISSION

    @staticmethod
    def _publish_reject(st: _RuleState, reason: str):
        """CCL rejects land in the typed event journal (deduped per
        rule x reason so a flood cannot evict rarer events)."""
        from galaxysql_tpu.utils import events
        events.publish("ccl_reject",
                       f"CCL rule '{st.rule.name}' rejected a query "
                       f"({reason})",
                       dedupe=f"ccl-{st.rule.name}-{reason}",
                       rule=st.rule.name, reason=reason)


GLOBAL_CCL = CclManager()
