"""Error taxonomy with MySQL error codes.

Reference analog: `polardbx-common/.../exception/code` (SURVEY.md §2.8).  Frontend-visible
errors carry (mysql_errno, sqlstate) so the wire layer can emit proper ERR packets.
"""

from __future__ import annotations


class TddlError(Exception):
    """Base framework error (named after the reference's TddlRuntimeException lineage)."""

    errno = 1105          # ER_UNKNOWN_ERROR
    sqlstate = "HY000"

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message


class SqlSyntaxError(TddlError):
    errno = 1064          # ER_PARSE_ERROR
    sqlstate = "42000"

    def __init__(self, message: str, sql: str = "", pos: int = -1):
        if pos >= 0:
            line = sql.count("\n", 0, pos) + 1
            near = sql[pos:pos + 32]
            message = f"{message} near '{near}' at line {line}"
        super().__init__(message)
        self.sql = sql
        self.pos = pos


class UnknownDatabaseError(TddlError):
    errno = 1049
    sqlstate = "42000"


class UnknownTableError(TddlError):
    errno = 1146
    sqlstate = "42S02"


class UnknownColumnError(TddlError):
    errno = 1054
    sqlstate = "42S22"


class TableExistsError(TddlError):
    errno = 1050
    sqlstate = "42S01"


class AmbiguousColumnError(TddlError):
    errno = 1052
    sqlstate = "23000"


class NotSupportedError(TddlError):
    errno = 1235          # ER_NOT_SUPPORTED_YET
    sqlstate = "42000"


class DuplicateKeyError(TddlError):
    errno = 1062
    sqlstate = "23000"


class TransactionError(TddlError):
    errno = 1205
    sqlstate = "HY000"


class DeadlockError(TddlError):
    errno = 1213
    sqlstate = "40001"


class AccessDeniedError(TddlError):
    errno = 1045
    sqlstate = "28000"


class CclRejectError(TddlError):
    """Query rejected/queued-timeout by concurrency control (CCL analog)."""
    errno = 3168
    sqlstate = "HY000"


class ServerOverloadError(TddlError):
    """Admission control shed this query: the server is saturated (per-class
    concurrency limit + full wait queue, CRITICAL memory pressure, or a
    deadline that cannot cover the digest's predicted service time).

    Carries `retry_after_ms` — the client-visible backoff suggestion — so a
    well-behaved driver retries later instead of amplifying the overload.
    Typed (never a hang, never a raw queue error): the overload harness
    asserts every refusal under flood is this class or CclRejectError."""
    errno = 9003
    sqlstate = "HY000"

    def __init__(self, message: str, retry_after_ms: int = 100):
        super().__init__(message)
        self.retry_after_ms = int(retry_after_ms)


class QueryTimeoutError(TddlError):
    """Query exceeded its MAX_EXECUTION_TIME deadline (ER_QUERY_TIMEOUT).

    Raised at operator drain / fused-segment / MPP-stage boundaries and by
    workers that receive a fragment whose propagated deadline already passed —
    a deadline-killed query dies TYPED everywhere, never as a hang.

    `sent` mirrors WorkerUnavailableError: False means the deadline expired
    BEFORE any bytes hit the wire (provably nothing applied remotely); True
    (default) means a remote side may have executed work."""
    errno = 3024
    sqlstate = "HY000"
    sent = True

    def __init__(self, message: str, sent: bool = True):
        super().__init__(message)
        self.sent = sent


class WorkerUnavailableError(TddlError):
    """A worker endpoint is unreachable: retry budget exhausted or the
    circuit breaker is open (fast-fail).  Callers with an alternate endpoint
    (replica reads) fail over; callers without one surface this typed.

    `sent` tells write callers whether the request may have REACHED the
    worker: False means nothing ever hit the wire (breaker fast-fail,
    connect refused) — the outcome is provably "nothing applied" and an
    explicit transaction can survive with statement-scoped semantics; True
    (the conservative default) means the outcome is ambiguous."""
    errno = 9002
    sqlstate = "HY000"
    sent = True

    def __init__(self, message: str, sent: bool = True):
        super().__init__(message)
        self.sent = sent


class CoordinatorUnavailableError(TddlError):
    """A peer coordinator in the serving tier is unreachable (router
    transport failure, fence, or a dead process found mid-statement).

    Sticky (session-pinned) statements surface this typed EXACTLY ONCE —
    the pinned peer's session state (txn, temp tables, session vars) died
    with it and cannot be transparently replayed; the session then unpins
    and the next statement re-routes.  Stateless statements never see it:
    the router fails over within the statement."""
    errno = 9004
    sqlstate = "HY000"


class ProtocolError(TddlError):
    """Corrupt/overlong RPC frame on the CN<->worker wire (ER_NET_READ_ERROR).

    Raised instead of trusting an attacker-or-corruption-controlled length
    prefix: the framing layer caps header/name/array sizes and kills the
    connection rather than allocating arbitrary memory."""
    errno = 1158
    sqlstate = "08S01"


def span_attrs(exc: BaseException) -> dict:
    """Error attributes for span tracing: the (errno, sqlstate) taxonomy above
    rides error spans so SHOW TRACE / the Chrome-trace export explain a failed
    query the same way the wire's ERR packet would."""
    return {
        "exception": type(exc).__name__,
        "errno": int(getattr(exc, "errno", 1105) or 1105),
        "sqlstate": str(getattr(exc, "sqlstate", "HY000") or "HY000"),
        "message": str(exc)[:256],
    }
