"""Fail-point injection framework.

Reference analog: `executor/utils/failpoint/FailPoint.java:63-111` (SURVEY.md §4) —
no-op unless a key is armed (there via session vars `set @FP_X=...`); used by DDL
crash-recovery tests to kill execution between tasks.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

FP_RANDOM_CRASH = "FP_RANDOM_CRASH"
FP_BEFORE_DDL_TASK = "FP_BEFORE_DDL_TASK"
FP_AFTER_DDL_TASK = "FP_AFTER_DDL_TASK"
FP_BEFORE_COMMIT = "FP_BEFORE_COMMIT"
FP_BACKFILL_PAUSE = "FP_BACKFILL_PAUSE"
# armed with a key VALUE: the batch scheduler fails exactly that key's
# sessions inside a flush (error-isolation testing, server/batch_scheduler.py)
FP_BATCH_POISON_KEY = "FP_BATCH_POISON_KEY"


class FailPointError(RuntimeError):
    """Raised by an armed fail point (simulated crash)."""


class _FailPoints:
    def __init__(self):
        self._armed: Dict[str, Any] = {}
        self._hits: Dict[str, int] = {}
        self._lock = threading.Lock()

    def arm(self, key: str, value: Any = True):
        with self._lock:
            self._armed[key] = value
            self._hits[key] = 0

    def disarm(self, key: str):
        with self._lock:
            self._armed.pop(key, None)

    def clear(self):
        with self._lock:
            self._armed.clear()
            self._hits.clear()

    def value(self, key: str) -> Optional[Any]:
        with self._lock:
            return self._armed.get(key)

    def inject(self, key: str, detail: str = ""):
        """Raise FailPointError if `key` is armed.  Armed value semantics:
        True -> fire always; int n -> fire on the n-th hit (1-based)."""
        with self._lock:
            v = self._armed.get(key)
            if v is None:
                return
            self._hits[key] = self._hits.get(key, 0) + 1
            hits = self._hits[key]
        if v is True or (isinstance(v, int) and hits == v):
            raise FailPointError(f"failpoint {key} fired ({detail})")


FAIL_POINTS = _FailPoints()
