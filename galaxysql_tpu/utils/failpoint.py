"""Fail-point injection framework.

Reference analog: `executor/utils/failpoint/FailPoint.java:63-111` (SURVEY.md §4) —
no-op unless a key is armed (there via session vars `set @FP_X=...`); used by DDL
crash-recovery tests to kill execution between tasks.

The network-plane keys (FP_RPC_*) drive the chaos harness (tests/test_chaos.py):
they are consulted inside `net/dn.WorkerClient.request` / `net/worker.Worker.handle`
and accept OP-SCOPED arm values so a schedule can say "drop the reply leg of the
next dml" without touching reads.  Arm-value forms for the RPC keys:

- True            applies to every op
- "dml"           applies to that op only
- int n           applies to the first n matching hits, then auto-exhausts
- {"op": "dml", "n": 1, "leg": "reply", "ms": 50}
                  full form: op filter, hit budget, request/reply leg
                  (FP_RPC_DROP), delay milliseconds (FP_RPC_DELAY_MS)
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

FP_BEFORE_DDL_TASK = "FP_BEFORE_DDL_TASK"
FP_AFTER_DDL_TASK = "FP_AFTER_DDL_TASK"
FP_BEFORE_COMMIT = "FP_BEFORE_COMMIT"
FP_BACKFILL_PAUSE = "FP_BACKFILL_PAUSE"
# armed with a key VALUE: the batch scheduler fails exactly that key's
# sessions inside a flush (error-isolation testing, server/batch_scheduler.py)
FP_BATCH_POISON_KEY = "FP_BATCH_POISON_KEY"
# armed with a key VALUE: the DML batch scheduler fails exactly that key's
# sessions inside a write flush — the duplicate-key/constraint-violation
# stand-in proving per-session error isolation (server/dml_batch.py)
FP_DML_POISON_KEY = "FP_DML_POISON_KEY"
# sleep N ms inside the async applier before each task batch
# (txn/async_apply.py): makes the GSI/replica apply lag observable so the
# read-your-writes fence is actually exercised
FP_APPLY_DELAY_MS = "FP_APPLY_DELAY_MS"

# -- network-plane faults (coordinator-side unless noted) ---------------------
# drop the request or reply leg of an RPC: the socket dies mid-exchange.  A
# reply-leg drop is the double-apply trap — the worker HAS executed the op
# when the coordinator's retry fires (dedupe-window territory).
FP_RPC_DROP = "FP_RPC_DROP"
# sleep N ms before sending (slow network / slow worker; deadline fodder)
FP_RPC_DELAY_MS = "FP_RPC_DELAY_MS"
# fail the next N matching requests with a transport error before send
FP_RPC_FAIL_N = "FP_RPC_FAIL_N"
# WORKER-side: the worker process exits hard on the next matching op
# (armed remotely via the `failpoint` sync action)
FP_WORKER_CRASH = "FP_WORKER_CRASH"
# WORKER-side slow drain (overload harness): the worker sleeps N ms inside
# every matching request — a busy/brownout worker, not a dead one, so
# breakers stay closed while queue depth and RTT climb.  Armed remotely via
# the `failpoint` sync action; dict form {"ms": 50, "op": "exec_sql"}.
FP_WORKER_SLOW_DRAIN = "FP_WORKER_SLOW_DRAIN"
# host memory-pressure injection (overload harness): overrides the memory
# governor's computed tier.  Arm value: "elevated" | "critical" | a float
# usage fraction (e.g. 0.95) fed through the normal thresholds.
FP_MEM_PRESSURE = "FP_MEM_PRESSURE"
# -- elastic rebalancing (ddl/rebalance.py) ----------------------------------
# crash inside the shadow backfill's chunk loop, AFTER the [src, offset]
# checkpoint persisted (crash-resume granularity proof)
FP_REBALANCE_CHUNK = "FP_REBALANCE_CHUNK"
# crash inside the CDC catchup loop, between event pages (the persisted seq
# watermark makes the re-applied page idempotent)
FP_REBALANCE_CATCHUP = "FP_REBALANCE_CATCHUP"
# force the verify gate to see a checksum mismatch: drives the engine's
# REAL TddlError -> reverse-order-undo path (rollback restores the source)
FP_REBALANCE_VERIFY_MISMATCH = "FP_REBALANCE_VERIFY_MISMATCH"
# crash inside the cutover critical section BEFORE the partition/router swap
# (resume must redo the final catchup + swap)
FP_REBALANCE_BEFORE_SWAP = "FP_REBALANCE_BEFORE_SWAP"
# crash AFTER the swap + durable cutover marker but before cache
# invalidation/cleanup (resume must detect the swap already happened and
# NOT re-run it)
FP_REBALANCE_AFTER_SWAP = "FP_REBALANCE_AFTER_SWAP"

# lockdep witness proof (tests/test_lint.py): the DML insert ramp performs a
# DELIBERATE partition-lock -> append_lock acquisition (the reverse of the
# canonical order) so the runtime lock-order witness provably trips on a
# real engine code path (storage/table_store.py `_lockdep_probe`)
FP_LOCK_INVERT = "FP_LOCK_INVERT"

# SLO-plane burn-rate determinism (server/session.py _finish_query): pad the
# OBSERVED elapsed time of matching finished queries without sleeping — arm
# with an int (pad every query by N ms) or a dict
# {"ms": N, "workload": "TP", "schema": "s"} to scope the inflation to one
# digest class / tenant; feeds the latency histogram, statement summary and
# the SLO engine's recent-p99 windows deterministically
FP_SLO_LATENCY_MS = "FP_SLO_LATENCY_MS"


class FailPointError(RuntimeError):
    """Raised by an armed fail point (simulated crash)."""


class _FailPoints:
    def __init__(self):
        self._armed: Dict[str, Any] = {}
        self._hits: Dict[str, int] = {}
        self._lock = threading.Lock()
        # lock-free fast gate: hot paths (the RPC layer) check this plain
        # bool and skip the locked lookup entirely when nothing is armed
        self.active = False

    def arm(self, key: str, value: Any = True):
        with self._lock:
            self._armed[key] = value
            self._hits[key] = 0
            self.active = True

    def disarm(self, key: str):
        with self._lock:
            self._armed.pop(key, None)
            self.active = bool(self._armed)

    def clear(self):
        with self._lock:
            self._armed.clear()
            self._hits.clear()
            self.active = False

    def value(self, key: str) -> Optional[Any]:
        with self._lock:
            return self._armed.get(key)

    def inject(self, key: str, detail: str = ""):
        """Raise FailPointError if `key` is armed.  Armed value semantics:
        True -> fire always; int n -> fire on the n-th hit (1-based)."""
        with self._lock:
            v = self._armed.get(key)
            if v is None:
                return
            self._hits[key] = self._hits.get(key, 0) + 1
            hits = self._hits[key]
        if v is True or (isinstance(v, int) and hits == v):
            raise FailPointError(f"failpoint {key} fired ({detail})")

    def rpc_spec(self, key: str, op: str) -> Optional[dict]:
        """Match an RPC-plane key against `op`; returns the normalized spec
        dict ({"leg","ms",...}) when it applies to THIS hit, else None.

        Int-budget arms ({"n": k} / bare int) consume one unit per matching
        hit and auto-disarm at zero, so "fail the next 2 dml requests" is a
        one-liner in a chaos schedule."""
        with self._lock:
            v = self._armed.get(key)
            if v is None:
                return None
            spec: dict
            if v is True:
                spec = {}
            elif isinstance(v, str):
                if v != op:
                    return None
                spec = {}
            elif isinstance(v, int):
                spec = {"n": v}
            elif isinstance(v, dict):
                spec = dict(v)
                want = spec.get("op")
                if want is not None and want != op:
                    return None
            else:
                return None
            n = spec.get("n")
            if n is not None:
                if n <= 0:
                    return None
                n -= 1
                # write back the decremented budget in the SAME value shape
                # (an exhausted arm stays visible until disarm/clear but no
                # longer fires)
                if isinstance(v, dict):
                    v = dict(v)
                    v["n"] = n
                else:
                    v = n
                self._armed[key] = v
            self._hits[key] = self._hits.get(key, 0) + 1
            return spec


FAIL_POINTS = _FailPoints()
