"""Tracing / profiling: slow-SQL recorder, per-query runtime statistics, and
the hierarchical span-tracing subsystem.

Reference analog: SURVEY.md §5.1 — `SQLRecorder` (slow-SQL ring), `SQLTracer`
(SHOW TRACE, held per session as `last_trace`), and `RuntimeStatistics` counters
surfaced via EXPLAIN ANALYZE and SHOW FULL STATS.  The span layer goes past the
coordinator boundary the reference stops at: one `TraceContext` per traced
query collects a span TREE — coordinator operators, fused-segment dispatches,
MPP per-shard stages, device-cache transfers, XLA compile events, and
worker-process child spans grafted back over the wire with clock-offset
correction — exported as Chrome-trace/Perfetto JSON from `/trace/<trace_id>`.

Span COLLECTION is always-on (every query builds a lightweight host-side span
tree — ramp timestamps only, no device syncs); RETENTION is tail-sampled: a
per-digest head sampler keeps 1-in-N healthy traces, and traces that end slow,
shed, or errored are always kept, into the byte-budgeted per-node `TraceStore`
ring.  `GALAXYSQL_TRACING=0` (read once at import) or
`ENABLE_QUERY_TRACING=false` restores the old fully-opt-in behaviour: with
collection off, `current()` returns None and no code path allocates a span,
times a dispatch, or syncs a device.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import itertools
import os
import threading
import time
import zlib
from typing import Any, Deque, Dict, List, Optional, Tuple

# Emergency hatch (same trio convention as GALAXYSQL_PALLAS / _COLUMNAR):
# env kills always-on collection process-wide, read once at import so the
# per-query check is one attribute load.
ALWAYS_ON = os.environ.get("GALAXYSQL_TRACING", "1") != "0"

# -- node-prefixed trace ids ---------------------------------------------------
#
# Trace ids stay BIGINT-shaped (every surface — SHOW SLOW, query_stats,
# /query/<id> — stores them as int64), but the high bits carry a per-instance
# node hash: two coordinators (Instance.sync_peer topologies) mint from their
# own allocators and can never collide the way the old process-monotonic
# counter did when each process restarted its count at 1.

_NODE_BITS = 40  # low bits: per-node monotonic counter (~10^12 queries)


class TraceIdAllocator:
    """Per-instance trace-id mint: `(crc32(node_id) << 40) | counter`.

    Monotonic within a node; globally unique across nodes up to the 22-bit
    node-hash birthday bound (id collisions across coordinators were certain
    before — two nodes both counting 1, 2, 3…)."""

    def __init__(self, node_id: str):
        self.node_id = node_id
        self._prefix = (zlib.crc32(node_id.encode()) & 0x3FFFFF) << _NODE_BITS
        self._count = itertools.count(1)

    def next(self) -> int:
        # itertools.count.__next__ is a single C call (GIL-atomic): every
        # query mints an id, and a lock here is a measurable convoy at
        # batched-TP serving rates
        return self._prefix | next(self._count)


def trace_node_hash(trace_id: int) -> int:
    """The minting node's 22-bit hash embedded in a trace id."""
    return (int(trace_id) >> _NODE_BITS) & 0x3FFFFF


@dataclasses.dataclass
class SlowEntry:
    sql: str
    elapsed_s: float
    conn_id: int
    at: float
    trace_id: int = 0     # links SHOW SLOW rows to information_schema.query_stats
    workload: str = ""    # TP | AP
    error: str = ""       # non-empty: the query FAILED after elapsed_s
    digest: str = ""      # statement digest: jumps to SHOW STATEMENT SUMMARY


class SlowLog:
    """Bounded ring of slow queries (SQLRecorder analog)."""

    def __init__(self, capacity: int = 256):
        self._ring: Deque[SlowEntry] = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()

    def record(self, sql: str, elapsed_s: float, conn_id: int,
               trace_id: int = 0, workload: str = "", error: str = "",
               digest: str = ""):
        with self._lock:
            self._ring.append(SlowEntry(sql[:512], elapsed_s, conn_id,
                                        time.time(), trace_id, workload,
                                        error, digest))

    def entries(self) -> List[SlowEntry]:
        with self._lock:
            return list(self._ring)

    def clear(self):
        with self._lock:
            self._ring.clear()


SLOW_LOG = SlowLog()


@dataclasses.dataclass
class SegmentSpan:
    """One fused-pipeline-segment dispatch (exec/fusion.py)."""
    segment_id: int   # stable per FusedSegment instance
    chain: str        # op chain, e.g. "filter>project"
    rows_in: int      # live rows entering the segment
    rows_out: int     # live rows surviving it
    compiled: bool    # True: this dispatch paid a fresh trace+compile
    wall_ms: float


class SegmentTracer:
    """Per-segment span recorder — fused pipelines collapse several operators
    into one program, so EXPLAIN-style per-operator stats can't see inside
    them; these spans keep them observable.

    Off by default: rows in/out force a device sync per batch, which the hot
    path must never pay.  Two ways to enable:

    - `scoped(sink)` (preferred): a context manager binding a per-query sink on
      the calling thread, so spans from concurrent sessions land in their own
      QueryProfile instead of interleaving in one shared ring.
    - `enabled = True`: the legacy module-level ring fallback (spans from every
      thread without an active scope share `_ring`)."""

    def __init__(self, capacity: int = 1024):
        self._ring: Deque[SegmentSpan] = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._local = threading.local()
        self.enabled = False

    def _sink(self) -> Optional[list]:
        return getattr(self._local, "sink", None)

    @property
    def active(self) -> bool:
        """True when spans should be recorded on this thread (a scoped sink is
        bound, or the global ring is enabled)."""
        return self.enabled or self._sink() is not None

    @contextlib.contextmanager
    def scoped(self, sink: Optional[list] = None):
        """Route this thread's spans into `sink` (a plain list) for the
        duration — the query-scoped collector.  Nests: the previous sink is
        restored on exit."""
        if sink is None:
            sink = []
        prev = self._sink()
        self._local.sink = sink
        try:
            yield sink
        finally:
            self._local.sink = prev

    def record(self, span: SegmentSpan):
        sink = self._sink()
        if sink is not None:
            sink.append(span)
            return
        with self._lock:
            self._ring.append(span)

    def spans(self) -> List[SegmentSpan]:
        with self._lock:
            return list(self._ring)

    def clear(self):
        with self._lock:
            self._ring.clear()


SEGMENT_TRACER = SegmentTracer()


# -- hierarchical span tracing -------------------------------------------------


def now_us() -> int:
    """Wall-clock microseconds — the shared timebase span timestamps use so
    worker-process spans can be offset-corrected against the coordinator's."""
    return int(time.time() * 1e6)


@dataclasses.dataclass
class Span:
    """One node of a query's span tree.  `parent_id == 0` marks the root.
    Mutable on purpose: operator spans are opened at plan-build time and their
    timing filled in as execution drains them."""

    span_id: int
    parent_id: int
    name: str
    kind: str                  # query|operator|segment|stage|shard|rpc|worker|
    #                            compile|transfer|cache|error
    node: str = ""             # node_id of the process that recorded it
    start_us: int = 0
    dur_us: float = 0.0
    attrs: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def span_from_dict(d: Dict[str, Any]) -> Span:
    return Span(int(d.get("span_id", 0)), int(d.get("parent_id", 0)),
                str(d.get("name", "")), str(d.get("kind", "")),
                str(d.get("node", "")), int(d.get("start_us", 0)),
                float(d.get("dur_us", 0.0)), dict(d.get("attrs") or {}))


class TraceContext:
    """Per-query span collector.

    A query executes on ONE host thread (MPP stages are host-dispatched from
    it; worker spans arrive on it via the RPC reply), so parenting uses a plain
    `cursor` — the span id runtime recorders should attach under.  Structural
    code (operator build, stage recursion, RPC round-trips) moves the cursor
    with begin/end or the `span()` context manager; leaf recorders (segment
    dispatches, compile events, cache transfers) just read it."""

    def __init__(self, trace_id: int, node: str = ""):
        self.trace_id = trace_id
        self.node = node
        self.spans: List[Span] = []
        self._ids = itertools.count(1)
        self._lock = threading.Lock()
        self.cursor = 0  # current parent span id (0 = attach to root/none)

    # -- span construction ---------------------------------------------------

    def add(self, name: str, kind: str, parent: Optional[int] = None,
            start_us: Optional[int] = None, dur_us: float = 0.0,
            **attrs) -> Span:
        """Append a span (explicit or cursor parent); returns it for later
        timing fill-in."""
        with self._lock:
            sid = next(self._ids)
            sp = Span(sid, self.cursor if parent is None else parent,
                      name, kind, self.node,
                      now_us() if start_us is None else start_us,
                      dur_us, attrs)
            self.spans.append(sp)
        return sp

    def event(self, name: str, kind: str = "event", **attrs) -> Span:
        """Instantaneous (zero-duration) span under the cursor — compile
        events, cache hits, transfer markers."""
        return self.add(name, kind, **attrs)

    def begin(self, name: str, kind: str, **attrs) -> Span:
        """Open a span and move the cursor under it (manual form; pair with
        `end`)."""
        sp = self.add(name, kind, **attrs)
        sp._t0 = time.perf_counter()
        sp._prev_cursor = self.cursor
        self.cursor = sp.span_id
        return sp

    def end(self, sp: Span):
        sp.dur_us = round((time.perf_counter() - sp._t0) * 1e6, 1)
        self.cursor = sp._prev_cursor

    @contextlib.contextmanager
    def span(self, name: str, kind: str, **attrs):
        sp = self.begin(name, kind, **attrs)
        try:
            yield sp
        except BaseException as e:
            sp.attrs["error"] = f"{type(e).__name__}: {e}"[:256]
            raise
        finally:
            self.end(sp)

    @property
    def root_id(self) -> int:
        return self.spans[0].span_id if self.spans else 0

    # -- cross-process grafting ----------------------------------------------

    def graft(self, span_dicts: List[Dict[str, Any]], parent: int,
              offset_us: int = 0) -> List[Span]:
        """Adopt spans recorded by another process: remint span ids into this
        context's id space (the worker's counter collides with ours), hang
        orphans under `parent`, and shift their wall clocks by `offset_us`
        (the NTP-style offset the RPC layer measured)."""
        remap: Dict[int, int] = {}
        out: List[Span] = []
        with self._lock:
            for d in span_dicts:
                sp = span_from_dict(d)
                new_id = next(self._ids)
                remap[sp.span_id] = new_id
                sp.span_id = new_id
                sp.parent_id = remap.get(sp.parent_id, parent)
                sp.start_us += offset_us
                self.spans.append(sp)
                out.append(sp)
        return out

    # -- rendering -----------------------------------------------------------

    def tree_lines(self) -> List[str]:
        return span_tree_lines(self.spans)

    def chrome_trace(self) -> Dict[str, Any]:
        return chrome_trace(self.trace_id, self.spans)


def span_tree_lines(spans: List[Span]) -> List[str]:
    """The span tree as indented text (the SHOW TRACE rendering)."""
    children: Dict[int, List[Span]] = {}
    by_id = {s.span_id: s for s in spans}
    roots: List[Span] = []
    for s in spans:
        if s.parent_id and s.parent_id in by_id:
            children.setdefault(s.parent_id, []).append(s)
        else:
            roots.append(s)
    lines: List[str] = []

    def walk(sp: Span, depth: int):
        extra = " ".join(f"{k}={v}" for k, v in sorted(sp.attrs.items()))
        node = f" @{sp.node}" if sp.node else ""
        lines.append(f"{'  ' * depth}{sp.name} [{sp.kind}] "
                     f"{sp.dur_us / 1000:.3f}ms{node}"
                     f"{(' ' + extra) if extra else ''}")
        for c in children.get(sp.span_id, []):
            walk(c, depth + 1)

    for r in roots:
        walk(r, 0)
    return lines


def chrome_trace(trace_id: int, spans: List[Span]) -> Dict[str, Any]:
    """Chrome-trace / Perfetto JSON (`chrome://tracing` 'JSON Array' dialect
    wrapped in an object): complete `X` events, one pid per recording node,
    one tid row per shard/worker lane so mesh skew is visible at a glance."""
    pids: Dict[str, int] = {}
    events: List[Dict[str, Any]] = []
    for sp in spans:
        pid = pids.setdefault(sp.node or "local", len(pids) + 1)
        tid = int(sp.attrs.get("shard", 0)) + 1 if "shard" in sp.attrs else 0
        events.append({"name": sp.name, "cat": sp.kind or "span", "ph": "X",
                       "ts": sp.start_us, "dur": max(sp.dur_us, 1.0),
                       "pid": pid, "tid": tid,
                       "args": {"span_id": sp.span_id,
                                "parent_id": sp.parent_id, **sp.attrs}})
    meta = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": node}} for node, pid in pids.items()]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms",
            "otherData": {"trace_id": str(trace_id)}}


# thread-local active TraceContext: leaf recorders everywhere (fusion
# dispatches, global_jit compiles, device-cache transfers, RPC clients) read
# it; only the session (or the worker RPC handler) ever sets it.

_ACTIVE = threading.local()


def current() -> Optional[TraceContext]:
    return getattr(_ACTIVE, "trace", None)


@contextlib.contextmanager
def activate(tc: Optional[TraceContext]):
    prev = current()
    _ACTIVE.trace = tc
    try:
        yield tc
    finally:
        _ACTIVE.trace = prev


def swap_active(tc: Optional[TraceContext]) -> Optional[TraceContext]:
    """Set the thread's active context, returning the previous one.  The
    always-on query ramp uses this instead of `activate` — two thread-local
    ops, no generator frame (the context-manager overhead is measurable at
    point-serving rates)."""
    prev = getattr(_ACTIVE, "trace", None)
    _ACTIVE.trace = tc
    return prev


# -- per-query runtime statistics ---------------------------------------------


@dataclasses.dataclass
class QueryProfile:
    """One query's runtime statistics (RuntimeStatistics / MPP QueryStats
    analog, §5.1): identity + totals always (host-side, zero device syncs),
    per-operator rows/time and segment spans only when profiling was enabled
    for the execution (`profiled`)."""

    trace_id: int
    sql: str
    schema: str
    conn_id: int
    started_at: float = 0.0
    workload: str = ""            # TP | AP
    engine: str = "local"         # local | mpp | point
    elapsed_ms: float = 0.0
    rows: int = 0                 # result cardinality (free: host rows exist)
    peak_rss_kb: int = 0          # process high-water host memory at finish
    profiled: bool = False        # per-operator stats were collected
    op_stats: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    segments: List[SegmentSpan] = dataclasses.field(default_factory=list)
    trace: List[str] = dataclasses.field(default_factory=list)
    # span tree (TraceContext.spans alias) when the query ran traced; includes
    # grafted worker-side spans and compile/transfer telemetry events
    spans: List[Span] = dataclasses.field(default_factory=list)
    error: str = ""               # non-empty: the query FAILED mid-execution
    # phase breakdown (ms) stamped at the session ramps: fence_wait,
    # admission, queue, plan, compile, execute, serialize.  Shed/failed
    # queries keep whatever phases completed before the raise — partial
    # attribution is the point (a shed storm shows WHERE the wait went).
    phases: Dict[str, float] = dataclasses.field(default_factory=dict)
    # head-sampling state stamped at query entry (ISSUE 20): `traced` means
    # collection was enabled for this query (the tail ramps may retain it
    # even without spans); `sampled` is the head sampler's one-probe verdict
    # (or the router hint's propagated flag), decided EXACTLY ONCE per query
    # — the sampler keeps per-digest cadence counters, so the finish ramps
    # must reuse this bit instead of re-asking
    traced: bool = False
    sampled: bool = False

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        # op_stats node ids are process addresses — meaningless outside
        for st in d["op_stats"]:
            st.pop("node_id", None)
        return d


class ProfileRing:
    """Bounded ring of the last-N QueryProfiles (per engine instance), indexed
    by trace id for the web console's /query/<trace_id> resource."""

    def __init__(self, capacity: int = 256):
        self._ring: Deque[QueryProfile] = collections.deque(maxlen=capacity)

    def record(self, profile: QueryProfile):
        # deque(maxlen).append is one C call (GIL-atomic); EVERY query lands
        # here, and a lock convoys at batched-TP serving rates.  Readers
        # snapshot with list(ring) — also a single C call — and iterate the
        # snapshot, so they never see a deque mutating under them.
        self._ring.append(profile)

    def record_many(self, profiles):
        """Bulk append (one C call) — the batch scheduler records a whole
        group's profiles at scatter time."""
        self._ring.extend(profiles)

    def entries(self) -> List[QueryProfile]:
        return list(self._ring)

    def get(self, trace_id) -> Optional[QueryProfile]:
        """Exact-id lookup.  Ids are node-prefixed (TraceIdAllocator), so a
        ring shared between peer-coordinator tests can never serve node A's
        profile for node B's id; numeric strings (the web console's raw path
        segment) are accepted."""
        try:
            tid = int(trace_id)
        except (TypeError, ValueError):
            return None
        for p in list(self._ring):
            if p.trace_id == tid:
                return p
        return None

    def clear(self):
        self._ring.clear()


# -- tail-sampled trace retention ---------------------------------------------


@dataclasses.dataclass
class RetainedTrace:
    """One retained query trace: the span tree in wire/persistable (dict)
    form plus the identity needed to correlate it with statement-summary
    rows, events, and incident bundles."""

    trace_id: int
    digest: str
    sql: str
    schema: str
    workload: str
    elapsed_ms: float
    error: str
    reason: str                  # sampled | slow | error | shed | remote
    node: str
    at: float
    phases: Dict[str, float] = dataclasses.field(default_factory=dict)
    spans: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    approx_bytes: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class TraceSampler:
    """Per-digest head sampler: the per-query decision is one dict probe plus
    one compare (the hot-path budget ISSUE 20 sets).  Keeps every Nth
    occurrence of a digest where N = round(1/rate) — the FIRST occurrence
    always retains, so new digests are never invisible.  rate <= 0 disables
    head sampling entirely (tail retention still fires)."""

    MAX_DIGESTS = 8192

    def __init__(self, rate: float = 0.01):
        self.configure(rate)

    def configure(self, rate: float):
        self.rate = max(0.0, float(rate))
        self._period = int(round(1.0 / self.rate)) if self.rate > 0 else 0
        self._counts: Dict[str, int] = {}

    def decide(self, digest: str) -> bool:
        if not self._period:
            return False
        n = self._counts.get(digest, 0)
        if len(self._counts) > self.MAX_DIGESTS:
            self._counts.clear()  # epoch reset, bounded (admission idiom)
        self._counts[digest] = n + 1
        return n % self._period == 0


class TraceStore:
    """Byte-budgeted per-node ring of retained traces, digest-indexed.

    Healthy traces land via the head sampler; slow/errored/shed traces are
    ALWAYS retained (tail-based retention — the trace you need is the one
    the anomaly already marked).  Eviction is oldest-first until the byte
    budget holds; the estimate is a cheap host-side sum computed only for
    traces that retain, never on the per-query hot path."""

    def __init__(self, budget_bytes: int = 4 << 20, rate: float = 0.01,
                 node: str = ""):
        self.node = node
        self.sampler = TraceSampler(rate)
        self._budget = max(1, int(budget_bytes))
        self._entries: "collections.OrderedDict[int, RetainedTrace]" = \
            collections.OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self.retained = 0
        self.evicted = 0

    def configure(self, rate: Optional[float] = None,
                  budget_bytes: Optional[int] = None):
        if rate is not None and rate != self.sampler.rate:
            self.sampler.configure(rate)
        if budget_bytes is not None:
            self._budget = max(1, int(budget_bytes))

    @staticmethod
    def _estimate(rt: RetainedTrace) -> int:
        n = 256 + len(rt.sql) + 24 * len(rt.phases)
        for d in rt.spans:
            n += 96 + len(d.get("name", ""))
            n += sum(len(str(k)) + len(str(v)) + 16
                     for k, v in (d.get("attrs") or {}).items())
        return n

    def offer(self, prof: "QueryProfile", digest: str,
              slow: bool = False, shed: bool = False,
              forced: bool = False) -> Optional[RetainedTrace]:
        """Retention decision for a finished (or aborted) query.  Tail
        conditions (error/slow/shed) always retain; `forced` marks an
        upstream router's propagated sampling decision (the trace hint's
        sampled flag — the router will pull this id back by exact match);
        otherwise `prof.sampled` — the head verdict stamped ONCE at query
        entry (the sampler keeps cadence counters; re-asking here would
        double-count the digest).  Returns the retained entry or None."""
        if prof.error or shed:
            reason = "shed" if shed else "error"
        elif slow:
            reason = "slow"
        elif forced:
            reason = "remote"
        elif prof.sampled:
            reason = "sampled"
        else:
            return None
        if prof.spans:
            spans = [s.to_dict() for s in prof.spans]
            if not spans[0].get("dur_us"):
                # the root span is still open at the finish ramp (it closes
                # when the ramp unwinds); stamp the observed elapsed so
                # retained trees render a closed root
                spans[0]["dur_us"] = prof.elapsed_ms * 1000.0
        else:
            # unsampled query that tail-retained: the hot path skipped the
            # span machinery, so synthesize the root from the profile — the
            # phase breakdown is the evidence, the tree is a formality
            attrs: Dict[str, Any] = {"sql": prof.sql[:128],
                                     "conn": prof.conn_id,
                                     "schema": prof.schema,
                                     "synthesized": True}
            if prof.phases:
                attrs["phases"] = dict(prof.phases)
            if prof.error:
                attrs["error"] = prof.error[:256]
            spans = [{"span_id": 1, "parent_id": 0, "name": "query",
                      "kind": "query", "node": self.node,
                      "start_us": int(prof.started_at * 1e6),
                      "dur_us": round(prof.elapsed_ms * 1000.0, 1),
                      "attrs": attrs}]
        rt = RetainedTrace(
            trace_id=prof.trace_id, digest=digest, sql=prof.sql[:512],
            schema=prof.schema, workload=prof.workload,
            elapsed_ms=round(prof.elapsed_ms, 3), error=prof.error[:256],
            reason=reason, node=self.node, at=time.time(),
            phases=dict(prof.phases), spans=spans)
        return self.put(rt)

    def put(self, rt: RetainedTrace) -> RetainedTrace:
        """Insert an already-assembled trace under the byte budget — the
        router retains its grafted cluster-path trees through here, and
        offer() lands its retention decisions here too."""
        rt.approx_bytes = self._estimate(rt)
        with self._lock:
            # re-retention of the same id (leader + member finish ramps,
            # or a router re-grafting a pulled peer trace)
            prev = self._entries.pop(rt.trace_id, None)
            if prev is not None:
                self._bytes -= prev.approx_bytes
            self._entries[rt.trace_id] = rt
            self._bytes += rt.approx_bytes
            self.retained += 1
            while self._bytes > self._budget and len(self._entries) > 1:
                _, old = self._entries.popitem(last=False)
                self._bytes -= old.approx_bytes
                self.evicted += 1
        return rt

    def get(self, trace_id) -> Optional[RetainedTrace]:
        try:
            tid = int(trace_id)
        except (TypeError, ValueError):
            return None
        with self._lock:
            return self._entries.get(tid)

    def for_digest(self, digest: str, limit: int = 4) -> List[RetainedTrace]:
        """Most-recent-first retained traces for one statement digest — the
        flight recorder's evidence query."""
        with self._lock:
            out = [rt for rt in reversed(self._entries.values())
                   if rt.digest == digest]
        return out[:limit]

    def entries(self, limit: int = 0) -> List[RetainedTrace]:
        with self._lock:
            out = list(reversed(self._entries.values()))
        return out[:limit] if limit else out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"count": len(self._entries), "bytes": self._bytes,
                    "budget": self._budget, "retained": self.retained,
                    "evicted": self.evicted, "rate": self.sampler.rate}

    def clear(self):
        with self._lock:
            self._entries.clear()
            self._bytes = 0


class MatrixStatistics:
    """Instance-level counters (SHOW @@stats analog, §5.5)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.queries = 0
        self.dml = 0
        self.errors = 0
        self.slow = 0
        self.active_connections = 0

    def bump(self, field: str, n: int = 1):
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def snapshot(self) -> List[Tuple[str, int]]:
        with self._lock:
            return [("queries", self.queries), ("dml", self.dml),
                    ("errors", self.errors), ("slow", self.slow),
                    ("active_connections", self.active_connections)]


GLOBAL_STATS = MatrixStatistics()
