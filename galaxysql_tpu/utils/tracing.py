"""Tracing / profiling: slow-SQL recorder + per-query runtime statistics.

Reference analog: SURVEY.md §5.1 — `SQLRecorder` (slow-SQL ring), `SQLTracer`
(SHOW TRACE, held per session as `last_trace`), and `RuntimeStatistics` counters
surfaced via EXPLAIN ANALYZE and SHOW FULL STATS.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import itertools
import threading
import time
from typing import Any, Deque, Dict, List, Optional, Tuple

# -- monotonic trace ids -------------------------------------------------------

_TRACE_IDS = itertools.count(1)
_TRACE_ID_LOCK = threading.Lock()


def next_trace_id() -> int:
    """Process-monotonic query trace id (the reference's traceId, §5.1)."""
    with _TRACE_ID_LOCK:
        return next(_TRACE_IDS)


@dataclasses.dataclass
class SlowEntry:
    sql: str
    elapsed_s: float
    conn_id: int
    at: float
    trace_id: int = 0     # links SHOW SLOW rows to information_schema.query_stats
    workload: str = ""    # TP | AP


class SlowLog:
    """Bounded ring of slow queries (SQLRecorder analog)."""

    def __init__(self, capacity: int = 256):
        self._ring: Deque[SlowEntry] = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()

    def record(self, sql: str, elapsed_s: float, conn_id: int,
               trace_id: int = 0, workload: str = ""):
        with self._lock:
            self._ring.append(SlowEntry(sql[:512], elapsed_s, conn_id,
                                        time.time(), trace_id, workload))

    def entries(self) -> List[SlowEntry]:
        with self._lock:
            return list(self._ring)

    def clear(self):
        with self._lock:
            self._ring.clear()


SLOW_LOG = SlowLog()


@dataclasses.dataclass
class SegmentSpan:
    """One fused-pipeline-segment dispatch (exec/fusion.py)."""
    segment_id: int   # stable per FusedSegment instance
    chain: str        # op chain, e.g. "filter>project"
    rows_in: int      # live rows entering the segment
    rows_out: int     # live rows surviving it
    compiled: bool    # True: this dispatch paid a fresh trace+compile
    wall_ms: float


class SegmentTracer:
    """Per-segment span recorder — fused pipelines collapse several operators
    into one program, so EXPLAIN-style per-operator stats can't see inside
    them; these spans keep them observable.

    Off by default: rows in/out force a device sync per batch, which the hot
    path must never pay.  Two ways to enable:

    - `scoped(sink)` (preferred): a context manager binding a per-query sink on
      the calling thread, so spans from concurrent sessions land in their own
      QueryProfile instead of interleaving in one shared ring.
    - `enabled = True`: the legacy module-level ring fallback (spans from every
      thread without an active scope share `_ring`)."""

    def __init__(self, capacity: int = 1024):
        self._ring: Deque[SegmentSpan] = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._local = threading.local()
        self.enabled = False

    def _sink(self) -> Optional[list]:
        return getattr(self._local, "sink", None)

    @property
    def active(self) -> bool:
        """True when spans should be recorded on this thread (a scoped sink is
        bound, or the global ring is enabled)."""
        return self.enabled or self._sink() is not None

    @contextlib.contextmanager
    def scoped(self, sink: Optional[list] = None):
        """Route this thread's spans into `sink` (a plain list) for the
        duration — the query-scoped collector.  Nests: the previous sink is
        restored on exit."""
        if sink is None:
            sink = []
        prev = self._sink()
        self._local.sink = sink
        try:
            yield sink
        finally:
            self._local.sink = prev

    def record(self, span: SegmentSpan):
        sink = self._sink()
        if sink is not None:
            sink.append(span)
            return
        with self._lock:
            self._ring.append(span)

    def spans(self) -> List[SegmentSpan]:
        with self._lock:
            return list(self._ring)

    def clear(self):
        with self._lock:
            self._ring.clear()


SEGMENT_TRACER = SegmentTracer()


# -- per-query runtime statistics ---------------------------------------------


@dataclasses.dataclass
class QueryProfile:
    """One query's runtime statistics (RuntimeStatistics / MPP QueryStats
    analog, §5.1): identity + totals always (host-side, zero device syncs),
    per-operator rows/time and segment spans only when profiling was enabled
    for the execution (`profiled`)."""

    trace_id: int
    sql: str
    schema: str
    conn_id: int
    started_at: float = 0.0
    workload: str = ""            # TP | AP
    engine: str = "local"         # local | mpp | point
    elapsed_ms: float = 0.0
    rows: int = 0                 # result cardinality (free: host rows exist)
    peak_rss_kb: int = 0          # process high-water host memory at finish
    profiled: bool = False        # per-operator stats were collected
    op_stats: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    segments: List[SegmentSpan] = dataclasses.field(default_factory=list)
    trace: List[str] = dataclasses.field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        # op_stats node ids are process addresses — meaningless outside
        for st in d["op_stats"]:
            st.pop("node_id", None)
        return d


class ProfileRing:
    """Bounded ring of the last-N QueryProfiles (per engine instance), indexed
    by trace id for the web console's /query/<trace_id> resource."""

    def __init__(self, capacity: int = 256):
        self._ring: Deque[QueryProfile] = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()

    def record(self, profile: QueryProfile):
        with self._lock:
            self._ring.append(profile)

    def entries(self) -> List[QueryProfile]:
        with self._lock:
            return list(self._ring)

    def get(self, trace_id: int) -> Optional[QueryProfile]:
        with self._lock:
            for p in self._ring:
                if p.trace_id == trace_id:
                    return p
        return None

    def clear(self):
        with self._lock:
            self._ring.clear()


class MatrixStatistics:
    """Instance-level counters (SHOW @@stats analog, §5.5)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.queries = 0
        self.dml = 0
        self.errors = 0
        self.slow = 0
        self.active_connections = 0

    def bump(self, field: str, n: int = 1):
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def snapshot(self) -> List[Tuple[str, int]]:
        with self._lock:
            return [("queries", self.queries), ("dml", self.dml),
                    ("errors", self.errors), ("slow", self.slow),
                    ("active_connections", self.active_connections)]


GLOBAL_STATS = MatrixStatistics()
