"""Tracing / profiling: slow-SQL recorder + per-query runtime statistics.

Reference analog: SURVEY.md §5.1 — `SQLRecorder` (slow-SQL ring), `SQLTracer`
(SHOW TRACE, held per session as `last_trace`), and `RuntimeStatistics` counters
surfaced via EXPLAIN ANALYZE and SHOW FULL STATS.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Deque, List, Tuple


@dataclasses.dataclass
class SlowEntry:
    sql: str
    elapsed_s: float
    conn_id: int
    at: float


class SlowLog:
    """Bounded ring of slow queries (SQLRecorder analog)."""

    def __init__(self, capacity: int = 256):
        self._ring: Deque[SlowEntry] = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()

    def record(self, sql: str, elapsed_s: float, conn_id: int):
        with self._lock:
            self._ring.append(SlowEntry(sql[:512], elapsed_s, conn_id, time.time()))

    def entries(self) -> List[SlowEntry]:
        with self._lock:
            return list(self._ring)

    def clear(self):
        with self._lock:
            self._ring.clear()


SLOW_LOG = SlowLog()


class MatrixStatistics:
    """Instance-level counters (SHOW @@stats analog, §5.5)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.queries = 0
        self.dml = 0
        self.errors = 0
        self.slow = 0
        self.active_connections = 0

    def bump(self, field: str, n: int = 1):
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def snapshot(self) -> List[Tuple[str, int]]:
        with self._lock:
            return [("queries", self.queries), ("dml", self.dml),
                    ("errors", self.errors), ("slow", self.slow),
                    ("active_connections", self.active_connections)]


GLOBAL_STATS = MatrixStatistics()
