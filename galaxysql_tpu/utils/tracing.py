"""Tracing / profiling: slow-SQL recorder + per-query runtime statistics.

Reference analog: SURVEY.md §5.1 — `SQLRecorder` (slow-SQL ring), `SQLTracer`
(SHOW TRACE, held per session as `last_trace`), and `RuntimeStatistics` counters
surfaced via EXPLAIN ANALYZE and SHOW FULL STATS.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Deque, List, Tuple


@dataclasses.dataclass
class SlowEntry:
    sql: str
    elapsed_s: float
    conn_id: int
    at: float


class SlowLog:
    """Bounded ring of slow queries (SQLRecorder analog)."""

    def __init__(self, capacity: int = 256):
        self._ring: Deque[SlowEntry] = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()

    def record(self, sql: str, elapsed_s: float, conn_id: int):
        with self._lock:
            self._ring.append(SlowEntry(sql[:512], elapsed_s, conn_id, time.time()))

    def entries(self) -> List[SlowEntry]:
        with self._lock:
            return list(self._ring)

    def clear(self):
        with self._lock:
            self._ring.clear()


SLOW_LOG = SlowLog()


@dataclasses.dataclass
class SegmentSpan:
    """One fused-pipeline-segment dispatch (exec/fusion.py)."""
    segment_id: int   # stable per FusedSegment instance
    chain: str        # op chain, e.g. "filter>project"
    rows_in: int      # live rows entering the segment
    rows_out: int     # live rows surviving it
    compiled: bool    # True: this dispatch paid a fresh trace+compile
    wall_ms: float


class SegmentTracer:
    """Bounded ring of per-segment spans — fused pipelines collapse several
    operators into one program, so EXPLAIN-style per-operator stats can't see
    inside them; these spans keep them observable.

    Off by default: rows in/out force a device sync per batch, which the hot
    path must never pay.  Enable around a query, then read `spans()`."""

    def __init__(self, capacity: int = 1024):
        self._ring: Deque[SegmentSpan] = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self.enabled = False

    def record(self, span: SegmentSpan):
        with self._lock:
            self._ring.append(span)

    def spans(self) -> List[SegmentSpan]:
        with self._lock:
            return list(self._ring)

    def clear(self):
        with self._lock:
            self._ring.clear()


SEGMENT_TRACER = SegmentTracer()


class MatrixStatistics:
    """Instance-level counters (SHOW @@stats analog, §5.5)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.queries = 0
        self.dml = 0
        self.errors = 0
        self.slow = 0
        self.active_connections = 0

    def bump(self, field: str, n: int = 1):
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def snapshot(self) -> List[Tuple[str, int]]:
        with self._lock:
            return [("queries", self.queries), ("dml", self.dml),
                    ("errors", self.errors), ("slow", self.slow),
                    ("active_connections", self.active_connections)]


GLOBAL_STATS = MatrixStatistics()
