"""FastChecker: order-insensitive hash comparison of base table vs GSI content.

Reference analog: `executor/fastchecker/FastChecker.java` (SURVEY.md App.F) — per-batch
hash aggregates pushed to both sides; equal checksums mean the index is consistent with
its base table.  The checksum is the elementwise sum of mixed row-hashes over the
shared columns, so row order and partition placement don't matter.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from galaxysql_tpu.utils import errors

_MASK = np.uint64(0xFFFFFFFFFFFFFFFF)


def _mix(h: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        h = h ^ (h >> np.uint64(33))
        h = h * np.uint64(0xff51afd7ed558ccd)
        h = h ^ (h >> np.uint64(33))
        h = h * np.uint64(0xc4ceb9fe1a85ec53)
        h = h ^ (h >> np.uint64(33))
    return h


def table_checksum(store, columns: List[str], snapshot_ts: Optional[int] = None
                   ) -> Tuple[int, int]:
    """(row_count, order-insensitive checksum) over visible rows of `columns`."""
    return partitions_checksum(store.partitions, columns, snapshot_ts)


def partitions_checksum(partitions, columns: List[str],
                        snapshot_ts: Optional[int] = None) -> Tuple[int, int]:
    """table_checksum over an explicit partition list: the rebalance verify
    gate compares one table's SOURCE partitions against the job's shadow
    partitions (which live outside any store until cutover)."""
    total = np.uint64(0)
    count = 0
    with np.errstate(over="ignore"):
        for p in partitions:
            # a consistent cut per partition: a concurrent append rebinds the
            # lane arrays, so visibility and lanes read OUTSIDE the lock can
            # disagree on length (torn read -> bogus mismatch/IndexError).
            # Appends never mutate the [0, n) prefix, so slicing to one
            # locked row count is exact.
            with p.lock:
                n_rows = p.num_rows
                vis = p.visible_mask(snapshot_ts)[:n_rows]
                raws = {c: p.lanes[c][:n_rows][vis] for c in columns}
                valids = {c: p.valid[c][:n_rows][vis] for c in columns}
            n = int(vis.sum())
            if not n:
                continue
            count += n
            h = np.zeros(n, dtype=np.uint64)
            for c in columns:
                raw = raws[c]
                if raw.dtype.kind == "f":
                    # hash the BIT PATTERN: astype would truncate fractions and
                    # miss sub-integer corruption
                    lane = raw.view(np.uint32 if raw.dtype.itemsize == 4
                                    else np.uint64).astype(np.uint64)
                else:
                    lane = raw.astype(np.int64).astype(np.uint64)
                lane = np.where(valids[c], _mix(lane),
                                np.uint64(0xdeadbeefcafebabe))
                h = _mix(h * np.uint64(31) + lane)
            total = (total + h.sum(dtype=np.uint64)) & _MASK
    return count, int(total)


def check_gsi(instance, schema: str, table: str, index: str,
              snapshot_ts: Optional[int] = None) -> dict:
    """Compare a base table against one of its GSIs; returns a report dict."""
    tm = instance.catalog.table(schema, table)
    idx = next((i for i in tm.indexes if i.name.lower() == index.lower()), None)
    if idx is None or not idx.global_index:
        raise errors.TddlError(f"'{index}' is not a global index of {table}")
    gsi_tm = instance.catalog.table(schema, f"{table}${index}")
    ts = snapshot_ts or instance.tso.next_timestamp()
    shared = [c.name for c in gsi_tm.columns if tm.has_column(c.name)]
    base_n, base_sum = table_checksum(instance.store(schema, table), shared, ts)
    gsi_n, gsi_sum = table_checksum(instance.store(schema, gsi_tm.name), shared, ts)
    return {
        "table": f"{schema}.{table}", "index": index, "columns": shared,
        "base_rows": base_n, "gsi_rows": gsi_n,
        "consistent": base_n == gsi_n and base_sum == gsi_sum,
    }
