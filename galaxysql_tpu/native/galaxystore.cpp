// Native storage runtime: host-side hot paths of the DN-analog store.
//
// Reference analog: the galaxyengine DN is C++ (SURVEY.md 2.9); the CN-side runtime
// here keeps the accelerator path in XLA and moves the storage shim's per-row host
// loops (hash routing, MVCC visibility, compaction, bloom filters, checksums) into
// native code.  Exposed as a C ABI consumed via ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstring>
#include <cstddef>

extern "C" {

// splitmix64-style finalizer -- MUST match kernels/relational.py::_mix64 and
// meta/catalog.py::_mix64_np so host routing and device repartitioning agree.
static inline uint64_t mix64(uint64_t h) {
    h ^= h >> 33;
    h *= 0xff51afd7ed558ccdULL;
    h ^= h >> 33;
    h *= 0xc4ceb9fe1a85ec53ULL;
    h ^= h >> 33;
    return h;
}

// shard id per key: mix64(key) % nparts
void gx_hash_partition(const int64_t* keys, int32_t* out, size_t n, int32_t nparts) {
    const uint64_t m = (uint64_t)nparts;
    for (size_t i = 0; i < n; i++) {
        out[i] = (int32_t)(mix64((uint64_t)keys[i]) % m);
    }
}

// MVCC visibility: begin/end timestamp lanes, negative = uncommitted (-txn_id)
void gx_visible_mask(const int64_t* begin_ts, const int64_t* end_ts, uint8_t* out,
                     size_t n, int64_t snapshot_ts, int64_t txn_id) {
    const int64_t own = -txn_id;
    for (size_t i = 0; i < n; i++) {
        const int64_t b = begin_ts[i], e = end_ts[i];
        bool ins = (b >= 0 && b <= snapshot_ts) || (txn_id != 0 && b == own);
        bool del = (e >= 0 && e <= snapshot_ts) || (txn_id != 0 && e == own);
        out[i] = (uint8_t)(ins && !del);
    }
}

// ---- bloom filter (runtime-filter plane; reference operator/util/bloomfilter) ----
// Standard 2-probe blocked layout: bits array of u64 words, nwords power of two.

void gx_bloom_build(const int64_t* keys, size_t n, uint64_t* words, size_t nwords) {
    const uint64_t mask = (uint64_t)nwords - 1;
    for (size_t i = 0; i < n; i++) {
        uint64_t h = mix64((uint64_t)keys[i]);
        uint64_t w1 = (h >> 6) & mask;
        uint64_t w2 = (h >> 38) & mask;
        words[w1] |= 1ULL << (h & 63);
        words[w2] |= 1ULL << ((h >> 32) & 63);
    }
}

void gx_bloom_query(const int64_t* keys, size_t n, const uint64_t* words,
                    size_t nwords, uint8_t* out) {
    const uint64_t mask = (uint64_t)nwords - 1;
    for (size_t i = 0; i < n; i++) {
        uint64_t h = mix64((uint64_t)keys[i]);
        uint64_t w1 = (h >> 6) & mask;
        uint64_t w2 = (h >> 38) & mask;
        bool hit = (words[w1] >> (h & 63)) & 1ULL;
        hit = hit && ((words[w2] >> ((h >> 32) & 63)) & 1ULL);
        out[i] = (uint8_t)hit;
    }
}

// ---- vectorized equi-join hot loop ----
// Reference analog: ParallelHashJoinExec.java:131-226 / ConcurrentRawHashTable
// (SURVEY.md §3.3).  Chained hash table over 64-bit key hashes: build links
// rows per slot through a next[] array; probe walks the chain comparing the
// FULL 64-bit hash (slot collisions cost chain hops, hash collisions cost
// duplicate candidate pairs that the caller's exact-key verification filters —
// never correctness).  This is the CPU-backend twin of the XLA formulations in
// kernels/relational.py (TPU keeps sort/searchsorted + CSR: scatters serialize
// there, while this loop is exactly what a scalar core does well).

void gx_join_build(const uint64_t* hashes, const uint8_t* live, size_t nb,
                   int32_t* heads, size_t M, int32_t* next) {
    const uint64_t mask = (uint64_t)M - 1;
    for (size_t i = 0; i < nb; i++) {
        next[i] = -1;
        if (!live[i]) continue;
        size_t s = (size_t)(hashes[i] & mask);
        next[i] = heads[s];
        heads[s] = (int32_t)i;
    }
}

// Emits candidate (build,probe) pairs; returns the TOTAL number of matches.
// If the total exceeds cap only the first cap pairs are written and the caller
// retries with a larger buffer (exact size now known).
size_t gx_join_probe(const uint64_t* hashes, const uint8_t* live, size_t npr,
                     const uint64_t* build_hashes,
                     const int32_t* heads, size_t M, const int32_t* next,
                     int32_t* out_b, int32_t* out_p, size_t cap) {
    const uint64_t mask = (uint64_t)M - 1;
    size_t o = 0;
    for (size_t i = 0; i < npr; i++) {
        if (!live[i]) continue;
        const uint64_t h = hashes[i];
        for (int32_t j = heads[(size_t)(h & mask)]; j >= 0; j = next[j]) {
            if (build_hashes[j] == h) {
                if (o < cap) { out_b[o] = j; out_p[o] = (int32_t)i; }
                o++;
            }
        }
    }
    return o;
}

// Single-int64-key specialization: the chain stores row ids and matching
// compares the KEY LANE itself — exact equality, so the caller skips both the
// hash materialization and the verification pass (the dominant join shape:
// FK/PK equi joins on integer/dictionary-code/date/decimal lanes).

void gx_join_build_k1(const int64_t* keys, const uint8_t* live, size_t nb,
                      int32_t* heads, size_t M, int32_t* next) {
    const uint64_t mask = (uint64_t)M - 1;
    for (size_t i = 0; i < nb; i++) {
        next[i] = -1;
        if (!live[i]) continue;
        size_t s = (size_t)(mix64((uint64_t)keys[i]) & mask);
        next[i] = heads[s];
        heads[s] = (int32_t)i;
    }
}

size_t gx_join_probe_k1(const int64_t* keys, const uint8_t* live, size_t npr,
                        const int64_t* build_keys,
                        const int32_t* heads, size_t M, const int32_t* next,
                        int32_t* out_b, int32_t* out_p, size_t cap) {
    // blocked probe: slots for a block are computed (and their head entries
    // prefetched) before any chain walk — the walk's random L2 misses then
    // overlap instead of serializing on the mix64+load dependency chain
    enum { B = 64 };
    const uint64_t mask = (uint64_t)M - 1;
    uint32_t slot[B];
    size_t o = 0;
    for (size_t base = 0; base < npr; base += B) {
        const size_t hi = (base + B < npr) ? base + B : npr;
        for (size_t i = base; i < hi; i++) {
            // slot computed unconditionally (a dead-row SENTINEL would
            // collide with a real slot at M == 2^32); deadness re-checks
            // live[] in the walk loop
            uint32_t s = (uint32_t)(mix64((uint64_t)keys[i]) & mask);
            slot[i - base] = s;
            if (live[i]) __builtin_prefetch(&heads[s], 0, 1);
        }
        for (size_t i = base; i < hi; i++) {
            if (!live[i]) continue;
            const int64_t k = keys[i];
            for (int32_t j = heads[slot[i - base]]; j >= 0; j = next[j]) {
                if (build_keys[j] == k) {
                    if (o < cap) { out_b[o] = j; out_p[o] = (int32_t)i; }
                    o++;
                }
            }
        }
    }
    return o;
}

// Compact-id probe: iterate a precollected live-row id list instead of
// branching on a sparse live mask (random-pattern live branches mispredict;
// np.nonzero collects ids vectorized, this loop then runs dense).
size_t gx_join_probe_k1_idx(const int64_t* keys, const int32_t* ids,
                            size_t n_ids, const int64_t* build_keys,
                            const int32_t* heads, size_t M,
                            const int32_t* next,
                            int32_t* out_b, int32_t* out_p, size_t cap) {
    enum { B = 64 };
    const uint64_t mask = (uint64_t)M - 1;
    uint32_t slot[B];
    size_t o = 0;
    for (size_t base = 0; base < n_ids; base += B) {
        const size_t hi = (base + B < n_ids) ? base + B : n_ids;
        for (size_t t = base; t < hi; t++) {
            uint32_t s = (uint32_t)(mix64((uint64_t)keys[ids[t]]) & mask);
            slot[t - base] = s;
            __builtin_prefetch(&heads[s], 0, 1);
        }
        for (size_t t = base; t < hi; t++) {
            const int32_t i = ids[t];
            const int64_t k = keys[i];
            for (int32_t j = heads[slot[t - base]]; j >= 0; j = next[j]) {
                if (build_keys[j] == k) {
                    if (o < cap) { out_b[o] = j; out_p[o] = i; }
                    o++;
                }
            }
        }
    }
    return o;
}

// Combined key-lane hashing (the np/jnp hash_columns twin): fold `lane` into
// the running combined hash the same way kernels/relational.py::hash_columns
// does.  first=1 initializes; null slots carry the NULL tag so NULL keys chain
// together (verification decides join semantics).
void gx_hash_combine(uint64_t* h, const int64_t* lane, const uint8_t* valid,
                     size_t n, int32_t first) {
    for (size_t i = 0; i < n; i++) {
        uint64_t l = mix64((uint64_t)lane[i]);
        if (valid && !valid[i]) l = 0xdeadbeefcafebabeULL;
        h[i] = first ? l
                     : mix64(h[i] * 31ULL + l + 0x9e3779b97f4a7c15ULL);
    }
}

// ---- page checksum (persistence integrity; crc32c, software table) ----

static uint32_t crc_table[256];
static bool crc_init_done = false;

static void crc_init() {
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? (0x82F63B78u ^ (c >> 1)) : (c >> 1);
        crc_table[i] = c;
    }
    crc_init_done = true;
}

uint32_t gx_crc32c(const uint8_t* data, size_t n, uint32_t seed) {
    if (!crc_init_done) crc_init();
    uint32_t c = seed ^ 0xFFFFFFFFu;
    for (size_t i = 0; i < n; i++)
        c = crc_table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
    return c ^ 0xFFFFFFFFu;
}

// ---- delta + zigzag varint codec for int64 lanes (cold persistence pages) ----

static inline uint64_t zigzag(int64_t v) { return ((uint64_t)v << 1) ^ (uint64_t)(v >> 63); }
static inline int64_t unzigzag(uint64_t v) { return (int64_t)(v >> 1) ^ -(int64_t)(v & 1); }

// dst must have room for 10*n bytes; returns encoded size
size_t gx_encode_i64(const int64_t* src, size_t n, uint8_t* dst) {
    size_t o = 0;
    int64_t prev = 0;
    for (size_t i = 0; i < n; i++) {
        uint64_t v = zigzag(src[i] - prev);
        prev = src[i];
        while (v >= 0x80) { dst[o++] = (uint8_t)(v | 0x80); v >>= 7; }
        dst[o++] = (uint8_t)v;
    }
    return o;
}

size_t gx_decode_i64(const uint8_t* src, size_t nbytes, int64_t* dst, size_t n) {
    size_t o = 0, i = 0;
    int64_t prev = 0;
    while (i < n && o < nbytes) {
        uint64_t v = 0;
        int shift = 0;
        while (o < nbytes) {
            uint8_t b = src[o++];
            v |= (uint64_t)(b & 0x7F) << shift;
            if (!(b & 0x80)) break;
            shift += 7;
        }
        prev += unzigzag(v);
        dst[i++] = prev;
    }
    return i;
}

}  // extern "C"
