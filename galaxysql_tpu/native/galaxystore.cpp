// Native storage runtime: host-side hot paths of the DN-analog store.
//
// Reference analog: the galaxyengine DN is C++ (SURVEY.md 2.9); the CN-side runtime
// here keeps the accelerator path in XLA and moves the storage shim's per-row host
// loops (hash routing, MVCC visibility, compaction, bloom filters, checksums) into
// native code.  Exposed as a C ABI consumed via ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstring>
#include <cstddef>

extern "C" {

// splitmix64-style finalizer -- MUST match kernels/relational.py::_mix64 and
// meta/catalog.py::_mix64_np so host routing and device repartitioning agree.
static inline uint64_t mix64(uint64_t h) {
    h ^= h >> 33;
    h *= 0xff51afd7ed558ccdULL;
    h ^= h >> 33;
    h *= 0xc4ceb9fe1a85ec53ULL;
    h ^= h >> 33;
    return h;
}

// shard id per key: mix64(key) % nparts
void gx_hash_partition(const int64_t* keys, int32_t* out, size_t n, int32_t nparts) {
    const uint64_t m = (uint64_t)nparts;
    for (size_t i = 0; i < n; i++) {
        out[i] = (int32_t)(mix64((uint64_t)keys[i]) % m);
    }
}

// MVCC visibility: begin/end timestamp lanes, negative = uncommitted (-txn_id)
void gx_visible_mask(const int64_t* begin_ts, const int64_t* end_ts, uint8_t* out,
                     size_t n, int64_t snapshot_ts, int64_t txn_id) {
    const int64_t own = -txn_id;
    for (size_t i = 0; i < n; i++) {
        const int64_t b = begin_ts[i], e = end_ts[i];
        bool ins = (b >= 0 && b <= snapshot_ts) || (txn_id != 0 && b == own);
        bool del = (e >= 0 && e <= snapshot_ts) || (txn_id != 0 && e == own);
        out[i] = (uint8_t)(ins && !del);
    }
}

// ---- bloom filter (runtime-filter plane; reference operator/util/bloomfilter) ----
// Standard 2-probe blocked layout: bits array of u64 words, nwords power of two.

void gx_bloom_build(const int64_t* keys, size_t n, uint64_t* words, size_t nwords) {
    const uint64_t mask = (uint64_t)nwords - 1;
    for (size_t i = 0; i < n; i++) {
        uint64_t h = mix64((uint64_t)keys[i]);
        uint64_t w1 = (h >> 6) & mask;
        uint64_t w2 = (h >> 38) & mask;
        words[w1] |= 1ULL << (h & 63);
        words[w2] |= 1ULL << ((h >> 32) & 63);
    }
}

void gx_bloom_query(const int64_t* keys, size_t n, const uint64_t* words,
                    size_t nwords, uint8_t* out) {
    const uint64_t mask = (uint64_t)nwords - 1;
    for (size_t i = 0; i < n; i++) {
        uint64_t h = mix64((uint64_t)keys[i]);
        uint64_t w1 = (h >> 6) & mask;
        uint64_t w2 = (h >> 38) & mask;
        bool hit = (words[w1] >> (h & 63)) & 1ULL;
        hit = hit && ((words[w2] >> ((h >> 32) & 63)) & 1ULL);
        out[i] = (uint8_t)hit;
    }
}

// ---- page checksum (persistence integrity; crc32c, software table) ----

static uint32_t crc_table[256];
static bool crc_init_done = false;

static void crc_init() {
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? (0x82F63B78u ^ (c >> 1)) : (c >> 1);
        crc_table[i] = c;
    }
    crc_init_done = true;
}

uint32_t gx_crc32c(const uint8_t* data, size_t n, uint32_t seed) {
    if (!crc_init_done) crc_init();
    uint32_t c = seed ^ 0xFFFFFFFFu;
    for (size_t i = 0; i < n; i++)
        c = crc_table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
    return c ^ 0xFFFFFFFFu;
}

// ---- delta + zigzag varint codec for int64 lanes (cold persistence pages) ----

static inline uint64_t zigzag(int64_t v) { return ((uint64_t)v << 1) ^ (uint64_t)(v >> 63); }
static inline int64_t unzigzag(uint64_t v) { return (int64_t)(v >> 1) ^ -(int64_t)(v & 1); }

// dst must have room for 10*n bytes; returns encoded size
size_t gx_encode_i64(const int64_t* src, size_t n, uint8_t* dst) {
    size_t o = 0;
    int64_t prev = 0;
    for (size_t i = 0; i < n; i++) {
        uint64_t v = zigzag(src[i] - prev);
        prev = src[i];
        while (v >= 0x80) { dst[o++] = (uint8_t)(v | 0x80); v >>= 7; }
        dst[o++] = (uint8_t)v;
    }
    return o;
}

size_t gx_decode_i64(const uint8_t* src, size_t nbytes, int64_t* dst, size_t n) {
    size_t o = 0, i = 0;
    int64_t prev = 0;
    while (i < n && o < nbytes) {
        uint64_t v = 0;
        int shift = 0;
        while (o < nbytes) {
            uint8_t b = src[o++];
            v |= (uint64_t)(b & 0x7F) << shift;
            if (!(b & 0x80)) break;
            shift += 7;
        }
        prev += unzigzag(v);
        dst[i++] = prev;
    }
    return i;
}

}  // extern "C"
