"""ctypes bindings for the native storage runtime (libgalaxystore).

Builds on demand with g++ if the shared library is missing (no pybind11 in the image —
plain C ABI + ctypes per the environment constraints).  Every entry point has a numpy
fallback so the engine runs without a compiler; `AVAILABLE` tells callers which path
is live.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libgalaxystore.so")
_SRC = os.path.join(_DIR, "galaxystore.cpp")

_lib: Optional[ctypes.CDLL] = None
_lock = threading.Lock()
AVAILABLE = False


def _build() -> bool:
    try:
        subprocess.run(["g++", "-O3", "-march=native", "-shared", "-fPIC",
                        "-o", _SO, _SRC], check=True, capture_output=True,
                       timeout=120)
        return True
    except Exception:
        return False


def _load():
    global _lib, AVAILABLE
    with _lock:
        if _lib is not None or AVAILABLE:
            return
        needs_build = not os.path.exists(_SO) or (
            os.path.exists(_SRC) and
            os.path.getmtime(_SRC) > os.path.getmtime(_SO))
        if needs_build and os.path.exists(_SRC):
            _build()
        if not os.path.exists(_SO):
            return
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return
        u64p = ctypes.POINTER(ctypes.c_uint64)
        i64p = ctypes.POINTER(ctypes.c_int64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        st = ctypes.c_size_t
        lib.gx_hash_partition.argtypes = [i64p, i32p, st, ctypes.c_int32]
        lib.gx_visible_mask.argtypes = [i64p, i64p, u8p, st, ctypes.c_int64,
                                        ctypes.c_int64]
        lib.gx_join_build.argtypes = [u64p, u8p, st, i32p, st, i32p]
        lib.gx_join_probe.argtypes = [u64p, u8p, st, u64p, i32p, st, i32p,
                                      i32p, i32p, st]
        lib.gx_join_probe.restype = st
        lib.gx_join_build_k1.argtypes = [i64p, u8p, st, i32p, st, i32p]
        lib.gx_join_probe_k1.argtypes = [i64p, u8p, st, i64p, i32p, st, i32p,
                                         i32p, i32p, st]
        lib.gx_join_probe_k1.restype = st
        lib.gx_join_probe_k1_idx.argtypes = [i64p, i32p, st, i64p, i32p, st,
                                             i32p, i32p, i32p, st]
        lib.gx_join_probe_k1_idx.restype = st
        lib.gx_hash_combine.argtypes = [u64p, i64p, u8p, st, ctypes.c_int32]
        lib.gx_bloom_build.argtypes = [i64p, st, u64p, st]
        lib.gx_bloom_query.argtypes = [i64p, st, u64p, st, u8p]
        lib.gx_crc32c.argtypes = [u8p, st, ctypes.c_uint32]
        lib.gx_crc32c.restype = ctypes.c_uint32
        lib.gx_encode_i64.argtypes = [i64p, st, u8p]
        lib.gx_encode_i64.restype = st
        lib.gx_decode_i64.argtypes = [u8p, st, i64p, st]
        lib.gx_decode_i64.restype = st
        _lib = lib
        AVAILABLE = True


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


_load()


# ---------------------------------------------------------------------------
# public API (native or numpy fallback)
# ---------------------------------------------------------------------------

def hash_partition(keys: np.ndarray, nparts: int) -> np.ndarray:
    keys = np.ascontiguousarray(keys, dtype=np.int64)
    if AVAILABLE and keys.size:
        out = np.empty(keys.size, dtype=np.int32)
        _lib.gx_hash_partition(_ptr(keys, ctypes.c_int64), _ptr(out, ctypes.c_int32),
                               keys.size, nparts)
        return out
    with np.errstate(over="ignore"):
        h = keys.astype(np.uint64)
        h ^= h >> np.uint64(33)
        h *= np.uint64(0xff51afd7ed558ccd)
        h ^= h >> np.uint64(33)
        h *= np.uint64(0xc4ceb9fe1a85ec53)
        h ^= h >> np.uint64(33)
    return (h % np.uint64(nparts)).astype(np.int32)


def visible_mask(begin_ts: np.ndarray, end_ts: np.ndarray, snapshot_ts: Optional[int],
                 txn_id: int) -> np.ndarray:
    begin_ts = np.ascontiguousarray(begin_ts, dtype=np.int64)
    end_ts = np.ascontiguousarray(end_ts, dtype=np.int64)
    n = begin_ts.shape[0]
    if AVAILABLE and n and snapshot_ts is not None:
        out = np.empty(n, dtype=np.uint8)
        _lib.gx_visible_mask(_ptr(begin_ts, ctypes.c_int64),
                             _ptr(end_ts, ctypes.c_int64),
                             _ptr(out, ctypes.c_uint8), n, snapshot_ts, txn_id)
        return out.view(np.bool_)
    # numpy fallback (also the snapshot_ts=None path)
    b, e = begin_ts, end_ts
    if snapshot_ts is None:
        ins = b >= 0
        dele = e != np.iinfo(np.int64).max
    else:
        ins = (b >= 0) & (b <= snapshot_ts)
        dele = (e >= 0) & (e <= snapshot_ts)
    if txn_id:
        ins = ins | (b == -txn_id)
        dele = dele | (e == -txn_id)
    return ins & ~dele


def bloom_build(keys: np.ndarray, nwords: int) -> np.ndarray:
    """nwords MUST be a power of two; returns the u64 word array."""
    keys = np.ascontiguousarray(keys, dtype=np.int64)
    words = np.zeros(nwords, dtype=np.uint64)
    if AVAILABLE and keys.size:
        _lib.gx_bloom_build(_ptr(keys, ctypes.c_int64), keys.size,
                            _ptr(words, ctypes.c_uint64), nwords)
        return words
    with np.errstate(over="ignore"):
        h = _mix_np(keys.astype(np.uint64))
    m = np.uint64(nwords - 1)
    w1 = (h >> np.uint64(6)) & m
    w2 = (h >> np.uint64(38)) & m
    np.bitwise_or.at(words, w1.astype(np.int64), np.uint64(1) << (h & np.uint64(63)))
    np.bitwise_or.at(words, w2.astype(np.int64),
                     np.uint64(1) << ((h >> np.uint64(32)) & np.uint64(63)))
    return words


def bloom_query(keys: np.ndarray, words: np.ndarray) -> np.ndarray:
    keys = np.ascontiguousarray(keys, dtype=np.int64)
    if AVAILABLE and keys.size:
        out = np.empty(keys.size, dtype=np.uint8)
        _lib.gx_bloom_query(_ptr(keys, ctypes.c_int64), keys.size,
                            _ptr(words, ctypes.c_uint64), words.size,
                            _ptr(out, ctypes.c_uint8))
        return out.view(np.bool_)
    with np.errstate(over="ignore"):
        h = _mix_np(keys.astype(np.uint64))
    m = np.uint64(words.size - 1)
    w1 = words[((h >> np.uint64(6)) & m).astype(np.int64)]
    w2 = words[((h >> np.uint64(38)) & m).astype(np.int64)]
    hit1 = (w1 >> (h & np.uint64(63))) & np.uint64(1)
    hit2 = (w2 >> ((h >> np.uint64(32)) & np.uint64(63))) & np.uint64(1)
    return (hit1 & hit2).astype(np.bool_)


def hash_combine(h: Optional[np.ndarray], lane: np.ndarray,
                 valid: Optional[np.ndarray]) -> np.ndarray:
    """Fold one key lane into the running combined hash — the host twin of
    kernels/relational.py::hash_columns (identical constants; the two must agree
    or nothing, since build and probe both hash here)."""
    lane = np.ascontiguousarray(lane, dtype=np.int64)
    n = lane.shape[0]
    first = h is None
    if first:
        h = np.empty(n, dtype=np.uint64)
    if AVAILABLE and n:
        v = None if valid is None else \
            np.ascontiguousarray(valid, dtype=np.uint8)
        _lib.gx_hash_combine(_ptr(h, ctypes.c_uint64),
                             _ptr(lane, ctypes.c_int64),
                             None if v is None else _ptr(v, ctypes.c_uint8),
                             n, 1 if first else 0)
        return h
    with np.errstate(over="ignore"):
        l = _mix_np(lane.astype(np.uint64))
        if valid is not None:
            l = np.where(valid, l, np.uint64(0xDEADBEEFCAFEBABE))
        if first:
            return l
        return _mix_np(h * np.uint64(31) + l + np.uint64(0x9E3779B97F4A7C15))


def _as_u8(mask: np.ndarray) -> np.ndarray:
    """bool mask -> uint8 lane, as a zero-copy view when already contiguous."""
    if mask.dtype == np.bool_ and mask.flags["C_CONTIGUOUS"]:
        return mask.view(np.uint8)
    return np.ascontiguousarray(mask, dtype=np.uint8)


def join_build(hashes: np.ndarray, live: np.ndarray):
    """Chained hash table over build hashes -> (heads, next, M)."""
    nb = hashes.shape[0]
    M = 1 << max(4, int(max(nb, 1) * 2 - 1).bit_length())
    heads = np.full(M, -1, dtype=np.int32)
    nxt = np.empty(max(nb, 1), dtype=np.int32)
    live8 = _as_u8(live)
    if AVAILABLE and nb:
        _lib.gx_join_build(_ptr(hashes, ctypes.c_uint64),
                           _ptr(live8, ctypes.c_uint8), nb,
                           _ptr(heads, ctypes.c_int32), M,
                           _ptr(nxt, ctypes.c_int32))
        return heads, nxt, M
    # fallback marker: heads=None, nxt = LIVE row ids in hash-sorted order
    ids = np.nonzero(np.asarray(live))[0]
    order = ids[np.argsort(hashes[ids], kind="stable")]
    return None, order, M


def join_probe(probe_hashes: np.ndarray, probe_live: np.ndarray,
               build_hashes: np.ndarray, table) -> tuple:
    """Candidate pairs (b_idx, p_idx) for every probe row whose 64-bit hash
    matches a build row's; exact-key verification is the caller's."""
    heads, nxt, M = table
    npr = probe_hashes.shape[0]
    live8 = _as_u8(probe_live)
    if AVAILABLE and heads is not None:
        # start at npr/4: selective joins rarely exceed it, and buffer
        # allocation is the dominant cost at large npr (a miss re-probes at
        # the now-exact size — one extra pass over the lanes, ~1ms/M rows)
        cap = max(int(npr) // 4, 1024)
        while True:
            out_b = np.empty(cap, dtype=np.int32)
            out_p = np.empty(cap, dtype=np.int32)
            total = _lib.gx_join_probe(
                _ptr(probe_hashes, ctypes.c_uint64),
                _ptr(live8, ctypes.c_uint8), npr,
                _ptr(build_hashes, ctypes.c_uint64),
                _ptr(heads, ctypes.c_int32), M,
                _ptr(nxt, ctypes.c_int32),
                _ptr(out_b, ctypes.c_int32), _ptr(out_p, ctypes.c_int32), cap)
            if total <= cap:
                return out_b[:total], out_p[:total]
            cap = int(total)
    # fallback: sort/searchsorted over the LIVE build hashes (see join_build)
    order = nxt  # live build row ids in hash order
    sh = build_hashes[order]
    lo = np.searchsorted(sh, probe_hashes, side="left")
    hi = np.searchsorted(sh, probe_hashes, side="right")
    counts = np.where(probe_live, hi - lo, 0).astype(np.int64)
    total = int(counts.sum())
    p_of = np.repeat(np.arange(npr, dtype=np.int32), counts)
    offs = np.concatenate([[0], np.cumsum(counts)])[:-1]
    k = np.arange(total, dtype=np.int64) - np.repeat(offs, counts)
    b_of = order[(np.repeat(lo, counts) + k).astype(np.int64)].astype(np.int32)
    return b_of, p_of


def join_build_k1(keys: np.ndarray, live: np.ndarray):
    """Single-int64-key chained table; matching compares keys exactly (no
    verification pass needed).  Returns (keys, heads, next, M)."""
    keys = np.ascontiguousarray(keys, dtype=np.int64)
    nb = keys.shape[0]
    M = 1 << max(4, int(max(nb, 1) * 2 - 1).bit_length())
    heads = np.full(M, -1, dtype=np.int32)
    nxt = np.empty(max(nb, 1), dtype=np.int32)
    live8 = _as_u8(live)
    if AVAILABLE and nb:
        _lib.gx_join_build_k1(_ptr(keys, ctypes.c_int64),
                              _ptr(live8, ctypes.c_uint8), nb,
                              _ptr(heads, ctypes.c_int32), M,
                              _ptr(nxt, ctypes.c_int32))
        return keys, heads, nxt, M
    # fallback marker: heads=None, nxt = LIVE row ids in key-sorted order
    ids = np.nonzero(np.asarray(live))[0]
    order = ids[np.argsort(keys[ids], kind="stable")]
    return keys, None, order, M


def join_probe_k1(probe_keys: np.ndarray, probe_live: np.ndarray,
                  table) -> tuple:
    """Exact (b_idx, p_idx) pairs for a single-int64-key join."""
    build_keys, heads, nxt, M = table
    probe_keys = np.ascontiguousarray(probe_keys, dtype=np.int64)
    npr = probe_keys.shape[0]
    if AVAILABLE and heads is not None:
        n_live = int(np.count_nonzero(probe_live))
        sparse = n_live * 2 < npr
        if sparse:
            # sparse live mask: random-pattern `if (!live)` branches mispredict
            # in the scalar loop; collect ids vectorized, probe dense
            ids = np.nonzero(probe_live)[0].astype(np.int32)
        else:
            live8 = _as_u8(probe_live)
        cap = max(n_live, 1024)
        while True:
            out_b = np.empty(cap, dtype=np.int32)
            out_p = np.empty(cap, dtype=np.int32)
            if sparse:
                total = _lib.gx_join_probe_k1_idx(
                    _ptr(probe_keys, ctypes.c_int64),
                    _ptr(ids, ctypes.c_int32), ids.size,
                    _ptr(build_keys, ctypes.c_int64),
                    _ptr(heads, ctypes.c_int32), M,
                    _ptr(nxt, ctypes.c_int32),
                    _ptr(out_b, ctypes.c_int32),
                    _ptr(out_p, ctypes.c_int32), cap)
            else:
                total = _lib.gx_join_probe_k1(
                    _ptr(probe_keys, ctypes.c_int64),
                    _ptr(live8, ctypes.c_uint8), npr,
                    _ptr(build_keys, ctypes.c_int64),
                    _ptr(heads, ctypes.c_int32), M,
                    _ptr(nxt, ctypes.c_int32),
                    _ptr(out_b, ctypes.c_int32),
                    _ptr(out_p, ctypes.c_int32), cap)
            if total <= cap:
                return out_b[:total], out_p[:total]
            cap = int(total)
    # numpy fallback: sorted live build keys + searchsorted expansion (exact)
    order = nxt  # live build row ids in key order (see join_build_k1)
    sk = build_keys[order]
    lo = np.searchsorted(sk, probe_keys, side="left")
    hi = np.searchsorted(sk, probe_keys, side="right")
    counts = np.where(probe_live, hi - lo, 0).astype(np.int64)
    total = int(counts.sum())
    p_of = np.repeat(np.arange(npr, dtype=np.int32), counts)
    offs = np.concatenate([[0], np.cumsum(counts)])[:-1]
    k = np.arange(total, dtype=np.int64) - np.repeat(offs, counts)
    b_of = order[(np.repeat(lo, counts) + k).astype(np.int64)].astype(np.int32)
    return b_of, p_of


def crc32c(data: bytes, seed: int = 0) -> int:
    if AVAILABLE:
        buf = np.frombuffer(data, dtype=np.uint8)
        if buf.size:
            return int(_lib.gx_crc32c(_ptr(buf, ctypes.c_uint8), buf.size, seed))
    import zlib
    return zlib.crc32(data, seed) & 0xFFFFFFFF  # fallback: crc32 (not castagnoli)


def encode_i64(values: np.ndarray) -> bytes:
    """Explicit one-byte format tag: b'V' = delta varint, b'R' = raw little-endian
    (a length heuristic would be ambiguous with legitimate varint streams)."""
    values = np.ascontiguousarray(values, dtype=np.int64)
    if AVAILABLE and values.size:
        out = np.empty(values.size * 10, dtype=np.uint8)
        n = _lib.gx_encode_i64(_ptr(values, ctypes.c_int64), values.size,
                               _ptr(out, ctypes.c_uint8))
        return b"V" + out[:n].tobytes()
    return b"R" + values.tobytes()


def decode_i64(data: bytes, n: int) -> np.ndarray:
    tag, body = data[:1], data[1:]
    if tag == b"R":
        return np.frombuffer(body, dtype=np.int64).copy()
    if tag != b"V":
        raise ValueError(f"unknown lane encoding tag {tag!r}")
    buf = np.frombuffer(body, dtype=np.uint8)
    out = np.empty(n, dtype=np.int64)
    if AVAILABLE:
        got = _lib.gx_decode_i64(_ptr(buf, ctypes.c_uint8), buf.size,
                                 _ptr(out, ctypes.c_int64), n)
        return out[:got]
    raise RuntimeError("varint-coded lane requires the native library")


def _mix_np(h):
    h = h ^ (h >> np.uint64(33))
    h = h * np.uint64(0xff51afd7ed558ccd)
    h = h ^ (h >> np.uint64(33))
    h = h * np.uint64(0xc4ceb9fe1a85ec53)
    h = h ^ (h >> np.uint64(33))
    return h
