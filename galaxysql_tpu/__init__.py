"""galaxysql_tpu: a TPU-native distributed SQL engine (PolarDB-X CN capabilities,
re-designed for JAX/XLA — see SURVEY.md for the blueprint)."""

import os


def _ensure_platforms():
    """Allow a CPU backend beside the accelerator (TP queries run host-side).

    Must run before JAX initializes its backends.  When JAX_PLATFORMS pins a single
    accelerator platform (e.g. 'axon'), extend it with 'cpu'."""
    plats = os.environ.get("JAX_PLATFORMS", "")
    if plats and "cpu" not in plats.split(","):
        os.environ["JAX_PLATFORMS"] = plats + ",cpu"


_ensure_platforms()
