"""Pallas multi-key hash-agg placement kernel (the TPU tier under `hash_groupby`).

The reference formulation in `kernels/relational.py` assigns group ids with a
vectorized round loop: each round, unresolved rows probing an EMPTY slot elect
an owner by scatter-min on row index, then every row verifies its identity
lanes against the owner's.  On TPU that scatter serializes; this kernel walks
the same open-addressing schedule as an explicit in-VMEM loop instead — the
`AggOpenHashMap` insert loop (SURVEY.md §3.3) expressed as a Pallas program.

Exact equivalence to the reference round (proved by the `kernel` marker suite,
bit-for-bit): one round here is two sequential passes over the rows —

- pass 1 (elect): ascending row order, an unresolved row probing a slot that
  was empty AT ROUND START claims it first-write-wins.  First-write-wins in
  ascending order IS scatter-min on row index, and the round-start snapshot
  (`occ`) reproduces the reference's "occupied" read-before-scatter.
- pass 2 (adopt): every unresolved row compares its identity lanes (data AND
  valid) against the slot owner elected above; matches adopt the slot as gid.

A fully sequential insert loop (no round structure) would NOT be equivalent —
a row can win a later-probe slot the reference reserves for a later round —
hence the two-pass round shape.  Rounds past convergence are identity in the
reference (every candidate is sentinel), so running the static `max_rounds`
gated on an unresolved counter matches the reference's early-exit while_loop.

The `pl.pallas_call` is constructed inside a `global_jit` builder (galaxylint
`pallas-raw`): the kernel object is cached per static shape and the call
traces into the enclosing operator program, so zero-steady-retrace discipline
and the overflow ladder (placement failure -> doubled capacity) are unchanged.

TPU note: the probe math is uint64 (bit-identical with `hash_columns`); Mosaic
int64 support is limited on older TPU generations — 32-bit limb emulation of
the `(s0 + r*step) & (M-1)` walk is the known follow-up (the masked stride is
exact in uint32 because M divides 2^32).  Off-TPU backends run interpret mode,
which is what the CPU correctness matrix exercises.
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from galaxysql_tpu.exec import operators as ops


def _interpret() -> bool:
    """Mosaic lowering only on real TPU; everywhere else the kernel runs in
    interpret mode (reference-exact, slow — gated behind the selector)."""
    return jax.default_backend() != "tpu"


def _make_place_kernel(n: int, M: int, max_rounds: int,
                       has_valid: Tuple[bool, ...]):
    sentinel = np.int32(n)
    mask = np.uint64(M - 1)
    k = len(has_valid)

    def kernel(*refs):
        live_ref, s0_ref, step_ref = refs[0], refs[1], refs[2]
        d_refs, v_refs = [], []
        pos = 3
        for hv in has_valid:
            d_refs.append(refs[pos])
            pos += 1
            v_refs.append(refs[pos] if hv else None)
            pos += 1 if hv else 0
        rep_ref, resolved_ref, gid_ref, occ_ref, unres_ref = refs[pos:pos + 5]

        rep_ref[...] = jnp.full((M,), sentinel, jnp.int32)
        resolved_ref[...] = jnp.where(live_ref[...],
                                      jnp.int8(0), jnp.int8(1))
        gid_ref[...] = jnp.zeros((n,), jnp.int32)
        unres_ref[0] = jnp.sum(live_ref[...]).astype(jnp.int32)

        def slot_of(i, ru):
            return ((s0_ref[i] + ru * step_ref[i]) & mask).astype(jnp.int32)

        def round_body(r, carry):
            @pl.when(unres_ref[0] > 0)
            def _round():
                ru = r.astype(jnp.uint64)
                # round-start occupancy snapshot: rows probing a slot claimed
                # EARLIER THIS ROUND must still bid (and lose to the smaller
                # row id), exactly like the reference's pre-scatter read
                occ_ref[...] = (rep_ref[...] != sentinel).astype(jnp.int8)

                def elect(i, c):
                    @pl.when(resolved_ref[i] == 0)
                    def _():
                        s = slot_of(i, ru)

                        @pl.when((occ_ref[s] == 0) &
                                 (rep_ref[s] == sentinel))
                        def _():
                            rep_ref[s] = i.astype(jnp.int32)
                    return c

                jax.lax.fori_loop(0, n, elect, 0)

                def adopt(i, c):
                    @pl.when(resolved_ref[i] == 0)
                    def _():
                        s = slot_of(i, ru)
                        owner = rep_ref[s]
                        safe = jnp.clip(owner, 0, max(n - 1, 0))
                        same = owner != sentinel
                        for d_ref, v_ref in zip(d_refs, v_refs):
                            same = same & (d_ref[safe] == d_ref[i])
                            if v_ref is not None:
                                same = same & (v_ref[safe] == v_ref[i])

                        @pl.when(same)
                        def _():
                            resolved_ref[i] = jnp.int8(1)
                            gid_ref[i] = s
                            unres_ref[0] = unres_ref[0] - 1
                    return c

                jax.lax.fori_loop(0, n, adopt, 0)
            return carry

        jax.lax.fori_loop(0, max_rounds, round_body, 0)

    return kernel


def hash_place(ident: Sequence[Tuple[Any, Any]], live: Any, s0: Any,
               step: Any, M: int, max_rounds: int):
    """Slot placement for `hash_groupby`: (rep, resolved, gid), bit-identical
    to the reference scatter-min round loop.  `ident` are the canonicalized
    identity lanes (`_ident_lanes`), `s0`/`step` the masked uint64 probe walk."""
    n = int(live.shape[0])
    has_valid = tuple(v is not None for _, v in ident)
    dts = tuple(str(d.dtype) for d, _ in ident)
    interp = _interpret()
    key = ("pallas_agg_place", n, M, max_rounds, has_valid, dts, interp)

    def build():
        kernel = _make_place_kernel(n, M, max_rounds, has_valid)
        out_shape = (
            jax.ShapeDtypeStruct((M,), jnp.int32),   # rep: slot owner row
            jax.ShapeDtypeStruct((n,), jnp.int8),    # resolved (bool as i8)
            jax.ShapeDtypeStruct((n,), jnp.int32),   # gid
            jax.ShapeDtypeStruct((M,), jnp.int8),    # occ round snapshot
            jax.ShapeDtypeStruct((1,), jnp.int32),   # unresolved counter
        )
        return pl.pallas_call(kernel, out_shape=out_shape, interpret=interp)

    call = ops.global_jit(key, build)
    args = [live, s0, step]
    for (d, v), hv in zip(ident, has_valid):
        args.append(d)
        if hv:
            args.append(v)
    rep, resolved8, gid, _occ, _unres = call(*args)
    return rep, resolved8.astype(jnp.bool_), gid
