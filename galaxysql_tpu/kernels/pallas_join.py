"""Pallas hash-join kernels: slot hashing (build + probe) and CSR expansion.

Three pieces of the CSR join pipeline (`hash_join_build_slots` ->
`hash_join_probe_csr`) move into Pallas here; the surrounding XLA gather /
verify / segment arithmetic is already TPU-shaped and stays in
`kernels/relational.py`:

- `build_slots`: the chained-hash BUILD kernel — per build row, the full
  `hash_columns` mix (SplitMix64 avalanche per lane, NULL tag, 31x combine)
  masked to `M` slots, with dead rows parked at slot `M` so the CSR
  segment-sum drops them.  Emits exactly the slot vector the reference emits.
- `hash_slots`: the same mix for PROBE rows (no liveness masking — the
  reference handles probe liveness in the count step).
- `expand_offsets`: the probe-side pair expansion — the reference's
  scatter-max-at-segment-starts followed by a cummax becomes an explicit
  in-VMEM scatter loop plus a running-max sweep.  Equivalence: first-write at
  each segment start with `jnp.maximum` IS `.at[].max`, the `(count>0) &
  (start<cap)` guard IS `mode="drop"` with the count-0 rows parked at `cap`,
  and the sweep IS `lax.cummax`.

All `pl.pallas_call`s are constructed inside `global_jit` builders (galaxylint
`pallas-raw`) and trace into the enclosing operator program: retrace keys,
the probe-capacity overflow ladder, and hybrid hot/cold splitting are
untouched.  Off-TPU these run in interpret mode (bit-exact; the CPU `kernel`
matrix drives them with `KERNEL(PALLAS)`), and uint64 in-kernel math shares
the Mosaic caveat noted in `pallas_agg` for older TPU generations.
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from galaxysql_tpu.exec import operators as ops
from galaxysql_tpu.kernels.relational import _GOLDEN, _M1, _M2

_NULL_TAG = np.uint64(0xDEADBEEFCAFEBABE)
_THIRTYONE = np.uint64(31)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _mix64_v(h):
    """SplitMix64 avalanche, vectorized over a whole lane inside the kernel —
    same constants, same shift schedule as `relational._mix64`."""
    h = h ^ (h >> np.uint64(33))
    h = h * _M1
    h = h ^ (h >> np.uint64(33))
    h = h * _M2
    h = h ^ (h >> np.uint64(33))
    return h


def _make_slots_kernel(M: int, has_valid: Tuple[bool, ...], masked: bool):
    """Combined-hash slot kernel.  `masked`: build variant — takes a leading
    live lane and parks dead rows at slot M (the CSR drop segment)."""
    mask = np.uint64(M - 1)

    def kernel(*refs):
        pos = 0
        live_ref = None
        if masked:
            live_ref = refs[pos]
            pos += 1
        d_refs, v_refs = [], []
        for hv in has_valid:
            d_refs.append(refs[pos])
            pos += 1
            v_refs.append(refs[pos] if hv else None)
            pos += 1 if hv else 0
        out_ref = refs[pos]

        h = None
        for d_ref, v_ref in zip(d_refs, v_refs):
            lane = _mix64_v(d_ref[...].astype(jnp.uint64))
            if v_ref is not None:
                lane = jnp.where(v_ref[...], lane, _NULL_TAG)
            if h is None:
                h = lane
            else:
                h = _mix64_v(h * _THIRTYONE + lane + _GOLDEN)
        s = (h & mask).astype(jnp.int32)
        if masked:
            s = jnp.where(live_ref[...], s, jnp.int32(M))
        out_ref[...] = s

    return kernel


def _slots_call(keys: Sequence[Tuple[Any, Any]], live, M: int, tag: str):
    n = int(keys[0][0].shape[0])
    has_valid = tuple(v is not None for _, v in keys)
    dts = tuple(str(d.dtype) for d, _ in keys)
    masked = live is not None
    interp = _interpret()
    key = ("pallas_join_slots", tag, n, M, has_valid, dts, masked, interp)

    def build():
        kernel = _make_slots_kernel(M, has_valid, masked)
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n,), jnp.int32),
            interpret=interp,
        )

    call = ops.global_jit(key, build)
    args = []
    if masked:
        args.append(live)
    for (d, v), hv in zip(keys, has_valid):
        args.append(d)
        if hv:
            args.append(v)
    return call(*args)


def build_slots(build_keys: Sequence[Tuple[Any, Any]], b_live, M: int):
    """Build-side slot vector: `(hash_columns(keys) & (M-1)) | dead->M`,
    bit-identical with the reference `hash_join_build_slots` body."""
    return _slots_call(build_keys, b_live, M, "build")


def hash_slots(probe_keys: Sequence[Tuple[Any, Any]], M: int):
    """Probe-side slot vector (unmasked): `hash_columns(keys) & (M-1)`."""
    return _slots_call(probe_keys, None, M, "probe")


def _make_expand_kernel(npr: int, cap: int):
    def kernel(counts_ref, starts_ref, p_of_ref):
        p_of_ref[...] = jnp.zeros((cap,), jnp.int32)

        def scat(i, c):
            # (count>0) & (start<cap) reproduces the reference's
            # `.at[scatter_at].max(..., mode="drop")`: count-0 rows are
            # parked at cap there, and overflow starts land out of bounds
            @pl.when((counts_ref[i] > 0) & (starts_ref[i] < cap))
            def _():
                s = starts_ref[i]
                prev = p_of_ref[s]
                p_of_ref[s] = jnp.maximum(prev, i.astype(jnp.int32))
            return c

        jax.lax.fori_loop(0, npr, scat, 0)

        def sweep(j, run):
            run = jnp.maximum(run, p_of_ref[j])
            p_of_ref[j] = run
            return run

        jax.lax.fori_loop(0, cap, sweep, jnp.int32(0))

    return kernel


def expand_offsets(counts, starts, cap: int):
    """Probe->pair owner map: for pair slot j, the probe row whose [start,
    start+count) segment covers j.  Matches the reference scatter-max +
    `lax.cummax` expansion bit-for-bit."""
    npr = int(counts.shape[0])
    interp = _interpret()
    key = ("pallas_join_expand", npr, cap, interp)

    def build():
        kernel = _make_expand_kernel(npr, cap)
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((cap,), jnp.int32),
            interpret=interp,
        )

    call = ops.global_jit(key, build)
    return call(counts, starts)
