"""Core relational kernels over fixed-shape device arrays.

These replace the reference's operator hot loops (SURVEY.md §3.3: hash-table build/probe in
`ParallelHashJoinExec.java:131-226`, agg-map updates in `AggOpenHashMap`, sorts) with
TPU-friendly primitives:

- **group-by = lexicographic sort + segmented reduction.**  No pointer-chasing hash map: rows
  are lexsorted on the key lanes (exact — dictionary codes make string keys integer), group
  boundaries are detected by comparing adjacent rows, and aggregates are `jax.ops.segment_*`
  reductions.  The reference's sort-based fallback for huge-NDV aggs (`SpillableAggHashMap`)
  is here the *primary* strategy because sort is what the hardware does well.
- **hash join = hash + sort + searchsorted probe.**  The build side is sorted by a 64-bit key
  hash; probes binary-search the sorted hash lane; every candidate pair is then verified
  against the actual key columns, so hash collisions cost duplicates-filtered work, never
  correctness.  This is the flat-array open-addressing idea of `ConcurrentRawHashTable`
  (Appendix A) re-expressed without scatter contention.

All kernels are fixed-shape: output capacity is a static argument and kernels report
`overflow` so the host can re-bucket and retry (the dynamic-shape escape hatch, SURVEY.md
§7.3).  Dead rows are carried via `live` masks, never compacted implicitly.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Any, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# ---------------------------------------------------------------------------
# hashing
# ---------------------------------------------------------------------------

_M1 = np.uint64(0xff51afd7ed558ccd)
_M2 = np.uint64(0xc4ceb9fe1a85ec53)
_GOLDEN = np.uint64(0x9e3779b97f4a7c15)


def _mix64(h):
    h = h ^ (h >> 33)
    h = h * _M1
    h = h ^ (h >> 33)
    h = h * _M2
    h = h ^ (h >> 33)
    return h


def hash_columns(cols: Sequence[Tuple[Any, Optional[Any]]]) -> Any:
    """Combine key columns (data, valid) into one uint64 hash lane.

    NULL contributes a distinct tag so NULL keys group together but a verify pass still
    decides join-match semantics (SQL: NULL never equals NULL in joins).
    """
    h = None
    for data, valid in cols:
        lane = _mix64(data.astype(jnp.uint64))
        if valid is not None:
            lane = jnp.where(valid, lane, jnp.uint64(0xdeadbeefcafebabe))
        h = lane if h is None else _mix64(h * np.uint64(31) + lane + _GOLDEN)
    assert h is not None
    return h


# ---------------------------------------------------------------------------
# kernel-tier selector: Pallas vs reference formulation
# ---------------------------------------------------------------------------
# The hatch trio, outermost wins:  GALAXYSQL_PALLAS=0 env kills the tier for
# the whole process; the ENABLE_PALLAS_KERNELS param (via `exec_kernel_mode`)
# gates it per instance/session; the KERNEL(OFF|PALLAS|ON) hint per statement.
# Selection happens at TRACE time (row counts are static shapes), so the mode
# must ride the `global_jit` key (`kernel_selector_key`) — a flipped hint is a
# DIFFERENT program, not a silent reuse of the wrong formulation.

_PALLAS_ENV_OFF = os.environ.get("GALAXYSQL_PALLAS", "1") == "0"

# stats-driven row floor for auto mode: below this the fixed kernel-launch
# overhead beats any VMEM-locality win, so small batches keep the reference
# formulation (which is also the correctness oracle and the only CPU path)
PALLAS_MIN_ROWS = 65536

# trace-time selection counters — the dispatch-count guards in the `kernel`
# test matrix prove a gated-off selector never even CONSIDERED Pallas for a
# traced program (structurally off-path, not merely numerically equal)
KERNEL_STATS = {"pallas": 0, "reference": 0}

_KERNEL_TLS = threading.local()


def kernel_mode() -> str:
    """Current thread's selector mode: 'auto' | 'off' | 'pallas'."""
    return getattr(_KERNEL_TLS, "mode", "auto")


@contextlib.contextmanager
def kernel_scope(mode: str):
    """Scope the selector mode for one statement (thread-local: concurrent
    sessions pick their own formulation without racing)."""
    prev = getattr(_KERNEL_TLS, "mode", "auto")
    _KERNEL_TLS.mode = mode
    try:
        yield
    finally:
        _KERNEL_TLS.mode = prev


def kernel_selector_key() -> str:
    """Token for `global_jit` keys of programs that trace through the
    selector (join/agg operator and MPP programs)."""
    return "k=" + kernel_mode()


_PALLAS_OK: Optional[bool] = None


def _pallas_ok() -> bool:
    """Import gate: jax.experimental.pallas may be absent or broken in a
    stripped runtime — the tier then degrades to the reference formulation
    instead of erroring (checked once, cached)."""
    global _PALLAS_OK
    if _PALLAS_OK is None:
        try:
            from galaxysql_tpu.kernels import pallas_agg  # noqa: F401
            from galaxysql_tpu.kernels import pallas_join  # noqa: F401
            _PALLAS_OK = True
        except Exception:
            _PALLAS_OK = False
    return _PALLAS_OK


def use_pallas(n: int) -> bool:
    """Trace-time formulation choice for one kernel call site (`n` is the
    static row count of the array the kernel sweeps)."""
    mode = kernel_mode()
    if _PALLAS_ENV_OFF or mode == "off" or not _pallas_ok():
        KERNEL_STATS["reference"] += 1
        return False
    if mode == "pallas":
        KERNEL_STATS["pallas"] += 1
        return True
    hit = jax.default_backend() == "tpu" and n >= PALLAS_MIN_ROWS
    KERNEL_STATS["pallas" if hit else "reference"] += 1
    return hit


def exec_kernel_mode(hints, instance, session_overlay=None) -> str:
    """Resolve the selector mode for one statement: KERNEL hint beats the
    ENABLE_PALLAS_KERNELS param (session > instance > default); the env hatch
    is enforced inside `use_pallas` and beats everything.  KERNEL(PALLAS)
    forces the Pallas tier below the auto row floor; KERNEL(ON) restores
    auto selection under a disabling param."""
    h = (hints or {}).get("kernel")
    if h == "off":
        return "off"
    if h == "pallas":
        return "pallas"
    if h == "on":
        return "auto"
    if instance is not None and getattr(instance, "config", None) is not None:
        if not instance.config.get("ENABLE_PALLAS_KERNELS", session_overlay):
            return "off"
    return "auto"


# ---------------------------------------------------------------------------
# group-by
# ---------------------------------------------------------------------------

class AggSpec(NamedTuple):
    kind: str  # 'sum' | 'count' | 'count_star' | 'min' | 'max' | 'sum_float'
    # operand index into the inputs list (-1 for count_star)
    arg: int


class GroupByResult(NamedTuple):
    keys: Tuple[Tuple[Any, Any], ...]  # per key: (data [max_groups], valid-or-None)
    aggs: Tuple[Tuple[Any, Any], ...]  # per agg: (data [max_groups], valid-or-None)
    live: Any                      # [max_groups] bool — which output slots are real groups
    num_groups: Any                # scalar int32
    overflow: Any                  # scalar bool


def sort_groupby(keys: Sequence[Tuple[Any, Optional[Any]]],
                 inputs: Sequence[Tuple[Any, Optional[Any]]],
                 specs: Sequence[AggSpec],
                 live: Any,
                 max_groups: int) -> GroupByResult:
    """Grouped aggregation.  `keys`/`inputs` are (data, valid) lanes of equal length n.

    TPU note: after the lexsort, groups are CONTIGUOUS runs, so every reduction is a
    cumulative scan + gathers at run boundaries.  No `segment_sum`/scatter anywhere —
    XLA scatters serialize on TPU and were measured 1000x slower than this formulation.
    """
    n = keys[0][0].shape[0] if keys else live.shape[0]
    dead = ~live

    # null flag participates in grouping (SQL GROUP BY: NULLs form one group)
    key_lanes: List[Any] = []
    for data, valid in keys:
        if valid is not None:
            key_lanes.append(~valid)
            key_lanes.append(jnp.where(valid, data, jnp.zeros_like(data)))
        else:
            key_lanes.append(data)

    # lexsort: last key is primary => (minor..major); dead rows pushed to the end
    order = jnp.lexsort(tuple(reversed([dead.astype(jnp.int8)] + key_lanes))) \
        if key_lanes else jnp.argsort(dead.astype(jnp.int8), stable=True)
    live_s = live[order]
    sorted_lanes = [k[order] for k in key_lanes]

    if sorted_lanes:
        prev_differs = jnp.zeros(n, dtype=jnp.bool_)
        for lane in sorted_lanes:
            prev_differs = prev_differs | jnp.concatenate(
                [jnp.ones(1, dtype=jnp.bool_), lane[1:] != lane[:-1]])
        new_group = prev_differs & live_s
        new_group = new_group.at[0].set(live_s[0])
    else:
        new_group = jnp.zeros(n, dtype=jnp.bool_).at[0].set(live_s[0])

    num_groups = jnp.sum(new_group.astype(jnp.int32))
    overflow = num_groups > max_groups

    # run starts: positions of new_group, padded with n (a virtual end sentinel)
    (starts_raw,) = jnp.nonzero(new_group, size=max_groups + 1, fill_value=n)
    starts = starts_raw[:max_groups]                # [G] start row of group g
    ends = starts_raw[1:max_groups + 1]             # [G] start of the next group
    # dead rows sort to the end, so group g covers sorted rows [starts[g], ends[g]);
    # the LAST live group's end is the count of live rows, not n
    n_live = jnp.sum(live_s.astype(jnp.int32))
    ends = jnp.minimum(ends, n_live)
    gvalid = starts < n_live                               # real group slots
    starts_c = jnp.clip(starts, 0, max(n - 1, 0))

    def run_reduce_sum(masked):
        c = jnp.cumsum(masked, axis=0)
        c0 = jnp.concatenate([jnp.zeros(1, dtype=c.dtype), c])
        return c0[ends] - c0[starts_c]

    out_keys = []
    out_key_valid = []
    for data, valid in keys:
        out_keys.append(data[order][starts_c])
        out_key_valid.append(None if valid is None else valid[order][starts_c])

    out_aggs: List[Tuple[Any, Any]] = []
    for spec in specs:
        if spec.kind == "count_star":
            cnt = run_reduce_sum(live_s.astype(jnp.int64))
            out_aggs.append((cnt, None))
            continue
        data, valid = inputs[spec.arg]
        d_s = data[order]
        v_s = valid[order] if valid is not None else None
        present = live_s if v_s is None else (live_s & v_s)
        if spec.kind == "count":
            out_aggs.append((run_reduce_sum(present.astype(jnp.int64)), None))
        elif spec.kind in ("sum", "sum_float"):
            if jnp.issubdtype(d_s.dtype, jnp.floating):
                masked = jnp.where(present, d_s, jnp.zeros((), dtype=d_s.dtype))
            else:
                masked = jnp.where(present, d_s.astype(jnp.int64), 0)
            s = run_reduce_sum(masked)
            nonempty = run_reduce_sum(present.astype(jnp.int32)) > 0
            out_aggs.append((s, nonempty))
        elif spec.kind in ("min", "max"):
            if jnp.issubdtype(d_s.dtype, jnp.floating):
                neutral = jnp.array(np.inf if spec.kind == "min" else -np.inf,
                                    d_s.dtype)
            else:
                info = jnp.iinfo(d_s.dtype)
                neutral = jnp.array(info.max if spec.kind == "min" else info.min,
                                    d_s.dtype)
            masked = jnp.where(present, d_s, neutral)
            # segmented running min/max restarting at each run boundary; the last
            # element of each run then holds the run's reduction
            m = _segmented_scan(masked, new_group, spec.kind == "min")
            last = jnp.clip(ends - 1, 0, max(n - 1, 0))
            nonempty = run_reduce_sum(present.astype(jnp.int32)) > 0
            out_aggs.append((m[last], nonempty))
        else:
            raise ValueError(f"unknown agg kind {spec.kind}")

    out_live = gvalid & (jnp.arange(max_groups, dtype=jnp.int32) <
                         jnp.minimum(num_groups, max_groups))
    return GroupByResult(tuple(zip(out_keys, out_key_valid)), tuple(out_aggs), out_live,
                         jnp.minimum(num_groups, max_groups).astype(jnp.int32), overflow)


def matmul_groupby(keys: Sequence[Tuple[Any, Optional[Any]]],
                   inputs: Sequence[Tuple[Any, Optional[Any]]],
                   specs: Sequence[AggSpec],
                   live: Any,
                   domains: Sequence[int]) -> GroupByResult:
    """Small-domain grouped aggregation on the MXU: one-hot int8 matmul, no sort.

    When every group key has a statically known small domain (dictionary-encoded
    strings, booleans), the group id enumerates the full key cross product and the
    aggregation becomes `A^T @ onehot(gid)` — an int8 x int8 -> int32 matmul that
    runs on the MXU systolic array instead of the O(n log n) lexsort of
    `sort_groupby` (reference seam: `HashAggExec.java:37` + `AggOpenHashMap`).

    Exact int64 sums via byte-limb decomposition: each 64-bit value contributes 8
    bias-corrected byte lanes (byte - 128 fits int8); per-group limb sums are
    recombined with shifts mod 2**64, so two's-complement wraparound reproduces
    int64 arithmetic exactly.  min/max use masked reductions over the (tiny)
    domain.  Floats are NOT supported for sum (caller falls back to sort_groupby).

    Output slots enumerate the domain in (major key .. minor key) order with NULL
    sorting last — the same group order sort_groupby produces — but live groups
    are NOT compacted to a prefix; `live` marks the non-empty slots.  `overflow`
    is always False (capacity is the static domain).
    """
    n = live.shape[0]
    gid, sizes, D = _domain_gid(keys, domains, n)

    # lane plan: [ones] + [present per distinct input] + [8 limbs per sum input]
    present_lane: dict = {}
    present_of: List[Any] = []
    for spec in specs:
        if spec.arg >= 0 and spec.arg not in present_lane:
            dta, val = inputs[spec.arg]
            present_lane[spec.arg] = len(present_of)
            present_of.append(live if val is None else (live & val))
    sum_args = sorted({s.arg for s in specs if s.kind in ("sum",) and s.arg >= 0})
    lanes: List[Any] = [live.astype(jnp.int8)]
    for a in present_of:
        lanes.append(a.astype(jnp.int8))
    limb_base: dict = {}
    for a in sum_args:
        dta, val = inputs[a]
        pres = present_of[present_lane[a]]
        v = jnp.where(pres, dta.astype(jnp.int64), jnp.int64(0))
        limb_base[a] = len(lanes)
        for j in range(8):
            byte = ((v >> jnp.int64(8 * j)) & jnp.int64(0xFF)).astype(jnp.int32)
            lanes.append((byte - 128).astype(jnp.int8))
    A = jnp.stack(lanes, axis=1)  # [n, L] int8

    # blocked contraction: int32 accumulators stay exact while n_chunk*127 < 2^31
    CHUNK = 4_000_000
    acc = jnp.zeros((A.shape[1], D), dtype=jnp.int64)
    for s0 in range(0, max(n, 1), CHUNK):
        s1 = min(s0 + CHUNK, n)
        if s1 <= s0:
            break
        oh = (gid[s0:s1, None] == jnp.arange(D, dtype=jnp.int32)[None, :])
        oh = (oh & live[s0:s1, None]).astype(jnp.int8)
        part = jax.lax.dot_general(
            A[s0:s1], oh, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        acc = acc + part.astype(jnp.int64)

    # ones/present lanes were appended as raw 0/1 int8 (no bias): no correction
    live_cnt = acc[0]
    out_live = live_cnt > 0
    num_groups = jnp.sum(out_live.astype(jnp.int32))

    def decode_sum(a: int) -> Any:
        base = limb_base[a]
        total = jnp.zeros(D, dtype=jnp.int64)
        for j in range(8):
            byte_sum = acc[base + j] + 128 * live_cnt
            total = total + (byte_sum << jnp.int64(8 * j))
        return total

    # output key lanes decode the slot index back into per-key codes
    idx = jnp.arange(D, dtype=jnp.int32)
    out_keys = _domain_out_keys(keys, domains, sizes, D)

    out_aggs: List[Tuple[Any, Any]] = []
    for spec in specs:
        if spec.kind == "count_star":
            out_aggs.append((live_cnt.astype(jnp.int64), None))
            continue
        pres = present_of[present_lane[spec.arg]]
        pres_cnt = acc[1 + present_lane[spec.arg]]
        if spec.kind == "count":
            out_aggs.append((pres_cnt.astype(jnp.int64), None))
        elif spec.kind == "sum":
            out_aggs.append((decode_sum(spec.arg), pres_cnt > 0))
        elif spec.kind in ("min", "max"):
            dta, _val = inputs[spec.arg]
            if jnp.issubdtype(dta.dtype, jnp.floating):
                neutral = jnp.array(np.inf if spec.kind == "min" else -np.inf,
                                    dta.dtype)
            else:
                info = jnp.iinfo(dta.dtype)
                neutral = jnp.array(info.max if spec.kind == "min" else info.min,
                                    dta.dtype)
            # masked reduce over the domain: [n, D] is generated, fused into the
            # reduction by XLA (never materialized at full n x D for small D)
            sel = (gid[:, None] == idx[None, :]) & pres[:, None]
            m = jnp.where(sel, dta[:, None], neutral)
            red = jnp.min(m, axis=0) if spec.kind == "min" else jnp.max(m, axis=0)
            out_aggs.append((red, pres_cnt > 0))
        else:
            raise ValueError(f"unsupported matmul agg kind {spec.kind}")

    return GroupByResult(tuple(out_keys), tuple(out_aggs), out_live,
                         num_groups.astype(jnp.int32), jnp.bool_(False))


def _domain_gid(keys, domains, n):
    """Encode small-domain key lanes into one dense group id (NULL slot last per
    key) plus per-key sizes; shared by the matmul and scatter formulations."""
    sizes: List[int] = []
    effs: List[Any] = []
    for (data, valid), dom in zip(keys, domains):
        d = jnp.clip(data.astype(jnp.int32), 0, dom - 1)
        size = dom + (1 if valid is not None else 0)
        effs.append(d if valid is None else jnp.where(valid, d, jnp.int32(dom)))
        sizes.append(size)
    D = 1
    for s in sizes:
        D *= s
    gid = jnp.zeros(n, dtype=jnp.int32)
    for eff, size in zip(effs, sizes):
        gid = gid * size + eff
    return gid, sizes, D


def _domain_out_keys(keys, domains, sizes, D):
    """Decode domain slot indices back into per-key code lanes (matmul layout)."""
    idx = jnp.arange(D, dtype=jnp.int32)
    out_keys: List[Tuple[Any, Any]] = []
    stride = D
    for (data, valid), dom, size in zip(keys, domains, sizes):
        stride //= size
        slot = (idx // stride) % size
        kd = jnp.clip(slot, 0, dom - 1).astype(data.dtype)
        kv = None if valid is None else (slot < dom)
        out_keys.append((kd, kv))
    return out_keys


def scatter_groupby(keys: Sequence[Tuple[Any, Optional[Any]]],
                    inputs: Sequence[Tuple[Any, Optional[Any]]],
                    specs: Sequence[AggSpec],
                    live: Any,
                    domains: Sequence[int]) -> GroupByResult:
    """Small-domain grouped aggregation via scatter-add: the XLA:CPU twin of
    `matmul_groupby`.

    Same contract and slot layout as `matmul_groupby` (domain cross product,
    NULL slot last, live marks non-empty slots, overflow always False), but the
    reduction is `jax.ops.segment_*` — on CPU, XLA lowers scatters to tight
    native loops (measured ~7x faster than the one-hot int8 matmul at 1.2M
    rows), while on TPU scatters serialize and the matmul path wins.  Float
    sums are supported here (no byte-limb decomposition needed: segment_sum
    accumulates in the input dtype, matching `sort_groupby`)."""
    n = live.shape[0]
    gid, sizes, D = _domain_gid(keys, domains, n)
    # dead rows land in a scratch slot D that every reduction slices off
    seg = jnp.where(live, gid, jnp.int32(D))

    live_cnt = jax.ops.segment_sum(live.astype(jnp.int64), seg,
                                   num_segments=D + 1)[:D]
    out_live = live_cnt > 0
    num_groups = jnp.sum(out_live.astype(jnp.int32))

    present_of: dict = {}
    pres_cnt: dict = {}
    for spec in specs:
        if spec.arg >= 0 and spec.arg not in present_of:
            dta, val = inputs[spec.arg]
            p = live if val is None else (live & val)
            present_of[spec.arg] = p
            pres_cnt[spec.arg] = jax.ops.segment_sum(
                p.astype(jnp.int64), seg, num_segments=D + 1)[:D]

    out_keys = _domain_out_keys(keys, domains, sizes, D)

    out_aggs: List[Tuple[Any, Any]] = []
    for spec in specs:
        if spec.kind == "count_star":
            out_aggs.append((live_cnt, None))
            continue
        dta, _val = inputs[spec.arg]
        pres = present_of[spec.arg]
        if spec.kind == "count":
            out_aggs.append((pres_cnt[spec.arg], None))
        elif spec.kind in ("sum", "sum_float"):
            if jnp.issubdtype(dta.dtype, jnp.floating):
                masked = jnp.where(pres, dta, jnp.zeros((), dtype=dta.dtype))
            else:
                masked = jnp.where(pres, dta.astype(jnp.int64), jnp.int64(0))
            s = jax.ops.segment_sum(masked, seg, num_segments=D + 1)[:D]
            out_aggs.append((s, pres_cnt[spec.arg] > 0))
        elif spec.kind in ("min", "max"):
            if jnp.issubdtype(dta.dtype, jnp.floating):
                neutral = jnp.array(np.inf if spec.kind == "min" else -np.inf,
                                    dta.dtype)
            else:
                info = jnp.iinfo(dta.dtype)
                neutral = jnp.array(info.max if spec.kind == "min" else info.min,
                                    dta.dtype)
            masked = jnp.where(pres, dta, neutral)
            red_fn = jax.ops.segment_min if spec.kind == "min" else jax.ops.segment_max
            red = red_fn(masked, seg, num_segments=D + 1)[:D]
            # empty slots come back as the op's own identity; normalize to neutral
            red = jnp.where(pres_cnt[spec.arg] > 0, red, neutral.astype(dta.dtype)) \
                if jnp.issubdtype(dta.dtype, jnp.floating) else red
            out_aggs.append((red, pres_cnt[spec.arg] > 0))
        else:
            raise ValueError(f"unsupported scatter agg kind {spec.kind}")

    return GroupByResult(tuple(out_keys), tuple(out_aggs), out_live,
                         num_groups.astype(jnp.int32), jnp.bool_(False))


def _ident_lanes(keys):
    """Per-key (data_canon, valid) identity lanes for hashing/equality.

    Floats are canonicalized (-0.0 -> +0.0, NaN -> one bit pattern) then
    bitcast to same-width ints so hash and equality agree with SQL GROUP BY
    semantics (0.0 == -0.0 one group, all NaNs one group, NULLs one group)."""
    out = []
    for data, valid in keys:
        if jnp.issubdtype(data.dtype, jnp.floating):
            d = jnp.where(data == 0, jnp.zeros((), data.dtype), data)
            d = jnp.where(jnp.isnan(d), jnp.full((), jnp.nan, data.dtype), d)
            width = jnp.int32 if data.dtype == jnp.float32 else jnp.int64
            d = jax.lax.bitcast_convert_type(d, width)
        else:
            d = data
        if valid is not None:
            d = jnp.where(valid, d, jnp.zeros((), d.dtype))
        out.append((d, valid))
    return out


def _hash_place(ident: Sequence[Tuple[Any, Optional[Any]]], live: Any,
                s0: Any, step: Any, M: int, max_rounds: int):
    """Reference slot placement for `hash_groupby` — and the correctness
    oracle the Pallas kernel (`pallas_agg.hash_place`) must match bit-for-bit.
    Vectorized scatter-min election rounds with an early-exit while_loop."""
    n = live.shape[0]
    rowid = jnp.arange(n, dtype=jnp.int32)
    sentinel = jnp.int32(n)

    def cond(state):
        r, rep, resolved, gid = state
        return (r < max_rounds) & jnp.any(~resolved)

    def body(state):
        r, rep, resolved, gid = state
        s = ((s0 + r.astype(jnp.uint64) * step) &
             jnp.uint64(M - 1)).astype(jnp.int32)
        occupied = rep[s] != sentinel
        cand = jnp.where(resolved | occupied, sentinel, rowid)
        rep = rep.at[s].min(cand)
        owner = rep[s]
        safe = jnp.clip(owner, 0, max(n - 1, 0))
        same = owner != sentinel
        for d, valid in ident:
            same = same & (d[safe] == d)
            if valid is not None:
                same = same & (valid[safe] == valid)
        newly = ~resolved & same
        gid = jnp.where(newly, s, gid)
        return r + jnp.uint64(1), rep, resolved | newly, gid

    state = (jnp.uint64(0), jnp.full(M, sentinel, jnp.int32),
             ~live, jnp.zeros(n, jnp.int32))
    _, rep, resolved, gid = jax.lax.while_loop(cond, body, state)
    return rep, resolved, gid


def hash_groupby(keys: Sequence[Tuple[Any, Optional[Any]]],
                 inputs: Sequence[Tuple[Any, Optional[Any]]],
                 specs: Sequence[AggSpec],
                 live: Any,
                 max_groups: int,
                 max_rounds: int = 64) -> GroupByResult:
    """General grouped aggregation via open-addressing hash slots — no sort.

    The XLA:CPU twin of `sort_groupby`: on CPU, XLA's comparator sorts are
    single-threaded and catastrophically slow (lexsort of 1.2M rows ~1.3s)
    while scatters are fast (~10ms), so group ids are assigned by hashing keys
    into a power-of-two slot table.  Each round, unresolved rows probing an
    EMPTY slot elect an owner by scatter-min on row index; every row then
    verifies its actual key lanes against the owner's (hash collisions cost
    extra rounds, never correctness).  Rows whose keys match the owner adopt
    the slot as their group id; the rest re-probe with an odd per-key stride.
    Aggregation is then `jax.ops.segment_*` by slot.

    Output slots are in hash order, NOT compacted — `live` marks real groups,
    the same contract `matmul_groupby` established.  `overflow` is True when
    placement fails within `max_rounds` (distinct groups exceed capacity or
    pathological clustering); callers retry with doubled `max_groups`."""
    n = live.shape[0] if not keys else keys[0][0].shape[0]
    cap = max(16, min(max_groups, n))
    M = 1 << int(cap * 2 - 1).bit_length()  # load factor <= 0.5 at capacity

    ident = _ident_lanes(keys)
    h = hash_columns(ident)
    s0 = h & jnp.uint64(M - 1)
    # odd stride => full cycle mod the power-of-two table size
    step = ((h >> jnp.uint64(32)) << jnp.uint64(1)) | jnp.uint64(1)

    sentinel = jnp.int32(n)
    if n > 0 and use_pallas(n):
        from galaxysql_tpu.kernels import pallas_agg
        rep, resolved, gid = pallas_agg.hash_place(ident, live, s0, step,
                                                   M, max_rounds)
    else:
        rep, resolved, gid = _hash_place(ident, live, s0, step, M, max_rounds)
    overflow = jnp.any(~resolved)

    placed = resolved & live
    seg = jnp.where(placed, gid, jnp.int32(M))

    live_cnt = jax.ops.segment_sum(live.astype(jnp.int64), seg,
                                   num_segments=M + 1)[:M]
    out_live = rep != sentinel
    num_groups = jnp.sum(out_live.astype(jnp.int32))

    safe_rep = jnp.clip(rep, 0, max(n - 1, 0))
    out_keys = []
    for data, valid in keys:
        out_keys.append((data[safe_rep],
                         None if valid is None else (valid[safe_rep] & out_live)))

    present_of: dict = {}
    pres_cnt: dict = {}
    for spec in specs:
        if spec.arg >= 0 and spec.arg not in present_of:
            dta, val = inputs[spec.arg]
            p = placed if val is None else (placed & val)
            present_of[spec.arg] = p
            pres_cnt[spec.arg] = jax.ops.segment_sum(
                p.astype(jnp.int64), seg, num_segments=M + 1)[:M]

    out_aggs: List[Tuple[Any, Any]] = []
    for spec in specs:
        if spec.kind == "count_star":
            out_aggs.append((live_cnt, None))
            continue
        dta, _val = inputs[spec.arg]
        pres = present_of[spec.arg]
        if spec.kind == "count":
            out_aggs.append((pres_cnt[spec.arg], None))
        elif spec.kind in ("sum", "sum_float"):
            if jnp.issubdtype(dta.dtype, jnp.floating):
                masked = jnp.where(pres, dta, jnp.zeros((), dtype=dta.dtype))
            else:
                masked = jnp.where(pres, dta.astype(jnp.int64), jnp.int64(0))
            s = jax.ops.segment_sum(masked, seg, num_segments=M + 1)[:M]
            out_aggs.append((s, pres_cnt[spec.arg] > 0))
        elif spec.kind in ("min", "max"):
            if jnp.issubdtype(dta.dtype, jnp.floating):
                neutral = jnp.array(np.inf if spec.kind == "min" else -np.inf,
                                    dta.dtype)
            else:
                info = jnp.iinfo(dta.dtype)
                neutral = jnp.array(info.max if spec.kind == "min" else info.min,
                                    dta.dtype)
            masked = jnp.where(pres, dta, neutral)
            red_fn = jax.ops.segment_min if spec.kind == "min" else jax.ops.segment_max
            red = red_fn(masked, seg, num_segments=M + 1)[:M]
            red = jnp.where(pres_cnt[spec.arg] > 0, red, neutral.astype(dta.dtype)) \
                if jnp.issubdtype(dta.dtype, jnp.floating) else red
            out_aggs.append((red, pres_cnt[spec.arg] > 0))
        else:
            raise ValueError(f"unsupported hash agg kind {spec.kind}")

    return GroupByResult(tuple(out_keys), tuple(out_aggs), out_live,
                         num_groups.astype(jnp.int32), overflow)


def prefer_scatter() -> bool:
    """Kernel-formulation choice is a backend property: XLA:CPU lowers scatters
    to fast native loops but its comparator sorts are single-threaded (measured
    1.3s to lexsort 1.2M rows vs ~10ms for a segment_sum); TPU is the inverse
    (scatters serialize, bitonic sorts + MXU matmuls are fast)."""
    return jax.default_backend() == "cpu"


def groupby(keys, inputs, specs, live, max_groups, domains=None):
    """Backend-adaptive grouped aggregation dispatch (see `prefer_scatter`).

    `domains` (per-key small static domains, or None) selects the dense-slot
    formulations; float SUM is only a restriction for the matmul byte-limb
    path, not for scatter."""
    if domains is None and not keys:
        domains = []  # global aggregation: one dense slot, never hash/sort
    if domains is not None:
        if prefer_scatter():
            return scatter_groupby(keys, inputs, specs, live, domains)
        float_sum = any(
            s.kind in ("sum", "sum_float") and s.arg >= 0 and
            jnp.issubdtype(inputs[s.arg][0].dtype, jnp.floating) for s in specs)
        if not float_sum:
            return matmul_groupby(keys, inputs, specs, live, domains)
    if prefer_scatter():
        return hash_groupby(keys, inputs, specs, live, max_groups)
    return sort_groupby(keys, inputs, specs, live, max_groups)


def _segmented_scan(x, reset, is_min: bool):
    """Running min/max that restarts where `reset` is True (log-depth, no scatter).

    min and max are separate combiners on purpose: computing max as -scan_min(-x)
    would wrap the integer neutral (-INT_MIN == INT_MIN) and poison groups that
    contain NULLs."""
    pick = jnp.minimum if is_min else jnp.maximum

    def combine(a, b):
        av, ar = a
        bv, br = b
        v = jnp.where(br, bv, pick(av, bv))
        return v, ar | br

    vals, _ = jax.lax.associative_scan(combine, (x, reset))
    return vals


# ---------------------------------------------------------------------------
# join
# ---------------------------------------------------------------------------

class JoinPairs(NamedTuple):
    build_idx: Any      # [cap] int32 indices into build arrays
    probe_idx: Any      # [cap] int32 indices into probe arrays
    live: Any           # [cap] bool — verified pairs
    probe_matched: Any  # [n_probe] bool — probe rows with >=1 verified match
    probe_starts: Any   # [n_probe] int64 — first pair slot of each probe row
    probe_offsets: Any  # [n_probe] int64 — end pair slot of each probe row
    overflow: Any       # scalar bool


def _effective_live(keys, live):
    m = live
    for _, valid in keys:
        if valid is not None:
            m = m & valid
    return m


def hash_join_pairs(build_keys: Sequence[Tuple[Any, Optional[Any]]],
                    probe_keys: Sequence[Tuple[Any, Optional[Any]]],
                    build_live: Any,
                    probe_live: Any,
                    cap: int) -> JoinPairs:
    """Equi-join match enumeration: returns verified (build, probe) index pairs.

    NULL join keys never match (SQL semantics): rows with any NULL key are masked out of
    both sides before hashing.  Backend-adaptive: the TPU formulation sorts the
    build hashes and binary-searches them (sorts vectorize, scatters serialize);
    the CPU formulation buckets the build side into a slot-table CSR and probes
    by direct gather (XLA:CPU searchsorted costs ~200ms per 1.2M probes — 18
    full gather passes — while scatters are native loops)."""
    if prefer_scatter():
        return _hash_join_pairs_table(build_keys, probe_keys, build_live,
                                      probe_live, cap)
    return _hash_join_pairs_sorted(build_keys, probe_keys, build_live,
                                   probe_live, cap)


def _hash_join_pairs_sorted(build_keys, probe_keys, build_live, probe_live,
                            cap: int) -> JoinPairs:
    b_live = _effective_live(build_keys, build_live)
    p_live = _effective_live(probe_keys, probe_live)
    nb = build_keys[0][0].shape[0]
    npr = probe_keys[0][0].shape[0]

    h_b = hash_columns(build_keys)
    # dead build rows get a sentinel hash sorted to the end and never matched
    h_b = jnp.where(b_live, h_b, jnp.uint64(0xffffffffffffffff))
    perm = jnp.argsort(h_b)
    h_sorted = h_b[perm]

    h_p = hash_columns(probe_keys)
    left = jnp.searchsorted(h_sorted, h_p, side="left")
    right = jnp.searchsorted(h_sorted, h_p, side="right")
    counts = jnp.where(p_live, (right - left).astype(jnp.int64), 0)

    offsets = jnp.cumsum(counts)
    total = offsets[-1] if npr else jnp.int64(0)
    overflow = total > cap
    starts = offsets - counts

    # ragged expansion: slot j -> probe row p, k-th candidate
    slots = jnp.arange(cap, dtype=jnp.int64)
    p_of = jnp.searchsorted(offsets, slots, side="right").astype(jnp.int32)
    p_of = jnp.clip(p_of, 0, max(npr - 1, 0))
    k = slots - starts[p_of]
    pair_live = slots < jnp.minimum(total, cap)
    bpos = jnp.clip(left[p_of] + k.astype(jnp.int32), 0, max(nb - 1, 0))
    b_of = perm[bpos].astype(jnp.int32)

    # verify candidate pairs on the actual key lanes (hash collisions filtered here)
    verified = pair_live
    for (bd, bv), (pd, pv) in zip(build_keys, probe_keys):
        eq = bd[b_of] == pd[p_of]
        verified = verified & eq
    verified = verified & b_live[b_of] & p_live[p_of]

    # pair slots are ordered by probe row, so per-probe-row "any verified" is a
    # prefix-sum range query — no scatter (TPU scatters serialize)
    probe_matched = probe_matched_from(verified, starts, offsets) \
        if npr else jnp.zeros(0, jnp.bool_)

    return JoinPairs(b_of, p_of, verified, probe_matched, starts, offsets, overflow)


def _device_csr(build_keys, build_live, nb: int):
    """Device-side CSR over the build slots: (perm, slot_starts, slot_counts,
    M).  One argsort of the SMALL side groups build row ids contiguously per
    slot; M = 4x build capacity => expected <=0.25 collision candidates per
    probe, filtered by key verification like the sorted path."""
    M = 1 << max(4, int(nb * 4 - 1).bit_length())
    # slot-id lane shared with the host-CSR path (hash + dead-row masking):
    # one definition every join formulation and the hybrid union probe reuse
    s_b = hash_join_build_slots(build_keys, build_live, M)
    perm = jnp.argsort(s_b).astype(jnp.int32)
    slot_counts = jax.ops.segment_sum(jnp.ones(nb, jnp.int32), s_b,
                                      num_segments=M + 1)[:M]
    slot_ends = jnp.cumsum(slot_counts)
    slot_starts = slot_ends - slot_counts
    return perm, slot_starts, slot_counts, M


def _expand_offsets(counts, starts, npr: int, cap: int):
    """Ragged probe->pair expansion: scatter each non-empty probe row's id at
    its first pair slot, then forward-fill with cummax (starts are unique
    among non-empty rows) — ~10x faster than searchsorted(offsets,
    arange(cap)) on XLA:CPU.  Selector-gated: the Pallas variant runs the
    same scatter + running-max sweep in VMEM."""
    if npr > 0 and cap > 0 and use_pallas(cap):
        from galaxysql_tpu.kernels import pallas_join
        return pallas_join.expand_offsets(counts, starts, cap)
    scatter_at = jnp.where(counts > 0, starts, jnp.int64(cap))
    p_of = jnp.zeros(cap, jnp.int32).at[scatter_at].max(
        jnp.arange(npr, dtype=jnp.int32), mode="drop")
    return jax.lax.cummax(p_of)


def _hash_join_pairs_table(build_keys, probe_keys, build_live, probe_live,
                           cap: int) -> JoinPairs:
    """CPU join: slot-table CSR over the build side, gather-probe, scatter
    expand.  Thin composition of `_device_csr` + `hash_join_probe_csr` — the
    hybrid probe and the Pallas tier ride the exact same pipeline."""
    nb = build_keys[0][0].shape[0]
    perm, slot_starts, slot_counts, M = _device_csr(build_keys, build_live, nb)
    return hash_join_probe_csr(build_keys, probe_keys, build_live, probe_live,
                               perm, slot_starts, slot_counts, M, cap)


def hash_join_build_slots(build_keys: Sequence[Tuple[Any, Optional[Any]]],
                          build_live: Any, M: int) -> Any:
    """Build-side slot ids for the host-CSR join (CPU backend).

    XLA:CPU's comparator sort is ~12x slower than numpy's introsort (measured
    106ms vs 8ms argsorting 327k int32), so the CSR construction (argsort +
    bincount of these slot ids) runs on the host; this device kernel only
    computes the slot id lane (hash + mask) that both sides must agree on.
    Dead/NULL-key rows get the scratch slot M."""
    b_live = _effective_live(build_keys, build_live)
    nb = build_keys[0][0].shape[0]
    if nb > 0 and use_pallas(nb):
        from galaxysql_tpu.kernels import pallas_join
        return pallas_join.build_slots(build_keys, b_live, M)
    h_b = hash_columns(build_keys)
    s_b = (h_b & jnp.uint64(M - 1)).astype(jnp.int32)
    return jnp.where(b_live, s_b, jnp.int32(M))


def hash_join_probe_csr(build_keys, probe_keys, build_live, probe_live,
                        perm, slot_starts, slot_counts,
                        M: int, cap: int) -> JoinPairs:
    """Probe half of the CPU slot-table join against a host-built CSR.

    Identical pair enumeration to `_hash_join_pairs_table` from the probe hash
    onward; the build-side argsort/cumsum live outside (host numpy, see
    `hash_join_build_slots`).  The CSR is reused across probe batches and
    overflow retries — the build side is never re-sorted."""
    b_live = _effective_live(build_keys, build_live)
    p_live = _effective_live(probe_keys, probe_live)
    nb = build_keys[0][0].shape[0]
    npr = probe_keys[0][0].shape[0]

    if npr > 0 and use_pallas(npr):
        from galaxysql_tpu.kernels import pallas_join
        s_p = pallas_join.hash_slots(probe_keys, M)
    else:
        h_p = hash_columns(probe_keys)
        s_p = (h_p & jnp.uint64(M - 1)).astype(jnp.int32)
    counts = jnp.where(p_live, slot_counts[s_p].astype(jnp.int64), 0)

    offsets = jnp.cumsum(counts)
    total = offsets[-1] if npr else jnp.int64(0)
    overflow = total > cap
    starts = offsets - counts

    slots = jnp.arange(cap, dtype=jnp.int64)
    p_of = _expand_offsets(counts, starts, npr, cap)
    k = slots - starts[p_of]
    pair_live = slots < jnp.minimum(total, cap)
    bpos = jnp.clip(slot_starts[s_p[p_of]].astype(jnp.int64) + k, 0,
                    max(nb - 1, 0))
    b_of = perm[bpos]

    verified = pair_live
    for (bd, bv), (pd, pv) in zip(build_keys, probe_keys):
        verified = verified & (bd[b_of] == pd[p_of])
    verified = verified & b_live[b_of] & p_live[p_of]

    probe_matched = probe_matched_from(verified, starts, offsets) \
        if npr else jnp.zeros(0, jnp.bool_)

    return JoinPairs(b_of, p_of, verified, probe_matched, starts, offsets, overflow)


def hot_key_mask(keys: Sequence[Tuple[Any, Optional[Any]]],
                 hot_hashes: Any, hot_valid: Any) -> Any:
    """Heavy-hitter classification lane for the skew-aware hybrid join.

    True where the row's combined key hash (the SAME `hash_columns` lane the
    repartition destinations derive from) is one of the `hot_hashes` runtime
    values (`hot_valid` masks the static padding slots — the hot-set size is
    a runtime property and must not retrace).  Classification is purely
    hash-based ON PURPOSE: a cold key colliding with a hot hash is classified
    hot on BOTH sides of the join, so the broadcast/shuffle lanes stay
    consistent and correctness never depends on the hot set's contents."""
    h = hash_columns(keys)
    hit = (h[:, None] == hot_hashes[None, :]) & hot_valid[None, :]
    return jnp.any(hit, axis=1)


def hash_join_probe_hybrid(build_keys: Sequence[Tuple[Any, Optional[Any]]],
                           probe_keys: Sequence[Tuple[Any, Optional[Any]]],
                           build_live: Any, probe_live: Any,
                           cap: int) -> JoinPairs:
    """Union-lane probe of the skew-aware hybrid join.

    The caller concatenates each shard's two build partitions — the broadcast
    hot lane and the hash-shuffled cold lane — and likewise the two probe
    partitions (locally-kept hot rows + shuffled cold rows); this entry
    enumerates verified pairs over the union in ONE pass with the standard
    fixed-shape/overflow contract.  Both lanes go through the same build-slot
    construction (`hash_join_build_slots` inside `_device_csr`), and the
    probe rides `hash_join_probe_csr` on EVERY backend — one implementation
    shared with the batch-streamed CSR probe and the Pallas probe kernel
    instead of a re-derived pair enumeration per entry point."""
    nb = build_keys[0][0].shape[0]
    perm, slot_starts, slot_counts, M = _device_csr(build_keys, build_live, nb)
    return hash_join_probe_csr(build_keys, probe_keys, build_live, probe_live,
                               perm, slot_starts, slot_counts, M, cap)


def probe_matched_from(pair_live: Any, starts: Any, offsets: Any) -> Any:
    """matched[p] = any pair in [starts[p], offsets[p]) is live (prefix-sum ranges)."""
    cap = pair_live.shape[0]
    c = jnp.concatenate([jnp.zeros(1, jnp.int64),
                         jnp.cumsum(pair_live.astype(jnp.int64))])
    s = jnp.clip(starts, 0, cap)
    e = jnp.clip(offsets, 0, cap)
    return (c[e] - c[s]) > 0


def bloom_query_device(keys: Any, words: Any) -> Any:
    """Device-side bloom membership test; bit layout matches the native builder
    (galaxystore gx_bloom_build) and this module's _mix64."""
    h = _mix64(keys.astype(jnp.uint64))
    nwords = words.shape[0]
    m = jnp.uint64(nwords - 1)
    w1 = words[((h >> jnp.uint64(6)) & m).astype(jnp.int32)]
    w2 = words[((h >> jnp.uint64(38)) & m).astype(jnp.int32)]
    hit1 = (w1 >> (h & jnp.uint64(63))) & jnp.uint64(1)
    hit2 = (w2 >> ((h >> jnp.uint64(32)) & jnp.uint64(63))) & jnp.uint64(1)
    return (hit1 & hit2).astype(jnp.bool_)


# ---------------------------------------------------------------------------
# sort / topn
# ---------------------------------------------------------------------------

def sort_indices(keys: Sequence[Tuple[Any, Optional[Any], bool, bool]],
                 live: Any) -> Any:
    """Stable multi-key sort.  Each key: (data, valid, descending, nulls_first).

    Returns a permutation with live rows first in the requested order.
    MySQL default: NULLs sort first ascending, last descending.
    """
    lanes: List[Any] = []
    for data, valid, desc, nulls_first in keys:
        if jnp.issubdtype(data.dtype, jnp.floating):
            lane = -data if desc else data
        elif data.dtype == jnp.bool_:
            lane = (~data if desc else data).astype(jnp.int8)
        else:
            lane = -data.astype(jnp.int64) if desc else data.astype(jnp.int64)
        if valid is not None:
            non_null_rank = jnp.asarray(1 if nulls_first else 0, dtype=jnp.int8)
            null_rank = jnp.asarray(0 if nulls_first else 1, dtype=jnp.int8)
            lanes.append(jnp.where(valid, non_null_rank, null_rank))
            zero = jnp.zeros((), dtype=lane.dtype)
            lane = jnp.where(valid, lane, zero)
        lanes.append(lane)
    dead = (~live).astype(jnp.int8)
    order = jnp.lexsort(tuple(reversed([dead] + lanes)))
    return order


# ---------------------------------------------------------------------------
# compaction / misc
# ---------------------------------------------------------------------------

def compaction_order(live: Any) -> Tuple[Any, Any]:
    """Stable permutation putting live rows first; returns (order, num_live)."""
    order = jnp.argsort(~live, stable=True)
    return order, jnp.sum(live.astype(jnp.int32))


def limit_mask(live: Any, offset: int, count: int) -> Any:
    """LIMIT offset, count over live rows (order = physical order)."""
    rank = jnp.cumsum(live.astype(jnp.int64)) - 1
    return live & (rank >= offset) & (rank < offset + count)


# ---------------------------------------------------------------------------
# window functions
# ---------------------------------------------------------------------------

class WindowSpec(NamedTuple):
    kind: str    # row_number | rank | dense_rank | sum | count | min | max |
                 # lag | lead | first_value | last_value
    arg: int     # input lane index (-1 for rank-family)
    offset: int  # lag/lead distance
    # frame: 'running' (ROWS ..CURRENT), 'range' (RANGE ..CURRENT: ties share the
    # run-end value), 'whole' (entire partition)
    frame: str


def window_eval(part_keys: Sequence[Tuple[Any, Optional[Any]]],
                order_keys: Sequence[Tuple[Any, Optional[Any], bool, bool]],
                inputs: Sequence[Tuple[Any, Optional[Any]]],
                specs: Sequence[WindowSpec],
                live: Any):
    """Evaluate window functions (OverWindowFramesExec analog) scatter-free.

    Rows are sorted by (partition keys, order keys); all computations are cumulative
    scans + boundary gathers over the contiguous partition/peer runs.  Returns
    (order permutation, live_sorted, [(data, valid)] per spec) — outputs align to the
    SORTED order; the operator gathers payload columns with the same permutation."""
    n = live.shape[0]
    sort_keys = [(d, v, False, True) for d, v in part_keys] + list(order_keys)
    order = sort_indices(sort_keys, live)
    live_s = live[order]
    arange = jnp.arange(n, dtype=jnp.int64)

    def boundaries(keys):
        flag = jnp.zeros(n, dtype=jnp.bool_)
        for d, v in keys:
            # canonicalize NULLs: the data under an invalid slot is unspecified and
            # must not split the all-NULLs partition/peer run
            dc = d if v is None else jnp.where(v, d, jnp.zeros_like(d))
            d_s = dc[order]
            flag = flag | jnp.concatenate(
                [jnp.ones(1, jnp.bool_), d_s[1:] != d_s[:-1]])
            if v is not None:
                v_s = v[order]
                flag = flag | jnp.concatenate(
                    [jnp.zeros(1, jnp.bool_), v_s[1:] != v_s[:-1]])
        return flag.at[0].set(True)

    new_part = boundaries(part_keys) if part_keys else \
        jnp.zeros(n, jnp.bool_).at[0].set(True)
    new_run = new_part | (boundaries([(d, v) for d, v, _, _ in order_keys])
                          if order_keys else new_part)

    # per-row partition start / peer-run start (cummax of marked positions)
    part_start = jax.lax.cummax(jnp.where(new_part, arange, -1))
    run_start = jax.lax.cummax(jnp.where(new_run, arange, -1))
    # run/partition END per row: position before the NEXT boundary
    # dead rows sort to the global end; ends must stop at the last LIVE row or a
    # whole/range-frame gather would land on a dead padded slot
    n_live = jnp.sum(live_s.astype(jnp.int64))
    last_live = jnp.clip(n_live - 1, 0, n - 1)
    (starts_list,) = jnp.nonzero(new_run, size=n + 1, fill_value=n)
    run_ix = jnp.cumsum(new_run.astype(jnp.int64)) - 1
    run_end = jnp.clip(starts_list[jnp.clip(run_ix + 1, 0, n)] - 1, 0, n - 1)
    run_end = jnp.minimum(run_end, last_live)
    (pstarts_list,) = jnp.nonzero(new_part, size=n + 1, fill_value=n)
    part_ix = jnp.cumsum(new_part.astype(jnp.int64)) - 1
    part_end = jnp.clip(pstarts_list[jnp.clip(part_ix + 1, 0, n)] - 1, 0, n - 1)
    part_end = jnp.minimum(part_end, last_live)

    out = []
    for spec in specs:
        if spec.kind == "row_number":
            out.append(((arange - part_start + 1).astype(jnp.int64), None))
            continue
        if spec.kind == "rank":
            out.append(((run_start - part_start + 1).astype(jnp.int64), None))
            continue
        if spec.kind == "dense_rank":
            c = jnp.cumsum(new_run.astype(jnp.int64))
            dr = c - c[jnp.clip(part_start, 0, n - 1)] + 1
            out.append((dr.astype(jnp.int64), None))
            continue

        d, v = inputs[spec.arg]
        d_s = d[order]
        v_s = v[order] if v is not None else None
        present = live_s if v_s is None else (live_s & v_s)

        if spec.kind in ("lag", "lead"):
            idx = arange - spec.offset if spec.kind == "lag" else \
                arange + spec.offset
            in_part = (idx >= part_start) & (idx <= part_end)
            idxc = jnp.clip(idx, 0, n - 1).astype(jnp.int32)
            data = d_s[idxc]
            valid = in_part & (present[idxc])
            out.append((data, valid))
            continue
        if spec.kind == "first_value":
            pos = jnp.clip(part_start, 0, n - 1).astype(jnp.int32)
            out.append((d_s[pos], present[pos]))
            continue
        if spec.kind == "last_value":
            pos = (run_end if spec.frame == "range" else
                   part_end if spec.frame == "whole" else arange)
            pos = jnp.clip(pos, 0, n - 1).astype(jnp.int32)
            out.append((d_s[pos], present[pos]))
            continue

        # aggregates over the frame
        if spec.kind == "count":
            masked = present.astype(jnp.int64)
        elif spec.kind == "sum":
            if jnp.issubdtype(d_s.dtype, jnp.floating):
                masked = jnp.where(present, d_s, jnp.zeros((), d_s.dtype))
            else:
                masked = jnp.where(present, d_s.astype(jnp.int64), 0)
        elif spec.kind in ("min", "max"):
            if jnp.issubdtype(d_s.dtype, jnp.floating):
                neutral = jnp.array(np.inf if spec.kind == "min" else -np.inf,
                                    d_s.dtype)
            else:
                info = jnp.iinfo(d_s.dtype)
                neutral = jnp.array(info.max if spec.kind == "min" else info.min,
                                    d_s.dtype)
            masked = jnp.where(present, d_s, neutral)
        else:
            raise ValueError(f"unknown window kind {spec.kind}")

        if spec.kind in ("min", "max"):
            running = _segmented_scan(masked, new_part, spec.kind == "min")
            nonempty_run = _segmented_scan(present.astype(jnp.int8), new_part,
                                           False) > 0
        else:
            c = jnp.cumsum(masked)
            base = jnp.where(part_start > 0,
                             c[jnp.clip(part_start - 1, 0, n - 1)], 0)
            running = c - base
            cp = jnp.cumsum(present.astype(jnp.int64))
            basep = jnp.where(part_start > 0,
                              cp[jnp.clip(part_start - 1, 0, n - 1)], 0)
            nonempty_run = (cp - basep) > 0

        pos = (run_end if spec.frame == "range" else
               part_end if spec.frame == "whole" else arange)
        pos = jnp.clip(pos, 0, n - 1).astype(jnp.int32)
        data = running[pos]
        if spec.kind == "count":
            out.append((data, None))
        else:
            out.append((data, nonempty_run[pos]))
    return order, live_s, out
