"""Core relational kernels over fixed-shape device arrays.

These replace the reference's operator hot loops (SURVEY.md §3.3: hash-table build/probe in
`ParallelHashJoinExec.java:131-226`, agg-map updates in `AggOpenHashMap`, sorts) with
TPU-friendly primitives:

- **group-by = lexicographic sort + segmented reduction.**  No pointer-chasing hash map: rows
  are lexsorted on the key lanes (exact — dictionary codes make string keys integer), group
  boundaries are detected by comparing adjacent rows, and aggregates are `jax.ops.segment_*`
  reductions.  The reference's sort-based fallback for huge-NDV aggs (`SpillableAggHashMap`)
  is here the *primary* strategy because sort is what the hardware does well.
- **hash join = hash + sort + searchsorted probe.**  The build side is sorted by a 64-bit key
  hash; probes binary-search the sorted hash lane; every candidate pair is then verified
  against the actual key columns, so hash collisions cost duplicates-filtered work, never
  correctness.  This is the flat-array open-addressing idea of `ConcurrentRawHashTable`
  (Appendix A) re-expressed without scatter contention.

All kernels are fixed-shape: output capacity is a static argument and kernels report
`overflow` so the host can re-bucket and retry (the dynamic-shape escape hatch, SURVEY.md
§7.3).  Dead rows are carried via `live` masks, never compacted implicitly.
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# ---------------------------------------------------------------------------
# hashing
# ---------------------------------------------------------------------------

_M1 = np.uint64(0xff51afd7ed558ccd)
_M2 = np.uint64(0xc4ceb9fe1a85ec53)
_GOLDEN = np.uint64(0x9e3779b97f4a7c15)


def _mix64(h):
    h = h ^ (h >> 33)
    h = h * _M1
    h = h ^ (h >> 33)
    h = h * _M2
    h = h ^ (h >> 33)
    return h


def hash_columns(cols: Sequence[Tuple[Any, Optional[Any]]]) -> Any:
    """Combine key columns (data, valid) into one uint64 hash lane.

    NULL contributes a distinct tag so NULL keys group together but a verify pass still
    decides join-match semantics (SQL: NULL never equals NULL in joins).
    """
    h = None
    for data, valid in cols:
        lane = _mix64(data.astype(jnp.uint64))
        if valid is not None:
            lane = jnp.where(valid, lane, jnp.uint64(0xdeadbeefcafebabe))
        h = lane if h is None else _mix64(h * np.uint64(31) + lane + _GOLDEN)
    assert h is not None
    return h


# ---------------------------------------------------------------------------
# group-by
# ---------------------------------------------------------------------------

class AggSpec(NamedTuple):
    kind: str  # 'sum' | 'count' | 'count_star' | 'min' | 'max' | 'sum_float'
    # operand index into the inputs list (-1 for count_star)
    arg: int


class GroupByResult(NamedTuple):
    keys: Tuple[Tuple[Any, Any], ...]  # per key: (data [max_groups], valid-or-None)
    aggs: Tuple[Tuple[Any, Any], ...]  # per agg: (data [max_groups], valid-or-None)
    live: Any                      # [max_groups] bool — which output slots are real groups
    num_groups: Any                # scalar int32
    overflow: Any                  # scalar bool


def sort_groupby(keys: Sequence[Tuple[Any, Optional[Any]]],
                 inputs: Sequence[Tuple[Any, Optional[Any]]],
                 specs: Sequence[AggSpec],
                 live: Any,
                 max_groups: int) -> GroupByResult:
    """Grouped aggregation.  `keys`/`inputs` are (data, valid) lanes of equal length n."""
    n = keys[0][0].shape[0] if keys else live.shape[0]
    dead = ~live

    # null flag participates in grouping (SQL GROUP BY: NULLs form one group)
    key_lanes: List[Any] = []
    for data, valid in keys:
        if valid is not None:
            key_lanes.append(~valid)  # nulls group separately, after non-null? order irrelevant
            key_lanes.append(jnp.where(valid, data, jnp.zeros_like(data)))
        else:
            key_lanes.append(data)

    # lexsort: last key is primary => (minor..major); dead rows pushed to the end
    order = jnp.lexsort(tuple(reversed([dead.astype(jnp.int8)] + key_lanes))) \
        if key_lanes else jnp.argsort(dead.astype(jnp.int8), stable=True)
    live_s = live[order]
    sorted_lanes = [k[order] for k in key_lanes]

    if sorted_lanes:
        prev_differs = jnp.zeros(n, dtype=jnp.bool_)
        for lane in sorted_lanes:
            prev_differs = prev_differs | jnp.concatenate(
                [jnp.ones(1, dtype=jnp.bool_), lane[1:] != lane[:-1]])
        new_group = prev_differs & live_s
        new_group = new_group.at[0].set(live_s[0])
    else:
        new_group = jnp.zeros(n, dtype=jnp.bool_).at[0].set(live_s[0])

    seg = jnp.cumsum(new_group.astype(jnp.int32)) - 1
    num_groups = seg[-1] + 1 if n else jnp.int32(0)
    num_groups = jnp.where(live_s.any(), num_groups, 0) if n else jnp.int32(0)
    overflow = num_groups > max_groups
    # dead rows and overflowing groups land in a trash segment
    seg = jnp.where(live_s, jnp.minimum(seg, max_groups), max_groups)
    nseg = max_groups + 1

    # representative row per group for key materialization
    first_row = jax.ops.segment_min(jnp.arange(n, dtype=jnp.int32), seg,
                                    num_segments=nseg)[:max_groups]
    first_row = jnp.clip(first_row, 0, max(n - 1, 0))

    out_keys = []
    for data, valid in keys:
        d_s = data[order]
        out_keys.append(d_s[first_row])
    out_key_valid = []
    for data, valid in keys:
        if valid is None:
            out_key_valid.append(None)
        else:
            out_key_valid.append(valid[order][first_row])

    out_aggs: List[Tuple[Any, Any]] = []
    for spec in specs:
        if spec.kind == "count_star":
            cnt = jax.ops.segment_sum(live_s.astype(jnp.int64), seg,
                                      num_segments=nseg)[:max_groups]
            out_aggs.append((cnt, None))
            continue
        data, valid = inputs[spec.arg]
        d_s = data[order]
        v_s = valid[order] if valid is not None else None
        present = live_s if v_s is None else (live_s & v_s)
        if spec.kind == "count":
            cnt = jax.ops.segment_sum(present.astype(jnp.int64), seg,
                                      num_segments=nseg)[:max_groups]
            out_aggs.append((cnt, None))
        elif spec.kind in ("sum", "sum_float"):
            if spec.kind == "sum_float" or jnp.issubdtype(d_s.dtype, jnp.floating):
                zero = jnp.zeros((), dtype=d_s.dtype)
                masked = jnp.where(present, d_s, zero)
            else:
                masked = jnp.where(present, d_s.astype(jnp.int64), 0)
            s = jax.ops.segment_sum(masked, seg, num_segments=nseg)[:max_groups]
            nonempty = jax.ops.segment_sum(present.astype(jnp.int32), seg,
                                           num_segments=nseg)[:max_groups] > 0
            out_aggs.append((s, nonempty))
        elif spec.kind in ("min", "max"):
            if jnp.issubdtype(d_s.dtype, jnp.floating):
                neutral = jnp.array(np.inf if spec.kind == "min" else -np.inf, d_s.dtype)
            else:
                info = jnp.iinfo(d_s.dtype)
                neutral = jnp.array(info.max if spec.kind == "min" else info.min, d_s.dtype)
            masked = jnp.where(present, d_s, neutral)
            f = jax.ops.segment_min if spec.kind == "min" else jax.ops.segment_max
            m = f(masked, seg, num_segments=nseg)[:max_groups]
            nonempty = jax.ops.segment_sum(present.astype(jnp.int32), seg,
                                           num_segments=nseg)[:max_groups] > 0
            out_aggs.append((m, nonempty))
        else:
            raise ValueError(f"unknown agg kind {spec.kind}")

    out_live = jnp.arange(max_groups, dtype=jnp.int32) < jnp.minimum(num_groups, max_groups)
    return GroupByResult(tuple(zip(out_keys, out_key_valid)), tuple(out_aggs), out_live,
                         jnp.minimum(num_groups, max_groups).astype(jnp.int32), overflow)


# ---------------------------------------------------------------------------
# join
# ---------------------------------------------------------------------------

class JoinPairs(NamedTuple):
    build_idx: Any     # [cap] int32 indices into build arrays
    probe_idx: Any     # [cap] int32 indices into probe arrays
    live: Any          # [cap] bool — verified pairs
    probe_matched: Any  # [n_probe] bool — probe rows with >=1 verified match
    build_matched: Any  # [n_build] bool — build rows with >=1 verified match
    overflow: Any      # scalar bool


def hash_join_pairs(build_keys: Sequence[Tuple[Any, Optional[Any]]],
                    probe_keys: Sequence[Tuple[Any, Optional[Any]]],
                    build_live: Any,
                    probe_live: Any,
                    cap: int) -> JoinPairs:
    """Equi-join match enumeration: returns verified (build, probe) index pairs.

    NULL join keys never match (SQL semantics): rows with any NULL key are masked out of
    both sides before hashing.
    """
    def effective_live(keys, live):
        m = live
        for _, valid in keys:
            if valid is not None:
                m = m & valid
        return m

    b_live = effective_live(build_keys, build_live)
    p_live = effective_live(probe_keys, probe_live)
    nb = build_keys[0][0].shape[0]
    npr = probe_keys[0][0].shape[0]

    h_b = hash_columns(build_keys)
    # dead build rows get a sentinel hash sorted to the end and never matched
    h_b = jnp.where(b_live, h_b, jnp.uint64(0xffffffffffffffff))
    perm = jnp.argsort(h_b)
    h_sorted = h_b[perm]

    h_p = hash_columns(probe_keys)
    left = jnp.searchsorted(h_sorted, h_p, side="left")
    right = jnp.searchsorted(h_sorted, h_p, side="right")
    counts = jnp.where(p_live, (right - left).astype(jnp.int64), 0)

    offsets = jnp.cumsum(counts)
    total = offsets[-1] if npr else jnp.int64(0)
    overflow = total > cap
    starts = offsets - counts

    # ragged expansion: slot j -> probe row p, k-th candidate
    slots = jnp.arange(cap, dtype=jnp.int64)
    p_of = jnp.searchsorted(offsets, slots, side="right").astype(jnp.int32)
    p_of = jnp.clip(p_of, 0, max(npr - 1, 0))
    k = slots - starts[p_of]
    pair_live = slots < jnp.minimum(total, cap)
    bpos = jnp.clip(left[p_of] + k.astype(jnp.int32), 0, max(nb - 1, 0))
    b_of = perm[bpos].astype(jnp.int32)

    # verify candidate pairs on the actual key lanes (hash collisions filtered here)
    verified = pair_live
    for (bd, bv), (pd, pv) in zip(build_keys, probe_keys):
        eq = bd[b_of] == pd[p_of]
        verified = verified & eq
    verified = verified & b_live[b_of] & p_live[p_of]

    # segment_sum, not segment_max: empty segments must yield False (segment_max's
    # identity is INT_MIN, which would cast to True)
    probe_matched = (jax.ops.segment_sum(
        verified.astype(jnp.int32), p_of, num_segments=npr) > 0) \
        if npr else jnp.zeros(0, jnp.bool_)
    build_matched = (jax.ops.segment_sum(
        verified.astype(jnp.int32), b_of, num_segments=nb) > 0) \
        if nb else jnp.zeros(0, jnp.bool_)

    return JoinPairs(b_of, p_of, verified, probe_matched, build_matched, overflow)


# ---------------------------------------------------------------------------
# sort / topn
# ---------------------------------------------------------------------------

def sort_indices(keys: Sequence[Tuple[Any, Optional[Any], bool, bool]],
                 live: Any) -> Any:
    """Stable multi-key sort.  Each key: (data, valid, descending, nulls_first).

    Returns a permutation with live rows first in the requested order.
    MySQL default: NULLs sort first ascending, last descending.
    """
    lanes: List[Any] = []
    for data, valid, desc, nulls_first in keys:
        if jnp.issubdtype(data.dtype, jnp.floating):
            lane = -data if desc else data
        elif data.dtype == jnp.bool_:
            lane = (~data if desc else data).astype(jnp.int8)
        else:
            lane = -data.astype(jnp.int64) if desc else data.astype(jnp.int64)
        if valid is not None:
            non_null_rank = jnp.asarray(1 if nulls_first else 0, dtype=jnp.int8)
            null_rank = jnp.asarray(0 if nulls_first else 1, dtype=jnp.int8)
            lanes.append(jnp.where(valid, non_null_rank, null_rank))
            zero = jnp.zeros((), dtype=lane.dtype)
            lane = jnp.where(valid, lane, zero)
        lanes.append(lane)
    dead = (~live).astype(jnp.int8)
    order = jnp.lexsort(tuple(reversed([dead] + lanes)))
    return order


# ---------------------------------------------------------------------------
# compaction / misc
# ---------------------------------------------------------------------------

def compaction_order(live: Any) -> Tuple[Any, Any]:
    """Stable permutation putting live rows first; returns (order, num_live)."""
    order = jnp.argsort(~live, stable=True)
    return order, jnp.sum(live.astype(jnp.int32))


def limit_mask(live: Any, offset: int, count: int) -> Any:
    """LIMIT offset, count over live rows (order = physical order)."""
    rank = jnp.cumsum(live.astype(jnp.int64)) - 1
    return live & (rank >= offset) & (rank < offset + count)
