"""Asyncio MySQL-protocol frontend.

Reference analog: `NIOAcceptor`/`NIOProcessor`/`FrontendConnection` +
`FrontendCommandHandler` (SURVEY.md §2.1, §3.2).  One asyncio task per connection
replaces the reactor threads; blocking query execution runs in a thread pool so the
event loop keeps serving other connections (the NIOProcessor R/W split analog).

Served commands: handshake/auth (mysql_native_password), COM_QUERY (multi-statement),
COM_INIT_DB, COM_PING, COM_FIELD_LIST, COM_STMT_PREPARE/EXECUTE/CLOSE/RESET,
COM_SET_OPTION, COM_QUIT.
"""

from __future__ import annotations

import asyncio
import os
import secrets
import struct
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Optional

from galaxysql_tpu.net import packets as P
from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import ResultSet, Session
from galaxysql_tpu.sql.parser import parse as parse_sql
from galaxysql_tpu.utils import errors


class PreparedStatement:
    def __init__(self, stmt_id: int, sql: str, n_params: int):
        self.stmt_id = stmt_id
        self.sql = sql
        self.n_params = n_params
        # param types from the first COM_STMT_EXECUTE (connectors omit them later)
        self.param_types = None


class Connection:
    def __init__(self, server: "MySQLServer", reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter):
        self.server = server
        self.reader = reader
        self.writer = writer
        self.session = Session(server.instance)
        self.seq = 0
        self.stmts: Dict[int, PreparedStatement] = {}
        self.next_stmt_id = 1
        self.closed = False
        # compressed protocol (CLIENT_COMPRESS): active after a successful
        # handshake that negotiated it; MySQL packets then ride inside
        # [3B comp-len][1B comp-seq][3B uncompressed-len] frames (zlib when
        # uncompressed-len > 0, verbatim when 0)
        self.compressed = False
        self.cseq = 0
        self._inbuf = b""
        self._outbuf: list = []

    # -- framing ---------------------------------------------------------------

    async def _read_raw(self, n: int) -> bytes:
        """n bytes of the logical (post-decompression) stream."""
        if not self.compressed:
            return await self.reader.readexactly(n)
        import zlib
        while len(self._inbuf) < n:
            hdr = await self.reader.readexactly(7)
            clen = hdr[0] | (hdr[1] << 8) | (hdr[2] << 16)
            self.cseq = (hdr[3] + 1) & 0xFF
            ulen = hdr[4] | (hdr[5] << 8) | (hdr[6] << 16)
            body = await self.reader.readexactly(clen)
            self._inbuf += zlib.decompress(body) if ulen else body
        out, self._inbuf = self._inbuf[:n], self._inbuf[n:]
        return out

    async def read_packet(self) -> Optional[bytes]:
        # reassemble >=16MB payloads split across continuation packets
        payload = b""
        while True:
            header = await self._read_raw(4)
            length = header[0] | (header[1] << 8) | (header[2] << 16)
            self.seq = (header[3] + 1) & 0xFF
            payload += await self._read_raw(length)
            if length < 0xFFFFFF:
                return payload

    def send(self, payload: bytes):
        while True:
            chunk, payload = payload[:0xFFFFFF], payload[0xFFFFFF:]
            header = struct.pack("<I", len(chunk))[:3] + bytes([self.seq])
            self.seq = (self.seq + 1) & 0xFF
            if self.compressed:
                self._outbuf.append(header + chunk)
            else:
                self.writer.write(header + chunk)
            if len(chunk) < 0xFFFFFF:
                break

    MIN_COMPRESS = 50  # MySQL: tiny frames ship uncompressed (ulen = 0)

    async def flush(self):
        if self.compressed and self._outbuf:
            import zlib
            data = b"".join(self._outbuf)
            self._outbuf = []
            for off in range(0, len(data), 0xFFFFF0):
                part = data[off:off + 0xFFFFF0]
                body, ulen = part, 0
                if len(part) >= self.MIN_COMPRESS:
                    z = zlib.compress(part)
                    # incompressible payloads ship verbatim (ulen=0): zlib
                    # expansion could overflow the 3-byte length field
                    if len(z) < len(part):
                        body, ulen = z, len(part)
                hdr = (struct.pack("<I", len(body))[:3] + bytes([self.cseq]) +
                       struct.pack("<I", ulen)[:3])
                self.cseq = (self.cseq + 1) & 0xFF
                self.writer.write(hdr + body)
        await self.writer.drain()

    def _status(self) -> int:
        st = P.SERVER_STATUS_AUTOCOMMIT if self.session.autocommit else 0
        if self.session.txn is not None:
            st |= P.SERVER_STATUS_IN_TRANS
        return st

    # -- lifecycle -------------------------------------------------------------

    async def run(self):
        try:
            await self._run_inner()
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
            pass  # client vanished or sent garbage framing: drop quietly
        finally:
            self.session.close()
            try:
                self.writer.close()
            except Exception:  # galaxylint: disable=swallow -- client already vanished; socket close is best-effort
                pass

    async def _upgrade_tls(self):
        """Switch the accepted plaintext stream to TLS in place (SSLRequest).

        `StreamWriter.start_tls` only exists on py>=3.11; on 3.10 this replays
        its implementation over `loop.start_tls`: wrap the raw transport in an
        SSL transport and repoint the writer + stream protocol at it (the
        reader keeps the raw transport — it is only used for flow control,
        exactly what CPython's 3.11 `_replace_writer` does)."""
        ctx = self.server.ssl_context
        if hasattr(self.writer, "start_tls"):
            await self.writer.start_tls(ctx)
            return
        loop = asyncio.get_running_loop()
        protocol = self.writer.transport.get_protocol()
        await self.writer.drain()
        new_tr = await loop.start_tls(self.writer.transport, protocol, ctx,
                                      server_side=True)
        self.writer._transport = new_tr
        protocol._transport = new_tr
        protocol._over_ssl = True

    async def _run_inner(self):
        # salt bytes must avoid NUL: clients read the second half null-terminated
        seed = bytes(secrets.choice(range(1, 256)) for _ in range(20))
        caps = P.SERVER_CAPABILITIES | \
            (P.CLIENT_SSL if self.server.ssl_context is not None else 0)
        self.send(P.handshake_v10(self.session.conn_id, seed, caps))
        await self.flush()
        payload = await self.read_packet()
        # SSLRequest (FrontendCommandHandler.java:99 / net/ssl analog): a short
        # response with CLIENT_SSL set means "switch to TLS now"; the real
        # handshake response then arrives over the encrypted stream
        if len(payload) < 36 and \
                struct.unpack_from("<I", payload, 0)[0] & P.CLIENT_SSL:
            if self.server.ssl_context is None:
                self.send(P.err_packet(3159, "HY000",
                                       "SSL is not enabled on this server"))
                await self.flush()
                return
            await self._upgrade_tls()
            payload = await self.read_packet()
        creds = P.parse_handshake_response(payload)
        if not self.server.authenticate(creds["user"], creds["auth"], seed):
            self.send(P.err_packet(1045, "28000",
                                   f"Access denied for user '{creds['user']}'"))
            await self.flush()
            return
        self.session.user = creds["user"]
        if creds.get("database"):
            try:
                self.session.execute(f"USE `{creds['database']}`")
            except errors.TddlError as e:
                self.send(P.err_packet(e.errno, e.sqlstate, e.message))
                await self.flush()
                return
        self.send(P.ok_packet(status=self._status()))
        await self.flush()
        # the handshake exchange is always uncompressed; the negotiated
        # compressed framing starts with the first command
        self.compressed = bool(creds["capabilities"] & P.CLIENT_COMPRESS)
        while not self.closed:
            self.seq = 0
            self.cseq = 0
            try:
                payload = await self.read_packet()
            except (asyncio.IncompleteReadError, ConnectionResetError):
                break
            if not payload:
                break
            await self.dispatch(payload)
            await self.flush()

    # -- command dispatch --------------------------------------------------------

    async def dispatch(self, payload: bytes):
        cmd = payload[0]
        try:
            if cmd == P.COM_QUIT:
                self.closed = True
            elif cmd == P.COM_PING:
                self.send(P.ok_packet(status=self._status()))
            elif cmd == P.COM_INIT_DB:
                db = payload[1:].decode("utf8", "replace")
                await self.run_blocking(self.session.execute, f"USE `{db}`")
                self.send(P.ok_packet(status=self._status()))
            elif cmd == P.COM_QUERY:
                sql = payload[1:].decode("utf8", "replace")
                results = await self.run_blocking(self.session.execute_all, sql)
                # CLIENT_MULTI_STATEMENTS: every statement's result is sent, with
                # SERVER_MORE_RESULTS_EXISTS on all but the last
                for i, r in enumerate(results):
                    more = P.SERVER_MORE_RESULTS_EXISTS if i + 1 < len(results) else 0
                    self.send_result(r, status_extra=more)
            elif cmd == P.COM_FIELD_LIST:
                table = payload[1:].split(b"\0")[0].decode("utf8", "replace")
                r = await self.run_blocking(self.session.execute,
                                            f"DESC `{table}`")
                for row in r.rows:
                    from galaxysql_tpu.types import datatype as dt
                    self.send(P.column_def(row[0], dt.VARCHAR, table))
                self.send(P.eof_packet(self._status()))
            elif cmd == P.COM_STMT_PREPARE:
                self.stmt_prepare(payload[1:].decode("utf8", "replace"))
            elif cmd == P.COM_STMT_EXECUTE:
                await self.stmt_execute(payload)
            elif cmd == P.COM_STMT_SEND_LONG_DATA:
                pass  # protocol: NO response; long-data binding not yet supported
            elif cmd == P.COM_STMT_CLOSE:
                stmt_id = struct.unpack_from("<I", payload, 1)[0]
                self.stmts.pop(stmt_id, None)  # no response
            elif cmd == P.COM_STMT_RESET:
                self.send(P.ok_packet(status=self._status()))
            elif cmd == P.COM_SET_OPTION:
                self.send(P.eof_packet(self._status()))
            elif cmd == P.COM_BINLOG_DUMP:
                await self.binlog_dump(payload)
            else:
                self.send(P.err_packet(1047, "08S01", f"Unknown command {cmd:#x}"))
        except errors.TddlError as e:
            self.send(P.err_packet(e.errno, e.sqlstate, e.message))
        except Exception as e:  # pragma: no cover - hardening
            self.send(P.err_packet(1105, "HY000", f"{type(e).__name__}: {e}"))

    BINLOG_DUMP_NON_BLOCK = 0x01

    async def binlog_dump(self, payload: bytes):
        """COM_BINLOG_DUMP: stream the CDC change log from a position.

        Reference analog: `FrontendCommandHandler.java:99-104` routes the
        binlog-dump op to the CDC component; like the reference's logical
        binlog, events here are the engine's row-image records — each packet is
        [0x00][json event] with seq/commit_ts/schema/table/kind/payload fields
        (txn/cdc.py's wire form, replayable via cdc.replay).  Position = the
        last-seen event SEQ (0 = from the start) — seq, not commit_ts, so a
        transaction whose events straddle a page boundary resumes without
        loss.  With BINLOG_DUMP_NON_BLOCK the stream ends in EOF at the log's
        end; otherwise it keeps tailing until the client drops."""
        import json
        pos = struct.unpack_from("<I", payload, 1)[0]
        flags = struct.unpack_from("<H", payload, 5)[0] \
            if len(payload) >= 7 else self.BINLOG_DUMP_NON_BLOCK
        since = int(pos)
        if len(payload) >= 19:
            # seq positions may exceed the 4-byte pos field: clients append
            # the full 64-bit watermark where the filename would sit
            since = struct.unpack_from("<Q", payload, 11)[0]
        cdc = self.session.instance.cdc
        PAGE = 10000
        while not self.closed:
            events = await self.run_blocking(cdc.events_after_seq, since, PAGE)
            for seq, cts, schema, table, kind, pl in events:
                ev = {"seq": seq, "commit_ts": cts, "schema": schema,
                      "table": table, "kind": kind, "payload": pl}
                self.send(b"\x00" + json.dumps(ev).encode("utf8"))
                since = max(since, seq)
            await self.flush()
            if len(events) == PAGE:
                continue  # more pages pending: drain before EOF/tail decision
            if flags & self.BINLOG_DUMP_NON_BLOCK:
                self.send(P.eof_packet(self._status()))
                return
            await asyncio.sleep(0.2)  # tail the log

    async def run_blocking(self, fn, *args):
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(self.server.pool, fn, *args)

    def send_result(self, r: ResultSet, binary: bool = False,
                    status_extra: int = 0):
        status = self._status() | status_extra
        if not r.is_query:
            self.send(P.ok_packet(r.affected, r.last_insert_id, status,
                                  info=r.info.encode("utf8")))
            return
        self.send(P.lenenc_int(len(r.names)))
        for name, typ in zip(r.names, r.types):
            self.send(P.column_def(name, typ))
        self.send(P.eof_packet(status))
        for row in r.rows:
            if binary:
                self.send(P.binary_row(row, r.types))
            else:
                self.send(P.text_row(row))
        self.send(P.eof_packet(status))

    # -- prepared statements -------------------------------------------------------

    def stmt_prepare(self, sql: str):
        from galaxysql_tpu.sql.lexer import T, tokenize
        parse_sql(sql)  # validate syntax up front (errors -> ERR packet)
        n_params = sum(1 for t in tokenize(sql) if t.kind == T.PARAM)
        stmt = PreparedStatement(self.next_stmt_id, sql, n_params)
        self.next_stmt_id += 1
        self.stmts[stmt.stmt_id] = stmt
        # response: [ok][stmt_id][n_cols][n_params][filler][warnings]
        head = (b"\x00" + struct.pack("<I", stmt.stmt_id) +
                struct.pack("<H", 0) + struct.pack("<H", n_params) +
                b"\x00" + struct.pack("<H", 0))
        self.send(head)
        if n_params:
            from galaxysql_tpu.types import datatype as dt
            for i in range(n_params):
                self.send(P.column_def(f"?{i}", dt.VARCHAR))
            self.send(P.eof_packet(self._status()))

    async def stmt_execute(self, payload: bytes):
        stmt_id = struct.unpack_from("<I", payload, 1)[0]
        stmt = self.stmts.get(stmt_id)
        if stmt is None:
            self.send(P.err_packet(1243, "HY000", "Unknown prepared statement"))
            return
        params, types = P.parse_stmt_execute_params(payload, stmt.n_params,
                                                     stmt.param_types)
        if types:
            stmt.param_types = types
        r = await self.run_blocking(self.session.execute, stmt.sql, params)
        self.send_result(r, binary=True)


class MySQLServer:
    """The frontend acceptor (CobarServer.startupServer analog, §3.1)."""

    def __init__(self, instance: Instance, host: str = "127.0.0.1", port: int = 3406,
                 users: Optional[Dict[str, str]] = None, pool_size: int = 16,
                 ssl_certfile: Optional[str] = None,
                 ssl_keyfile: Optional[str] = None):
        self.instance = instance
        self.host = host
        self.port = port
        self.users = users  # None -> authenticate against the metadb user table
        self.pool = ThreadPoolExecutor(max_workers=pool_size,
                                       thread_name_prefix="exec")
        self._server: Optional[asyncio.AbstractServer] = None
        # TLS (net/ssl analog): when a cert is configured the handshake
        # advertises CLIENT_SSL and honors the SSLRequest upgrade
        self.ssl_context = None
        if ssl_certfile:
            import ssl as _ssl
            ctx = _ssl.SSLContext(_ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(ssl_certfile, ssl_keyfile)
            self.ssl_context = ctx

    def authenticate(self, user: str, auth: bytes, seed: bytes) -> bool:
        # explicit user map (tests) takes precedence; otherwise the metadb
        # privilege tables decide (PolarPrivManager analog)
        if self.users is not None and user in self.users:
            password = self.users[user].encode("utf8")
            if not password:
                return auth in (b"", b"\0")
            return auth == P.native_password_scramble(password, seed)
        if self.users is not None:
            return False
        import hashlib
        stored = self.instance.privileges.password_hash(user)  # SHA1(SHA1(pw))
        if stored is None:
            return False
        if not stored:
            return auth in (b"", b"\0")
        if not auth:
            return False
        # scramble = SHA1(pw) XOR SHA1(seed + stored); recover SHA1(pw) and verify
        h3 = hashlib.sha1(seed + stored).digest()
        sha1_pw = bytes(a ^ b for a, b in zip(auth, h3))
        return hashlib.sha1(sha1_pw).digest() == stored

    async def start(self):
        async def handler(reader, writer):
            conn = Connection(self, reader, writer)
            await conn.run()

        self._server = await asyncio.start_server(handler, self.host, self.port)
        if self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self):
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        self.pool.shutdown(wait=False)

    async def serve_forever(self):
        await self.start()
        await self._server.serve_forever()


class CoordinatorSyncListener:
    """dn-wire sync endpoint on a COORDINATOR process.

    The serving tier's gossip plane: a front router dials this port with
    the same `WorkerClient` it uses for workers, so `ping`/`sync` ops —
    and FP_RPC_* failpoints, the circuit breaker, retry budgets — work
    against peer coordinators unchanged.  `sync` dispatches into
    `Instance.apply_sync_action` (the `health` action carries admission
    gossip both ways); every reply piggybacks the same `wl` load block
    workers ship, so the router weighs peers by queue depth and memory
    tier without a dedicated probe RPC.
    """

    def __init__(self, instance: Instance):
        self.instance = instance
        self.port = 0
        self._srv = None
        self._thread = None

    def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        import socket
        import threading
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, port))
        srv.listen(16)
        self.port = srv.getsockname()[1]
        self._srv = srv
        self._thread = threading.Thread(target=self._accept_loop,
                                        args=(srv,), daemon=True)
        self._thread.start()
        return self.port

    def stop(self):
        if self._srv is not None:
            try:
                self._srv.close()
            except OSError:
                pass
            self._srv = None

    def _accept_loop(self, srv):
        import threading
        while True:
            try:
                conn, _ = srv.accept()
            except OSError:
                return  # listener closed
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _handle(self, header: dict) -> dict:
        import time as _t
        inst = self.instance
        op = header.get("op")
        if op == "ping":
            resp = {"ok": True, "node": inst.node_id}
        elif op == "sync":
            try:
                resp = inst.apply_sync_action(header.get("action"),
                                              header.get("payload") or {})
            except Exception as e:
                resp = {"error": f"{type(e).__name__}: {e}",
                        "errno": int(getattr(e, "errno", 1105) or 1105)}
        else:
            resp = {"error": f"unknown op {op!r} (coordinator sync plane "
                             f"serves ping/sync only)"}
        if isinstance(resp, dict) and "wl" not in resp:
            try:
                adm = inst.admission
                snap = adm.cluster_snapshot()
                q = int(snap["tp"]["inflight"] + snap["ap"]["inflight"])
                resp["wl"] = {"q": q, "mt": adm.governor.tier(),
                              "up": round(_t.time() - inst.started_at, 1),
                              "ns": inst.metric_history.samples_count}
            except Exception:  # galaxylint: disable=swallow -- load telemetry must never fail a gossip reply; workers do the same
                pass
        return resp

    def _serve_conn(self, conn):
        import socket
        from galaxysql_tpu.net.dn import recv_msg, send_msg
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while True:
                header, _arrays = recv_msg(conn)
                send_msg(conn, self._handle(header), {})
        except (ConnectionError, OSError, errors.ProtocolError):
            pass  # peer hung up / corrupt frame: drop the connection
        finally:
            conn.close()


def main():  # pragma: no cover - manual entry point
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=3406)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--sync-port", type=int, default=-1,
                    help="coordinator sync-plane port (0 = auto, -1 = off)")
    ap.add_argument("--data-dir", default=None,
                    help="shared metadb/data directory (serving tier peers "
                         "point at the same one)")
    ap.add_argument("--init-sql", default=None,
                    help="semicolon-separated bootstrap statements")
    ap.add_argument("--platform", default=None,
                    help="force the jax platform (e.g. cpu) in-process")
    ap.add_argument("--announce", action="store_true",
                    help="print 'SERVER_READY <mysql_port> <sync_port>' "
                         "once listening (bench/chaos harness handshake)")
    args = ap.parse_args()
    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)
    inst = Instance(data_dir=args.data_dir) if args.data_dir else Instance()
    if args.init_sql:
        sess = Session(inst)
        sess.execute_all(args.init_sql)
        sess.close()
    sync = None
    if args.sync_port >= 0:
        sync = CoordinatorSyncListener(inst)
        sync.start(args.host, args.sync_port)
    server = MySQLServer(inst, args.host, args.port)

    async def _serve():
        await server.start()
        if args.announce:
            print(f"SERVER_READY {server.port} "
                  f"{sync.port if sync else -1}", flush=True)
        await server._server.serve_forever()

    asyncio.run(_serve())


if __name__ == "__main__":  # pragma: no cover
    main()
