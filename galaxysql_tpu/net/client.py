"""Minimal blocking MySQL client (tests + tooling).

Speaks the same wire dialect the server emits: handshake v10 + mysql_native_password,
COM_QUERY with text resultsets, COM_STMT_PREPARE/EXECUTE with binary rows.  Kept
deliberately simple — it exists so protocol tests exercise real bytes end-to-end
without an external driver.
"""

from __future__ import annotations

import socket
import struct
from typing import Any, List, Optional, Tuple

from galaxysql_tpu.net import packets as P


class MySQLError(Exception):
    def __init__(self, errno: int, sqlstate: str, message: str):
        super().__init__(f"({errno}, {sqlstate}): {message}")
        self.errno = errno
        self.sqlstate = sqlstate
        self.message = message


class MiniClient:
    def __init__(self, host: str, port: int, user: str = "root", password: str = "",
                 database: Optional[str] = None, timeout: float = 30.0,
                 compress: bool = False, use_ssl: bool = False):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.seq = 0
        self.more_results = False
        # compressed protocol: negotiated at handshake, framing active after
        self.compress = compress
        self.compressed = False
        self.use_ssl = use_ssl
        self.cseq = 0
        self._inbuf = b""
        self._handshake(user, password, database)
        if compress:
            self.compressed = True

    # -- framing ---------------------------------------------------------------

    def _read_raw(self, n: int) -> bytes:
        if not self.compressed:
            return self._recvn(n)
        import zlib
        while len(self._inbuf) < n:
            hdr = self._recvn(7)
            clen = hdr[0] | (hdr[1] << 8) | (hdr[2] << 16)
            self.cseq = (hdr[3] + 1) & 0xFF
            ulen = hdr[4] | (hdr[5] << 8) | (hdr[6] << 16)
            body = self._recvn(clen)
            self._inbuf += zlib.decompress(body) if ulen else body
        out, self._inbuf = self._inbuf[:n], self._inbuf[n:]
        return out

    def _read_packet(self) -> bytes:
        payload = b""
        while True:
            header = self._read_raw(4)
            length = header[0] | (header[1] << 8) | (header[2] << 16)
            self.seq = (header[3] + 1) & 0xFF
            payload += self._read_raw(length)
            if length < 0xFFFFFF:
                return payload

    def _recvn(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("server closed connection")
            buf += chunk
        return buf

    def _send(self, payload: bytes):
        frames = []
        while True:
            chunk, payload = payload[:0xFFFFFF], payload[0xFFFFFF:]
            header = struct.pack("<I", len(chunk))[:3] + bytes([self.seq])
            self.seq = (self.seq + 1) & 0xFF
            frames.append(header + chunk)
            if len(chunk) < 0xFFFFFF:
                break
        data = b"".join(frames)
        if not self.compressed:
            self.sock.sendall(data)
            return
        import zlib
        # chunk at the same bound the server uses: one compressed frame may not
        # describe more than 2^24-1 payload bytes (3-byte lengths on the wire)
        out = []
        while data:
            chunk, data = data[:0xFFFFF0], data[0xFFFFF0:]
            body, ulen = chunk, 0
            if len(chunk) >= 50:
                z = zlib.compress(chunk)
                # MySQL rule: ship uncompressed (ulen=0) when zlib does not
                # shrink — worst-case expansion on incompressible input would
                # overflow the 3-byte compressed-length field
                if len(z) < len(chunk):
                    body, ulen = z, len(chunk)
            hdr = (struct.pack("<I", len(body))[:3] + bytes([self.cseq]) +
                   struct.pack("<I", ulen)[:3])
            self.cseq = (self.cseq + 1) & 0xFF
            out.append(hdr + body)
        self.sock.sendall(b"".join(out))

    def _command(self, payload: bytes):
        self.seq = 0
        self.cseq = 0
        self._send(payload)

    # -- handshake -------------------------------------------------------------

    def _handshake(self, user: str, password: str, database: Optional[str]):
        greeting = self._read_packet()
        if greeting[0] == 0xFF:
            raise self._err(greeting)
        pos = 1
        end = greeting.index(b"\0", pos)
        self.server_version = greeting[pos:end].decode()
        pos = end + 1
        self.conn_id = struct.unpack_from("<I", greeting, pos)[0]
        pos += 4
        seed = greeting[pos:pos + 8]
        pos += 9
        pos += 2 + 1 + 2 + 2 + 1 + 10  # caps_lo, charset, status, caps_hi, authlen, pad
        end = greeting.index(b"\0", pos)
        seed += greeting[pos:end]
        caps = (P.CLIENT_PROTOCOL_41 | P.CLIENT_SECURE_CONNECTION |
                P.CLIENT_PLUGIN_AUTH | P.CLIENT_MULTI_STATEMENTS |
                P.CLIENT_TRANSACTIONS)
        if self.compress:
            caps |= P.CLIENT_COMPRESS
        if database:
            caps |= P.CLIENT_CONNECT_WITH_DB
        if self.use_ssl:
            # SSLRequest: short header-only response with CLIENT_SSL, then the
            # TLS handshake; the credentialed response goes over the ciphertext
            import ssl as _ssl
            sslreq = struct.pack("<IIB", caps | P.CLIENT_SSL, 1 << 24, 255) + \
                b"\0" * 23
            self._send(sslreq)
            ctx = _ssl.SSLContext(_ssl.PROTOCOL_TLS_CLIENT)
            ctx.check_hostname = False
            ctx.verify_mode = _ssl.CERT_NONE  # self-signed server cert (tests)
            self.sock = ctx.wrap_socket(self.sock)
            caps |= P.CLIENT_SSL
        auth = P.native_password_scramble(password.encode(), seed[:20])
        payload = struct.pack("<IIB", caps, 1 << 24, 255) + b"\0" * 23
        payload += user.encode() + b"\0"
        payload += bytes([len(auth)]) + auth
        if database:
            payload += database.encode() + b"\0"
        payload += b"mysql_native_password\0"
        self._send(payload)
        resp = self._read_packet()
        if resp[0] == 0xFF:
            raise self._err(resp)

    def _err(self, payload: bytes) -> MySQLError:
        errno = struct.unpack_from("<H", payload, 1)[0]
        sqlstate = payload[4:9].decode("ascii", "replace")
        message = payload[9:].decode("utf8", "replace")
        return MySQLError(errno, sqlstate, message)

    # -- queries -----------------------------------------------------------------

    def query(self, sql: str) -> Tuple[List[str], List[Tuple]]:
        """Returns the LAST statement's (column names, rows); use query_all for all."""
        return self.query_all(sql)[-1]

    def query_all(self, sql: str) -> List[Tuple[List[str], List[Tuple]]]:
        self._command(bytes([P.COM_QUERY]) + sql.encode("utf8"))
        out = [self._read_result(binary=False)]
        while self.more_results:
            out.append(self._read_result(binary=False))
        return out

    def ping(self) -> bool:
        self._command(bytes([P.COM_PING]))
        return self._read_packet()[0] == 0

    def binlog_dump(self, since_seq: int = 0, non_block: bool = True) -> list:
        """COM_BINLOG_DUMP: pull the CDC change stream from a SEQ position.

        Returns the decoded event dicts (non-blocking mode reads to the log's
        end).  Each event carries seq/commit_ts/schema/table/kind/payload —
        the server's logical binlog wire form (txn/cdc.py); resume from the
        max seq seen."""
        import json
        flags = 0x01 if non_block else 0
        payload = (bytes([P.COM_BINLOG_DUMP]) +
                   struct.pack("<I", since_seq & 0xFFFFFFFF) +
                   struct.pack("<H", flags) +
                   struct.pack("<I", 1) + struct.pack("<Q", since_seq))
        self._command(payload)
        events = []
        while True:
            pkt = self._read_packet()
            if pkt[0] == 0xFF:
                raise self._err(pkt)
            if pkt[0] == 0xFE and len(pkt) < 9:
                return events  # EOF
            events.append(json.loads(pkt[1:].decode("utf8")))

    def prepare(self, sql: str) -> int:
        self._command(bytes([P.COM_STMT_PREPARE]) + sql.encode("utf8"))
        resp = self._read_packet()
        if resp[0] == 0xFF:
            raise self._err(resp)
        stmt_id = struct.unpack_from("<I", resp, 1)[0]
        n_params = struct.unpack_from("<H", resp, 7)[0]
        for _ in range(n_params):
            self._read_packet()
        if n_params:
            self._read_packet()  # EOF
        self._stmt_params = getattr(self, "_stmt_params", {})
        self._stmt_params[stmt_id] = n_params
        return stmt_id

    def execute(self, stmt_id: int, params: List[Any]) -> Tuple[List[str], List[Tuple]]:
        n = self._stmt_params.get(stmt_id, len(params))
        payload = bytearray(bytes([P.COM_STMT_EXECUTE]) +
                            struct.pack("<IBI", stmt_id, 0, 1))
        if n:
            null_bitmap = bytearray((n + 7) // 8)
            types = bytearray()
            values = bytearray()
            for i, v in enumerate(params):
                if v is None:
                    null_bitmap[i // 8] |= 1 << (i % 8)
                    types += bytes([P.T_NULL, 0])
                elif isinstance(v, bool):
                    types += bytes([P.T_TINY, 0])
                    values += struct.pack("<b", int(v))
                elif isinstance(v, int):
                    types += bytes([P.T_LONGLONG, 0])
                    values += struct.pack("<q", v)
                elif isinstance(v, float):
                    types += bytes([P.T_DOUBLE, 0])
                    values += struct.pack("<d", v)
                else:
                    types += bytes([P.T_VAR_STRING, 0])
                    values += P.lenenc_str(str(v).encode("utf8"))
            payload += bytes(null_bitmap) + b"\x01" + bytes(types) + bytes(values)
        self._command(bytes(payload))
        return self._read_result(binary=True)

    def _read_result(self, binary: bool) -> Tuple[List[str], List[Tuple]]:
        first = self._read_packet()
        if first[0] == 0xFF:
            self.more_results = False
            raise self._err(first)
        if first[0] == 0x00:
            # OK packet: [affected][last_id][status][warnings]
            pos = 1
            _, pos = P.read_lenenc_int(first, pos)
            _, pos = P.read_lenenc_int(first, pos)
            status = struct.unpack_from("<H", first, pos)[0]
            self.more_results = bool(status & P.SERVER_MORE_RESULTS_EXISTS)
            return [], []
        n_cols, _ = P.read_lenenc_int(first, 0)
        names: List[str] = []
        types: List[int] = []
        for _ in range(n_cols):
            cd = self._read_packet()
            pos = 0
            for _field in range(4):  # catalog, schema, table, org_table
                _, pos = P.read_lenenc_str(cd, pos)
            name, pos = P.read_lenenc_str(cd, pos)
            _, pos = P.read_lenenc_str(cd, pos)
            pos += 1 + 2 + 4
            types.append(cd[pos])
            names.append(name.decode("utf8"))
        self._read_packet()  # EOF
        rows: List[Tuple] = []
        while True:
            pkt = self._read_packet()
            if pkt[0] == 0xFE and len(pkt) < 9:
                status = struct.unpack_from("<H", pkt, 3)[0]
                self.more_results = bool(status & P.SERVER_MORE_RESULTS_EXISTS)
                break
            if pkt[0] == 0xFF:
                raise self._err(pkt)
            rows.append(self._decode_row(pkt, types, binary))
        return names, rows

    def _decode_row(self, pkt: bytes, types: List[int], binary: bool) -> Tuple:
        if not binary:
            out = []
            pos = 0
            for _ in types:
                if pkt[pos] == 0xFB:
                    out.append(None)
                    pos += 1
                else:
                    s, pos = P.read_lenenc_str(pkt, pos)
                    out.append(s.decode("utf8"))
            return tuple(out)
        n = len(types)
        null_bitmap = pkt[1:1 + (n + 7 + 2) // 8]
        pos = 1 + (n + 7 + 2) // 8
        out = []
        for i, t in enumerate(types):
            if null_bitmap[(i + 2) // 8] & (1 << ((i + 2) % 8)):
                out.append(None)
                continue
            if t == P.T_TINY:
                out.append(struct.unpack_from("<b", pkt, pos)[0])
                pos += 1
            elif t == P.T_SHORT:
                out.append(struct.unpack_from("<h", pkt, pos)[0])
                pos += 2
            elif t == P.T_LONG:
                out.append(struct.unpack_from("<i", pkt, pos)[0])
                pos += 4
            elif t == P.T_LONGLONG:
                out.append(struct.unpack_from("<q", pkt, pos)[0])
                pos += 8
            elif t == P.T_FLOAT:
                out.append(struct.unpack_from("<f", pkt, pos)[0])
                pos += 4
            elif t == P.T_DOUBLE:
                out.append(struct.unpack_from("<d", pkt, pos)[0])
                pos += 8
            elif t in (P.T_DATE, P.T_DATETIME, P.T_TIMESTAMP):
                ln = pkt[pos]
                pos += 1
                if ln >= 4:
                    y, m, d = struct.unpack_from("<HBB", pkt, pos)
                    s = f"{y:04d}-{m:02d}-{d:02d}"
                    if ln >= 7:
                        hh, mm, ss = struct.unpack_from("<BBB", pkt, pos + 4)
                        s += f" {hh:02d}:{mm:02d}:{ss:02d}"
                    out.append(s)
                else:
                    out.append(None)
                pos += ln
            else:
                s, pos = P.read_lenenc_str(pkt, pos)
                out.append(s.decode("utf8"))
        return tuple(out)

    def close(self):
        try:
            self._command(bytes([P.COM_QUIT]))
        except Exception:  # galaxylint: disable=swallow -- best-effort COM_QUIT on teardown; peer may already be gone
            pass
        self.sock.close()
