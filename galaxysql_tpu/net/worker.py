"""Worker process: a second-process engine serving shipped plan fragments.

Reference analog: the DN side of the CN->DN plane (the MySQL storage node that
`MyJdbcHandler.java:691` ships physical SQL to) collapsed onto this engine: the
worker boots its own `Instance` (own stores, own metadb, own planner) and
serves:

- exec_sql: run shipped SQL, return columnar results (lane arrays + validity
  + dictionary decode on the string columns, so the coordinator re-encodes
  into its own dictionaries)
- sync:     the inter-node sync-action bus (SyncManagerHelper.java:36) —
  invalidate plan cache / baselines, SET config, stats refresh
- ping:     liveness

Run as a process: `python -m galaxysql_tpu.net.worker --port 0` (prints the
bound port on stdout so a parent can attach).
"""

from __future__ import annotations

import argparse
import socket
import sys
import threading
import traceback
from typing import Dict

import numpy as np

from galaxysql_tpu.net.dn import recv_msg, send_msg


class Worker:
    def __init__(self, data_dir=None):
        from galaxysql_tpu.server.instance import Instance
        self.instance = Instance(data_dir=data_dir)
        self.queries: list = []  # shipped-SQL log (tests assert pushdown)
        self._lock = threading.Lock()

    # -- request handlers ----------------------------------------------------

    def handle(self, header: dict, arrays: Dict[str, np.ndarray]):
        op = header.get("op")
        if op == "ping":
            return {"ok": True, "node": self.instance.node_id}, {}
        if op == "exec_sql":
            return self._exec_sql(header)
        if op == "sync":
            return self._sync(header)
        return {"error": f"unknown op {op!r}"}, {}

    def _exec_sql(self, header: dict):
        from galaxysql_tpu.server.session import Session
        sql = header["sql"]
        with self._lock:
            self.queries.append(sql)
        s = Session(self.instance, schema=header.get("schema") or None)
        try:
            rs = s.execute(sql)
            cols = rs.names
            arrays: Dict[str, np.ndarray] = {}
            types = []
            for i, (name, typ) in enumerate(zip(rs.names, rs.types)):
                vals = [r[i] for r in rs.rows]
                valid = np.array([v is not None for v in vals], dtype=bool)
                if typ.is_string:
                    data = np.array([v if v is not None else "" for v in vals],
                                    dtype=object).astype(str)
                elif typ.sql_name().startswith(("DECIMAL", "DOUBLE", "FLOAT")):
                    data = np.array([v if v is not None else 0.0 for v in vals],
                                    dtype=np.float64)
                elif typ.sql_name() in ("DATE", "DATETIME"):
                    data = np.array([v if v is not None else "" for v in vals],
                                    dtype=object).astype(str)
                else:
                    data = np.array([v if v is not None else 0 for v in vals],
                                    dtype=np.int64)
                arrays[f"d::{name}"] = data
                if not valid.all():
                    arrays[f"v::{name}"] = valid
                types.append(typ.sql_name())
            return ({"columns": cols, "types": types, "rows": len(rs.rows),
                     "affected": rs.affected}, arrays)
        finally:
            s.close()

    def _sync(self, header: dict):
        """Sync-action bus (SyncManagerHelper analog)."""
        action = header.get("action")
        payload = header.get("payload") or {}
        inst = self.instance
        if action == "invalidate_plan_cache":
            inst.planner.cache.invalidate_all()
            return {"ok": True, "action": action}, {}
        if action == "invalidate_baselines":
            for row in list(inst.planner.spm.rows()):
                inst.planner.spm.delete(row[0])
            return {"ok": True, "action": action}, {}
        if action == "set_config":
            inst.config.set_instance(payload["name"], payload["value"])
            return {"ok": True, "action": action}, {}
        if action == "table_meta":
            tm = inst.catalog.table(payload["schema"], payload["table"])
            return {"ok": True,
                    "columns": [[c.name, c.dtype.sql_name().split("(")[0],
                                 c.dtype.precision, c.dtype.scale, c.nullable]
                                for c in tm.columns],
                    "primary_key": list(tm.primary_key)}, {}
        if action == "query_log":
            with self._lock:
                return {"ok": True, "queries": list(self.queries)}, {}
        return {"error": f"unknown sync action {action!r}"}, {}

    # -- server loop ---------------------------------------------------------

    def serve(self, host: str = "127.0.0.1", port: int = 0):
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, port))
        srv.listen(16)
        self.port = srv.getsockname()[1]
        print(f"WORKER_READY {self.port}", flush=True)
        while True:
            conn, _ = srv.accept()
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket):
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while True:
                header, arrays = recv_msg(conn)
                try:
                    resp, out = self.handle(header, arrays)
                except Exception as e:
                    traceback.print_exc(file=sys.stderr)
                    resp, out = {"error": f"{type(e).__name__}: {e}"}, {}
                send_msg(conn, resp, out)
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--platform", default=None,
                    help="force the jax platform (e.g. cpu); the environment's "
                         "sitecustomize clobbers JAX_PLATFORMS, so an env var "
                         "cannot do this — it must happen in-process before "
                         "first device use")
    ap.add_argument("--init-sql", default=None,
                    help="semicolon-separated bootstrap statements")
    args = ap.parse_args()
    import os
    import jax
    platform = args.platform or os.environ.get("GALAXYSQL_WORKER_PLATFORM")
    if platform:
        jax.config.update("jax_platforms", platform)
    jax.config.update("jax_enable_x64", True)
    w = Worker(data_dir=args.data_dir)
    if args.init_sql:
        from galaxysql_tpu.server.session import Session
        s = Session(w.instance)
        s.execute(args.init_sql)
        s.close()
    w.serve(port=args.port)


if __name__ == "__main__":
    main()
