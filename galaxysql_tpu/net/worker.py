"""Worker process: a second-process engine serving shipped plan fragments.

Reference analog: the DN side of the CN->DN plane (the MySQL storage node that
`MyJdbcHandler.java:691` ships physical SQL to) collapsed onto this engine: the
worker boots its own `Instance` (own stores, own metadb, own planner) and
serves:

- exec_sql: run shipped SQL, return columnar results (lane arrays + validity
  + dictionary decode on the string columns, so the coordinator re-encodes
  into its own dictionaries)
- sync:     the inter-node sync-action bus (SyncManagerHelper.java:36) —
  invalidate plan cache / baselines, SET config, stats refresh
- ping:     liveness

Run as a process: `python -m galaxysql_tpu.net.worker --port 0` (prints the
bound port on stdout so a parent can attach).
"""

from __future__ import annotations

import argparse
import collections
import os as _os
import socket
import sys
import threading
import time as _time
import traceback
from typing import Dict, Optional

import numpy as np

from galaxysql_tpu.net.dn import recv_msg, send_msg
from galaxysql_tpu.utils.failpoint import (FAIL_POINTS, FP_WORKER_CRASH,
                                           FP_WORKER_SLOW_DRAIN)


class Worker:
    # bounded exactly-once window: uid -> recorded response.  Sized so a
    # coordinator's retry horizon (seconds) fits comfortably; an evicted uid
    # re-applying would need a retry delayed past 1024 newer writes.
    # In-process by design: the exactly-once guarantee is scoped to a worker
    # process lifetime — transactional DML that must survive a crash rides
    # the XA branch protocol (an uncommitted branch dies with the process),
    # and autocommit uid writes retry within milliseconds while a worker
    # restart takes seconds, so a crash lands those retries on a closed
    # port (typed failure), not on a fresh window.
    DEDUPE_WINDOW = 1024

    def __init__(self, data_dir=None):
        from galaxysql_tpu.server.instance import Instance
        self.instance = Instance(data_dir=data_dir)
        self.queries: list = []  # shipped-SQL log (tests assert pushdown)
        self._lock = threading.Lock()
        # open distributed-txn branches: xid -> Session with an open local txn
        self._branches: Dict[str, object] = {}
        # per-branch execution locks: a deadline-killed coordinator may send
        # xa_rollback on a fresh connection while the branch's DML is STILL
        # executing on another thread — the rollback must wait for the
        # in-flight statement, not tear the session out from under it
        self._branch_locks: Dict[str, threading.RLock] = {}
        # resolved-branch tombstones: a late DML that lost the lock race to
        # its own txn's rollback must NOT auto-recreate the branch (an
        # orphaned open txn invisible to xa_recover); bounded like the
        # dedupe window — xids are unique per txn, never legitimately reused
        self._resolved_xids: "collections.OrderedDict[str, bool]" = \
            collections.OrderedDict()
        # idempotency dedupe window: uid-stamped writes record their response
        # so a reconnect replay returns the recorded result instead of
        # double-applying (the coordinator's retry policy relies on this)
        self._dedupe: "collections.OrderedDict[str, tuple]" = \
            collections.OrderedDict()
        self.dedupe_hits = 0
        # sync-epoch plane: origin node -> last-applied broadcast epoch
        # (persisted in the metadb so a restart keeps the gap detector armed)
        self._sync_epochs: Dict[str, int] = {}
        self.heals = 0
        # in-flight request tokens (GIL-atomic list ops): the queue-depth
        # half of the backpressure piggyback every reply carries
        self._active: list = []

    # -- request handlers ----------------------------------------------------

    def handle(self, header: dict, arrays: Dict[str, np.ndarray]):
        if FAIL_POINTS.active and FAIL_POINTS.rpc_spec(
                FP_WORKER_CRASH, header.get("op")) is not None:
            print(f"FP_WORKER_CRASH fired on {header.get('op')}",
                  file=sys.stderr, flush=True)
            _os._exit(137)  # hard crash: no atexit, no flush — chaos realism
        self._active.append(None)
        try:
            if FAIL_POINTS.active:
                # overload harness: a busy/brownout worker (slow drain) —
                # still alive, still correct, just late; breakers stay
                # closed while queue depth and RTT climb.  The sleep sits
                # INSIDE the active bracket so browned-out requests are
                # visible to the queue-depth piggyback.
                spec = FAIL_POINTS.rpc_spec(FP_WORKER_SLOW_DRAIN,
                                            header.get("op"))
                if spec is not None:
                    _time.sleep(float(spec.get("ms", 25.0)) / 1000.0)
            resp, out = self._handle_epochs(header, arrays)
        finally:
            try:
                self._active.pop()
            except IndexError:  # pragma: no cover - bracket imbalance guard
                pass
        if isinstance(resp, dict):
            # backpressure piggyback: queue depth + memory-pressure tier ride
            # every reply (one list len + one pool division — no syncs)
            try:
                # "up"/"ns" (uptime, history samples) feed the pull-free
                # cluster-health view (Instance.cluster_health(pull=False))
                resp["wl"] = {"q": len(self._active),
                              "mt": self.instance.admission.governor.tier(),
                              "up": round(
                                  _time.time() - self.instance.started_at, 1),
                              "ns": self.instance.metric_history.samples_count}
            except Exception as tex:
                # load telemetry must never fail a data request — but a
                # BROKEN piggyback means the coordinator routes blind, so
                # journal it once instead of swallowing (lint: typed-error
                # discipline); deduped: one event, not one per reply
                from galaxysql_tpu.utils import events
                events.publish(
                    "worker_telemetry_failed",
                    f"load piggyback failed: {type(tex).__name__}: {tex}",
                    severity="warn", dedupe="worker-wl")
        return resp, out

    def _handle_epochs(self, header: dict, arrays: Dict[str, np.ndarray]):
        origin, se = header.get("origin"), header.get("se")
        be = header.get("bcast_epoch")
        want_heal = bool(header.get("heal"))  # coordinator-tracked miss
        epoch = None
        if origin and (se is not None or be is not None):
            origin = str(origin)
            epoch = int(be if be is not None else se)
            want_heal |= self._sync_epoch_gap(origin, epoch,
                                              is_bcast=be is not None)
        if want_heal:
            # heal BEFORE the epoch advances: a failed invalidation raises,
            # the request fails, nothing is recorded — the coordinator keeps
            # its needs_heal flag and the next request retries the heal
            self._heal_caches()
        if epoch is not None:
            self._note_sync_epoch(origin, epoch)
        dl = header.get("deadline_ms")
        if dl is not None:
            # remaining-budget form survives clock skew between processes;
            # handlers check the absolute worker-local deadline
            header["_deadline"] = _time.time() + max(0, int(dl)) / 1000.0
        tr = header.get("trace")
        if tr:
            return self._handle_traced(header, arrays, tr)
        return self._handle(header, arrays)

    # -- sync-epoch healing --------------------------------------------------

    def _last_sync_epoch(self, origin: str) -> Optional[int]:
        """Caller holds self._lock."""
        last = self._sync_epochs.get(origin)
        if last is None:
            v = self.instance.metadb.kv_get(f"sync.epoch.{origin}")
            last = int(v) if v is not None else None
        return last

    def _sync_epoch_gap(self, origin: str, se: int, is_bcast: bool) -> bool:
        """Detect missed SyncBus broadcasts; returns True when a heal is due
        (does NOT advance the stored mark — that happens only after a due
        heal succeeded, or a partially-failed heal would be recorded as
        done and the stale-cache hole would silently reopen).

        Only NON-broadcast requests drive the gap check: they carry the
        coordinator's SETTLED epoch (every broadcast through it has
        completed delivery), so anything beyond this worker's last-applied
        mark means an invalidation never arrived.  Broadcast deliveries
        merely advance the mark — concurrent broadcasts race each other's
        client-lock acquisition, so out-of-order arrival is normal, not a
        gap (a genuinely FAILED delivery is covered by the coordinator's
        needs_heal flag)."""
        with self._lock:
            last = self._last_sync_epoch(origin)
            return not is_bcast and last is not None and se > last

    def _note_sync_epoch(self, origin: str, se: int):
        with self._lock:
            last = self._last_sync_epoch(origin)
            if last is None or se > last:
                self._sync_epochs[origin] = se
                self.instance.metadb.kv_put(f"sync.epoch.{origin}", str(se))

    def _heal_caches(self):
        """Wholesale invalidation (missed-broadcast repair).  Failures
        PROPAGATE: the request must fail rather than record a half-done
        heal as success."""
        from galaxysql_tpu.utils.metrics import SYNC_HEALS
        inst = self.instance
        inst.planner.cache.invalidate_all()
        inst.frag_cache.clear()
        inst.privileges.invalidate_cache()
        with self._lock:
            self.heals += 1
        SYNC_HEALS.inc()
        from galaxysql_tpu.utils import events
        events.publish("sync_heal",
                       "missed sync broadcast detected: plan/fragment/"
                       "privilege caches wholesale-invalidated",
                       node=getattr(inst, "node_id", ""))

    # -- idempotency dedupe window -------------------------------------------

    def _dedupe_execute(self, uid: Optional[str], fn):
        """Exactly-once execution for uid-stamped writes, including the
        CONCURRENT-replay race: a reconnect retry can arrive on a fresh
        connection while the original request is still executing (reply-leg
        loss + immediate retry), so the window holds an in-flight marker —
        the racer parks on the owner's event and replays the recorded
        outcome instead of running the statement a second time."""
        if not uid:
            return fn()
        while True:
            with self._lock:
                ent = self._dedupe.get(uid)
                if ent is None:
                    ev = threading.Event()
                    self._dedupe[uid] = ("pending", ev, None)
                    break  # this request owns the execution
            if ent[0] == "done":
                with self._lock:
                    self.dedupe_hits += 1
                resp = dict(ent[1])
                resp["dedup"] = True
                return resp, ent[2]
            # in flight: wait for the owner to settle, then re-check (a
            # FAILED owner removes the entry and the racer executes fresh)
            if not ent[1].wait(timeout=120.0):
                # the original is STILL running: its outcome is unknown to
                # this replay — flag ambiguity so a write caller takes the
                # unknown-outcome path instead of "statement failed, nothing
                # applied" (the original may yet commit)
                return {"error": f"duplicate of uid {uid} still executing",
                        "ambiguous": True}, {}
        try:
            resp, out = fn()
        except Exception:
            with self._lock:
                self._dedupe.pop(uid, None)
            ev.set()
            raise
        with self._lock:
            if resp.get("error"):
                # failures are not recorded: nothing applied, a retry may
                # legitimately re-execute
                self._dedupe.pop(uid, None)
            else:
                self._dedupe[uid] = ("done", dict(resp), out)
                self._dedupe.move_to_end(uid)
                while len(self._dedupe) > self.DEDUPE_WINDOW:
                    # evict the oldest SETTLED entry; in-flight markers are
                    # skipped (never evicted) but must not dam the window —
                    # a hung statement at the head would otherwise let it
                    # grow without bound
                    victim = next((k for k, v in self._dedupe.items()
                                   if v[0] != "pending"), None)
                    if victim is None:
                        break  # only in-flight markers remain
                    del self._dedupe[victim]
        ev.set()
        return resp, out

    def _handle_traced(self, header: dict, arrays: Dict[str, np.ndarray],
                       tr: dict):
        """Coordinator-injected trace context: run the request under a
        worker-local TraceContext and ship the recorded spans back (plus this
        process's request/reply wall clocks, so the coordinator can correct
        for clock offset before grafting them into the query's tree)."""
        from galaxysql_tpu.utils import tracing
        w_recv = tracing.now_us()
        tc = tracing.TraceContext(int(tr.get("trace_id", 0)),
                                  node=self.instance.node_id)
        with tracing.activate(tc):
            with tc.span(f"worker:{header.get('op')}", kind="worker"):
                resp, out = self._handle(header, arrays)
        resp = dict(resp)
        resp["trace"] = {"w_recv_us": w_recv, "w_send_us": tracing.now_us(),
                         "spans": [s.to_dict() for s in tc.spans]}
        return resp, out

    def _handle(self, header: dict, arrays: Dict[str, np.ndarray]):
        op = header.get("op")
        if op == "ping":
            return {"ok": True, "node": self.instance.node_id}, {}
        def _deadline_gate():
            dl = header.get("_deadline")
            if dl is not None and _time.time() > dl:
                from galaxysql_tpu.utils import errors
                # the propagated deadline passed: abort BEFORE doing work.
                # `unapplied` tells the coordinator nothing executed, so a
                # write caller keeps statement-scoped semantics.
                return {"error": f"deadline exceeded before {op}",
                        "errno": errors.QueryTimeoutError.errno,
                        "unapplied": True}, {}
            return None

        uid = header.get("uid") if op in ("dml", "exec_sql") else None
        if uid:
            # dedupe replay outranks the deadline check: a retry of an
            # already-applied write must report the recorded SUCCESS — a
            # timeout answer would tell the client a write failed that its
            # branch will commit (replay costs nothing anyway)
            handler = self._exec_sql if op == "exec_sql" else self._dml
            return self._dedupe_execute(
                uid, lambda: _deadline_gate() or handler(header))
        gated = _deadline_gate()
        if gated is not None:
            return gated
        if op == "exec_sql":
            return self._exec_sql(header)
        if op == "sync":
            return self._sync(header)
        if op == "exec_plan":
            return self._exec_plan(header)
        if op == "dml":
            return self._dml(header)
        if op == "xa_prepare":
            return self._xa_prepare(header)
        if op == "xa_commit":
            return self._xa_commit(header)
        if op == "xa_rollback":
            return self._xa_rollback(header)
        if op == "xa_recover":
            return self._xa_recover()
        return {"error": f"unknown op {op!r}"}, {}

    # -- distributed-txn branch ops (the DN side of TsoTransaction 2PC,
    # TsoTransaction.java:166-216: per-shard XA PREPARE/COMMIT) --------------

    def _branch_lock(self, xid: str) -> threading.RLock:
        with self._lock:
            lk = self._branch_locks.get(xid)
            if lk is None:
                lk = self._branch_locks[xid] = threading.RLock()
            return lk

    def _tombstone_branch(self, xid: str):
        """Record a resolved xid (called INSIDE the branch lock so a parked
        DML observes it the moment it wakes)."""
        with self._lock:
            self._resolved_xids[xid] = True
            while len(self._resolved_xids) > self.DEDUPE_WINDOW * 4:
                self._resolved_xids.popitem(last=False)

    def _dml(self, header: dict):
        """Execute shipped DML inside the branch's open local transaction."""
        from galaxysql_tpu.server.session import Session
        xid = header["xid"]
        with self._branch_lock(xid):
            with self._lock:
                self.queries.append(header["sql"])
                s = self._branches.get(xid)
                if s is None and xid in self._resolved_xids:
                    # this branch already committed/rolled back — a late DML
                    # that lost the lock race must not resurrect it as an
                    # orphaned open transaction
                    return {"error":
                            f"branch {xid!r} already resolved"}, {}
                if s is None:
                    s = Session(self.instance,
                                schema=header.get("schema") or None)
                    s.autocommit = False
                    s._begin()
                    self._branches[xid] = s
            if header.get("schema"):
                s.schema = header["schema"]
            rs = self._with_deadline(
                s, header.get("_deadline"),
                lambda: s.execute(header["sql"], header.get("params") or []))
            return {"ok": True, "affected": rs.affected}, {}

    _UNSET = object()

    @classmethod
    def _with_deadline(cls, sess, deadline, fn):
        """Run `fn` with the remaining deadline budget handed to the nested
        session as its own MAX_EXECUTION_TIME (drain-boundary checks enforce
        it); shared by the shipped-SQL and branch-DML handlers.  Branch
        sessions are long-lived, so any pre-existing session value is
        restored, not dropped."""
        if deadline is None:
            return fn()
        prior = sess.vars.get("MAX_EXECUTION_TIME", cls._UNSET)
        sess.vars["MAX_EXECUTION_TIME"] = \
            max(1, int((deadline - _time.time()) * 1000))
        try:
            return fn()
        finally:
            if prior is cls._UNSET:
                sess.vars.pop("MAX_EXECUTION_TIME", None)
            else:
                sess.vars["MAX_EXECUTION_TIME"] = prior

    def _xa_prepare(self, header: dict):
        import json
        from galaxysql_tpu.txn.xa import participants_of
        xid = header["xid"]
        with self._branch_lock(xid):
            return self._xa_prepare_locked(header, xid, json,
                                           participants_of)

    def _xa_prepare_locked(self, header, xid, json, participants_of):
        s = self._branches.get(xid)
        if s is None or s.txn is None:
            return {"ok": False, "error": f"unknown branch {xid!r}"}, {}
        parts = participants_of(s.txn)
        for sp in parts:
            if not sp.prepare():
                for done in parts:
                    done.rollback()
                self._branches.pop(xid, None)
                s.txn = None
                s.close()  # deregister: a leaked session reads as an open txn
                return {"ok": False, "error": "branch prepare failed"}, {}
        # durability order: store snapshots FIRST, marker LAST — a crash before
        # the marker means prepare was never acked (presumed abort is correct);
        # after the marker the provisional rows are on disk and recovery holds
        # them in doubt (recover_persisted skips marked branches)
        self.instance.save()
        self.instance.metadb.kv_put(
            f"xa.branch.{xid}",
            json.dumps({"txn_id": s.txn.txn_id, "state": "PREPARED"}))
        return {"ok": True}, {}

    def _branch_txn_id(self, xid: str):
        import json
        v = self.instance.metadb.kv_get(f"xa.branch.{xid}")
        if v is None:
            return None
        try:
            return int(json.loads(v)["txn_id"])
        except Exception:  # galaxylint: disable=swallow -- kv probe: None means no such branch, the caller's contract
            return None

    def _finalize_stamps(self, txn_id: int, commit_ts):
        """Resolve ±txn_id provisional stamps across all stores (used when the
        branch session died with the process; mirrors recover_persisted)."""
        from galaxysql_tpu.storage.table_store import INFINITY_TS
        own = -txn_id
        for store in self.instance.stores.values():
            for p in store.partitions:
                with p.lock:
                    if commit_ts is not None:
                        p.begin_ts[p.begin_ts == own] = commit_ts
                        p.end_ts[p.end_ts == own] = commit_ts
                    else:
                        p.end_ts[p.end_ts == own] = INFINITY_TS
                        mine = p.begin_ts == own
                        p.begin_ts[mine] = INFINITY_TS
                        p.end_ts[mine] = 0
            store.table.bump_version()
        self.instance.catalog.version += 1

    def _xa_commit(self, header: dict):
        import json
        from galaxysql_tpu.txn.xa import participants_of
        xid = header["xid"]
        with self._branch_lock(xid):
            out = self._xa_commit_locked(header, xid, json, participants_of)
            self._tombstone_branch(xid)
        with self._lock:
            # branch resolved: drop its lock entry (unique xids would
            # otherwise leak one RLock per distributed txn forever)
            self._branch_locks.pop(xid, None)
        return out

    def _xa_commit_locked(self, header, xid, json, participants_of):
        commit_ts = int(header["commit_ts"])
        # the coordinator's TSO is the clock: local snapshots must advance past
        # the commit stamp or the new rows would be invisible to local reads
        self.instance.tso.observe(commit_ts)
        s = self._branches.pop(xid, None)
        if s is not None and s.txn is not None:
            txn = s.txn
            s.txn = None
            for sp in participants_of(txn):
                sp.commit(commit_ts)
            self.instance.cdc.flush_txn(txn, commit_ts)
            self.instance.catalog.version += 1
            s.close()
            txn_id = txn.txn_id
        else:
            txn_id = self._branch_txn_id(xid)
            if txn_id is None:
                # idempotent: branch already resolved (re-sent commit)
                return {"ok": True, "already": True}, {}
            self._finalize_stamps(txn_id, commit_ts)
        self.instance.metadb.tx_log_put(txn_id, "DONE", commit_ts)
        self.instance.metadb.kv_put(f"xa.branch.{xid}",
                                    json.dumps({"txn_id": txn_id,
                                                "state": "DONE"}))
        self.instance.save()
        return {"ok": True}, {}

    def _xa_rollback(self, header: dict):
        import json
        from galaxysql_tpu.txn.xa import participants_of
        xid = header["xid"]
        # serialized against an in-flight _dml on the same branch: roll back
        # only AFTER the statement settles, never mid-execution
        with self._branch_lock(xid):
            out = self._xa_rollback_locked(header, xid, json,
                                           participants_of)
            self._tombstone_branch(xid)
        with self._lock:
            self._branch_locks.pop(xid, None)  # branch resolved
        return out

    def _xa_rollback_locked(self, header, xid, json, participants_of):
        s = self._branches.pop(xid, None)
        if s is not None and s.txn is not None:
            txn = s.txn
            s.txn = None
            for sp in participants_of(txn):
                sp.rollback()
            s.close()
            txn_id = txn.txn_id
        else:
            txn_id = self._branch_txn_id(xid)
            if txn_id is None:
                return {"ok": True, "already": True}, {}
            self._finalize_stamps(txn_id, None)
        self.instance.metadb.tx_log_put(txn_id, "ABORTED")
        self.instance.metadb.kv_put(f"xa.branch.{xid}",
                                    json.dumps({"txn_id": txn_id,
                                                "state": "ABORTED"}))
        self.instance.save()
        return {"ok": True}, {}

    def _xa_recover(self):
        """List PREPARED (in-doubt) branches for the coordinator to resolve."""
        import json
        xids = []
        for k, v in self.instance.metadb.kv_scan("xa.branch."):
            try:
                if json.loads(v).get("state") == "PREPARED":
                    xids.append(k[len("xa.branch."):])
            except Exception:  # galaxylint: disable=swallow -- one corrupt branch record must not hide the other in-doubt xids
                continue
        return {"ok": True, "xids": xids}, {}

    def _exec_sql(self, header: dict):
        import contextlib
        from galaxysql_tpu.server.session import Session
        from galaxysql_tpu.utils import tracing
        sql = header["sql"]
        with self._lock:
            self.queries.append(sql)
        tc = tracing.current()

        def scope(name):
            return tc.span(name, kind="operator") if tc is not None \
                else contextlib.nullcontext()
        # an xid routes the statement through that branch's open session so
        # reads observe the branch's own uncommitted writes (the degrade path
        # must keep the same txn visibility the fragment path has)
        branch = self._branches.get(header.get("xid")) \
            if header.get("xid") else None
        dl = header.get("_deadline")
        if branch is not None:
            if header.get("schema"):
                branch.schema = header["schema"]
            with scope("execute"):
                rs = self._with_deadline(branch, dl,
                                         lambda: branch.execute(sql))
            with scope("serialize"):
                return self._serialize_rs(rs)
        s = Session(self.instance, schema=header.get("schema") or None)
        try:
            with scope("execute"):
                rs = self._with_deadline(s, dl, lambda: s.execute(sql))
            with scope("serialize"):
                return self._serialize_rs(rs)
        finally:
            s.close()

    @staticmethod
    def _serialize_rs(rs):
        """ResultSet -> wire response (shared by the plain and branch paths)."""
        cols = rs.names
        arrays: Dict[str, np.ndarray] = {}
        types = []
        batch_cols = None
        if rs.batch is not None:
            bc = rs.batch.compact()
            if len(bc.names()) == len(rs.names):
                batch_cols = [bc.columns[n] for n in bc.names()]
        for i, (name, typ) in enumerate(zip(rs.names, rs.types)):
            vals = [r[i] for r in rs.rows]
            valid = np.array([v is not None for v in vals], dtype=bool)
            if typ.is_string:
                data = np.array([v if v is not None else "" for v in vals],
                                dtype=object).astype(str)
            elif typ.sql_name().startswith("DECIMAL") and batch_cols is not None:
                # lane-exact: scaled int64 straight from the engine lane —
                # a float round-trip truncates >15-16 significant digits
                data = batch_cols[i].np_data().astype(np.int64)
                arrays[f"d::{name}"] = data
                if not valid.all():
                    arrays[f"v::{name}"] = valid
                types.append(typ.sql_name() + "#scaled")
                continue
            elif typ.sql_name().startswith(("DECIMAL", "DOUBLE", "FLOAT")):
                data = np.array([v if v is not None else 0.0 for v in vals],
                                dtype=np.float64)
            elif typ.sql_name() in ("DATE", "DATETIME"):
                data = np.array([v if v is not None else "" for v in vals],
                                dtype=object).astype(str)
            else:
                data = np.array([v if v is not None else 0 for v in vals],
                                dtype=np.int64)
            arrays[f"d::{name}"] = data
            if not valid.all():
                arrays[f"v::{name}"] = valid
            types.append(typ.sql_name())
        return ({"columns": cols, "types": types, "rows": len(rs.rows),
                 "affected": rs.affected}, arrays)

    _SARG_OPS = {"eq": np.equal, "lt": np.less, "le": np.less_equal,
                 "gt": np.greater, "ge": np.greater_equal}

    @staticmethod
    def _wire_lane(tm, cname: str, lane: np.ndarray):
        """Lane -> wire array + type tag: the ONE encoder for fragment results
        and deleted-key lists (strings decode via the dictionary, DATE/DATETIME
        format to text, DECIMAL ships scaled int64 tagged '#scaled')."""
        cm = tm.column(cname)
        tname = cm.dtype.sql_name()
        if cm.dtype.is_string:
            d = tm.dictionaries.get(cname.lower())
            vals = d.decode(lane) if d is not None else [""] * lane.size
            arr = np.array([x if x is not None else "" for x in vals],
                           dtype=object).astype(str) if lane.size else \
                np.zeros(0, dtype="U1")
            return arr, tname
        if tname.startswith("DECIMAL"):
            return lane.astype(np.int64), tname + "#scaled"
        if tname in ("DATE", "DATETIME"):
            from galaxysql_tpu.types import temporal
            fmt = temporal.format_date if tname == "DATE" \
                else temporal.format_datetime
            arr = np.array([fmt(int(x)) for x in lane],
                           dtype=object).astype(str) if lane.size else \
                np.zeros(0, dtype="U1")
            return arr, tname
        if tname in ("DOUBLE", "FLOAT"):
            return lane.astype(np.float64), tname
        return lane.astype(np.int64), tname

    def _exec_plan(self, header: dict):
        """Execute a shipped physical scan fragment straight against the store.

        Reference analog: `PolarxExecPlan` key-Get/scan execution
        (`MyJdbcHandler.java:691-742`, `RelToXPlanConverter.java:41`): the
        coordinator ships a bound fragment — table, pruned column list,
        lane-domain SARGs, optional point key — and the worker runs it with
        zero parse/plan work.  Unsupported shapes raise; the coordinator
        degrades to SQL text (`XPlanTemplate.java:132` fallback)."""
        f = header["fragment"]
        with self._lock:
            self.queries.append(f"PLAN:{f['schema']}.{f['table']}"
                                f":{','.join(f['columns'])}")
        inst = self.instance
        tm = inst.catalog.table(f["schema"], f["table"])
        store = inst.store(f["schema"], f["table"])
        snapshot = inst.tso.next_timestamp()
        # read-your-own-writes across the seam: a fragment carrying the
        # session's branch xid sees that branch's provisional rows (the
        # reference reads through the txn-bound DN connection)
        txn_id = 0
        bs = self._branches.get(f.get("xid")) if f.get("xid") else None
        if bs is not None and bs.txn is not None:
            txn_id = bs.txn.txn_id
        point = f.get("point")
        lane_point = None
        if point is not None:
            # the CN ships point keys ALREADY in lane domain (scan.point_eq is
            # _lane_encode'd there); re-encoding would double-scale decimals
            lane_point = point[1]
        sargs = f.get("sargs") or []
        since = f.get("since")  # delta reads (online table move catchup)
        del_of = f.get("deleted_since_of")
        cols_out: Dict[str, list] = {c: [] for c in f["columns"]}
        valid_out: Dict[str, list] = {c: [] for c in f["columns"]}
        deleted_keys: list = []
        # traced fragments: scan / rf-prune / serialize child spans under the
        # worker root (grafted into the coordinator's tree by the RPC layer)
        import contextlib
        from galaxysql_tpu.utils import tracing
        tc = tracing.current()
        scan_scope = tc.span("scan", kind="operator",
                             table=f"{f['schema']}.{f['table']}") \
            if tc is not None else contextlib.nullcontext()
        # rf-prune attribution is traced-only: counting surviving rows costs
        # an O(partition) sum the untraced fragment path must not pay
        rf_clock = [0.0, 0] \
            if tc is not None and (f.get("rf_in") or sargs) else None
        with scan_scope:
            err = self._exec_plan_scan(f, store, snapshot, txn_id, lane_point,
                                       point, sargs, since, del_of, cols_out,
                                       valid_out, deleted_keys, rf_clock,
                                       deadline=header.get("_deadline"))
        if err is not None:
            return err, {}
        if rf_clock is not None:
            tc.add("rf-prune", kind="operator",
                   dur_us=round(rf_clock[0] * 1e6, 1),
                   rows_pruned=rf_clock[1])
        ser_scope = tc.span("serialize", kind="operator") \
            if tc is not None else contextlib.nullcontext()
        with ser_scope:
            return self._exec_plan_reply(f, tm, del_of, cols_out, valid_out,
                                         deleted_keys, snapshot)

    def _exec_plan_scan(self, f, store, snapshot, txn_id, lane_point, point,
                        sargs, since, del_of, cols_out, valid_out,
                        deleted_keys, rf_clock, deadline=None):
        import time as _t
        from galaxysql_tpu.utils import errors as _err
        for p in store.partitions:
            if deadline is not None and _t.time() > deadline:
                # partition boundary = the worker's drain boundary: abort the
                # fragment typed instead of finishing a doomed scan
                raise _err.QueryTimeoutError(
                    f"fragment deadline exceeded scanning "
                    f"{f['schema']}.{f['table']}")
            if p.num_rows == 0:
                continue
            with p.lock:
                if lane_point is not None:
                    ids = p.key_candidates(point[0], lane_point)
                    if ids.size == 0:
                        continue
                    from galaxysql_tpu import native as _native
                    # visibility over the CANDIDATE slice only — a full-lane
                    # mask would cost O(partition) on the point hot path
                    keep = p.valid[point[0]][ids] & _native.visible_mask(
                        p.begin_ts[ids], p.end_ts[ids], snapshot, txn_id)
                    ids = ids[keep]
                else:
                    vis = p.visible_mask(snapshot, txn_id)
                    if since is not None:
                        vis = vis & (p.begin_ts > int(since))
                    t_rf = _t.perf_counter() if rf_clock is not None else 0.0
                    before = int(vis.sum()) if rf_clock is not None else 0
                    for col, op, val in sargs:
                        opf = self._SARG_OPS.get(op)
                        if opf is None:
                            return {"error": f"unsupported sarg op {op!r}"}
                        lane = p.lanes[col]
                        # integer lanes compare in int64 — a float64 cast
                        # collapses values beyond 2^53 and worker-side
                        # exclusion is load-bearing (rows never reach the CN)
                        if isinstance(val, int) and \
                                np.issubdtype(lane.dtype, np.integer):
                            vis = vis & p.valid[col] & \
                                opf(lane.astype(np.int64), np.int64(val))
                        else:
                            vis = vis & p.valid[col] & \
                                opf(lane.astype(np.float64), float(val))
                    for col, vals in (f.get("rf_in") or []):
                        # runtime-filter IN-list (small join build sides):
                        # exact membership prune before rows cross the seam
                        lane = p.lanes[col]
                        arr = np.asarray(vals)
                        vis = vis & p.valid[col] & \
                            np.isin(lane, arr.astype(lane.dtype, copy=False))
                    ids = np.nonzero(vis)[0]
                    if rf_clock is not None:
                        # rf-prune attribution (host-side): time + rows
                        # removed by SARGs/IN-lists, summed over partitions
                        rf_clock[0] += _t.perf_counter() - t_rf
                        rf_clock[1] += before - int(ids.size)
                if del_of is not None:
                    dmask = (p.end_ts >= 0) & (p.end_ts > int(since or 0)) & \
                        (p.end_ts <= snapshot)
                    if dmask.any():
                        deleted_keys.append(p.lanes[del_of][dmask])
                if ids.size == 0:
                    continue
                for c in f["columns"]:
                    cols_out[c].append(p.lanes[c][ids])
                    valid_out[c].append(p.valid[c][ids])
        return None

    def _exec_plan_reply(self, f, tm, del_of, cols_out, valid_out,
                         deleted_keys, snapshot):
        """Wire-encode the gathered lanes (the `serialize` span's work)."""
        arrays: Dict[str, np.ndarray] = {}
        types = []
        for c in f["columns"]:
            lane = (np.concatenate(cols_out[c]) if cols_out[c]
                    else np.zeros(0, dtype=tm.column(c).dtype.lane))
            v = (np.concatenate(valid_out[c]) if valid_out[c]
                 else np.zeros(0, dtype=np.bool_))
            arr, tname = self._wire_lane(tm, c, lane)
            arrays[f"d::{c}"] = arr
            if lane.size and not bool(v.all()):
                arrays[f"v::{c}"] = v
            types.append(tname)
        if del_of is not None:
            dk = (np.concatenate(deleted_keys) if deleted_keys
                  else np.zeros(0, dtype=np.int64))
            # wire-value domain (decoded strings / formatted dates / scaled
            # ints) so the caller's DELETE literals match what it inserted
            arrays["deleted::keys"], _ = self._wire_lane(tm, del_of, dk)
        n = int(arrays[f"d::{f['columns'][0]}"].shape[0]) if f["columns"] else 0
        return ({"columns": list(f["columns"]), "types": types, "rows": n,
                 "affected": 0, "snapshot": snapshot}, arrays)

    def _sync(self, header: dict):
        """Sync-action bus (SyncManagerHelper analog)."""
        action = header.get("action")
        payload = header.get("payload") or {}
        inst = self.instance
        if action == "invalidate_plan_cache":
            inst.planner.cache.invalidate_all()
            return {"ok": True, "action": action}, {}
        if action == "invalidate_fragment_cache":
            # a coordinator wrote to a table this node may hold cached
            # fragments for: bump the epoch (remote-keyed fragments) and drop
            # resident entries (exec/fragment_cache.py invalidation plane)
            key = payload.get("table_key") or \
                (f"{payload.get('schema', '').lower()}"
                 f".{payload.get('table', '').lower()}")
            inst.frag_cache.bump_epoch(key)
            return {"ok": True, "action": action}, {}
        if action == "invalidate_baselines":
            for row in list(inst.planner.spm.rows()):
                inst.planner.spm.delete(row[0])
            return {"ok": True, "action": action}, {}
        if action == "set_config":
            inst.config.set_instance(payload["name"], payload["value"])
            return {"ok": True, "action": action}, {}
        if action == "table_meta":
            tm = inst.catalog.table(payload["schema"], payload["table"])
            return {"ok": True,
                    "columns": [[c.name, c.dtype.sql_name().split("(")[0],
                                 c.dtype.precision, c.dtype.scale, c.nullable]
                                for c in tm.columns],
                    "primary_key": list(tm.primary_key)}, {}
        if action == "query_log":
            with self._lock:
                return {"ok": True, "queries": list(self.queries)}, {}
        if action == "failpoint":
            # remote fault arming for the chaos harness: the coordinator (or
            # a test) plants worker-side failpoints (e.g. FP_WORKER_CRASH)
            if payload.get("clear"):
                FAIL_POINTS.clear()
            elif payload.get("disarm"):
                FAIL_POINTS.disarm(payload["key"])
            else:
                FAIL_POINTS.arm(payload["key"], payload.get("value", True))
            return {"ok": True, "action": action}, {}
        if action == "worker_stats":
            # fault-tolerance observability: dedupe window, sync-epoch heals
            with self._lock:
                return {"ok": True, "node": inst.node_id,
                        "dedupe_entries": len(self._dedupe),
                        "dedupe_hits": self.dedupe_hits,
                        "heals": self.heals,
                        "sync_epochs": dict(self._sync_epochs)}, {}
        if action == "health":
            # SLO-plane cluster view: workers run the same sampler over
            # their own registries (the Worker's Instance constructs one);
            # a health pull takes an interval-gated sample, then reports a
            # snapshot summary — pull-driven, so an idle worker pays zero
            mh = inst.metric_history
            mh.maybe_sample()
            return {"ok": True, "action": action, "node": inst.node_id,
                    "uptime_s": round(_time.time() - inst.started_at, 3),
                    "active": float(len(self._active)),
                    "qps": round(mh.rate("queries_total"), 3),
                    "error_rate": round(mh.rate("query_errors"), 6),
                    "mem_tier": int(inst.admission.governor.tier()),
                    "samples": int(mh.summary()["samples"]),
                    "burning": inst.slo.burning_names()}, {}
        return {"error": f"unknown sync action {action!r}"}, {}

    # -- server loop ---------------------------------------------------------

    def serve(self, host: str = "127.0.0.1", port: int = 0):
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, port))
        srv.listen(16)
        self.port = srv.getsockname()[1]
        print(f"WORKER_READY {self.port}", flush=True)
        while True:
            conn, _ = srv.accept()
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket):
        from galaxysql_tpu.utils import errors as _err
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while True:
                header, arrays = recv_msg(conn)
                try:
                    resp, out = self.handle(header, arrays)
                except Exception as e:
                    traceback.print_exc(file=sys.stderr)
                    # typed errors keep their errno across the wire so the
                    # coordinator re-raises the same class (QueryTimeoutError
                    # must not come back as a generic TddlError)
                    resp, out = {"error": f"{type(e).__name__}: {e}",
                                 "errno": int(getattr(e, "errno", 1105)
                                              or 1105)}, {}
                try:
                    send_msg(conn, resp, out)
                except _err.ProtocolError as pe:
                    # the RESULT was oversized: encode_msg rejected it before
                    # any byte shipped, so the stream is still aligned —
                    # reply typed instead of dropping a healthy connection
                    # (and triggering coordinator retries of the same query)
                    send_msg(conn, {"error": str(pe), "errno": pe.errno}, {})
        except (ConnectionError, OSError):
            pass
        except _err.ProtocolError:
            # corrupt frame: the stream is unrecoverable — drop the conn
            traceback.print_exc(file=sys.stderr)
        finally:
            conn.close()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--platform", default=None,
                    help="force the jax platform (e.g. cpu); the environment's "
                         "sitecustomize clobbers JAX_PLATFORMS, so an env var "
                         "cannot do this — it must happen in-process before "
                         "first device use")
    ap.add_argument("--init-sql", default=None,
                    help="semicolon-separated bootstrap statements")
    args = ap.parse_args()
    import os
    import jax
    platform = args.platform or os.environ.get("GALAXYSQL_WORKER_PLATFORM")
    if platform:
        jax.config.update("jax_platforms", platform)
    jax.config.update("jax_enable_x64", True)
    w = Worker(data_dir=args.data_dir)
    if args.init_sql:
        from galaxysql_tpu.server.session import Session
        s = Session(w.instance)
        s.execute(args.init_sql)
        s.close()
    w.serve(port=args.port)


if __name__ == "__main__":
    main()
