"""The CN<->worker RPC plane: plan shipping to a second process.

Reference analog: the CN->DN seam — `repo/mysql/spi/MyJdbcHandler.java:691`
(physical SQL shipped to the shard's storage node and executed there) plus the
inter-CN sync-action bus (`executor/sync/SyncManagerHelper.java:36`).  A worker
(`galaxysql_tpu.net.worker`) is a real second OS process hosting its own
engine Instance; the coordinator attaches its tables as *remote tables* whose
scans compile to shipped SQL (filters/column pruning pushed down), so one
query's fragments genuinely span two processes.

Wire format: length-prefixed JSON header + raw npy column payloads over a
localhost TCP socket.  JSON (not pickle) on purpose: the socket is an internal
trust boundary and must not be an arbitrary-code-execution vector.
"""

from __future__ import annotations

import io
import json
import socket
import struct
import threading
from time import perf_counter as _perf
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

_HDR = struct.Struct(">I")


def send_msg(sock: socket.socket, header: dict,
             arrays: Optional[Dict[str, np.ndarray]] = None):
    """[u32 jsonlen][json][per-array: u32 namelen][name][u32 npylen][npy]"""
    arrays = arrays or {}
    header = dict(header)
    header["n_arrays"] = len(arrays)
    hb = json.dumps(header).encode()
    out = [_HDR.pack(len(hb)), hb]
    for name, arr in arrays.items():
        buf = io.BytesIO()
        np.save(buf, np.ascontiguousarray(arr), allow_pickle=False)
        nb = name.encode()
        out += [_HDR.pack(len(nb)), nb, _HDR.pack(buf.getbuffer().nbytes),
                buf.getvalue()]
    sock.sendall(b"".join(out))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        b = sock.recv(min(n, 1 << 20))
        if not b:
            raise ConnectionError("peer closed")
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


def recv_msg(sock: socket.socket) -> Tuple[dict, Dict[str, np.ndarray]]:
    (hlen,) = _HDR.unpack(_recv_exact(sock, 4))
    header = json.loads(_recv_exact(sock, hlen))
    arrays: Dict[str, np.ndarray] = {}
    for _ in range(header.get("n_arrays", 0)):
        (nlen,) = _HDR.unpack(_recv_exact(sock, 4))
        name = _recv_exact(sock, nlen).decode()
        (alen,) = _HDR.unpack(_recv_exact(sock, 4))
        arrays[name] = np.load(io.BytesIO(_recv_exact(sock, alen)),
                               allow_pickle=False)
    return header, arrays


class WorkerClient:
    """Coordinator-side connection to one worker process (one socket, locked:
    the protocol is strictly request/response)."""

    def __init__(self, host: str, port: int, timeout: float = 180.0):
        # generous default: the worker's FIRST query on a cold process pays
        # XLA compiles; ping() overrides with a short probe timeout
        self.timeout = timeout
        self.addr = (host, port)
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def _connect(self):
        if self._sock is None:
            s = socket.create_connection(self.addr, timeout=self.timeout)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s

    # ops whose worker-side execution is worth a span subtree; control-plane
    # chatter (ping, sync, xa_*) stays untraced
    _TRACED_OPS = frozenset({"exec_plan", "exec_sql", "dml"})

    def request(self, header: dict,
                arrays: Optional[Dict[str, np.ndarray]] = None
                ) -> Tuple[dict, Dict[str, np.ndarray]]:
        from galaxysql_tpu.utils import tracing
        from galaxysql_tpu.utils.metrics import RPC_RTT_MS
        tc = tracing.current()
        rpc_span = None
        if tc is not None and header.get("op") in self._TRACED_OPS:
            # inject trace context into the fragment RPC: the worker opens
            # child spans under `parent` and ships them back in the response
            header = dict(header)
            header["trace"] = {"trace_id": tc.trace_id,
                               "parent": tc.cursor, "node": tc.node}
            rpc_span = tc.begin(f"rpc:{header['op']}", kind="rpc",
                                worker=f"{self.addr[0]}:{self.addr[1]}")
        # timestamps bracket the ACTUAL wire round-trip (captured inside the
        # lock, re-captured on the reconnect retry): lock-wait and retry time
        # must skew neither the NTP-style clock offset nor rpc_rtt_ms
        t_send = t_recv = 0
        rtt_ms = 0.0
        try:
            with self._lock:
                self._connect()
                try:
                    t_send, t0 = tracing.now_us(), _perf()
                    send_msg(self._sock, header, arrays)
                    resp, arrs = recv_msg(self._sock)
                except (ConnectionError, OSError):
                    # one reconnect: the worker may have restarted between
                    # queries
                    self.close()
                    self._connect()
                    t_send, t0 = tracing.now_us(), _perf()
                    send_msg(self._sock, header, arrays)
                    resp, arrs = recv_msg(self._sock)
                rtt_ms = (_perf() - t0) * 1000.0
                t_recv = tracing.now_us()
        finally:
            if rpc_span is not None:
                tc.end(rpc_span)
        RPC_RTT_MS.observe(rtt_ms)
        if rpc_span is not None:
            self._graft_trace(tc, rpc_span, resp, t_send, t_recv)
        if resp.get("error"):
            from galaxysql_tpu.utils import errors
            raise errors.TddlError(f"worker {self.addr}: {resp['error']}")
        return resp, arrs

    @staticmethod
    def _graft_trace(tc, rpc_span, resp: dict, t_send: int, t_recv: int):
        """Adopt the worker's span subtree under the RPC span, correcting its
        wall clock: the NTP-style offset `((t_send+t_recv) - (w_recv+w_send))
        / 2` maps the worker's timestamps onto the coordinator's timeline
        (symmetric-latency assumption — localhost sockets here, where the
        residual error is microseconds)."""
        wt = resp.pop("trace", None)
        if not wt:
            return
        try:
            w_recv = int(wt.get("w_recv_us", 0))
            w_send = int(wt.get("w_send_us", 0))
            offset = ((t_send + t_recv) - (w_recv + w_send)) // 2 \
                if w_recv and w_send else 0
            spans = tc.graft(wt.get("spans") or [], parent=rpc_span.span_id,
                             offset_us=offset)
            rpc_span.attrs["worker_spans"] = len(spans)
            rpc_span.attrs["clock_offset_us"] = offset
        except Exception:
            # a malformed trace payload must never fail the data request
            rpc_span.attrs["worker_spans"] = -1

    def execute(self, sql: str, schema: str = "",
                xid: Optional[str] = None) -> Tuple[List[str], List[str],
                                                    Dict[str, np.ndarray],
                                                    Dict[str, np.ndarray]]:
        """Ship SQL; returns (columns, sql_types, data arrays, valid arrays).
        With `xid`, the worker runs it in that txn branch's session (reads see
        the branch's uncommitted writes)."""
        hdr = {"op": "exec_sql", "sql": sql, "schema": schema}
        if xid is not None:
            hdr["xid"] = xid
        resp, arrs = self.request(hdr)
        cols = resp["columns"]
        data = {c: arrs[f"d::{c}"] for c in cols}
        valid = {c: arrs[f"v::{c}"] for c in cols if f"v::{c}" in arrs}
        return cols, resp["types"], data, valid

    def exec_plan(self, fragment: dict) -> Tuple[List[str], List[str],
                                                 Dict[str, np.ndarray],
                                                 Dict[str, np.ndarray]]:
        """Ship a serialized physical fragment (XPlan analog,
        `RelToXPlanConverter.java:41` / `XPlanTemplate.java:86`): the worker
        executes it straight against its store — no re-parse, no re-plan.
        Raises on an unsupported fragment; the caller degrades to exec_sql."""
        resp, arrs = self.request({"op": "exec_plan", "fragment": fragment})
        cols = resp["columns"]
        data = {c: arrs[f"d::{c}"] for c in cols}
        valid = {c: arrs[f"v::{c}"] for c in cols if f"v::{c}" in arrs}
        return cols, resp["types"], data, valid

    def sync_action(self, action: str, payload: dict) -> dict:
        """Inter-node sync bus (SyncManagerHelper analog): cache invalidation,
        config changes, baseline ops."""
        resp, _ = self.request({"op": "sync", "action": action,
                                "payload": payload})
        return resp

    def ping(self, timeout: float = 5.0) -> bool:
        try:
            with self._lock:
                self._connect()
                self._sock.settimeout(timeout)
                try:
                    send_msg(self._sock, {"op": "ping"})
                    resp, _ = recv_msg(self._sock)
                finally:
                    self._sock.settimeout(self.timeout)
            return resp.get("ok", False)
        except Exception:
            self.close()
            return False

    def close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None


class SyncBus:
    """Coordinator-side broadcast of sync actions to every attached worker
    (`SyncManagerHelper.sync(...)` analog): best-effort fan-out, collects acks."""

    def __init__(self):
        self.workers: List[WorkerClient] = []

    def attach(self, client: WorkerClient):
        if client not in self.workers:
            self.workers.append(client)

    def broadcast(self, action: str, payload: dict) -> List[dict]:
        out = []
        for w in self.workers:
            try:
                out.append(w.sync_action(action, payload))
            except Exception as e:  # a dead worker must not block the others
                out.append({"ok": False, "error": str(e)})
        return out
