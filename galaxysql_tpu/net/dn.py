"""The CN<->worker RPC plane: plan shipping to a second process.

Reference analog: the CN->DN seam — `repo/mysql/spi/MyJdbcHandler.java:691`
(physical SQL shipped to the shard's storage node and executed there) plus the
inter-CN sync-action bus (`executor/sync/SyncManagerHelper.java:36`).  A worker
(`galaxysql_tpu.net.worker`) is a real second OS process hosting its own
engine Instance; the coordinator attaches its tables as *remote tables* whose
scans compile to shipped SQL (filters/column pruning pushed down), so one
query's fragments genuinely span two processes.

Wire format: length-prefixed JSON header + raw npy column payloads over a
localhost TCP socket.  JSON (not pickle) on purpose: the socket is an internal
trust boundary and must not be an arbitrary-code-execution vector.  Frame
lengths are CAPPED (`_MAX_*`): a corrupt/hostile length prefix raises a typed
ProtocolError instead of allocating arbitrary memory.

Fault tolerance (the FailPoint-proven layer the reference's SyncManager/HA
machinery implies):

- **Per-op retry policy.**  Transport failures retry ONLY retry-safe requests:
  reads (exec_plan, read-only exec_sql), idempotent control ops (ping/sync/
  xa_*), and uid-stamped writes — the worker keeps a bounded dedupe window
  keyed on the uid and replays the recorded result, so a reconnect retry can
  never double-apply DML.  Retries use capped exponential backoff with full
  jitter (first retry reconnects immediately: the worker may simply have
  restarted between queries).
- **Deadlines.**  A caller-supplied absolute deadline rides the header as the
  remaining budget (`deadline_ms`); the worker aborts past-deadline fragments
  and this side fails typed (QueryTimeoutError) instead of hanging.
- **Circuit breaker.**  Consecutive transport failures open the breaker:
  requests fast-fail typed (WorkerUnavailableError) without touching the dead
  socket; after a cooldown the breaker half-opens, one ping probe decides
  closed vs re-open.
- **Sync epochs.**  Every SyncBus broadcast bumps a monotonic epoch carried on
  ALL requests; a worker that detects a gap (it was down/unreachable during a
  broadcast) wholesale-invalidates its caches — a missed invalidation heals at
  first contact instead of serving stale caches forever.
"""

from __future__ import annotations

import io
import json
import random
import re
import socket
import struct
import threading
import time
from time import perf_counter as _perf
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from galaxysql_tpu.utils.failpoint import (FAIL_POINTS, FP_RPC_DELAY_MS,
                                           FP_RPC_DROP, FP_RPC_FAIL_N)

_HDR = struct.Struct(">I")

# framing caps: the 4-byte length prefixes arrive from the wire and must not
# be trusted unbounded (satellite: a corrupt frame must fail typed, not OOM)
_MAX_HEADER_BYTES = 16 << 20      # JSON header
_MAX_NAME_BYTES = 4 << 10         # array name
_MAX_ARRAY_BYTES = (2 << 30) - 1  # one npy payload
_MAX_ARRAYS = 4096                # arrays per message


def encode_msg(header: dict,
               arrays: Optional[Dict[str, np.ndarray]] = None) -> bytes:
    """Validate + encode one frame (no IO).  Caps are enforced on BOTH
    sides: a payload the receiver would reject as corrupt must fail typed
    here, BEFORE any byte ships, naming the real cause (oversized result)
    instead of dying mid-transfer as 'corrupt frame' on a healthy
    connection.  Separated from the send so callers can distinguish
    pre-wire validation failures from transmission failures."""
    arrays = arrays or {}
    header = dict(header)
    header["n_arrays"] = _checked_len(len(arrays), _MAX_ARRAYS,
                                      "outbound array count")
    hb = json.dumps(header).encode()
    _checked_len(len(hb), _MAX_HEADER_BYTES, "outbound header")
    out = [_HDR.pack(len(hb)), hb]
    for name, arr in arrays.items():
        buf = io.BytesIO()
        np.save(buf, np.ascontiguousarray(arr), allow_pickle=False)
        nb = name.encode()
        _checked_len(len(nb), _MAX_NAME_BYTES, "outbound array name")
        _checked_len(buf.getbuffer().nbytes, _MAX_ARRAY_BYTES,
                     f"outbound array {name!r} (result too large)")
        out += [_HDR.pack(len(nb)), nb, _HDR.pack(buf.getbuffer().nbytes),
                buf.getvalue()]
    return b"".join(out)


def send_msg(sock: socket.socket, header: dict,
             arrays: Optional[Dict[str, np.ndarray]] = None):
    """[u32 jsonlen][json][per-array: u32 namelen][name][u32 npylen][npy]"""
    sock.sendall(encode_msg(header, arrays))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        b = sock.recv(min(n, 1 << 20))
        if not b:
            raise ConnectionError("peer closed")
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


def _checked_len(n: int, cap: int, what: str) -> int:
    if n > cap:
        from galaxysql_tpu.utils import errors
        raise errors.ProtocolError(
            f"corrupt frame: {what} length {n} exceeds cap {cap}")
    return n


def recv_msg(sock: socket.socket) -> Tuple[dict, Dict[str, np.ndarray]]:
    try:
        (hlen,) = _HDR.unpack(_recv_exact(sock, 4))
        header = json.loads(_recv_exact(
            sock, _checked_len(hlen, _MAX_HEADER_BYTES, "header")))
        arrays: Dict[str, np.ndarray] = {}
        n_arrays = int(header.get("n_arrays", 0))
        _checked_len(n_arrays, _MAX_ARRAYS, "array count")
        for _ in range(n_arrays):
            (nlen,) = _HDR.unpack(_recv_exact(sock, 4))
            name = _recv_exact(
                sock,
                _checked_len(nlen, _MAX_NAME_BYTES, "array name")).decode()
            (alen,) = _HDR.unpack(_recv_exact(sock, 4))
            arrays[name] = np.load(
                io.BytesIO(_recv_exact(
                    sock, _checked_len(alen, _MAX_ARRAY_BYTES, "array"))),
                allow_pickle=False)
        return header, arrays
    except (ValueError, EOFError, UnicodeDecodeError, AttributeError) as e:
        # decode failure (bad JSON, corrupt npy, mangled name) is the SAME
        # desynchronized-stream condition as a blown length cap: it must
        # surface typed so the retry/ambiguity machinery engages, never as
        # a raw ValueError that bypasses every handler
        from galaxysql_tpu.utils import errors
        raise errors.ProtocolError(
            f"corrupt frame: {type(e).__name__}: {e}") from e


# ops whose handler is idempotent by construction: control-plane chatter plus
# the XA verbs (the worker's prepare/commit/rollback all tolerate replay — the
# "already" paths) and pure-read fragments
_IDEMPOTENT_OPS = frozenset({"ping", "sync", "exec_plan", "xa_prepare",
                             "xa_commit", "xa_rollback", "xa_recover"})
_READONLY_SQL_RE = re.compile(
    r"^\s*(?:/\*.*?\*/\s*)*(?:select|show|explain|describe|desc)\b",
    re.I | re.S)


def _retry_safe(header: dict) -> bool:
    """May this request be re-sent after a transport failure?  Reads and
    idempotent control ops always; writes ONLY when uid-stamped (the worker's
    dedupe window makes the replay exactly-once) or explicitly flagged
    idempotent by the caller (`idem`, e.g. CREATE ... IF NOT EXISTS)."""
    op = header.get("op")
    if op in _IDEMPOTENT_OPS:
        return True
    if header.get("uid") or header.get("idem"):
        return True
    if op == "exec_sql":
        return bool(_READONLY_SQL_RE.match(header.get("sql") or ""))
    return False


class RetryBudget:
    """Token bucket bounding retry attempts per worker endpoint.

    Under saturation every retry is ADDED load on a box already failing to
    keep up — unbounded retries turn one slow worker into a metastable storm
    (the whole fleet re-sending the same work).  Each retry attempt takes one
    token; tokens refill at a steady rate, so a brief blip retries freely
    while a sustained failure quickly degrades to fail-fast typed errors.
    Locked, but only touched on the failure path — never on a healthy RPC."""

    def __init__(self, capacity: int = 64, refill_per_s: float = 8.0):
        self.capacity = max(0, int(capacity))
        self.refill_per_s = max(0.0, float(refill_per_s))
        self._tokens = float(self.capacity)
        self._at = time.monotonic()
        self._lock = threading.Lock()
        self.exhausted = 0  # lifetime fail-fast count (SHOW WORKERS)

    def _refill_locked(self, now: float):
        self._tokens = min(float(self.capacity),
                           self._tokens + (now - self._at) * self.refill_per_s)
        self._at = now

    def configure(self, capacity: int, refill_per_s: float):
        with self._lock:
            self._refill_locked(time.monotonic())
            self.capacity = max(0, int(capacity))
            self.refill_per_s = max(0.0, float(refill_per_s))
            self._tokens = min(self._tokens, float(self.capacity))

    def try_take(self) -> bool:
        with self._lock:
            self._refill_locked(time.monotonic())
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            self.exhausted += 1
            return False

    def remaining(self) -> float:
        with self._lock:
            self._refill_locked(time.monotonic())
            return self._tokens


class WorkerClient:
    """Coordinator-side connection to one worker process (one socket, locked:
    the protocol is strictly request/response)."""

    def __init__(self, host: str, port: int, timeout: float = 180.0,
                 max_retries: int = 2, retry_backoff_ms: int = 20,
                 failure_threshold: int = 3, cooldown_ms: int = 1000,
                 config=None):
        # generous default: the worker's FIRST query on a cold process pays
        # XLA compiles; ping() overrides with a short probe timeout
        self.timeout = timeout
        self.addr = (host, port)
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        # retry/breaker knobs: with a ConfigParams bound (Instance-created
        # clients) the values read LIVE, so SET GLOBAL BREAKER_* /
        # RPC_MAX_RETRIES apply to already-attached workers too; the
        # constructor kwargs are the standalone/test fallbacks
        self._cfg = config
        self._max_retries = max(0, int(max_retries))
        self._retry_backoff_ms = max(1, int(retry_backoff_ms))
        # circuit breaker: closed -> (threshold consecutive transport
        # failures) -> open -> (cooldown) -> half-open (ping probe) ->
        # closed | open.  State reads on the hot path are lock-free.
        self._failure_threshold = max(1, int(failure_threshold))
        self._cooldown_ms = max(1, int(cooldown_ms))
        self._bk_lock = threading.Lock()
        self._bk_state = "closed"
        self._bk_fails = 0          # consecutive transport failures
        self._bk_opened_at = 0.0
        # lifetime stats for SHOW WORKERS / information_schema.workers
        self.stat_retries = 0
        self.stat_failures = 0
        self.stat_opens = 0
        self.last_error = ""
        # retry budget (token bucket): each retry attempt takes one token;
        # empty bucket -> fail typed instead of retrying (no retry storms).
        # Live-config clients re-read the knobs on each take.
        self.retry_budget = RetryBudget(
            int(self._param("RPC_RETRY_BUDGET", 64)),
            float(self._param("RPC_RETRY_REFILL_PER_S", 8)))
        # worker-piggybacked load (queue depth + memory tier from RPC
        # replies): routing deprioritizes pressured endpoints
        self.load_q = 0
        self.load_tier = 0
        self.load_at = 0.0
        # SLO-plane piggyback twin: worker uptime + history sample count
        # feed the pull-free cluster-health view
        self.load_up = 0.0
        self.load_samples = 0
        # sync-epoch plane: bound by SyncBus.attach; adds {se, origin} to
        # every request so the worker can detect missed broadcasts
        self._sync_bus = None
        # set when a broadcast delivery to THIS worker failed: the next
        # successful request carries a heal directive (wholesale cache
        # invalidation), closing the missed-invalidation hole exactly —
        # epoch comparison alone can miss an out-of-order-completed gap.
        # The generation counter guards the clear: a miss flagged WHILE a
        # heal-carrying request was in flight must survive that request's
        # success (its heal predates the new miss).
        self.needs_heal = False
        self._heal_gen = 0

    def mark_needs_heal(self):
        with self._bk_lock:
            self._heal_gen += 1
            self.needs_heal = True

    def bind_sync_bus(self, bus):
        self._sync_bus = bus

    def _param(self, name: str, fallback):
        if self._cfg is not None:
            v = self._cfg.get(name)
            if v is not None:
                return v
        return fallback

    @property
    def max_retries(self) -> int:
        return max(0, int(self._param("RPC_MAX_RETRIES", self._max_retries)))

    @property
    def retry_backoff_ms(self) -> int:
        return max(1, int(self._param("RPC_RETRY_BACKOFF_MS",
                                      self._retry_backoff_ms)))

    @property
    def failure_threshold(self) -> int:
        return max(1, int(self._param("BREAKER_FAILURE_THRESHOLD",
                                      self._failure_threshold)))

    @property
    def cooldown_s(self) -> float:
        return max(0.001, int(self._param("BREAKER_COOLDOWN_MS",
                                          self._cooldown_ms)) / 1000.0)

    def _connect(self, timeout: Optional[float] = None):
        if self._sock is None:
            s = socket.create_connection(self.addr,
                                         timeout=timeout or self.timeout)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s

    # -- circuit breaker -----------------------------------------------------

    def breaker_state(self) -> str:
        return self._bk_state

    def breaker_blocked(self) -> bool:
        """True while requests would fast-fail: breaker open AND still inside
        the cooldown, or half-open with a probe already in flight.  Routing
        skips blocked endpoints; a cooled-down open breaker stays routable so
        the next request runs the half-open probe."""
        if self._bk_state == "half-open":
            return True
        return self._bk_state == "open" and \
            time.time() - self._bk_opened_at < self.cooldown_s

    def breaker_snapshot(self) -> dict:
        with self._bk_lock:
            return {"state": self._bk_state, "consec_failures": self._bk_fails,
                    "opens": self.stat_opens, "retries": self.stat_retries,
                    "failures": self.stat_failures,
                    "last_error": self.last_error}

    def _breaker_ok(self):
        if self._bk_fails or self._bk_state != "closed":
            with self._bk_lock:
                reopened = self._bk_state != "closed"
                self._bk_fails = 0
                self._bk_state = "closed"
            if reopened:
                from galaxysql_tpu.utils import events
                events.publish("breaker_close",
                               f"worker {self.addr[0]}:{self.addr[1]}: "
                               "circuit breaker closed (probe succeeded)",
                               worker=f"{self.addr[0]}:{self.addr[1]}")

    def _breaker_fail(self, exc: BaseException):
        from galaxysql_tpu.utils.metrics import BREAKER_OPENS
        with self._bk_lock:
            self._bk_fails += 1
            self.stat_failures += 1
            self.last_error = f"{type(exc).__name__}: {exc}"[:160]
            if self._bk_fails >= self.failure_threshold and \
                    self._bk_state != "open":
                self._bk_state = "open"
                self._bk_opened_at = time.time()
                self.stat_opens += 1
                BREAKER_OPENS.inc()
                from galaxysql_tpu.utils import events, tracing
                tc = tracing.current()
                events.publish("breaker_open",
                               f"worker {self.addr[0]}:{self.addr[1]}: "
                               f"breaker opened after {self._bk_fails} "
                               f"failures ({self.last_error})",
                               worker=f"{self.addr[0]}:{self.addr[1]}",
                               consec_failures=self._bk_fails,
                               trace_id=tc.trace_id if tc is not None else 0)

    def _breaker_gate(self):
        """Fast-fail while open; after the cooldown, half-open and let ONE
        ping probe decide — concurrent callers fast-fail typed instead of
        piling blocking probes onto a possibly-dead worker.  The hot path
        (closed) is a single attribute read."""
        if self._bk_state == "closed":
            return
        from galaxysql_tpu.utils import errors
        with self._bk_lock:
            if self._bk_state == "closed":
                return
            if self._bk_state == "half-open":
                # another caller owns the in-flight probe
                raise errors.WorkerUnavailableError(
                    f"worker {self.addr[0]}:{self.addr[1]}: circuit breaker "
                    f"half-open (probe in flight)", sent=False)
            if time.time() - self._bk_opened_at < self.cooldown_s:
                raise errors.WorkerUnavailableError(
                    f"worker {self.addr[0]}:{self.addr[1]}: circuit breaker "
                    f"open ({self._bk_fails} consecutive failures: "
                    f"{self.last_error})", sent=False)
            self._bk_state = "half-open"  # this caller owns the probe
        # probe outside the breaker lock (socket IO); ping() resets the
        # breaker on success, so a passing probe closes it — ping never
        # raises, so the half-open claim cannot leak
        if not self.ping(timeout=min(2.0, self.cooldown_s * 2)):
            from galaxysql_tpu.utils.metrics import BREAKER_OPENS
            with self._bk_lock:
                self._bk_state = "open"
                self._bk_opened_at = time.time()
                # a re-open IS an open transition: SHOW WORKERS and the
                # breaker_opens counter must show a flapping endpoint
                self.stat_opens += 1
            BREAKER_OPENS.inc()
            from galaxysql_tpu.utils import events, tracing
            tc = tracing.current()
            events.publish("breaker_open",
                           f"worker {self.addr[0]}:{self.addr[1]}: "
                           "half-open probe failed; breaker re-opened",
                           worker=f"{self.addr[0]}:{self.addr[1]}",
                           trace_id=tc.trace_id if tc is not None else 0)
            raise errors.WorkerUnavailableError(
                f"worker {self.addr[0]}:{self.addr[1]}: half-open probe "
                f"failed; breaker re-opened", sent=False)

    # ops whose worker-side execution is worth a span subtree; control-plane
    # chatter (ping, sync, xa_*) stays untraced
    _TRACED_OPS = frozenset({"exec_plan", "exec_sql", "dml"})

    def _fault_plan(self, op: str):
        """Armed network failpoints for this attempt: (fail_now, delay_ms,
        drop_leg).  One locked lookup per armed key; nothing when idle.
        FAIL_N preempts the attempt entirely, so it must not consume the
        budgets of co-armed delay/drop keys (they fire on later attempts)."""
        if not FAIL_POINTS.active:
            return False, 0.0, None
        if FAIL_POINTS.rpc_spec(FP_RPC_FAIL_N, op) is not None:
            return True, 0.0, None
        d = FAIL_POINTS.rpc_spec(FP_RPC_DELAY_MS, op)
        delay = float(d.get("ms", 25.0)) if d is not None else 0.0
        drop = FAIL_POINTS.rpc_spec(FP_RPC_DROP, op)
        leg = (drop.get("leg", "request") if drop is not None else None)
        return False, delay, leg

    def _exchange(self, header: dict, arrays, op: str,
                  deadline: Optional[float]):
        """One locked wire round-trip: connect, inject armed faults, stamp
        the remaining deadline budget, send, receive.  On ANY failure the
        socket is closed while still holding the lock — a deferred close
        would race a concurrent request's freshly-connected socket on this
        shared client.  Returns (resp, arrs, t_send, t_recv, rtt_ms).

        Transport exceptions are annotated with `_gx_sent`: whether bytes may
        have reached the worker (True once send began) — write callers use it
        to tell provably-unapplied failures from ambiguous ones."""
        from galaxysql_tpu.utils import errors
        from galaxysql_tpu.utils import tracing
        sent = False
        with self._lock:
            try:
                if deadline is not None:
                    # the deadline must bound the CONNECT too: a blackholed
                    # endpoint would otherwise hold this client's lock for
                    # the 180s default while the caller promised a bound
                    remaining = deadline - time.time()
                    if remaining <= 0:
                        raise errors.QueryTimeoutError(
                            f"deadline exceeded before rpc:{op} to "
                            f"{self.addr[0]}:{self.addr[1]}", sent=False)
                    self._connect(timeout=min(self.timeout,
                                              max(0.05, remaining) + 1.0))
                else:
                    self._connect()
                fail_now, delay_ms, drop_leg = self._fault_plan(op)
                if delay_ms:
                    time.sleep(delay_ms / 1000.0)
                if fail_now:
                    raise ConnectionError("FP_RPC_FAIL_N armed")
                if drop_leg == "request":
                    raise ConnectionError("FP_RPC_DROP request leg")
                if deadline is not None:
                    # the shipped budget is computed at the LAST moment
                    # (after lock-wait and injected delays): an expired
                    # deadline dies typed here, a live one also bounds the
                    # socket wait — a silent peer cannot hang a
                    # deadline-carrying request
                    remaining = deadline - time.time()
                    if remaining <= 0:
                        raise errors.QueryTimeoutError(
                            f"deadline exceeded before rpc:{op} to "
                            f"{self.addr[0]}:{self.addr[1]}", sent=False)
                    header["deadline_ms"] = int(remaining * 1000)
                    self._sock.settimeout(max(0.05, remaining) + 1.0)
                try:
                    # encode (and cap-validate) BEFORE the wire: a frame
                    # rejected here provably never reached the worker
                    payload = encode_msg(header, arrays)
                    t_send, t0 = tracing.now_us(), _perf()
                    sent = True  # from here, bytes may have hit the wire
                    self._sock.sendall(payload)
                    if drop_leg == "reply":
                        # the worker HAS the request (it will execute it);
                        # this side loses the reply — the double-apply trap
                        # the dedupe window covers
                        raise ConnectionError("FP_RPC_DROP reply leg")
                    resp, arrs = recv_msg(self._sock)
                finally:
                    if deadline is not None and self._sock is not None:
                        self._sock.settimeout(self.timeout)
            except errors.QueryTimeoutError:
                raise  # pre-send: nothing on the wire, socket stays aligned
            except Exception as e:
                # transport failure or corrupt frame: the stream must not be
                # reused (ProtocolError mid-frame is desynchronized too)
                e._gx_sent = sent
                self.close()
                if deadline is not None and isinstance(e, TimeoutError) \
                        and time.time() >= deadline:
                    # the deadline-bounded socket wait tripped: this is the
                    # QUERY dying, not the worker — typed timeout, no
                    # breaker accounting against a live-but-slow endpoint,
                    # and the sent flag survives (a connect timeout provably
                    # put nothing on the wire)
                    stage = "awaiting reply from" if sent else "connecting to"
                    raise errors.QueryTimeoutError(
                        f"deadline exceeded {stage} rpc:{op} "
                        f"{self.addr[0]}:{self.addr[1]}", sent=sent) from e
                raise
            rtt_ms = (_perf() - t0) * 1000.0
            t_recv = tracing.now_us()
        return resp, arrs, t_send, t_recv, rtt_ms

    def request(self, header: dict,
                arrays: Optional[Dict[str, np.ndarray]] = None,
                deadline: Optional[float] = None
                ) -> Tuple[dict, Dict[str, np.ndarray]]:
        from galaxysql_tpu.utils import errors
        from galaxysql_tpu.utils import tracing
        from galaxysql_tpu.utils.metrics import (RPC_FAILURES, RPC_RETRIES,
                                                 RPC_RTT_MS)
        self._breaker_gate()
        op = header.get("op")
        header = dict(header)
        if self._sync_bus is not None and self._sync_bus.origin:
            # sync-epoch plane: data requests carry the SETTLED epoch (all
            # broadcasts through it have completed delivery), never the live
            # counter — stamping a mid-flight epoch would race the delivery
            # threads and trigger spurious wholesale heals on the worker
            header["se"] = self._sync_bus.settled
            header["origin"] = self._sync_bus.origin
        heal_gen = None
        if self.needs_heal:
            # this worker missed a broadcast: ask it to wholesale-invalidate
            header["heal"] = 1
            with self._bk_lock:
                heal_gen = self._heal_gen
        tc = tracing.current()
        rpc_span = None
        if tc is not None and op in self._TRACED_OPS:
            # inject trace context into the fragment RPC: the worker opens
            # child spans under `parent` and ships them back in the response
            header["trace"] = {"trace_id": tc.trace_id,
                               "parent": tc.cursor, "node": tc.node}
            rpc_span = tc.begin(f"rpc:{op}", kind="rpc",
                                worker=f"{self.addr[0]}:{self.addr[1]}")
        retryable = _retry_safe(header)
        any_sent = False  # did any attempt put bytes on the wire?
        attempts = 1 + (self.max_retries if retryable else 0)
        # timestamps bracket the ACTUAL wire round-trip (captured inside the
        # lock, re-captured on each retry): lock-wait and retry time must skew
        # neither the NTP-style clock offset nor rpc_rtt_ms
        t_send = t_recv = 0
        rtt_ms = 0.0
        resp: dict = {}
        arrs: Dict[str, np.ndarray] = {}
        try:
            for attempt in range(attempts):
                try:
                    resp, arrs, t_send, t_recv, rtt_ms = \
                        self._exchange(header, arrays, op, deadline)
                    self._breaker_ok()
                    break
                except errors.QueryTimeoutError as e:
                    # a deadline kill is never retried — but a PRE-send kill
                    # on a RETRY attempt must not erase the evidence that an
                    # EARLIER attempt already put this statement on the wire
                    if any_sent:
                        e.sent = True
                    raise
                except (ConnectionError, OSError) as e:
                    # transport failure: the worker may have restarted between
                    # queries (first retry reconnects immediately) or be down
                    # (_exchange already closed the socket, under the lock)
                    any_sent |= getattr(e, "_gx_sent", True)
                    self._breaker_fail(e)
                    if not retryable or attempt == attempts - 1:
                        RPC_FAILURES.inc()
                        raise errors.WorkerUnavailableError(
                            f"worker {self.addr[0]}:{self.addr[1]} rpc:{op} "
                            f"failed after {attempt + 1} attempt(s): "
                            f"{type(e).__name__}: {e}",
                            sent=any_sent) from e
                    if self._cfg is not None:
                        # live knobs: SET GLOBAL RPC_RETRY_BUDGET applies to
                        # attached workers (failure path only — never paid
                        # on a healthy RPC)
                        self.retry_budget.configure(
                            int(self._param("RPC_RETRY_BUDGET", 64)),
                            float(self._param("RPC_RETRY_REFILL_PER_S", 8)))
                    if not self.retry_budget.try_take():
                        # budget empty: retrying now only amplifies the
                        # overload — fail typed instead (no retry storm)
                        from galaxysql_tpu.utils.metrics import \
                            RETRY_BUDGET_EXHAUSTED
                        RETRY_BUDGET_EXHAUSTED.inc()
                        RPC_FAILURES.inc()
                        from galaxysql_tpu.utils import events
                        events.publish(
                            "retry_budget_exhausted",
                            f"worker {self.addr[0]}:{self.addr[1]}: retry "
                            f"budget exhausted; rpc:{op} fails without "
                            f"retry",
                            dedupe=f"rb-{self.addr[0]}:{self.addr[1]}",
                            worker=f"{self.addr[0]}:{self.addr[1]}")
                        raise errors.WorkerUnavailableError(
                            f"worker {self.addr[0]}:{self.addr[1]} rpc:{op} "
                            f"retry budget exhausted after {attempt + 1} "
                            f"attempt(s): {type(e).__name__}: {e}",
                            sent=any_sent) from e
                    with self._bk_lock:
                        self.stat_retries += 1
                    RPC_RETRIES.inc()
                    if rpc_span is not None:
                        rpc_span.attrs["retries"] = attempt + 1
                    if attempt > 0:
                        # capped exponential backoff with full jitter; the
                        # immediate first retry keeps the worker-restarted
                        # fast path as cheap as the old blind reconnect
                        cap = self.retry_backoff_ms * (2 ** (attempt - 1))
                        time.sleep(random.uniform(0, cap) / 1000.0)
        finally:
            if rpc_span is not None:
                tc.end(rpc_span)
        RPC_RTT_MS.observe(rtt_ms)
        wl = resp.pop("wl", None)
        if wl is not None:
            # worker-piggybacked backpressure: queue depth + memory tier ride
            # every reply, so routing deprioritizes pressured endpoints
            # without any extra probe RPC (plain attribute writes — readers
            # tolerate benign races)
            try:
                self.load_q = int(wl.get("q", 0))
                self.load_tier = int(wl.get("mt", 0))
                self.load_up = float(wl.get("up", 0.0))
                self.load_samples = int(wl.get("ns", 0))
                self.load_at = time.time()
            except (TypeError, ValueError, AttributeError):
                pass  # malformed piggyback must never fail a data request
        if rpc_span is not None:
            self._graft_trace(tc, rpc_span, resp, t_send, t_recv)
        if resp.get("error"):
            if int(resp.get("errno") or 0) == errors.QueryTimeoutError.errno:
                # `unapplied` marks the worker's PRE-work rejection: nothing
                # executed, so write callers may keep statement-scoped
                # semantics (sent=False), unlike a mid-execution timeout
                raise errors.QueryTimeoutError(
                    f"worker {self.addr}: {resp['error']}",
                    sent=not resp.get("unapplied"))
            if resp.get("ambiguous"):
                # the worker could not prove the outcome (e.g. a duplicate
                # replay timed out waiting on the still-executing original):
                # write callers must take the unknown-outcome path
                raise errors.WorkerUnavailableError(
                    f"worker {self.addr}: {resp['error']}", sent=True)
            raise errors.TddlError(f"worker {self.addr}: {resp['error']}")
        if heal_gen is not None:
            # the request SUCCEEDED app-level, so the worker really healed
            # (a failed heal raises worker-side and lands above as an error
            # response — the flag must survive it).  Clear only if no NEW
            # miss was flagged while this request was in flight.
            with self._bk_lock:
                if heal_gen == self._heal_gen:
                    self.needs_heal = False
        return resp, arrs

    @staticmethod
    def _graft_trace(tc, rpc_span, resp: dict, t_send: int, t_recv: int):
        """Adopt the worker's span subtree under the RPC span, correcting its
        wall clock: the NTP-style offset `((t_send+t_recv) - (w_recv+w_send))
        / 2` maps the worker's timestamps onto the coordinator's timeline
        (symmetric-latency assumption — localhost sockets here, where the
        residual error is microseconds)."""
        wt = resp.pop("trace", None)
        if not wt:
            return
        try:
            w_recv = int(wt.get("w_recv_us", 0))
            w_send = int(wt.get("w_send_us", 0))
            offset = ((t_send + t_recv) - (w_recv + w_send)) // 2 \
                if w_recv and w_send else 0
            spans = tc.graft(wt.get("spans") or [], parent=rpc_span.span_id,
                             offset_us=offset)
            rpc_span.attrs["worker_spans"] = len(spans)
            rpc_span.attrs["clock_offset_us"] = offset
        except Exception:  # galaxylint: disable=swallow -- malformed trace payload must not fail the data request; span records worker_spans=-1
            # a malformed trace payload must never fail the data request
            rpc_span.attrs["worker_spans"] = -1

    def execute(self, sql: str, schema: str = "",
                xid: Optional[str] = None, uid: Optional[str] = None,
                idem: bool = False,
                deadline: Optional[float] = None
                ) -> Tuple[List[str], List[str],
                           Dict[str, np.ndarray],
                           Dict[str, np.ndarray]]:
        """Ship SQL; returns (columns, sql_types, data arrays, valid arrays).
        With `xid`, the worker runs it in that txn branch's session (reads see
        the branch's uncommitted writes).  Writes should stamp a `uid`
        (exactly-once via the worker's dedupe window) or declare themselves
        `idem` (textually idempotent, e.g. CREATE ... IF NOT EXISTS) to be
        retry-safe across reconnects."""
        hdr: Dict[str, Any] = {"op": "exec_sql", "sql": sql, "schema": schema}
        if xid is not None:
            hdr["xid"] = xid
        if uid is not None:
            hdr["uid"] = uid
        if idem:
            hdr["idem"] = True
        resp, arrs = self.request(hdr, deadline=deadline)
        cols = resp["columns"]
        data = {c: arrs[f"d::{c}"] for c in cols}
        valid = {c: arrs[f"v::{c}"] for c in cols if f"v::{c}" in arrs}
        return cols, resp["types"], data, valid

    def exec_plan(self, fragment: dict, deadline: Optional[float] = None
                  ) -> Tuple[List[str], List[str],
                             Dict[str, np.ndarray],
                             Dict[str, np.ndarray]]:
        """Ship a serialized physical fragment (XPlan analog,
        `RelToXPlanConverter.java:41` / `XPlanTemplate.java:86`): the worker
        executes it straight against its store — no re-parse, no re-plan.
        Raises on an unsupported fragment; the caller degrades to exec_sql."""
        resp, arrs = self.request({"op": "exec_plan", "fragment": fragment},
                                  deadline=deadline)
        cols = resp["columns"]
        data = {c: arrs[f"d::{c}"] for c in cols}
        valid = {c: arrs[f"v::{c}"] for c in cols if f"v::{c}" in arrs}
        return cols, resp["types"], data, valid

    def sync_action(self, action: str, payload: dict) -> dict:
        """Inter-node sync bus (SyncManagerHelper analog): cache invalidation,
        config changes, baseline ops."""
        resp, _ = self.request({"op": "sync", "action": action,
                                "payload": payload})
        return resp

    def sync_broadcast(self, action: str, payload: dict, epoch: int,
                       deadline: Optional[float] = None) -> dict:
        """A BROADCAST delivery (SyncBus.broadcast fan-out): carries the
        broadcast's own epoch so the worker can advance its last-applied mark
        — direct sync_action calls (table_meta, worker_stats, ...) must NOT
        look like broadcast deliveries or they would mask a missed one.  The
        deadline bounds the SOCKET wait: a hung (not dead) worker must not
        park the delivery thread — which holds this client's lock — for the
        full default timeout."""
        resp, _ = self.request({"op": "sync", "action": action,
                                "payload": payload, "bcast_epoch": int(epoch)},
                               deadline=deadline)
        return resp

    def ping(self, timeout: float = 5.0) -> bool:
        try:
            with self._lock:
                try:
                    self._connect()
                    self._sock.settimeout(timeout)
                    try:
                        send_msg(self._sock, {"op": "ping"})
                        resp, _ = recv_msg(self._sock)
                    finally:
                        self._sock.settimeout(self.timeout)
                except Exception:
                    # close INSIDE the lock: a deferred close would race a
                    # concurrent request's freshly-connected socket
                    self.close()
                    raise
            ok = resp.get("ok", False)
            if ok:
                # a live worker closes the breaker (HA probe / half-open path)
                self._breaker_ok()
            return ok
        except Exception:  # galaxylint: disable=swallow -- ping() is a boolean probe: False IS the failure report
            return False

    def close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None


class SyncBus:
    """Coordinator-side broadcast of sync actions to every attached worker
    (`SyncManagerHelper.sync(...)` analog): parallel fan-out, collects acks.

    Every broadcast bumps a monotonic `epoch` and each delivery carries the
    broadcast's OWN epoch; ordinary requests carry the `settled` epoch (all
    broadcasts through it have completed delivery — stamping the live counter
    would race in-flight delivery threads into spurious heals).  A worker
    that missed a broadcast detects the epoch gap at its next contact — and,
    belt-and-braces, a failed delivery marks the client `needs_heal`, so the
    next successful request to that exact worker forces the wholesale
    invalidation even when epoch arithmetic alone couldn't prove the gap
    (out-of-order completion of concurrent broadcasts)."""

    # a dead worker must cost one bounded join, not a full connect timeout
    # serially added to every broadcast
    BROADCAST_JOIN_S = 20.0

    def __init__(self, origin: Optional[str] = None):
        self.workers: List[WorkerClient] = []
        self.origin = origin
        self.epoch = 0
        self.settled = 0
        self._inflight: set = set()
        self._lock = threading.Lock()

    def attach(self, client):
        with self._lock:
            if client not in self.workers:
                self.workers.append(client)
        if hasattr(client, "bind_sync_bus"):
            client.bind_sync_bus(self)

    def _settle(self, e: int):
        with self._lock:
            self._inflight.discard(e)
            self.settled = (min(self._inflight) - 1) if self._inflight \
                else self.epoch

    def broadcast(self, action: str, payload: dict) -> List[dict]:
        from galaxysql_tpu.utils.metrics import SYNC_FAILURES
        with self._lock:
            self.epoch += 1
            e = self.epoch
            self._inflight.add(e)
            targets = list(self.workers)
        try:
            if not targets:
                return []
            out: List[Optional[dict]] = [None] * len(targets)

            # delivery deadline ≈ the join bound: a hung worker releases the
            # client lock when the bounded socket wait trips, instead of
            # pinning it (and the next data request) for the 180s default
            dl = time.time() + self.BROADCAST_JOIN_S

            def _one(i: int, w):
                # broadcast-flavored delivery for real WorkerClients (carries
                # the epoch); plain sync_action for peer/in-process endpoints
                try:
                    fn = getattr(w, "sync_broadcast", None)
                    out[i] = fn(action, payload, e, deadline=dl) \
                        if fn is not None else w.sync_action(action, payload)
                except Exception as ex:  # a dead worker must not block others
                    out[i] = {"ok": False, "error": str(ex)}

            # per-broadcast daemon threads (not a pool): non-daemon pool
            # threads stuck on a dead worker would block process exit, and a
            # pooled queue would let one hung delivery delay later
            # broadcasts.  Even a SINGLE target goes through the thread so
            # the bounded join holds — a hung (not dead) worker must cost at
            # most BROADCAST_JOIN_S, never a full socket-timeout stall on
            # the issuing session.
            threads = [threading.Thread(target=_one, args=(i, w),
                                        daemon=True)
                       for i, w in enumerate(targets)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(max(0.0, dl - time.time()))
            # failure accounting happens HERE, once per slot, on a SNAPSHOT
            # of each slot — a delivery completing after the join timeout
            # must neither double-count nor flip an already-accounted result
            results: List[dict] = []
            for i, w in enumerate(targets):
                r = out[i]
                if r is None:
                    r = {"ok": False, "error": "sync broadcast timed out"}
                if not r.get("ok"):
                    SYNC_FAILURES.inc()
                    from galaxysql_tpu.utils import events
                    events.publish("sync_failure",
                                   f"sync '{action}' delivery failed: "
                                   f"{r.get('error', '')}"[:200],
                                   node=self.origin or "", action=action)
                    if hasattr(w, "mark_needs_heal"):
                        w.mark_needs_heal()
                results.append(r)
            return results
        finally:
            self._settle(e)
