"""MySQL client/server protocol packet codec.

Reference analog: `polardbx-net/src/main/java/.../net/packet` (SURVEY.md §2.1) —
handshake v10, auth, COM_* commands, OK/ERR/EOF, column definitions, textual and binary
resultset rows.  Pure codec; transport lives in `net/server.py` (asyncio replaces the
reference's NIO reactor threads, §7.1 stance).
"""

from __future__ import annotations

import hashlib
import struct
from typing import Any, Iterable, List, Optional, Sequence, Tuple

from galaxysql_tpu.types import datatype as dt

PROTOCOL_VERSION = 10
SERVER_VERSION = b"8.0.3-galaxysql-tpu"
CHARSET_UTF8MB4 = 255

# capability flags
CLIENT_LONG_PASSWORD = 1
CLIENT_FOUND_ROWS = 2
CLIENT_LONG_FLAG = 4
CLIENT_CONNECT_WITH_DB = 8
CLIENT_COMPRESS = 32
CLIENT_PROTOCOL_41 = 512
CLIENT_SSL = 2048
CLIENT_TRANSACTIONS = 8192
CLIENT_SECURE_CONNECTION = 32768
CLIENT_MULTI_STATEMENTS = 1 << 16
CLIENT_MULTI_RESULTS = 1 << 17
CLIENT_PLUGIN_AUTH = 1 << 19
CLIENT_DEPRECATE_EOF = 1 << 24

SERVER_CAPABILITIES = (CLIENT_LONG_PASSWORD | CLIENT_FOUND_ROWS | CLIENT_LONG_FLAG |
                       CLIENT_CONNECT_WITH_DB | CLIENT_COMPRESS | CLIENT_PROTOCOL_41 |
                       CLIENT_TRANSACTIONS | CLIENT_SECURE_CONNECTION |
                       CLIENT_MULTI_STATEMENTS | CLIENT_MULTI_RESULTS |
                       CLIENT_PLUGIN_AUTH)

# status flags
SERVER_STATUS_AUTOCOMMIT = 2
SERVER_STATUS_IN_TRANS = 1
SERVER_MORE_RESULTS_EXISTS = 8

# commands
COM_QUIT = 0x01
COM_INIT_DB = 0x02
COM_QUERY = 0x03
COM_FIELD_LIST = 0x04
COM_PING = 0x0E
COM_STMT_PREPARE = 0x16
COM_STMT_EXECUTE = 0x17
COM_STMT_SEND_LONG_DATA = 0x18
COM_STMT_CLOSE = 0x19
COM_STMT_RESET = 0x1A
COM_SET_OPTION = 0x1B
COM_BINLOG_DUMP = 0x12

# column type codes
T_DECIMAL = 0x00
T_TINY = 0x01
T_SHORT = 0x02
T_LONG = 0x03
T_FLOAT = 0x04
T_DOUBLE = 0x05
T_NULL = 0x06
T_TIMESTAMP = 0x07
T_LONGLONG = 0x08
T_DATE = 0x0A
T_TIME = 0x0B
T_DATETIME = 0x0C
T_VARCHAR = 0x0F
T_NEWDECIMAL = 0xF6
T_VAR_STRING = 0xFD
T_STRING = 0xFE


def mysql_type_of(t: dt.DataType) -> int:
    c = t.clazz
    if c == dt.TypeClass.DECIMAL:
        return T_NEWDECIMAL
    if c in (dt.TypeClass.INT, dt.TypeClass.UINT, dt.TypeClass.BOOL):
        return {1: T_TINY, 2: T_SHORT, 4: T_LONG, 8: T_LONGLONG}.get(
            t.lane.itemsize, T_LONGLONG)
    if c == dt.TypeClass.FLOAT:
        return T_DOUBLE if t.precision == 8 else T_FLOAT
    if c == dt.TypeClass.DATE:
        return T_DATE
    if c == dt.TypeClass.DATETIME:
        return T_DATETIME
    if c == dt.TypeClass.TIME:
        return T_TIME
    return T_VAR_STRING


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------

def lenenc_int(n: int) -> bytes:
    if n < 0xFB:
        return bytes([n])
    if n < (1 << 16):
        return b"\xfc" + struct.pack("<H", n)
    if n < (1 << 24):
        return b"\xfd" + struct.pack("<I", n)[:3]
    return b"\xfe" + struct.pack("<Q", n)


def read_lenenc_int(buf: bytes, pos: int) -> Tuple[int, int]:
    first = buf[pos]
    if first < 0xFB:
        return first, pos + 1
    if first == 0xFC:
        return struct.unpack_from("<H", buf, pos + 1)[0], pos + 3
    if first == 0xFD:
        return struct.unpack_from("<I", buf[pos + 1:pos + 4] + b"\0")[0], pos + 4
    return struct.unpack_from("<Q", buf, pos + 1)[0], pos + 9


def lenenc_str(s: bytes) -> bytes:
    return lenenc_int(len(s)) + s


def read_lenenc_str(buf: bytes, pos: int) -> Tuple[bytes, int]:
    n, pos = read_lenenc_int(buf, pos)
    return buf[pos:pos + n], pos + n


def native_password_scramble(password: bytes, seed: bytes) -> bytes:
    """mysql_native_password: SHA1(pw) XOR SHA1(seed + SHA1(SHA1(pw)))."""
    if not password:
        return b""
    h1 = hashlib.sha1(password).digest()
    h2 = hashlib.sha1(h1).digest()
    h3 = hashlib.sha1(seed + h2).digest()
    return bytes(a ^ b for a, b in zip(h1, h3))


# ---------------------------------------------------------------------------
# server -> client packets (payloads; framing added by the transport)
# ---------------------------------------------------------------------------

def handshake_v10(conn_id: int, seed: bytes, caps: int = 0) -> bytes:
    caps = caps or SERVER_CAPABILITIES
    out = bytearray()
    out.append(PROTOCOL_VERSION)
    out += SERVER_VERSION + b"\0"
    out += struct.pack("<I", conn_id)
    out += seed[:8] + b"\0"
    out += struct.pack("<H", caps & 0xFFFF)
    out.append(CHARSET_UTF8MB4)
    out += struct.pack("<H", SERVER_STATUS_AUTOCOMMIT)
    out += struct.pack("<H", (caps >> 16) & 0xFFFF)
    out.append(len(seed) + 1)
    out += b"\0" * 10
    out += seed[8:] + b"\0"
    out += b"mysql_native_password\0"
    return bytes(out)


def parse_handshake_response(payload: bytes) -> dict:
    caps = struct.unpack_from("<I", payload, 0)[0]
    pos = 4 + 4 + 1 + 23  # caps, max packet, charset, filler
    end = payload.index(b"\0", pos)
    user = payload[pos:end].decode("utf8", "replace")
    pos = end + 1
    if caps & CLIENT_SECURE_CONNECTION:
        alen = payload[pos]
        auth = payload[pos + 1:pos + 1 + alen]
        pos += 1 + alen
    else:
        end = payload.index(b"\0", pos)
        auth = payload[pos:end]
        pos = end + 1
    database = None
    if caps & CLIENT_CONNECT_WITH_DB and pos < len(payload):
        end = payload.find(b"\0", pos)
        if end < 0:
            end = len(payload)
        database = payload[pos:end].decode("utf8", "replace") or None
        pos = end + 1
    return {"capabilities": caps, "user": user, "auth": auth, "database": database}


def ok_packet(affected: int = 0, last_insert_id: int = 0,
              status: int = SERVER_STATUS_AUTOCOMMIT, warnings: int = 0,
              info: bytes = b"") -> bytes:
    return (b"\x00" + lenenc_int(affected) + lenenc_int(last_insert_id) +
            struct.pack("<HH", status, warnings) + info)


def err_packet(errno: int, sqlstate: str, message: str) -> bytes:
    return (b"\xff" + struct.pack("<H", errno) + b"#" +
            sqlstate.encode("ascii")[:5].ljust(5, b"0") +
            message.encode("utf8")[:512])


def eof_packet(status: int = SERVER_STATUS_AUTOCOMMIT, warnings: int = 0) -> bytes:
    return b"\xfe" + struct.pack("<HH", warnings, status)


def column_def(name: str, typ: dt.DataType, table: str = "",
               schema: str = "") -> bytes:
    tcode = mysql_type_of(typ)
    charset = CHARSET_UTF8MB4 if typ.is_string else 63  # 63 = binary
    length = 255 if typ.is_string else 21
    decimals = typ.scale if typ.clazz == dt.TypeClass.DECIMAL else 0
    out = bytearray()
    out += lenenc_str(b"def")
    out += lenenc_str(schema.encode("utf8"))
    out += lenenc_str(table.encode("utf8"))
    out += lenenc_str(table.encode("utf8"))
    out += lenenc_str(name.encode("utf8"))
    out += lenenc_str(name.encode("utf8"))
    out.append(0x0C)
    out += struct.pack("<H", charset)
    out += struct.pack("<I", length)
    out.append(tcode)
    out += struct.pack("<H", 0)  # flags
    out.append(decimals)
    out += b"\0\0"
    return bytes(out)


def text_value(v: Any) -> bytes:
    if v is None:
        return b"\xfb"
    if isinstance(v, bool):
        v = int(v)
    if isinstance(v, float):
        s = repr(v).encode("ascii")
    elif isinstance(v, bytes):
        s = v
    else:
        s = str(v).encode("utf8")
    return lenenc_str(s)


def text_row(values: Sequence[Any]) -> bytes:
    return b"".join(text_value(v) for v in values)


def binary_row(values: Sequence[Any], types: Sequence[dt.DataType]) -> bytes:
    """Binary-protocol resultset row (COM_STMT_EXECUTE responses)."""
    n = len(values)
    null_bitmap = bytearray((n + 7 + 2) // 8)
    body = bytearray()
    for i, (v, t) in enumerate(zip(values, types)):
        if v is None:
            null_bitmap[(i + 2) // 8] |= 1 << ((i + 2) % 8)
            continue
        code = mysql_type_of(t)
        if code in (T_TINY,):
            body += struct.pack("<b", int(v))
        elif code == T_SHORT:
            body += struct.pack("<h", int(v))
        elif code == T_LONG:
            body += struct.pack("<i", int(v))
        elif code == T_LONGLONG:
            body += struct.pack("<q", int(v))
        elif code == T_FLOAT:
            body += struct.pack("<f", float(v))
        elif code == T_DOUBLE:
            body += struct.pack("<d", float(v))
        elif code in (T_DATE, T_DATETIME, T_TIMESTAMP):
            body += _binary_datetime(str(v))
        else:  # decimals and strings travel as text
            body += lenenc_str(str(v).encode("utf8"))
    return b"\x00" + bytes(null_bitmap) + bytes(body)


def _binary_datetime(s: str) -> bytes:
    date_part, _, time_part = s.partition(" ")
    y, m, d = (int(x) for x in date_part.split("-"))
    if not time_part:
        return bytes([4]) + struct.pack("<HBB", y, m, d)
    hh, mm, ss = time_part.split(":")
    frac = 0
    if "." in ss:
        ss, f = ss.split(".")
        frac = int(f.ljust(6, "0"))
    if frac:
        return bytes([11]) + struct.pack("<HBBBBBI", y, m, d, int(hh), int(mm),
                                         int(ss), frac)
    return bytes([7]) + struct.pack("<HBBBBB", y, m, d, int(hh), int(mm), int(ss))


def parse_stmt_execute_params(payload: bytes, n_params: int,
                              known_types: Optional[List[Tuple[int, int]]] = None
                              ) -> Tuple[List[Any], List[Tuple[int, int]]]:
    """COM_STMT_EXECUTE: [stmt_id][flags][iter][null bitmap][new_params][types][values].

    Connectors send parameter types only on the FIRST execute (new_params_bound_flag);
    later executes reuse them — the caller caches `types` and passes `known_types`.
    Returns (values, types_used)."""
    pos = 1 + 4 + 1 + 4
    if n_params == 0:
        return [], []
    nb_len = (n_params + 7) // 8
    null_bitmap = payload[pos:pos + nb_len]
    pos += nb_len
    new_params = payload[pos]
    pos += 1
    params: List[Any] = [None] * n_params
    if new_params:
        types = []
        for i in range(n_params):
            types.append((payload[pos], payload[pos + 1]))
            pos += 2
    elif known_types is not None:
        types = known_types
    else:
        from galaxysql_tpu.utils.errors import TddlError
        raise TddlError("malformed COM_STMT_EXECUTE: no parameter types bound")
    for i in range(n_params):
        if null_bitmap[i // 8] & (1 << (i % 8)):
            params[i] = None
            continue
        tcode, flags = types[i]
        unsigned = flags & 0x80
        if tcode == T_TINY:
            params[i] = payload[pos] if unsigned else \
                struct.unpack_from("<b", payload, pos)[0]
            pos += 1
        elif tcode == T_SHORT:
            params[i] = struct.unpack_from("<H" if unsigned else "<h", payload, pos)[0]
            pos += 2
        elif tcode == T_LONG:
            params[i] = struct.unpack_from("<I" if unsigned else "<i", payload, pos)[0]
            pos += 4
        elif tcode == T_LONGLONG:
            params[i] = struct.unpack_from("<Q" if unsigned else "<q", payload, pos)[0]
            pos += 8
        elif tcode == T_FLOAT:
            params[i] = struct.unpack_from("<f", payload, pos)[0]
            pos += 4
        elif tcode == T_DOUBLE:
            params[i] = struct.unpack_from("<d", payload, pos)[0]
            pos += 8
        elif tcode in (T_DATE, T_DATETIME, T_TIMESTAMP):
            ln = payload[pos]
            pos += 1
            if ln >= 4:
                y, m, d = struct.unpack_from("<HBB", payload, pos)
                val = f"{y:04d}-{m:02d}-{d:02d}"
                if ln >= 7:
                    hh, mm, ss = struct.unpack_from("<BBB", payload, pos + 4)
                    val += f" {hh:02d}:{mm:02d}:{ss:02d}"
                params[i] = val
            else:
                params[i] = "0000-00-00"
            pos += ln
        else:  # string-ish: lenenc
            s, pos = read_lenenc_str(payload, pos)
            params[i] = s.decode("utf8", "replace")
    return params, types
