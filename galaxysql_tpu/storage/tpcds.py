"""TPC-DS subset: schema, data generator, and a 10-query suite (BASELINE config #5).

Reference analog: the TPC-DS planner golden suite (`planner/tpcds/TpcdsPlanTest.java`,
SURVEY.md §4).  Queries are the official texts of q3/q7/q19/q22/q27/q42/q52/q55/q96/q59
lightly adapted to the supported grammar (no syntax changes beyond alias style).  The
generator follows the same approach as `tpch.py`: uniform draws over the spec's value
domains with SF-scaled cardinalities — representative for engine testing, not audited
TPC-DS publication.  Dates are epoch-day ints; decimals are floats at insert time
(encoded to scaled int64 lanes by the DECIMAL column types).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from galaxysql_tpu.types import temporal

TPCDS_DDL = {
    "date_dim": """
        CREATE TABLE date_dim (
            d_date_sk   INT NOT NULL PRIMARY KEY,
            d_date      DATE NOT NULL,
            d_year      INT NOT NULL,
            d_moy       INT NOT NULL,
            d_dom       INT NOT NULL,
            d_qoy       INT NOT NULL,
            d_week_seq  INT NOT NULL,
            d_month_seq INT NOT NULL,
            d_day_name  VARCHAR(9) NOT NULL
        ) BROADCAST
    """,
    "time_dim": """
        CREATE TABLE time_dim (
            t_time_sk INT NOT NULL PRIMARY KEY,
            t_hour    INT NOT NULL,
            t_minute  INT NOT NULL
        ) BROADCAST
    """,
    "item": """
        CREATE TABLE item (
            i_item_sk      INT NOT NULL PRIMARY KEY,
            i_item_id      VARCHAR(16) NOT NULL,
            i_brand_id     INT,
            i_brand        VARCHAR(50),
            i_class_id     INT,
            i_class        VARCHAR(50),
            i_category_id  INT,
            i_category     VARCHAR(50),
            i_manufact_id  INT,
            i_manufact     VARCHAR(50),
            i_manager_id   INT,
            i_product_name VARCHAR(50),
            i_current_price DECIMAL(7,2)
        ) PARTITION BY HASH(i_item_sk) PARTITIONS 4
    """,
    "customer": """
        CREATE TABLE customer (
            c_customer_sk      INT NOT NULL PRIMARY KEY,
            c_customer_id      VARCHAR(16) NOT NULL,
            c_current_cdemo_sk INT,
            c_current_addr_sk  INT,
            c_first_name       VARCHAR(20),
            c_last_name        VARCHAR(30)
        ) PARTITION BY HASH(c_customer_sk) PARTITIONS 4
    """,
    "customer_address": """
        CREATE TABLE customer_address (
            ca_address_sk INT NOT NULL PRIMARY KEY,
            ca_state      VARCHAR(2),
            ca_zip        VARCHAR(10),
            ca_county     VARCHAR(30),
            ca_country    VARCHAR(20)
        ) PARTITION BY HASH(ca_address_sk) PARTITIONS 4
    """,
    "customer_demographics": """
        CREATE TABLE customer_demographics (
            cd_demo_sk          INT NOT NULL PRIMARY KEY,
            cd_gender           VARCHAR(1),
            cd_marital_status   VARCHAR(1),
            cd_education_status VARCHAR(20),
            cd_dep_count        INT
        ) BROADCAST
    """,
    "household_demographics": """
        CREATE TABLE household_demographics (
            hd_demo_sk      INT NOT NULL PRIMARY KEY,
            hd_dep_count    INT,
            hd_vehicle_count INT
        ) BROADCAST
    """,
    "store": """
        CREATE TABLE store (
            s_store_sk    INT NOT NULL PRIMARY KEY,
            s_store_id    VARCHAR(16) NOT NULL,
            s_store_name  VARCHAR(50),
            s_number_employees INT,
            s_state       VARCHAR(2),
            s_zip         VARCHAR(10),
            s_county      VARCHAR(30)
        ) BROADCAST
    """,
    "promotion": """
        CREATE TABLE promotion (
            p_promo_sk      INT NOT NULL PRIMARY KEY,
            p_channel_dmail VARCHAR(1),
            p_channel_email VARCHAR(1),
            p_channel_event VARCHAR(1),
            p_channel_tv    VARCHAR(1)
        ) BROADCAST
    """,
    "warehouse": """
        CREATE TABLE warehouse (
            w_warehouse_sk   INT NOT NULL PRIMARY KEY,
            w_warehouse_name VARCHAR(20)
        ) BROADCAST
    """,
    "inventory": """
        CREATE TABLE inventory (
            inv_date_sk          INT NOT NULL,
            inv_item_sk          INT NOT NULL,
            inv_warehouse_sk     INT NOT NULL,
            inv_quantity_on_hand INT
        ) PARTITION BY HASH(inv_item_sk) PARTITIONS 4
    """,
    "store_sales": """
        CREATE TABLE store_sales (
            ss_sold_date_sk   INT,
            ss_sold_time_sk   INT,
            ss_item_sk        INT NOT NULL,
            ss_customer_sk    INT,
            ss_cdemo_sk       INT,
            ss_hdemo_sk       INT,
            ss_addr_sk        INT,
            ss_store_sk       INT,
            ss_promo_sk       INT,
            ss_quantity       INT,
            ss_list_price     DECIMAL(7,2),
            ss_sales_price    DECIMAL(7,2),
            ss_ext_sales_price DECIMAL(7,2),
            ss_ext_discount_amt DECIMAL(7,2),
            ss_coupon_amt     DECIMAL(7,2),
            ss_net_profit     DECIMAL(7,2)
        ) PARTITION BY HASH(ss_item_sk) PARTITIONS 8
    """,
}

TABLE_ORDER = ["date_dim", "time_dim", "item", "customer", "customer_address",
               "customer_demographics", "household_demographics", "store",
               "promotion", "warehouse", "inventory", "store_sales"]

_DAY_NAMES = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday", "Friday",
              "Saturday"]
_STATES = ["TN", "SD", "AL", "GA", "OH", "TX", "CA", "WA"]
_CATEGORIES = ["Books", "Home", "Electronics", "Jewelry", "Sports", "Music",
               "Women", "Men", "Children", "Shoes"]
_EDU = ["College", "2 yr Degree", "4 yr Degree", "Advanced Degree", "Primary",
        "Secondary", "Unknown"]


def generate(sf: float, seed: int = 20030101) -> Dict[str, Dict[str, list]]:
    """All twelve tables at scale factor `sf` as column dicts of Python values."""
    rng = np.random.default_rng(seed)
    out: Dict[str, Dict[str, list]] = {}

    # date_dim: calendar 1998-01-01 .. 2002-12-31 (the window the queries hit)
    d0 = temporal.parse_date("1998-01-01")
    d1 = temporal.parse_date("2002-12-31")
    days = np.arange(d0, d1 + 1)
    ymd = [temporal.civil_from_days(int(d)) for d in days]
    years = np.array([y for y, _m, _d in ymd])
    moys = np.array([m for _y, m, _d in ymd])
    doms = np.array([d for _y, _m, d in ymd])
    # TPC-DS d_date_sk base is 2415022 (julian-ish); keep small consecutive sks
    sks = np.arange(len(days)) + 2450815
    out["date_dim"] = {
        "d_date_sk": sks.tolist(),
        "d_date": days.tolist(),
        "d_year": years.tolist(),
        "d_moy": moys.tolist(),
        "d_dom": doms.tolist(),
        "d_qoy": ((moys - 1) // 3 + 1).tolist(),
        "d_week_seq": ((days - d0) // 7 + 5000).tolist(),
        "d_month_seq": ((years - 1900) * 12 + moys - 1).tolist(),
        "d_day_name": [_DAY_NAMES[int(d + 4) % 7] for d in days],  # 1998-01-01 = Thu
    }
    date_sks = sks

    n_time = 1440
    out["time_dim"] = {
        "t_time_sk": list(range(n_time)),
        "t_hour": [t // 60 for t in range(n_time)],
        "t_minute": [t % 60 for t in range(n_time)],
    }

    n_item = max(int(18000 * sf), 200)
    brands = rng.integers(1, 1000, n_item)
    cats = rng.integers(0, len(_CATEGORIES), n_item)
    classes = rng.integers(1, 100, n_item)
    out["item"] = {
        "i_item_sk": list(range(1, n_item + 1)),
        "i_item_id": [f"ITEM{k:012d}"[:16] for k in rng.integers(0, n_item // 2 + 1, n_item)],
        "i_brand_id": brands.tolist(),
        "i_brand": [f"brand#{b}" for b in brands],
        "i_class_id": classes.tolist(),
        "i_class": [f"class{c}" for c in classes],
        "i_category_id": (cats + 1).tolist(),
        "i_category": [_CATEGORIES[c] for c in cats],
        "i_manufact_id": rng.integers(1, 200, n_item).tolist(),
        "i_manufact": [f"manu#{m}" for m in rng.integers(1, 100, n_item)],
        "i_manager_id": rng.integers(1, 40, n_item).tolist(),
        "i_product_name": [f"prod{p}" for p in rng.integers(1, n_item // 4 + 2, n_item)],
        "i_current_price": np.round(rng.uniform(0.5, 100, n_item), 2).tolist(),
    }

    n_cust = max(int(100_000 * sf), 500)
    n_addr = max(n_cust // 2, 250)
    n_cd = 720
    n_hd = 144
    out["customer"] = {
        "c_customer_sk": list(range(1, n_cust + 1)),
        "c_customer_id": [f"CUST{k:012d}"[:16] for k in range(1, n_cust + 1)],
        "c_current_cdemo_sk": rng.integers(1, n_cd + 1, n_cust).tolist(),
        "c_current_addr_sk": rng.integers(1, n_addr + 1, n_cust).tolist(),
        "c_first_name": [f"fn{k}" for k in rng.integers(0, 500, n_cust)],
        "c_last_name": [f"ln{k}" for k in rng.integers(0, 700, n_cust)],
    }
    out["customer_address"] = {
        "ca_address_sk": list(range(1, n_addr + 1)),
        "ca_state": [_STATES[k] for k in rng.integers(0, len(_STATES), n_addr)],
        "ca_zip": [f"{z:05d}" for z in rng.integers(10000, 99999, n_addr)],
        "ca_county": [f"county{k}" for k in rng.integers(0, 30, n_addr)],
        "ca_country": ["United States"] * n_addr,
    }
    out["customer_demographics"] = {
        "cd_demo_sk": list(range(1, n_cd + 1)),
        "cd_gender": [("M", "F")[k % 2] for k in range(n_cd)],
        "cd_marital_status": ["SMDWU"[k // 2 % 5] for k in range(n_cd)],
        "cd_education_status": [_EDU[k // 10 % len(_EDU)] for k in range(n_cd)],
        "cd_dep_count": [k % 7 for k in range(n_cd)],
    }
    out["household_demographics"] = {
        "hd_demo_sk": list(range(1, n_hd + 1)),
        "hd_dep_count": [k % 10 for k in range(n_hd)],
        "hd_vehicle_count": [k % 5 for k in range(n_hd)],
    }

    n_store = 12
    out["store"] = {
        "s_store_sk": list(range(1, n_store + 1)),
        "s_store_id": [f"ST{k:014d}"[:16] for k in range(1, n_store + 1)],
        "s_store_name": [("ese", "ought", "able", "bar")[k % 4]
                         for k in range(n_store)],
        "s_number_employees": rng.integers(200, 300, n_store).tolist(),
        "s_state": [_STATES[k % len(_STATES)] for k in range(n_store)],
        "s_zip": [f"{z:05d}" for z in rng.integers(10000, 99999, n_store)],
        "s_county": [f"county{k % 30}" for k in range(n_store)],
    }
    n_promo = 300
    yn = np.array(["Y", "N"])
    out["promotion"] = {
        "p_promo_sk": list(range(1, n_promo + 1)),
        "p_channel_dmail": yn[rng.integers(0, 2, n_promo)].tolist(),
        "p_channel_email": yn[rng.integers(0, 2, n_promo)].tolist(),
        "p_channel_event": yn[rng.integers(0, 2, n_promo)].tolist(),
        "p_channel_tv": yn[rng.integers(0, 2, n_promo)].tolist(),
    }

    n_wh = 5
    out["warehouse"] = {
        "w_warehouse_sk": list(range(1, n_wh + 1)),
        "w_warehouse_name": [f"wh{k}" for k in range(1, n_wh + 1)],
    }
    n_inv = max(int(sf * 200_000), 5000)
    out["inventory"] = {
        "inv_date_sk": rng.choice(date_sks, n_inv).tolist(),
        "inv_item_sk": rng.integers(1, n_item + 1, n_inv).tolist(),
        "inv_warehouse_sk": rng.integers(1, n_wh + 1, n_inv).tolist(),
        "inv_quantity_on_hand": rng.integers(0, 1000, n_inv).tolist(),
    }

    n_ss = max(int(sf * 2_880_000), 20_000)
    qty = rng.integers(1, 101, n_ss)
    list_price = np.round(rng.uniform(1, 200, n_ss), 2)
    sales_price = np.round(list_price * rng.uniform(0.2, 1.0, n_ss), 2)
    ext_sales = np.round(sales_price * qty, 2)
    out["store_sales"] = {
        "ss_sold_date_sk": rng.choice(date_sks, n_ss).tolist(),
        "ss_sold_time_sk": rng.integers(0, n_time, n_ss).tolist(),
        "ss_item_sk": rng.integers(1, n_item + 1, n_ss).tolist(),
        "ss_customer_sk": rng.integers(1, n_cust + 1, n_ss).tolist(),
        "ss_cdemo_sk": rng.integers(1, n_cd + 1, n_ss).tolist(),
        "ss_hdemo_sk": rng.integers(1, n_hd + 1, n_ss).tolist(),
        "ss_addr_sk": rng.integers(1, n_addr + 1, n_ss).tolist(),
        "ss_store_sk": rng.integers(1, n_store + 1, n_ss).tolist(),
        "ss_promo_sk": rng.integers(1, n_promo + 1, n_ss).tolist(),
        "ss_quantity": qty.tolist(),
        "ss_list_price": list_price.tolist(),
        "ss_sales_price": sales_price.tolist(),
        "ss_ext_sales_price": ext_sales.tolist(),
        "ss_ext_discount_amt": np.round((list_price - sales_price) * qty, 2).tolist(),
        "ss_coupon_amt": np.round(rng.uniform(0, 20, n_ss), 2).tolist(),
        "ss_net_profit": np.round(ext_sales * rng.uniform(-0.1, 0.4, n_ss), 2).tolist(),
    }
    return out


QUERIES: Dict[str, str] = {
    "q3": """
        SELECT d_year, i_brand_id, i_brand, sum(ss_ext_sales_price) AS sum_agg
        FROM date_dim, store_sales, item
        WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
          AND i_manufact_id = 128 AND d_moy = 11
        GROUP BY d_year, i_brand, i_brand_id
        ORDER BY d_year, sum_agg DESC, i_brand_id LIMIT 100
    """,
    "q7": """
        SELECT i_item_id, avg(ss_quantity) AS agg1, avg(ss_list_price) AS agg2,
               avg(ss_coupon_amt) AS agg3, avg(ss_sales_price) AS agg4
        FROM store_sales, customer_demographics, date_dim, item, promotion
        WHERE ss_sold_date_sk = d_date_sk AND ss_item_sk = i_item_sk
          AND ss_cdemo_sk = cd_demo_sk AND ss_promo_sk = p_promo_sk
          AND cd_gender = 'M' AND cd_marital_status = 'S'
          AND cd_education_status = 'College'
          AND (p_channel_email = 'N' OR p_channel_event = 'N') AND d_year = 2000
        GROUP BY i_item_id ORDER BY i_item_id LIMIT 100
    """,
    "q19": """
        SELECT i_brand_id, i_brand, i_manufact_id, i_manufact,
               sum(ss_ext_sales_price) AS ext_price
        FROM date_dim, store_sales, item, customer, customer_address, store
        WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
          AND i_manager_id = 8 AND d_moy = 11 AND d_year = 1998
          AND ss_customer_sk = c_customer_sk AND c_current_addr_sk = ca_address_sk
          AND ss_store_sk = s_store_sk
          AND substr(ca_zip, 1, 5) <> substr(s_zip, 1, 5)
        GROUP BY i_brand, i_brand_id, i_manufact_id, i_manufact
        ORDER BY ext_price DESC, i_brand, i_brand_id, i_manufact_id, i_manufact
        LIMIT 100
    """,
    "q22": """
        SELECT i_product_name, i_brand, i_class, i_category,
               avg(inv_quantity_on_hand) AS qoh
        FROM inventory, date_dim, item
        WHERE inv_date_sk = d_date_sk AND inv_item_sk = i_item_sk
          AND d_month_seq BETWEEN 1200 AND 1211
        GROUP BY ROLLUP(i_product_name, i_brand, i_class, i_category)
        ORDER BY qoh, i_product_name, i_brand, i_class, i_category LIMIT 100
    """,
    "q27": """
        SELECT i_item_id, s_state, avg(ss_quantity) AS agg1,
               avg(ss_list_price) AS agg2, avg(ss_coupon_amt) AS agg3,
               avg(ss_sales_price) AS agg4
        FROM store_sales, customer_demographics, date_dim, store, item
        WHERE ss_sold_date_sk = d_date_sk AND ss_item_sk = i_item_sk
          AND ss_store_sk = s_store_sk AND ss_cdemo_sk = cd_demo_sk
          AND cd_gender = 'M' AND cd_marital_status = 'S'
          AND cd_education_status = 'College' AND d_year = 2002
          AND s_state IN ('TN', 'SD')
        GROUP BY ROLLUP(i_item_id, s_state)
        ORDER BY i_item_id, s_state LIMIT 100
    """,
    "q42": """
        SELECT d_year, i_category_id, i_category, sum(ss_ext_sales_price) AS s
        FROM date_dim, store_sales, item
        WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
          AND i_manager_id = 1 AND d_moy = 11 AND d_year = 2000
        GROUP BY d_year, i_category_id, i_category
        ORDER BY s DESC, d_year, i_category_id, i_category LIMIT 100
    """,
    "q52": """
        SELECT d_year, i_brand_id, i_brand, sum(ss_ext_sales_price) AS ext_price
        FROM date_dim, store_sales, item
        WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
          AND i_manager_id = 1 AND d_moy = 11 AND d_year = 2000
        GROUP BY d_year, i_brand, i_brand_id
        ORDER BY d_year, ext_price DESC, i_brand_id LIMIT 100
    """,
    "q55": """
        SELECT i_brand_id, i_brand, sum(ss_ext_sales_price) AS ext_price
        FROM date_dim, store_sales, item
        WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
          AND i_manager_id = 28 AND d_moy = 11 AND d_year = 1999
        GROUP BY i_brand, i_brand_id
        ORDER BY ext_price DESC, i_brand_id LIMIT 100
    """,
    "q96": """
        SELECT count(*) AS cnt
        FROM store_sales, household_demographics, time_dim, store
        WHERE ss_sold_time_sk = t_time_sk AND ss_hdemo_sk = hd_demo_sk
          AND ss_store_sk = s_store_sk AND t_hour = 20 AND t_minute >= 30
          AND hd_dep_count = 7 AND s_store_name = 'ese'
    """,
    "q59": """
        WITH wss AS (
            SELECT d_week_seq, ss_store_sk,
                sum(CASE WHEN d_day_name = 'Sunday' THEN ss_sales_price
                    ELSE NULL END) AS sun_sales,
                sum(CASE WHEN d_day_name = 'Monday' THEN ss_sales_price
                    ELSE NULL END) AS mon_sales,
                sum(CASE WHEN d_day_name = 'Friday' THEN ss_sales_price
                    ELSE NULL END) AS fri_sales
            FROM store_sales, date_dim
            WHERE d_date_sk = ss_sold_date_sk
            GROUP BY d_week_seq, ss_store_sk)
        SELECT y.s_store_name1, y.s_store_id1, y.d_week_seq1,
               y.sun_sales1 / x.sun_sales2 AS r1,
               y.mon_sales1 / x.mon_sales2 AS r2,
               y.fri_sales1 / x.fri_sales2 AS r3
        FROM (SELECT s_store_name AS s_store_name1, wss.d_week_seq AS d_week_seq1,
                     s_store_id AS s_store_id1, sun_sales AS sun_sales1,
                     mon_sales AS mon_sales1, fri_sales AS fri_sales1
              FROM wss, store, date_dim d
              WHERE d.d_week_seq = wss.d_week_seq AND ss_store_sk = s_store_sk
                AND d_month_seq BETWEEN 1212 AND 1223) y,
             (SELECT s_store_name AS s_store_name2, wss.d_week_seq AS d_week_seq2,
                     s_store_id AS s_store_id2, sun_sales AS sun_sales2,
                     mon_sales AS mon_sales2, fri_sales AS fri_sales2
              FROM wss, store, date_dim d
              WHERE d.d_week_seq = wss.d_week_seq AND ss_store_sk = s_store_sk
                AND d_month_seq BETWEEN 1224 AND 1235) x
        WHERE y.s_store_id1 = x.s_store_id2
          AND y.d_week_seq1 = x.d_week_seq2 - 52
        ORDER BY y.s_store_name1, y.d_week_seq1, y.s_store_id1 LIMIT 100
    """,
}
