"""Partitioned columnar table store — the DN (data node) storage analog.

Reference analog: the `galaxyengine` DN holds sharded row storage; the CN ships physical
operations per shard (SURVEY.md §2.9, §3.2).  Here each partition is a host-resident
struct-of-arrays column set (numpy lanes + null masks) with:

- append path used by INSERT/LOAD (routes rows via PartitionRouter),
- scan path yielding ColumnBatches (bucketed/padded for stable jit shapes),
- persistence as one .npz per partition + dictionaries, for restart.

MVCC: each partition keeps per-row `begin_ts`/`end_ts` lanes; a snapshot scan at ts sees
rows with begin_ts <= ts < end_ts.  DML writes go through `txn/` which stamps these lanes
(TSO ordering, SURVEY.md §3.4).
"""

from __future__ import annotations

import itertools
import json
import os
import threading
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from galaxysql_tpu.chunk.batch import Column, ColumnBatch, Dictionary, column_from_pylist
from galaxysql_tpu.meta.catalog import PartitionRouter, TableMeta
from galaxysql_tpu.types import datatype as dt
from galaxysql_tpu.utils import errors
from galaxysql_tpu.utils.failpoint import FAIL_POINTS, FP_LOCK_INVERT
from galaxysql_tpu.utils.lockdep import named_lock

INFINITY_TS = (1 << 63) - 1  # int64 max; must exceed any TSO value (phys_ms << 22 ~ 7.5e18)


class Partition:
    """One shard of a table: numpy lanes + validity + MVCC timestamps."""

    def __init__(self, table: TableMeta, pid: int):
        self.table = table
        self.pid = pid
        self.lanes: Dict[str, np.ndarray] = {
            c.name: np.zeros(0, dtype=c.dtype.lane) for c in table.columns}
        self.valid: Dict[str, np.ndarray] = {
            c.name: np.zeros(0, dtype=np.bool_) for c in table.columns}
        self.begin_ts = np.zeros(0, dtype=np.int64)
        self.end_ts = np.zeros(0, dtype=np.int64)
        # lockdep class splits base tables from GSI stores ($-named): the
        # write path legitimately nests base-partition -> gsi-partition
        # (e.g. UPDATE holds the base row lock while maintaining the index),
        # which is a cross-class ORDER, not a same-class hazard
        self.lock = named_lock(
            "partition.gsi" if "$" in table.name else "partition")
        # append-aware sorted key indexes: col -> (lane_gen, n0, perm, sorted_keys)
        # where perm sorts rows [0, n0).  Appends don't invalidate (MVCC rows are
        # immutable; the [n0, n) tail is probed linearly until it outgrows
        # _INDEX_TAIL); wholesale lane replacement (column DDL, load) bumps
        # lane_gen and forces a rebuild.
        self._key_indexes: Dict[str, Tuple[int, int, np.ndarray, np.ndarray]] = {}
        self.lane_gen = 0

    _INDEX_TAIL = 8192

    def invalidate_indexes(self):
        """Call after replacing lane arrays in place (column DDL, reload)."""
        self.lane_gen += 1
        self._key_indexes.clear()

    def key_index(self, col: str):
        """(n0, perm, sorted_keys) of the append-aware sorted index over
        `col` (building it if stale).  `perm` stable-sorts rows [0, n0), so
        perm[lo:hi] enumerates equal-key rows in ascending row-id order; rows
        [n0, num_rows) are the unsorted appended tail the caller must probe
        separately.  Caller must hold `self.lock`."""
        n = self.num_rows
        lane = self.lanes[col]
        entry = self._key_indexes.get(col)
        if entry is None or entry[0] != self.lane_gen or \
                n - entry[1] > self._INDEX_TAIL:
            perm = np.argsort(lane[:n], kind="stable")
            entry = (self.lane_gen, n, perm, lane[:n][perm])
            self._key_indexes[col] = entry
        return entry[1], entry[2], entry[3]

    def key_candidates(self, col: str, lane_value) -> np.ndarray:
        """Row ids whose `col` lane equals the (lane-encoded) value.

        MVCC-unaware: returns every physical row version with that key; the
        caller applies visibility.  O(log n) over the indexed prefix plus a
        linear probe of the unsorted appended tail (XPlan key-Get analog,
        RelToXPlanConverter.java:41)."""
        with self.lock:
            n = self.num_rows
            lane = self.lanes[col]
            n0, perm, skeys = self.key_index(col)
            lo = np.searchsorted(skeys, lane_value, side="left")
            hi = np.searchsorted(skeys, lane_value, side="right")
            ids = perm[lo:hi]
            if n > n0:
                tail = np.nonzero(lane[n0:n] == lane_value)[0] + n0
                ids = np.concatenate([ids, tail]) if tail.size else ids
            return ids

    @property
    def num_rows(self) -> int:
        return int(self.begin_ts.shape[0])

    def append(self, lanes: Dict[str, np.ndarray], valid: Dict[str, np.ndarray],
               begin_ts: int):
        n = next(iter(lanes.values())).shape[0] if lanes else 0
        with self.lock:
            for c in self.table.columns:
                self.lanes[c.name] = np.concatenate([self.lanes[c.name], lanes[c.name]])
                self.valid[c.name] = np.concatenate([self.valid[c.name], valid[c.name]])
            self.begin_ts = np.concatenate(
                [self.begin_ts, np.full(n, begin_ts, dtype=np.int64)])
            self.end_ts = np.concatenate(
                [self.end_ts, np.full(n, INFINITY_TS, dtype=np.int64)])

    def visible_mask(self, snapshot_ts: Optional[int], txn_id: int = 0) -> np.ndarray:
        """MVCC visibility.  Uncommitted changes carry NEGATIVE timestamps (-txn_id):
        visible only to the owning transaction; finalized to real TSO values at commit
        (the in-process analog of the reference's innodb snapshot_seq/commit_seq
        dance, SURVEY.md §3.4).  Computed by the native runtime when available."""
        from galaxysql_tpu import native
        return native.visible_mask(self.begin_ts, self.end_ts, snapshot_ts, txn_id)

    def delete_rows(self, row_ids: np.ndarray, commit_ts: int):
        with self.lock:
            self.end_ts[row_ids] = commit_ts

    def update_rows(self, row_ids: np.ndarray, new_lanes: Dict[str, np.ndarray],
                    new_valid: Dict[str, np.ndarray], commit_ts: int):
        """MVCC update = end old versions + append new versions."""
        with self.lock:
            full_lanes = {}
            full_valid = {}
            for c in self.table.columns:
                if c.name in new_lanes:
                    full_lanes[c.name] = new_lanes[c.name]
                    full_valid[c.name] = new_valid[c.name]
                else:
                    full_lanes[c.name] = self.lanes[c.name][row_ids]
                    full_valid[c.name] = self.valid[c.name][row_ids]
            self.end_ts[row_ids] = commit_ts
            self.append(full_lanes, full_valid, commit_ts)


class TableStore:
    _next_uid = itertools.count(1)

    def __init__(self, table: TableMeta):
        self.table = table
        self.router = PartitionRouter(table)
        n = table.partition.num_partitions
        self.partitions = [Partition(table, i) for i in range(n)]
        # process-unique identity for caches (id() can be recycled after GC)
        self.uid = next(TableStore._next_uid)
        # serializes the (before-count -> append -> derive appended ranges)
        # critical section DML writers run: two concurrent inserts reading
        # num_rows, appending, and re-reading would each attribute the
        # OTHER's rows to their own [start, n) range — double-captured CDC,
        # double-propagated GSI rows, mis-ranged txn undo entries.  Partition
        # locks only make each append atomic, not the count arithmetic.
        self.append_lock = named_lock(
            "append_lock.gsi" if "$" in table.name else "append_lock")

    # -- write path ----------------------------------------------------------

    def _lockdep_probe(self):
        """FP_LOCK_INVERT: deliberately acquire a partition lock and THEN the
        append_lock — the reverse of the canonical order — on the real insert
        ramp, so the lockdep witness test proves the runtime cycle check trips
        where it matters.  Disarmed (always, outside that test), this is one
        bool read.  Called BEFORE the ramp takes append_lock: a nested
        re-entrant acquisition would not create a graph edge."""
        if FAIL_POINTS.active and FAIL_POINTS.value(FP_LOCK_INVERT) \
                and self.partitions:
            p = self.partitions[0]
            with p.lock:
                with self.append_lock:  # galaxylint: disable=lock-order -- deliberate seeded inversion proving the lockdep witness trips (tests/test_lint.py)
                    pass

    def insert_pylists(self, data: Dict[str, List[Any]], begin_ts: int) -> int:
        """Encode python values and route rows to partitions.  Returns rows inserted."""
        lanes, valid, n = self.encode_pylists(data)
        return self.append_encoded(lanes, valid, n, begin_ts)

    def encode_pylists(self, data: Dict[str, List[Any]]):
        """Phase 1 of insert_pylists: python values -> (lanes, valid, n),
        mutating NOTHING except auto-increment allocation.  Split out so the
        batched write path can fail a bad value strictly pre-mutation."""
        table = self.table
        n = len(next(iter(data.values()))) if data else 0
        lanes: Dict[str, np.ndarray] = {}
        valid: Dict[str, np.ndarray] = {}
        for c in table.columns:
            values = data.get(c.name)
            if values is None:
                if c.auto_increment:
                    start = table.auto_increment_next
                    table.auto_increment_next += n
                    lanes[c.name] = np.arange(start, start + n, dtype=c.dtype.lane)
                    valid[c.name] = np.ones(n, dtype=np.bool_)
                    continue
                dv = c.default
                values = [dv] * n
            col = column_from_pylist(values, c.dtype,
                                     table.dictionaries.get(c.name.lower()))
            lanes[c.name] = col.np_data()
            valid[c.name] = col.np_valid()
            if not c.nullable and not valid[c.name].all() and c.default is None:
                raise errors.TddlError(f"Column '{c.name}' cannot be null")
        return lanes, valid, n

    def append_encoded(self, lanes, valid, n: int, begin_ts: int) -> int:
        """Phase 2 of insert_pylists: route + append pre-encoded lanes."""
        pids = self._route(lanes)
        for pid in np.unique(pids):
            sel = np.nonzero(pids == pid)[0]
            self.partitions[int(pid)].append(
                {k: v[sel] for k, v in lanes.items()},
                {k: v[sel] for k, v in valid.items()}, begin_ts)
        self.table.stats.row_count += n
        return n

    def insert_arrays(self, data: Dict[str, Any], begin_ts: int) -> int:
        """Bulk ingestion fast path: numeric columns as numpy arrays pass through;
        string columns are dictionary-encoded via np.unique (LOAD DATA analog)."""
        table = self.table
        n = len(next(iter(data.values()))) if data else 0
        lanes: Dict[str, np.ndarray] = {}
        valid: Dict[str, np.ndarray] = {}
        for c in table.columns:
            values = data.get(c.name)
            if values is None:
                if c.auto_increment:
                    start = table.auto_increment_next
                    table.auto_increment_next += n
                    lanes[c.name] = np.arange(start, start + n, dtype=c.dtype.lane)
                    valid[c.name] = np.ones(n, dtype=np.bool_)
                    continue
                lanes[c.name] = np.zeros(n, dtype=c.dtype.lane)
                valid[c.name] = np.zeros(n, dtype=np.bool_)
                continue
            if c.dtype.is_string:
                arr = np.asarray(values, dtype=object)
                uniq, inverse = np.unique(arr.astype(str), return_inverse=True)
                d = table.dictionaries[c.name.lower()]
                trans = np.fromiter((d.encode_one(u) for u in uniq.tolist()),
                                    dtype=np.int32, count=len(uniq))
                lanes[c.name] = trans[inverse].astype(np.int32)
                valid[c.name] = np.ones(n, dtype=np.bool_)
            elif c.dtype.clazz == dt.TypeClass.DECIMAL:
                a = np.asarray(values, dtype=np.float64)
                lanes[c.name] = np.round(a * 10 ** c.dtype.scale).astype(np.int64)
                valid[c.name] = ~np.isnan(a)
            else:
                lanes[c.name] = np.asarray(values).astype(c.dtype.lane)
                valid[c.name] = np.ones(n, dtype=np.bool_)
        pids = self._route(lanes)
        for pid in np.unique(pids):
            sel = np.nonzero(pids == pid)[0]
            self.partitions[int(pid)].append(
                {k: v[sel] for k, v in lanes.items()},
                {k: v[sel] for k, v in valid.items()}, begin_ts)
        table.stats.row_count += n
        return n

    def _route(self, lanes: Dict[str, np.ndarray]) -> np.ndarray:
        info = self.table.partition
        n = next(iter(lanes.values())).shape[0] if lanes else 0
        if info.method in ("single", "broadcast"):
            return np.zeros(n, dtype=np.int32)
        keys = [lanes[c] if c in lanes else lanes[self.table.column(c).name]
                for c in info.columns]
        return self.router.route_rows(keys)

    # -- read path -------------------------------------------------------------

    def scan_partition(self, pid: int, columns: Sequence[str],
                       snapshot_ts: Optional[int] = None,
                       batch_rows: int = 1 << 20,
                       txn_id: int = 0) -> Iterator[ColumnBatch]:
        """Yield ColumnBatches of up to batch_rows visible rows."""
        p = self.partitions[pid]
        with p.lock:
            vis = p.visible_mask(snapshot_ts, txn_id)
            idx = np.nonzero(vis)[0]
            data = {c: p.lanes[c][idx] for c in columns}
            valid = {c: p.valid[c][idx] for c in columns}
        n = idx.shape[0]
        table = self.table
        for off in range(0, max(n, 1), batch_rows):
            hi = min(off + batch_rows, n)
            if n == 0 and off > 0:
                break
            cols = {}
            for c in columns:
                cm = table.column(c)
                v = valid[c][off:hi]
                cols[c] = Column(data[c][off:hi], None if v.all() else v, cm.dtype,
                                 table.dictionaries.get(c.lower()))
            yield ColumnBatch(cols, None)
            if hi >= n:
                break

    def scan(self, columns: Sequence[str], partitions: Optional[Sequence[int]] = None,
             snapshot_ts: Optional[int] = None, txn_id: int = 0
             ) -> Iterator[ColumnBatch]:
        pids = range(len(self.partitions)) if partitions is None else partitions
        for pid in pids:
            yield from self.scan_partition(pid, columns, snapshot_ts, txn_id=txn_id)

    def row_count(self, snapshot_ts: Optional[int] = None, txn_id: int = 0) -> int:
        return sum(int(p.visible_mask(snapshot_ts, txn_id).sum())
                   for p in self.partitions)

    def truncate(self):
        n = self.table.partition.num_partitions
        self.partitions = [Partition(self.table, i) for i in range(n)]
        self.table.stats.row_count = 0

    # -- persistence -------------------------------------------------------------

    def save(self, directory: str):
        os.makedirs(directory, exist_ok=True)
        for p in self.partitions:
            arrays = {f"lane__{k}": v for k, v in p.lanes.items()}
            arrays.update({f"valid__{k}": v for k, v in p.valid.items()})
            arrays["begin_ts"] = p.begin_ts
            arrays["end_ts"] = p.end_ts
            np.savez_compressed(os.path.join(directory, f"p{p.pid}.npz"), **arrays)
        dicts = {k: d.values for k, d in self.table.dictionaries.items()}
        with open(os.path.join(directory, "dictionaries.json"), "w") as f:
            json.dump(dicts, f)

    def load(self, directory: str):
        dpath = os.path.join(directory, "dictionaries.json")
        if os.path.exists(dpath):
            with open(dpath) as f:
                dicts = json.load(f)
            for k, values in dicts.items():
                d = self.table.dictionaries.get(k)
                if d is not None:
                    for v in values:
                        d.encode_one(v)
        for p in self.partitions:
            path = os.path.join(directory, f"p{p.pid}.npz")
            if not os.path.exists(path):
                continue
            z = np.load(path, allow_pickle=False)
            p.begin_ts = z["begin_ts"]
            p.end_ts = z["end_ts"]
            for c in self.table.columns:
                p.lanes[c.name] = z[f"lane__{c.name}"]
                p.valid[c.name] = z[f"valid__{c.name}"]
            p.invalidate_indexes()
        self.table.stats.row_count = self.row_count()
