"""TPC-H schema, data generator, and query texts (from the public TPC-H specification).

The reference validates its planner against TPC-H plan fixtures
(`planner/tpch/MppTpchPlan100gTest.java`, SURVEY.md §4); here TPC-H is both the planner
test corpus and the benchmark workload (BASELINE.md configs).

The generator is a simplified dbgen: uniform distributions with the spec's value domains and
cardinality ratios (SF-scaled), deterministic per seed.  It is NOT word-for-word dbgen (no
text grammar); v_strings are drawn from small vocabularies, which keeps dictionaries compact
— representative for engine benchmarking, not for audited TPC-H publication.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

# ---------------------------------------------------------------------------
# schema (spec §1.4) — PolarB-X-flavoured partitioned DDL
# ---------------------------------------------------------------------------

TPCH_DDL = {
    "region": """
        CREATE TABLE region (
            r_regionkey INT NOT NULL PRIMARY KEY,
            r_name      VARCHAR(25) NOT NULL,
            r_comment   VARCHAR(152)
        ) BROADCAST
    """,
    "nation": """
        CREATE TABLE nation (
            n_nationkey INT NOT NULL PRIMARY KEY,
            n_name      VARCHAR(25) NOT NULL,
            n_regionkey INT NOT NULL,
            n_comment   VARCHAR(152)
        ) BROADCAST
    """,
    "supplier": """
        CREATE TABLE supplier (
            s_suppkey   INT NOT NULL PRIMARY KEY,
            s_name      VARCHAR(25) NOT NULL,
            s_address   VARCHAR(40) NOT NULL,
            s_nationkey INT NOT NULL,
            s_phone     VARCHAR(15) NOT NULL,
            s_acctbal   DECIMAL(15,2) NOT NULL,
            s_comment   VARCHAR(101) NOT NULL
        ) PARTITION BY HASH(s_suppkey) PARTITIONS 8
    """,
    "part": """
        CREATE TABLE part (
            p_partkey     INT NOT NULL PRIMARY KEY,
            p_name        VARCHAR(55) NOT NULL,
            p_mfgr        VARCHAR(25) NOT NULL,
            p_brand       VARCHAR(10) NOT NULL,
            p_type        VARCHAR(25) NOT NULL,
            p_size        INT NOT NULL,
            p_container   VARCHAR(10) NOT NULL,
            p_retailprice DECIMAL(15,2) NOT NULL,
            p_comment     VARCHAR(23) NOT NULL
        ) PARTITION BY HASH(p_partkey) PARTITIONS 8
    """,
    "partsupp": """
        CREATE TABLE partsupp (
            ps_partkey    INT NOT NULL,
            ps_suppkey    INT NOT NULL,
            ps_availqty   INT NOT NULL,
            ps_supplycost DECIMAL(15,2) NOT NULL,
            ps_comment    VARCHAR(199) NOT NULL,
            PRIMARY KEY (ps_partkey, ps_suppkey)
        ) PARTITION BY HASH(ps_partkey) PARTITIONS 8
    """,
    "customer": """
        CREATE TABLE customer (
            c_custkey    INT NOT NULL PRIMARY KEY,
            c_name       VARCHAR(25) NOT NULL,
            c_address    VARCHAR(40) NOT NULL,
            c_nationkey  INT NOT NULL,
            c_phone      VARCHAR(15) NOT NULL,
            c_acctbal    DECIMAL(15,2) NOT NULL,
            c_mktsegment VARCHAR(10) NOT NULL,
            c_comment    VARCHAR(117) NOT NULL
        ) PARTITION BY HASH(c_custkey) PARTITIONS 8
    """,
    "orders": """
        CREATE TABLE orders (
            o_orderkey      BIGINT NOT NULL PRIMARY KEY,
            o_custkey       INT NOT NULL,
            o_orderstatus   VARCHAR(1) NOT NULL,
            o_totalprice    DECIMAL(15,2) NOT NULL,
            o_orderdate     DATE NOT NULL,
            o_orderpriority VARCHAR(15) NOT NULL,
            o_clerk         VARCHAR(15) NOT NULL,
            o_shippriority  INT NOT NULL,
            o_comment       VARCHAR(79) NOT NULL
        ) PARTITION BY HASH(o_orderkey) PARTITIONS 8
    """,
    "lineitem": """
        CREATE TABLE lineitem (
            l_orderkey      BIGINT NOT NULL,
            l_partkey       INT NOT NULL,
            l_suppkey       INT NOT NULL,
            l_linenumber    INT NOT NULL,
            l_quantity      DECIMAL(15,2) NOT NULL,
            l_extendedprice DECIMAL(15,2) NOT NULL,
            l_discount      DECIMAL(15,2) NOT NULL,
            l_tax           DECIMAL(15,2) NOT NULL,
            l_returnflag    VARCHAR(1) NOT NULL,
            l_linestatus    VARCHAR(1) NOT NULL,
            l_shipdate      DATE NOT NULL,
            l_commitdate    DATE NOT NULL,
            l_receiptdate   DATE NOT NULL,
            l_shipinstruct  VARCHAR(25) NOT NULL,
            l_shipmode      VARCHAR(10) NOT NULL,
            l_comment       VARCHAR(44) NOT NULL,
            PRIMARY KEY (l_orderkey, l_linenumber)
        ) PARTITION BY HASH(l_orderkey) PARTITIONS 8
    """,
}

TABLE_ORDER = ["region", "nation", "supplier", "part", "partsupp", "customer",
               "orders", "lineitem"]

REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1), ("EGYPT", 4),
    ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3), ("INDIA", 2), ("INDONESIA", 2),
    ("IRAN", 4), ("IRAQ", 4), ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0),
    ("MOROCCO", 0), ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3), ("UNITED KINGDOM", 3),
    ("UNITED STATES", 1),
]
SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
SHIPINSTRUCT = ["DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN"]
TYPE_S1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
TYPE_S2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
TYPE_S3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
CONTAINERS1 = ["SM", "LG", "MED", "JUMBO", "WRAP"]
CONTAINERS2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]
P_NAME_WORDS = ["almond", "antique", "aquamarine", "azure", "beige", "bisque", "black",
                "blanched", "blue", "blush", "brown", "burlywood", "burnished", "chartreuse",
                "chiffon", "chocolate", "coral", "cornflower", "cornsilk", "cream", "cyan",
                "dark", "deep", "dim", "dodger", "drab", "firebrick", "floral", "forest",
                "frosted", "gainsboro", "ghost", "goldenrod", "green", "grey", "honeydew",
                "hot", "hunter", "indian", "ivory", "khaki", "lace", "lavender", "lawn",
                "lemon", "light", "lime", "linen", "magenta", "maroon", "medium", "metallic",
                "midnight", "mint", "misty", "moccasin", "navajo", "navy", "olive", "orange",
                "orchid", "pale", "papaya", "peach", "peru", "pink", "plum", "powder",
                "puff", "purple", "red", "rose", "rosy", "royal", "saddle", "salmon",
                "sandy", "seashell", "sienna", "sky", "slate", "smoke", "snow", "spring",
                "steel", "tan", "thistle", "tomato", "turquoise", "violet", "wheat",
                "white", "yellow"]

_EPOCH_1992 = 8035   # days('1992-01-01')
_ORDER_DATE_RANGE = 2406  # through 1998-08-02

_COMMENT_WORDS = np.array(["carefully", "quickly", "furiously", "slyly", "blithely",
                           "final", "special", "pending", "regular", "express", "ironic",
                           "even", "bold", "silent", "dogged", "instructions", "requests",
                           "deposits", "packages", "accounts", "foxes", "ideas", "theodolites",
                           "pinto", "beans", "platelets", "asymptotes"])


def _comments(rng: np.random.Generator, n: int) -> List[str]:
    w = _COMMENT_WORDS[rng.integers(0, len(_COMMENT_WORDS), (n, 3))]
    return [" ".join(r) for r in w]


def generate(sf: float, seed: int = 19920101) -> Dict[str, Dict[str, list]]:
    """Generate all eight tables at scale factor `sf` as column dicts of Python values."""
    rng = np.random.default_rng(seed)
    out: Dict[str, Dict[str, list]] = {}

    out["region"] = {
        "r_regionkey": list(range(5)),
        "r_name": REGIONS,
        "r_comment": _comments(rng, 5),
    }
    out["nation"] = {
        "n_nationkey": list(range(25)),
        "n_name": [n for n, _ in NATIONS],
        "n_regionkey": [r for _, r in NATIONS],
        "n_comment": _comments(rng, 25),
    }

    n_supp = max(int(10_000 * sf), 50)
    supp_keys = np.arange(1, n_supp + 1)
    out["supplier"] = {
        "s_suppkey": supp_keys.tolist(),
        "s_name": [f"Supplier#{k:09d}" for k in supp_keys],
        "s_address": [f"addr{k}" for k in supp_keys],
        "s_nationkey": rng.integers(0, 25, n_supp).tolist(),
        "s_phone": [f"{10+k%25}-{k%900+100}-{k%9000+1000}" for k in supp_keys],
        "s_acctbal": np.round(rng.uniform(-999.99, 9999.99, n_supp), 2).tolist(),
        "s_comment": _comments(rng, n_supp),
    }

    n_part = max(int(200_000 * sf), 200)
    part_keys = np.arange(1, n_part + 1)
    name_ix = rng.integers(0, len(P_NAME_WORDS), (n_part, 5))
    mfgr = rng.integers(1, 6, n_part)
    brand = mfgr * 10 + rng.integers(1, 6, n_part)
    out["part"] = {
        "p_partkey": part_keys.tolist(),
        "p_name": [" ".join(P_NAME_WORDS[j] for j in row) for row in name_ix],
        "p_mfgr": [f"Manufacturer#{m}" for m in mfgr],
        "p_brand": [f"Brand#{b}" for b in brand],
        "p_type": [f"{TYPE_S1[a]} {TYPE_S2[b]} {TYPE_S3[c]}"
                   for a, b, c in zip(rng.integers(0, 6, n_part),
                                      rng.integers(0, 5, n_part),
                                      rng.integers(0, 5, n_part))],
        "p_size": rng.integers(1, 51, n_part).tolist(),
        "p_container": [f"{CONTAINERS1[a]} {CONTAINERS2[b]}"
                        for a, b in zip(rng.integers(0, 5, n_part),
                                        rng.integers(0, 8, n_part))],
        "p_retailprice": np.round(
            900 + (part_keys % 1000) / 10 + 100 * (part_keys % 10), 2).tolist(),
        "p_comment": _comments(rng, n_part),
    }

    n_ps = n_part * 4
    ps_part = np.repeat(part_keys, 4)
    ps_supp = np.zeros(n_ps, dtype=np.int64)
    for j in range(4):
        ps_supp[j::4] = (ps_part[j::4] + (j * (n_supp // 4 + (ps_part[j::4] - 1)
                                               % (n_supp // 4)))) % n_supp + 1
    out["partsupp"] = {
        "ps_partkey": ps_part.tolist(),
        "ps_suppkey": ps_supp.tolist(),
        "ps_availqty": rng.integers(1, 10_000, n_ps).tolist(),
        "ps_supplycost": np.round(rng.uniform(1.0, 1000.0, n_ps), 2).tolist(),
        "ps_comment": _comments(rng, n_ps),
    }

    n_cust = max(int(150_000 * sf), 150)
    cust_keys = np.arange(1, n_cust + 1)
    out["customer"] = {
        "c_custkey": cust_keys.tolist(),
        "c_name": [f"Customer#{k:09d}" for k in cust_keys],
        "c_address": [f"addr{k}" for k in cust_keys],
        "c_nationkey": rng.integers(0, 25, n_cust).tolist(),
        "c_phone": [f"{10+k%25}-{k%900+100}-{k%9000+1000}" for k in cust_keys],
        "c_acctbal": np.round(rng.uniform(-999.99, 9999.99, n_cust), 2).tolist(),
        "c_mktsegment": [SEGMENTS[i] for i in rng.integers(0, 5, n_cust)],
        "c_comment": _comments(rng, n_cust),
    }

    n_ord = n_cust * 10
    ord_keys = np.arange(1, n_ord + 1) * 4 - 3  # sparse keys like dbgen
    o_date = _EPOCH_1992 + rng.integers(0, _ORDER_DATE_RANGE, n_ord)
    # only ~2/3 of customers have orders (spec): map to custkey % 3 != 0
    o_cust = rng.integers(1, n_cust + 1, n_ord)
    o_cust = o_cust - (o_cust % 3 == 0)
    o_cust = np.where(o_cust == 0, 1, o_cust)
    out["orders"] = {
        "o_orderkey": ord_keys.tolist(),
        "o_custkey": o_cust.tolist(),
        "o_orderstatus": ["F"] * n_ord,  # fixed after lineitem below
        "o_totalprice": np.zeros(n_ord).tolist(),
        "o_orderdate": o_date.tolist(),
        "o_orderpriority": [PRIORITIES[i] for i in rng.integers(0, 5, n_ord)],
        "o_clerk": [f"Clerk#{i:09d}" for i in rng.integers(1, max(int(sf * 1000), 10),
                                                           n_ord)],
        "o_shippriority": [0] * n_ord,
        "o_comment": _comments(rng, n_ord),
    }

    # lineitem: 1-7 lines per order
    lines_per = rng.integers(1, 8, n_ord)
    n_li = int(lines_per.sum())
    li_order = np.repeat(ord_keys, lines_per)
    li_odate = np.repeat(o_date, lines_per)
    li_lineno = np.concatenate([np.arange(1, c + 1) for c in lines_per])
    l_part = rng.integers(1, n_part + 1, n_li)
    l_supp = ((l_part + rng.integers(0, 4, n_li) * (n_supp // 4 + 1)) % n_supp) + 1
    qty = rng.integers(1, 51, n_li)
    retail = 900 + (l_part % 1000) / 10 + 100 * (l_part % 10)
    eprice = np.round(qty * retail, 2)
    ship = li_odate + rng.integers(1, 122, n_li)
    commit = li_odate + rng.integers(30, 91, n_li)
    receipt = ship + rng.integers(1, 31, n_li)
    today = _EPOCH_1992 + 1839  # 1995-06-17 per spec currentdate
    rflag = np.where(receipt <= today,
                     np.where(rng.random(n_li) < 0.5, "R", "A"), "N")
    lstatus = np.where(ship > today, "O", "F")
    out["lineitem"] = {
        "l_orderkey": li_order.tolist(),
        "l_partkey": l_part.tolist(),
        "l_suppkey": l_supp.tolist(),
        "l_linenumber": li_lineno.tolist(),
        "l_quantity": qty.astype(float).tolist(),
        "l_extendedprice": eprice.tolist(),
        "l_discount": np.round(rng.integers(0, 11, n_li) / 100, 2).tolist(),
        "l_tax": np.round(rng.integers(0, 9, n_li) / 100, 2).tolist(),
        "l_returnflag": rflag.tolist(),
        "l_linestatus": lstatus.tolist(),
        "l_shipdate": ship.tolist(),
        "l_commitdate": commit.tolist(),
        "l_receiptdate": receipt.tolist(),
        "l_shipinstruct": [SHIPINSTRUCT[i] for i in rng.integers(0, 4, n_li)],
        "l_shipmode": [SHIPMODES[i] for i in rng.integers(0, 7, n_li)],
        "l_comment": _comments(rng, n_li),
    }

    # orders.o_orderstatus consistency: F if all lines F, O if all O, else P
    import collections
    status_by_order: Dict[int, set] = collections.defaultdict(set)
    for k, s in zip(li_order.tolist(), lstatus.tolist()):
        status_by_order[k].add(s)
    o_status = []
    totals = collections.defaultdict(float)
    for k, p in zip(li_order.tolist(), eprice.tolist()):
        totals[k] += p
    for k in ord_keys.tolist():
        st = status_by_order.get(k)
        if not st:
            o_status.append("O")
        elif st == {"F"}:
            o_status.append("F")
        elif st == {"O"}:
            o_status.append("O")
        else:
            o_status.append("P")
    out["orders"]["o_orderstatus"] = o_status
    out["orders"]["o_totalprice"] = [round(totals.get(k, 0.0), 2)
                                     for k in ord_keys.tolist()]
    return out
