"""Columnar HTAP replica: a CDC-fed delta+base tier serving AP scans.

Reference analog: PolarDB-X's columnar index / IMCI-style HTAP replica
(PAPER.md §HTAP) — a continuously-maintained column store fed from the global
binlog, snapshot-consistent at a TSO watermark, serving analytical scans while
TP stays on the row store.  The pieces here:

- **Tailer** (`ColumnarReplicaManager.tail_once` + a lazy poll thread, the
  `txn/async_apply.py` shape): drains `txn/cdc.py`'s commit-TSO-ordered
  stream per enrolled table.  Inserts land in an in-memory columnar *delta*
  (per-event chunks in lane domain); deletes stamp `end_ts` through a PK
  multiset map — the replica mirrors the row store's MVCC lanes exactly, so
  a read at watermark W is *bit-identical* to a row-store read at W.
- **Base stripes**: compaction folds the delta into immutable, pre-padded
  stripes with per-column zone maps (`storage/zonemap.py`, shared with the
  TTL parquet archive) used for SARG stripe pruning.  Stripe lanes keep the
  live table's dictionary codes, so decoded batches drop straight into the
  fused pipeline next to row-store batches.
- **Watermark protocol**: seeding scans the row store at a *lagged*
  `ts0 = now − margin` (commits at or below ts0 have their lane stamps
  landed) and starts the tail cursor at the last binlog event with
  `commit_ts <= ts0` — commits inside the margin window are invisible at
  ts0, so their events replay; events with `commit_ts <= ts0` are skipped
  (covered by the seed).  The watermark only ever advances to
  `t_head − margin` after a drain that reached the binlog head, where
  `t_head` was fetched before the drain — the same "binlog writes trail row
  visibility by less than the margin" assumption the rebalance verifier
  (`REBALANCE_VERIFY_LAG_MS`) already relies on.  Never to the last applied
  commit_ts: a concurrent commit with a smaller TSO may not have reached
  the binlog yet.

Concurrency: one manager lock (lockdep class "columnar", rank 0) serializes
tailer operations — seed, apply, compact, persist.  The QUERY path takes no
lock at all: routing snapshots `replica.tier` (an immutable (stripes, delta)
tuple replaced wholesale by writers) plus the watermark into a `ReplicaView`,
so a compaction mid-query can never mix tiers.  Compaction only drops dead
rows below the MINIMUM watermark across replicas — a multi-table query routes
at `min(W_v)`, so no future view can need them.

Escape hatches (the standard trio): `COLUMNAR(OFF|ON)` statement hint,
`ENABLE_COLUMNAR_REPLICA` param (default off), `GALAXYSQL_COLUMNAR=0` env.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from galaxysql_tpu.meta.tso import LOGICAL_BITS
from galaxysql_tpu.storage.table_store import INFINITY_TS
from galaxysql_tpu.storage.zonemap import lane_minmax, sargs_refuted
from galaxysql_tpu.utils import errors

# environment escape hatch (trio leg 3): kills routing AND tailing wholesale
ENABLED = os.environ.get("GALAXYSQL_COLUMNAR", "1") != "0"

SEEDING = "SEEDING"
READY = "READY"
RESEED = "RESEED"


# -- RLE (persistence encoding) ---------------------------------------------

def rle_encode(arr: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """(run values, run lengths).  begin_ts/end_ts lanes are near-constant
    per stripe (one commit stamps many rows), so runs collapse them to a
    handful of entries on disk."""
    if arr.size == 0:
        return arr, np.zeros(0, dtype=np.int64)
    starts = np.concatenate([[0], np.nonzero(np.diff(arr))[0] + 1])
    lengths = np.diff(np.concatenate([starts, [arr.size]]))
    return arr[starts], lengths.astype(np.int64)


def rle_decode(values: np.ndarray, lengths: np.ndarray) -> np.ndarray:
    return np.repeat(values, lengths)


def _save_lane(arrays: Dict[str, np.ndarray], name: str, arr: np.ndarray):
    """Store `arr` under `name`, RLE-encoded when the runs actually pay."""
    vals, lens = rle_encode(arr)
    if vals.size * 2 < arr.size:
        arrays[f"rv::{name}"] = vals
        arrays[f"rn::{name}"] = lens
    else:
        arrays[name] = arr


def _load_lane(z, name: str) -> Optional[np.ndarray]:
    if name in z:
        return z[name]
    if f"rv::{name}" in z:
        return rle_decode(z[f"rv::{name}"], z[f"rn::{name}"])
    return None


# -- tiers -------------------------------------------------------------------

class Stripe:
    """Immutable columnar slab, pre-padded to a power-of-two bucket so every
    query reuses one compiled kernel shape (and one device-cache entry —
    stripe lanes never change, which is the whole point vs. re-concatenating
    the row store per version bump).  `end_ts` is the one mutable side array:
    delete events stamp it; `has_deletes` retires the static fast path."""

    __slots__ = ("uid", "lanes", "valid", "begin_ts", "end_ts", "num_rows",
                 "cap", "zmap", "max_begin", "has_deletes", "_pad_live")

    def __init__(self, uid: int, lanes, valid, begin_ts, end_ts,
                 num_rows: int, cap: int, zmap):
        self.uid = uid
        self.lanes = lanes          # col -> np lane, length cap
        self.valid = valid          # col -> np bool lane or None (all valid)
        self.begin_ts = begin_ts    # length cap; padding rows are dead
        self.end_ts = end_ts        # length cap; padding gets end_ts=0
        self.num_rows = num_rows
        self.cap = cap
        self.zmap = zmap            # col -> (lo, hi), numeric lanes only
        self.max_begin = int(begin_ts[:num_rows].max()) if num_rows else 0
        self.has_deletes = bool(
            (end_ts[:num_rows] != INFINITY_TS).any()) if num_rows else False
        self._pad_live = None if cap == num_rows else \
            (np.arange(cap) < num_rows)

    def live_mask(self, w: int):
        """MVCC visibility at watermark `w` — the numpy twin of
        native.visible_mask for rows that are never provisional (the tailer
        only ever applies committed stamps)."""
        if not self.has_deletes and self.max_begin <= w:
            return self._pad_live  # None = all rows live
        m = (self.begin_ts <= w) & (self.end_ts > w)
        return m


class _DeltaChunk:
    """One insert event's rows, unpadded: the scan path concatenates all
    chunks into a single padded batch, so sustained small-row DML costs one
    extra batch per query, not one per event."""

    __slots__ = ("lanes", "valid", "begin_ts", "end_ts")

    def __init__(self, lanes, valid, begin_ts, end_ts):
        self.lanes = lanes
        self.valid = valid
        self.begin_ts = begin_ts
        self.end_ts = end_ts


class ReplicaView:
    """Lock-free query-time snapshot: (stripes, delta) tuple + watermark
    captured once at routing.  Consistent by construction — writers replace
    `replica.tier` wholesale, never mutate it."""

    __slots__ = ("replica", "stripes", "delta", "watermark", "seed_ts",
                 "events", "max_applied_ts")

    def __init__(self, replica, stripes, delta, watermark, seed_ts,
                 events, max_applied_ts):
        self.replica = replica
        self.stripes = stripes
        self.delta = delta
        self.watermark = watermark
        self.seed_ts = seed_ts
        # content generation for the fragment cache: (seed_ts, events)
        # changes exactly when the visible set can change, and
        # max_applied_ts bounds the commit range the tier carries — any
        # watermark at or above it sees the identical visible set, so
        # cached artifacts stay valid across idle watermark advances
        self.events = events
        self.max_applied_ts = max_applied_ts


class TableReplica:
    """Per-table replica state.  All mutation happens under the manager lock;
    `tier`, `watermark`, `state` are read lock-free by the router."""

    def __init__(self, key: str):
        self.key = key
        self.state = SEEDING
        self.sig: Tuple[str, ...] = ()
        self.tier: Tuple[tuple, tuple] = ((), ())   # (stripes, delta chunks)
        self.delta_rows = 0
        self.watermark = 0       # replica is exact for any ts in
        self.seed_ts = 0         # [seed_ts, watermark]
        self.seq = 0             # last binlog seq consumed
        self.pk = None           # lazy: match-key -> [[obj, row], ...]
        self.max_applied_ts = 0  # highest commit_ts stamped into the tier
        self.snap = None         # published consistent view tuple (below)
        self.compactions = 0
        self.reseeds = 0
        self.pruned_stripes = 0
        self.applied_events = 0
        self.applied_rows = 0

    def lag_ms(self) -> float:
        if self.watermark <= 0:
            return -1.0
        return max(time.time() * 1000.0 - (self.watermark >> LOGICAL_BITS),
                   0.0)

    def publish(self):
        """Tailer-side: expose the current tier/watermark/generation as ONE
        tuple swap.  Queries snapshot it with a single attribute read, so a
        view can never pair a drained watermark with a pre-drain tier (or a
        stale generation with a fresh tier).  In-place end_ts stamps applied
        after a publish are benign: their commit_ts exceeds every already-
        published watermark (the margin invariant), so they are invisible at
        any watermark a live view can carry."""
        stripes, delta = self.tier
        self.snap = (stripes, delta, self.watermark, self.seed_ts,
                     self.applied_events, self.max_applied_ts)

    def view(self) -> Optional[ReplicaView]:
        snap = self.snap  # one read: atomic vs. the tailer's publish()
        if self.state != READY or snap is None:
            return None
        return ReplicaView(self, *snap)


# -- scan --------------------------------------------------------------------

def scan_view(view: ReplicaView, tm, columns: List[str], sargs=None,
              manager=None):
    """Yield padded ColumnBatches for `columns` at the view's watermark,
    zone-map-pruning stripes the SARGs refute.  Lock-free: operates purely on
    the snapshot."""
    from galaxysql_tpu.chunk.batch import Column, ColumnBatch
    from galaxysql_tpu.exec.operators import bucket_capacity
    w = view.watermark
    sargs = sargs or []
    for s in view.stripes:
        if sargs and sargs_refuted(s.zmap, sargs):
            view.replica.pruned_stripes += 1
            if manager is not None:
                manager.pruned.inc()
            continue
        cols = {}
        for c in columns:
            cm = tm.column(c)
            cols[c] = Column(s.lanes[c], s.valid[c], cm.dtype,
                             tm.dictionaries.get(c.lower()))
        yield ColumnBatch(cols, s.live_mask(w))
    if not view.delta:
        return
    chunks = view.delta
    n = sum(ch.begin_ts.shape[0] for ch in chunks)
    if n == 0:
        return
    cap = bucket_capacity(n)
    begin = np.concatenate([ch.begin_ts for ch in chunks])
    end = np.concatenate([ch.end_ts for ch in chunks])
    live = (begin <= w) & (end > w)
    if cap != n:
        live = np.concatenate([live, np.zeros(cap - n, dtype=np.bool_)])
    cols = {}
    for c in columns:
        cm = tm.column(c)
        lane = np.concatenate([ch.lanes[c] for ch in chunks])
        if cap != n:
            lane = np.concatenate(
                [lane, np.zeros(cap - n, dtype=lane.dtype)])
        valid = None
        if any(ch.valid.get(c) is not None for ch in chunks):
            valid = np.concatenate(
                [ch.valid[c] if ch.valid.get(c) is not None else
                 np.ones(ch.begin_ts.shape[0], dtype=np.bool_)
                 for ch in chunks])
            if cap != n:
                valid = np.concatenate(
                    [valid, np.zeros(cap - n, dtype=np.bool_)])
        cols[c] = Column(lane, valid, cm.dtype,
                         tm.dictionaries.get(c.lower()))
    yield ColumnBatch(cols, live)


# -- the manager -------------------------------------------------------------

class ColumnarReplicaManager:
    """Owns every table replica plus the tailer thread (`instance.columnar`).

    Lock discipline: `self._lock` (lockdep class "columnar") is TAILER-ONLY —
    held across seed/apply/compact/persist, and ordered BEFORE partition and
    metadb locks (seeding scans partitions, draining queries the binlog).
    Nothing acquires it under those, and the query path never takes it."""

    IDLE_WAIT_S = 0.5

    def __init__(self, instance):
        self.instance = instance
        self.replicas: Dict[str, TableReplica] = {}
        from galaxysql_tpu.utils.lockdep import named_lock
        self._lock = named_lock("columnar")
        self._cond = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self._next_uid = 0
        m = instance.metrics
        self.events_applied = m.counter(
            "columnar_events_applied", "binlog events applied to replicas")
        self.rows_applied = m.counter(
            "columnar_rows_applied", "rows applied to columnar replicas")
        self.compactions = m.counter(
            "columnar_compactions", "delta->base stripe compactions")
        self.pruned = m.counter(
            "columnar_pruned_stripes", "stripes skipped by zone-map SARGs")
        self.routed = m.counter(
            "columnar_routed_queries", "queries served by the columnar replica")
        self.reseed_count = m.counter(
            "columnar_reseeds", "replica reseeds (DDL mid-tail / delete miss)")
        self.lag_gauge = m.gauge(
            "columnar_lag_ms", "max replica watermark lag (ms)")
        self.delta_gauge = m.gauge(
            "columnar_delta_rows", "total uncompacted delta rows")

    # -- enrollment -----------------------------------------------------------

    def enabled(self, session=None) -> bool:
        if not ENABLED:
            return False
        v = self.instance.config.get(
            "ENABLE_COLUMNAR_REPLICA", session.vars if session else None)
        return bool(v)

    def replica(self, schema: str, table: str) -> Optional[TableReplica]:
        return self.replicas.get(self.instance.store_key(schema, table))

    def request(self, schema: str, table: str) -> TableReplica:
        """Async enroll: register the table (SEEDING) and wake the tailer.
        Routing keeps using the row store until the replica turns READY."""
        key = self.instance.store_key(schema, table)
        with self._lock:
            rep = self.replicas.get(key)
            if rep is None:
                rep = TableReplica(key)
                self.replicas[key] = rep
        self._start_thread()
        with self._cond:
            self._cond.notify_all()
        return rep

    def ensure_ready(self, schema: str, table: str,
                     timeout_s: float = 30.0) -> TableReplica:
        """Synchronous enroll + seed + drain (COLUMNAR(ON) hint, tests)."""
        rep = self.request(schema, table)
        deadline = time.time() + timeout_s
        while rep.state != READY:
            self.tail_once()
            if rep.state != READY and time.time() > deadline:
                raise errors.TddlError(
                    f"columnar replica {rep.key} did not become READY "
                    f"within {timeout_s}s (state={rep.state})")
        return rep

    def drop(self, schema: str, table: str):
        with self._lock:
            self.replicas.pop(self.instance.store_key(schema, table), None)

    # -- tailer ---------------------------------------------------------------

    def _start_thread(self):
        poll_ms = self.instance.config.get("COLUMNAR_POLL_MS")
        if poll_ms is None or float(poll_ms) <= 0:
            return  # synchronous mode (tests drive tail_once directly)
        if self._thread is None or not self._thread.is_alive():
            self._stop = False
            self._thread = threading.Thread(
                target=self._run, name="columnar-tailer", daemon=True)
            self._thread.start()

    def _run(self):
        from galaxysql_tpu.utils import events
        while not self._stop:
            poll = float(self.instance.config.get("COLUMNAR_POLL_MS") or 50)
            with self._cond:
                self._cond.wait(min(poll / 1000.0, self.IDLE_WAIT_S))
            if self._stop:
                return
            try:
                self.tail_once()
            except Exception as e:
                # background plane: a tail fault is published as an error
                # event and retried next poll; dying silently would freeze
                # the watermark
                events.publish(  # galaxylint: disable=event-uncorrelated -- background tailer cycle: no query trace or statement digest exists; the flight recorder implicates via replica state
                    "columnar_tail_failed",
                    f"columnar tailer cycle failed: {e}",
                    severity="error", node=self.instance.node_id,
                    error=f"{type(e).__name__}")
                time.sleep(self.IDLE_WAIT_S)

    def shutdown(self):
        self._stop = True
        with self._cond:
            self._cond.notify_all()

    def margin(self) -> int:
        lag_ms = int(self.instance.config.get("COLUMNAR_WATERMARK_LAG_MS")
                     or 100)
        return lag_ms << LOGICAL_BITS

    def tail_once(self) -> int:
        """One synchronous tail cycle: seed/reseed pending replicas, drain
        the binlog into READY ones, advance watermarks, compact.  Returns the
        number of events applied."""
        if not ENABLED:
            return 0
        applied = 0
        with self._lock:
            for key, rep in list(self.replicas.items()):
                if rep.state in (SEEDING, RESEED):
                    self._seed(rep)
            for rep in self.replicas.values():
                if rep.state == READY:
                    applied += self._drain(rep)
            for rep in self.replicas.values():
                if rep.state == READY:
                    self._maybe_compact(rep)
            self._update_gauges()
        return applied

    def _meta(self, rep: TableReplica):
        schema, table = rep.key.split(".", 1)
        try:
            tm = self.instance.catalog.table(schema, table)
        except Exception:
            tm = None
        store = self.instance.stores.get(rep.key)
        return tm, store

    def _seed(self, rep: TableReplica):
        """Snapshot the row store into base stripes.  Protocol: scan at a
        *lagged* ts0 = now − margin (every commit at or below ts0 has its
        lane stamps landed — the margin absorbs stamps trailing their TSO
        fetch), and start the tail cursor at the LAST binlog event with
        commit_ts <= ts0, not the head: commits inside the margin window are
        invisible at ts0, so their events must replay.  The binlog is
        commit-TSO-ordered (one write lock, stamp-then-publish), so every
        event past the cursor has commit_ts > ts0; the `cts <= seed_ts`
        drain skip then covers events published late for seeded commits."""
        inst = self.instance
        tm, store = self._meta(rep)
        if tm is None or store is None:
            self.replicas.pop(rep.key, None)  # table dropped mid-enrollment
            return
        ts0 = max(inst.tso.next_timestamp() - self.margin(), 1)
        row = inst.metadb.query(
            "SELECT COALESCE(MAX(seq), 0) FROM binlog_events "
            "WHERE commit_ts <= ?", (ts0,))
        s0 = int(row[0][0]) if row else 0
        cols = tm.column_names()
        parts_data = []
        for p in store.partitions:
            if p.num_rows == 0:
                continue
            with p.lock:
                ids = np.nonzero(p.visible_mask(ts0))[0]
                if ids.size == 0:
                    continue
                lanes = {c: p.lanes[c][ids] for c in cols}
                valid = {c: p.valid[c][ids].copy() for c in cols}
                begin = p.begin_ts[ids].copy()
            parts_data.append((lanes, valid, begin))
        ckey = self._cluster_key(rep, tm)
        if ckey is None:
            stripes = [self._make_stripe(
                tm, lanes, valid, begin,
                np.full(begin.shape[0], INFINITY_TS, dtype=np.int64))
                for lanes, valid, begin in parts_data]
        else:
            stripes = self._clustered_stripes(tm, cols, ckey, parts_data)
        if rep.state == RESEED:
            rep.reseeds += 1
            self.reseed_count.inc()
        rep.sig = tuple(cols)
        rep.tier = (tuple(stripes), ())
        rep.delta_rows = 0
        rep.pk = None
        rep.seq = s0
        rep.seed_ts = ts0
        rep.watermark = ts0
        rep.max_applied_ts = ts0
        rep.state = READY
        rep.publish()

    def _cluster_key(self, rep: TableReplica, tm) -> Optional[str]:
        """Resolve COLUMNAR_CLUSTER_BY ('table:column,...') for this
        replica's table; None when unconfigured or the column is unknown."""
        spec = str(self.instance.config.get("COLUMNAR_CLUSTER_BY") or "")
        if not spec:
            return None
        table = rep.key.split(".", 1)[1]
        for part in spec.split(","):
            if ":" not in part:
                continue
            t, c = part.split(":", 1)
            if t.strip().lower().split(".")[-1] != table:
                continue
            c = c.strip().lower()
            for cn in tm.column_names():
                if cn.lower() == c:
                    return cn
        return None

    def _clustered_stripes(self, tm, cols, ckey, parts_data) -> list:
        """Globally sort the seed snapshot on the cluster column and slice it
        into compaction-threshold stripes: consecutive stripes then cover
        disjoint key ranges, so the per-stripe zone maps turn range SARGs
        into whole-stripe prunes instead of per-row filter work.  Delta
        compactions keep arrival order — clustering is a seed-time layout."""
        if not parts_data:
            return []
        lanes = {c: np.concatenate([pl[c] for pl, _, _ in parts_data])
                 for c in cols}
        valid = {c: np.concatenate([pv[c] for _, pv, _ in parts_data])
                 for c in cols}
        begin = np.concatenate([b for _, _, b in parts_data])
        order = np.argsort(lanes[ckey], kind="stable")
        lanes = {c: a[order] for c, a in lanes.items()}
        valid = {c: a[order] for c, a in valid.items()}
        begin = begin[order]
        threshold = int(self.instance.config.get("COLUMNAR_COMPACT_ROWS")
                        or 65536)
        stripes = []
        for lo in range(0, int(begin.shape[0]), threshold):
            hi = min(lo + threshold, int(begin.shape[0]))
            stripes.append(self._make_stripe(
                tm, {c: a[lo:hi] for c, a in lanes.items()},
                {c: a[lo:hi] for c, a in valid.items()}, begin[lo:hi],
                np.full(hi - lo, INFINITY_TS, dtype=np.int64)))
        return stripes

    def _make_stripe(self, tm, lanes, valid, begin, end) -> Stripe:
        from galaxysql_tpu.exec.operators import bucket_capacity
        n = int(begin.shape[0])
        cap = bucket_capacity(max(n, 1))

        def pad(arr, fill=0):
            if arr.shape[0] == cap:
                return arr
            return np.concatenate(
                [arr, np.full(cap - arr.shape[0], fill, dtype=arr.dtype)])

        zmap = {}
        out_lanes, out_valid = {}, {}
        for c, lane in lanes.items():
            v = valid.get(c)
            all_valid = v is None or bool(v.all())
            if not tm.column(c).dtype.is_string:
                # dictionary codes carry no order: a code-lane zone map would
                # wrongly refute range sargs, so string lanes get no stats
                mm = lane_minmax(lane[:n], None if all_valid else v[:n])
                if mm is not None:
                    zmap[c] = mm
            out_lanes[c] = pad(lane)
            out_valid[c] = None if all_valid else pad(v, False)
        uid = self._next_uid
        self._next_uid += 1
        # padding rows: end_ts=0 keeps them dead at every watermark
        return Stripe(uid, out_lanes, out_valid, pad(begin),
                      pad(end, 0), n, cap, zmap)

    def _drain(self, rep: TableReplica) -> int:
        """Page this replica's events from the binlog; advance the watermark
        only when the drain reached the head (see module docstring)."""
        inst = self.instance
        tm, store = self._meta(rep)
        if tm is None or store is None:
            self.replicas.pop(rep.key, None)
            return 0
        if tuple(tm.column_names()) != rep.sig:
            rep.state = RESEED  # DDL landed: delta lanes no longer line up
            rep.snap = None
            return 0
        t_head = inst.tso.next_timestamp()
        applied = 0
        reached_head = False
        while True:
            evs = inst.cdc.events_after_seq(rep.seq, limit=5000)
            for seq, cts, schema, table, kind, payload in evs:
                rep.seq = seq
                if f"{schema}.{table}" != rep.key:
                    continue
                if cts <= rep.seed_ts:
                    continue  # covered by the seed snapshot
                d = json.loads(payload)
                if tuple(d["columns"]) != rep.sig:
                    # DDL mid-tail: this event predates/postdates our lane
                    # layout.  Reseed — the fresh seed's ts0 exceeds every
                    # stale commit_ts, so skipping the rest stays sound.
                    rep.state = RESEED
                    rep.snap = None
                    return applied
                if kind == "insert":
                    self._apply_insert(rep, tm, d, cts)
                elif kind == "delete":
                    if not self._apply_delete(rep, tm, d, cts):
                        rep.state = RESEED  # unmatched image: self-heal
                        rep.snap = None
                        return applied
                else:
                    raise errors.TddlError(
                        f"unknown binlog event kind {kind!r}")
                applied += 1
                rep.applied_events += 1
                rep.max_applied_ts = max(rep.max_applied_ts, cts)
                rep.applied_rows += len(d["rows"])
                self.events_applied.inc()
                self.rows_applied.inc(len(d["rows"]))
            if len(evs) < 5000:
                reached_head = True
                break
        if reached_head:
            rep.watermark = max(rep.watermark, t_head - self.margin())
        if applied or reached_head:
            rep.publish()
        return applied

    def _apply_insert(self, rep: TableReplica, tm, d: dict, cts: int):
        from galaxysql_tpu.chunk.batch import column_from_pylist
        cols = d["columns"]
        rows = d["rows"]
        n = len(rows)
        if n == 0:
            return
        lanes, valid = {}, {}
        for i, c in enumerate(cols):
            cm = tm.column(c)
            col = column_from_pylist([r[i] for r in rows], cm.dtype,
                                     tm.dictionaries.get(c.lower()))
            lanes[c] = col.np_data()
            valid[c] = None if col.valid is None else col.np_valid()
        chunk = _DeltaChunk(lanes, valid,
                            np.full(n, cts, dtype=np.int64),
                            np.full(n, INFINITY_TS, dtype=np.int64))
        stripes, delta = rep.tier
        rep.tier = (stripes, delta + (chunk,))
        rep.delta_rows += n
        if rep.pk is not None:
            match_cols = tm.primary_key or cols
            ix = {c: i for i, c in enumerate(cols)}
            for ri, r in enumerate(rows):
                key = tuple(str(r[ix[c]]) for c in match_cols)
                rep.pk.setdefault(key, []).append([chunk, ri])

    def _apply_delete(self, rep: TableReplica, tm, d: dict,
                      cts: int) -> bool:
        """Stamp end_ts on the rows matching the event's images — a multiset
        pop (one live ref per event row), which mirrors the row store: the
        event rows ARE the rows the row store deleted, and identical images
        are indistinguishable.  False = an image had no live match (the
        replica diverged; caller reseeds)."""
        if rep.pk is None:
            rep.pk = self._build_pk(rep, tm)
        cols = d["columns"]
        match_cols = tm.primary_key or cols
        ix = {c: i for i, c in enumerate(cols)}
        for r in d["rows"]:
            key = tuple(str(r[ix[c]]) for c in match_cols)
            refs = rep.pk.get(key)
            hit = None
            while refs:
                obj, row = refs[0]
                if obj.end_ts[row] == INFINITY_TS:
                    hit = (obj, row)
                    break
                refs.pop(0)  # already dead: retire the stale ref
            if hit is None:
                return False
            obj, row = hit
            refs.pop(0)
            if not refs:
                rep.pk.pop(key, None)
            obj.end_ts[row] = cts
            if isinstance(obj, Stripe):
                obj.has_deletes = True
        return True

    def _build_pk(self, rep: TableReplica, tm) -> Dict[tuple, list]:
        """Match-key map over every LIVE row in the current tier.  Built
        lazily on the first delete — insert-only tables (the AP common case)
        never pay the python-domain decode."""
        from galaxysql_tpu.chunk.batch import Column
        from galaxysql_tpu.types import datatype as dt
        match_cols = tm.primary_key or list(tm.column_names())
        nonint = (dt.TypeClass.DECIMAL, dt.TypeClass.DATE,
                  dt.TypeClass.DATETIME, dt.TypeClass.FLOAT,
                  dt.TypeClass.BOOL)
        pk: Dict[tuple, list] = {}
        stripes, delta = rep.tier
        for obj in list(stripes) + list(delta):
            n = obj.num_rows if isinstance(obj, Stripe) else \
                obj.begin_ts.shape[0]
            if n == 0:
                continue
            keys = []
            for c in match_cols:
                cm = tm.column(c)
                lane = obj.lanes[c][:n]
                v = obj.valid.get(c)
                if v is None and not cm.dtype.is_string and \
                        cm.dtype.clazz not in nonint and \
                        lane.dtype.kind in "iu":
                    # integer pk lane, no NULLs: astype('U') renders the
                    # same decimal strings str(int(x)) would, without the
                    # per-element to_pylist loop (the common-case pk map
                    # over a million-row table must not stall the tailer)
                    keys.append(lane.astype("U21").tolist())
                    continue
                col = Column(lane, None if v is None else v[:n], cm.dtype,
                             tm.dictionaries.get(c.lower()))
                keys.append([str(x) for x in col.to_pylist()])
            end = obj.end_ts
            live = np.nonzero(end[:n] == INFINITY_TS)[0]
            tups = list(zip(*keys))
            for i in live.tolist():
                pk.setdefault(tups[i], []).append([obj, i])
        return pk

    def _min_watermark(self) -> int:
        ws = [r.watermark for r in self.replicas.values()
              if r.state == READY and r.watermark > 0]
        return min(ws) if ws else 0

    def _maybe_compact(self, rep: TableReplica):
        """Fold the delta into a new base stripe once it crosses the
        threshold.  Dead rows are dropped only below the MINIMUM watermark
        across replicas: multi-table queries route at min(W_v), and views
        hold tier snapshots, so no reader can need a dropped row."""
        threshold = int(self.instance.config.get("COLUMNAR_COMPACT_ROWS")
                        or 65536)
        if rep.delta_rows < threshold:
            return
        tm, _store = self._meta(rep)
        if tm is None:
            return
        stripes, delta = rep.tier
        if not delta:
            return
        horizon = self._min_watermark()
        begin = np.concatenate([ch.begin_ts for ch in delta])
        end = np.concatenate([ch.end_ts for ch in delta])
        keep = end > horizon
        lanes, valid = {}, {}
        for c in rep.sig:
            lane = np.concatenate([ch.lanes[c] for ch in delta])[keep]
            lanes[c] = lane
            if any(ch.valid.get(c) is not None for ch in delta):
                valid[c] = np.concatenate(
                    [ch.valid[c] if ch.valid.get(c) is not None else
                     np.ones(ch.begin_ts.shape[0], dtype=np.bool_)
                     for ch in delta])[keep]
            else:
                valid[c] = None
        stripe = self._make_stripe(tm, lanes, valid, begin[keep], end[keep])
        rep.tier = (stripes + (stripe,), ())
        rep.delta_rows = 0
        rep.pk = None  # refs point at retired chunks; rebuilt lazily
        rep.compactions += 1
        self.compactions.inc()
        # compaction preserves the visible set above the horizon, so the
        # generation (applied_events) deliberately does NOT move: cached
        # scan artifacts stay valid across the tier swap
        rep.publish()

    def _update_gauges(self):
        lag = 0.0
        delta = 0
        for rep in self.replicas.values():
            if rep.state == READY:
                lag = max(lag, rep.lag_ms())
                delta += rep.delta_rows
        self.lag_gauge.set(round(lag, 3))
        self.delta_gauge.set(float(delta))

    # -- surfaces -------------------------------------------------------------

    def rows(self) -> List[tuple]:
        """SHOW COLUMNAR REPLICA / information_schema.columnar_replica rows:
        (table, state, watermark, lag_ms, delta_rows, base_stripes,
        compactions, reseeds, pruned_stripes, applied_events, applied_rows)."""
        out = []
        for key in sorted(self.replicas):
            rep = self.replicas[key]
            stripes, _delta = rep.tier
            out.append((key, rep.state, rep.watermark,
                        round(rep.lag_ms(), 3), rep.delta_rows,
                        len(stripes), rep.compactions, rep.reseeds,
                        rep.pruned_stripes, rep.applied_events,
                        rep.applied_rows))
        return out

    # -- persistence ----------------------------------------------------------

    def save(self):
        """Checkpoint READY replicas: stripes + delta as npz (RLE-encoded
        lanes where runs pay) under data_dir/columnar, watermark/seq/sig in
        the metadb kv — a restarted tailer resumes from the persisted seq."""
        data_dir = self.instance.data_dir
        if not data_dir:
            return
        with self._lock:
            for key, rep in self.replicas.items():
                if rep.state != READY:
                    continue
                d = os.path.join(data_dir, "columnar",
                                 key.replace(".", os.sep))
                os.makedirs(d, exist_ok=True)
                for f in os.listdir(d):
                    if f.endswith(".npz"):
                        os.remove(os.path.join(d, f))
                stripes, delta = rep.tier
                for i, s in enumerate(stripes):
                    arrays: Dict[str, np.ndarray] = {}
                    n = s.num_rows
                    for c, lane in s.lanes.items():
                        _save_lane(arrays, f"lane__{c}", lane[:n])
                        if s.valid[c] is not None:
                            arrays[f"valid__{c}"] = s.valid[c][:n]
                    _save_lane(arrays, "begin_ts", s.begin_ts[:n])
                    _save_lane(arrays, "end_ts", s.end_ts[:n])
                    np.savez(os.path.join(d, f"stripe{i}.npz"), **arrays)
                if delta:
                    arrays = {}
                    begin = np.concatenate([ch.begin_ts for ch in delta])
                    n = begin.shape[0]
                    _save_lane(arrays, "begin_ts", begin)
                    _save_lane(arrays, "end_ts",
                               np.concatenate([ch.end_ts for ch in delta]))
                    for c in rep.sig:
                        _save_lane(arrays, f"lane__{c}", np.concatenate(
                            [ch.lanes[c] for ch in delta]))
                        if any(ch.valid.get(c) is not None for ch in delta):
                            arrays[f"valid__{c}"] = np.concatenate(
                                [ch.valid[c] if ch.valid.get(c) is not None
                                 else np.ones(ch.begin_ts.shape[0],
                                              dtype=np.bool_)
                                 for ch in delta])
                    np.savez(os.path.join(d, "delta.npz"), **arrays)
                meta = {"stripes": len(stripes), "delta": bool(delta),
                        "seq": rep.seq, "watermark": rep.watermark,
                        "seed_ts": rep.seed_ts, "sig": list(rep.sig)}
                self.instance.metadb.kv_put(f"columnar.{key}.meta",
                                            json.dumps(meta))

    def load(self):
        """Boot-time restore: rebuild stripes (zone maps recomputed) and
        resume the tail from the persisted seq.  Dictionary codes persisted
        in stripe lanes stay valid because dictionaries are append-only and
        checkpointed in the same save()."""
        if not self.instance.data_dir:
            return
        with self._lock:
            for k, v in self.instance.metadb.kv_scan("columnar."):
                key = k[len("columnar."):-len(".meta")]
                if not k.endswith(".meta") or "." not in key:
                    continue
                try:
                    meta = json.loads(v)
                except Exception:
                    continue  # a corrupt record must not poison boot
                schema, table = key.split(".", 1)
                try:
                    tm = self.instance.catalog.table(schema, table)
                except Exception:
                    tm = None
                if tm is None or tuple(tm.column_names()) != \
                        tuple(meta["sig"]):
                    continue  # schema moved since the checkpoint: reseed lazily
                d = os.path.join(self.instance.data_dir, "columnar",
                                 key.replace(".", os.sep))
                rep = TableReplica(key)
                rep.sig = tuple(meta["sig"])
                stripes = []
                try:
                    for i in range(int(meta["stripes"])):
                        with np.load(os.path.join(d, f"stripe{i}.npz")) as z:
                            stripes.append(self._load_tier_chunk(tm, rep, z,
                                                                 as_stripe=True))
                    delta = ()
                    if meta.get("delta"):
                        with np.load(os.path.join(d, "delta.npz")) as z:
                            delta = (self._load_tier_chunk(tm, rep, z,
                                                           as_stripe=False),)
                except (OSError, KeyError):
                    continue  # missing/partial files: leave unenrolled
                rep.tier = (tuple(stripes), delta)
                rep.delta_rows = sum(ch.begin_ts.shape[0] for ch in delta)
                rep.seq = int(meta["seq"])
                rep.watermark = int(meta["watermark"])
                rep.seed_ts = int(meta["seed_ts"])
                # stamps applied inside the margin window can exceed the
                # persisted watermark: recover the true bound from the tier
                mx = rep.watermark
                for ch in list(rep.tier[0]) + list(rep.tier[1]):
                    n = ch.num_rows if isinstance(ch, Stripe) else \
                        int(ch.begin_ts.shape[0])
                    if n == 0:
                        continue
                    mx = max(mx, int(ch.begin_ts[:n].max()))
                    e = ch.end_ts[:n]
                    e = e[e < INFINITY_TS]
                    if e.size:
                        mx = max(mx, int(e.max()))
                rep.max_applied_ts = mx
                rep.state = READY
                rep.publish()
                self.replicas[key] = rep
        if self.replicas:
            self._start_thread()

    def _load_tier_chunk(self, tm, rep, z, as_stripe: bool):
        begin = _load_lane(z, "begin_ts")
        end = _load_lane(z, "end_ts")
        lanes, valid = {}, {}
        for c in rep.sig:
            lanes[c] = _load_lane(z, f"lane__{c}")
            valid[c] = z[f"valid__{c}"] if f"valid__{c}" in z else None
        if as_stripe:
            return self._make_stripe(tm, lanes, valid, begin, end)
        return _DeltaChunk(lanes, valid, begin, end)
