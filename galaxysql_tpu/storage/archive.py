"""Cold-data archive: TTL-driven partition archival to Parquet.

Reference analog: the OSS/ORC cold-storage path (SURVEY.md §2.6 archive,
`OSSTableScanExec`, §2.10 local-partition rotation): rows older than a TTL cutoff move
out of the hot MVCC store into columnar files (Parquet via pyarrow standing in for
ORC-on-OSS), and scans transparently union hot + archived data.  Archived rows are
immutable; DML against them is rejected by absence (they no longer exist in the hot
store).  Dictionary-encoded string lanes are decoded to Arrow dictionary columns, so
archive files are self-describing and readable by any Parquet tool.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Iterator, List, Optional

import numpy as np

from galaxysql_tpu.chunk.batch import Column, ColumnBatch
from galaxysql_tpu.storage.zonemap import sargs_refuted
from galaxysql_tpu.types import datatype as dt
from galaxysql_tpu.utils import errors

try:
    import pyarrow as pa
    import pyarrow.parquet as pq
    PARQUET_AVAILABLE = True
except ImportError:  # pragma: no cover
    PARQUET_AVAILABLE = False


_MANIFEST_SCHEMA = """
CREATE TABLE IF NOT EXISTS archive_files (
    path TEXT PRIMARY KEY, table_key TEXT, archive_ts INTEGER, state TEXT,
    arc_txn INTEGER DEFAULT 0);
"""


class ArchiveManager:
    """Per-instance archive registry backed by the metadb manifest.

    Crash-safe flow: write parquet -> manifest PENDING -> delete hot rows ->
    manifest LIVE.  Boot recovery (`attach`): LIVE entries load into the registry;
    PENDING entries mean the hot rows were never deleted, so the orphan file is
    dropped and the next TTL run re-archives."""

    def __init__(self, directory: Optional[str] = None):
        self.directory = directory
        # key -> [(path, archive_ts)]
        self._files: Dict[str, List] = {}
        self._lock = threading.Lock()
        self._seq = 0
        self.metadb = None
        self._decoded: Dict[str, object] = {}  # path -> pyarrow table (immutable)
        self._file_stats: Dict[str, dict] = {}  # path -> column min-max (immutable)
        self.pruned_files = 0  # observable SARG skip counter
        self.rf_pruned_files = 0  # files skipped by runtime-filter ranges

    def attach(self, metadb):
        """Bind the metadb manifest + recover registry state (boot path)."""
        self.metadb = metadb
        with metadb._lock:
            metadb._conn.executescript(_MANIFEST_SCHEMA)
            cols = [r[1] for r in metadb._conn.execute(
                "PRAGMA table_info(archive_files)")]
            if "arc_txn" not in cols:  # migrate pre-arc_txn manifests
                metadb._conn.execute("ALTER TABLE archive_files "
                                     "ADD COLUMN arc_txn INTEGER DEFAULT 0")
            metadb._conn.commit()
        with self._lock:
            self._files.clear()
        for path, key, ats, state, arc_txn in metadb.query(
                "SELECT path, table_key, archive_ts, state, arc_txn "
                "FROM archive_files"):
            if state == "LIVE" and os.path.exists(path):
                with self._lock:
                    self._files.setdefault(key, []).append((path, ats))
                continue
            # PENDING: decided by the archive txn's commit point in the tx log
            # (recover_persisted re-commits/rolls back the hot-store stamps the
            # same way, so file and store stay consistent)
            log = metadb.tx_log_get(arc_txn) if arc_txn else None
            if log is not None and log[0] in ("COMMITTED", "DONE") and \
                    os.path.exists(path):
                metadb.execute("UPDATE archive_files SET state='LIVE' "
                               "WHERE path=?", (path,))
                with self._lock:
                    self._files.setdefault(key, []).append((path, ats))
            else:
                # no commit point — or a commit point whose file did not survive
                # the crash (parquet unsynced at power loss): discard the file
                # and force the txn ABORTED so recover_persisted (which runs
                # after attach) rolls the hot-row stamps back instead of
                # re-committing a delete whose archive copy no longer exists
                if arc_txn and log is not None and log[0] in ("COMMITTED",):
                    metadb.tx_log_put(arc_txn, "ABORTED")
                try:
                    os.unlink(path)
                except OSError:
                    pass
                metadb.execute("DELETE FROM archive_files WHERE path=?", (path,))

    def _dir_for(self, key: str) -> str:
        base = self.directory
        if base is None:
            import tempfile
            base = tempfile.mkdtemp(prefix="galaxysql_archive_")
            self.directory = base
        d = os.path.join(base, key.replace(".", os.sep))
        os.makedirs(d, exist_ok=True)
        return d

    def files_for(self, key: str, snapshot_ts: Optional[int] = None) -> List[str]:
        """Files whose archival committed at-or-before the snapshot (a transaction
        whose snapshot predates an archival still sees those rows HOT)."""
        with self._lock:
            entries = list(self._files.get(key, []))
        if snapshot_ts is None:
            return [p for p, _ in entries]
        return [p for p, ats in entries if ats <= snapshot_ts]

    def archive_older_than(self, instance, schema: str, table: str,
                           ttl_column: str, cutoff_days: int,
                           snapshot_ts: Optional[int] = None) -> int:
        """Move rows with ttl_column < cutoff (epoch days) into a parquet file.

        Returns rows archived.  The move is archive-write-then-delete: a crash
        between the two leaves rows duplicated in archive + hot, resolved by the
        idempotent re-run (delete again) — never lost."""
        if not PARQUET_AVAILABLE:
            raise errors.NotSupportedError("pyarrow is required for archiving")
        key = instance.store_key(schema, table)
        store = instance.store(schema, table)
        tm = store.table
        cm = tm.column(ttl_column)
        if not cm.dtype.clazz == dt.TypeClass.DATE:
            raise errors.TddlError("TTL column must be a DATE")
        from galaxysql_tpu.storage.table_store import INFINITY_TS
        ts = snapshot_ts or instance.tso.next_timestamp()
        total = 0
        # One file per partition, archived as a mini 2PC with the hot store as the
        # participant and the parquet file as the other, so the slow encode runs
        # WITHOUT the partition lock while staying race-free against session DML
        # (this job runs on the scheduler thread):
        #   1. under lock: select expired rows, stamp a provisional write intent
        #      (-arc_txn) on them, copy their lanes.  The intent makes concurrent
        #      DML on those rows a write conflict (sessions re-check under the
        #      lock); readers still see them hot.
        #   2. no lock: encode + write the parquet, manifest PENDING (+arc_txn),
        #      then log the commit point (tx_log COMMITTED @ archive_ts).
        #   3. commit the intent to archive_ts via StoreParticipant (bumps the
        #      table version -> invalidates device-cached ts lanes), THEN flip
        #      the manifest LIVE — readers never observe a row hot and archived.
        # Crash recovery: before the commit point, recover_persisted rolls the
        # -arc_txn stamps back and attach() discards the PENDING file; after it,
        # recover_persisted re-commits the stamps at archive_ts and attach()
        # promotes the PENDING file to LIVE — both sides always agree with the
        # logged decision.
        from galaxysql_tpu.txn.xa import StoreParticipant
        for p in store.partitions:
            arc_txn = instance.tso.next_timestamp()
            with p.lock:
                vis = p.visible_mask(ts)
                # NULL TTL values never expire.  Rows with ANY pending end stamp
                # (provisional -txn delete, or a delete committed after our
                # snapshot) stay hot: archiving them and then having the delete
                # resolve the other way would resurrect/duplicate the row.
                old = (vis & (p.end_ts == INFINITY_TS) & p.valid[cm.name]
                       & (p.lanes[cm.name] < cutoff_days))
                ids = np.nonzero(old)[0]
                if not ids.size:
                    continue
                p.end_ts[ids] = -arc_txn
                snap = {c.name: (p.lanes[c.name][ids].copy(),
                                 p.valid[c.name][ids].copy())
                        for c in tm.columns}
            sp = StoreParticipant(store, arc_txn)
            sp.deleted.append((p.pid, ids,
                               np.full(ids.size, INFINITY_TS, dtype=np.int64)))
            try:
                arrays = {}
                for c in tm.columns:
                    lane, valid = snap[c.name]
                    if c.dtype.is_string:
                        d = tm.dictionaries[c.name.lower()]
                        values = [d.values[code]
                                  if ok and 0 <= code < len(d.values) else None
                                  for code, ok in zip(lane.tolist(),
                                                      valid.tolist())]
                        arrays[c.name] = pa.array(values, type=pa.string())
                    else:
                        arrays[c.name] = pa.array(
                            [v if ok else None
                             for v, ok in zip(lane.tolist(), valid.tolist())])
                with self._lock:
                    self._seq += 1
                    path = os.path.join(
                        self._dir_for(key), f"archive_{ts}_{self._seq}.parquet")
                pq.write_table(pa.table(arrays), path)
                fd = os.open(path, os.O_RDONLY)  # durable BEFORE the commit point
                try:
                    os.fsync(fd)
                finally:
                    os.close(fd)
                archive_ts = instance.tso.next_timestamp()
                if self.metadb is not None:
                    self.metadb.execute(
                        "INSERT OR REPLACE INTO archive_files VALUES (?,?,?,?,?)",
                        (path, key, archive_ts, "PENDING", arc_txn))
                    # commit point: from here the archival is decided
                    self.metadb.tx_log_put(arc_txn, "COMMITTED", archive_ts)
            except Exception:
                sp.rollback()  # release the write intent; rows stay hot
                if self.metadb is not None:
                    self.metadb.tx_log_put(arc_txn, "ABORTED")
                try:  # drop the partial parquet: nothing references it
                    os.unlink(path)
                except (OSError, UnboundLocalError):
                    pass
                raise
            sp.commit(archive_ts)
            tm.stats.row_count = store.row_count()
            instance.catalog.version += 1
            if self.metadb is not None:
                self.metadb.execute("UPDATE archive_files SET state='LIVE' "
                                    "WHERE path=?", (path,))
                self.metadb.tx_log_put(arc_txn, "DONE", archive_ts)
            with self._lock:
                self._files.setdefault(key, []).append((path, archive_ts))
            total += ids.size
        return total

    def file_refuted(self, path: str, sargs) -> bool:
        """True when parquet column min-max stats prove NO row can satisfy
        the conjunctive sargs [(column, op, lane_value)] — the SARG/min-max
        file skip of the reference's columnar scans (OSSTableScanExec.java:
        45-61).  Evaluation itself lives in `storage/zonemap.sargs_refuted`,
        shared with the HTAP replica's stripe zone maps; this method only
        builds + caches the per-file stats from parquet metadata."""
        if not sargs:
            return False
        with self._lock:
            stats = self._file_stats.get(path)
        if stats is None:
            stats = {}
            try:
                md = pq.ParquetFile(path).metadata
                for rg in range(md.num_row_groups):
                    row = md.row_group(rg)
                    for ci in range(row.num_columns):
                        col = row.column(ci)
                        st = col.statistics
                        if st is None or not st.has_min_max:
                            continue
                        name = col.path_in_schema
                        lo, hi = st.min, st.max
                        if not isinstance(lo, (int, float)):
                            continue
                        old_st = stats.get(name)
                        if old_st is None:
                            stats[name] = (lo, hi)
                        else:
                            stats[name] = (min(old_st[0], lo), max(old_st[1], hi))
            except Exception:
                stats = {}
            with self._lock:
                self._file_stats[path] = stats
        return sargs_refuted(stats, sargs)

    def scan_archive(self, instance, schema: str, table: str,
                     columns: List[str],
                     snapshot_ts: Optional[int] = None,
                     sargs=None, rf_sargs=None,
                     rf_pruned_cb=None) -> Iterator[ColumnBatch]:
        """Yield archived rows as ColumnBatches (strings re-encoded against the
        table's live dictionaries so joins/filters stay in code space).  Decoded
        parquet tables cache by path (archive files are immutable).

        `rf_sargs` are runtime-filter min/max ranges (join build sides):
        files they refute are skipped through the same min-max machinery,
        counted separately (`rf_pruned_files` + the per-file callback) so the
        pruning win is observable apart from WHERE-derived sargs."""
        if not PARQUET_AVAILABLE:
            return
        key = instance.store_key(schema, table)
        files = self.files_for(key, snapshot_ts)
        if not files:
            return
        tm = instance.catalog.table(schema, table)
        for path in files:
            if sargs and self.file_refuted(path, sargs):
                self.pruned_files += 1
                continue
            if rf_sargs and self.file_refuted(path, rf_sargs):
                # NOT pruned_files: that counter keeps meaning WHERE-derived
                # sarg refutation only, so dashboards can tell the two apart
                self.rf_pruned_files += 1
                if rf_pruned_cb is not None:
                    rf_pruned_cb(path)
                continue
            with self._lock:
                t = self._decoded.get(path)
            if t is None:
                t = pq.read_table(path)
                with self._lock:
                    if len(self._decoded) > 64:
                        self._decoded.clear()
                    self._decoded[path] = t
            t = t.select(list(columns))
            cols = {}
            for name in columns:
                cm = tm.column(name)
                arr = t.column(name)
                pylist = arr.to_pylist()
                valid = np.array([v is not None for v in pylist], dtype=np.bool_)
                if cm.dtype.is_string:
                    d = tm.dictionaries[name.lower()]
                    lane = np.fromiter(
                        (d.encode_one(v) if v is not None else 0 for v in pylist),
                        dtype=np.int32, count=len(pylist))
                else:
                    lane = np.array([v if v is not None else 0 for v in pylist],
                                    dtype=cm.dtype.lane)
                cols[name] = Column(lane, None if valid.all() else valid, cm.dtype,
                                    tm.dictionaries.get(name.lower()))
            yield ColumnBatch(cols, None)
