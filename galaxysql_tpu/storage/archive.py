"""Cold-data archive: TTL-driven partition archival to Parquet.

Reference analog: the OSS/ORC cold-storage path (SURVEY.md §2.6 archive,
`OSSTableScanExec`, §2.10 local-partition rotation): rows older than a TTL cutoff move
out of the hot MVCC store into columnar files (Parquet via pyarrow standing in for
ORC-on-OSS), and scans transparently union hot + archived data.  Archived rows are
immutable; DML against them is rejected by absence (they no longer exist in the hot
store).  Dictionary-encoded string lanes are decoded to Arrow dictionary columns, so
archive files are self-describing and readable by any Parquet tool.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Iterator, List, Optional

import numpy as np

from galaxysql_tpu.chunk.batch import Column, ColumnBatch
from galaxysql_tpu.types import datatype as dt
from galaxysql_tpu.utils import errors

try:
    import pyarrow as pa
    import pyarrow.parquet as pq
    PARQUET_AVAILABLE = True
except ImportError:  # pragma: no cover
    PARQUET_AVAILABLE = False


_MANIFEST_SCHEMA = """
CREATE TABLE IF NOT EXISTS archive_files (
    path TEXT PRIMARY KEY, table_key TEXT, archive_ts INTEGER, state TEXT);
"""


class ArchiveManager:
    """Per-instance archive registry backed by the metadb manifest.

    Crash-safe flow: write parquet -> manifest PENDING -> delete hot rows ->
    manifest LIVE.  Boot recovery (`attach`): LIVE entries load into the registry;
    PENDING entries mean the hot rows were never deleted, so the orphan file is
    dropped and the next TTL run re-archives."""

    def __init__(self, directory: Optional[str] = None):
        self.directory = directory
        # key -> [(path, archive_ts)]
        self._files: Dict[str, List] = {}
        self._lock = threading.Lock()
        self._seq = 0
        self.metadb = None
        self._decoded: Dict[str, object] = {}  # path -> pyarrow table (immutable)

    def attach(self, metadb):
        """Bind the metadb manifest + recover registry state (boot path)."""
        self.metadb = metadb
        with metadb._lock:
            metadb._conn.executescript(_MANIFEST_SCHEMA)
            metadb._conn.commit()
        with self._lock:
            self._files.clear()
        for path, key, ats, state in metadb.query(
                "SELECT path, table_key, archive_ts, state FROM archive_files"):
            if state == "LIVE" and os.path.exists(path):
                with self._lock:
                    self._files.setdefault(key, []).append((path, ats))
            else:  # PENDING: hot rows were never deleted; discard the orphan
                try:
                    os.unlink(path)
                except OSError:
                    pass
                metadb.execute("DELETE FROM archive_files WHERE path=?", (path,))

    def _dir_for(self, key: str) -> str:
        base = self.directory
        if base is None:
            import tempfile
            base = tempfile.mkdtemp(prefix="galaxysql_archive_")
            self.directory = base
        d = os.path.join(base, key.replace(".", os.sep))
        os.makedirs(d, exist_ok=True)
        return d

    def files_for(self, key: str, snapshot_ts: Optional[int] = None) -> List[str]:
        """Files whose archival committed at-or-before the snapshot (a transaction
        whose snapshot predates an archival still sees those rows HOT)."""
        with self._lock:
            entries = list(self._files.get(key, []))
        if snapshot_ts is None:
            return [p for p, _ in entries]
        return [p for p, ats in entries if ats <= snapshot_ts]

    def archive_older_than(self, instance, schema: str, table: str,
                           ttl_column: str, cutoff_days: int,
                           snapshot_ts: Optional[int] = None) -> int:
        """Move rows with ttl_column < cutoff (epoch days) into a parquet file.

        Returns rows archived.  The move is archive-write-then-delete: a crash
        between the two leaves rows duplicated in archive + hot, resolved by the
        idempotent re-run (delete again) — never lost."""
        if not PARQUET_AVAILABLE:
            raise errors.NotSupportedError("pyarrow is required for archiving")
        key = instance.store_key(schema, table)
        store = instance.store(schema, table)
        tm = store.table
        cm = tm.column(ttl_column)
        if not cm.dtype.clazz == dt.TypeClass.DATE:
            raise errors.TddlError("TTL column must be a DATE")
        ts = snapshot_ts or instance.tso.next_timestamp()
        total = 0
        tables = []
        for p in store.partitions:
            vis = p.visible_mask(ts)
            # NULL TTL values never expire
            old = vis & p.valid[cm.name] & (p.lanes[cm.name] < cutoff_days)
            ids = np.nonzero(old)[0]
            if not ids.size:
                continue
            arrays = {}
            for c in tm.columns:
                lane = p.lanes[c.name][ids]
                valid = p.valid[c.name][ids]
                if c.dtype.is_string:
                    d = tm.dictionaries[c.name.lower()]
                    values = [d.values[code] if ok and 0 <= code < len(d.values)
                              else None
                              for code, ok in zip(lane.tolist(), valid.tolist())]
                    arrays[c.name] = pa.array(values, type=pa.string())
                else:
                    arrays[c.name] = pa.array(
                        [v if ok else None
                         for v, ok in zip(lane.tolist(), valid.tolist())])
            tables.append(pa.table(arrays))
            total += ids.size
            # delete AFTER the write below; remember ids per partition
            p._archive_pending = ids  # type: ignore
        if not tables:
            return 0
        merged = pa.concat_tables(tables)
        with self._lock:
            self._seq += 1
            path = os.path.join(self._dir_for(key),
                                f"archive_{ts}_{self._seq}.parquet")
        pq.write_table(merged, path)
        archive_ts = instance.tso.next_timestamp()
        if self.metadb is not None:
            self.metadb.execute("INSERT OR REPLACE INTO archive_files VALUES "
                                "(?,?,?,?)", (path, key, archive_ts, "PENDING"))
        # drop archived rows from the hot store, THEN publish the file: readers
        # never observe a row both hot and archived
        for p in store.partitions:
            ids = getattr(p, "_archive_pending", None)
            if ids is not None and len(ids):
                p.delete_rows(ids, archive_ts)
                p._archive_pending = None  # type: ignore
        if self.metadb is not None:
            self.metadb.execute("UPDATE archive_files SET state='LIVE' "
                                "WHERE path=?", (path,))
        with self._lock:
            self._files.setdefault(key, []).append((path, archive_ts))
        tm.stats.row_count = store.row_count()
        tm.bump_version()
        instance.catalog.version += 1
        return total

    def scan_archive(self, instance, schema: str, table: str,
                     columns: List[str],
                     snapshot_ts: Optional[int] = None) -> Iterator[ColumnBatch]:
        """Yield archived rows as ColumnBatches (strings re-encoded against the
        table's live dictionaries so joins/filters stay in code space).  Decoded
        parquet tables cache by path (archive files are immutable)."""
        if not PARQUET_AVAILABLE:
            return
        key = instance.store_key(schema, table)
        files = self.files_for(key, snapshot_ts)
        if not files:
            return
        tm = instance.catalog.table(schema, table)
        for path in files:
            with self._lock:
                t = self._decoded.get(path)
            if t is None:
                t = pq.read_table(path)
                with self._lock:
                    if len(self._decoded) > 64:
                        self._decoded.clear()
                    self._decoded[path] = t
            t = t.select(list(columns))
            cols = {}
            for name in columns:
                cm = tm.column(name)
                arr = t.column(name)
                pylist = arr.to_pylist()
                valid = np.array([v is not None for v in pylist], dtype=np.bool_)
                if cm.dtype.is_string:
                    d = tm.dictionaries[name.lower()]
                    lane = np.fromiter(
                        (d.encode_one(v) if v is not None else 0 for v in pylist),
                        dtype=np.int32, count=len(pylist))
                else:
                    lane = np.array([v if v is not None else 0 for v in pylist],
                                    dtype=cm.dtype.lane)
                cols[name] = Column(lane, None if valid.all() else valid, cm.dtype,
                                    tm.dictionaries.get(name.lower()))
            yield ColumnBatch(cols, None)
