"""Zone-map / SARG refutation shared by every columnar tier.

One evaluation rule for "can min-max stats prove NO row here satisfies these
conjunctive sargs?" — used by the TTL parquet archive (`storage/archive.py`
file skip, the reference's OSSTableScanExec SARG path) and the HTAP columnar
replica's base stripes (`storage/columnar.py`).  Keeping it in one place is
the point: the two tiers must agree on the semantics (missing stats never
prune; NULLs are excluded from min/max so conjuncts on an all-NULL column
never refute) or a scan routed to one tier could silently see fewer rows.

Sargs are `(column, op, value)` conjuncts with `op` in
{eq, lt, le, gt, ge} and `value` already in lane domain (dictionary code for
encoded strings, epoch days for dates) — the same shape `plan/physical.py`
pushes into `ScanSource` nodes.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

MinMax = Tuple[float, float]


def sargs_refuted(stats: Dict[str, MinMax], sargs) -> bool:
    """True when the per-column (min, max) stats prove the conjunction can
    match nothing.  Advisory: a column missing from `stats` contributes
    nothing (never prunes), so stale or partial stats only cost speed."""
    if not sargs:
        return False
    for cname, op, v in sargs:
        mm = stats.get(cname)
        if mm is None:
            continue
        lo, hi = mm
        if (op == "eq" and (v < lo or v > hi)) or \
                (op == "lt" and lo >= v) or \
                (op == "le" and lo > v) or \
                (op == "gt" and hi <= v) or \
                (op == "ge" and hi < v):
            return True
    return False


def lane_minmax(lane, valid) -> Optional[MinMax]:
    """(min, max) of a numeric lane over its valid rows, or None when no
    valid row exists (an all-NULL zone has no zone map — it never prunes
    via sargs_refuted's missing-stats rule, matching SQL tri-state)."""
    if valid is not None:
        lane = lane[valid]
    if lane.size == 0:
        return None
    # float()/int() over np scalars, not .item(): lanes here are host numpy
    # (stripe builders run on the tailer thread), never device buffers
    return (float(lane.min()), float(lane.max()))
