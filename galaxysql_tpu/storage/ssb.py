"""Star Schema Benchmark: schema, generator, and the 13 queries (public SSB spec).

BASELINE.md config 4: wide fact scan + broadcast dimension joins — the shape the
broadcast-join path of the MPP engine exists for.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from galaxysql_tpu.storage.tpch import REGIONS, NATIONS, _comments

SSB_DDL = {
    "dates": """
        CREATE TABLE dates (
            d_datekey INT NOT NULL PRIMARY KEY,
            d_date VARCHAR(18), d_dayofweek VARCHAR(9), d_month VARCHAR(9),
            d_year INT, d_yearmonthnum INT, d_yearmonth VARCHAR(7),
            d_weeknuminyear INT
        ) BROADCAST
    """,
    "supplier": """
        CREATE TABLE supplier (
            s_suppkey INT NOT NULL PRIMARY KEY, s_name VARCHAR(25),
            s_address VARCHAR(25), s_city VARCHAR(10), s_nation VARCHAR(15),
            s_region VARCHAR(12), s_phone VARCHAR(15)
        ) BROADCAST
    """,
    "customer": """
        CREATE TABLE customer (
            c_custkey INT NOT NULL PRIMARY KEY, c_name VARCHAR(25),
            c_address VARCHAR(25), c_city VARCHAR(10), c_nation VARCHAR(15),
            c_region VARCHAR(12), c_phone VARCHAR(15), c_mktsegment VARCHAR(10)
        ) PARTITION BY HASH(c_custkey) PARTITIONS 8
    """,
    "part": """
        CREATE TABLE part (
            p_partkey INT NOT NULL PRIMARY KEY, p_name VARCHAR(22),
            p_mfgr VARCHAR(6), p_category VARCHAR(7), p_brand1 VARCHAR(9),
            p_color VARCHAR(11), p_type VARCHAR(25), p_size INT,
            p_container VARCHAR(10)
        ) BROADCAST
    """,
    "lineorder": """
        CREATE TABLE lineorder (
            lo_orderkey BIGINT NOT NULL, lo_linenumber INT NOT NULL,
            lo_custkey INT NOT NULL, lo_partkey INT NOT NULL,
            lo_suppkey INT NOT NULL, lo_orderdate INT NOT NULL,
            lo_orderpriority VARCHAR(15), lo_shippriority INT,
            lo_quantity INT, lo_extendedprice BIGINT, lo_ordtotalprice BIGINT,
            lo_discount INT, lo_revenue BIGINT, lo_supplycost BIGINT,
            lo_tax INT, lo_commitdate INT, lo_shipmode VARCHAR(10),
            PRIMARY KEY (lo_orderkey, lo_linenumber)
        ) PARTITION BY HASH(lo_orderkey) PARTITIONS 8
    """,
}

TABLE_ORDER = ["dates", "supplier", "customer", "part", "lineorder"]

_MONTHS = ["January", "February", "March", "April", "May", "June", "July",
           "August", "September", "October", "November", "December"]
_DOW = ["Monday", "Tuesday", "Wednesday", "Thursday", "Friday", "Saturday", "Sunday"]
_COLORS = ["red", "green", "blue", "pink", "azure", "ivory", "linen", "navy",
           "peru", "plum", "puff", "snow"]
_CITY_N = 10


def generate(sf: float, seed: int = 19980101) -> Dict[str, Dict[str, list]]:
    rng = np.random.default_rng(seed)
    out: Dict[str, Dict[str, list]] = {}

    # dates: 1992-01-01 .. 1998-12-31 (datekey = yyyymmdd)
    import datetime
    day = datetime.date(1992, 1, 1)
    end = datetime.date(1998, 12, 31)
    keys, dstr, dow, mon, yr, ymn, ym, wk = [], [], [], [], [], [], [], []
    while day <= end:
        keys.append(day.year * 10000 + day.month * 100 + day.day)
        dstr.append(day.isoformat())
        dow.append(_DOW[day.weekday()])
        mon.append(_MONTHS[day.month - 1])
        yr.append(day.year)
        ymn.append(day.year * 100 + day.month)
        ym.append(f"{_MONTHS[day.month - 1][:3]}{day.year}")
        wk.append(int(day.isocalendar()[1]))
        day += datetime.timedelta(days=1)
    out["dates"] = {"d_datekey": keys, "d_date": dstr, "d_dayofweek": dow,
                    "d_month": mon, "d_year": yr, "d_yearmonthnum": ymn,
                    "d_yearmonth": ym, "d_weeknuminyear": wk}

    nations = [n for n, _ in NATIONS]
    region_of = {n: REGIONS[r].replace(" ", "") for n, r in NATIONS}

    def geo(n):
        nat = [nations[i] for i in rng.integers(0, len(nations), n)]
        city = [f"{x[:9]}{rng.integers(0, _CITY_N)}" for x in nat]
        reg = [region_of[x] for x in nat]
        return nat, city, reg

    n_supp = max(int(2_000 * sf), 20)
    sk = np.arange(1, n_supp + 1)
    nat, city, reg = geo(n_supp)
    out["supplier"] = {
        "s_suppkey": sk.tolist(), "s_name": [f"Supplier#{k:09d}" for k in sk],
        "s_address": [f"addr{k}" for k in sk], "s_city": city, "s_nation": nat,
        "s_region": reg, "s_phone": [f"{k % 25}-{k % 900 + 100}" for k in sk]}

    n_cust = max(int(30_000 * sf), 60)
    ck = np.arange(1, n_cust + 1)
    nat, city, reg = geo(n_cust)
    out["customer"] = {
        "c_custkey": ck.tolist(), "c_name": [f"Customer#{k:09d}" for k in ck],
        "c_address": [f"addr{k}" for k in ck], "c_city": city, "c_nation": nat,
        "c_region": reg, "c_phone": [f"{k % 25}-{k % 900 + 100}" for k in ck],
        "c_mktsegment": ["AUTOMOBILE"] * n_cust}

    n_part = max(int(200_000 * min(sf, 1) ** 0.5 * 0.2), 200)
    pk = np.arange(1, n_part + 1)
    mfgr = rng.integers(1, 6, n_part)
    cat = mfgr * 10 + rng.integers(1, 6, n_part)
    brand = cat * 100 + rng.integers(1, 41, n_part)
    out["part"] = {
        "p_partkey": pk.tolist(), "p_name": [f"part{k}" for k in pk],
        "p_mfgr": [f"MFGR#{m}" for m in mfgr],
        "p_category": [f"MFGR#{c}" for c in cat],
        "p_brand1": [f"MFGR#{b}" for b in brand],
        "p_color": [_COLORS[i] for i in rng.integers(0, len(_COLORS), n_part)],
        "p_type": [f"type{i}" for i in rng.integers(0, 25, n_part)],
        "p_size": rng.integers(1, 51, n_part).tolist(),
        "p_container": ["SM BOX"] * n_part}

    n_lo = max(int(6_000_000 * sf), 1000)
    lo_key = np.arange(1, n_lo + 1)
    odate = np.asarray(out["dates"]["d_datekey"])[
        rng.integers(0, len(keys), n_lo)]
    qty = rng.integers(1, 51, n_lo)
    price = rng.integers(90_000, 10_000_000, n_lo)
    disc = rng.integers(0, 11, n_lo)
    out["lineorder"] = {
        "lo_orderkey": lo_key.tolist(),
        "lo_linenumber": np.ones(n_lo, dtype=np.int64).tolist(),
        "lo_custkey": rng.integers(1, n_cust + 1, n_lo).tolist(),
        "lo_partkey": rng.integers(1, n_part + 1, n_lo).tolist(),
        "lo_suppkey": rng.integers(1, n_supp + 1, n_lo).tolist(),
        "lo_orderdate": odate.tolist(),
        "lo_orderpriority": ["1-URGENT"] * n_lo,
        "lo_shippriority": [0] * n_lo,
        "lo_quantity": qty.tolist(),
        "lo_extendedprice": price.tolist(),
        "lo_ordtotalprice": (price * 3).tolist(),
        "lo_discount": disc.tolist(),
        "lo_revenue": (price * (100 - disc) // 100).tolist(),
        "lo_supplycost": (price * 6 // 10).tolist(),
        "lo_tax": rng.integers(0, 9, n_lo).tolist(),
        "lo_commitdate": odate.tolist(),
        "lo_shipmode": ["TRUCK"] * n_lo}
    return out


QUERIES = {
    "1.1": """SELECT sum(lo_extendedprice * lo_discount) AS revenue
              FROM lineorder, dates WHERE lo_orderdate = d_datekey
              AND d_year = 1993 AND lo_discount BETWEEN 1 AND 3
              AND lo_quantity < 25""",
    "1.2": """SELECT sum(lo_extendedprice * lo_discount) AS revenue
              FROM lineorder, dates WHERE lo_orderdate = d_datekey
              AND d_yearmonthnum = 199401 AND lo_discount BETWEEN 4 AND 6
              AND lo_quantity BETWEEN 26 AND 35""",
    "1.3": """SELECT sum(lo_extendedprice * lo_discount) AS revenue
              FROM lineorder, dates WHERE lo_orderdate = d_datekey
              AND d_weeknuminyear = 6 AND d_year = 1994
              AND lo_discount BETWEEN 5 AND 7 AND lo_quantity BETWEEN 26 AND 35""",
    "2.1": """SELECT sum(lo_revenue) AS r, d_year, p_brand1
              FROM lineorder, dates, part, supplier
              WHERE lo_orderdate = d_datekey AND lo_partkey = p_partkey
              AND lo_suppkey = s_suppkey AND p_category = 'MFGR#12'
              AND s_region = 'AMERICA'
              GROUP BY d_year, p_brand1 ORDER BY d_year, p_brand1""",
    "2.2": """SELECT sum(lo_revenue) AS r, d_year, p_brand1
              FROM lineorder, dates, part, supplier
              WHERE lo_orderdate = d_datekey AND lo_partkey = p_partkey
              AND lo_suppkey = s_suppkey
              AND p_brand1 BETWEEN 'MFGR#2221' AND 'MFGR#2228'
              AND s_region = 'ASIA'
              GROUP BY d_year, p_brand1 ORDER BY d_year, p_brand1""",
    "2.3": """SELECT sum(lo_revenue) AS r, d_year, p_brand1
              FROM lineorder, dates, part, supplier
              WHERE lo_orderdate = d_datekey AND lo_partkey = p_partkey
              AND lo_suppkey = s_suppkey AND p_brand1 = 'MFGR#2239'
              AND s_region = 'EUROPE'
              GROUP BY d_year, p_brand1 ORDER BY d_year, p_brand1""",
    "3.1": """SELECT c_nation, s_nation, d_year, sum(lo_revenue) AS r
              FROM customer, lineorder, supplier, dates
              WHERE lo_custkey = c_custkey AND lo_suppkey = s_suppkey
              AND lo_orderdate = d_datekey AND c_region = 'ASIA'
              AND s_region = 'ASIA' AND d_year >= 1992 AND d_year <= 1997
              GROUP BY c_nation, s_nation, d_year
              ORDER BY d_year, r DESC""",
    "3.2": """SELECT c_city, s_city, d_year, sum(lo_revenue) AS r
              FROM customer, lineorder, supplier, dates
              WHERE lo_custkey = c_custkey AND lo_suppkey = s_suppkey
              AND lo_orderdate = d_datekey AND c_nation = 'UNITED STATES'
              AND s_nation = 'UNITED STATES'
              AND d_year >= 1992 AND d_year <= 1997
              GROUP BY c_city, s_city, d_year ORDER BY d_year, r DESC""",
    "3.3": """SELECT c_city, s_city, d_year, sum(lo_revenue) AS r
              FROM customer, lineorder, supplier, dates
              WHERE lo_custkey = c_custkey AND lo_suppkey = s_suppkey
              AND lo_orderdate = d_datekey
              AND (c_city = 'UNITED KI1' OR c_city = 'UNITED KI5')
              AND (s_city = 'UNITED KI1' OR s_city = 'UNITED KI5')
              AND d_year >= 1992 AND d_year <= 1997
              GROUP BY c_city, s_city, d_year ORDER BY d_year, r DESC""",
    "3.4": """SELECT c_city, s_city, d_year, sum(lo_revenue) AS r
              FROM customer, lineorder, supplier, dates
              WHERE lo_custkey = c_custkey AND lo_suppkey = s_suppkey
              AND lo_orderdate = d_datekey
              AND (c_city = 'UNITED KI1' OR c_city = 'UNITED KI5')
              AND (s_city = 'UNITED KI1' OR s_city = 'UNITED KI5')
              AND d_yearmonth = 'Dec1997'
              GROUP BY c_city, s_city, d_year ORDER BY d_year, r DESC""",
    "4.1": """SELECT d_year, c_nation, sum(lo_revenue - lo_supplycost) AS profit
              FROM dates, customer, supplier, part, lineorder
              WHERE lo_custkey = c_custkey AND lo_suppkey = s_suppkey
              AND lo_partkey = p_partkey AND lo_orderdate = d_datekey
              AND c_region = 'AMERICA' AND s_region = 'AMERICA'
              AND (p_mfgr = 'MFGR#1' OR p_mfgr = 'MFGR#2')
              GROUP BY d_year, c_nation ORDER BY d_year, c_nation""",
    "4.2": """SELECT d_year, s_nation, p_category,
              sum(lo_revenue - lo_supplycost) AS profit
              FROM dates, customer, supplier, part, lineorder
              WHERE lo_custkey = c_custkey AND lo_suppkey = s_suppkey
              AND lo_partkey = p_partkey AND lo_orderdate = d_datekey
              AND c_region = 'AMERICA' AND s_region = 'AMERICA'
              AND (d_year = 1997 OR d_year = 1998)
              AND (p_mfgr = 'MFGR#1' OR p_mfgr = 'MFGR#2')
              GROUP BY d_year, s_nation, p_category
              ORDER BY d_year, s_nation, p_category""",
    "4.3": """SELECT d_year, s_city, p_brand1,
              sum(lo_revenue - lo_supplycost) AS profit
              FROM dates, customer, supplier, part, lineorder
              WHERE lo_custkey = c_custkey AND lo_suppkey = s_suppkey
              AND lo_partkey = p_partkey AND lo_orderdate = d_datekey
              AND s_nation = 'UNITED STATES' AND (d_year = 1997 OR d_year = 1998)
              AND p_category = 'MFGR#14'
              GROUP BY d_year, s_city, p_brand1
              ORDER BY d_year, s_city, p_brand1""",
}
