"""Device-resident column batches — the TPU-native Chunk/Block engine.

Reference analog: `polardbx-executor/.../executor/chunk` (SURVEY.md §2.6, Appendix A):
`Chunk` = positionCount + Block[] + optional selection vector.  Here:

- `Column`  ~= Block: one fixed-dtype lane array + optional validity (null) mask.
- `ColumnBatch` ~= Chunk: dict of named Columns + a `live` row mask standing in for the
  reference's `int[] selection` indirection.  A filter doesn't compact rows (dynamic shapes
  would defeat XLA); it ANDs into `live`, and compaction is an explicit operator applied when
  the plan profits from it — exactly the role selection vectors play in the reference
  (`Chunk.java:79`).

Both are registered JAX pytrees, so whole operator pipelines jit/shard_map over them.
Strings are dictionary-encoded (int32 code lanes); the Dictionary itself is host-side static
metadata and travels in the pytree aux data.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from galaxysql_tpu.types import datatype as dt
from galaxysql_tpu.types import temporal


class Dictionary:
    """Host-side string dictionary: code lane (int32) <-> Python strings.

    Identity-hashed: a Dictionary instance is static jit metadata; rebuilding a dictionary
    creates a new compile key (same trade the reference makes by caching plans per schema
    version).
    """

    __slots__ = ("values", "index", "sorted_codes", "_is_sorted", "uid")

    _next_uid = itertools.count(1)

    def __init__(self, values: Sequence[str] = ()):  # code i -> values[i]
        self.values: List[str] = list(values)
        self.index: Dict[str, int] = {v: i for i, v in enumerate(self.values)}
        self.sorted_codes: Optional[np.ndarray] = None
        self._is_sorted: Optional[bool] = None
        # process-unique, never-reused identity (id() can be recycled after GC, which
        # would alias compiled-kernel cache keys)
        self.uid = next(Dictionary._next_uid)

    def __len__(self) -> int:
        return len(self.values)

    def encode_one(self, s: str, add: bool = True) -> int:
        code = self.index.get(s)
        if code is None:
            if not add:
                return -1
            code = len(self.values)
            self.values.append(s)
            self.index[s] = code
            self._is_sorted = None
        return code

    def encode(self, strings: Sequence[str], add: bool = True) -> np.ndarray:
        return np.fromiter((self.encode_one(s, add) for s in strings), dtype=np.int32,
                           count=len(strings))

    def decode(self, codes: np.ndarray) -> List[Optional[str]]:
        out: List[Optional[str]] = []
        for c in np.asarray(codes).tolist():
            out.append(self.values[c] if 0 <= c < len(self.values) else None)
        return out

    @property
    def is_sorted(self) -> bool:
        if self._is_sorted is None:
            self._is_sorted = all(self.values[i] <= self.values[i + 1]
                                  for i in range(len(self.values) - 1))
        return self._is_sorted

    def rank_array(self) -> np.ndarray:
        """rank[code] = position of code's string in sorted order (for <,> on dict lanes)."""
        order = np.argsort(np.array(self.values, dtype=object), kind="stable")
        rank = np.empty(len(self.values), dtype=np.int32)
        rank[order] = np.arange(len(self.values), dtype=np.int32)
        return rank

    def codes_matching(self, pred) -> np.ndarray:
        """All codes whose string satisfies `pred` — LIKE/regex evaluate host-side once per
        dictionary, then become device-side set membership (SURVEY.md §7 'strings' stance)."""
        return np.array([i for i, v in enumerate(self.values) if pred(v)], dtype=np.int32)

    def sorted_order(self) -> np.ndarray:
        """order[rank] = code whose string sorts at position `rank` (inverse of
        rank_array)."""
        return np.argsort(np.array(self.values, dtype=object), kind="stable").astype(np.int32)


def dictionary_translation(target: Dictionary, source: Dictionary) -> np.ndarray:
    """trans[source_code] = target_code (or -1 when the string is absent from target).

    Single home for cross-dictionary alignment, used by both the expression compiler
    (column-vs-column string compare) and the hash join (key domain normalization)."""
    return np.array([target.encode_one(v, add=False) for v in source.values] or [-1],
                    dtype=np.int32)


_UNION_TRANS_CACHE: Dict[Tuple[int, int, int], np.ndarray] = {}


def dictionary_union_translation(target: Dictionary,
                                 source: Dictionary) -> np.ndarray:
    """trans[source_code] = target_code, EXTENDING target with values it lacks
    (UNION semantics: every source string must exist in the output dictionary).

    Cached by (target uid, source uid, len(source)): codes never change once
    assigned, so a cached table stays valid as either dictionary grows."""
    key = (target.uid, source.uid, len(source))
    t = _UNION_TRANS_CACHE.get(key)
    if t is None:
        t = np.array([target.encode_one(v) for v in source.values] or [0],
                     dtype=np.int32)
        if len(_UNION_TRANS_CACHE) > 4096:
            _UNION_TRANS_CACHE.clear()
        _UNION_TRANS_CACHE[key] = t
    return t


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Column:
    """One column lane: `data` + optional validity mask (True = non-null)."""

    data: Any  # jnp/np array, shape [n]
    valid: Optional[Any]  # bool array [n] or None (all valid)
    dtype: dt.DataType = dataclasses.field(default=dt.BIGINT)
    dictionary: Optional[Dictionary] = None

    def tree_flatten(self):
        return (self.data, self.valid), (self.dtype, self.dictionary)

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, valid = children
        return cls(data, valid, aux[0], aux[1])

    def __len__(self) -> int:
        return int(self.data.shape[0])

    def valid_mask(self) -> Any:
        if self.valid is None:
            return jnp.ones(self.data.shape[0], dtype=jnp.bool_)
        return self.valid

    def np_data(self) -> np.ndarray:
        return np.asarray(self.data)

    def np_valid(self) -> np.ndarray:
        if self.valid is None:
            return np.ones(self.data.shape[0], dtype=np.bool_)
        return np.asarray(self.valid)

    # -- host conversions --------------------------------------------------

    def to_pylist(self) -> List[Any]:
        data = self.np_data()
        valid = self.np_valid()
        t = self.dtype
        out: List[Any] = []
        if t.is_string and self.dictionary is not None:
            decoded = self.dictionary.decode(data)
            return [decoded[i] if valid[i] else None for i in range(len(decoded))]
        for i in range(data.shape[0]):
            if not valid[i]:
                out.append(None)
            elif t.clazz == dt.TypeClass.DECIMAL:
                out.append(int(data[i]) / (10 ** t.scale))
            elif t.clazz == dt.TypeClass.DATE:
                out.append(temporal.format_date(int(data[i])))
            elif t.clazz == dt.TypeClass.DATETIME:
                out.append(temporal.format_datetime(int(data[i])))
            elif t.clazz == dt.TypeClass.FLOAT:
                out.append(float(data[i]))
            elif t.clazz == dt.TypeClass.BOOL:
                out.append(bool(data[i]))
            else:
                out.append(int(data[i]))
        return out


def column_from_pylist(values: Sequence[Any], typ: dt.DataType,
                       dictionary: Optional[Dictionary] = None) -> Column:
    """Build a Column from Python values (None = NULL), encoding per type."""
    n = len(values)
    valid = np.array([v is not None for v in values], dtype=np.bool_)
    lane = np.zeros(n, dtype=typ.lane)
    if typ.is_string:
        dictionary = dictionary if dictionary is not None else Dictionary()
        codes = [dictionary.encode_one(v) if v is not None else 0 for v in values]
        lane = np.array(codes, dtype=np.int32)
    else:
        for i, v in enumerate(values):
            if v is None:
                continue
            if typ.clazz == dt.TypeClass.DECIMAL:
                lane[i] = round(float(v) * (10 ** typ.scale))
            elif typ.clazz == dt.TypeClass.DATE:
                lane[i] = temporal.parse_date(v) if isinstance(v, str) else int(v)
            elif typ.clazz == dt.TypeClass.DATETIME:
                lane[i] = temporal.parse_datetime(v) if isinstance(v, str) else int(v)
            else:
                lane[i] = v
    return Column(lane, None if bool(valid.all()) else valid, typ, dictionary)


@jax.tree_util.register_pytree_node_class
class ColumnBatch:
    """A batch of rows: named Columns of equal length + a `live` row mask.

    `live` plays the selection-vector role: rows with live=False exist physically (fixed
    shapes for XLA) but are logically deleted.  `None` means all rows live.
    """

    def __init__(self, columns: Dict[str, Column], live: Optional[Any] = None):
        self.columns = columns
        self.live = live

    def tree_flatten(self):
        names = tuple(self.columns.keys())
        return (tuple(self.columns[n] for n in names), self.live), names

    @classmethod
    def tree_unflatten(cls, names, children):
        cols, live = children
        return cls(dict(zip(names, cols)), live)

    # -- shape -------------------------------------------------------------

    @property
    def capacity(self) -> int:
        if not self.columns:
            return 0
        return int(next(iter(self.columns.values())).data.shape[0])

    def live_mask(self) -> Any:
        if self.live is None:
            return jnp.ones(self.capacity, dtype=jnp.bool_)
        return self.live

    def np_live(self) -> np.ndarray:
        if self.live is None:
            return np.ones(self.capacity, dtype=np.bool_)
        return np.asarray(self.live)

    def num_live(self) -> int:
        if self.live is None:
            return self.capacity
        return int(np.asarray(self.live).sum())

    def __getitem__(self, name: str) -> Column:
        return self.columns[name]

    def names(self) -> List[str]:
        return list(self.columns.keys())

    # -- host-side utilities (not for use under jit) ------------------------

    def compact(self) -> "ColumnBatch":
        """Drop dead rows (host-side gather)."""
        if self.live is None:
            return self
        idx = np.nonzero(np.asarray(self.live))[0]
        cols = {}
        for name, c in self.columns.items():
            valid = c.np_valid()[idx]
            cols[name] = Column(c.np_data()[idx], None if bool(valid.all()) else valid,
                                c.dtype, c.dictionary)
        return ColumnBatch(cols, None)

    def pad_to(self, capacity: int) -> "ColumnBatch":
        """Pad with dead rows up to `capacity` (bucketing to avoid recompiles)."""
        n = self.capacity
        if n == capacity:
            if self.live is None:
                return ColumnBatch(dict(self.columns),
                                   np.ones(n, dtype=np.bool_))
            return self
        if n > capacity:
            raise ValueError(f"cannot pad batch of {n} down to {capacity}")
        pad = capacity - n
        live = np.zeros(capacity, dtype=np.bool_)
        live[:n] = self.np_live()
        cols = {}
        for name, c in self.columns.items():
            data = np.concatenate([c.np_data(), np.zeros(pad, dtype=c.dtype.lane)])
            valid = np.concatenate([c.np_valid(), np.zeros(pad, dtype=np.bool_)])
            cols[name] = Column(data, valid, c.dtype, c.dictionary)
        return ColumnBatch(cols, live)

    def to_pylist(self) -> List[Tuple]:
        """Live rows as tuples of Python values (row-at-a-time boundary, like ChunkRow)."""
        cb = self.compact()
        cols = [cb.columns[n].to_pylist() for n in cb.names()]
        return list(zip(*cols)) if cols else []

    def to_pydict(self) -> Dict[str, List[Any]]:
        cb = self.compact()
        return {n: cb.columns[n].to_pylist() for n in cb.names()}

    def select(self, names: Sequence[str]) -> "ColumnBatch":
        return ColumnBatch({n: self.columns[n] for n in names}, self.live)

    def rename(self, mapping: Dict[str, str]) -> "ColumnBatch":
        return ColumnBatch({mapping.get(n, n): c for n, c in self.columns.items()}, self.live)


def batch_from_pydict(data: Dict[str, Sequence[Any]], schema: Dict[str, dt.DataType],
                      dictionaries: Optional[Dict[str, Dictionary]] = None) -> ColumnBatch:
    cols = {}
    for name, values in data.items():
        d = (dictionaries or {}).get(name)
        cols[name] = column_from_pylist(values, schema[name], d)
    return ColumnBatch(cols, None)


def concat_batches(batches: Sequence[ColumnBatch]) -> ColumnBatch:
    """Host-side concatenation of compacted batches (dictionaries must be shared)."""
    batches = [b.compact() for b in batches if b.capacity]
    if not batches:
        return ColumnBatch({}, None)
    names = batches[0].names()
    cols = {}
    for n in names:
        ref = batches[0].columns[n]
        data = np.concatenate([b.columns[n].np_data() for b in batches])
        valid = np.concatenate([b.columns[n].np_valid() for b in batches])
        cols[n] = Column(data, None if bool(valid.all()) else valid, ref.dtype, ref.dictionary)
    return ColumnBatch(cols, None)
