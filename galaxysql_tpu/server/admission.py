"""Adaptive admission control + memory-pressure governance (overload plane).

Reference analog: the CN resource-governance subsystems the reference treats
as first-class (`optimizer/ccl` rule queuing, SURVEY.md §2.5, and the
memory/spill framework, §2.6), extended with the serving-stack shape every
saturated system needs: admit only work the box can finish, degrade with
typed errors, never collapse.

Four cooperating pieces:

- **Workload-class admission gate** in front of every query: statements
  classify TP (point/batched/short) vs AP (heavy) from the per-digest
  statement-summary cost (the PR 10 runtime-truth substrate — each finished
  query feeds its digest's observed class + latency EWMA back here) with a
  keyword heuristic for never-seen digests.  Each class holds an adaptive
  concurrency limit, AIMD-adjusted on observed latency: additive increase
  while the class meets its latency target, multiplicative decrease when the
  EWMA blows through it — the same control loop TCP uses to find a link's
  capacity, here finding the box's.
- **Deadline-aware shedding**: a statement whose remaining
  MAX_EXECUTION_TIME cannot cover its digest's predicted service time is
  refused immediately (typed, retry-after) instead of burning a slot on work
  that is already dead.
- **Memory-pressure tiers** (NORMAL -> ELEVATED -> CRITICAL) computed from
  the root `exec/memory.py` pool: ELEVATED shrinks the fragment-cache budget
  and drops spill thresholds 4x (queries trade disk for headroom);
  CRITICAL refuses new AP admissions and revokes the largest revocable
  query's pool (its operators spill at the next batch boundary) rather than
  letting the process OOM.
- **Typed refusals**: every shed is a `ServerOverloadError` carrying
  `retry_after_ms`, published to the event journal — the overload harness
  (`make overload-smoke`) asserts no other failure mode exists under flood.

Hot-path stance: when limits are idle the admit fast path is LOCK-FREE —
class token lists (GIL-atomic append/pop), one dict read for the digest
cost, one comparison against the limit.  The condition lock is touched only
by waiters and by releases that observe waiters.

Escape hatches (house trio): `ENABLE_ADMISSION_CONTROL` param,
``GALAXYSQL_ADMISSION=0`` env, per-statement ``ADMISSION(OFF)`` hint.
"""

from __future__ import annotations

import os
import re
import threading
import time
from typing import Dict, List, Optional, Tuple

from galaxysql_tpu.utils import errors

# kill switch: GALAXYSQL_ADMISSION=0 disables the whole subsystem (the A/B
# lever for the overload bench and the no-governance equivalence tests)
ENABLED = os.environ.get("GALAXYSQL_ADMISSION", "1") != "0"

TIERS = ("NORMAL", "ELEVATED", "CRITICAL")

# never-seen digests: heavy-shaped SQL (joins, grouping, global aggregates)
# is presumed AP until its first execution records the truth
_AP_GUESS_RE = re.compile(
    r"\b(?:group\s+by|join|order\s+by|sum\s*\(|avg\s*\(|count\s*\()", re.I)
# a hint comment can only matter when one exists; this pre-gate keeps the
# regex off plain statements
_HINT_MARK = "/*"


class MemoryGovernor:
    """Pressure tiers over the root memory pool + the responses per tier.

    ``tier()`` is called on every admission (and by workers piggybacking
    pressure into RPC replies): one division and a compare on the steady
    path.  Tier TRANSITIONS apply the governance actions — fragment-cache
    budget shrink/restore — and publish a `mem_pressure` event."""

    def __init__(self, instance=None, pool=None):
        from galaxysql_tpu.exec.memory import GLOBAL_POOL
        self.instance = instance
        self.pool = pool if pool is not None else GLOBAL_POOL
        self._last_tier = 0
        self._frag_base: Optional[int] = None
        self._lock = threading.Lock()

    def _pct(self, name: str, default: int) -> float:
        inst = self.instance
        if inst is not None:
            v = inst.config.get(name)
            if v is not None:
                return int(v) / 100.0
        return default / 100.0

    def usage(self) -> float:
        from galaxysql_tpu.utils.failpoint import FAIL_POINTS, FP_MEM_PRESSURE
        if FAIL_POINTS.active:
            v = FAIL_POINTS.value(FP_MEM_PRESSURE)
            if v is not None:
                if v == "elevated":
                    return self._pct("MEM_ELEVATED_PCT", 70)
                if v == "critical":
                    return self._pct("MEM_CRITICAL_PCT", 90)
                try:
                    return float(v)
                except (TypeError, ValueError):
                    return 1.0
        from galaxysql_tpu.exec.memory import usage_fraction
        return usage_fraction(self.pool)

    def tier(self) -> int:
        u = self.usage()
        if u >= self._pct("MEM_CRITICAL_PCT", 90):
            t = 2
        elif u >= self._pct("MEM_ELEVATED_PCT", 70):
            t = 1
        else:
            t = 0
        if t != self._last_tier:
            self._on_transition(t, u)
        return t

    def _on_transition(self, t: int, usage: float):
        with self._lock:
            prev = self._last_tier
            if t == prev:
                return
            self._last_tier = t
        inst = self.instance
        fcache = getattr(inst, "frag_cache", None) if inst else None
        if fcache is not None:
            if self._frag_base is None:
                self._frag_base = fcache.budget
            # ELEVATED halves the cache's claim on host memory, CRITICAL
            # quarters it; NORMAL restores the boot budget.  set_budget
            # evicts LRU down to the new cap immediately.
            scale = (1.0, 0.5, 0.25)[t]
            fcache.set_budget(int(self._frag_base * scale))
        if inst is not None:
            inst.metrics.gauge(
                "memory_pressure_tier",
                "memory governor tier (0=NORMAL 1=ELEVATED 2=CRITICAL)"
            ).set(t)
        from galaxysql_tpu.utils import events
        events.publish(
            "mem_pressure",
            f"memory pressure {TIERS[prev]} -> {TIERS[t]} "
            f"(root pool {usage:.0%} used)",
            severity="warn" if t > prev else "info",
            node=getattr(inst, "node_id", "") if inst else "",
            tier=TIERS[t], usage=round(usage, 3))

    def spill_scale(self) -> float:
        """Spill-threshold multiplier per tier: under pressure operators
        trade disk for resident state sooner."""
        return (1.0, 0.25, 0.125)[self.tier()]

    def revoke_largest_query(self) -> int:
        """CRITICAL response: flag the biggest per-query pool's operators to
        spill (flag-based revoke — the owning thread spills at its next
        batch boundary).  Returns the targeted pool's resident bytes."""
        from galaxysql_tpu.exec.memory import largest_query_child
        victim = largest_query_child(self.pool)
        if victim is None:
            return 0
        held = victim.reserved
        victim.revoke(held)
        from galaxysql_tpu.utils import events
        events.publish("mem_pressure",
                       f"CRITICAL: revoking largest query pool "
                       f"'{victim.name}' ({held} bytes resident)",
                       severity="warn", dedupe=f"revoke-{victim.name}",
                       pool=victim.name, bytes=held)
        return held


class _Ticket:
    """Admission handle: release() feeds observed latency + the true
    workload class back into the AIMD loop and the digest cost map.
    Idempotent (the Session exception paths may cross release sites)."""

    __slots__ = ("ctl", "cls", "digest", "t0", "_released")

    def __init__(self, ctl: Optional["AdmissionController"], cls: str,
                 digest: str):
        self.ctl = ctl
        self.cls = cls
        self.digest = digest
        self.t0 = time.time() if ctl is not None else 0.0
        self._released = False

    def release(self, prof=None, error: bool = False):
        if self.ctl is None or self._released:
            return
        self._released = True
        workload = getattr(prof, "workload", "") if prof is not None else ""
        err = error or bool(getattr(prof, "error", "")) \
            if prof is not None else error
        self.ctl._on_release(self, workload, err)


_NO_TICKET = _Ticket(None, "TP", "")


class AdmissionController:
    """Per-instance admission gate (see module docstring)."""

    # AIMD cadence: adjust a class's limit every N completions (per class)
    AIMD_SAMPLE = 16
    # multiplicative decrease / additive increase constants
    MD_FACTOR = 0.7
    AI_STEP = 1.0
    # digest cost map bound (plain dict, lock-free reads)
    MAX_DIGESTS = 4096

    def __init__(self, instance):
        self.instance = instance
        self.governor = MemoryGovernor(instance)
        # class -> in-flight tokens (list append/pop is GIL-atomic: the idle
        # fast path never takes a lock)
        self._tokens: Dict[str, list] = {"TP": [], "AP": []}
        # digest -> (class, latency EWMA ms); fed by _on_release
        self._digest_cost: Dict[str, Tuple[str, float]] = {}
        self._cond = threading.Condition()
        self._nwait = {"TP": 0, "AP": 0}  # plain-int waiter counts
        self._limit: Dict[str, float] = {}
        self._limit_max: Dict[str, float] = {}
        # config generation the cached limits were derived from: SET GLOBAL
        # ADMISSION_*_LIMIT must apply live (resetting AIMD state — config
        # changes are rare, a stale operator knob forever is worse)
        self._cfg_ver = -1
        self._ewma: Dict[str, float] = {"TP": 0.0, "AP": 0.0}
        self._since_adjust: Dict[str, int] = {"TP": 0, "AP": 0}
        self._aimd_lock = threading.Lock()
        # lifetime stats (SHOW ADMISSION / information_schema.admission_stats)
        self.admitted: Dict[str, int] = {"TP": 0, "AP": 0}
        self.shed_queue_full = 0
        self.shed_timeout = 0
        self.shed_deadline = 0
        self.shed_memory = 0
        self._stats_lock = threading.Lock()
        # cluster gossip (serving tier): node -> (snapshot, received_at) fed
        # by the health sync action / router gossip_tick.  The hot path only
        # reads `_cluster_min` (one dict get) — recomputed lazily when the
        # freshness window rolls, never per-admit.
        self._peer_snaps: Dict[str, Tuple[dict, float]] = {}
        self._cluster_min: Dict[str, float] = {}
        self._cluster_expire = 0.0

    # -- config ---------------------------------------------------------------

    def enabled(self, session=None, sql: str = "") -> bool:
        if not ENABLED:
            return False
        svars = getattr(session, "vars", None) if session is not None else None
        if not self.instance.config.get("ENABLE_ADMISSION_CONTROL", svars):
            return False
        if sql and _HINT_MARK in sql[:160]:
            from galaxysql_tpu.sql.hints import parse_hints
            if parse_hints(sql).get("admission") == "off":
                return False
        return True

    @staticmethod
    def _cfg_int(v, default: int) -> int:
        # NOT `v or default`: a configured 0 is a real value (queue size 0 =
        # shed immediately, limit 0 = refuse the class), never the fallback
        return default if v is None else int(v)

    def limit(self, cls: str) -> float:
        ver = self.instance.config.version
        if ver != self._cfg_ver:
            self._cfg_ver = ver
            self._limit.clear()
            self._limit_max.clear()
        lim = self._limit.get(cls)
        if lim is None:
            base = self.instance.config.get(
                "ADMISSION_TP_LIMIT" if cls == "TP" else "ADMISSION_AP_LIMIT")
            lim = float(self._cfg_int(base, 256 if cls == "TP" else 8))
            self._limit[cls] = lim
            self._limit_max[cls] = max(lim, 1.0) * 4
        return lim

    def _target_ms(self, cls: str) -> float:
        return float(self._cfg_int(
            self.instance.config.get(
                "ADMISSION_TARGET_TP_MS" if cls == "TP"
                else "ADMISSION_TARGET_AP_MS"),
            100 if cls == "TP" else 5000))

    # -- cluster gossip (serving tier) ----------------------------------------

    def cluster_snapshot(self) -> dict:
        """This node's admission state as gossiped to peers (rides the
        `health` sync action reply): per-class AIMD limit + in-flight, plus
        total sheds.  Small and JSON-plain — it travels the dn wire."""
        snap = {"node": self.instance.node_id}
        for cls in ("TP", "AP"):
            snap[cls.lower()] = {
                "limit": round(self.limit(cls), 2),
                "inflight": len(self._tokens[cls]),
                "ewma_ms": round(self._ewma[cls], 2),
            }
        snap["shed"] = (self.shed_queue_full + self.shed_timeout +
                        self.shed_deadline + self.shed_memory)
        return snap

    def note_peer(self, node: str, snap: Optional[dict],
                  at: Optional[float] = None):
        """Record a peer coordinator's gossiped admission snapshot.  Feeds
        effective_limit(): the cluster-wide clamp is min(local AIMD limit,
        fresh peer limits) — a flood that collapsed peer A's AP limit drags
        every peer's effective AP limit down with it until A recovers."""
        if not node or node == self.instance.node_id \
                or not isinstance(snap, dict):
            return
        with self._stats_lock:
            self._peer_snaps[node] = (snap, at if at is not None
                                      else time.time())
            self._cluster_expire = 0.0  # force a lazy recompute

    def forget_peer(self, node: str):
        with self._stats_lock:
            self._peer_snaps.pop(node, None)
            self._cluster_expire = 0.0

    def _fresh_s(self) -> float:
        v = self.instance.config.get("GOSSIP_FRESH_S")
        return float(v) if v is not None else 5.0

    def _recompute_cluster(self, now: float):
        """Rebuild the per-class min over FRESH peer limits.  `_cluster_expire`
        is set to the earliest moment the picture can change (a snapshot
        aging out or the freshness window), so the admit fast path pays one
        float compare between recomputes."""
        fresh = self._fresh_s()
        with self._stats_lock:
            mins: Dict[str, float] = {}
            next_expire = now + fresh
            for node, (snap, at) in list(self._peer_snaps.items()):
                age = now - at
                if age > max(fresh * 4, 30.0):
                    del self._peer_snaps[node]  # long-dead peer: drop it
                    continue
                if age > fresh:
                    continue  # stale: ignored but retained for SHOW rows
                next_expire = min(next_expire, at + fresh)
                for cls in ("TP", "AP"):
                    ent = snap.get(cls.lower())
                    if isinstance(ent, dict) and "limit" in ent:
                        lim = float(ent["limit"])
                        mins[cls] = min(mins.get(cls, lim), lim)
            self._cluster_min = mins
            self._cluster_expire = next_expire

    def effective_limit(self, cls: str) -> float:
        """The limit admit() enforces: the local AIMD limit clamped to the
        min of fresh peer limits when cluster admission is on.  Floors at
        ADMISSION_MIN_LIMIT — a peer's collapse throttles, never starves.
        Single-coordinator cost: one empty-dict check."""
        lim = self.limit(cls)
        if not self._peer_snaps:
            return lim
        if not self.instance.config.get("ENABLE_CLUSTER_ADMISSION"):
            return lim
        now = time.time()
        if now > self._cluster_expire:
            self._recompute_cluster(now)
        m = self._cluster_min.get(cls)
        if m is None or m >= lim:
            return lim
        floor = float(self._cfg_int(
            self.instance.config.get("ADMISSION_MIN_LIMIT"), 1))
        return max(floor, m)

    def peer_gossip_rows(self):
        """(node, snapshot, age_s) for SHOW COORDINATORS — stale peers
        included (the age column IS the staleness report)."""
        now = time.time()
        with self._stats_lock:
            return [(node, dict(snap), now - at)
                    for node, (snap, at) in sorted(self._peer_snaps.items())]

    # -- classification -------------------------------------------------------

    def classify(self, session, sql: str) -> Tuple[str, Optional[float], str]:
        """(class, predicted service ms | None, digest key).  Digest truth
        wins (the summary-fed cost map); unknown digests fall back to the
        heavy-SQL keyword guess."""
        digest = ""
        try:
            digest = session._digest_of(sql)
        except Exception:
            pass  # unparseable text classifies by heuristic; admit decides
        if digest:
            info = self._digest_cost.get(digest)
            if info is not None:
                return info[0], info[1], digest
        if "information_schema" in sql[:256].lower():
            return "TP", None, digest  # observability must stay reachable
        if _AP_GUESS_RE.search(sql):
            return "AP", None, digest
        return "TP", None, digest

    # -- admit / release ------------------------------------------------------

    def admit(self, session, sql: str) -> _Ticket:
        if not self.enabled(session, sql):
            return _NO_TICKET
        cls, predicted_ms, digest = self.classify(session, sql)
        # deadline-aware shed: remaining MAX_EXECUTION_TIME budget that
        # cannot cover the digest's predicted service time is dead work
        deadline = getattr(session, "_deadline", None)
        if deadline is not None and predicted_ms:
            remaining_ms = (deadline - time.time()) * 1000.0
            if remaining_ms < predicted_ms:
                self._shed("deadline", cls, digest,
                           f"remaining deadline {remaining_ms:.0f}ms cannot "
                           f"cover predicted {predicted_ms:.0f}ms",
                           retry_after_ms=int(predicted_ms))
        tier = self.governor.tier()
        if tier >= 2:
            # CRITICAL: shed load AND free memory — refuse the AP admission
            # and squeeze the largest resident query toward disk
            self.governor.revoke_largest_query()
            if cls == "AP":
                self._shed("memory", cls, digest,
                           "memory pressure CRITICAL: AP admission refused",
                           retry_after_ms=500)
        tokens = self._tokens[cls]
        tokens.append(None)  # optimistic claim (GIL-atomic)
        if len(tokens) <= self.effective_limit(cls):
            # idle/uncontended fast path: no lock was taken
            self.admitted[cls] += 1  # benign GIL race; aggregate insight
            return _Ticket(self, cls, digest)
        # over the limit: give the claim back and take the queued slow path
        self._pop_token(cls)
        return self._admit_queued(session, cls, digest, predicted_ms)

    def _pop_token(self, cls: str):
        try:
            self._tokens[cls].pop()
        except IndexError:  # pragma: no cover - bracket imbalance guard
            pass

    def _admit_queued(self, session, cls: str, digest: str,
                      predicted_ms: Optional[float]) -> _Ticket:
        qsize = self._cfg_int(
            self.instance.config.get("ADMISSION_QUEUE_SIZE"), 64)
        wait_s = self._cfg_int(
            self.instance.config.get("ADMISSION_WAIT_MS"), 1000) / 1000.0
        retry_ms = int(predicted_ms or 100)
        with self._cond:
            if self._nwait[cls] >= qsize:
                self._shed("queue_full", cls, digest,
                           f"{cls} admission queue full "
                           f"({self._nwait[cls]} waiting)",
                           retry_after_ms=retry_ms)
            self._nwait[cls] += 1
            self._update_queue_gauges()
            deadline = time.time() + wait_s
            try:
                while True:
                    tokens = self._tokens[cls]
                    if len(tokens) < self.effective_limit(cls):
                        tokens.append(None)
                        self.admitted[cls] += 1
                        return _Ticket(self, cls, digest)
                    remaining = deadline - time.time()
                    if remaining <= 0:
                        self._shed("timeout", cls, digest,
                                   f"{cls} admission wait timed out "
                                   f"({wait_s * 1000:.0f}ms)",
                                   retry_after_ms=retry_ms)
                    self._cond.wait(remaining)
            finally:
                self._nwait[cls] -= 1
                self._update_queue_gauges()

    def _shed(self, reason: str, cls: str, digest: str, msg: str,
              retry_after_ms: int):
        with self._stats_lock:
            if reason == "queue_full":
                self.shed_queue_full += 1
            elif reason == "timeout":
                self.shed_timeout += 1
            elif reason == "deadline":
                self.shed_deadline += 1
            else:
                self.shed_memory += 1
        m = self.instance.metrics
        m.counter("admission_shed_total",
                  "queries refused by admission control (typed)").inc()
        m.counter(f"admission_shed_{reason}",
                  f"admission sheds: {reason}").inc()
        from galaxysql_tpu.utils import events
        events.publish("admission_reject", msg, node=self.instance.node_id,
                       dedupe=f"adm-{reason}-{cls}",
                       reason=reason, workload=cls, digest=digest)
        raise errors.ServerOverloadError(
            f"server overloaded: {msg}; retry after {retry_after_ms}ms",
            retry_after_ms=retry_after_ms)

    def _on_release(self, ticket: _Ticket, workload: str, error: bool):
        self._pop_token(ticket.cls)
        if self._nwait["TP"] or self._nwait["AP"]:
            with self._cond:
                self._cond.notify_all()
        elapsed_ms = (time.time() - ticket.t0) * 1000.0
        cls = workload if workload in ("TP", "AP") else ticket.cls
        if ticket.digest:
            # feed the runtime truth back: next admission of this digest
            # classifies from observation, not the keyword guess
            prev = self._digest_cost.get(ticket.digest)
            ewma = elapsed_ms if prev is None \
                else 0.7 * prev[1] + 0.3 * elapsed_ms
            if len(self._digest_cost) > self.MAX_DIGESTS:
                self._digest_cost.clear()  # epoch reset, bounded
            self._digest_cost[ticket.digest] = (cls, ewma)
        if not error:
            self._aimd(cls, elapsed_ms)

    def _aimd(self, cls: str, elapsed_ms: float):
        """Additive-increase / multiplicative-decrease on the class limit,
        driven by the observed latency EWMA vs the class target."""
        with self._aimd_lock:
            self._ewma[cls] = elapsed_ms if self._ewma[cls] == 0.0 \
                else 0.8 * self._ewma[cls] + 0.2 * elapsed_ms
            self._since_adjust[cls] += 1
            if self._since_adjust[cls] < self.AIMD_SAMPLE:
                return
            self._since_adjust[cls] = 0
            lim = self.limit(cls)
            floor = float(self._cfg_int(
                self.instance.config.get("ADMISSION_MIN_LIMIT"), 1))
            if self._ewma[cls] > self._target_ms(cls):
                new = max(floor, lim * self.MD_FACTOR)
            elif len(self._tokens[cls]) >= lim * 0.75:
                # the limit is binding and latency is healthy: probe up
                new = min(self._limit_max.get(cls, lim * 4),
                          lim + self.AI_STEP)
            else:
                return
            if new != lim:
                self._limit[cls] = new
                self.instance.metrics.gauge(
                    f"admission_limit_{cls.lower()}",
                    f"adaptive {cls} admission concurrency limit").set(new)

    # -- observability --------------------------------------------------------

    def _update_queue_gauges(self):
        m = self.instance.metrics
        m.gauge("admission_queue_depth_tp",
                "TP queries waiting for an admission slot"
                ).set(self._nwait["TP"])
        m.gauge("admission_queue_depth_ap",
                "AP queries waiting for an admission slot"
                ).set(self._nwait["AP"])

    def _retry_budget_remaining(self) -> float:
        total = 0.0
        for client in getattr(self.instance, "workers", {}).values():
            b = getattr(client, "retry_budget", None)
            if b is not None:
                total += b.remaining()
        return total

    def stats_rows(self) -> List[Tuple[str, float]]:
        """(stat, value) rows for SHOW ADMISSION and the
        information_schema.admission_stats twin; refreshes the gauges."""
        tier = self.governor.tier()
        m = self.instance.metrics
        m.gauge("memory_pressure_tier",
                "memory governor tier (0=NORMAL 1=ELEVATED 2=CRITICAL)"
                ).set(tier)
        self._update_queue_gauges()
        budget = self._retry_budget_remaining()
        m.gauge("retry_budget_remaining",
                "retry-bucket tokens left across attached workers"
                ).set(budget)
        rows: List[Tuple[str, float]] = [
            ("enabled", 1.0 if self.enabled() else 0.0),
            ("memory_pressure_tier", float(tier)),
            ("memory_usage_frac", round(self.governor.usage(), 4)),
            ("retry_budget_remaining", budget),
        ]
        for cls in ("TP", "AP"):
            rows += [
                (f"{cls.lower()}_limit", float(self.limit(cls))),
                (f"{cls.lower()}_effective_limit",
                 float(self.effective_limit(cls))),
                (f"{cls.lower()}_inflight", float(len(self._tokens[cls]))),
                (f"{cls.lower()}_queue_depth", float(self._nwait[cls])),
                (f"{cls.lower()}_admitted", float(self.admitted[cls])),
                (f"{cls.lower()}_latency_ewma_ms",
                 round(self._ewma[cls], 3)),
            ]
        rows += [
            ("shed_queue_full", float(self.shed_queue_full)),
            ("shed_timeout", float(self.shed_timeout)),
            ("shed_deadline", float(self.shed_deadline)),
            ("shed_memory", float(self.shed_memory)),
        ]
        return rows
