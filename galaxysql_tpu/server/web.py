"""REST observability: JSON endpoints over the engine's runtime state.

Reference analog: `polardbx-executor/.../mpp/web/*` (query/stage/cluster JSON
resources served by the MPP coordinator's HTTP server).  Endpoints:

- /status            node identity, uptime, engine counters
- /queries           per-session state + last trace + the slow-query log
- /cluster           HA node states, leader, attached workers + fence state
- /plan-cache        hit/miss/size
- /baselines         SPM baselines (SHOW BASELINE as JSON)
- /scheduler         background jobs + recent firings
- /query-stats       last-N QueryProfile summaries (newest first)
- /statements        statement-digest summary store: top digests (ranked by
                     total time), per digest x plan rows, window history,
                     and the recent instance-event journal
- /query/<trace_id>  one query's full profile: per-operator rows/time,
                     fused-segment spans, trace tags (QueryStats analog)
- /trace/<trace_id>  the query's span tree as Chrome-trace/Perfetto JSON
                     (load in chrome://tracing or ui.perfetto.dev: one pid
                     per node — coordinator + each worker — one tid row per
                     mesh shard, compile/transfer events attributed in place;
                     falls back to the tail-sampled TraceStore, so retained
                     traces — including router-grafted cluster paths —
                     outlive the profile ring)
- /traces            the TraceStore's retained-trace index (id, digest,
                     reason, elapsed, phases) + store budget stats
- /incidents         flight-recorder bundle index (newest first)
- /incidents/<id>    one incident bundle's full evidence JSON
- /metrics           the typed counter/gauge registry in Prometheus text
                     exposition format (the scrape endpoint)
- /health            machine-readable liveness/readiness: SLO burn state,
                     per-worker breaker/fence telemetry, history summary
                     (status=degraded while any objective burns or any
                     worker is unreachable/fenced)
- /timeseries/<m>    one metric's windowed (ts, value) points from the
                     delta-encoded history ring, for plotting
- /events            journal tail; ?kind= / ?severity= / ?like= filters

Read-only by design: mutations go through SQL/DAL, never HTTP.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class WebConsole:
    def __init__(self, instance, host: str = "127.0.0.1", port: int = 0):
        self.instance = instance
        self.host = host
        self.port = port
        self.started_at = time.time()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- resources -----------------------------------------------------------

    def resource(self, path: str):
        inst = self.instance
        # query-string support (only /events and /timeseries use it today):
        # resource() is also called directly by tests with bare paths
        query = {}
        if "?" in path:
            from urllib.parse import parse_qs
            path, _, qs = path.partition("?")
            path = path.rstrip("/") or path
            query = {k: v[-1] for k, v in parse_qs(qs).items()}
        if path == "/status":
            return {"node_id": inst.node_id,
                    "uptime_s": round(time.time() - self.started_at, 1),
                    "counters": dict(inst.counters),
                    "sessions": len(inst.sessions)}
        if path == "/queries":
            from galaxysql_tpu.utils.tracing import SLOW_LOG
            sessions = []
            for cid, s in list(inst.sessions.items()):
                sessions.append({
                    "conn_id": cid, "schema": getattr(s, "schema", None),
                    "user": getattr(s, "user", None),
                    "in_txn": getattr(s, "txn", None) is not None,
                    "last_trace": list(getattr(s, "last_trace", []))[-8:]})
            slow = [{"sql": e.sql, "elapsed_s": e.elapsed_s,
                     "conn_id": e.conn_id, "at": e.at,
                     "trace_id": e.trace_id, "workload": e.workload,
                     "error": e.error, "digest": e.digest}
                    for e in SLOW_LOG.entries()]
            return {"sessions": sessions, "slow_queries": slow[-50:]}
        if path == "/cluster":
            inst.ha.check()
            return {"nodes": dict(inst.ha.states),
                    "leader": inst.ha.leader(),
                    "workers": [{"host": h, "port": p,
                                 "fenced": inst.ha.worker_fenced((h, p))}
                                for (h, p) in inst.workers]}
        if path == "/plan-cache":
            c = inst.planner.cache
            return {"hits": c.hits, "misses": c.misses,
                    "size": len(c._map), "capacity": c.capacity}
        if path == "/baselines":
            cols = ["baseline_id", "schema", "sql", "accepted", "origin",
                    "runs", "avg_ms", "candidate", "regressions",
                    "last_regression", "state", "rollbacks", "last_heal"]
            return {"baselines": [dict(zip(cols, r))
                                  for r in inst.planner.spm.rows()]}
        if path == "/scheduler":
            jobs = [{"name": n, "kind": k, "schema": s, "table": t,
                     "interval_s": i, "enabled": bool(e), "last_fire": lf}
                    for n, k, s, t, i, e, lf in inst.scheduler.jobs()]
            hist = [{"name": n, "fired_at": at, "status": st, "detail": d}
                    for n, at, st, d in inst.scheduler.history()[-50:]]
            return {"jobs": jobs, "history": hist}
        if path == "/query-stats":
            return {"queries": [
                {"trace_id": p.trace_id, "conn_id": p.conn_id,
                 "schema": p.schema, "workload": p.workload,
                 "engine": p.engine, "elapsed_ms": p.elapsed_ms,
                 "rows": p.rows, "profiled": p.profiled, "sql": p.sql}
                for p in reversed(inst.profiles.entries())]}
        if path == "/statements":
            from galaxysql_tpu.utils.events import EVENTS
            ss = inst.stmt_summary
            k = int(inst.config.get("STMT_SUMMARY_PROM_TOPK"))
            sum_cols = ["digest", "schema", "plan", "engines", "execs",
                        "errors", "avg_ms", "p95_ms", "p99_ms",
                        "rows_returned", "rows_examined", "retraces",
                        "frag_hits", "rf_rows_pruned", "skew_activations",
                        "rpc_retries", "spill_bytes", "peak_rss_kb",
                        "regressed", "join_order", "sql"]
            hist_cols = ["digest", "schema", "plan", "window_start", "execs",
                         "errors", "avg_ms", "min_ms", "max_ms",
                         "rows_returned", "rows_examined", "retraces",
                         "frag_hits", "rf_rows_pruned", "rpc_retries",
                         "spill_bytes", "sql"]
            return {"top": ss.top_digests(k),
                    "statements": [dict(zip(sum_cols, r))
                                   for r in ss.rows()],
                    "history": [dict(zip(hist_cols, r))
                                for r in ss.history_rows()[:200]],
                    "events": [{"seq": e.seq, "at": e.at, "kind": e.kind,
                                "severity": e.severity, "node": e.node,
                                "detail": e.detail, "attrs": e.attrs}
                               for e in EVENTS.entries()[-50:]]}
        if path.startswith("/query/"):
            try:
                trace_id = int(path[len("/query/"):])
            except ValueError:
                return None
            p = inst.profiles.get(trace_id)
            if p is None:
                return None
            return p.to_dict()  # segments/op_stats serialized there
        if path.startswith("/trace/"):
            from galaxysql_tpu.utils.tracing import (chrome_trace,
                                                     span_from_dict)
            tid = path[len("/trace/"):]
            p = inst.profiles.get(tid)
            if p is not None and p.spans:
                return chrome_trace(p.trace_id, p.spans)
            # tail-retained traces (slow/shed/errored/sampled, and the
            # router's grafted cluster paths) outlive the profile ring
            store = getattr(inst, "trace_store", None)
            rt = store.get(tid) if store is not None else None
            if rt is None or not rt.spans:
                return None  # untraced query: no tree to export
            return chrome_trace(rt.trace_id,
                                [span_from_dict(d) for d in rt.spans])
        if path == "/traces":
            # the retained-trace index: what the tail sampler kept and why
            store = getattr(inst, "trace_store", None)
            if store is None:
                return None
            return {"stats": store.stats(),
                    "traces": [{"trace_id": rt.trace_id, "digest": rt.digest,
                                "reason": rt.reason, "node": rt.node,
                                "at": round(rt.at, 3),
                                "elapsed_ms": rt.elapsed_ms,
                                "error": rt.error, "phases": rt.phases,
                                "spans": len(rt.spans), "sql": rt.sql}
                               for rt in store.entries(limit=128)]}
        if path.startswith("/incidents"):
            rec = getattr(inst, "recorder", None)
            if rec is None:
                return None
            rest = path[len("/incidents"):].strip("/")
            if rest:
                b = rec.get(rest)
                return b.to_dict() if b is not None else None
            return {"incidents": [
                {"incident_id": b.incident_id, "at": round(b.at, 3),
                 "kind": b.kind, "severity": b.severity,
                 "episode": b.episode, "node": b.node,
                 "digests": list(b.digests), "traces": len(b.traces),
                 "events": len(b.events), "detail": b.detail}
                for b in rec.bundles()],
                "captured": rec.captured, "suppressed": rec.suppressed}
        if path == "/health":
            # machine-readable liveness/readiness + SLO burn state + per-
            # worker telemetry; `status` is degraded while any objective
            # burns or any worker is unreachable/fenced (load balancers
            # key off this — it must render even when a worker is wedged,
            # so worker state comes from piggybacked telemetry, no pull)
            mh = inst.metric_history
            burning = inst.slo.burning_names()
            workers = []
            degraded = bool(burning)
            for (h, p), client in sorted(inst.workers.items()):
                bk = client.breaker_snapshot() \
                    if hasattr(client, "breaker_snapshot") else {"state": "closed"}
                fenced = bool(inst.ha.worker_fenced((h, p)))
                state = ("FENCED" if fenced else
                         "UNREACHABLE" if bk["state"] == "open" else "OK")
                degraded = degraded or state != "OK"
                workers.append({"host": h, "port": p, "state": state,
                                "breaker": bk["state"], "fenced": fenced,
                                "queue_depth": getattr(client, "load_q", 0),
                                "mem_tier": getattr(client, "load_tier", 0)})
            return {"status": "degraded" if degraded else "ok",
                    "live": True,
                    "ready": not degraded,
                    "node_id": inst.node_id,
                    "leader": bool(inst.ha.is_leader()),
                    "uptime_s": round(time.time() - inst.started_at, 1),
                    "burning_slos": burning,
                    "slo": [{"name": r[0], "state": r[8],
                             "fast_burn": r[6], "slow_burn": r[7]}
                            for r in inst.slo.rows()],
                    "history": mh.summary(),
                    "qps": round(mh.rate("queries_total"), 3),
                    "error_rate": round(mh.rate("query_errors"), 6),
                    "mem_tier": int(inst.admission.governor.tier()),
                    "workers": workers}
        if path.startswith("/timeseries/"):
            # one metric's replayed (ts, value) points for plotting
            name = path[len("/timeseries/"):]
            mh = inst.metric_history
            pts = mh.series(name)
            if not pts:
                return None  # unknown metric (or history disarmed): 404
            return {"metric": name,
                    "points": [[round(t, 3), v] for t, v in pts],
                    "rate_per_s": round(mh.rate(name), 6)}
        if path == "/events":
            # journal tail with ?kind= / ?severity= / ?like= triage filters
            from galaxysql_tpu.utils.events import EVENTS
            evs = EVENTS.entries(kind=query.get("kind"),
                                 severity=query.get("severity"),
                                 kind_like=query.get("like"))
            return {"events": [{"seq": e.seq, "at": round(e.at, 3),
                                "kind": e.kind, "severity": e.severity,
                                "node": e.node, "detail": e.detail,
                                "attrs": e.attrs, "trace_id": e.trace_id,
                                "digest": e.digest}
                               for e in reversed(evs)]}
        return None

    def metrics_text(self) -> str:
        """Prometheus text for /metrics: the instance registry plus a few
        point-in-time gauges stamped at scrape time.  The scrape-time gauges
        live in a throwaway registry — persisting them in the instance
        registry would leave stale point-in-time values visible to SHOW
        METRICS / information_schema.metrics between scrapes."""
        from galaxysql_tpu.utils.metrics import MetricsRegistry
        from galaxysql_tpu.utils.tracing import GLOBAL_STATS
        scrape = MetricsRegistry()
        scrape.gauge("sessions_active", "open sessions").set(
            len(self.instance.sessions))
        scrape.gauge("uptime_seconds", "web console uptime").set(
            round(time.time() - self.started_at, 1))
        scrape.gauge("query_profiles_retained",
                     "profiles in the last-N ring").set(
            len(self.instance.profiles.entries()))
        for name, value in GLOBAL_STATS.snapshot():
            scrape.gauge(f"instance_{name}",
                         "MatrixStatistics counter").set(value)
        return self.instance.metrics.prometheus_text() + \
            scrape.prometheus_text() + self._insight_text()

    def _insight_text(self) -> str:
        """Workload-insight exposition: instance-event counters (a `kind`
        label per event type) and the top-K statement digests' latency
        summaries (a `digest` label, bounded cardinality — top-K by total
        time only, K = STMT_SUMMARY_PROM_TOPK)."""
        from galaxysql_tpu.utils.events import EVENTS
        inst = self.instance
        ns = inst.metrics.namespace
        out = ["# HELP %s_events_total instance events by kind" % ns,
               "# TYPE %s_events_total counter" % ns]
        for kind, n in sorted(EVENTS.counts().items()):
            out.append(f'{ns}_events_total{{kind="{kind}"}} {n}')
        ss = getattr(inst, "stmt_summary", None)
        if ss is not None:
            # K=0 is a real setting (digest labels off), not "use default"
            k = int(inst.config.get("STMT_SUMMARY_PROM_TOPK"))
            tops = ss.top_digests(k) if k > 0 else []
            if tops:
                out.append(f"# HELP {ns}_stmt_latency_ms top-{k} statement "
                           "digests, latency summary")
                out.append(f"# TYPE {ns}_stmt_latency_ms summary")
                for d in tops:
                    lbl = f'digest="{d["digest"]}"'
                    for q, key in ((0.5, "p50_ms"), (0.95, "p95_ms"),
                                   (0.99, "p99_ms")):
                        out.append(f'{ns}_stmt_latency_ms{{{lbl},'
                                   f'quantile="{q}"}} {d[key]}')
                    out.append(f'{ns}_stmt_latency_ms_sum{{{lbl}}} '
                               f'{d["total_ms"]}')
                    out.append(f'{ns}_stmt_latency_ms_count{{{lbl}}} '
                               f'{d["execs"]}')
                out.append(f"# HELP {ns}_stmt_errors_total top-{k} statement "
                           "digests, failed executions")
                out.append(f"# TYPE {ns}_stmt_errors_total counter")
                for d in tops:
                    out.append(f'{ns}_stmt_errors_total{{digest='
                               f'"{d["digest"]}"}} {d["errors"]}')
        return "\n".join(out) + "\n"

    # -- http ----------------------------------------------------------------

    def start(self):
        console = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path.rstrip("/") == "/metrics":
                    # Prometheus scrape endpoint: text exposition, not JSON
                    try:
                        data = console.metrics_text().encode()
                    except Exception as e:
                        self.send_response(500)
                        self.end_headers()
                        self.wfile.write(json.dumps({"error": str(e)}).encode())
                        return
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                    return
                try:
                    body = console.resource(self.path.rstrip("/") or "/status")
                except Exception as e:  # a broken resource must not kill the server
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(json.dumps({"error": str(e)}).encode())
                    return
                if body is None:
                    self.send_response(404)
                    self.end_headers()
                    self.wfile.write(b'{"error": "unknown resource"}')
                    return
                data = json.dumps(body, default=str).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *a):  # no stderr chatter
                pass

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="web-console")
        self._thread.start()
        return self.port

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
