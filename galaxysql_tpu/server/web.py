"""REST observability: JSON endpoints over the engine's runtime state.

Reference analog: `polardbx-executor/.../mpp/web/*` (query/stage/cluster JSON
resources served by the MPP coordinator's HTTP server).  Endpoints:

- /status      node identity, uptime, engine counters
- /queries     per-session state + last trace + the slow-query log
- /cluster     HA node states, leader, attached workers + fence state
- /plan-cache  hit/miss/size
- /baselines   SPM baselines (SHOW BASELINE as JSON)
- /scheduler   background jobs + recent firings

Read-only by design: mutations go through SQL/DAL, never HTTP.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class WebConsole:
    def __init__(self, instance, host: str = "127.0.0.1", port: int = 0):
        self.instance = instance
        self.host = host
        self.port = port
        self.started_at = time.time()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- resources -----------------------------------------------------------

    def resource(self, path: str):
        inst = self.instance
        if path == "/status":
            return {"node_id": inst.node_id,
                    "uptime_s": round(time.time() - self.started_at, 1),
                    "counters": dict(inst.counters),
                    "sessions": len(inst.sessions)}
        if path == "/queries":
            from galaxysql_tpu.utils.tracing import SLOW_LOG
            sessions = []
            for cid, s in list(inst.sessions.items()):
                sessions.append({
                    "conn_id": cid, "schema": getattr(s, "schema", None),
                    "user": getattr(s, "user", None),
                    "in_txn": getattr(s, "txn", None) is not None,
                    "last_trace": list(getattr(s, "last_trace", []))[-8:]})
            slow = [{"sql": e.sql, "elapsed_s": e.elapsed_s,
                     "conn_id": e.conn_id, "at": e.at}
                    for e in SLOW_LOG.entries()]
            return {"sessions": sessions, "slow_queries": slow[-50:]}
        if path == "/cluster":
            inst.ha.check()
            return {"nodes": dict(inst.ha.states),
                    "leader": inst.ha.leader(),
                    "workers": [{"host": h, "port": p,
                                 "fenced": inst.ha.worker_fenced((h, p))}
                                for (h, p) in inst.workers]}
        if path == "/plan-cache":
            c = inst.planner.cache
            return {"hits": c.hits, "misses": c.misses,
                    "size": len(c._map), "capacity": c.capacity}
        if path == "/baselines":
            cols = ["baseline_id", "schema", "sql", "accepted", "origin",
                    "runs", "avg_ms", "candidate"]
            return {"baselines": [dict(zip(cols, r))
                                  for r in inst.planner.spm.rows()]}
        if path == "/scheduler":
            jobs = [{"name": n, "kind": k, "schema": s, "table": t,
                     "interval_s": i, "enabled": bool(e), "last_fire": lf}
                    for n, k, s, t, i, e, lf in inst.scheduler.jobs()]
            hist = [{"name": n, "fired_at": at, "status": st, "detail": d}
                    for n, at, st, d in inst.scheduler.history()[-50:]]
            return {"jobs": jobs, "history": hist}
        return None

    # -- http ----------------------------------------------------------------

    def start(self):
        console = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                try:
                    body = console.resource(self.path.rstrip("/") or "/status")
                except Exception as e:  # a broken resource must not kill the server
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(json.dumps({"error": str(e)}).encode())
                    return
                if body is None:
                    self.send_response(404)
                    self.end_headers()
                    self.wfile.write(b'{"error": "unknown resource"}')
                    return
                data = json.dumps(body, default=str).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *a):  # no stderr chatter
                pass

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="web-console")
        self._thread.start()
        return self.port

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
