"""Heat-driven Balancer: proposes partition SPLIT/MERGE/MOVE from observed
runtime truth, and executes them through the ddl/rebalance.py job family.

Reference analog: `executor/balancer/Balancer.java` (SURVEY.md §2.6) — the
policy half of scale-out.  The signals are the PR 9/10 substrate:

- per-partition HEAT = visible row share plus the hot-key mass the
  heavy-hitter sketches (`TableStats.heavy[_rt]` on the partition column)
  route to each partition — a skewed hot key shows up as heat long before
  row counts diverge;
- statement-summary TRAFFIC gates which tables are worth touching at all
  (a cold table never rebalances, however lopsided its rows);
- the admission plane gates WHEN: under memory pressure or a saturated
  admission queue the balancer proposes nothing — rebalance yields to
  serving (PR 12 graceful degradation), and the backfill task additionally
  paces its chunks under pressure.

`run_once` is the maintain-loop entry (`@job_kind("rebalance")`,
server/scheduler.py); `REBALANCE TABLE t` runs the same pipeline
synchronously and returns the decisions as rows.
"""

from __future__ import annotations

import re
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from galaxysql_tpu.utils import errors


class Balancer:
    def __init__(self, instance):
        self.instance = instance
        # last proposals per table key (SHOW REBALANCE-adjacent operator aid)
        self.last_proposals: List[dict] = []
        self.last_run_at: float = 0.0
        # no-progress damping: table key -> (n_parts, hot/mean ratio, pid)
        # recorded at each split proposal; see propose_table
        self._split_outcome: Dict[str, Tuple[int, float, int]] = {}

    # -- config knobs --------------------------------------------------------

    def _cfg(self, name: str, default):
        v = self.instance.config.get(name)
        return default if v is None else v

    # -- signals -------------------------------------------------------------

    def table_traffic(self) -> Dict[str, float]:
        """total statement-summary time (ms) attributed per table name (by
        digest-text match — digests don't carry a table list, but the
        parameterized text does)."""
        store = getattr(self.instance, "stmt_summary", None)
        if store is None:
            return {}
        out: Dict[str, float] = {}
        for r in store.rows():
            schema, text = (r[1] or "").lower(), (r[-1] or "").lower()
            total_ms = float(r[6]) * max(int(r[4]), 1)
            s = self.instance.catalog.schemas.get(schema)
            if s is None:
                continue
            for tname in s.tables:
                if tname.startswith("__recycle__") or "$" in tname:
                    continue
                # word-boundary match: a table named `t` must not collect
                # the traffic of every statement containing the letter t
                if re.search(r"\b%s\b" % re.escape(tname), text):
                    key = f"{schema}.{tname}"
                    out[key] = out.get(key, 0.0) + total_ms
        return out

    def partition_heat(self, tm, store) -> List[float]:
        """heat[pid] = visible rows + HOT_WEIGHT x sketch-estimated hot-key
        occurrences routed to pid (lane domain -> router, the exact mapping
        writes use)."""
        heat = [float(p.num_rows) for p in store.partitions]
        info = tm.partition
        if not info.columns:
            return heat
        try:
            col = tm.column(info.columns[0]).name  # stats key on exact name
        except errors.TddlError:
            return heat
        sketch = tm.stats.heavy_rt.get(col) or tm.stats.heavy.get(col)
        if sketch is None or not sketch.counts:
            return heat
        hot_w = float(self._cfg("REBALANCE_HOT_WEIGHT", 4.0))
        vals = np.asarray(list(sketch.counts.keys()))
        freqs = list(sketch.counts.values())
        try:
            pids = store.router.route_rows([vals])
        except Exception:
            return heat
        for pid, f in zip(pids.tolist(), freqs):
            if 0 <= pid < len(heat):
                heat[pid] += hot_w * float(f)
        return heat

    # -- proposal policy -----------------------------------------------------

    def propose_table(self, tm, store) -> List[dict]:
        info = tm.partition
        if info.method in ("single", "broadcast") or "$" in tm.name or \
                getattr(tm, "remote", None) is not None or \
                not tm.primary_key:
            return []
        n = info.num_partitions
        if n != len(store.partitions):
            return []  # mid-cutover snapshot; skip
        heat = self.partition_heat(tm, store)
        total = sum(heat)
        min_rows = int(self._cfg("REBALANCE_MIN_ROWS", 1000))
        if total < min_rows:
            return []
        mean = total / max(n, 1)
        out: List[dict] = []
        split_f = float(self._cfg("REBALANCE_SPLIT_FACTOR", 2.0))
        merge_f = float(self._cfg("REBALANCE_MERGE_FACTOR", 0.25))
        max_parts = int(self._cfg("REBALANCE_MAX_PARTITIONS", 64))
        key = f"{tm.schema.lower()}.{tm.name.lower()}"
        hot = int(np.argmax(heat))
        # split proposals are hash/key-only: a range split needs an explicit
        # AT (value) boundary the balancer cannot synthesize faithfully in
        # literal domain (operators split range tables manually)
        if heat[hot] > split_f * mean and n < max_parts and \
                info.method in ("hash", "key"):
            # no-progress damping: a split moves whole buckets, so one
            # dominant key's mass lands intact on a single target and
            # re-trips the trigger next tick — without this check one hot
            # key drives a full backfill+cutover per maintain tick all the
            # way to max_parts.  Park further splits of the same table once
            # a landed split (n grew) left the same partition's imbalance
            # essentially unchanged; un-park when the ratio improves, the
            # hot spot moves, or a merge shrinks the table back.
            ratio = heat[hot] / max(mean, 1.0)
            prev = self._split_outcome.get(key)
            if prev is not None and n > prev[0] and hot in \
                    (prev[2], prev[0]) and ratio >= 0.9 * prev[1]:
                pass  # previous split bought nothing; stop chasing the key
            else:
                out.append({"table": key, "op": "split", "pids": [hot],
                            "why": f"heat {heat[hot]:.0f} > {split_f:.1f}x "
                                   f"mean {mean:.0f}"})
                self._split_outcome[key] = (n, ratio, hot)
        elif n > 1 and info.method in ("hash", "key"):
            order = np.argsort(heat)
            a, b = int(order[0]), int(order[1])
            if heat[a] + heat[b] < merge_f * mean:
                out.append({"table": key, "op": "merge",
                            "pids": sorted((a, b)),
                            "why": f"cold pair {heat[a] + heat[b]:.0f} < "
                                   f"{merge_f:.2f}x mean {mean:.0f}"})
        # cross-group placement: move the hottest partition of the most
        # loaded group to the least loaded one (groups opt-in via the
        # REBALANCE_GROUPS csv param)
        groups = [g.strip() for g in
                  str(self._cfg("REBALANCE_GROUPS", "") or "").split(",")
                  if g.strip()]
        if len(groups) > 1 and not out:
            load = {g: 0.0 for g in groups}
            for pid, h in enumerate(heat):
                load[info.group_of(pid)] = \
                    load.get(info.group_of(pid), 0.0) + h
            src_g = max(load, key=load.get)
            dst_g = min(load, key=load.get)
            if load[src_g] > 2.0 * max(load[dst_g], 1.0):
                cands = [(h, pid) for pid, h in enumerate(heat)
                         if info.group_of(pid) == src_g]
                if cands:
                    _, pid = max(cands)
                    out.append({"table": key, "op": "move", "pids": [pid],
                                "group": dst_g,
                                "why": f"group {src_g} load "
                                       f"{load[src_g]:.0f} > 2x {dst_g} "
                                       f"{load[dst_g]:.0f}"})
        return out

    def propose(self, schema: Optional[str] = None,
                table: Optional[str] = None) -> List[dict]:
        traffic = self.table_traffic()
        min_ms = float(self._cfg("REBALANCE_MIN_TRAFFIC_MS", 0.0))
        out: List[dict] = []
        for s in list(self.instance.catalog.schemas.values()):
            if s.name == "information_schema":
                continue
            if schema and s.name.lower() != schema.lower():
                continue
            for tm in list(s.tables.values()):
                if table and tm.name.lower() != table.lower():
                    continue
                if tm.name.startswith("__recycle__") or "$" in tm.name:
                    continue
                key = f"{tm.schema.lower()}.{tm.name.lower()}"
                if min_ms > 0 and traffic.get(key, 0.0) < min_ms:
                    continue  # cold table: not worth moving bytes for
                store = self.instance.stores.get(key)
                if store is None:
                    continue
                out.extend(self.propose_table(tm, store))
        self.last_proposals = out
        return out

    # -- execution -----------------------------------------------------------

    def overloaded(self) -> bool:
        """Rebalance yields to serving: propose/execute nothing while the
        memory governor reports pressure."""
        adm = getattr(self.instance, "admission", None)
        gov = getattr(adm, "governor", None)
        return gov is not None and gov.tier() > 0

    def execute(self, prop: dict) -> int:
        from galaxysql_tpu.ddl import rebalance as rb
        schema, tname = prop["table"].split(".", 1)
        op = prop["op"]
        sql = f"/* balancer */ rebalance {op} {prop['table']} {prop['pids']}"
        if op == "split":
            job = rb.split_partition_job(schema, sql, tname, prop["pids"][0],
                                         int(prop.get("into", 2)),
                                         prop.get("at"))
        elif op == "merge":
            job = rb.merge_partitions_job(schema, sql, tname,
                                          prop["pids"][0], prop["pids"][1])
        elif op == "move":
            job = rb.move_partition_job(schema, sql, tname, prop["pids"][0],
                                        prop["group"])
        else:
            raise errors.TddlError(f"unknown balancer op {op!r}")
        self.instance.ddl_engine.submit_and_run(job)
        return job.job_id or 0

    def run_once(self, schema: Optional[str] = None,
                 table: Optional[str] = None, apply: bool = True
                 ) -> List[dict]:
        """One maintain-loop tick: propose, and (optionally) execute the
        first proposal — one data movement per tick keeps the blast radius
        and the serving impact bounded."""
        self.last_run_at = time.time()
        if not bool(self._cfg("ENABLE_REBALANCE", True)):
            return []
        if self.overloaded():
            return []
        props = self.propose(schema, table)
        if apply and props:
            first = props[0]
            try:
                first["job_id"] = self.execute(first)
                first["applied"] = True
            except errors.TddlError as e:
                first["applied"] = False
                first["error"] = str(e)
        return props
