"""SHOW command handlers.

Reference analog: `manager/response/*` + `executor/handler` SHOW handlers (SURVEY.md
§2.2/§2.6 — 133 logical handlers).  Each handler returns a ResultSet shaped like MySQL's.
"""

from __future__ import annotations

import fnmatch
from typing import List, Tuple

from galaxysql_tpu.sql import ast
from galaxysql_tpu.types import datatype as dt
from galaxysql_tpu.utils import errors


def _like_filter(names: List[str], pattern) -> List[str]:
    if not pattern:
        return names
    translated = pattern.replace("%", "*").replace("_", "?")
    return [n for n in names if fnmatch.fnmatch(n.lower(), translated.lower())]


def _peer_pull(inst, want: List[str]):
    """(node_id, reply-or-None) per serving-tier peer: a `health` pull with
    `want` sections (statement_summary / metrics rollups).  Transport
    failures yield None — CLUSTER surfaces render them as rows, never
    errors."""
    out = []
    for node_id, peer in sorted(getattr(inst, "coordinators", {}).items()):
        try:
            out.append((node_id, peer.sync_action("health", {"want": want})))
        except Exception:
            # unreachable peer: record None -- CLUSTER surfaces render it as
            # an UNREACHABLE row, never an error
            out.append((node_id, None))
    return out


def _unreachable_row(node: str, types) -> Tuple:
    """A typed placeholder row for a peer that did not answer the pull."""
    row = [node, "UNREACHABLE"]
    for t in types[2:]:
        row.append("" if t is dt.VARCHAR else 0)
    return tuple(row)


def _max_shard_rows(p) -> int:
    """Largest per-shard live-row count across the profile's MPP stages —
    slow-query triage sees shard skew straight from SHOW PROFILES, without
    tracing enabled (0 for local-engine or unprofiled queries)."""
    m = 0
    for st in p.op_stats:
        per = st.get("rows_per_shard")
        if per:
            m = max(m, max(per))
    return m


def _profile_rows(inst):
    """Last-N QueryProfiles as a result set, newest first (SHOW FULL STATS)."""
    from galaxysql_tpu.server.session import ResultSet
    rows = []
    for p in reversed(inst.profiles.entries()):
        rows.append((p.trace_id, p.conn_id, p.schema, p.workload, p.engine,
                     p.elapsed_ms, p.rows, len(p.op_stats), len(p.segments),
                     _max_shard_rows(p), 1 if p.profiled else 0, p.sql))
    return ResultSet(
        ["Trace_id", "Conn", "Schema", "Workload", "Engine", "Elapsed_ms",
         "Rows", "Operators", "Segments", "Max_shard_rows", "Profiled",
         "SQL"],
        [dt.BIGINT, dt.BIGINT, dt.VARCHAR, dt.VARCHAR, dt.VARCHAR, dt.DOUBLE,
         dt.BIGINT, dt.BIGINT, dt.BIGINT, dt.BIGINT, dt.BIGINT, dt.VARCHAR],
        rows)


def handle(session, stmt: ast.Show):
    from galaxysql_tpu.server.session import ResultSet

    kind = stmt.kind
    inst = session.instance
    if kind == "databases":
        names = sorted(s.name for s in inst.catalog.schemas.values())
        names = _like_filter(names, stmt.like)
        return ResultSet(["Database"], [dt.VARCHAR], [(n,) for n in names])
    if kind == "tables":
        schema = stmt.target or session.schema
        if not schema:
            raise errors.TddlError("No database selected")
        s = inst.catalog.schema(schema)
        # recycled (dropped) tables are invisible here; SHOW RECYCLEBIN lists them
        names = sorted(t.name for t in s.tables.values()
                       if not t.name.startswith("__recycle__"))
        names = _like_filter(names, stmt.like)
        return ResultSet([f"Tables_in_{schema}"], [dt.VARCHAR], [(n,) for n in names])
    if kind == "recyclebin":
        rows = inst.recycle.rows()
        return ResultSet(["NAME", "ORIGINAL_NAME", "SCHEMA_NAME", "DROP_TIME"],
                         [dt.VARCHAR] * 4, rows)
    if kind == "columns":
        return session._describe(ast.TableName([stmt.target]))
    if kind == "binlog":
        # SHOW BINLOG EVENTS: the ordered global change stream (CDC surface)
        rows = inst.cdc.events()
        return ResultSet(
            ["SEQ", "COMMIT_TSO", "SCHEMA_NAME", "TABLE_NAME", "KIND", "PAYLOAD"],
            [dt.BIGINT, dt.BIGINT, dt.VARCHAR, dt.VARCHAR, dt.VARCHAR, dt.VARCHAR],
            rows)
    if kind == "baseline":
        # SPM DAL (PlanManager.java DAL analog): one row per plan baseline;
        # REGRESSIONS/LAST_REGRESSION carry the statement-summary sentinel's
        # runtime verdict on the accepted plan, STATE/ROLLBACKS/LAST_HEAL the
        # self-heal quarantine machine (HEALTHY -> REGRESSED -> PROBATION ->
        # HEALED | EVOLVED | HEAL_FAILED)
        rows = inst.planner.spm.rows()
        return ResultSet(
            ["BASELINE_ID", "SCHEMA_NAME", "PARAMETERIZED_SQL", "ACCEPTED_PLAN",
             "ORIGIN", "RUNS", "AVG_MS", "CANDIDATE_PLAN", "REGRESSIONS",
             "LAST_REGRESSION", "STATE", "ROLLBACKS", "LAST_HEAL"],
            [dt.BIGINT, dt.VARCHAR, dt.VARCHAR, dt.VARCHAR, dt.VARCHAR,
             dt.BIGINT, dt.DOUBLE, dt.VARCHAR, dt.BIGINT, dt.VARCHAR,
             dt.VARCHAR, dt.BIGINT, dt.VARCHAR], rows)
    if kind == "create_table":
        schema = session.schema
        tm = inst.catalog.table(schema, stmt.target)
        lines = [f"CREATE TABLE `{tm.name}` ("]
        parts = []
        for c in tm.columns:
            nn = "" if c.nullable else " NOT NULL"
            ai = " AUTO_INCREMENT" if c.auto_increment else ""
            parts.append(f"  `{c.name}` {c.dtype.sql_name()}{nn}{ai}")
        if tm.primary_key:
            parts.append("  PRIMARY KEY (" +
                         ", ".join(f"`{k}`" for k in tm.primary_key) + ")")
        for i in tm.indexes:
            g = "GLOBAL " if i.global_index else ""
            u = "UNIQUE " if i.unique else ""
            parts.append(f"  {g}{u}KEY `{i.name}` (" +
                         ", ".join(f"`{c}`" for c in i.columns) + ")")
        body = ",\n".join(parts)
        p = tm.partition
        tail = ""
        if p.method == "broadcast":
            tail = " BROADCAST"
        elif p.method == "single":
            tail = " SINGLE"
        elif p.method in ("hash", "key"):
            tail = (f" PARTITION BY {p.method.upper()}(" +
                    ", ".join(p.columns) + f") PARTITIONS {p.count}")
        elif p.method.startswith(("range", "list")):
            tail = f" PARTITION BY {p.method.upper()}({', '.join(p.columns)}) (...)"
        ddl = "\n".join([lines[0], body, ")" + tail])
        return ResultSet(["Table", "Create Table"], [dt.VARCHAR, dt.VARCHAR],
                         [(tm.name, ddl)])
    if kind == "variables":
        reg = inst.config.registry()
        rows: List[Tuple] = []
        overlay = {k: v for k, v in session.vars.items()}
        for name, d in sorted(reg.items()):
            rows.append((name.lower(), str(inst.config.get(name, overlay))))
        for name, v in sorted(session.vars.items()):
            if name.upper() not in reg:
                rows.append((name.lower(), str(v)))
        names = _like_filter([r[0] for r in rows], stmt.like)
        rows = [r for r in rows if r[0] in names]
        return ResultSet(["Variable_name", "Value"], [dt.VARCHAR, dt.VARCHAR], rows)
    if kind == "processlist":
        rows = []
        for cid, s in sorted(inst.sessions.items()):
            rows.append((cid, "root", "localhost", s.schema or "", "Query", 0, "", ""))
        return ResultSet(["Id", "User", "Host", "db", "Command", "Time", "State",
                          "Info"],
                         [dt.BIGINT, dt.VARCHAR, dt.VARCHAR, dt.VARCHAR, dt.VARCHAR,
                          dt.BIGINT, dt.VARCHAR, dt.VARCHAR], rows)
    if kind in ("index", "indexes", "keys"):
        schema = session.schema
        tm = inst.catalog.table(schema, stmt.target)
        rows = []
        for i in tm.indexes:
            for seq, c in enumerate(i.columns, 1):
                rows.append((tm.name, 0 if i.unique else 1, i.name, seq, c,
                             "GLOBAL" if i.global_index else "LOCAL", i.status))
        for seq, c in enumerate(tm.primary_key, 1):
            rows.append((tm.name, 0, "PRIMARY", seq, c, "LOCAL", "PUBLIC"))
        return ResultSet(["Table", "Non_unique", "Key_name", "Seq_in_index",
                          "Column_name", "Index_type", "Status"],
                         [dt.VARCHAR, dt.BIGINT, dt.VARCHAR, dt.BIGINT, dt.VARCHAR,
                          dt.VARCHAR, dt.VARCHAR], rows)
    if kind == "slow":
        from galaxysql_tpu.utils.tracing import SLOW_LOG
        # Trace_id links a slow row to its profile (SHOW FULL STATS /
        # information_schema.query_stats / web /query/<trace_id>); Error is
        # non-empty for queries that died mid-execution AFTER crossing the
        # slow gate — slow failures explain themselves here too
        # Digest jumps a slow row straight to its SHOW STATEMENT SUMMARY
        # aggregate (same digest key: schema + parameterized text)
        rows = [(e.conn_id, round(e.elapsed_s * 1000, 1), e.sql,
                 e.trace_id, e.workload, e.error, e.digest)
                for e in SLOW_LOG.entries()]
        return ResultSet(["Conn", "Elapsed_ms", "SQL", "Trace_id", "Workload",
                          "Error", "Digest"],
                         [dt.BIGINT, dt.DOUBLE, dt.VARCHAR, dt.BIGINT,
                          dt.VARCHAR, dt.VARCHAR, dt.VARCHAR], rows)
    if kind == "fragment" and (stmt.target or "").lower() == "cache":
        # SHOW FRAGMENT CACHE: one row per resident entry, MRU first, plus
        # the totals SHOW METRICS carries as frag_cache_* counters
        fcache = getattr(inst, "frag_cache", None)
        rows = fcache.rows() if fcache is not None else []
        return ResultSet(["Kind", "Tables", "Rows", "Bytes", "Hits"],
                         [dt.VARCHAR, dt.VARCHAR, dt.BIGINT, dt.BIGINT,
                          dt.BIGINT], rows)
    if kind == "batch" and (stmt.target or "").lower() == "stats":
        # SHOW BATCH STATS: the cross-session point-query batching scheduler
        # (group sizes, waits, hit ratio, window occupancy) plus the DML
        # batcher's group rows and the async-apply backlog/lag gauges — the
        # information_schema.batch_stats twin
        sched = getattr(inst, "batch_scheduler", None)
        rows = sched.stats_rows() if sched is not None else []
        dsched = getattr(inst, "dml_batch_scheduler", None)
        if dsched is not None:
            rows = rows + dsched.stats_rows()
        return ResultSet(["Stat", "Value"], [dt.VARCHAR, dt.DOUBLE],
                         [(n, float(v)) for n, v in rows])
    if kind == "statement_summary":
        # SHOW STATEMENT SUMMARY [HISTORY]: the statement-digest store
        # (meta/statement_summary.py) — per digest x plan aggregates, or the
        # time-bucketed window history (information_schema twins)
        ss = inst.stmt_summary
        if getattr(stmt, "cluster", False):
            # SHOW CLUSTER STATEMENT SUMMARY: peer rollups merged under a
            # leading Node column; an unreachable peer renders as a row,
            # never an error (triage must work mid-outage)
            names = ["Node", "Digest", "Schema", "Plan", "Engines", "Execs",
                     "Errors", "Avg_ms", "P95_ms", "P99_ms", "Rows_returned",
                     "Rows_examined", "Retraces", "Frag_hits",
                     "Rf_rows_pruned", "Skew_activations", "Rpc_retries",
                     "Spill_bytes", "Peak_rss_kb", "Regressed", "Join_order",
                     "SQL"]
            types = [dt.VARCHAR, dt.VARCHAR, dt.VARCHAR, dt.VARCHAR,
                     dt.VARCHAR, dt.BIGINT, dt.BIGINT, dt.DOUBLE, dt.DOUBLE,
                     dt.DOUBLE, dt.BIGINT, dt.BIGINT, dt.BIGINT, dt.BIGINT,
                     dt.BIGINT, dt.BIGINT, dt.BIGINT, dt.BIGINT, dt.BIGINT,
                     dt.BIGINT, dt.VARCHAR, dt.VARCHAR]
            rows = [(inst.node_id,) + tuple(r) for r in ss.rows()]
            for node, resp in _peer_pull(inst, ["statement_summary"]):
                if resp is None:
                    rows.append(_unreachable_row(node, types))
                    continue
                for r in resp.get("statement_summary") or []:
                    rows.append((node,) + tuple(r))
            return ResultSet(names, types, rows)
        if (stmt.target or "").lower() == "history":
            return ResultSet(
                ["Digest", "Schema", "Plan", "Window_start", "Execs",
                 "Errors", "Avg_ms", "Min_ms", "Max_ms", "Rows_returned",
                 "Rows_examined", "Retraces", "Frag_hits", "Rf_rows_pruned",
                 "Rpc_retries", "Spill_bytes", "SQL"],
                [dt.VARCHAR, dt.VARCHAR, dt.VARCHAR, dt.BIGINT, dt.BIGINT,
                 dt.BIGINT, dt.DOUBLE, dt.DOUBLE, dt.DOUBLE, dt.BIGINT,
                 dt.BIGINT, dt.BIGINT, dt.BIGINT, dt.BIGINT, dt.BIGINT,
                 dt.BIGINT, dt.VARCHAR], ss.history_rows())
        return ResultSet(
            ["Digest", "Schema", "Plan", "Engines", "Execs", "Errors",
             "Avg_ms", "P95_ms", "P99_ms", "Rows_returned", "Rows_examined",
             "Retraces", "Frag_hits", "Rf_rows_pruned", "Skew_activations",
             "Rpc_retries", "Spill_bytes", "Peak_rss_kb", "Regressed",
             "Join_order", "SQL"],
            [dt.VARCHAR, dt.VARCHAR, dt.VARCHAR, dt.VARCHAR, dt.BIGINT,
             dt.BIGINT, dt.DOUBLE, dt.DOUBLE, dt.DOUBLE, dt.BIGINT,
             dt.BIGINT, dt.BIGINT, dt.BIGINT, dt.BIGINT, dt.BIGINT,
             dt.BIGINT, dt.BIGINT, dt.BIGINT, dt.BIGINT, dt.VARCHAR,
             dt.VARCHAR],
            ss.rows())
    if kind == "events":
        # SHOW EVENTS [WARN|INFO|CRITICAL] [LIKE 'kind%']: the typed
        # instance-event journal (utils/events.py) — newest first.  The
        # optional severity word and kind LIKE-pattern make slo_burn /
        # metric_anomaly triage a one-liner instead of a journal scroll.
        import json as _json
        from galaxysql_tpu.utils.events import EVENTS
        severity = (stmt.target or "").lower()
        if severity and severity not in ("info", "warn", "critical"):
            raise errors.NotSupportedError(
                f"SHOW EVENTS severity '{stmt.target}' "
                "(expected INFO|WARN|CRITICAL)")
        rows = [(e.seq, round(e.at, 3), e.kind, e.severity, e.node, e.detail,
                 _json.dumps(e.attrs, default=str)[:512],
                 e.trace_id, e.digest)
                for e in reversed(EVENTS.entries(
                    severity=severity or None,
                    kind_like=stmt.like or None))]
        return ResultSet(
            ["Seq", "At", "Kind", "Severity", "Node", "Detail", "Attrs",
             "Trace_id", "Digest"],
            [dt.BIGINT, dt.DOUBLE, dt.VARCHAR, dt.VARCHAR, dt.VARCHAR,
             dt.VARCHAR, dt.VARCHAR, dt.BIGINT, dt.VARCHAR], rows)
    if kind == "incidents":
        # SHOW INCIDENTS [<seq>]: flight-recorder incident bundles
        # (server/flight_recorder.py), newest first.  With a seq the full
        # evidence detail renders as Field/Value lines — implicated
        # digests, metric-history window tails, retained trace trees with
        # their phase breakdowns, and the event tail around the trigger.
        import json as _json
        rec = getattr(inst, "recorder", None)
        if stmt.target:
            b = rec.get(stmt.target) if rec is not None else None
            if b is None:
                raise errors.TddlError(
                    f"unknown incident '{stmt.target}' (SHOW INCIDENTS "
                    "lists retained bundles)")
            rows = [("incident_id", b.incident_id), ("at", f"{b.at:.3f}"),
                    ("kind", b.kind), ("severity", b.severity),
                    ("episode", b.episode), ("node", b.node),
                    ("detail", b.detail),
                    ("digests", ",".join(b.digests)),
                    ("trace_ids", ",".join(str(t) for t in b.trace_ids)),
                    ("admission",
                     _json.dumps(b.admission, default=str)[:512]),
                    ("state", _json.dumps(b.state, default=str)[:512])]
            for name in sorted(b.metric_window):
                rows.append((f"metric:{name}", _json.dumps(
                    b.metric_window[name][-8:], default=str)[:512]))
            from galaxysql_tpu.utils.tracing import (span_from_dict,
                                                     span_tree_lines)
            for tr in b.traces:
                tid = tr.get("trace_id")
                rows.append((f"trace:{tid}",
                             (f"{tr.get('reason')} "
                              f"{tr.get('elapsed_ms')}ms phases="
                              f"{_json.dumps(tr.get('phases') or {})}")
                             [:512]))
                spans = [span_from_dict(d) for d in tr.get("spans") or []]
                for ln in span_tree_lines(spans)[:24]:
                    rows.append((f"trace:{tid}", ln[:512]))
            for e in b.events[-16:]:
                rows.append((f"event:{e.get('seq')}",
                             f"{e.get('kind')} {e.get('detail', '')}"[:256]))
            return ResultSet(["Field", "Value"], [dt.VARCHAR, dt.VARCHAR],
                             rows)
        rows = rec.rows() if rec is not None else []
        return ResultSet(
            ["Incident", "At", "Kind", "Severity", "Episode", "Node",
             "Digests", "Traces", "Events", "Detail"],
            [dt.VARCHAR, dt.DOUBLE, dt.VARCHAR, dt.VARCHAR, dt.VARCHAR,
             dt.VARCHAR, dt.VARCHAR, dt.BIGINT, dt.BIGINT, dt.VARCHAR],
            rows)
    if kind == "rebalance":
        # SHOW REBALANCE: live elastic-rebalance jobs (phase, rows copied,
        # catchup lag, last checkpoint) + bounded finished-job history
        from galaxysql_tpu.ddl.rebalance import progress_rows
        return ResultSet(
            ["JOB_ID", "TABLE_NAME", "KIND", "STATE", "PHASE", "SRC_PARTITIONS",
             "TARGETS", "ROWS_COPIED", "EVENTS_APPLIED", "CATCHUP_LAG_MS",
             "LAST_CHECKPOINT", "ROUTER_EPOCH"],
            [dt.BIGINT, dt.VARCHAR, dt.VARCHAR, dt.VARCHAR, dt.VARCHAR,
             dt.VARCHAR, dt.BIGINT, dt.BIGINT, dt.BIGINT, dt.DOUBLE,
             dt.VARCHAR, dt.BIGINT], progress_rows(inst))
    if kind == "coordinators":
        # SHOW COORDINATORS: the serving tier (server/router.py) — every
        # peer coordinator with epoch, per-class admission limits, routed
        # statement counts, affinity hit ratio, last gossip age.  Dead
        # peers show as UNREACHABLE rows (the observability surface must
        # outlive the peers it describes).
        return ResultSet(
            ["Node", "Role", "State", "Epoch", "Tp_limit", "Ap_limit",
             "Tp_inflight", "Ap_inflight", "Routed", "Affinity_ratio",
             "Gossip_age_s"],
            [dt.VARCHAR, dt.VARCHAR, dt.VARCHAR, dt.BIGINT, dt.DOUBLE,
             dt.DOUBLE, dt.DOUBLE, dt.DOUBLE, dt.BIGINT, dt.DOUBLE,
             dt.DOUBLE],
            inst.coordinator_rows(pull=True))
    if kind == "workers":
        # SHOW WORKERS: attached worker endpoints with fence + circuit-breaker
        # state and lifetime retry/failure counters (the fault-tolerance
        # plane's SQL surface; information_schema.workers twin)
        return ResultSet(
            ["Host", "Port", "Breaker", "Fenced", "Consec_failures",
             "Retries", "Failures", "Breaker_opens", "Last_error",
             "Retry_budget"],
            [dt.VARCHAR, dt.BIGINT, dt.VARCHAR, dt.BIGINT, dt.BIGINT,
             dt.BIGINT, dt.BIGINT, dt.BIGINT, dt.VARCHAR, dt.BIGINT],
            inst.worker_rows())
    if kind == "admission":
        # SHOW ADMISSION: the overload plane (server/admission.py) — per-class
        # adaptive limits/in-flight/queue depth, shed counters, memory tier,
        # retry-budget headroom (information_schema.admission_stats twin)
        adm = getattr(inst, "admission", None)
        rows = adm.stats_rows() if adm is not None else []
        return ResultSet(["Stat", "Value"], [dt.VARCHAR, dt.DOUBLE],
                         [(n, float(v)) for n, v in rows])
    if kind == "metrics":
        # the typed counter/gauge registry (information_schema.metrics twin)
        if getattr(stmt, "cluster", False):
            # SHOW CLUSTER METRICS: every peer's registry under a leading
            # Node column (unreachable peers as rows, never errors)
            types = [dt.VARCHAR, dt.VARCHAR, dt.VARCHAR, dt.DOUBLE,
                     dt.VARCHAR]
            rows = [(inst.node_id, n, k, float(v), h)
                    for n, k, v, h in inst.metrics.rows()]
            for node, resp in _peer_pull(inst, ["metrics"]):
                if resp is None:
                    rows.append(_unreachable_row(node, types))
                    continue
                for r in resp.get("metrics") or []:
                    n, k, v, h = r
                    rows.append((node, n, k, float(v), h))
            return ResultSet(["Node", "Name", "Kind", "Value", "Help"],
                             types, rows)
        rows = [(n, k, float(v), h) for n, k, v, h in inst.metrics.rows()]
        return ResultSet(["Name", "Kind", "Value", "Help"],
                         [dt.VARCHAR, dt.VARCHAR, dt.DOUBLE, dt.VARCHAR],
                         rows)
    if kind == "profiles":
        return _profile_rows(inst)
    if kind == "ccl_rules":
        from galaxysql_tpu.utils.ccl import GLOBAL_CCL
        rows = []
        for st in GLOBAL_CCL.rules():
            r = st.rule
            rows.append((r.name, r.max_concurrency, r.keyword or "", r.user or "",
                         st.running, st.waiting, st.total_matched, st.total_rejected))
        return ResultSet(["Rule", "Max_concurrency", "Keyword", "User", "Running",
                          "Waiting", "Matched", "Rejected"],
                         [dt.VARCHAR, dt.BIGINT, dt.VARCHAR, dt.VARCHAR, dt.BIGINT,
                          dt.BIGINT, dt.BIGINT, dt.BIGINT], rows)
    if kind == "stats":
        # SHOW STATS = instance counters (§5.5); SHOW FULL STATS = the last-N
        # per-query runtime profiles (the reference's SHOW FULL STATS surface)
        if stmt.full:
            return _profile_rows(inst)
        from galaxysql_tpu.utils.tracing import GLOBAL_STATS
        return ResultSet(["Name", "Value"], [dt.VARCHAR, dt.BIGINT],
                         GLOBAL_STATS.snapshot())
    if kind == "ddl":
        rows = inst.metadb.query(
            "SELECT job_id, schema_name, state, ddl_sql FROM ddl_engine "
            "ORDER BY job_id DESC LIMIT 50")
        return ResultSet(["Job_id", "Schema", "State", "SQL"],
                         [dt.BIGINT, dt.VARCHAR, dt.VARCHAR, dt.VARCHAR], rows)
    if kind == "warnings":
        return ResultSet(["Level", "Code", "Message"],
                         [dt.VARCHAR, dt.BIGINT, dt.VARCHAR], [])
    if kind == "trace":
        # flat trace tags first (the legacy SQLTracer lines), then — when the
        # last query ran with ENABLE_QUERY_TRACING — the full span tree,
        # worker-side spans included
        lines = list(session.last_trace)
        spans = getattr(session, "last_spans", None)
        if spans:
            from galaxysql_tpu.utils.tracing import span_tree_lines
            lines += span_tree_lines(spans)
        return ResultSet(["Trace"], [dt.VARCHAR], [(t,) for t in lines])
    if kind in ("status", "engines", "charset", "collation"):
        if kind == "engines":
            return ResultSet(["Engine", "Support", "Comment"],
                             [dt.VARCHAR] * 3,
                             [("TPU_COLUMNAR", "DEFAULT",
                               "Device-resident columnar engine")])
        if kind == "collation":
            # the enumerated handler registry (types/collation.py; reference
            # *CollationHandler set) — charset = name prefix, MySQL layout.
            # Default marks THE default collation of each charset (MySQL 8.0
            # defaults), not case-insensitivity.
            from galaxysql_tpu.types.collation import COLLATIONS
            defaults = {"utf8mb4": "utf8mb4_0900_ai_ci",
                        "utf8": "utf8_general_ci",
                        "utf8mb3": "utf8mb3_general_ci",
                        "latin1": "latin1_swedish_ci",
                        "ascii": "ascii_general_ci",
                        "gbk": "gbk_chinese_ci",
                        "big5": "big5_chinese_ci",
                        "gb18030": "gb18030_chinese_ci",
                        "utf16": "utf16_general_ci",
                        "utf32": "utf32_general_ci",
                        "ucs2": "ucs2_general_ci",
                        "binary": "binary"}
            rows = []
            names = _like_filter(sorted(COLLATIONS), stmt.like)
            for i, name in enumerate(sorted(COLLATIONS), 1):
                if name not in names:
                    continue
                charset = name.split("_")[0] if "_" in name else name
                rows.append((name, charset, i,
                             "Yes" if defaults.get(charset) == name else "",
                             "Yes", 1))
            return ResultSet(
                ["Collation", "Charset", "Id", "Default", "Compiled",
                 "Sortlen"],
                [dt.VARCHAR, dt.VARCHAR, dt.BIGINT, dt.VARCHAR, dt.VARCHAR,
                 dt.BIGINT], rows)
        return ResultSet(["Variable_name", "Value"], [dt.VARCHAR, dt.VARCHAR], [])
    if kind == "slo":
        # SHOW SLO: every objective (built-in + CREATE SLO) with its live
        # fast/slow burn ratios and BURNING/OK state (server/slo.py)
        return ResultSet(
            ["Name", "Kind", "Schema", "Class", "Target", "Measured",
             "Fast_burn", "Slow_burn", "State", "Since", "Source"],
            [dt.VARCHAR, dt.VARCHAR, dt.VARCHAR, dt.VARCHAR, dt.DOUBLE,
             dt.DOUBLE, dt.DOUBLE, dt.DOUBLE, dt.VARCHAR, dt.DOUBLE,
             dt.VARCHAR],
            session.instance.slo.rows())
    if kind == "metric_history":
        # SHOW METRIC HISTORY [LIKE pattern]: per-metric window summaries
        # from the delta-encoded ring (utils/metric_history.py)
        return ResultSet(
            ["Metric", "Points", "Latest", "Min", "Max", "Rate_per_s"],
            [dt.VARCHAR, dt.BIGINT, dt.DOUBLE, dt.DOUBLE, dt.DOUBLE,
             dt.DOUBLE],
            session.instance.metric_history.rows(stmt.like))
    if kind == "columnar_replica":
        # SHOW COLUMNAR REPLICA: per-table tailer state, watermark freshness,
        # and tier shape (storage/columnar.py)
        return ResultSet(
            ["Table", "State", "Watermark", "Lag_ms", "Delta_rows",
             "Base_stripes", "Compactions", "Reseeds", "Pruned_stripes",
             "Applied_events", "Applied_rows"],
            [dt.VARCHAR, dt.VARCHAR, dt.BIGINT, dt.DOUBLE, dt.BIGINT,
             dt.BIGINT, dt.BIGINT, dt.BIGINT, dt.BIGINT, dt.BIGINT,
             dt.BIGINT],
            session.instance.columnar.rows())
    if kind == "cluster_health":
        # SHOW CLUSTER HEALTH: this coordinator + a fresh `health` pull
        # from every attached worker (UNREACHABLE rows, never errors)
        return ResultSet(
            ["Node", "Role", "Addr", "State", "Leader", "Uptime_s",
             "Sessions", "Qps", "Error_rate", "Mem_tier", "Burning_slos",
             "Samples"],
            [dt.VARCHAR, dt.VARCHAR, dt.VARCHAR, dt.VARCHAR, dt.BIGINT,
             dt.DOUBLE, dt.DOUBLE, dt.DOUBLE, dt.DOUBLE, dt.BIGINT,
             dt.VARCHAR, dt.BIGINT],
            session.instance.cluster_health(pull=True))
    raise errors.NotSupportedError(f"SHOW {kind}")
