"""Engine instance: the in-process root object (CobarServer/TDataSource analog).

Owns the catalog, table stores, planner, TSO, metadb (GMS), DDL engine, and config
(SURVEY.md §2.2/§3.1 boot path).  Sessions (`server/session.py`) hang off an Instance
the way ServerConnections hang off CobarServer.  `boot()` mirrors
`MatrixConfigHolder.doInit`: load catalog from the metadb, attach stores, reload
persisted partitions, then resume interrupted DDL jobs (§3.5 crash recovery).
"""

from __future__ import annotations

import os
import threading
import uuid
from typing import Dict, Optional

from galaxysql_tpu.config.params import ConfigParams
from galaxysql_tpu.meta.catalog import Catalog, TableMeta
from galaxysql_tpu.meta.gms import ConfigListener, MetaDb
from galaxysql_tpu.meta.tso import TimestampOracle
from galaxysql_tpu.plan.planner import Planner
from galaxysql_tpu.storage.table_store import TableStore


class Instance:
    def __init__(self, data_dir: Optional[str] = None, boot: bool = True):
        self.catalog = Catalog()
        self.stores: Dict[str, TableStore] = {}
        self.planner = Planner(self.catalog)
        self.tso = TimestampOracle()
        self.config = ConfigParams()
        self.data_dir = data_dir
        self.metadb = MetaDb(os.path.join(data_dir, "metadb.sqlite")
                             if data_dir else None)
        self.config_listener = ConfigListener(self.metadb)
        from galaxysql_tpu.ddl.jobs import DdlEngine
        self.ddl_engine = DdlEngine(self)
        from galaxysql_tpu.meta.sequence import SequenceManager
        self.sequences = SequenceManager(self.metadb)
        from galaxysql_tpu.meta.privileges import PrivilegeManager
        self.privileges = PrivilegeManager(self.metadb)
        from galaxysql_tpu.txn.xa import TwoPhaseCoordinator
        self.xa_coordinator = TwoPhaseCoordinator(self)
        from galaxysql_tpu.utils.locks import LockingFunctionManager
        self.locks = LockingFunctionManager()
        from galaxysql_tpu.txn.cdc import CdcManager
        # ordered change log keyed by commit TSO (CdcManager.java:135)
        self.cdc = CdcManager(self)
        from galaxysql_tpu.meta.mdl import MdlManager
        # per-table metadata locks: statements hold SHARED for their duration,
        # DDL cutover (repartition swap) takes EXCLUSIVE (MdlManager.java:35)
        self.mdl = MdlManager()
        from galaxysql_tpu.server.scheduler import ScheduledJobManager
        self.scheduler = ScheduledJobManager(self)
        from galaxysql_tpu.storage.archive import ArchiveManager
        self.archive = ArchiveManager(
            os.path.join(data_dir, "archive") if data_dir else None)
        self.node_id = f"cn-{uuid.uuid4().hex[:8]}"
        from galaxysql_tpu.net.dn import SyncBus
        self.workers: Dict[tuple, object] = {}  # (host, port) -> WorkerClient
        self.sync_bus = SyncBus()
        from galaxysql_tpu.meta.ha import HaManager
        self.ha = HaManager(self)
        import collections
        self.counters = collections.Counter()  # engine_counters virtual table
        self.lock = threading.RLock()
        self.next_conn_id = 1
        self.sessions: Dict[int, object] = {}
        self.catalog.create_schema("information_schema", if_not_exists=True)
        if boot:
            self.boot()

    # -- boot ------------------------------------------------------------------

    def _reload_global_config(self, *_):
        """Pull persisted SET GLOBAL values from the shared metadb (fired by
        the config listener when a peer coordinator changes one)."""
        import json
        for k, v in self.metadb.kv_scan("config.param."):
            try:
                self.config.set_instance(k[len("config.param."):], json.loads(v))
            except Exception:
                continue  # an unknown/stale param must not poison boot

    def boot(self):
        """Load persisted metadata + data, then recover interrupted DDL jobs."""
        self.planner.spm.attach(self.metadb)
        self.config_listener.bind("config.params", self._reload_global_config)
        self._reload_global_config()
        loaded = self.metadb.load_catalog(self.catalog)
        for tm in loaded:
            store = self.register_table(tm, persist=False)
            if self.data_dir:
                d = os.path.join(self.data_dir, tm.schema.lower(), tm.name.lower())
                if os.path.isdir(d):
                    store.load(d)
        self.archive.attach(self.metadb)
        # resolve provisional ±txn_id MVCC stamps left by a crash against the
        # durable tx log BEFORE anything reads the loaded partitions
        from galaxysql_tpu.txn.xa import recover_persisted
        recover_persisted(self)
        self.metadb.heartbeat(self.node_id, "coordinator", "127.0.0.1", 0)
        self.ddl_engine.recover()

    # -- store management ------------------------------------------------------

    def store_key(self, schema: str, table: str) -> str:
        return f"{schema.lower()}.{table.lower()}"

    def register_table(self, tm: TableMeta, persist: bool = True) -> TableStore:
        store = TableStore(tm)
        self.stores[self.store_key(tm.schema, tm.name)] = store
        if persist:
            self.metadb.save_table(tm)
        return store

    def drop_store(self, schema: str, table: str):
        self.stores.pop(self.store_key(schema, table), None)
        self.metadb.drop_table(schema, table)

    def store(self, schema: str, table: str) -> TableStore:
        return self.stores[self.store_key(schema, table)]

    # -- persistence -----------------------------------------------------------

    def save(self):
        """Flush all table data + metadata to disk (checkpoint)."""
        if not self.data_dir:
            return
        # marker time is captured BEFORE the store snapshots: a txn committing
        # while save() runs may have provisional stamps in an already-written
        # npz, so tx-log purge may only drop entries resolved before this point
        import time
        t0 = time.time()
        for key, store in self.stores.items():
            store.save(os.path.join(self.data_dir, key.replace(".", os.sep)))
            self.metadb.save_table(store.table)
        self.metadb.kv_put("last_checkpoint_at", repr(t0))

    def allocate_conn_id(self) -> int:
        with self.lock:
            cid = self.next_conn_id
            self.next_conn_id += 1
            return cid

    def attach_remote_table(self, schema: str, name: str, host: str,
                            port: int):
        """Register a worker-process table: scans compile to shipped SQL
        (MyJdbcHandler.java:691 plan-shipping seam).  The worker is also wired
        into the sync-action bus and the HA prober."""
        from galaxysql_tpu.net.dn import WorkerClient
        from galaxysql_tpu.types import datatype as dt
        from galaxysql_tpu.meta.catalog import ColumnMeta, TableMeta, SINGLE
        key = (host, port)
        client = self.workers.get(key)
        if client is None:
            client = WorkerClient(host, port)
            self.workers[key] = client
            self.sync_bus.attach(client)
        resp = client.sync_action("table_meta", {"schema": schema,
                                                 "table": name})
        cols = [ColumnMeta(n, dt.from_sql_name(t, p or 0, s or 0), nullable)
                for n, t, p, s, nullable in resp["columns"]]
        tm = TableMeta(schema, name, cols, resp.get("primary_key") or [],
                       SINGLE)
        tm.remote = {"host": host, "port": port}
        self.catalog.create_schema(schema, if_not_exists=True)
        self.catalog.add_table(tm, if_not_exists=True)
        self.catalog.version += 1
        return tm

    def mesh(self):
        """The instance's device mesh for MPP execution (None on a single device)."""
        if not hasattr(self, "_mesh"):
            import jax
            try:
                devs = jax.devices()
            except RuntimeError:
                devs = []
            if len(devs) > 1:
                from galaxysql_tpu.parallel.mesh import make_mesh
                self._mesh = make_mesh(devices=devs)
            else:
                self._mesh = None
        return self._mesh
