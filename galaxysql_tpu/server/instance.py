"""Engine instance: the in-process root object (CobarServer/TDataSource analog).

Owns the catalog, table stores, planner, TSO, and config (SURVEY.md §2.2/§3.1 boot
path).  Sessions (`server/session.py`) hang off an Instance the way ServerConnections
hang off CobarServer.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional

from galaxysql_tpu.config.params import ConfigParams
from galaxysql_tpu.meta.catalog import Catalog, TableMeta
from galaxysql_tpu.meta.tso import TimestampOracle
from galaxysql_tpu.plan.planner import Planner
from galaxysql_tpu.storage.table_store import TableStore


class Instance:
    def __init__(self, data_dir: Optional[str] = None):
        self.catalog = Catalog()
        self.stores: Dict[str, TableStore] = {}
        self.planner = Planner(self.catalog)
        self.tso = TimestampOracle()
        self.config = ConfigParams()
        self.data_dir = data_dir
        self.lock = threading.RLock()
        self.catalog.create_schema("information_schema", if_not_exists=True)
        self.next_conn_id = 1
        self.sessions: Dict[int, object] = {}

    # -- store management ------------------------------------------------------

    def store_key(self, schema: str, table: str) -> str:
        return f"{schema.lower()}.{table.lower()}"

    def register_table(self, tm: TableMeta) -> TableStore:
        store = TableStore(tm)
        self.stores[self.store_key(tm.schema, tm.name)] = store
        return store

    def drop_store(self, schema: str, table: str):
        self.stores.pop(self.store_key(schema, table), None)

    def store(self, schema: str, table: str) -> TableStore:
        return self.stores[self.store_key(schema, table)]

    # -- persistence -----------------------------------------------------------

    def save(self):
        if not self.data_dir:
            return
        for key, store in self.stores.items():
            store.save(os.path.join(self.data_dir, key.replace(".", os.sep)))

    def allocate_conn_id(self) -> int:
        with self.lock:
            cid = self.next_conn_id
            self.next_conn_id += 1
            return cid

    def mesh(self):
        """The instance's device mesh for MPP execution (None on a single device)."""
        if not hasattr(self, "_mesh"):
            import jax
            try:
                devs = jax.devices()
            except RuntimeError:
                devs = []
            if len(devs) > 1:
                from galaxysql_tpu.parallel.mesh import make_mesh
                self._mesh = make_mesh(devices=devs)
            else:
                self._mesh = None
        return self._mesh
