"""Engine instance: the in-process root object (CobarServer/TDataSource analog).

Owns the catalog, table stores, planner, TSO, metadb (GMS), DDL engine, and config
(SURVEY.md §2.2/§3.1 boot path).  Sessions (`server/session.py`) hang off an Instance
the way ServerConnections hang off CobarServer.  `boot()` mirrors
`MatrixConfigHolder.doInit`: load catalog from the metadb, attach stores, reload
persisted partitions, then resume interrupted DDL jobs (§3.5 crash recovery).
"""

from __future__ import annotations

import json
import os
import threading
import time as _time
import uuid
from typing import Dict, Optional

from galaxysql_tpu.config.params import ConfigParams
from galaxysql_tpu.meta.catalog import Catalog, TableMeta
from galaxysql_tpu.meta.gms import ConfigListener, MetaDb
from galaxysql_tpu.meta.tso import TimestampOracle
from galaxysql_tpu.plan.planner import Planner
from galaxysql_tpu.storage.table_store import TableStore
from galaxysql_tpu.utils import errors


class Instance:
    def __init__(self, data_dir: Optional[str] = None, boot: bool = True):
        self.catalog = Catalog()
        self.stores: Dict[str, TableStore] = {}
        self.planner = Planner(self.catalog)
        self.tso = TimestampOracle()
        self.config = ConfigParams()
        self.data_dir = data_dir
        self.metadb = MetaDb(os.path.join(data_dir, "metadb.sqlite")
                             if data_dir else None)
        self.config_listener = ConfigListener(self.metadb)
        from galaxysql_tpu.ddl.jobs import DdlEngine
        self.ddl_engine = DdlEngine(self)
        from galaxysql_tpu.meta.sequence import SequenceManager
        self.sequences = SequenceManager(self.metadb)
        from galaxysql_tpu.meta.privileges import PrivilegeManager
        self.privileges = PrivilegeManager(self.metadb)
        from galaxysql_tpu.txn.xa import TwoPhaseCoordinator
        self.xa_coordinator = TwoPhaseCoordinator(self)
        from galaxysql_tpu.utils.locks import LockingFunctionManager
        self.locks = LockingFunctionManager()
        from galaxysql_tpu.txn.cdc import CdcManager
        # ordered change log keyed by commit TSO (CdcManager.java:135)
        self.cdc = CdcManager(self)
        from galaxysql_tpu.meta.mdl import MdlManager
        # per-table metadata locks: statements hold SHARED for their duration,
        # DDL cutover (repartition swap) takes EXCLUSIVE (MdlManager.java:35)
        self.mdl = MdlManager()
        from galaxysql_tpu.server.scheduler import ScheduledJobManager
        self.scheduler = ScheduledJobManager(self)
        from galaxysql_tpu.storage.archive import ArchiveManager
        self.archive = ArchiveManager(
            os.path.join(data_dir, "archive") if data_dir else None)
        self.node_id = f"cn-{uuid.uuid4().hex[:8]}"
        self.started_at = _time.time()  # /health + cluster-view uptime
        from galaxysql_tpu.net.dn import SyncBus
        self.workers: Dict[tuple, object] = {}  # (host, port) -> WorkerClient
        # origin rides every RPC with the bus epoch: workers key their
        # last-applied sync epoch per coordinator (net/worker sync healing)
        self.sync_bus = SyncBus(origin=self.node_id)
        from galaxysql_tpu.meta.ha import HaManager
        self.ha = HaManager(self)
        from galaxysql_tpu.utils.metrics import (BATCH_GROUP_SIZE,
                                                 BATCH_WAIT_MS, BREAKER_OPENS,
                                                 DML_GROUP_SIZE, DML_WAIT_MS,
                                                 MetricsRegistry, QUERY_TIMEOUTS,
                                                 RETRY_BUDGET_EXHAUSTED,
                                                 RPC_FAILURES, RPC_RETRIES,
                                                 RPC_RTT_MS, SEGMENT_WALL_MS,
                                                 SPILL_BYTES, SPILL_FILES,
                                                 SYNC_FAILURES, SYNC_HEALS,
                                                 WORKER_FAILOVERS)
        from galaxysql_tpu.utils.tracing import ProfileRing, TraceIdAllocator
        # typed counter/gauge registry: SQL (information_schema.metrics,
        # SHOW METRICS), web (/metrics Prometheus text) and the legacy
        # engine-counter surface all render from here
        self.metrics = MetricsRegistry()
        # process-shared latency histograms (segment dispatch wall, worker RPC
        # round-trip) surface through this instance's registry; query latency
        # is per-instance and observed in Session._finish_query
        self.metrics.adopt(SEGMENT_WALL_MS)
        self.metrics.adopt(RPC_RTT_MS)
        self.metrics.adopt(BATCH_GROUP_SIZE)
        self.metrics.adopt(BATCH_WAIT_MS)
        self.metrics.adopt(DML_GROUP_SIZE)
        self.metrics.adopt(DML_WAIT_MS)
        # fault-tolerance plane counters (net/dn.py retry/breaker, SyncBus
        # healing, deadline kills) — process-shared, surfaced per instance
        for m in (RPC_RETRIES, RPC_FAILURES, BREAKER_OPENS, WORKER_FAILOVERS,
                  SYNC_FAILURES, SYNC_HEALS, QUERY_TIMEOUTS,
                  RETRY_BUDGET_EXHAUSTED, SPILL_BYTES, SPILL_FILES):
            self.metrics.adopt(m)
        self.metrics.histogram("query_latency_ms",
                               "end-to-end query latency (ms)")
        # node-prefixed trace-id mint: peer coordinators (sync_peer setups)
        # must never stamp two queries with one id
        self.trace_ids = TraceIdAllocator(self.node_id)
        # dict-like view over typed counters (engine_counters virtual table);
        # `counters["x"] += 1` call sites keep working unchanged
        self.counters = self.metrics.counter_map("engine")
        # cross-query fragment cache (exec/fragment_cache.py): versioned
        # hash-join build artifacts, deterministic subplan results, cached
        # runtime-filter publications.  Per-instance so multi-coordinator
        # tests stay isolated; frag_cache_* metrics ride this registry.
        from galaxysql_tpu.exec.fragment_cache import FragmentCache
        self.frag_cache = FragmentCache(metrics=self.metrics)
        # device lane cache observability: device_cache_* gauges alongside
        # the frag_cache_* family in SHOW METRICS / /metrics
        from galaxysql_tpu.exec.device_cache import GLOBAL_DEVICE_CACHE
        GLOBAL_DEVICE_CACHE.bind_metrics(self.metrics)
        # last-N per-query runtime profiles (information_schema.query_stats,
        # SHOW FULL STATS, web /query/<trace_id>)
        self.profiles = ProfileRing()
        # tail-sampled trace retention (utils/tracing.TraceStore): every
        # query's finish ramp offers its span tree — per-digest head sample
        # for healthy traces, always-keep for slow/shed/errored — into this
        # byte-budgeted per-node ring; the flight recorder and SHOW TRACE
        # cluster pulls read it
        from galaxysql_tpu.utils.tracing import TraceStore
        self.trace_store = TraceStore(
            budget_bytes=int(self.config.get("TRACE_STORE_BUDGET_BYTES")
                             or (4 << 20)),
            rate=float(self.config.get("TRACE_SAMPLE_RATE") or 0.0),
            node=self.node_id)
        # statement-digest workload-insight store (meta/statement_summary.py):
        # per digest x plan fingerprint time-windowed aggregates + the
        # plan-regression sentinel; fed by Session._finish_query
        from galaxysql_tpu.meta.statement_summary import StatementSummaryStore
        self.stmt_summary = StatementSummaryStore(self)
        # (schema, parameterized-sql) -> PointPlan: binder-free execution of
        # archetypal point SELECTs (DirectShardingKeyTableOperation analog)
        self.point_plans: Dict[tuple, object] = {}
        # (workload, engine) -> bound metric handles for Session._finish_query
        # (registry name-sanitize + lookup x4 per query is measurable at TP
        # serving rates; the handle tuple is immutable so plain dict is safe)
        self.finish_metrics: Dict[tuple, tuple] = {}
        # cross-session point-query batching (server/batch_scheduler.py):
        # plan-cache-identical point reads arriving within the collection
        # window coalesce into one vectorized dispatch per partition
        from galaxysql_tpu.server.batch_scheduler import BatchScheduler
        self.batch_scheduler = BatchScheduler(self)
        # cross-session DML batching (server/dml_batch.py): plan-identical
        # autocommit point writes coalesce into one vectorized flush with a
        # shared flush-time TSO, coalesced CDC/version bumps, and async
        # GSI/replica apply — the write-side mirror of the read batcher
        from galaxysql_tpu.server.dml_batch import DmlBatchScheduler
        self.dml_batch_scheduler = DmlBatchScheduler(self)
        # (schema, parameterized-sql) -> DML batch plan (write-side
        # PointPlans; server/dml_batch.try_register)
        self.dml_plans: Dict[tuple, dict] = {}
        # background applier for GSI maintenance + replica DML legs with
        # read-your-writes watermark fencing (txn/async_apply.py)
        from galaxysql_tpu.txn.async_apply import AsyncApplier
        self.applier = AsyncApplier(self)
        # columnar HTAP replica (storage/columnar.py): CDC-fed delta+base
        # tier serving large AP scans at a TSO watermark while TP stays on
        # the row store; sessions route through it in _run_query_admitted
        from galaxysql_tpu.storage.columnar import ColumnarReplicaManager
        self.columnar = ColumnarReplicaManager(self)
        # overload plane (server/admission.py): workload-class admission gate
        # (AIMD limits, deadline-aware shedding) + the memory-pressure
        # governor (tiered fragment-cache/spill/AP-refusal responses)
        from galaxysql_tpu.server.admission import AdmissionController
        self.admission = AdmissionController(self)
        # SLO plane (utils/metric_history.py + server/slo.py): bounded
        # delta-encoded history of every scalar this node exposes, and the
        # burn-rate / anomaly engine judging it.  Sampled by the maintain
        # loop via slo_tick(); workers run the same sampler over their own
        # registries and the `health` sync action pulls their snapshots.
        from galaxysql_tpu.utils.metric_history import MetricHistory
        self.metric_history = MetricHistory(self)
        from galaxysql_tpu.server.slo import SloEngine
        self.slo = SloEngine(self)
        # incident flight recorder (server/flight_recorder.py): watches the
        # event journal for trigger kinds on every slo_tick and snapshots
        # correlated evidence bundles into data_dir/incidents/
        from galaxysql_tpu.server.flight_recorder import FlightRecorder
        self.recorder = FlightRecorder(self)
        from galaxysql_tpu.server.maintain import RecycleBin
        self.recycle = RecycleBin(self)
        # elastic rebalancing (ddl/rebalance.py + server/balancer.py): the
        # in-memory half of live jobs' shadow partitions, and the heat-driven
        # proposal/execution policy the maintain loop ticks
        self.rebalance_shadows: Dict[str, object] = {}
        from galaxysql_tpu.server.balancer import Balancer
        self.balancer = Balancer(self)
        # physical placement bindings (server/placement.py): group label ->
        # worker endpoint / coordinator / device, persisted in the shared
        # metadb so MOVE PARTITION changes real locality cluster-wide
        from galaxysql_tpu.server.placement import PlacementBinding
        self.placement = PlacementBinding(self)
        # serving tier peer registry: node_id -> sync endpoint (sync_peer()
        # object or a dn-wire client to a remote coordinator's sync listener).
        # Maintained by attach_coordinator/detach_coordinator; the front
        # router (server/router.py) and the SHOW CLUSTER merges read it.
        self.coordinators: Dict[str, object] = {}
        # named for the lockdep witness (unranked class "instance"); a plain
        # RLock when lockdep is disarmed — the default
        from galaxysql_tpu.utils.lockdep import named_lock
        self.lock = named_lock("instance")
        self.next_conn_id = 1
        self.sessions: Dict[int, object] = {}
        self.catalog.create_schema("information_schema", if_not_exists=True)
        if boot:
            self.boot()

    def finish_handles(self, workload: str, engine: str) -> tuple:
        """(latency histogram, total/workload/engine counters) bound once per
        (workload, engine) — shared by Session._finish_query and the batch
        scheduler's bulk group finish."""
        handles = self.finish_metrics.get((workload, engine))
        if handles is None:
            m = self.metrics
            handles = (m.histogram("query_latency_ms",
                                   "end-to-end query latency (ms)"),
                       m.counter("queries_total", "queries executed"),
                       m.counter(f"queries_{workload.lower()}",
                                 f"{workload} workload queries"),
                       m.counter(f"engine_exec_{engine}",
                                 f"queries served by the {engine} engine"))
            self.finish_metrics[(workload, engine)] = handles
        return handles

    # -- boot ------------------------------------------------------------------

    def _reload_global_config(self, *_):
        """Pull persisted SET GLOBAL values from the shared metadb (fired by
        the config listener when a peer coordinator changes one)."""
        for k, v in self.metadb.kv_scan("config.param."):
            try:
                self.config.set_instance(k[len("config.param."):], json.loads(v))
            except Exception:
                continue  # an unknown/stale param must not poison boot

    def boot(self):
        """Load persisted metadata + data, then recover interrupted DDL jobs."""
        # persistent AOT compile cache: attach FIRST so every program traced
        # during/after boot can be replayed from disk on the next restart.
        # Booting without a data_dir DETACHES — the cache is process-global
        # and a later memory-only instance must not inherit another's dir.
        from galaxysql_tpu.exec.compile_cache import GLOBAL_COMPILE_CACHE
        if self.data_dir and self.config.get("ENABLE_COMPILE_CACHE"):
            GLOBAL_COMPILE_CACHE.attach(
                os.path.join(self.data_dir, "compile_cache"),
                budget=int(self.config.get("COMPILE_CACHE_BYTES")))
            GLOBAL_COMPILE_CACHE.bind_metrics(self.metrics)
        else:
            GLOBAL_COMPILE_CACHE.detach()
        self.planner.spm.attach(self.metadb)
        self.config_listener.bind("config.params", self._reload_global_config)
        self._reload_global_config()
        loaded = self.metadb.load_catalog(self.catalog)
        for tm in loaded:
            store = self.register_table(tm, persist=False)
            if self.data_dir:
                d = os.path.join(self.data_dir, tm.schema.lower(), tm.name.lower())
                if os.path.isdir(d):
                    store.load(d)
        # restore the checkpointed catalog counters: replaying schema loads
        # re-derives schema_version differently than the live history did,
        # which would silently invalidate every persisted SPM baseline (and
        # with them the self-heal quarantine state) on restart.  max() so the
        # counters never run backwards past the replayed DDL.
        v = self.metadb.kv_get("catalog.versions")
        if v:
            try:
                parts = json.loads(v)
                self.catalog.version = max(self.catalog.version,
                                           int(parts[0]))
                self.catalog.schema_version = max(self.catalog.schema_version,
                                                  int(parts[1]))
                if len(parts) > 2:  # added with the self-heal stats epoch
                    self.catalog.stats_version = max(
                        self.catalog.stats_version, int(parts[2]))
            except Exception:
                pass  # a corrupt counter record must not poison boot
        self.archive.attach(self.metadb)
        # columnar replicas restore AFTER stores/dictionaries load (persisted
        # stripe lanes hold dictionary codes) and resume tailing from the
        # checkpointed binlog seq
        self.columnar.load()
        # resolve provisional ±txn_id MVCC stamps left by a crash against the
        # durable tx log BEFORE anything reads the loaded partitions
        from galaxysql_tpu.txn.xa import recover_persisted
        recover_persisted(self)
        self.metadb.heartbeat(self.node_id, "coordinator", "127.0.0.1", 0)
        self.ddl_engine.recover()

    # -- store management ------------------------------------------------------

    def store_key(self, schema: str, table: str) -> str:
        return f"{schema.lower()}.{table.lower()}"

    def register_table(self, tm: TableMeta, persist: bool = True) -> TableStore:
        store = TableStore(tm)
        self.stores[self.store_key(tm.schema, tm.name)] = store
        if persist:
            self.metadb.save_table(tm)
        return store

    def drop_store(self, schema: str, table: str):
        self.stores.pop(self.store_key(schema, table), None)
        self.metadb.drop_table(schema, table)

    def store(self, schema: str, table: str) -> TableStore:
        return self.stores[self.store_key(schema, table)]

    # -- persistence -----------------------------------------------------------

    def save(self):
        """Flush all table data + metadata to disk (checkpoint)."""
        if not self.data_dir:
            return
        # pending async GSI/replica applies must land before the snapshot:
        # a checkpoint taken mid-apply would persist a base table whose GSI
        # rows exist only in the in-memory queue — and that queue has no
        # redo source, so saving anyway would freeze the divergence forever.
        # A wedged applier therefore fails the checkpoint LOUDLY.
        applier = getattr(self, "applier", None)
        if applier is not None and not applier.drain():
            raise errors.TddlError(
                "checkpoint aborted: async GSI/replica applies did not "
                "drain (backlog wedged); retry after the applier recovers")
        # marker time is captured BEFORE the store snapshots: a txn committing
        # while save() runs may have provisional stamps in an already-written
        # npz, so tx-log purge may only drop entries resolved before this point
        import time
        t0 = time.time()
        for key, store in self.stores.items():
            store.save(os.path.join(self.data_dir, key.replace(".", os.sep)))
            self.metadb.save_table(store.table)
        self.metadb.kv_put("last_checkpoint_at", repr(t0))
        # columnar replica checkpoint rides the same save: stripe lanes hold
        # dictionary codes, so persisting them beside the stores' own
        # dictionaries.json keeps the code spaces consistent on reload
        self.columnar.save()
        # catalog counters ride the checkpoint so a restarted coordinator
        # keeps its persisted SPM baselines + heal state valid (see boot())
        self.metadb.kv_put("catalog.versions", json.dumps(
            [self.catalog.version, self.catalog.schema_version,
             self.catalog.stats_version]))
        # AOT-serialize this process's steady-state programs alongside the
        # checkpoint; best-effort — a program that won't serialize must never
        # fail a data checkpoint
        try:
            from galaxysql_tpu.exec.compile_cache import GLOBAL_COMPILE_CACHE
            GLOBAL_COMPILE_CACHE.flush()
        except Exception:  # galaxylint: disable=swallow -- best-effort AOT flush: a serialization failure must never fail the data checkpoint (per-entry errors are already handled inside flush)
            pass

    def allocate_conn_id(self) -> int:
        with self.lock:
            cid = self.next_conn_id
            self.next_conn_id += 1
            return cid

    def worker_client(self, host: str, port: int):
        """Get-or-create the WorkerClient for an endpoint, configured from
        instance params (retry budget, breaker thresholds) and wired into the
        sync bus — the ONE constructor for coordinator->worker connections."""
        from galaxysql_tpu.net.dn import WorkerClient
        key = (host, port)
        client = self.workers.get(key)
        if client is None:
            # bind the live config: SET GLOBAL RPC_*/BREAKER_* hatches apply
            # to already-attached workers, not just future attachments
            client = WorkerClient(host, port, config=self.config)
            self.workers[key] = client
            self.sync_bus.attach(client)
        return client

    def worker_rows(self):
        """SHOW WORKERS / information_schema.workers row source: one row per
        attached worker with fence + circuit-breaker state and lifetime
        retry/failure counters."""
        rows = []
        for (host, port), client in sorted(self.workers.items()):
            bk = client.breaker_snapshot() if hasattr(client, "breaker_snapshot") \
                else {"state": "closed", "consec_failures": 0, "opens": 0,
                      "retries": 0, "failures": 0, "last_error": ""}
            budget = getattr(client, "retry_budget", None)
            rows.append((host, port, bk["state"],
                         1 if self.ha.worker_fenced((host, port)) else 0,
                         bk["consec_failures"], bk["retries"], bk["failures"],
                         bk["opens"], bk["last_error"],
                         int(budget.remaining()) if budget is not None else 0))
        return rows

    # -- SLO plane ------------------------------------------------------------

    def slo_tick(self, now: Optional[float] = None,
                 force: bool = False) -> bool:
        """One SLO-plane tick: take a history sample (interval-gated
        unless `force`) and, when one lands, burn-rate every objective
        and rate-anomaly every counter.  Driven by the maintain loop on
        every poll (per-node — NOT leader-gated like scheduled jobs) and
        by tests with synthetic `now` stamps.  Advisory: never raises."""
        try:
            mh = self.metric_history
            sampled = mh.sample(now=now) if force else mh.maybe_sample(now=now)
            if sampled is None:
                return False
            self.slo.evaluate(now=now)
            rec = getattr(self, "recorder", None)
            if rec is not None:
                rec.tick(now=now)
            return True
        except Exception:  # galaxylint: disable=swallow -- advisory plane: a sampler fault must never affect serving (pragma: no cover)
            return False

    def cluster_health(self, pull: bool = True):
        """Cluster-wide health rows: this coordinator first, then one row
        per attached worker.  `pull=True` issues the `health` sync action
        (fresh per-worker sampler snapshots; an unreachable worker gets an
        UNREACHABLE row, never an exception); `pull=False` renders from
        piggybacked reply telemetry only — info_schema refresh uses that
        so a wedged worker cannot stall a catalog query."""
        mh = self.metric_history
        burning = self.slo.burning_names()
        rows = [(self.node_id, "coordinator", "local",
                 "BURNING" if burning else "OK",
                 1 if self.ha.is_leader() else 0,
                 round(_time.time() - self.started_at, 3),
                 float(len(getattr(self, "sessions", []) or [])),
                 round(mh.rate("queries_total"), 3),
                 round(mh.rate("query_errors"), 6),
                 int(self.admission.governor.tier()),
                 ",".join(burning), int(mh.summary()["samples"]))]
        for (host, port), client in sorted(self.workers.items()):
            addr = f"{host}:{port}"
            fenced = self.ha.worker_fenced((host, port))
            if pull:
                try:
                    resp = client.sync_action("health", {})
                except Exception:  # galaxylint: disable=swallow -- the UNREACHABLE row below IS the failure report; the sync client journals breaker state
                    resp = None
                if not (isinstance(resp, dict) and resp.get("ok")):
                    rows.append((addr, "worker", addr, "UNREACHABLE",
                                 0, 0.0, 0.0, 0.0, 0.0, 0,
                                 "", 0))
                    continue
                rows.append((resp.get("node", addr), "worker", addr,
                             "FENCED" if fenced else "OK", 0,
                             round(float(resp.get("uptime_s", 0.0)), 3),
                             float(resp.get("active", 0)),
                             round(float(resp.get("qps", 0.0)), 3),
                             round(float(resp.get("error_rate", 0.0)), 6),
                             int(resp.get("mem_tier", 0)), "",
                             int(resp.get("samples", 0))))
            else:
                rows.append((addr, "worker", addr,
                             "FENCED" if fenced else "OK", 0,
                             round(float(getattr(client, "load_up", 0.0)), 3),
                             float(getattr(client, "load_q", 0) or 0),
                             0.0, 0.0,
                             int(getattr(client, "load_tier", 0) or 0), "",
                             int(getattr(client, "load_samples", 0) or 0)))
        return rows

    def attach_remote_table(self, schema: str, name: str, host: str,
                            port: int):
        """Register a worker-process table: scans compile to shipped SQL
        (MyJdbcHandler.java:691 plan-shipping seam).  The worker is also wired
        into the sync-action bus and the HA prober."""
        from galaxysql_tpu.types import datatype as dt
        from galaxysql_tpu.meta.catalog import ColumnMeta, TableMeta, SINGLE
        client = self.worker_client(host, port)
        resp = client.sync_action("table_meta", {"schema": schema,
                                                 "table": name})
        # (re)attachment is the reconnect point: resolve any XA branches this
        # worker holds in doubt against our commit-point log (XARecoverTask)
        try:
            self.xa_coordinator.recover_remote()
        except Exception:
            pass
        cols = [ColumnMeta(n, dt.from_sql_name(t, p or 0, s or 0), nullable)
                for n, t, p, s, nullable in resp["columns"]]
        tm = TableMeta(schema, name, cols, resp.get("primary_key") or [],
                       SINGLE)
        tm.remote = {"host": host, "port": port}
        self.catalog.create_schema(schema, if_not_exists=True)
        if not self.catalog.add_table(tm, if_not_exists=True):
            # re-attach (worker restarted on a new port): repoint the existing
            # meta so in-flight plans route to the live endpoint
            tm = self.catalog.table(schema, name)
            tm.remote = {"host": host, "port": port}
        return tm

    def attach_replica(self, schema: str, name: str, host: str, port: int,
                       weight: int = 1, backfill: Optional[bool] = None):
        """Register a read replica for a remote table (read-write splitting,
        `TGroupDataSource` weighted-random analog).  Writes go to every live
        endpoint as branches of the same distributed txn (synchronous
        replication); reads pick a weighted-random unfenced endpoint.

        A replica must hold the table's data BEFORE it serves reads:
        `backfill=None` (default) copies from the primary when the replica's
        table is missing or empty and trusts a pre-seeded identical copy
        otherwise; True forces the copy (rebuilding a STALE replica requires
        it); False trusts the caller unconditionally."""
        key = (host, port)
        client = self.worker_client(host, port)
        tm = self.catalog.table(schema, name)
        if getattr(tm, "remote", None) is None:
            raise errors.NotSupportedError(
                f"{schema}.{name} is not a remote table")
        entry = next((r for r in tm.replicas
                      if (r["host"], r["port"]) == key), None)
        if entry is not None and entry.get("stale") and backfill is not True:
            raise errors.TddlError(
                f"replica {key} is stale (missed writes); re-attach with "
                f"backfill=True to rebuild it")
        if backfill is None:
            backfill = self._replica_needs_backfill(client, schema, name)
        # the copy AND the routing registration sit under one EXCLUSIVE MDL:
        # a write committing between the snapshot read and registration would
        # otherwise reach only the primary — a replica registered one row
        # short serves wrong reads forever (writes replicate per-statement to
        # replicas registered at statement time, session._remote_dml)
        with self.mdl.exclusive(f"{schema.lower()}.{name.lower()}"):
            if backfill:
                self._backfill_replica(client, schema, name)
            if entry is not None:
                entry["weight"] = weight
                entry["stale"] = False
                return tm
            tm.replicas.append({"host": host, "port": port, "weight": weight,
                                "stale": False})
        return tm

    def _replica_needs_backfill(self, client, schema: str, name: str) -> bool:
        try:
            _cols, _types, data, _valid = client.execute(
                f"SELECT count(*) FROM {name}", schema)
            lane = next(iter(data.values())) if data else None
            return lane is None or lane.size == 0 or int(lane[0]) == 0
        except Exception:
            return True  # table (or schema) missing on the replica

    def _backfill_replica(self, client, schema: str, name: str):
        """Snapshot copy primary -> replica under shared MDL (writes keep
        flowing; they also ship to the replica's branch once registered, and
        registration happens only after this copy completes)."""
        tm = self.catalog.table(schema, name)
        src = self.workers[(tm.remote["host"], tm.remote["port"])]
        cols_sql = ", ".join(
            f"{c.name} {c.dtype.sql_name()}" + ("" if c.nullable else " NOT NULL")
            for c in tm.columns)
        pk_sql = (f", PRIMARY KEY ({', '.join(tm.primary_key)})"
                  if tm.primary_key else "")
        # IF NOT EXISTS makes these textually idempotent -> retry-safe
        client.execute(f"CREATE DATABASE IF NOT EXISTS {schema}", "",
                       idem=True)
        client.execute(
            f"CREATE TABLE IF NOT EXISTS {name} ({cols_sql}{pk_sql})", schema,
            idem=True)
        cols = tm.column_names()
        # caller (attach_replica) holds the exclusive MDL: no concurrent DML
        names, types, data, valid = src.exec_plan(
            {"schema": schema, "table": name, "columns": cols})
        self._bulk_insert_remote(client, schema, name, names, types,
                                 data, valid)

    @staticmethod
    def _sql_literal(typ: str, v, valid: bool) -> str:
        if not valid:
            return "NULL"
        if typ.endswith("#scaled"):
            import re as _re
            m = _re.search(r"DECIMAL\(\d+,\s*(\d+)\)", typ)
            scale = int(m.group(1)) if m else 0
            s = str(int(v))
            neg = s.startswith("-")
            s = s.lstrip("-").rjust(scale + 1, "0")
            val = (s[:-scale] + "." + s[-scale:]) if scale else s
            return ("-" if neg else "") + val
        if isinstance(v, (int, float)):
            return repr(v)
        return "'" + str(v).replace("\\", "\\\\").replace("'", "''") + "'"

    def _bulk_insert_remote(self, client, schema, table, names, types,
                            data, valid, batch: int = 1000):
        n = len(next(iter(data.values()))) if data else 0
        for off in range(0, n, batch):
            hi = min(off + batch, n)
            rows = []
            for i in range(off, hi):
                vals = []
                for c, ty in zip(names, types):
                    ok_ = bool(valid[c][i]) if c in valid else True
                    vals.append(self._sql_literal(ty, data[c][i], ok_))
                rows.append("(" + ", ".join(vals) + ")")
            # uid-stamped: a reconnect retry of a backfill batch replays the
            # recorded result (worker dedupe window) instead of double-
            # inserting rows into the replica
            client.execute(f"INSERT INTO {table} ({', '.join(names)}) "
                           f"VALUES {', '.join(rows)}", schema,
                           uid=f"{self.node_id}:{self.trace_ids.next()}")

    def move_remote_table(self, schema: str, name: str, host: str, port: int):
        """Relocate a worker-resident table to another worker online.

        Reference analog: `executor/balancer/Balancer.java` data movement +
        the repartition backfill/catchup/cutover shape (ddl/repartition.py):

        1. snapshot backfill under SHARED MDL (writes keep flowing to the
           source),
        2. delta catchup + cutover under EXCLUSIVE MDL: rows inserted/deleted
           since the snapshot are replayed onto the target, then the table's
           primary endpoint swaps."""
        tm = self.catalog.table(schema, name)
        if getattr(tm, "remote", None) is None:
            raise errors.NotSupportedError(
                f"{schema}.{name} is not a remote table")
        src = self.workers[(tm.remote["host"], tm.remote["port"])]
        dst = self.worker_client(host, port)
        # target bootstrap: schema + table shape from this CN's meta
        cols_sql = ", ".join(
            f"{c.name} {c.dtype.sql_name()}" + ("" if c.nullable else " NOT NULL")
            for c in tm.columns)
        pk_sql = (f", PRIMARY KEY ({', '.join(tm.primary_key)})"
                  if tm.primary_key else "")
        dst.execute(f"CREATE DATABASE IF NOT EXISTS {schema}", "", idem=True)
        dst.execute(f"CREATE TABLE IF NOT EXISTS {name} ({cols_sql}{pk_sql})",
                    schema, idem=True)
        cols = tm.column_names()
        mdl_key = f"{schema.lower()}.{name.lower()}"
        pk = tm.primary_key[0] if tm.primary_key else cols[0]
        # phase 1: snapshot backfill (shared MDL: concurrent writes continue)
        with self.mdl.shared({mdl_key}):
            s0 = self.tso.next_timestamp()
            names, types, data, valid = src.exec_plan(
                {"schema": schema, "table": name, "columns": cols})
            self._bulk_insert_remote(dst, schema, name, names, types, data,
                                     valid)
        # phase 2: delta catchup + cutover (exclusive MDL: writes drained)
        with self.mdl.exclusive(mdl_key):
            # drain OPEN txns holding branches on the source worker: their
            # commits bypass MDL (statement-scoped) and would land on the old
            # primary after cutover — a silently lost write.  New DML is
            # blocked on our exclusive MDL, so waiting converges.
            import time as _time
            src_addr = (src.addr[0], src.addr[1])
            deadline = _time.time() + 30.0
            def _pinned():
                for sess in list(self.sessions.values()):
                    txn = getattr(sess, "txn", None)
                    if txn is not None and src_addr in getattr(txn, "remote", {}):
                        return True
                with self.xa_coordinator._lock:
                    for parts in self.xa_coordinator._in_doubt.values():
                        for sp in parts:
                            if getattr(sp, "addr", None) == src_addr:
                                return True
                return False
            while _pinned():
                if _time.time() > deadline:
                    raise errors.TddlError(
                        f"move {schema}.{name}: open transactions pin the "
                        f"source worker {src_addr}; retry later")
                _time.sleep(0.05)
            # delta window widened by a margin: a txn may DRAW its commit_ts
            # before s0 yet stamp the worker's lanes after the phase-1 read
            # (commit_ts issue and stamp application are not atomic).  The
            # delta apply is idempotent (delete-by-PK before insert), so
            # re-copying recent rows is safe; the margin only costs re-copy
            # volume.  10 minutes of physical TSO covers any realistic
            # prepare->stamp descheduling.
            from galaxysql_tpu.meta.tso import LOGICAL_BITS
            margin = 600_000 << LOGICAL_BITS  # 10 min of wall clock
            resp, arrs = src.request(
                {"op": "exec_plan",
                 "fragment": {"schema": schema, "table": name,
                              "columns": cols, "since": max(s0 - margin, 0),
                              "deleted_since_of": pk}})
            ddata = {c: arrs[f"d::{c}"] for c in cols}
            dvalid = {c: arrs[f"v::{c}"] for c in cols if f"v::{c}" in arrs}
            gone = arrs.get("deleted::keys")
            new_keys = list(ddata[pk].tolist()) if cols else []
            drop = set(new_keys) | set(gone.tolist() if gone is not None else [])
            if drop:
                # literal rendering follows the PK's wire type (scaled
                # decimals, quoted strings/dates) — the same encoding the
                # backfill INSERTs used, so the DELETE actually matches
                pk_type = dict(zip(resp["columns"], resp["types"]))[pk]
                in_list = ", ".join(self._sql_literal(pk_type, k, True)
                                    for k in drop)
                # the delta apply is idempotent by construction (delete-by-PK
                # before re-insert), so the DELETE is retry-safe
                dst.execute(f"DELETE FROM {name} WHERE {pk} IN ({in_list})",
                            schema, idem=True)
            self._bulk_insert_remote(dst, schema, name, resp["columns"],
                                     resp["types"], ddata, dvalid)
            tm.remote = {"host": host, "port": port}
            self.catalog.bump_schema()
        self.counters.inc("table_moves")
        return tm

    def try_revive_worker(self, addr) -> bool:
        """Lazy fence revival: ONE ping decides whether a fenced endpoint
        recovered (no background prober exists in production — fencing must
        not be forever).  Returns True when the endpoint is now unfenced.
        Shared by read routing and the remote-DML primary gate so the HA
        policy lives in one place."""
        client = self.workers.get(addr)
        if client is None or not self.ha.worker_fenced(addr):
            return False
        if client.ping(timeout=2.0):
            self.ha.fence_worker(addr, False)
            return True
        return False

    def read_endpoint(self, tm):
        """Pick the endpoint to serve a read of `tm`: weighted random over the
        primary + non-stale replicas, skipping fenced workers.  Returns
        (addr, client) or raises if every endpoint is down."""
        import random
        from galaxysql_tpu.utils import errors as _errors
        cands = [((tm.remote["host"], tm.remote["port"]),
                  tm.remote.get("weight", 1))]
        for r in tm.replicas:
            if not r.get("stale"):
                cands.append(((r["host"], r["port"]), r.get("weight", 1)))
        # breaker-blocked endpoints (open + cooling down) are as good as
        # fenced for routing: picking one would only fast-fail and burn a
        # failover attempt.  A cooled-down breaker stays routable — the next
        # request half-opens it with a ping probe.
        live = [(a, w) for a, w in cands
                if a in self.workers and not self.ha.worker_fenced(a) and
                not getattr(self.workers[a], "breaker_blocked",
                            lambda: False)()]
        if not live:
            # lazy fence revival: fencing has no background prober in
            # production, so before refusing, ping each fenced candidate
            # once and unfence responders (a recovered worker serves again
            # at the first read that needs it)
            for a, w in cands:
                if self.try_revive_worker(a):
                    live.append((a, w))
        if not live:
            raise _errors.WorkerUnavailableError(
                f"remote table {tm.name}: every endpoint is fenced/unattached")
        # backpressure-aware weighting: endpoints that piggybacked a deep
        # queue or an elevated memory tier in recent replies are
        # deprioritized (never excluded — a uniformly-pressured fleet must
        # still serve).  Stale load reports (>5s) decay to neutral.
        import time as _t
        now = _t.time()
        # physical-placement locality: the endpoint bound to this table's
        # dominant group (server/placement.py) gets a 4x boost — MOVE
        # PARTITION into a bound group shifts real read traffic, but a
        # mis-bound group can never black-hole reads (boost, not filter)
        preferred = None
        placement = getattr(self, "placement", None)
        if placement is not None and len(live) > 1:
            try:
                preferred = placement.preferred_endpoint(tm)
            except Exception:  # galaxylint: disable=swallow -- locality is advisory: a placement fault must never fail a read
                preferred = None

        def _load_weight(a, w):
            c = self.workers.get(a)
            if a == preferred:
                w = w * 4.0
            if c is None or now - getattr(c, "load_at", 0.0) > 5.0:
                return float(w)
            penalty = 1.0 + getattr(c, "load_q", 0) \
                + 4.0 * getattr(c, "load_tier", 0)
            return float(w) / penalty

        live = [(a, _load_weight(a, w)) for a, w in live]
        total = sum(w for _, w in live)
        pick = random.random() * total
        for a, w in live:
            pick -= w
            if pick <= 0:
                return a, self.workers[a]
        return live[-1][0], self.workers[live[-1][0]]

    def apply_sync_action(self, action: str, payload: dict) -> dict:
        """Coordinator-side receiver of sync-bus actions (the CN twin of
        net/worker.Worker._sync): peer coordinators attached to each other's
        SyncBus via `sync_peer()` invalidate caches without sharing memory."""
        payload = payload or {}
        if action == "invalidate_fragment_cache":
            key = payload.get("table_key") or \
                f"{payload.get('schema', '').lower()}.{payload.get('table', '').lower()}"
            self.frag_cache.bump_epoch(key)
            return {"ok": True, "action": action, "node": self.node_id}
        if action == "invalidate_plan_cache":
            self.planner.cache.invalidate_all()
            return {"ok": True, "action": action, "node": self.node_id}
        if action == "invalidate_privilege_cache":
            self.privileges.invalidate_cache()
            return {"ok": True, "action": action, "node": self.node_id}
        if action == "health":
            # peer coordinators answer the same health pull workers do.
            # The serving tier rides extra freight on this one action:
            # - inbound `peer_admission` {node: snapshot} gossip is ingested
            #   (the router acts as gossip hub, relaying every peer's
            #   admission state to every other peer), and
            # - the reply carries this node's own admission snapshot, sync
            #   epoch, served placement groups, steady-state retrace count,
            #   and — on request via `want` — bounded statement-summary /
            #   metrics rollups for the SHOW CLUSTER merges.
            mh = self.metric_history
            mh.maybe_sample()
            for node, snap in (payload.get("peer_admission") or {}).items():
                self.admission.note_peer(node, snap)
            reply = {"ok": True, "action": action, "node": self.node_id,
                     "uptime_s": round(_time.time() - self.started_at, 3),
                     "active": float(len(self.sessions)),
                     "qps": round(mh.rate("queries_total"), 3),
                     "error_rate": round(mh.rate("query_errors"), 6),
                     "mem_tier": int(self.admission.governor.tier()),
                     "samples": int(mh.summary()["samples"]),
                     "burning": self.slo.burning_names(),
                     "epoch": int(self.sync_bus.epoch),
                     "admission": self.admission.cluster_snapshot(),
                     "groups": [g.strip().lower() for g in
                                str(self.config.get("COORDINATOR_GROUPS")
                                    or "").split(",") if g.strip()],
                     "retraces": self._retrace_count()}
            want = payload.get("want") or []
            if "statement_summary" in want:
                reply["statement_summary"] = \
                    [list(r) for r in self.stmt_summary.rows()[:256]]
            if "metrics" in want:
                reply["metrics"] = [[n, k, float(v), h] for n, k, v, h
                                    in self.metrics.rows()[:512]]
            if "traces" in want:
                reply["traces"] = [rt.to_dict() for rt in
                                   self.trace_store.entries(limit=64)]
            # exact-id trace pull: the router grafts a routed statement's
            # peer-side span tree back into its own context (ISSUE 20
            # cluster propagation), same want-freight pattern as above
            tid = payload.get("trace_id")
            if tid is not None:
                rt = self.trace_store.get(tid)
                reply["trace"] = rt.to_dict() if rt is not None else None
            return reply
        return {"ok": False, "error": f"unknown sync action {action!r}"}

    @staticmethod
    def _retrace_count() -> int:
        """Process-lifetime XLA retrace count (exec compile stats) — the
        scale-out bench asserts this stays flat per peer at steady state."""
        try:
            from galaxysql_tpu.exec.operators import COMPILE_STATS
            return int(COMPILE_STATS.get("retraces", 0))
        except Exception:  # galaxylint: disable=swallow -- a health reply must not fail because compile stats moved; 0 reads as "unknown"
            return 0

    # -- serving tier (peer coordinators) --------------------------------------

    def attach_coordinator(self, node_id: str, peer) -> None:
        """Register a peer coordinator: `peer` is any sync endpoint
        (`sync_peer()` object in-process, or a dn-wire client pointed at the
        peer's sync listener).  The peer joins this instance's SyncBus so
        cache-invalidation broadcasts reach it, and the admission/gossip and
        SHOW CLUSTER planes start seeing it."""
        from galaxysql_tpu.utils import events
        self.coordinators[node_id] = peer
        self.sync_bus.attach(peer)
        events.publish("coordinator_joined",
                       f"peer coordinator {node_id} joined the serving tier",
                       node=self.node_id, peer=node_id)

    def detach_coordinator(self, node_id: str, reason: str = "detach") -> None:
        peer = self.coordinators.pop(node_id, None)
        if peer is None:
            return
        with self.sync_bus._lock:
            if peer in self.sync_bus.workers:
                self.sync_bus.workers.remove(peer)
        self.admission.forget_peer(node_id)
        from galaxysql_tpu.utils import events
        events.publish("coordinator_left",
                       f"peer coordinator {node_id} left the serving tier "
                       f"({reason})", node=self.node_id, peer=node_id,
                       reason=reason)

    def coordinator_rows(self, pull: bool = True):
        """SHOW COORDINATORS / information_schema.coordinators row source:
        this node first, then every registered peer.  `pull=True` issues a
        fresh health sync per peer (UNREACHABLE rows, never errors);
        `pull=False` renders from the last gossip snapshots only."""
        router = getattr(self, "router", None)
        adm = self.admission
        gossip_age = {n: age for n, _s, age in adm.peer_gossip_rows()}

        def _aff(node):
            if router is None:
                return 0, 0, 0.0
            return router.affinity_of(node)

        routed, hits, ratio = _aff(self.node_id)
        rows = [(self.node_id, "local", "OK", int(self.sync_bus.epoch),
                 round(adm.effective_limit("TP"), 1),
                 round(adm.effective_limit("AP"), 1),
                 float(len(adm._tokens["TP"])), float(len(adm._tokens["AP"])),
                 routed, round(ratio, 4), -1.0)]
        for node_id, peer in sorted(self.coordinators.items()):
            routed, hits, ratio = _aff(node_id)
            age = round(gossip_age.get(node_id, -1.0), 3)
            resp = None
            if pull:
                try:
                    resp = peer.sync_action("health", {})
                except Exception:  # galaxylint: disable=swallow -- the UNREACHABLE row below IS the failure report
                    resp = None
            else:
                snap = next((s for n, s, _a in adm.peer_gossip_rows()
                             if n == node_id), None)
                if snap is not None:
                    resp = {"ok": True, "admission": snap, "epoch": -1}
            if not (isinstance(resp, dict) and resp.get("ok")):
                rows.append((node_id, "peer", "UNREACHABLE", -1,
                             0.0, 0.0, 0.0, 0.0, routed, round(ratio, 4),
                             age))
                continue
            snap = resp.get("admission") or {}
            tp, ap = snap.get("tp") or {}, snap.get("ap") or {}
            rows.append((resp.get("node", node_id), "peer", "OK",
                         int(resp.get("epoch", -1)),
                         float(tp.get("limit", 0.0)),
                         float(ap.get("limit", 0.0)),
                         float(tp.get("inflight", 0)),
                         float(ap.get("inflight", 0)),
                         routed, round(ratio, 4), age))
        return rows

    def sync_peer(self):
        """In-process SyncBus endpoint for this instance: attach the returned
        object to a PEER coordinator's `sync_bus` and that peer's broadcasts
        (fragment/plan-cache invalidation) apply here — the multi-coordinator
        invalidation plane without a socket in between."""
        inst = self

        class _Peer:
            def sync_action(self, action: str, payload: dict) -> dict:
                return inst.apply_sync_action(action, payload)

            def ping(self, timeout: float = 5.0) -> bool:
                return True

        return _Peer()

    def mesh(self):
        """The instance's device mesh for MPP execution (None on a single device)."""
        if not hasattr(self, "_mesh"):
            import jax
            try:
                devs = jax.devices()
            except RuntimeError:
                devs = []
            if len(devs) > 1:
                from galaxysql_tpu.parallel.mesh import make_mesh
                self._mesh = make_mesh(devices=devs)
            else:
                self._mesh = None
        return self._mesh
