"""SLO engine: declarative objectives + multi-window burn-rate alerting.

Objectives are evaluated against the node's :class:`MetricHistory`
(utils/metric_history.py) on every sample tick, never on the query
path.  Two window lengths — fast (``SLO_FAST_WINDOW_SAMPLES``) and
slow (``SLO_SLOW_WINDOW_SAMPLES``), both expressed in *samples* so the
wall-clock windows scale with ``METRIC_HISTORY_INTERVAL_S`` and tests
can drive the whole burn/recover cycle with synthetic tick timestamps
in milliseconds — give the classic multi-window burn-rate rule:

* BURNING when fast-window burn >= ``SLO_BURN_FAST`` **and**
  slow-window burn >= ``SLO_BURN_SLOW`` (fast window catches the page,
  slow window suppresses blips);
* RECOVERED when the fast-window burn falls back under 1.0.

Transitions publish typed ``slo_burn`` / ``slo_recovered`` journal
events (severity ``critical`` at >= 2x the fast threshold, else
``warn``) and the ``slo_burn_active`` gauge tracks how many objectives
are currently burning.

Alongside the declarative objectives, a robust-EWMA anomaly detector
rates every counter in the history (retrace storms, breaker flaps,
shed spikes) and publishes ``metric_anomaly`` events when a rate blows
past ``mean + ANOMALY_SIGMA * mean-abs-deviation``.  Detection only:
the whole engine is advisory — it can journal, never fail a query.

SQL-created objectives (``CREATE SLO ... WITH ...``) persist in the
metadb kv space under ``slo.def.<name>`` and reload on restart, so a
tenant objective survives a coordinator bounce like CCL rules do.
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from galaxysql_tpu.utils import events

_KV_PREFIX = "slo.def."
_KINDS = ("latency_p99", "error_ratio", "columnar_lag")


@dataclass
class SloDef:
    """One objective.  ``param`` names a config param to read the target
    from live (built-in defaults track SET GLOBAL); SQL-created SLOs
    carry a literal ``target``."""
    name: str
    kind: str                       # latency_p99 | error_ratio
    target: Optional[float] = None  # literal target (SQL-created)
    param: Optional[str] = None     # config param backing the target
    schema: str = ""                # "" = all schemas
    workload: str = ""              # "TP" | "AP" | "" = all classes
    source: str = "sql"             # default | sql

    def resolve_target(self, config) -> float:
        if self.param:
            try:
                return float(config.get(self.param))
            except (TypeError, ValueError):
                pass  # unparsable SET value: fall through to the literal
        return float(self.target or 0.0)


@dataclass
class _Status:
    burning: bool = False
    since: float = 0.0
    fast_burn: float = 0.0
    slow_burn: float = 0.0
    measured: float = 0.0


@dataclass
class _AnomalyState:
    mean: float = 0.0
    dev: float = 0.0
    n: int = 0
    firing: bool = False


_DEFAULTS = (
    SloDef("tp_latency_p99", "latency_p99", param="SLO_TP_P99_MS",
           workload="TP", source="default"),
    SloDef("ap_latency_p99", "latency_p99", param="SLO_AP_P99_MS",
           workload="AP", source="default"),
    SloDef("typed_error_ratio", "error_ratio", param="SLO_ERROR_RATIO",
           source="default"),
    # HTAP freshness (ISSUE 20 satellite): the columnar replica's apply lag
    # joins the burn engine — a wedged tailer burns like a latency storm
    SloDef("columnar_freshness", "columnar_lag", param="SLO_COLUMNAR_LAG_MS",
           source="default"),
)


class SloEngine:
    def __init__(self, instance):
        self.instance = instance
        self._lock = threading.Lock()
        self._slos: Dict[str, SloDef] = {}
        self._status: Dict[str, _Status] = {}
        self._anom: Dict[str, _AnomalyState] = {}
        self._gauge = instance.metrics.gauge(
            "slo_burn_active", "objectives currently burning on this node")
        for d in _DEFAULTS:
            self._slos[d.name] = d
            self._status[d.name] = _Status()
        self._load_persisted()

    # -- definition management -------------------------------------------------

    def _load_persisted(self):
        try:
            rows = self.instance.metadb.kv_scan(_KV_PREFIX)
        except Exception:  # galaxylint: disable=swallow -- a metadb without a kv space still serves the built-in objectives; persistence is additive
            return
        for _key, raw in rows:
            try:
                d = json.loads(raw)
                slo = SloDef(name=d["name"], kind=d["kind"],
                             target=d.get("target"),
                             schema=d.get("schema", ""),
                             workload=d.get("workload", ""), source="sql")
                with self._lock:
                    self._slos[slo.name] = slo
                    self._status.setdefault(slo.name, _Status())
            except Exception:  # galaxylint: disable=swallow -- one corrupt persisted SLO row must not block loading the rest
                continue

    def create_sql(self, stmt) -> SloDef:
        """CREATE SLO dispatch target (session.py).  Exactly one of
        TARGET_P99_MS / ERROR_RATIO picks the kind."""
        from galaxysql_tpu.utils import errors
        name = stmt.name.lower()
        with self._lock:
            exists = name in self._slos
        if exists:
            if stmt.if_not_exists:
                return self._slos[name]
            raise errors.TddlError(f"SLO '{name}' already exists")
        if (stmt.p99_ms is None) == (stmt.error_ratio is None):
            raise errors.TddlError(
                "CREATE SLO requires exactly one of TARGET_P99_MS or "
                "ERROR_RATIO")
        if stmt.p99_ms is not None:
            kind, target = "latency_p99", float(stmt.p99_ms)
            workload = (stmt.workload or "TP").upper()
        else:
            kind, target = "error_ratio", float(stmt.error_ratio)
            workload = (stmt.workload or "").upper()
        if target <= 0:
            raise errors.TddlError("SLO target must be > 0")
        if workload not in ("", "TP", "AP"):
            raise errors.TddlError(f"unknown SLO class '{workload}'")
        slo = SloDef(name=name, kind=kind, target=target,
                     schema=(stmt.schema or "").lower(), workload=workload,
                     source="sql")
        with self._lock:
            self._slos[name] = slo
            self._status[name] = _Status()
        try:
            self.instance.metadb.kv_put(_KV_PREFIX + name, json.dumps({
                "name": name, "kind": kind, "target": target,
                "schema": slo.schema, "workload": workload}))
        except Exception:  # galaxylint: disable=swallow -- persistence is best-effort: the in-memory objective is already live and judged
            pass
        return slo

    def drop_sql(self, name: str, if_exists: bool = False):
        from galaxysql_tpu.utils import errors
        name = name.lower()
        with self._lock:
            slo = self._slos.pop(name, None)
            self._status.pop(name, None)
        if slo is None:
            if if_exists:
                return
            raise errors.TddlError(f"unknown SLO '{name}'")
        if slo.source == "sql":
            try:
                self.instance.metadb.kv_delete(_KV_PREFIX + name)
            except Exception:  # galaxylint: disable=swallow -- best-effort unpersist: the objective is already gone from evaluation
                pass
        self._refresh_gauge()

    def defs(self) -> List[SloDef]:
        with self._lock:
            return [self._slos[n] for n in sorted(self._slos)]

    # -- measurement -----------------------------------------------------------

    def _latency_metric(self, slo: SloDef) -> str:
        wl = (slo.workload or "TP").lower()
        if slo.schema:
            return f"stmt_tenant_{slo.schema}_{wl}_recent_p99_ms"
        return f"stmt_class_{wl}_recent_p99_ms"

    def _error_metrics(self, slo: SloDef) -> Tuple[str, str]:
        if slo.schema or slo.workload:
            wl = (slo.workload or "TP").lower()
            base = (f"stmt_tenant_{slo.schema}_{wl}" if slo.schema
                    else f"stmt_class_{wl}")
            return f"{base}_errors", f"{base}_execs"
        return "query_errors", "queries_total"

    def _burn(self, slo: SloDef, target: float, window: int) -> Tuple[float, float]:
        """(burn ratio, measured value) over the last ``window`` samples."""
        hist = self.instance.metric_history
        if target <= 0:
            return 0.0, 0.0
        if slo.kind == "latency_p99":
            measured = hist.mean(self._latency_metric(slo), samples=window)
            return measured / target, measured
        if slo.kind == "columnar_lag":
            measured = hist.mean("columnar_lag_ms", samples=window)
            return measured / target, measured
        err_name, tot_name = self._error_metrics(slo)
        errs = hist.series(err_name, samples=window)
        tots = hist.series(tot_name, samples=window)
        if len(errs) < 2 or len(tots) < 2:
            return 0.0, 0.0
        d_err = errs[-1][1] - errs[0][1]
        d_tot = tots[-1][1] - tots[0][1]
        if d_tot <= 0:
            return 0.0, 0.0
        ratio = max(0.0, d_err) / d_tot
        return ratio / target, ratio

    # -- evaluation ------------------------------------------------------------

    def evaluate(self, now: Optional[float] = None):
        """One tick: burn-rate every objective, then rate-anomaly every
        counter.  Called by Instance.slo_tick right after a history
        sample lands; advisory, so any internal error is swallowed
        after journaling through the typed path it owns."""
        if now is None:
            import time
            now = time.time()
        try:
            self._evaluate_slos(now)
        except Exception:  # galaxylint: disable=swallow -- advisory plane: a broken objective must not fail the maintain tick (pragma: no cover)
            pass
        try:
            self._evaluate_anomalies(now)
        except Exception:  # galaxylint: disable=swallow -- advisory plane: detector errors must not fail the maintain tick (pragma: no cover)
            pass

    def _evaluate_slos(self, now: float):
        cfg = self.instance.config
        hist = self.instance.metric_history
        fast_n = max(2, int(cfg.get("SLO_FAST_WINDOW_SAMPLES")))
        slow_n = max(fast_n, int(cfg.get("SLO_SLOW_WINDOW_SAMPLES")))
        fast_thresh = float(cfg.get("SLO_BURN_FAST"))
        slow_thresh = float(cfg.get("SLO_BURN_SLOW"))
        n_samples = int(hist.summary()["samples"])
        for slo in self.defs():
            st = self._status.setdefault(slo.name, _Status())
            target = slo.resolve_target(cfg)
            fast, measured = self._burn(slo, target, fast_n)
            slow, _ = self._burn(slo, target, slow_n)
            st.fast_burn, st.slow_burn, st.measured = fast, slow, measured
            if n_samples < fast_n:
                continue  # not enough history to judge yet
            if not st.burning and fast >= fast_thresh and slow >= slow_thresh:
                st.burning, st.since = True, now
                severity = ("critical" if fast >= 2 * fast_thresh else "warn")
                events.publish(  # galaxylint: disable=event-uncorrelated -- a burn implicates a workload/schema, not one statement; the flight recorder resolves digests from tail-retained traces
                    "slo_burn",
                    f"SLO {slo.name} burning: fast={fast:.2f}x "
                    f"slow={slow:.2f}x target={target:g} "
                    f"measured={measured:g}",
                    severity=severity, node=self.instance.node_id,
                    slo=slo.name, slo_kind=slo.kind,
                    fast_burn=round(fast, 4), slow_burn=round(slow, 4),
                    target=target, measured=round(measured, 4),
                    schema=slo.schema, workload=slo.workload)
            elif st.burning and fast < 1.0:
                st.burning = False
                events.publish(
                    "slo_recovered",
                    f"SLO {slo.name} recovered: fast={fast:.2f}x after "
                    f"{max(0.0, now - st.since):.1f}s",
                    severity="info", node=self.instance.node_id,
                    slo=slo.name, slo_kind=slo.kind,
                    fast_burn=round(fast, 4),
                    burned_s=round(max(0.0, now - st.since), 3))
        self._refresh_gauge()

    def _refresh_gauge(self):
        with self._lock:
            burning = sum(1 for s in self._status.values() if s.burning)
        self._gauge.set(burning)

    def _evaluate_anomalies(self, now: float):
        cfg = self.instance.config
        hist = self.instance.metric_history
        alpha = float(cfg.get("ANOMALY_EWMA_ALPHA"))
        sigma = float(cfg.get("ANOMALY_SIGMA"))
        min_rate = float(cfg.get("ANOMALY_MIN_RATE"))
        for name in hist.counter_names():
            pts = hist.series(name, samples=2)
            if len(pts) < 2:
                continue
            dt = pts[1][0] - pts[0][0]
            if dt <= 0:
                continue
            rate = max(0.0, (pts[1][1] - pts[0][1]) / dt)
            st = self._anom.setdefault(name, _AnomalyState())
            if st.n >= 3:  # judged only after a warmed-up baseline
                floor = max(0.05 * st.mean, 1e-6)
                thresh = max(min_rate, st.mean + sigma * max(st.dev, floor))
                if rate > thresh:
                    if not st.firing:
                        st.firing = True
                        events.publish(  # galaxylint: disable=event-uncorrelated -- a counter-rate anomaly names a metric, not a statement; the flight recorder resolves digests from tail-retained traces
                            "metric_anomaly",
                            f"counter {name} rate {rate:.1f}/s vs baseline "
                            f"{st.mean:.1f}±{st.dev:.1f}/s",
                            severity="warn", node=self.instance.node_id,
                            metric=name, rate=round(rate, 3),
                            baseline=round(st.mean, 3),
                            deviation=round(st.dev, 3))
                    # damp the baseline update so a sustained storm does
                    # not immediately become the new normal
                    rate = thresh
                else:
                    st.firing = False
            st.dev = (1 - alpha) * st.dev + alpha * abs(rate - st.mean)
            st.mean = (1 - alpha) * st.mean + alpha * rate
            st.n += 1

    # -- surfaces --------------------------------------------------------------

    def burning_names(self) -> List[str]:
        with self._lock:
            return sorted(n for n, s in self._status.items() if s.burning)

    def rows(self) -> List[Tuple]:
        """SHOW SLO / information_schema.slo_status rows."""
        cfg = self.instance.config
        out: List[Tuple] = []
        for slo in self.defs():
            st = self._status.get(slo.name) or _Status()
            out.append((slo.name, slo.kind, slo.schema or "*",
                        slo.workload or "*", slo.resolve_target(cfg),
                        round(st.measured, 4), round(st.fast_burn, 4),
                        round(st.slow_burn, 4),
                        "BURNING" if st.burning else "OK",
                        round(st.since, 3) if st.burning else 0.0,
                        slo.source))
        return out
