"""Maintenance surfaces: recycle bin, CHECK TABLE, index advisor.

Reference analogs:
- recycle bin: `polardbx-executor/.../recycle` (DROP TABLE renames into the
  bin; FLASHBACK TABLE ... TO BEFORE DROP restores; PURGE deletes for real).
  Like the reference, tables with global indexes drop directly — a GSI's
  backing table has its own lifecycle and is not restorable as a pair.
- CHECK TABLE: `executor/corrector/Checker.java` — store integrity plus
  base<->GSI checksum comparison (utils/fastchecker.py does the hashing).
- index advisor: `polardbx-optimizer/.../optimizer/index` — inspect a bound
  plan for equality/join predicates not served by any index lead and emit
  CREATE GLOBAL INDEX suggestions.
"""

from __future__ import annotations

import itertools
import json
import time
from typing import List, Optional

from galaxysql_tpu.utils import errors

_BIN_PREFIX = "recycle.bin."
# monotonic disambiguator: two drops of a same-named table in the same
# millisecond must NOT collide (a collision would overwrite — and lose — the
# previously parked table)
_BIN_SEQ = itertools.count(1)


class RecycleBin:
    """DROP TABLE parks tables here instead of destroying them."""

    def __init__(self, instance):
        self.instance = instance

    def _entries(self) -> List[dict]:
        out = []
        for _k, v in self.instance.metadb.kv_scan(_BIN_PREFIX):
            try:
                out.append(json.loads(v))
            except Exception:
                continue
        return sorted(out, key=lambda d: d["dropped_at"])

    def rows(self):
        return [(d["bin_name"], d["original"], d["schema"],
                 time.strftime("%Y-%m-%d %H:%M:%S",
                               time.localtime(d["dropped_at"])))
                for d in self._entries()]

    def drop(self, tm) -> bool:
        """Park `tm` in the bin (rename).  Returns False when the table is not
        recyclable (has global indexes / is remote) — caller drops directly."""
        if getattr(tm, "remote", None) is not None or \
                any(i.global_index for i in tm.indexes):
            return False
        inst = self.instance
        bin_name = (f"__recycle__{tm.name}_{int(time.time() * 1000)}"
                    f"_{next(_BIN_SEQ)}")
        cat = inst.catalog
        s = cat.schema(tm.schema)
        store = inst.store(tm.schema, tm.name)
        del s.tables[tm.name.lower()]
        inst.metadb.drop_table(tm.schema, tm.name)
        inst.stores.pop(inst.store_key(tm.schema, tm.name), None)
        original = tm.name
        tm.name = bin_name
        s.tables[bin_name.lower()] = tm
        inst.stores[inst.store_key(tm.schema, bin_name)] = store
        inst.metadb.save_table(tm)
        inst.metadb.kv_put(_BIN_PREFIX + bin_name.lower(), json.dumps(
            {"bin_name": bin_name, "original": original, "schema": tm.schema,
             "dropped_at": time.time()}))
        cat.bump_schema()
        return True

    def flashback(self, schema: str, original: str,
                  rename_to: Optional[str] = None) -> str:
        """Restore the MOST RECENT bin entry for `original`."""
        inst = self.instance
        cands = [d for d in self._entries()
                 if d["schema"].lower() == schema.lower() and
                 d["original"].lower() == original.lower()]
        if not cands:
            raise errors.TddlError(
                f"no dropped table '{original}' in the recycle bin")
        entry = cands[-1]
        target = rename_to or original
        cat = inst.catalog
        s = cat.schema(schema)
        if target.lower() in s.tables or cat.view(schema, target) is not None:
            raise errors.TddlError(
                f"cannot flashback: '{target}' already exists")
        tm = s.tables[entry["bin_name"].lower()]
        store = inst.store(schema, entry["bin_name"])
        del s.tables[entry["bin_name"].lower()]
        inst.metadb.drop_table(schema, entry["bin_name"])
        inst.stores.pop(inst.store_key(schema, entry["bin_name"]), None)
        tm.name = target
        s.tables[target.lower()] = tm
        inst.stores[inst.store_key(schema, target)] = store
        inst.metadb.save_table(tm)
        inst.metadb.kv_delete(_BIN_PREFIX + entry["bin_name"].lower())
        cat.bump_schema()
        return target

    def purge(self, bin_name: Optional[str] = None) -> int:
        """Destroy one entry (by bin name) or every entry.  Returns count."""
        inst = self.instance
        n = 0
        for d in self._entries():
            if bin_name is not None and \
                    d["bin_name"].lower() != bin_name.lower():
                continue
            schema = d["schema"]
            try:
                inst.catalog.drop_table(schema, d["bin_name"], if_exists=True)
            except errors.TddlError:
                pass
            inst.drop_store(schema, d["bin_name"])
            inst.metadb.kv_delete(_BIN_PREFIX + d["bin_name"].lower())
            n += 1
        if bin_name is not None and n == 0:
            raise errors.TddlError(f"'{bin_name}' is not in the recycle bin")
        return n

    def purge_schema(self, schema: str):
        """DROP DATABASE also empties that schema's bin entries."""
        for d in self._entries():
            if d["schema"].lower() == schema.lower():
                self.instance.metadb.kv_delete(
                    _BIN_PREFIX + d["bin_name"].lower())


def check_table(instance, tm, store) -> List[tuple]:
    """CHECK TABLE rows for one table: structural invariants + GSI checksums."""
    rows = []
    ok = True
    # structural: every lane/valid/ts array agrees on row count per partition
    for p in store.partitions:
        n = p.num_rows
        for c in tm.columns:
            lane = p.lanes.get(c.name)
            valid = p.valid.get(c.name)
            if lane is None or valid is None or lane.shape[0] != n or \
                    valid.shape[0] != n or p.end_ts.shape[0] != n:
                rows.append((f"{tm.schema}.{tm.name}", "check", "Error",
                             f"partition {p.pid} lane '{c.name}' shape "
                             f"mismatch"))
                ok = False
    # GSI consistency: order-insensitive checksum base vs index table
    from galaxysql_tpu.utils import fastchecker
    for i in tm.indexes:
        if not i.global_index or i.status != "PUBLIC":
            continue
        try:
            res = fastchecker.check_gsi(instance, tm.schema, tm.name, i.name)
        except errors.TddlError as e:
            rows.append((f"{tm.schema}.{tm.name}", "check", "Error",
                         f"gsi {i.name}: {e}"))
            ok = False
            continue
        if not res.get("consistent", False):
            rows.append((f"{tm.schema}.{tm.name}", "check", "Error",
                         f"gsi {i.name} diverges from base "
                         f"(base_rows={res.get('base_rows')}, "
                         f"gsi_rows={res.get('gsi_rows')})"))
            ok = False
    if ok:
        rows.append((f"{tm.schema}.{tm.name}", "check", "status", "OK"))
    return rows


def advise_indexes(instance, plan) -> List[tuple]:
    """Suggest GSIs for a bound SELECT plan.

    Walks the optimized rel: an equality (or IN) predicate column — or an
    equi-join key column — on a scan that no PK lead, partition lead, or
    existing index lead serves becomes a CREATE GLOBAL INDEX suggestion with
    the scan's referenced columns as COVERING (so the suggested index is
    immediately routable by `route_covering_gsi`)."""
    from galaxysql_tpu.expr import ir
    from galaxysql_tpu.plan import logical as L
    from galaxysql_tpu.plan.rules import conjuncts, _col_lit_cmp

    suggestions = []
    seen = set()

    def served(tm, col: str) -> bool:
        leads = set()
        if tm.primary_key:
            leads.add(tm.primary_key[0].lower())
        if tm.partition.columns:
            leads.add(tm.partition.columns[0].lower())
        for i in tm.indexes:
            if i.columns:
                leads.add(i.columns[0].lower())
        return col.lower() in leads

    def suggest(scan, col: str, why: str):
        tm = scan.table
        if "$" in tm.name or getattr(tm, "remote", None) is not None:
            return
        if served(tm, col):
            return
        key = (tm.schema.lower(), tm.name.lower(), col.lower())
        if key in seen:
            return
        seen.add(key)
        covering = [c for _, c in scan.columns
                    if c.lower() != col.lower() and
                    c.lower() not in (x.lower() for x in tm.primary_key)]
        cov = f" COVERING ({', '.join(covering)})" if covering else ""
        suggestions.append((
            tm.name, col, why,
            f"CREATE GLOBAL INDEX g_{col} ON {tm.name} ({col}){cov}"))

    def eq_cols_of(cond, scan):
        id_to_col = {oid: c for oid, c in scan.columns}
        for c in conjuncts(cond):
            if isinstance(c, ir.Call) and c.op == "eq" and len(c.args) == 2:
                cl = _col_lit_cmp(c)
                if cl is not None and cl[0].name in id_to_col:
                    yield id_to_col[cl[0].name], "equality predicate"
            if isinstance(c, ir.InList) and not c.negated and \
                    isinstance(c.arg, ir.ColRef) and c.arg.name in id_to_col:
                yield id_to_col[c.arg.name], "IN-list predicate"

    scans_by_id = {}
    for n in L.walk(plan.rel):
        if isinstance(n, L.Scan):
            for oid, col in n.columns:
                scans_by_id[oid] = (n, col)

    for n in L.walk(plan.rel):
        if isinstance(n, L.Filter) and isinstance(n.child, L.Scan):
            for col, why in eq_cols_of(n.cond, n.child):
                suggest(n.child, col, why)
        if isinstance(n, L.Join):
            for a, b in n.equi:
                for side in (a, b):
                    if isinstance(side, ir.ColRef) and side.name in scans_by_id:
                        scan, col = scans_by_id[side.name]
                        suggest(scan, col, "join key")
    return suggestions
