"""Scheduled background jobs.

Reference analog: `executor/scheduler` (SURVEY.md §2.6) — cron-style jobs persisted in
the metadb (`scheduled_jobs` + `fired_scheduled_jobs`, Appendix B): local-partition/TTL
rotation, OSS purge, statistics refresh.  Interval-based here (cron parsing adds
nothing for an embedded engine); each fire is recorded so SHOW-style introspection and
at-most-once semantics per interval hold across restarts.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

_JOBS_SCHEMA = """
CREATE TABLE IF NOT EXISTS scheduled_jobs (
    job_name TEXT PRIMARY KEY, job_kind TEXT, schema_name TEXT, table_name TEXT,
    params_json TEXT, interval_s REAL, enabled INTEGER, last_fire REAL);
CREATE TABLE IF NOT EXISTS fired_scheduled_jobs (
    job_name TEXT, fired_at REAL, status TEXT, detail TEXT);
"""

_KIND_REGISTRY: Dict[str, Callable] = {}


def job_kind(name: str):
    def deco(fn):
        _KIND_REGISTRY[name] = fn
        return fn
    return deco


@job_kind("ttl_archive")
def _run_ttl_archive(instance, schema: str, table: str, params: dict) -> str:
    """TTL rotation: archive rows whose DATE column is older than ttl_days."""
    from galaxysql_tpu.types import temporal
    cutoff = temporal.days_from_civil(*time.gmtime()[:3]) - int(params["ttl_days"])
    n = instance.archive.archive_older_than(instance, schema, table,
                                            params["column"], cutoff)
    return f"archived {n} rows"


@job_kind("analyze")
def _run_analyze(instance, schema: str, table: str, params: dict) -> str:
    from galaxysql_tpu.server.session import Session
    s = Session(instance, schema)
    try:
        s.execute(f"ANALYZE TABLE `{table}`")
    finally:
        s.close()
    return "statistics refreshed"


@job_kind("rebalance")
def _run_rebalance(instance, schema: str, table: str, params: dict) -> str:
    """Maintain-loop tick of the heat-driven balancer (server/balancer.py):
    propose partition split/merge/move from observed heat and execute at most
    one per tick.  Yields (proposes nothing) under admission pressure."""
    props = instance.balancer.run_once(schema or None, table or None,
                                       apply=bool(params.get("apply", True)))
    if not props:
        return "balanced (no proposals)"
    first = props[0]
    applied = f" job={first.get('job_id')}" if first.get("applied") else \
        f" NOT applied ({first.get('error', 'apply=0')})"
    return (f"{len(props)} proposal(s); first: {first['op']} "
            f"{first['table']} p{first['pids']}{applied}")


@job_kind("purge_tx_log")
def _run_purge_tx_log(instance, schema: str, table: str, params: dict) -> str:
    keep_s = float(params.get("keep_seconds", 86400))
    cutoff = time.time() - keep_s
    if instance.data_dir:
        # presumed-abort boot recovery resolves provisional stamps in the LAST
        # CHECKPOINT against this log: an entry may only be purged once a later
        # checkpoint has persisted the txn's final stamps — wall clock alone
        # would let recovery roll back a committed txn from a stale npz
        mark = instance.metadb.kv_get("last_checkpoint_at")
        if mark is None:
            return "purged 0 entries (no checkpoint yet)"
        cutoff = min(cutoff, float(mark))
    cur = instance.metadb.execute(
        "DELETE FROM global_tx_log WHERE state IN ('DONE','ABORTED') "
        "AND updated < ?", (cutoff,))
    return f"purged {cur.rowcount} entries"


class ScheduledJobManager:
    """Registers jobs in the metadb and fires due ones (leader-CN polling model)."""

    def __init__(self, instance):
        self.instance = instance
        with instance.metadb._lock:
            instance.metadb._conn.executescript(_JOBS_SCHEMA)
            instance.metadb._conn.commit()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- registry ------------------------------------------------------------

    def register(self, name: str, kind: str, schema: str, table: str,
                 params: dict, interval_s: float, enabled: bool = True):
        import json
        if kind not in _KIND_REGISTRY:
            from galaxysql_tpu.utils import errors
            raise errors.TddlError(f"unknown job kind '{kind}'")
        self.instance.metadb.execute(
            "INSERT OR REPLACE INTO scheduled_jobs VALUES (?,?,?,?,?,?,?,?)",
            (name, kind, schema, table, json.dumps(params), interval_s,
             int(enabled), 0.0))

    def drop(self, name: str) -> bool:
        cur = self.instance.metadb.execute(
            "DELETE FROM scheduled_jobs WHERE job_name=?", (name,))
        return cur.rowcount > 0

    def jobs(self) -> List[Tuple]:
        return self.instance.metadb.query(
            "SELECT job_name, job_kind, schema_name, table_name, interval_s, "
            "enabled, last_fire FROM scheduled_jobs ORDER BY job_name")

    def history(self, name: Optional[str] = None) -> List[Tuple]:
        if name:
            return self.instance.metadb.query(
                "SELECT job_name, fired_at, status, detail FROM "
                "fired_scheduled_jobs WHERE job_name=? ORDER BY fired_at", (name,))
        return self.instance.metadb.query(
            "SELECT job_name, fired_at, status, detail FROM fired_scheduled_jobs "
            "ORDER BY fired_at")

    # -- firing ------------------------------------------------------------------

    def run_due(self, now: Optional[float] = None) -> List[str]:
        """Fire every enabled job whose interval has elapsed; returns fired names."""
        import json
        now = now if now is not None else time.time()
        # leader-only: with several coordinators sharing one GMS, background
        # jobs fire on exactly one (HA re-elects when the leader's heartbeat
        # ages out — StorageHaManager/leader-key analog)
        if not self.instance.ha.is_leader():
            return []
        fired = []
        for name, kind, schema, table, params_json, interval_s, enabled, last in \
                self.instance.metadb.query(
                    "SELECT job_name, job_kind, schema_name, table_name, "
                    "params_json, interval_s, enabled, last_fire "
                    "FROM scheduled_jobs"):
            if not enabled or now - last < interval_s:
                continue
            # claim the slot first (at-most-once per interval, even if we crash);
            # a concurrent poller that lost the conditional UPDATE must not fire
            cur = self.instance.metadb.execute(
                "UPDATE scheduled_jobs SET last_fire=? WHERE job_name=? "
                "AND last_fire=?", (now, name, last))
            if cur.rowcount == 0:
                continue
            try:
                detail = _KIND_REGISTRY[kind](self.instance, schema, table,
                                              json.loads(params_json))
                status = "SUCCESS"
            except Exception as e:  # jobs must never kill the scheduler
                detail = f"{type(e).__name__}: {e}"
                status = "FAILED"
            self.instance.metadb.execute(
                "INSERT INTO fired_scheduled_jobs VALUES (?,?,?,?)",
                (name, now, status, detail[:512]))
            fired.append(name)
        return fired

    def start(self, poll_interval_s: float = 5.0):
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(poll_interval_s):
                try:
                    self.run_due()
                except Exception:
                    pass
                # SLO-plane sampler rides the same maintain poll but is
                # per-node, NOT leader-gated like run_due: every node keeps
                # its own history (interval-gated inside slo_tick)
                try:
                    self.instance.slo_tick()
                except Exception:
                    pass

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="scheduled-jobs")
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
