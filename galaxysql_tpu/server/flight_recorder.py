"""Incident flight recorder: trigger-driven correlated evidence capture.

The engine *detects* trouble end to end — burn-rate alerts (server/slo.py),
plan-regression sentinels (meta/statement_summary.py), breaker opens
(net/dn.py), shed storms (server/admission.py), columnar tail faults
(storage/columnar.py) — but an event row is a bare fact.  This module turns
the fact into a diagnosis: on every trigger event it snapshots an **incident
bundle** — the implicated digests' tail-retained traces (utils/tracing
.TraceStore) with their phase breakdowns, the matching statement-summary
rows, the metric-history window around the trigger, the admission/memory/
columnar state, and the recent event tail — deduped per episode
(breaker-style cooldown: one bundle per burn, not one per tick) and
persisted to ``data_dir/incidents/`` under a bounded ring.

Surfaces: ``SHOW INCIDENTS [id]``, ``information_schema.incidents``, web
``/incidents[/<id>]`` (the bundle carries its traces in Chrome-trace-graftable
span-dict form, so ``/trace/<id>`` stays Perfetto-linkable).

Discipline: runs only on the slo_tick maintenance path — never on a query
path, never raises (advisory plane, same contract as the SLO engine)."""

from __future__ import annotations

import collections
import dataclasses
import itertools
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from galaxysql_tpu.utils.events import EVENTS

# Trigger kinds captured straight off the journal.  admission_reject is NOT
# here: single rejects are routine backpressure — the recorder watches the
# lifetime counter and fires only on a storm (INCIDENT_REJECT_STORM delta
# per tick).
EVENT_TRIGGERS = frozenset({
    "slo_burn", "plan_regression", "breaker_open",
    "columnar_tail_failed", "metric_anomaly",
})

# metric-history series worth freezing into a bundle (substring match) —
# the latency/burn/shed families plus whatever metric the trigger names.
_WINDOW_HINTS = ("latency", "queries_total", "query_errors", "admission_",
                 "slow_queries", "columnar_lag", "breaker")
_WINDOW_SAMPLES = 24          # history points per frozen series
_WINDOW_SERIES_CAP = 16       # series per bundle
_EVENT_TAIL = 32              # journal entries per bundle
_TRACES_PER_DIGEST = 3
_SUMMARY_ROWS_CAP = 32


@dataclasses.dataclass
class IncidentBundle:
    """One captured incident: trigger identity + frozen evidence."""

    incident_id: str
    at: float
    kind: str                 # trigger event kind (admission_reject = storm)
    severity: str
    episode: str              # dedupe key (kind + correlation)
    detail: str
    node: str
    digests: List[str] = dataclasses.field(default_factory=list)
    trace_ids: List[int] = dataclasses.field(default_factory=list)
    traces: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    summary_rows: List[list] = dataclasses.field(default_factory=list)
    metric_window: Dict[str, List] = dataclasses.field(default_factory=dict)
    admission: List[list] = dataclasses.field(default_factory=list)
    state: Dict[str, Any] = dataclasses.field(default_factory=dict)
    events: List[Dict[str, Any]] = dataclasses.field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class FlightRecorder:
    """Per-instance recorder ticked from ``Instance.slo_tick``."""

    def __init__(self, instance):
        self.instance = instance
        self._lock = threading.Lock()
        self._ring: "collections.deque[IncidentBundle]" = \
            collections.deque(maxlen=256)
        self._seq = itertools.count(1)
        self._last_seq = 0            # journal high-water at last tick
        self._reject_base: Optional[int] = None
        self._episodes: Dict[str, float] = {}   # episode key -> last capture
        self.captured = 0
        self.suppressed = 0
        # the recorder is advisory and must never break serving, but its own
        # faults must not vanish either: every best-effort handler logs here
        self.faults = 0
        self.last_fault = ""

    def _note_fault(self, where: str, e: BaseException):
        self.faults += 1
        self.last_fault = f"{where}: {type(e).__name__}: {e}"[:256]

    # -- config ----------------------------------------------------------------

    def _cfg(self, name, default):
        try:
            v = self.instance.config.get(name)
            return default if v is None else v
        except Exception as e:
            self._note_fault("cfg", e)
            return default

    def enabled(self) -> bool:
        return bool(self._cfg("ENABLE_FLIGHT_RECORDER", True))

    def _dir(self) -> Optional[str]:
        d = getattr(self.instance, "data_dir", None)
        if not d:
            return None
        return os.path.join(d, "incidents")

    # -- tick ------------------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> int:
        """Scan the journal since the last tick; capture one bundle per new
        trigger episode.  Advisory: never raises."""
        try:
            return self._tick(now=now)
        except Exception as e:  # pragma: no cover - defensive (advisory plane)
            self._note_fault("tick", e)
            return 0

    def _tick(self, now: Optional[float] = None) -> int:
        now = time.time() if now is None else now
        counts = EVENTS.counts()
        rejects = int(counts.get("admission_reject", 0))
        if self._reject_base is None:
            self._reject_base = rejects
        if not self.enabled():
            self._reject_base = rejects
            return 0
        made = 0
        evs = EVENTS.entries()
        new = [e for e in evs if e.seq > self._last_seq
               and e.kind in EVENT_TRIGGERS]
        if evs:
            self._last_seq = max(self._last_seq, evs[-1].seq)
        for e in new:
            if self._capture_event(e, now):
                made += 1
        # shed STORM detector: dedupe collapses reject events in the ring,
        # so storms are judged off the lifetime counter delta per tick
        storm_n = int(self._cfg("INCIDENT_REJECT_STORM", 20))
        if storm_n > 0 and rejects - self._reject_base >= storm_n:
            tail = [e for e in evs if e.kind == "admission_reject"]
            last = tail[-1] if tail else None
            attrs = dict(last.attrs) if last is not None else {}
            attrs["rejects_delta"] = rejects - self._reject_base
            if self._capture(
                    "admission_reject", "warn",
                    f"shed storm: {rejects - self._reject_base} rejects "
                    f"since last tick",
                    attrs, trace_id=getattr(last, "trace_id", 0) if last
                    else 0, now=now):
                made += 1
        self._reject_base = rejects
        return made

    def _capture_event(self, e, now: float) -> bool:
        return self._capture(e.kind, e.severity, e.detail, dict(e.attrs),
                             trace_id=int(getattr(e, "trace_id", 0) or 0),
                             digest=str(getattr(e, "digest", "") or ""),
                             now=now)

    # -- capture ---------------------------------------------------------------

    @staticmethod
    def _correlation(kind: str, attrs: Dict[str, Any], digest: str) -> str:
        return str(digest or attrs.get("digest") or attrs.get("slo")
                   or attrs.get("metric") or attrs.get("worker")
                   or attrs.get("table") or attrs.get("reason") or "")

    def _capture(self, kind: str, severity: str, detail: str,
                 attrs: Dict[str, Any], trace_id: int = 0, digest: str = "",
                 now: Optional[float] = None) -> bool:
        now = time.time() if now is None else now
        corr = self._correlation(kind, attrs, digest)
        episode = f"{kind}:{corr}"
        cooldown = float(self._cfg("INCIDENT_COOLDOWN_S", 60.0))
        with self._lock:
            last = self._episodes.get(episode)
            if last is not None and now - last < cooldown:
                self.suppressed += 1
                return False
            self._episodes[episode] = now
            if len(self._episodes) > 1024:
                self._episodes.clear()  # epoch reset, bounded
            seq = next(self._seq)
        inst = self.instance
        bundle = IncidentBundle(
            incident_id=f"inc-{seq}", at=now, kind=kind,
            severity=severity or "warn", episode=episode,
            detail=str(detail)[:512], node=inst.node_id)
        bundle.state["trigger_attrs"] = {
            k: v for k, v in attrs.items() if isinstance(
                v, (str, int, float, bool, type(None)))}
        self._implicate(bundle, attrs, trace_id, digest)
        self._freeze_state(bundle, attrs)
        with self._lock:
            self._ring.append(bundle)
            self.captured += 1
        self._persist(bundle)
        return True

    def _implicate(self, bundle: IncidentBundle, attrs: Dict[str, Any],
                   trace_id: int, digest: str):
        """Resolve the trigger to digests + retained traces."""
        inst = self.instance
        digests: List[str] = []
        for d in (digest, attrs.get("digest")):
            if d and d not in digests:
                digests.append(str(d))
        store = getattr(inst, "trace_store", None)
        traces: List[Dict[str, Any]] = []
        seen_ids = set()
        if not digests:
            # no digest on the trigger (slo_burn / metric_anomaly): implicate
            # from evidence — recent tail-retained slow/error/shed traces
            # first (the burn's victims), then the hottest summary digests
            if store is not None:
                for rt in store.entries(limit=32):
                    if rt.reason != "sampled" and rt.digest and \
                            rt.digest not in digests:
                        wl = str(attrs.get("workload", "") or "").upper()
                        if wl and rt.workload and rt.workload.upper() != wl:
                            continue
                        sch = str(attrs.get("schema", "") or "").lower()
                        if sch and rt.schema.lower() != sch:
                            continue
                        digests.append(rt.digest)
                    if len(digests) >= 4:
                        break
            if not digests:
                try:
                    for r in inst.stmt_summary.rows()[:4]:
                        digests.append(str(r[0]))
                except Exception as e:
                    self._note_fault("implicate:summary", e)
        if store is not None:
            if trace_id:
                rt = store.get(trace_id)
                if rt is not None:
                    traces.append(rt.to_dict())
                    seen_ids.add(rt.trace_id)
            for d in digests:
                for rt in store.for_digest(d, limit=_TRACES_PER_DIGEST):
                    if rt.trace_id not in seen_ids:
                        seen_ids.add(rt.trace_id)
                        traces.append(rt.to_dict())
        bundle.digests = digests
        bundle.traces = traces
        bundle.trace_ids = sorted(seen_ids)
        try:
            dset = set(digests)
            bundle.summary_rows = [
                list(r) for r in inst.stmt_summary.rows()
                if str(r[0]) in dset][:_SUMMARY_ROWS_CAP]
        except Exception as e:
            self._note_fault("implicate:rows", e)
            bundle.summary_rows = []

    def _freeze_state(self, bundle: IncidentBundle, attrs: Dict[str, Any]):
        inst = self.instance
        mh = getattr(inst, "metric_history", None)
        if mh is not None:
            hints = _WINDOW_HINTS
            trig_metric = str(attrs.get("metric", "") or "").lower()
            if trig_metric:
                hints = hints + (trig_metric,)
            window: Dict[str, List] = {}
            try:
                for name in mh.names():
                    low = name.lower()
                    if any(h and h in low for h in hints):
                        pts = mh.series(name, samples=_WINDOW_SAMPLES)
                        if pts:
                            window[name] = [[round(t, 3), v] for t, v in pts]
                    if len(window) >= _WINDOW_SERIES_CAP:
                        break
            except Exception as e:
                self._note_fault("freeze:metrics", e)
            bundle.metric_window = window
        try:
            bundle.admission = [list(r) for r in
                                inst.admission.stats_rows()]
            bundle.state["mem_tier"] = int(inst.admission.governor.tier())
        except Exception as e:
            self._note_fault("freeze:admission", e)
        try:
            bundle.state["burning"] = list(inst.slo.burning_names())
        except Exception as e:
            self._note_fault("freeze:slo", e)
        try:
            bundle.state["columnar"] = [list(r) for r in
                                        inst.columnar.rows()[:16]]
        except Exception as e:
            self._note_fault("freeze:columnar", e)
        try:
            store = getattr(inst, "trace_store", None)
            if store is not None:
                bundle.state["trace_store"] = store.stats()
        except Exception as e:
            self._note_fault("freeze:traces", e)
        bundle.events = [
            {"seq": e.seq, "at": round(e.at, 3), "kind": e.kind,
             "severity": e.severity, "node": e.node, "detail": e.detail,
             "trace_id": int(getattr(e, "trace_id", 0) or 0),
             "digest": str(getattr(e, "digest", "") or ""),
             "attrs": {k: v for k, v in e.attrs.items() if isinstance(
                 v, (str, int, float, bool, type(None)))}}
            for e in EVENTS.entries()[-_EVENT_TAIL:]]

    # -- persistence -----------------------------------------------------------

    def _persist(self, bundle: IncidentBundle):
        d = self._dir()
        if not d:
            return
        try:
            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, f"{bundle.incident_id}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(bundle.to_dict(), f, default=str)
            os.replace(tmp, path)
            # bounded on disk too: reap oldest past INCIDENT_RING
            keep = int(self._cfg("INCIDENT_RING", 64))
            files = sorted((os.path.getmtime(os.path.join(d, n)),
                            os.path.join(d, n))
                           for n in os.listdir(d) if n.endswith(".json"))
            for _mt, p in files[:-keep] if keep > 0 else []:
                os.unlink(p)
        except Exception as e:  # pragma: no cover - disk faults are advisory
            self._note_fault("persist", e)

    # -- surfaces --------------------------------------------------------------

    def bundles(self) -> List[IncidentBundle]:
        with self._lock:
            return list(reversed(self._ring))

    def get(self, incident_id: str) -> Optional[IncidentBundle]:
        want = str(incident_id)
        if want and not want.startswith("inc-"):
            want = f"inc-{want}"
        with self._lock:
            for b in self._ring:
                if b.incident_id == want:
                    return b
        # fall through to disk (post-restart retrieval)
        d = self._dir()
        if d:
            path = os.path.join(d, f"{want}.json")
            try:
                with open(path) as f:
                    raw = json.load(f)
                b = IncidentBundle(
                    incident_id=str(raw.get("incident_id", want)),
                    at=float(raw.get("at", 0.0)),
                    kind=str(raw.get("kind", "")),
                    severity=str(raw.get("severity", "")),
                    episode=str(raw.get("episode", "")),
                    detail=str(raw.get("detail", "")),
                    node=str(raw.get("node", "")))
                b.digests = list(raw.get("digests") or [])
                b.trace_ids = list(raw.get("trace_ids") or [])
                b.traces = list(raw.get("traces") or [])
                b.summary_rows = list(raw.get("summary_rows") or [])
                b.metric_window = dict(raw.get("metric_window") or {})
                b.admission = list(raw.get("admission") or [])
                b.state = dict(raw.get("state") or {})
                b.events = list(raw.get("events") or [])
                return b
            except Exception as e:
                self._note_fault("get", e)
                return None
        return None

    def rows(self) -> List[tuple]:
        """SHOW INCIDENTS / information_schema.incidents row source:
        (id, at, kind, severity, episode, node, digests, traces, events,
        detail) — newest first."""
        out = []
        for b in self.bundles():
            out.append((b.incident_id, round(b.at, 3), b.kind, b.severity,
                        b.episode, b.node, ",".join(b.digests),
                        len(b.traces), len(b.events), b.detail))
        return out

    def clear(self):
        with self._lock:
            self._ring.clear()
            self._episodes.clear()
            self.captured = 0
            self.suppressed = 0
