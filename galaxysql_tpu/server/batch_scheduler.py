"""Cross-session mega-batched TP serving: the point-query batching scheduler.

At millions-of-users scale the TP ceiling is dispatches/sec, not single-query
latency: every session dispatching its own program serializes on the Python
machinery and the device launch path.  This scheduler coalesces sessions
executing the SAME parameterized point statement (plan-cache identity:
`ParameterizedSql.cache_key` + the registered PointPlan) inside a short
collection window into ONE vectorized lookup — parameter keys stacked as a
batched runtime argument of a single jitted program per partition
(`exec/operators.batched_point_lookup`), results gathered once and scattered
back per session.  The Tailwind case (PAPERS.md): amortize launch + transfer
across requests.

Protocol (leader/follower, no dedicated threads):

- `submit()` under the scheduler lock either JOINS an open group for the
  statement (follower: parks on a per-request event) or OPENS one (leader).
- The leader sleeps the collection window — adaptive: the window opens only
  when several point queries are IN FLIGHT right now (sequential traffic
  sees window 0 and falls straight back to the unbatched fast path, zero
  added latency) and sizes itself by the observed arrival rate toward
  `MAX_WINDOW_S` so saturated traffic approaches the max bucket — then
  seals the group, executes it, scatters rows/errors, and wakes followers.
- A group that fills to the max static bucket (1024, the
  `exec/operators._BATCH_KEY_BUCKETS` ladder cap) seals early.

Correctness envelope:

- Snapshot semantics: autocommit sessions share ONE flush-time TSO (all
  members linearize at the flush instant — they were concurrent); sessions
  inside a read-only transaction group only with sessions pinned to the SAME
  snapshot (the group key carries `pinned_ts`); sessions whose transaction
  holds writes (local, GSI, or remote branches) BYPASS batching entirely —
  their provisional stamps need the own-txn visibility path.
- Error isolation: a poisoned key fails only its own session(s); any
  group-scope failure falls every member back to the sequential path, where
  errors surface with per-session attribution.
- Plan validity: the group key carries the catalog schema_version; a DDL
  between submit and flush fails the version re-check and falls back.  The
  flush itself holds shared MDL on the table, like every other read path.

Escape hatches (the fusion/fragment-cache hatch trio): `BATCH(OFF)` hint
(hinted statements never register PointPlans, so they take the planned path
by construction), `GALAXYSQL_BATCHING=0` env, `ENABLE_BATCH_SCHEDULER`
config param.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from galaxysql_tpu.utils.failpoint import FAIL_POINTS, FP_BATCH_POISON_KEY, \
    FailPointError

# kill switch: GALAXYSQL_BATCHING=0 disables the whole subsystem (every point
# query runs the sequential fast path, exactly the pre-batching engine)
ENABLED = os.environ.get("GALAXYSQL_BATCHING", "1") != "0"

_BATCH_MAX_KEYS: Optional[int] = None  # lazy mirror of operators.BATCH_MAX_KEYS


def _close_pool(pool):
    """weakref.finalize target: must not reference the scheduler itself."""
    pool.close()


@dataclasses.dataclass
class BatchRequest:
    """One session's slot in a group; the leader fills rows/error/fallback."""

    lane_val: Any
    t0: float
    prof: Any = None  # the session's QueryProfile: leader bulk-finishes it
    event: threading.Event = dataclasses.field(default_factory=threading.Event)
    rows: Optional[List[tuple]] = None
    error: Optional[BaseException] = None
    fallback: bool = False
    group_size: int = 0
    wait_us: float = 0.0
    # DML members (server/dml_batch.py): affected-row count + the async-apply
    # watermark the session fences its own reads on (0 = nothing async)
    affected: int = 0
    apply_seq: int = 0


class _Group:
    __slots__ = ("gkey", "pp", "pinned_ts", "requests", "t0", "full",
                 "sealed", "target")

    def __init__(self, gkey, pp, pinned_ts, t0, target=None):
        self.gkey = gkey
        self.pp = pp
        self.pinned_ts = pinned_ts
        self.requests: List[BatchRequest] = []
        self.t0 = t0
        self.full = threading.Event()
        self.sealed = False
        # adaptive mode: the in-flight demand at open time — once this many
        # members joined, all known demand has arrived and the group seals
        # without waiting out the window (None = pinned-window mode)
        self.target = target


class BatchScheduler:
    """Per-Instance scheduler; sessions reach it via `_try_batched_point`."""

    MIN_WINDOW_S = 100e-6
    MAX_WINDOW_S = 500e-6
    # config param naming the fixed-window override (subclasses rebind: the
    # DML batcher keys off DML_BATCH_WINDOW_US so read/write windows tune
    # independently)
    WINDOW_PARAM = "BATCH_WINDOW_US"
    # adaptive collection extends past one window quantum WHILE members keep
    # arriving (follower wake->resubmit is serialized by the interpreter, so
    # a mega-group trickles in over several quanta); this caps the total
    # collection time of any one group.  Group-commit pacing (below) is what
    # actually sizes saturated groups; this guards open-loop trickle and
    # bounds the wait a member can be asked to pay.
    MAX_COLLECT_S = 25e-3
    # below this many point queries in flight RIGHT NOW, batching cannot pay
    # for its wait: the window collapses to 0 and sequential/low-concurrency
    # traffic keeps its p50.  (Arrival RATE is the wrong gate: a saturated
    # sequential path caps the observed rate at its own ceiling, so a
    # rate-gated window never opens exactly when batching would help most.)
    MIN_INFLIGHT = 4
    TARGET_GROUP = 256  # window sizes itself to collect about this many

    def __init__(self, instance):
        self.instance = instance
        self._lock = threading.Lock()
        self._groups: Dict[Tuple, _Group] = {}
        # group-commit pacing: gkey -> done-event of the flush in progress.
        # While a statement's flush drains, its NEXT group keeps collecting
        # (the new leader parks on this event instead of spending only a
        # microsecond window), so saturated group sizes approach the live
        # session count instead of the handful that arrive in one window.
        self._flush_done: Dict[Tuple, threading.Event] = {}
        # concurrency gate: point-path executions in flight right now
        # (sessions bracket the WHOLE point path with point_begin/point_end).
        # A deque-of-tokens, NOT an int-under-a-lock: deque append/pop are
        # single C-level (GIL-atomic) ops, so the two-per-query bracket never
        # parks a thread — a shared lock here convoys at high session counts.
        self._inflight_tokens: collections.deque = collections.deque()
        # EWMA of submit inter-arrival gap (seconds); starts "slow" so the
        # first queries of a burst lead unbatched while the estimate catches up
        self._interval_ewma = 1.0
        self._last_arrival = time.perf_counter()
        self._window_open_s = 0.0
        self._born = time.perf_counter()
        m = instance.metrics
        self.batched = m.counter(
            "batched_queries", "point queries served by a batch group")
        self.flushes = m.counter(
            "batch_flushes", "batch group executions (vectorized flushes)")
        self.fallbacks = m.counter(
            "batch_fallbacks", "batch members returned to the sequential path")
        self.singletons = m.counter(
            "batch_singletons", "groups flushed with a single member")
        # flush scratch rides the global memory pool: pressure sheds batch
        # work (fallback to sequential) before queries spill.  Instances have
        # no teardown, so a finalizer detaches the child pool on GC (same
        # pattern as FragmentCache's pool child).
        import weakref
        from galaxysql_tpu.exec.memory import GLOBAL_POOL
        self.pool = GLOBAL_POOL.child("batch-scheduler", 256 << 20)
        weakref.finalize(self, _close_pool, self.pool)

    # -- gating ----------------------------------------------------------------

    def enabled(self, session) -> bool:
        return ENABLED and bool(self.instance.config.get(
            "ENABLE_BATCH_SCHEDULER", session.vars))

    def _max_group(self) -> int:
        global _BATCH_MAX_KEYS
        if _BATCH_MAX_KEYS is None:  # deferred: operators pulls in jax
            from galaxysql_tpu.exec.operators import BATCH_MAX_KEYS
            _BATCH_MAX_KEYS = BATCH_MAX_KEYS
        cfg = self.instance.config.get("BATCH_MAX_GROUP") or _BATCH_MAX_KEYS
        return max(1, min(int(cfg), _BATCH_MAX_KEYS))

    def point_begin(self):
        """Sessions bracket the whole point path (batched OR sequential) so
        `_window_s` sees true point-query concurrency, the signal batching
        amortizes over."""
        self._inflight_tokens.append(None)

    def point_end(self):
        try:
            self._inflight_tokens.pop()
        except IndexError:  # pragma: no cover - bracket imbalance guard
            pass

    @property
    def _inflight(self) -> int:
        return len(self._inflight_tokens)

    def _window_s(self) -> float:
        """Collection window for a group opening NOW (caller holds the lock).

        `BATCH_WINDOW_US` > 0 pins it (deterministic tests); otherwise the
        window opens only when >= MIN_INFLIGHT point queries are in flight
        (concurrency IS the amortizable demand — sequential traffic pays
        nothing), sized to collect ~TARGET_GROUP keys at the observed
        arrival rate, clamped to [MIN_WINDOW_S, MAX_WINDOW_S]."""
        fixed = self.instance.config.get(self.WINDOW_PARAM)
        if fixed:
            return float(fixed) / 1e6
        if self._inflight < self.MIN_INFLIGHT:
            return 0.0
        return min(max(self.TARGET_GROUP * self._interval_ewma,
                       self.MIN_WINDOW_S), self.MAX_WINDOW_S)

    def current_window_us(self) -> float:
        with self._lock:
            return self._window_s() * 1e6

    # -- submit/wait -----------------------------------------------------------

    def submit(self, gkey: Tuple, pp: dict, lane_val,
               pinned_ts: Optional[int], prof=None) -> Optional[BatchRequest]:
        """Join or open the statement's batch group; block until the group
        flushes.  Returns the caller's filled BatchRequest, or None when the
        caller must run the sequential path itself (window closed, singleton
        group, or group-scope fallback)."""
        now = time.perf_counter()
        # arrival-gap EWMA OUTSIDE the lock: benign read/write races on a
        # heuristic are a fair trade for the shortest possible critical
        # section on the single most contended lock in the serving loop.
        # (clamp idle gaps so one quiet second doesn't need hundreds of
        # arrivals to re-open the window when a burst lands)
        gap = now - self._last_arrival
        self._last_arrival = now
        self._interval_ewma += 0.2 * (min(gap, 0.05) - self._interval_ewma)
        cap = self._max_group()
        with self._lock:
            g = self._groups.get(gkey)
            if g is not None and not g.sealed:
                req = BatchRequest(lane_val, now, prof)
                g.requests.append(req)
                if len(g.requests) >= cap or (
                        g.target is not None and
                        len(g.requests) >= g.target):
                    g.sealed = True
                    g.full.set()
                leader = False
            else:
                window = self._window_s()
                if window <= 0.0:
                    return None
                fixed = bool(self.instance.config.get(self.WINDOW_PARAM))
                # adaptive: all in-flight point queries are potential members
                target = None if fixed else min(max(self._inflight, 2), cap)
                g = _Group(gkey, pp, pinned_ts, now, target)
                req = BatchRequest(lane_val, now, prof)
                g.requests.append(req)
                self._groups[gkey] = g
                prev_done = self._flush_done.get(gkey)
                leader = True
        if not leader:
            if not req.event.wait(timeout=5.0):
                with self._lock:
                    if not g.sealed:
                        # leader vanished pre-seal (should not happen): the
                        # sequential path is always correct — WITHDRAW so the
                        # leader, were it to wake, never double-finishes our
                        # profile after the sequential path records it; retire
                        # the zombie group so new arrivals elect a fresh
                        # leader instead of parking behind the dead one (a
                        # woken old leader's `is g` guard tolerates the pop).
                        # Keyed on g.sealed, NOT dict identity: a peer
                        # follower's withdrawal may already have popped the
                        # group, and the second timed-out member must still
                        # withdraw rather than fall into the untimed wait
                        try:
                            g.requests.remove(req)
                        except ValueError:  # pragma: no cover
                            pass
                        if self._groups.get(gkey) is g:
                            self._groups.pop(gkey)
                        return None
                # sealed: the leader owns this request and its finally-block
                # guarantees scatter + wake — a first flush of a new bucket
                # shape can sit in XLA compile past the safety-net timeout
                req.event.wait()
            return None if req.fallback else req
        # -- leader: collect, seal, execute, scatter ---------------------------
        # Group-commit pacing: while the statement's PREVIOUS flush drains,
        # this group just collects (members join under the lock above) — the
        # classic group-commit shape, batch size ~ arrivals per flush.  Then
        # pinned mode waits the window out; adaptive mode waits in window
        # quanta and keeps collecting WHILE members arrive (their wake-ups
        # are interpreter-serialized), sealing early once the open-time
        # in-flight demand has all joined, hard-capped at MAX_COLLECT_S.
        deadline = g.t0 + (window if g.target is None else self.MAX_COLLECT_S)
        if prev_done is not None and g.target is not None:
            prev_done.wait(self.MAX_COLLECT_S)
        joined = 1
        while not g.full.wait(window):
            n_now = len(g.requests)  # racy read; the seal below is exact
            if g.target is None or n_now <= joined or \
                    time.perf_counter() >= deadline:
                break  # pinned window spent, arrivals stalled, or hard cap
            joined = n_now
        flush_t = time.perf_counter()
        done = threading.Event()
        with self._lock:
            g.sealed = True
            if self._groups.get(gkey) is g:
                self._groups.pop(gkey)
            reqs = list(g.requests)
            self._window_open_s += flush_t - g.t0
            if len(reqs) > 1:
                self._flush_done[gkey] = done
        try:
            if len(reqs) == 1:
                self.singletons.inc()
                req.fallback = True
            else:
                self._execute(gkey, pp, pinned_ts, reqs)
                self._bulk_finish(pp, reqs, flush_t)
        except Exception as ex:
            # group-scope failure: every member re-executes sequentially and
            # gets its own error attribution there
            for r in reqs:
                r.fallback = True
            self.fallbacks.inc(len(reqs))
            from galaxysql_tpu.utils import events
            events.publish("batch_fallback",
                           f"batch group of {len(reqs)} fell back to the "
                           f"sequential path: {type(ex).__name__}: {ex}",
                           node=self.instance.node_id,
                           group_size=len(reqs))
        finally:
            # unpark the NEXT group's leader before the followers: it starts
            # its stall-loop collecting while this group's members drain
            done.set()
            with self._lock:
                if self._flush_done.get(gkey) is done:
                    del self._flush_done[gkey]
            for r in reqs:
                if r is not req:
                    r.event.set()
        return None if req.fallback else req

    def _bulk_finish(self, pp: dict, reqs: List[BatchRequest], flush_t: float):
        """Leader-side group finish: profile fields, ring append, counters,
        latency/wait histograms — for EVERY served member, in bulk C-level
        operations.  Conserving total Python work is not enough at 1k+
        sessions; what matters is that the woken follower's serialized path
        is as short as possible (build ResultSet, return), so all per-query
        bookkeeping happens here, once per FLUSH instead of once per query.
        Members that fall back or error keep full session-side handling
        (their error ramp records the profile exactly once)."""
        from galaxysql_tpu.utils.metrics import BATCH_GROUP_SIZE, BATCH_WAIT_MS
        from galaxysql_tpu.utils.tracing import GLOBAL_STATS
        BATCH_GROUP_SIZE.observe(len(reqs))
        self.flushes.inc()
        # serving time = submit -> scatter: collection wait (flush_t - t0)
        # PLUS the vectorized execution that just finished — only the
        # member's own wake-up/return is excluded (it cannot observe that
        # before returning).  wait_us keeps the pure collection wait for the
        # batch_wait_ms histogram (window tuning signal).
        end_t = time.perf_counter()
        exec_us = (end_t - flush_t) * 1e6
        nfall = 0
        waits = []
        served = []
        serve_ms = []
        table = pp["table"]
        key_col = pp["key_col"]
        for r in reqs:
            n = len(reqs)
            r.group_size = n
            wait_us = (flush_t - r.t0) * 1e6
            r.wait_us = wait_us
            waits.append(wait_us / 1000.0)
            if r.fallback:
                nfall += 1
                continue
            if r.error is not None or r.prof is None:
                continue
            p = r.prof
            p.workload = "TP"
            p.engine = "batch"
            p.rows = len(r.rows)
            total_us = wait_us + exec_us
            p.elapsed_ms = round(total_us / 1000.0, 3)
            p.trace = [f"trace-id {p.trace_id}",
                       f"point-plan {table}.{key_col} "
                       f"[batched group={n} wait={wait_us:.0f}us "
                       f"exec={exec_us:.0f}us]",
                       f"elapsed={total_us / 1e6:.3f}s workload=TP"]
            served.append(p)
            serve_ms.append(total_us / 1000.0)
        BATCH_WAIT_MS.observe_many(waits)
        if nfall:
            self.fallbacks.inc(nfall)
        if served:
            inst = self.instance
            inst.profiles.record_many(served)
            lat_h, q_total, q_wl, q_eng = inst.finish_handles("TP", "batch")
            lat_h.observe_many(serve_ms)
            q_total.inc(len(served))
            q_wl.inc(len(served))
            q_eng.inc(len(served))
            GLOBAL_STATS.bump("queries", len(served))
            inst.counters.inc("batched_point_queries", len(served))
            self.batched.inc(len(served))

    # -- group execution -------------------------------------------------------

    def _execute(self, gkey: Tuple, pp: dict, pinned_ts: Optional[int],
                 reqs: List[BatchRequest]):
        """One vectorized flush: stack unique keys, route to partitions, run
        one jitted lookup per touched partition, gather each output column
        ONCE across all matches, slice rows back per key."""
        from galaxysql_tpu.chunk.batch import Column
        from galaxysql_tpu.exec.device_cache import GLOBAL_DEVICE_CACHE
        from galaxysql_tpu.exec.memory import MemoryLimitExceeded
        from galaxysql_tpu.exec.operators import (BATCH_MAXDUP,
                                                  batched_point_lookup)

        inst = self.instance
        if inst.catalog.schema_version != pp["schema_version"]:
            raise RuntimeError("schema changed under the group")  # galaxylint: disable=untyped-raise -- group fallback signal caught by the flush; never crosses the wire
        tm = inst.catalog.table(pp["schema"], pp["table"])
        store = inst.store(pp["schema"], pp["table"])
        inst_key = f"{tm.schema.lower()}.{tm.name.lower()}"
        if inst.archive.files_for(inst_key, None):
            raise RuntimeError("archive-backed table")  # galaxylint: disable=untyped-raise -- group fallback signal (cold rows) caught by the flush; never crosses the wire
        snap = pinned_ts if pinned_ts is not None else \
            inst.tso.next_timestamp()
        key_col = pp["key_col"]
        out_cols = pp["out_cols"]

        uniq: Dict[Any, int] = {}
        for r in reqs:
            uniq.setdefault(r.lane_val, len(uniq))
        uvals = list(uniq)
        results: List[List[tuple]] = [[] for _ in uvals]
        errors: List[Optional[BaseException]] = [None] * len(uvals)

        # flush scratch accounting through the memory pool (conservative:
        # keys + up to MAXDUP gathered rows per key per output column)
        est = len(uvals) * (16 + BATCH_MAXDUP * 16 * (len(out_cols) + 2))
        try:
            self.pool.reserve(est)
        except MemoryLimitExceeded:
            raise RuntimeError("batch scratch pool exhausted")  # galaxylint: disable=untyped-raise -- group fallback signal caught by the flush; never crosses the wire
        try:
            by_pid = self._route(tm, key_col, uvals, errors,
                                 len(store.partitions))
            with inst.mdl.shared({inst_key}):
                for pid in sorted(by_pid):
                    part = store.partitions[pid]
                    if part.num_rows == 0:
                        continue
                    sub = by_pid[pid]
                    sub_vals = [uvals[i] for i in sub]
                    ids, offs = batched_point_lookup(
                        store, pid, part, key_col, tm.version, sub_vals,
                        snap, 0, device_cache=GLOBAL_DEVICE_CACHE)
                    if ids.size == 0:
                        continue
                    with part.lock:
                        lists = []
                        for cname, typ in zip(out_cols, pp["types"]):
                            c = Column(part.lanes[cname][ids],
                                       part.valid[cname][ids],
                                       tm.column(cname).dtype,
                                       tm.dictionaries.get(cname.lower()))
                            lists.append(c.to_pylist())
                    flat = list(zip(*lists))
                    for j, u in enumerate(sub):
                        seg = flat[offs[j]:offs[j + 1]]
                        if seg:
                            results[u].extend(seg)
        finally:
            self.pool.release(est)

        poison = FAIL_POINTS.value(FP_BATCH_POISON_KEY)
        if poison is not None:
            for u, v in enumerate(uvals):
                if v == poison:
                    errors[u] = FailPointError(
                        f"failpoint {FP_BATCH_POISON_KEY} fired (key {v!r})")

        handed = [False] * len(uvals)
        for r in reqs:
            u = uniq[r.lane_val]
            if errors[u] is not None:
                r.error = errors[u]
            else:
                # each session's ResultSet takes ownership of its rows list;
                # duplicate-key members get their own copy
                r.rows = list(results[u]) if handed[u] else results[u]
                handed[u] = True

    def _route(self, tm, key_col: str, uvals, errors,
               nparts: int) -> Dict[int, List[int]]:
        """pid -> [unique-key index] routing, mirroring the sequential path's
        `PartitionRouter.prune_eq(key_col, int(lane_val))` (vectorized for
        the single-column hash/key case).  A per-key routing error — e.g. a
        LIST value with no partition — is isolated to that key's sessions."""
        from galaxysql_tpu.meta.catalog import PartitionRouter
        router = PartitionRouter(tm)
        info = tm.partition
        by_pid: Dict[int, List[int]] = {}
        if info.method in ("single", "broadcast"):
            by_pid[0] = list(range(len(uvals)))
            return by_pid
        if info.method in ("hash", "key") and len(info.columns) == 1 and \
                info.columns[0].lower() == key_col.lower():
            # int() matches prune_eq's route_literal([int(v)]) lane truncation
            arr = np.asarray([int(v) for v in uvals], dtype=np.int64)
            for u, pid in enumerate(router.route_rows([arr])):
                by_pid.setdefault(int(pid), []).append(u)
            return by_pid
        for u, v in enumerate(uvals):
            try:
                pids = router.prune_eq(key_col, int(v))
            except Exception as e:
                errors[u] = e
                continue
            if pids is None:
                pids = range(nparts)
            for pid in pids:
                by_pid.setdefault(int(pid), []).append(u)
        return by_pid

    # -- observability (SHOW BATCH STATS / information_schema.batch_stats) -----

    def stats_rows(self) -> List[Tuple[str, float]]:
        """(stat_name, value) rows: group-size/wait quantiles, hit ratio over
        all point-plan executions, window occupancy, live window state."""
        from galaxysql_tpu.utils.metrics import BATCH_GROUP_SIZE, BATCH_WAIT_MS
        gs = BATCH_GROUP_SIZE.quantiles()
        ws = BATCH_WAIT_MS.quantiles()
        batched = self.batched.value
        sequential = self.instance.counters.get("point_plan_queries", 0)
        uptime = max(time.perf_counter() - self._born, 1e-9)
        mean_group = (BATCH_GROUP_SIZE.sum / BATCH_GROUP_SIZE.count) \
            if BATCH_GROUP_SIZE.count else 0.0
        with self._lock:
            open_groups = len(self._groups)
            window_us = self._window_s() * 1e6
        return [
            ("batched_queries", float(batched)),
            ("batch_flushes", float(self.flushes.value)),
            ("batch_fallbacks", float(self.fallbacks.value)),
            ("batch_singletons", float(self.singletons.value)),
            ("group_size_mean", round(mean_group, 3)),
            ("group_size_p50", float(gs[0.5])),
            ("group_size_p95", float(gs[0.95])),
            ("group_size_p99", float(gs[0.99])),
            ("wait_ms_p50", float(ws[0.5])),
            ("wait_ms_p95", float(ws[0.95])),
            ("hit_ratio", round(batched / max(batched + sequential, 1), 4)),
            ("window_occupancy",
             round(min(self._window_open_s / uptime, 1.0), 4)),
            ("window_us", round(window_us, 1)),
            ("open_groups", float(open_groups)),
            ("point_inflight", float(self._inflight)),
        ]
